"""Non-uniform tiles (reference ex13_non_uniform_block_size.cc):
rectangular mb != nb tiling and ragged final tiles."""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st

a = st.Matrix.from_array(jnp.arange(100.0 * 70).reshape(100, 70),
                         mb=48, nb=32)
assert a.mt == 3 and a.nt == 3          # ragged tails
assert a.tile_mb(2) == 4 and a.tile_nb(2) == 6
t = a.tile(2, 1)
np.testing.assert_array_equal(np.asarray(t),
                              np.asarray(a.array)[96:100, 32:64])
print("ok: non-uniform tiling")
