"""Matrix hierarchy and views (reference examples/ex01_matrix.cc).

Create tiled matrices, inspect the tile grid, take transposed views.
"""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st

a = st.Matrix.from_array(jnp.arange(12.0 * 8).reshape(12, 8), mb=4, nb=4)
print(a, "tiles:", a.mt, "x", a.nt)
t = a.transpose()
assert t.m == 8 and t.n == 12
h = st.HermitianMatrix(jnp.eye(8) * 2, uplo=st.Uplo.Lower, mb=4, nb=4)
tri = st.TriangularMatrix(jnp.tril(jnp.ones((8, 8))), uplo=st.Uplo.Lower,
                          diag=st.Diag.Unit, mb=4, nb=4)
band = st.BandMatrix(jnp.eye(8), kl=1, ku=2, mb=4, nb=4)
print("ok: matrix hierarchy constructed")
