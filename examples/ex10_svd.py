"""Two-stage SVD (reference ex10_svd.cc): ge2tb -> tb2bd -> bdsqr."""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st

rng = np.random.default_rng(7)
a = jnp.asarray(rng.standard_normal((48, 32)), jnp.float32)
s = st.svd_vals(a)
sr = np.linalg.svd(np.asarray(a), compute_uv=False)
assert np.abs(np.sort(np.asarray(s))[::-1] - sr).max() < 1e-3
print("ok: singular values match")
