"""Matrix norms (reference examples/ex04_norm.cc)."""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st

rng = np.random.default_rng(0)
a = jnp.asarray(rng.standard_normal((96, 64)), jnp.float32)
for which, ref in [(st.Norm.Max, np.abs(np.asarray(a)).max()),
                   (st.Norm.One, np.linalg.norm(np.asarray(a), 1)),
                   (st.Norm.Inf, np.linalg.norm(np.asarray(a), np.inf)),
                   (st.Norm.Fro, np.linalg.norm(np.asarray(a)))]:
    got = float(st.norm(which, a))
    assert abs(got - ref) / ref < 1e-5, (which, got, ref)
print("ok: norms match numpy")
