"""Submatrix and slice views (reference examples/ex03_submatrix.cc).

sub() is tile-aligned, slice() element-aligned (BaseMatrix.hh sub/slice).
"""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import slate_tpu as st

a = st.Matrix.from_array(jnp.arange(16.0 * 16).reshape(16, 16), mb=4, nb=4)
s = a.sub(1, 2, 1, 2)          # tile rows 1..2, tile cols 1..2
assert s.m == 8 and s.n == 8
sl = a.slice(2, 9, 3, 12)      # element rows 2..9, cols 3..12
assert sl.m == 8 and sl.n == 10
print("ok: sub/slice views")
