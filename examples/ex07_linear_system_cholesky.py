"""Cholesky linear systems (reference ex07_linear_system_cholesky.cc)."""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st
from slate_tpu.testing import random_spd

n = 96
a = random_spd(n, dtype=jnp.float32, seed=3)
b = jnp.asarray(np.random.default_rng(4).standard_normal((n, 4)), jnp.float32)
A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=32, nb=32)
fac, x = st.posv(A, b)
r = np.linalg.norm(np.asarray(a) @ np.asarray(x) - np.asarray(b))
assert r / n < 1e-3
print("ok: posv residual", r)
