"""ScaLAPACK-style usage (reference ex14_scalapack_gemm.cc): BLACS-grid
shim + block-cyclic local arrays (scalapack_api/scalapack_gemm.cc)."""
import _path  # noqa: F401  (in-tree import bootstrap)
import numpy as np
from slate_tpu.api import scalapack as sl

grid = sl.BlacsGrid(2, 2)
m = n = k = 32
desc = sl.Desc(m, k, 8, 8)
rng = np.random.default_rng(11)
a = rng.standard_normal((m, k)).astype(np.float32)
b = rng.standard_normal((k, n)).astype(np.float32)
a_lg = sl.to_local(a, grid, desc)
b_lg = sl.to_local(b, grid, sl.Desc(k, n, 8, 8))
c0 = np.zeros((m, n), np.float32)
c_lg = sl.pgemm("N", "N", 1.0, a_lg, desc, b_lg, sl.Desc(k, n, 8, 8),
                0.0, sl.to_local(c0, grid, sl.Desc(m, n, 8, 8)),
                sl.Desc(m, n, 8, 8), grid)
c = sl.from_local(c_lg, grid, sl.Desc(m, n, 8, 8))
assert np.abs(c - a @ b).max() < 1e-3 * max(1.0, np.abs(a @ b).max())
print("ok: scalapack-style pgemm")
