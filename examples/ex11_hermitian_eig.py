"""Hermitian eigensolver (reference ex11_hermitian_eig.cc): two-stage
he2hb -> hb2st -> tridiagonal D&C."""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st

rng = np.random.default_rng(8)
n = 48
x0 = rng.standard_normal((n, n))
a = jnp.asarray((x0 + x0.T) / 2, jnp.float32)
A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=16, nb=16)
w, z = st.heev(A)
wr = np.linalg.eigvalsh(np.asarray(a))
assert np.abs(np.asarray(w) - wr).max() < 2e-3 * max(1.0, np.abs(wr).max())
print("ok: eigenvalues match")
