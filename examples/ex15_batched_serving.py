"""Batched drivers and the async serving front door (round 8).

The serving workload: many SMALL independent problems per second.  This
example runs the leading-batch-dim drivers directly, then serves mixed
single-problem requests through the request-batching queue with an AOT
warm start — the first request of a warmed bucket compiles nothing.
"""
import _path  # noqa: F401  (in-tree import bootstrap)
import numpy as np
import jax.numpy as jnp

import slate_tpu as st
from slate_tpu import serve
from slate_tpu.perf import metrics

rng = np.random.default_rng(0)
B, n = 16, 64

# --- batched drivers: one call owns the whole batch ----------------------
g = rng.standard_normal((B, n, n)).astype(np.float32)
spd = np.einsum("bij,bkj->bik", g, g) + n * np.eye(n, dtype=np.float32)
rhs = rng.standard_normal((B, n)).astype(np.float32)

l, x = st.posv_batched(jnp.asarray(spd), jnp.asarray(rhs))
resid = np.linalg.norm(np.einsum("bij,bj->bi", spd, np.asarray(x)) - rhs)
print(f"posv_batched: {B} solves, residual {resid:.2e}")

lu, perm, xg = st.gesv_batched(
    jnp.asarray(g + n * np.eye(n, dtype=np.float32)), jnp.asarray(rhs))
print(f"gesv_batched: LU {lu.shape}, perm {perm.shape}")

# --- the serving front door ----------------------------------------------
metrics.on()                       # watch the queue counters
serve.warm_start(specs=[{"op": "posv", "batch": 8, "dims": (64,)}])

futs = [serve.submit("posv", spd[i], rhs[i]) for i in range(8)]
xs = [f.result(timeout=60) for f in futs]
r0 = np.linalg.norm(spd[0] @ xs[0] - rhs[0]) / np.linalg.norm(rhs[0])
c = metrics.snapshot()["counters"]
print(f"served {int(c['serve.requests'])} requests in "
      f"{int(c['serve.dispatches'])} dispatches, "
      f"{int(c.get('serve.compile.on_demand', 0))} on-demand compiles "
      f"(warm-started), first residual {r0:.2e}")
serve.shutdown()
print("ok: batched serving round trip")
