/* C API smoke test (reference lapack_api/example_dgetrf.c analog):
 * build:  gcc c_api_smoke.c -I../include -L../slate_tpu/native \
 *             -l:_slate_host.so -Wl,-rpath,../slate_tpu/native -o c_smoke
 * The Python package builds _slate_host.so on first use; run
 * `python -c "import slate_tpu.native as n; n.available()"` first. */
#include <stdio.h>
#include <stdlib.h>
#include <math.h>
#include "slate_tpu.h"

int main(void) {
    const int64_t n = 192, nrhs = 4, nb = 64;
    double *a = malloc(n * n * sizeof *a);
    double *acpy = malloc(n * n * sizeof *a);
    double *b = malloc(n * nrhs * sizeof *b);
    double *x = malloc(n * nrhs * sizeof *b);
    srand(0);
    /* SPD: A = G G^T + n I, col-major */
    double *g = malloc(n * n * sizeof *g);
    for (int64_t i = 0; i < n * n; ++i) g[i] = rand() / (double)RAND_MAX - 0.5;
    for (int64_t j = 0; j < n; ++j)
        for (int64_t i = 0; i < n; ++i) {
            double s = (i == j) ? (double)n : 0.0;
            for (int64_t k = 0; k < n; ++k) s += g[k * n + i] * g[k * n + j];
            a[j * n + i] = s; acpy[j * n + i] = s;
        }
    for (int64_t i = 0; i < n * nrhs; ++i) { b[i] = rand() / (double)RAND_MAX; x[i] = b[i]; }

    int info = slate_host_potrf_f64(a, n, nb);
    if (info != 0) { printf("potrf failed: %d\n", info); return 1; }
    slate_host_potrs_f64(a, n, x, nrhs, nb);

    /* residual ||A x - b|| */
    double r2 = 0, b2 = 0;
    for (int64_t j = 0; j < nrhs; ++j)
        for (int64_t i = 0; i < n; ++i) {
            double s = -b[j * n + i];
            for (int64_t k = 0; k < n; ++k) s += acpy[k * n + i] * x[j * n + k];
            r2 += s * s; b2 += b[j * n + i] * b[j * n + i];
        }
    printf("relative residual: %.3e\n", sqrt(r2 / b2));
    if (sqrt(r2 / b2) > 1e-10) return 1;

    /* pool + numroc sanity */
    void* pool = slate_pool_create(4096);
    void* blk = slate_pool_alloc(pool);
    slate_pool_free(pool, blk);
    if (slate_pool_num_free(pool) != 1) return 1;
    slate_pool_destroy(pool);
    if (slate_numroc(100, 16, 1, 4) <= 0) return 1;
    printf("ok: C API smoke\n");
    return 0;
}
