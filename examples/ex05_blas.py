"""Parallel BLAS-3 (reference examples/ex05_blas.cc): gemm, syrk, trsm
via both the BLAS-named and the simplified verb-named APIs."""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st
from slate_tpu.api import simplified as easy

rng = np.random.default_rng(1)
a = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
b = jnp.asarray(rng.standard_normal((48, 32)), jnp.float32)
c = jnp.zeros((64, 32), jnp.float32)
out = st.gemm(1.0, a, b, 0.0, c)
out2 = easy.multiply(1.0, a, b, 0.0, c)
np.testing.assert_allclose(np.asarray(out), np.asarray(a) @ np.asarray(b),
                           rtol=1e-4, atol=1e-4)
np.testing.assert_allclose(np.asarray(out), np.asarray(out2))
print("ok: gemm residual small, APIs agree")
