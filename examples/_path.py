"""Make the in-tree slate_tpu package importable when examples run from
this directory (no install step, mirroring the reference's in-tree
example builds)."""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
