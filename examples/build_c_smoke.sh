#!/bin/sh
# Build + run both C smoke tests (host-runtime ABI and driver ABI).
set -e
cd "$(dirname "$0")"

# host-runtime smoke (links the native .so; built on first python use)
PYTHONPATH="$(cd .. && pwd)${PYTHONPATH:+:$PYTHONPATH}" python -c "import slate_tpu.native as n; assert n.available(), n.build_error()"
gcc c_api_smoke.c -I../include -L../slate_tpu/native \
    -l:_slate_host.so -Wl,-rpath,"$(cd ../slate_tpu/native && pwd)" \
    -O2 -lm -o /tmp/c_smoke
/tmp/c_smoke

# driver smoke (embeds CPython, runs the JAX drivers)
gcc c_api_driver_smoke.c ../src/c_api/c_api_core.c \
    ../src/c_api/driver_api.c -I../include \
    $(python3-config --includes) $(python3-config --ldflags --embed) \
    -O2 -lm -o /tmp/c_driver_smoke
SITE="$(python - <<'PY'
import site, sys
print(":".join(p for p in sys.path if p))
PY
)"
PYTHONPATH="$(cd .. && pwd):$SITE" JAX_PLATFORMS=cpu /tmp/c_driver_smoke

# ScaLAPACK compatibility smoke (2x2-grid round-trip through the
# drop-in p? symbols; single-controller BLACS emulation)
gcc scalapack_smoke.c ../src/c_api/c_api_core.c \
    ../src/c_api/driver_api.c ../src/c_api/scalapack_api.c -I../include \
    $(python3-config --includes) $(python3-config --ldflags --embed) \
    -O2 -lm -o /tmp/scalapack_smoke
PYTHONPATH="$(cd .. && pwd):$SITE" PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    /tmp/scalapack_smoke
