"""Least squares (reference ex09_least_squares.cc): gels auto-selects
QR vs CholQR per shape/conditioning (method.hh:236)."""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st

rng = np.random.default_rng(6)
m, n = 128, 48
a = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
b = jnp.asarray(rng.standard_normal((m, 2)), jnp.float32)
x = st.gels(a, b)
xv = np.asarray(getattr(x, "array", x))
xr = np.linalg.lstsq(np.asarray(a), np.asarray(b), rcond=None)[0]
assert np.abs(xv - xr).max() < 5e-3
print("ok: gels matches lstsq")
