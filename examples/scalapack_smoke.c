/* ScaLAPACK compatibility API smoke: round-trip a 2x2-grid
 * block-cyclic pdpotrf + pdgesv + pdgemm through the drop-in symbols
 * (reference analog: scalapack_api/example_pdgetrf.c).
 *
 * The single-controller BLACS emulation plays all four virtual ranks
 * in sequence: Cblacs_gridinfo reports the coordinates of the rank
 * whose turn it is, and the fourth p? call triggers the actual
 * computation (see src/c_api/scalapack_api.c header).
 *
 * build: see examples/build_c_smoke.sh
 */

#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

extern void Cblacs_gridinit(int*, const char*, int, int);
extern void Cblacs_gridinfo(int, int*, int*, int*, int*);
extern void Cblacs_gridexit(int);
extern void Cblacs_barrier(int, const char*);
extern int numroc_(const int*, const int*, const int*, const int*,
                   const int*);
extern void descinit_(int*, const int*, const int*, const int*, const int*,
                      const int*, const int*, const int*, const int*, int*);
extern void pdpotrf_(const char*, const int*, double*, const int*,
                     const int*, const int*, int*);
extern void pdgesv_(const int*, const int*, double*, const int*, const int*,
                    const int*, int*, double*, const int*, const int*,
                    const int*, int*);
extern void pdgemm_(const char*, const char*, const int*, const int*,
                    const int*, const double*, double*, const int*,
                    const int*, const int*, double*, const int*, const int*,
                    const int*, const double*, double*, const int*,
                    const int*, const int*);
extern void pdgetrf_(const int*, const int*, double*, const int*,
                     const int*, const int*, int*, int*);
extern void pdgetrs_(const char*, const int*, const int*, double*,
                     const int*, const int*, const int*, int*, double*,
                     const int*, const int*, const int*, int*);
extern void pdpotrs_(const char*, const int*, const int*, double*,
                     const int*, const int*, const int*, double*,
                     const int*, const int*, const int*, int*);
extern void pdtrsm_(const char*, const char*, const char*, const char*,
                    const int*, const int*, const double*, double*,
                    const int*, const int*, const int*, double*,
                    const int*, const int*, const int*);
extern double pdlange_(const char*, const int*, const int*, double*,
                       const int*, const int*, const int*, double*);
extern void pdsyev_(const char*, const char*, const int*, double*,
                    const int*, const int*, const int*, double*, double*,
                    const int*, const int*, const int*, double*,
                    const int*, int*);
extern int slate_c_init(void);
extern void slate_c_finalize(void);

#define N 48
#define NB 8
#define P 2
#define Q 2

static void scatter(const double* g, double* loc, int m, int n,
                    int mb, int nb, int pr, int pc, int lld) {
    /* smoke-side independent block-cyclic indexing (checks ours) */
    const int izero = 0, pp = P, qq = Q;
    int mloc = numroc_(&m, &mb, &pr, &izero, &pp);
    int nloc = numroc_(&n, &nb, &pc, &izero, &qq);
    for (int jl = 0; jl < nloc; ++jl) {
        int jg = ((jl / nb) * Q + pc) * nb + jl % nb;
        for (int il = 0; il < mloc; ++il) {
            int ig = ((il / mb) * P + pr) * mb + il % mb;
            loc[jl * lld + il] = g[jg * m + ig];
        }
    }
}

static void gather(double* g, const double* loc, int m, int n,
                   int mb, int nb, int pr, int pc, int lld) {
    const int izero = 0, pp = P, qq = Q;
    int mloc = numroc_(&m, &mb, &pr, &izero, &pp);
    int nloc = numroc_(&n, &nb, &pc, &izero, &qq);
    for (int jl = 0; jl < nloc; ++jl) {
        int jg = ((jl / nb) * Q + pc) * nb + jl % nb;
        for (int il = 0; il < mloc; ++il) {
            int ig = ((il / mb) * P + pr) * mb + il % mb;
            g[jg * m + ig] = loc[jl * lld + il];
        }
    }
}

int main(void) {
    if (slate_c_init()) { fprintf(stderr, "init failed\n"); return 1; }
    int ctxt, info, p, q, pr, pc;
    const int n = N, nb = NB, ione = 1, izero = 0;
    Cblacs_gridinit(&ctxt, "Col", P, Q);

    /* SPD global matrix, column-major */
    static double A[N * N], L[N * N], Afac[N * N];
    srand(7);
    for (int j = 0; j < N; ++j)
        for (int i = 0; i <= j; ++i) {
            double v = (double)rand() / RAND_MAX - 0.5;
            A[j * N + i] = A[i * N + j] = v;
        }
    for (int i = 0; i < N; ++i) A[i * N + i] += N;

    /* ---- pdpotrf on the 2x2 grid ---- */
    double* loc[P * Q];
    int desc[9], lld[P * Q];
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P, pcc = r / P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        int nloc = numroc_(&n, &nb, &pcc, &izero, (const int[]){Q});
        lld[r] = mloc > 1 ? mloc : 1;
        loc[r] = (double*)malloc(sizeof(double) * (size_t)mloc * nloc);
        scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
    }
    for (int r = 0; r < P * Q; ++r) {
        Cblacs_gridinfo(ctxt, &p, &q, &pr, &pc);
        descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt,
                  &lld[r], &info);
        pdpotrf_("L", &n, loc[r], &ione, &ione, desc, &info);
        if (info != 0) { fprintf(stderr, "pdpotrf info=%d\n", info);
                         return 2; }
    }
    for (int r = 0; r < P * Q; ++r)
        gather(Afac, loc[r], n, n, nb, nb, r % P, r / P, lld[r]);
    /* residual |A - L L^T| / (|A| n eps) over the lower triangle */
    memset(L, 0, sizeof(L));
    for (int j = 0; j < N; ++j)
        for (int i = j; i < N; ++i) L[j * N + i] = Afac[j * N + i];
    double maxe = 0, amax = 0;
    for (int j = 0; j < N; ++j)
        for (int i = j; i < N; ++i) {
            double s = 0;
            for (int k = 0; k < N; ++k) s += L[k * N + i] * L[k * N + j];
            double e = fabs(s - A[j * N + i]);
            if (e > maxe) maxe = e;
            if (fabs(A[j * N + i]) > amax) amax = fabs(A[j * N + i]);
        }
    double scaled = maxe / (amax * N * 2.22e-16);
    printf("pdpotrf 2x2 scaled residual: %.3f\n", scaled);
    if (scaled > 10) { fprintf(stderr, "pdpotrf FAILED\n"); return 3; }

    /* ---- pdgesv on the same grid ---- */
    #define NRHS 4
    const int nrhs = NRHS;
    static double B[N * NRHS], X[N * NRHS];
    for (int i = 0; i < N * nrhs; ++i)
        B[i] = (double)rand() / RAND_MAX - 0.5;
    double* bloc[P * Q];
    int* iploc[P * Q];
    int descb[9];
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P, pcc = r / P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        int nloc = numroc_(&nrhs, &nb, &pcc, &izero, (const int[]){Q});
        scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
        bloc[r] = (double*)malloc(sizeof(double)
                                  * (size_t)mloc * (nloc ? nloc : 1));
        iploc[r] = (int*)malloc(sizeof(int) * (size_t)(mloc + nb));
        scatter(B, bloc[r], n, nrhs, nb, nb, prr, pcc, mloc);
    }
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        int lldb = mloc > 1 ? mloc : 1;
        descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt,
                  &lld[r], &info);
        descinit_(descb, &n, &nrhs, &nb, &nb, &izero, &izero, &ctxt,
                  &lldb, &info);
        pdgesv_(&n, &nrhs, loc[r], &ione, &ione, desc, iploc[r],
                bloc[r], &ione, &ione, descb, &info);
        if (info != 0) { fprintf(stderr, "pdgesv info=%d\n", info);
                         return 4; }
    }
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        gather(X, bloc[r], n, nrhs, nb, nb, prr, r / P, mloc);
    }
    maxe = 0;
    for (int j = 0; j < nrhs; ++j)
        for (int i = 0; i < N; ++i) {
            double s = 0;
            for (int k = 0; k < N; ++k) s += A[k * N + i] * X[j * N + k];
            double e = fabs(s - B[j * N + i]);
            if (e > maxe) maxe = e;
        }
    scaled = maxe / (amax * N * 2.22e-16);
    printf("pdgesv 2x2 scaled residual: %.3f\n", scaled);
    if (scaled > 100) { fprintf(stderr, "pdgesv FAILED\n"); return 5; }

    /* ---- pdgemm C = 0.5*A^T*A - 0.25*C ---- */
    static double C0[N * N], Cres[N * N];
    for (int i = 0; i < N * N; ++i) C0[i] = (double)rand() / RAND_MAX;
    double* cloc[P * Q];
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P, pcc = r / P;
        scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
        cloc[r] = (double*)malloc(sizeof(double) * (size_t)N * N);
        scatter(C0, cloc[r], n, n, nb, nb, prr, pcc, lld[r]);
    }
    const double alpha = 0.5, beta = -0.25;
    for (int r = 0; r < P * Q; ++r) {
        descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt,
                  &lld[r], &info);
        pdgemm_("T", "N", &n, &n, &n, &alpha, loc[r], &ione, &ione, desc,
                loc[r], &ione, &ione, desc, &beta, cloc[r], &ione, &ione,
                desc);
    }
    for (int r = 0; r < P * Q; ++r)
        gather(Cres, cloc[r], n, n, nb, nb, r % P, r / P, lld[r]);
    maxe = 0;
    for (int j = 0; j < N; ++j)
        for (int i = 0; i < N; ++i) {
            double s = 0;
            for (int k = 0; k < N; ++k) s += A[i * N + k] * A[j * N + k];
            double want = alpha * s + beta * C0[j * N + i];
            double e = fabs(want - Cres[j * N + i]);
            if (e > maxe) maxe = e;
        }
    scaled = maxe / (amax * amax * N * 2.22e-16);
    printf("pdgemm 2x2 scaled residual: %.3f\n", scaled);
    if (scaled > 10) { fprintf(stderr, "pdgemm FAILED\n"); return 7; }

    /* ---- pdgetrf + pdgetrs round-trip on the same grid ---- */
    static double XLU[N * NRHS];
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P, pcc = r / P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
        scatter(B, bloc[r], n, nrhs, nb, nb, prr, pcc, mloc);
    }
    for (int r = 0; r < P * Q; ++r) {
        descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt,
                  &lld[r], &info);
        pdgetrf_(&n, &n, loc[r], &ione, &ione, desc, iploc[r], &info);
        if (info != 0) { fprintf(stderr, "pdgetrf info=%d\n", info);
                         return 8; }
    }
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        int lldb = mloc > 1 ? mloc : 1;
        descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt,
                  &lld[r], &info);
        descinit_(descb, &n, &nrhs, &nb, &nb, &izero, &izero, &ctxt,
                  &lldb, &info);
        pdgetrs_("N", &n, &nrhs, loc[r], &ione, &ione, desc, iploc[r],
                 bloc[r], &ione, &ione, descb, &info);
        if (info != 0) { fprintf(stderr, "pdgetrs info=%d\n", info);
                         return 9; }
    }
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        gather(XLU, bloc[r], n, nrhs, nb, nb, prr, r / P, mloc);
    }
    maxe = 0;
    for (int j = 0; j < nrhs; ++j)
        for (int i = 0; i < N; ++i) {
            double s = 0;
            for (int k = 0; k < N; ++k) s += A[k * N + i] * XLU[j * N + k];
            double e = fabs(s - B[j * N + i]);
            if (e > maxe) maxe = e;
        }
    scaled = maxe / (amax * N * 2.22e-16);
    printf("pdgetrf+pdgetrs 2x2 scaled residual: %.3f\n", scaled);
    if (scaled > 100) { fprintf(stderr, "pdgetrf/s FAILED\n"); return 10; }

    /* ---- windowed pdgemm: ia/ja != 1 submatrices ---- */
    /* global (N x N) arrays; multiply the 16x16 windows A(9:24, 5:20)
     * and A(17:32, 9:24) into C0's window at (3, 7) */
    {
        const int wm = 16, ia = 9, ja = 5, ib2 = 17, jb2 = 9,
                  ic = 3, jc = 7;
        for (int r = 0; r < P * Q; ++r) {
            int prr = r % P, pcc = r / P;
            scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
            scatter(C0, cloc[r], n, n, nb, nb, prr, pcc, lld[r]);
        }
        const double al2 = 1.0, be2 = 0.0;
        for (int r = 0; r < P * Q; ++r) {
            descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt,
                      &lld[r], &info);
            pdgemm_("N", "N", &wm, &wm, &wm, &al2,
                    loc[r], &ia, &ja, desc,
                    loc[r], &ib2, &jb2, desc, &be2,
                    cloc[r], &ic, &jc, desc);
        }
        for (int r = 0; r < P * Q; ++r)
            gather(Cres, cloc[r], n, n, nb, nb, r % P, r / P, lld[r]);
        maxe = 0;
        for (int j = 0; j < wm; ++j)
            for (int i = 0; i < wm; ++i) {
                double s = 0;
                for (int k = 0; k < wm; ++k)
                    s += A[(ja - 1 + k) * N + (ia - 1 + i)]
                       * A[(jb2 - 1 + j) * N + (ib2 - 1 + k)];
                double e = fabs(s - Cres[(jc - 1 + j) * N + (ic - 1 + i)]);
                if (e > maxe) maxe = e;
            }
        /* untouched entries outside the C window must be preserved */
        double keep = fabs(Cres[0] - C0[0])
                    + fabs(Cres[(N - 1) * N + N - 1] - C0[(N - 1) * N + N - 1]);
        scaled = maxe / (amax * amax * wm * 2.22e-16);
        printf("pdgemm windowed (ia/ja!=1) scaled residual: %.3f\n", scaled);
        if (scaled > 10 || keep != 0.0) {
            fprintf(stderr, "windowed pdgemm FAILED (keep=%g)\n", keep);
            return 11;
        }
    }
    Cblacs_gridexit(ctxt);

    /* ---- row-major grid order: pdpotrf on a "Row" grid ---- */
    {
        int ctxt2;
        Cblacs_gridinit(&ctxt2, "Row", P, Q);
        /* rank r -> (r / Q, r % Q) under row-major order */
        for (int r = 0; r < P * Q; ++r) {
            int prr = r / Q, pcc = r % Q;
            scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
        }
        for (int r = 0; r < P * Q; ++r) {
            Cblacs_gridinfo(ctxt2, &p, &q, &pr, &pc);
            if (pr != r / Q || pc != r % Q) {
                fprintf(stderr, "row-order gridinfo mismatch r=%d\n", r);
                return 12;
            }
            descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt2,
                      &lld[r], &info);
            pdpotrf_("L", &n, loc[r], &ione, &ione, desc, &info);
            if (info != 0) { fprintf(stderr, "row pdpotrf info=%d\n", info);
                             return 13; }
            Cblacs_barrier(ctxt2, "All");
        }
        for (int r = 0; r < P * Q; ++r)
            gather(Afac, loc[r], n, n, nb, nb, r / Q, r % Q, lld[r]);
        memset(L, 0, sizeof(L));
        for (int j = 0; j < N; ++j)
            for (int i = j; i < N; ++i) L[j * N + i] = Afac[j * N + i];
        maxe = 0;
        for (int j = 0; j < N; ++j)
            for (int i = j; i < N; ++i) {
                double s = 0;
                for (int k = 0; k < N; ++k) s += L[k * N + i] * L[k * N + j];
                double e = fabs(s - A[j * N + i]);
                if (e > maxe) maxe = e;
            }
        scaled = maxe / (amax * N * 2.22e-16);
        printf("pdpotrf row-order scaled residual: %.3f\n", scaled);
        if (scaled > 10) { fprintf(stderr, "row pdpotrf FAILED\n"); return 14; }
        Cblacs_gridexit(ctxt2);
    }

    /* ---- pdpotrs / pdtrsm / pdlange / pdsyev on a fresh Col grid ---- */
    {
        int ctxt3;
        Cblacs_gridinit(&ctxt3, "Col", P, Q);
        /* potrs: solve with the factor computed earlier (Afac holds L) */
        for (int r = 0; r < P * Q; ++r) {
            int prr = r % P, pcc = r / P;
            int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
            scatter(Afac, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
            scatter(B, bloc[r], n, nrhs, nb, nb, prr, pcc, mloc);
        }
        for (int r = 0; r < P * Q; ++r) {
            int prr = r % P;
            int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
            int lldb = mloc > 1 ? mloc : 1;
            descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt3,
                      &lld[r], &info);
            descinit_(descb, &n, &nrhs, &nb, &nb, &izero, &izero, &ctxt3,
                      &lldb, &info);
            pdpotrs_("L", &n, &nrhs, loc[r], &ione, &ione, desc,
                     bloc[r], &ione, &ione, descb, &info);
            if (info != 0) { fprintf(stderr, "pdpotrs info=%d\n", info);
                             return 15; }
        }
        for (int r = 0; r < P * Q; ++r) {
            int prr = r % P;
            int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
            gather(X, bloc[r], n, nrhs, nb, nb, prr, r / P, mloc);
        }
        maxe = 0;
        for (int j = 0; j < nrhs; ++j)
            for (int i = 0; i < N; ++i) {
                double s = 0;
                for (int k2 = 0; k2 < N; ++k2)
                    s += A[k2 * N + i] * X[j * N + k2];
                double e = fabs(s - B[j * N + i]);
                if (e > maxe) maxe = e;
            }
        scaled = maxe / (amax * N * 2.22e-16);
        printf("pdpotrs scaled residual: %.3f\n", scaled);
        if (scaled > 100) { fprintf(stderr, "pdpotrs FAILED\n"); return 16; }

        /* trsm, side=Right trans=T unit-diag: X L1^T = alpha B with L1
         * unit-lower from Afac; check X L1^T recovers alpha B */
        const double al3 = 2.0;
        for (int r = 0; r < P * Q; ++r) {
            int prr = r % P, pcc = r / P;
            int nloc_r = numroc_(&nrhs, &nb, &prr, &izero, (const int[]){P});
            (void)nloc_r;
            scatter(Afac, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
        }
        /* B2 is nrhs x n (rows = nrhs) so side=R dims differ from m */
        static double B2[NRHS * N], X2[NRHS * N];
        for (int i = 0; i < NRHS * N; ++i)
            B2[i] = (double)rand() / RAND_MAX - 0.5;
        double* b2loc[P * Q];
        int descb2[9];
        for (int r = 0; r < P * Q; ++r) {
            int prr = r % P, pcc = r / P;
            int mloc = numroc_((const int[]){NRHS}, &nb, &prr, &izero,
                               (const int[]){P});
            int nloc = numroc_(&n, &nb, &pcc, &izero, (const int[]){Q});
            (void)nloc;
            b2loc[r] = (double*)malloc(sizeof(double) * (size_t)NRHS * N);
            scatter(B2, b2loc[r], NRHS, n, nb, nb, prr, pcc,
                    mloc > 1 ? mloc : 1);
        }
        for (int r = 0; r < P * Q; ++r) {
            int prr = r % P;
            int mloc = numroc_((const int[]){NRHS}, &nb, &prr, &izero,
                               (const int[]){P});
            int lldb2 = mloc > 1 ? mloc : 1;
            const int nr = NRHS;
            descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt3,
                      &lld[r], &info);
            descinit_(descb2, &nr, &n, &nb, &nb, &izero, &izero, &ctxt3,
                      &lldb2, &info);
            pdtrsm_("R", "L", "T", "U", &nr, &n, &al3,
                    loc[r], &ione, &ione, desc,
                    b2loc[r], &ione, &ione, descb2);
        }
        for (int r = 0; r < P * Q; ++r) {
            int prr = r % P;
            int mloc = numroc_((const int[]){NRHS}, &nb, &prr, &izero,
                               (const int[]){P});
            gather(X2, b2loc[r], NRHS, n, nb, nb, prr, r / P,
                   mloc > 1 ? mloc : 1);
        }
        /* check X2 * L1^T == al3 * B2 where L1 = unit-lower(Afac):
         * (X L1^T)[i,j] = sum_{k<=j} X[i,k] * L1[j,k] */
        maxe = 0;
        for (int j = 0; j < N; ++j)
            for (int i = 0; i < NRHS; ++i) {
                double s = 0;
                for (int k2 = 0; k2 <= j; ++k2) {
                    double ljk = (k2 == j) ? 1.0 : Afac[k2 * N + j];
                    s += X2[k2 * NRHS + i] * ljk;
                }
                double e = fabs(s - al3 * B2[j * NRHS + i]);
                if (e > maxe) maxe = e;
            }
        scaled = maxe / (amax * N * 2.22e-16);
        printf("pdtrsm R/T/U scaled residual: %.3f\n", scaled);
        if (scaled > 100) { fprintf(stderr, "pdtrsm FAILED\n"); return 17; }
        for (int r = 0; r < P * Q; ++r) free(b2loc[r]);

        /* pdlange: Frobenius norm of A (value on the completing call) */
        double fro = 0;
        for (int i = 0; i < N * N; ++i) fro += A[i] * A[i];
        fro = sqrt(fro);
        for (int r = 0; r < P * Q; ++r) {
            int prr = r % P, pcc = r / P;
            scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
        }
        double got = 0;
        for (int r = 0; r < P * Q; ++r) {
            descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt3,
                      &lld[r], &info);
            double v = pdlange_("F", &n, &n, loc[r], &ione, &ione, desc, 0);
            if (v != 0.0) got = v;
        }
        printf("pdlange F: got %.6f want %.6f\n", got, fro);
        if (fabs(got - fro) > 1e-8 * fro) {
            fprintf(stderr, "pdlange FAILED\n"); return 18;
        }

        /* pdsyev: eigenvalues/vectors of symmetric A */
        static double W[N], Z[N * N];
        double* zloc[P * Q];
        int descz[9];
        for (int r = 0; r < P * Q; ++r) {
            int prr = r % P, pcc = r / P;
            scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
            zloc[r] = (double*)malloc(sizeof(double) * (size_t)N * N);
            memset(zloc[r], 0, sizeof(double) * (size_t)N * N);
        }
        static double Wr[P * Q][N];
        for (int r = 0; r < P * Q; ++r) {
            const int lwork_q = 4 * N;
            static double wk[4 * N];
            descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt3,
                      &lld[r], &info);
            descinit_(descz, &n, &n, &nb, &nb, &izero, &izero, &ctxt3,
                      &lld[r], &info);
            pdsyev_("V", "L", &n, loc[r], &ione, &ione, desc, Wr[r],
                    zloc[r], &ione, &ione, descz, wk,
                    (const int[]){lwork_q}, &info);
            if (info != 0) { fprintf(stderr, "pdsyev info=%d\n", info);
                             return 19; }
        }
        memcpy(W, Wr[P * Q - 1], sizeof(double) * N);
        for (int r = 0; r < P * Q; ++r)
            gather(Z, zloc[r], n, n, nb, nb, r % P, r / P, lld[r]);
        /* residual |A z - w z| and w replication across ranks */
        maxe = 0;
        for (int j = 0; j < N; ++j)
            for (int i = 0; i < N; ++i) {
                double s = 0;
                for (int k2 = 0; k2 < N; ++k2)
                    s += A[k2 * N + i] * Z[j * N + k2];
                double e = fabs(s - W[j] * Z[j * N + i]);
                if (e > maxe) maxe = e;
            }
        scaled = maxe / (amax * N * 2.22e-16);
        printf("pdsyev scaled residual: %.3f\n", scaled);
        if (scaled > 100) { fprintf(stderr, "pdsyev FAILED\n"); return 20; }
        for (int r = 0; r < P * Q; ++r) {
            if (memcmp(Wr[r], W, sizeof(double) * N)) {
                fprintf(stderr, "pdsyev w not replicated\n"); return 21;
            }
            free(zloc[r]); free(cloc[r]); free(loc[r]); free(bloc[r]);
            free(iploc[r]);
        }
        Cblacs_gridexit(ctxt3);
    }

    printf("ok: ScaLAPACK API smoke (2x2 grid round-trip)\n");
    slate_c_finalize();
    return 0;
}
