/* ScaLAPACK compatibility API smoke: round-trip a 2x2-grid
 * block-cyclic pdpotrf + pdgesv + pdgemm through the drop-in symbols
 * (reference analog: scalapack_api/example_pdgetrf.c).
 *
 * The single-controller BLACS emulation plays all four virtual ranks
 * in sequence: Cblacs_gridinfo reports the coordinates of the rank
 * whose turn it is, and the fourth p? call triggers the actual
 * computation (see src/c_api/scalapack_api.c header).
 *
 * build: see examples/build_c_smoke.sh
 */

#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

extern void Cblacs_gridinit(int*, const char*, int, int);
extern void Cblacs_gridinfo(int, int*, int*, int*, int*);
extern void Cblacs_gridexit(int);
extern int numroc_(const int*, const int*, const int*, const int*,
                   const int*);
extern void descinit_(int*, const int*, const int*, const int*, const int*,
                      const int*, const int*, const int*, const int*, int*);
extern void pdpotrf_(const char*, const int*, double*, const int*,
                     const int*, const int*, int*);
extern void pdgesv_(const int*, const int*, double*, const int*, const int*,
                    const int*, int*, double*, const int*, const int*,
                    const int*, int*);
extern void pdgemm_(const char*, const char*, const int*, const int*,
                    const int*, const double*, double*, const int*,
                    const int*, const int*, double*, const int*, const int*,
                    const int*, const double*, double*, const int*,
                    const int*, const int*, int*);
extern int slate_c_init(void);
extern void slate_c_finalize(void);

#define N 48
#define NB 8
#define P 2
#define Q 2

static void scatter(const double* g, double* loc, int m, int n,
                    int mb, int nb, int pr, int pc, int lld) {
    /* smoke-side independent block-cyclic indexing (checks ours) */
    const int izero = 0, pp = P, qq = Q;
    int mloc = numroc_(&m, &mb, &pr, &izero, &pp);
    int nloc = numroc_(&n, &nb, &pc, &izero, &qq);
    for (int jl = 0; jl < nloc; ++jl) {
        int jg = ((jl / nb) * Q + pc) * nb + jl % nb;
        for (int il = 0; il < mloc; ++il) {
            int ig = ((il / mb) * P + pr) * mb + il % mb;
            loc[jl * lld + il] = g[jg * m + ig];
        }
    }
}

static void gather(double* g, const double* loc, int m, int n,
                   int mb, int nb, int pr, int pc, int lld) {
    const int izero = 0, pp = P, qq = Q;
    int mloc = numroc_(&m, &mb, &pr, &izero, &pp);
    int nloc = numroc_(&n, &nb, &pc, &izero, &qq);
    for (int jl = 0; jl < nloc; ++jl) {
        int jg = ((jl / nb) * Q + pc) * nb + jl % nb;
        for (int il = 0; il < mloc; ++il) {
            int ig = ((il / mb) * P + pr) * mb + il % mb;
            g[jg * m + ig] = loc[jl * lld + il];
        }
    }
}

int main(void) {
    if (slate_c_init()) { fprintf(stderr, "init failed\n"); return 1; }
    int ctxt, info, p, q, pr, pc;
    const int n = N, nb = NB, ione = 1, izero = 0;
    Cblacs_gridinit(&ctxt, "Col", P, Q);

    /* SPD global matrix, column-major */
    static double A[N * N], L[N * N], Afac[N * N];
    srand(7);
    for (int j = 0; j < N; ++j)
        for (int i = 0; i <= j; ++i) {
            double v = (double)rand() / RAND_MAX - 0.5;
            A[j * N + i] = A[i * N + j] = v;
        }
    for (int i = 0; i < N; ++i) A[i * N + i] += N;

    /* ---- pdpotrf on the 2x2 grid ---- */
    double* loc[P * Q];
    int desc[9], lld[P * Q];
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P, pcc = r / P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        int nloc = numroc_(&n, &nb, &pcc, &izero, (const int[]){Q});
        lld[r] = mloc > 1 ? mloc : 1;
        loc[r] = (double*)malloc(sizeof(double) * (size_t)mloc * nloc);
        scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
    }
    for (int r = 0; r < P * Q; ++r) {
        Cblacs_gridinfo(ctxt, &p, &q, &pr, &pc);
        descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt,
                  &lld[r], &info);
        pdpotrf_("L", &n, loc[r], &ione, &ione, desc, &info);
        if (info != 0) { fprintf(stderr, "pdpotrf info=%d\n", info);
                         return 2; }
    }
    for (int r = 0; r < P * Q; ++r)
        gather(Afac, loc[r], n, n, nb, nb, r % P, r / P, lld[r]);
    /* residual |A - L L^T| / (|A| n eps) over the lower triangle */
    memset(L, 0, sizeof(L));
    for (int j = 0; j < N; ++j)
        for (int i = j; i < N; ++i) L[j * N + i] = Afac[j * N + i];
    double maxe = 0, amax = 0;
    for (int j = 0; j < N; ++j)
        for (int i = j; i < N; ++i) {
            double s = 0;
            for (int k = 0; k < N; ++k) s += L[k * N + i] * L[k * N + j];
            double e = fabs(s - A[j * N + i]);
            if (e > maxe) maxe = e;
            if (fabs(A[j * N + i]) > amax) amax = fabs(A[j * N + i]);
        }
    double scaled = maxe / (amax * N * 2.22e-16);
    printf("pdpotrf 2x2 scaled residual: %.3f\n", scaled);
    if (scaled > 10) { fprintf(stderr, "pdpotrf FAILED\n"); return 3; }

    /* ---- pdgesv on the same grid ---- */
    #define NRHS 4
    const int nrhs = NRHS;
    static double B[N * NRHS], X[N * NRHS];
    for (int i = 0; i < N * nrhs; ++i)
        B[i] = (double)rand() / RAND_MAX - 0.5;
    double* bloc[P * Q];
    int* iploc[P * Q];
    int descb[9];
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P, pcc = r / P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        int nloc = numroc_(&nrhs, &nb, &pcc, &izero, (const int[]){Q});
        scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
        bloc[r] = (double*)malloc(sizeof(double)
                                  * (size_t)mloc * (nloc ? nloc : 1));
        iploc[r] = (int*)malloc(sizeof(int) * (size_t)(mloc + nb));
        scatter(B, bloc[r], n, nrhs, nb, nb, prr, pcc, mloc);
    }
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        int lldb = mloc > 1 ? mloc : 1;
        descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt,
                  &lld[r], &info);
        descinit_(descb, &n, &nrhs, &nb, &nb, &izero, &izero, &ctxt,
                  &lldb, &info);
        pdgesv_(&n, &nrhs, loc[r], &ione, &ione, desc, iploc[r],
                bloc[r], &ione, &ione, descb, &info);
        if (info != 0) { fprintf(stderr, "pdgesv info=%d\n", info);
                         return 4; }
    }
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P;
        int mloc = numroc_(&n, &nb, &prr, &izero, (const int[]){P});
        gather(X, bloc[r], n, nrhs, nb, nb, prr, r / P, mloc);
    }
    maxe = 0;
    for (int j = 0; j < nrhs; ++j)
        for (int i = 0; i < N; ++i) {
            double s = 0;
            for (int k = 0; k < N; ++k) s += A[k * N + i] * X[j * N + k];
            double e = fabs(s - B[j * N + i]);
            if (e > maxe) maxe = e;
        }
    scaled = maxe / (amax * N * 2.22e-16);
    printf("pdgesv 2x2 scaled residual: %.3f\n", scaled);
    if (scaled > 100) { fprintf(stderr, "pdgesv FAILED\n"); return 5; }

    /* ---- pdgemm C = 0.5*A^T*A - 0.25*C ---- */
    static double C0[N * N], Cres[N * N];
    for (int i = 0; i < N * N; ++i) C0[i] = (double)rand() / RAND_MAX;
    double* cloc[P * Q];
    for (int r = 0; r < P * Q; ++r) {
        int prr = r % P, pcc = r / P;
        scatter(A, loc[r], n, n, nb, nb, prr, pcc, lld[r]);
        cloc[r] = (double*)malloc(sizeof(double) * (size_t)N * N);
        scatter(C0, cloc[r], n, n, nb, nb, prr, pcc, lld[r]);
    }
    const double alpha = 0.5, beta = -0.25;
    for (int r = 0; r < P * Q; ++r) {
        descinit_(desc, &n, &n, &nb, &nb, &izero, &izero, &ctxt,
                  &lld[r], &info);
        pdgemm_("T", "N", &n, &n, &n, &alpha, loc[r], &ione, &ione, desc,
                loc[r], &ione, &ione, desc, &beta, cloc[r], &ione, &ione,
                desc, &info);
        if (info != 0) { fprintf(stderr, "pdgemm info=%d\n", info);
                         return 6; }
    }
    for (int r = 0; r < P * Q; ++r)
        gather(Cres, cloc[r], n, n, nb, nb, r % P, r / P, lld[r]);
    maxe = 0;
    for (int j = 0; j < N; ++j)
        for (int i = 0; i < N; ++i) {
            double s = 0;
            for (int k = 0; k < N; ++k) s += A[i * N + k] * A[j * N + k];
            double want = alpha * s + beta * C0[j * N + i];
            double e = fabs(want - Cres[j * N + i]);
            if (e > maxe) maxe = e;
        }
    scaled = maxe / (amax * amax * N * 2.22e-16);
    printf("pdgemm 2x2 scaled residual: %.3f\n", scaled);
    if (scaled > 10) { fprintf(stderr, "pdgemm FAILED\n"); return 7; }

    Cblacs_gridexit(ctxt);
    printf("ok: ScaLAPACK API smoke (2x2 grid round-trip)\n");
    slate_c_finalize();
    return 0;
}
