/* Driver C API smoke test — exercises gesv/posv/gels/heev/svd through
 * the generated C ABI (include/slate_tpu_driver.h), the analog of the
 * reference's C API examples (include/slate/c_api/).
 *
 * build (see examples/build_c_smoke.sh):
 *   gcc c_api_driver_smoke.c ../src/c_api/c_api_core.c \
 *       ../src/c_api/driver_api.c -I../include \
 *       $(python3-config --includes) $(python3-config --ldflags --embed) \
 *       -o c_driver_smoke
 * run with PYTHONPATH pointing at the repo + venv site-packages.
 */
#include <stdio.h>
#include <stdlib.h>
#include <math.h>
#include "slate_tpu_driver.h"

static double frand(void) { return rand() / (double)RAND_MAX - 0.5; }

int main(void) {
    const int64_t n = 96, nrhs = 3, m = 160;
    int fails = 0;
    srand(7);

    if (slate_c_init() != 0) { printf("init failed\n"); return 1; }

    /* ---- dgesv ---- */
    double *a = malloc(n * n * sizeof *a);
    double *b = malloc(n * nrhs * sizeof *b);
    double *x = malloc(n * nrhs * sizeof *x);
    int64_t *ipiv = malloc(n * sizeof *ipiv);
    for (int64_t i = 0; i < n * n; ++i) a[i] = frand();
    for (int64_t i = 0; i < n; ++i) a[i * n + i] += n;
    for (int64_t i = 0; i < n * nrhs; ++i) b[i] = frand();
    if (slate_dgesv(n, n, a, n, nrhs, b, n, x, ipiv) != 0) {
        printf("dgesv: call failed\n"); fails++;
    } else {
        double r = 0, nb2 = 0;
        for (int64_t c = 0; c < nrhs; ++c)
            for (int64_t i = 0; i < n; ++i) {
                double s = 0;
                for (int64_t k = 0; k < n; ++k)
                    s += a[k * n + i] * x[c * n + k];
                double d = s - b[c * n + i];
                r += d * d; nb2 += b[c * n + i] * b[c * n + i];
            }
        printf("dgesv resid: %.2e %s\n", sqrt(r / nb2),
               sqrt(r / nb2) < 1e-10 ? "ok" : "FAIL");
        if (!(sqrt(r / nb2) < 1e-10)) fails++;
    }

    /* ---- dposv ---- */
    double *spd = malloc(n * n * sizeof *spd);
    for (int64_t j = 0; j < n; ++j)
        for (int64_t i = 0; i <= j; ++i) {
            double s = (i == j) ? (double)n : 0.0;
            for (int64_t k = 0; k < n; ++k)
                s += a[i * n + k] * a[j * n + k];
            spd[j * n + i] = s; spd[i * n + j] = s;
        }
    if (slate_dposv(n, n, spd, n, nrhs, b, n, x, 'L') != 0) {
        printf("dposv: call failed\n"); fails++;
    } else {
        double r = 0, nb2 = 0;
        for (int64_t c = 0; c < nrhs; ++c)
            for (int64_t i = 0; i < n; ++i) {
                double s = 0;
                for (int64_t k = 0; k < n; ++k)
                    s += spd[k * n + i] * x[c * n + k];
                double d = s - b[c * n + i];
                r += d * d; nb2 += b[c * n + i] * b[c * n + i];
            }
        printf("dposv resid: %.2e %s\n", sqrt(r / nb2),
               sqrt(r / nb2) < 1e-9 ? "ok" : "FAIL");
        if (!(sqrt(r / nb2) < 1e-9)) fails++;
    }

    /* ---- dgels (tall least squares) ---- */
    double *ta = malloc(m * n * sizeof *ta);
    double *tb = malloc(m * nrhs * sizeof *tb);
    double *tx = malloc(n * nrhs * sizeof *tx);
    for (int64_t i = 0; i < m * n; ++i) ta[i] = frand();
    for (int64_t i = 0; i < m * nrhs; ++i) tb[i] = frand();
    if (slate_dgels(m, n, ta, m, nrhs, tb, m, tx, 'L') != 0) {
        printf("dgels: call failed\n"); fails++;
    } else {
        /* normal equations residual: A^T (A x - b) ~ 0 */
        double r = 0;
        for (int64_t c = 0; c < nrhs; ++c)
            for (int64_t j = 0; j < n; ++j) {
                double s = 0;
                for (int64_t i = 0; i < m; ++i) {
                    double ax = 0;
                    for (int64_t k = 0; k < n; ++k)
                        ax += ta[k * m + i] * tx[c * n + k];
                    s += ta[j * m + i] * (ax - tb[c * m + i]);
                }
                r += s * s;
            }
        printf("dgels normal-eq resid: %.2e %s\n", sqrt(r),
               sqrt(r) < 1e-8 ? "ok" : "FAIL");
        if (!(sqrt(r) < 1e-8)) fails++;
    }

    /* ---- dheev ---- */
    double *w = malloc(n * sizeof *w);
    double *z = malloc(n * n * sizeof *z);
    if (slate_dheev(n, spd, n, w, z, 'L') != 0) {
        printf("dheev: call failed\n"); fails++;
    } else {
        /* A z_0 = w_0 z_0 */
        double r = 0, nz = 0;
        for (int64_t i = 0; i < n; ++i) {
            double s = 0;
            for (int64_t k = 0; k < n; ++k)
                s += spd[k * n + i] * z[0 * n + k];
            double d = s - w[0] * z[0 * n + i];
            r += d * d; nz += z[0 * n + i] * z[0 * n + i];
        }
        printf("dheev resid: %.2e %s\n", sqrt(r / nz) / w[n - 1],
               sqrt(r / nz) / w[n - 1] < 1e-10 ? "ok" : "FAIL");
        if (!(sqrt(r / nz) / w[n - 1] < 1e-10)) fails++;
    }

    /* ---- dsvd ---- */
    double *s = malloc(n * sizeof *s);
    double *u = malloc(m * n * sizeof *u);
    double *vt = malloc(n * n * sizeof *vt);
    if (slate_dsvd(m, n, ta, m, s, u, vt) != 0) {
        printf("dsvd: call failed\n"); fails++;
    } else {
        /* || A v_0 - s_0 u_0 || */
        double r = 0;
        for (int64_t i = 0; i < m; ++i) {
            double av = 0;
            for (int64_t k = 0; k < n; ++k)
                av += ta[k * m + i] * vt[k * n + 0];
            double d = av - s[0] * u[0 * m + i];
            r += d * d;
        }
        printf("dsvd resid: %.2e %s\n", sqrt(r) / s[0],
               sqrt(r) / s[0] < 1e-10 ? "ok" : "FAIL");
        if (!(sqrt(r) / s[0] < 1e-10)) fails++;
    }

    /* ---- sgemm (f32 path) ---- */
    float *fa = malloc(n * n * sizeof *fa);
    float *fc = malloc(n * n * sizeof *fc);
    for (int64_t i = 0; i < n * n; ++i) fa[i] = (float)frand();
    if (slate_sgemm(n, n, fa, n, n, fa, n, fc, 'L') != 0) {
        printf("sgemm: call failed\n"); fails++;
    } else {
        double maxd = 0;
        for (int64_t j = 0; j < n; j += 17)
            for (int64_t i = 0; i < n; i += 13) {
                double s2 = 0;
                for (int64_t k = 0; k < n; ++k)
                    s2 += (double)fa[k * n + i] * fa[j * n + k];
                double d = fabs(s2 - fc[j * n + i]);
                if (d > maxd) maxd = d;
            }
        printf("sgemm maxdiff: %.2e %s\n", maxd,
               maxd < 1e-3 ? "ok" : "FAIL");
        if (!(maxd < 1e-3)) fails++;
    }

    slate_c_finalize();
    printf(fails ? "C DRIVER SMOKE: %d FAILURES\n"
                 : "C DRIVER SMOKE: all ok\n", fails);
    return fails ? 1 : 0;
}
