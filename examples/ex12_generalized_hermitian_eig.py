"""Generalized Hermitian-definite eig (reference
ex12_generalized_hermitian_eig.cc): hegv = potrf + hegst + heev."""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import scipy.linalg
import slate_tpu as st
from slate_tpu.testing import random_spd

rng = np.random.default_rng(9)
n = 32
x0 = rng.standard_normal((n, n))
a = jnp.asarray((x0 + x0.T) / 2, jnp.float32)
b = random_spd(n, dtype=jnp.float32, seed=10)
A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=16, nb=16)
B = st.HermitianMatrix(b, uplo=st.Uplo.Lower, mb=16, nb=16)
w, z = st.hegv(A, B)
wr = scipy.linalg.eigh(np.asarray(a), np.asarray(b), eigvals_only=True)
assert np.abs(np.asarray(w) - wr).max() < 1e-2
print("ok: generalized eigenvalues match")
