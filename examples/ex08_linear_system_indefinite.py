"""Hermitian-indefinite solve (reference ex08_linear_system_indefinite.cc):
hesv via the pivoted LTL^H factorization."""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st

rng = np.random.default_rng(5)
n = 64
x0 = rng.standard_normal((n, n))
a = jnp.asarray((x0 + x0.T) / 2, jnp.float32)   # indefinite symmetric
b = jnp.asarray(rng.standard_normal((n, 3)), jnp.float32)
A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=16, nb=16)
fac, x = st.hesv(A, b)
r = np.linalg.norm(np.asarray(a) @ np.asarray(x) - np.asarray(b))
assert r / (np.linalg.norm(np.asarray(a)) * n) < 1e-4, r
print("ok: hesv residual", r)
