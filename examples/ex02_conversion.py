"""Precision/type conversion copies (reference examples/ex02_conversion.cc).

slate::copy converts precision tile by tile; here `copy` is one fused cast.
"""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st

a = st.Matrix.from_array(jnp.asarray(np.random.default_rng(0)
                                     .standard_normal((64, 64)), jnp.float32),
                         mb=16, nb=16)
a16 = st.copy(a, dtype=jnp.bfloat16)
assert a16.array.dtype == jnp.bfloat16
back = st.copy(a16, dtype=jnp.float32)
assert np.abs(np.asarray(back.array) - np.asarray(a.array)).max() < 0.02
print("ok: precision-converting copy")
