"""LU linear systems (reference examples/ex06_linear_system_lu.cc):
gesv, getrf+getrs, mixed-precision iterative refinement."""
import _path  # noqa: F401  (in-tree import bootstrap)
import jax.numpy as jnp
import numpy as np
import slate_tpu as st

rng = np.random.default_rng(2)
n = 96
a = jnp.asarray(rng.standard_normal((n, n)) + n * np.eye(n), jnp.float32)
b = jnp.asarray(rng.standard_normal((n, 4)), jnp.float32)
lu, piv, x = st.gesv(a, b)
r = np.linalg.norm(np.asarray(a) @ np.asarray(x) - np.asarray(b))
assert r / (np.linalg.norm(np.asarray(a)) * n) < 1e-5
x2, info = st.gesv_mixed(a, b)[:2] if isinstance(st.gesv_mixed(a, b), tuple) else (st.gesv_mixed(a, b), 0)
print("ok: lu solve residual", r)
