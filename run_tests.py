#!/usr/bin/env python
"""Suite orchestrator over tester.py — the analog of the reference's
``test/run_tests.py`` (size classes, per-run timeouts, summary, exit
code for CI).

Routines run IN-PROCESS by default so every sweep shares one jit cache
(the round-3 suite paid a fresh XLA compile per routine subprocess and
blew past the reference's --quick CI budget); ``--isolate`` restores the
one-subprocess-per-routine mode (fresh compile, hard timeouts) for
debugging a routine that corrupts global state.

Usage:
  python run_tests.py --quick              # small dims, every routine
  python run_tests.py -m                   # medium dims
  python run_tests.py --routines gemm,posv --types s,d
  python run_tests.py --dist               # distributed routines too
                                           # (use a CPU mesh: JAX_PLATFORMS=cpu
                                           #  XLA_FLAGS=--xla_force_host_platform_device_count=8)
"""

from __future__ import annotations

import argparse
import os
import pathlib
import subprocess
import sys
import time

QUICK = "128"
SMALL = "256"
MEDIUM = "512,1024"

#: the --chaos tier's canned low-rate deterministic fault plan
#: (slate_tpu/resilience/inject.py grammar): every routine suite runs
#: once with these faults firing and SLATE_TPU_HEALTH=retry +
#: SLATE_TPU_ABFT=correct degrading around them — green means the
#: resilience ladder absorbs them.  ISSUE 14 adds the numerical-silent
#: kinds: ``bitflip`` at the trailing-update seam (ABFT detects,
#: locates, corrects or recomputes) and ``device_loss`` at the step
#: boundary (checkpoint/restart resumes); zero stranded work and every
#: answer residual-gated, mirroring the PR 9 serve-chaos shape.
CHAOS_PLAN = ("driver.output=nan:0.02,autotune.probe=error:0.05,"
              "serve.dispatch=error:0.05,driver.update=bitflip:0.05,"
              "step.boundary=device_loss:0.02:2")
CHAOS_SEED = "20260803"

SINGLE = ["gemm", "symm", "hemm", "syrk", "herk", "syr2k", "her2k", "trmm",
          "trsm", "norm", "potrf", "potrs", "posv", "posv_mixed", "potri",
          "trtri", "getrf", "gesv", "gesv_mixed", "getri", "geqrf", "gelqf",
          "cholqr", "gels", "hesv", "gbsv", "heev", "svd"]
DIST = ["ppotrf", "pgesv", "pgeqrf"]
# the dense two-stage eig/SVD and inverse testers are O(n^3) with big
# constants at small nb — keep their dims small in every class
SLOW = {"heev", "svd", "getri", "gesv_mixed", "hesv", "trtri",
        "potri", "posv_mixed"}


def telemetry_smoke() -> int:
    """The --telemetry tier: serve one request with live telemetry on,
    scrape the Prometheus endpoint once over a real socket, and check
    the serve counters + latency histogram made it out — the ISSUE 10
    end-to-end path (queue → histogram → exporter) in a few seconds."""
    import urllib.request

    import numpy as np

    from slate_tpu.perf import telemetry
    from slate_tpu.serve.queue import BatchQueue, ServeConfig

    telemetry.on()
    port = telemetry.start_exporter(0)      # ephemeral: no port clashes
    srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002))
    n = 16
    rng = np.random.default_rng(0)
    g = rng.standard_normal((n, n)).astype(np.float32)
    spd = g @ g.T + n * np.eye(n, dtype=np.float32)
    rhs = rng.standard_normal(n).astype(np.float32)
    x = np.asarray(srv.submit("posv", spd, rhs).result(timeout=300))
    srv.close()
    resid = (np.linalg.norm(spd @ x - rhs)
             / (np.linalg.norm(spd) * np.linalg.norm(rhs)
                * float(np.finfo(np.float32).eps) * n))
    body = urllib.request.urlopen(
        "http://127.0.0.1:%d/metrics" % port, timeout=30).read().decode()
    telemetry.stop_exporter()
    checks = {
        "residual under gate": resid < 3,
        "serve.requests scraped":
            "slate_tpu_serve_requests 1" in body,
        "latency histogram scraped":
            "slate_tpu_serve_latency_ms_posv_fp32_n16_bucket" in body,
        "p99 quantile scraped": 'quantile{quantile="0.99"}' in body,
    }
    for name, ok in checks.items():
        print("  %s: %s" % (name, "ok" if ok else "FAIL"), flush=True)
    if all(checks.values()):
        print("==== telemetry smoke passed ====")
        return 0
    print("==== telemetry smoke FAILED ====")
    return 1


def full_fused_smoke() -> int:
    """The --full-fused tier: force the whole-factorization depth
    (``SLATE_TPU_AUTOTUNE_FORCE=lu_step=full,potrf_step=full``) at
    interpret-safe dims in a fresh subprocess and prove the ISSUE 12
    acceptance on CPU every run: the SHIPPED dispatch (not the raw
    kernels) takes the ``full`` depth, exactly ONE pallas_call owns
    each factorization, ``step.hbm_roundtrips == 0`` across it, and
    the factors pass the scaled-residual gate end to end."""
    import tempfile

    here = pathlib.Path(__file__).resolve().parent
    code = (
        "import numpy as np, jax, jax.numpy as jnp\n"
        "import slate_tpu as st\n"
        "from slate_tpu.linalg.lu import getrf_scattered\n"
        "from slate_tpu.perf import autotune, metrics\n"
        "from slate_tpu.perf.hlo_profile import count_pallas_calls\n"
        "metrics.on()\n"
        "rng = np.random.default_rng(12)\n"
        "a = rng.standard_normal((256, 256)).astype(np.float32)\n"
        "lu, perm = jax.jit(lambda x: getrf_scattered(x, 128))("
        "jnp.asarray(a))\n"
        "lu, perm = np.asarray(lu), np.asarray(perm)\n"
        "L = np.tril(lu, -1) + np.eye(256, dtype=np.float32)\n"
        "U = np.triu(lu)\n"
        "eps = float(np.finfo(np.float32).eps)\n"
        "res = np.abs(a[perm] - L @ U).max() "
        "/ (np.abs(a).max() * 256 * eps)\n"
        "assert res < 3.0, res\n"
        "dec = autotune.decisions()\n"
        "assert any(k.startswith('lu_step|') and v == 'full'\n"
        "           for k, v in dec.items()), dec\n"
        "assert count_pallas_calls(\n"
        "    lambda x: getrf_scattered(x, 128), jnp.asarray(a)) == 1\n"
        "g = rng.standard_normal((1024, 1024)).astype(np.float32)\n"
        "spd = g @ g.T / 1024 + np.eye(1024, dtype=np.float32)\n"
        "fac = st.potrf(st.HermitianMatrix(jnp.asarray(spd), "
        "uplo=st.Uplo.Lower))\n"
        "l = np.asarray(fac.data)\n"
        "res2 = np.linalg.norm(l @ l.T - spd) "
        "/ (np.linalg.norm(spd) * eps * 1024)\n"
        "assert res2 < 3.0, res2\n"
        "dec = autotune.decisions()\n"
        "assert any(k.startswith('potrf_step|') and v == 'full'\n"
        "           for k, v in dec.items()), dec\n"
        "snap = metrics.snapshot()['counters']\n"
        "assert snap.get('step.hbm_roundtrips', 0.0) == 0.0, snap\n"
        "print('full-fused smoke: getrf resid %.3g, potrf resid %.3g'\n"
        "      % (res, res2))\n"
    )
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SLATE_TPU_AUTOTUNE_FORCE="lu_step=full,potrf_step=full",
                   SLATE_TPU_AUTOTUNE_CACHE=os.path.join(td, "cache.json"))
        env.pop("SLATE_TPU_AUTOTUNE_BUNDLE", None)
        print("=== full-fused tier: SLATE_TPU_AUTOTUNE_FORCE="
              + env["SLATE_TPU_AUTOTUNE_FORCE"], flush=True)
        try:
            rc = subprocess.run([sys.executable, "-c", code], env=env,
                                cwd=str(here), timeout=900).returncode
        except subprocess.TimeoutExpired:
            rc = 124
    if rc == 0:
        print("==== full-fused smoke passed ====")
        return 0
    print("==== full-fused smoke FAILED (rc=%d) ====" % rc)
    return 1


def blackbox_smoke() -> int:
    """The --blackbox fast tier (ISSUE 15): run a distributed pgesv on
    a virtual CPU mesh with the flight recorder on, a 2-step checkpoint
    cadence, and ONE injected ``device_loss`` at a step boundary.  The
    loss rewinds one chunk (the run still residual-gates clean) and the
    recorder dumps EXACTLY ONE forensic bundle whose event tail names
    the checkpoint-restore rung; the stdlib ``tools/blackbox.py`` CLI
    then renders it — on a jax-poisoned path, like the other CLIs —
    and exits 0."""
    import glob as _glob
    import json
    import tempfile

    here = pathlib.Path(__file__).resolve().parent
    code = (
        "import numpy as np\n"
        "from slate_tpu.parallel import make_grid_mesh, pgesv, "
        "undistribute\n"
        "mesh = make_grid_mesh(2, 2)\n"
        "rng = np.random.default_rng(0)\n"
        "n, nb = 32, 4\n"
        "a = rng.standard_normal((n, n)).astype(np.float32) "
        "+ n * np.eye(n, dtype=np.float32)\n"
        "b = rng.standard_normal((n, 4)).astype(np.float32)\n"
        "_, _, x = pgesv(a, b, mesh, nb)\n"
        "xh = np.asarray(undistribute(x))\n"
        "res = np.linalg.norm(a @ xh - b) / (np.linalg.norm(a) "
        "* np.linalg.norm(xh) + np.linalg.norm(b))\n"
        "assert res < 1e-3, res\n"
        "print('BLACKBOX-RUN-OK')\n")
    with tempfile.TemporaryDirectory() as td:
        bdir = os.path.join(td, "bundles")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=4",
                   SLATE_TPU_BLACKBOX="1",
                   SLATE_TPU_BLACKBOX_DIR=bdir,
                   SLATE_TPU_CKPT_EVERY_STEPS="2",
                   SLATE_TPU_FAULT_INJECT="step.boundary="
                                          "device_loss:1:1",
                   SLATE_TPU_FAULT_SEED="7")
        env.pop("SLATE_TPU_DIST_TIMELINE", None)
        print("=== blackbox tier: SLATE_TPU_FAULT_INJECT="
              + env["SLATE_TPU_FAULT_INJECT"], flush=True)
        try:
            r = subprocess.run([sys.executable, "-c", code], env=env,
                               cwd=str(here), capture_output=True,
                               text=True, timeout=900)
        except subprocess.TimeoutExpired:
            print("==== blackbox smoke FAILED (timeout) ====")
            return 1
        checks = {"chaos run survived the device loss":
                  r.returncode == 0 and "BLACKBOX-RUN-OK" in r.stdout}
        if not checks["chaos run survived the device loss"]:
            print(r.stdout)
            print(r.stderr)
        bundles = sorted(_glob.glob(
            os.path.join(bdir, "slate_tpu_blackbox_*.json")))
        checks["exactly one bundle dumped"] = len(bundles) == 1
        if bundles:
            with open(bundles[0]) as f:
                blob = json.load(f)
            kinds = [e.get("kind") for e in blob.get("events", [])]
            checks["trigger reason is device_loss"] = \
                blob.get("trigger", {}).get("reason") == "device_loss"
            checks["event tail names the checkpoint-restore rung"] = \
                any(k in ("ckpt.restored", "abft.restarted")
                    for k in kinds[-8:])
            checks["ring saw the injected fault firing"] = \
                "inject.fired" in kinds
            # the CLI must render the bundle on a jax-free machine
            poison = os.path.join(td, "poison", "jax")
            os.makedirs(poison, exist_ok=True)
            with open(os.path.join(poison, "__init__.py"), "w") as f:
                f.write("raise ImportError('jax poisoned for CLI "
                        "test')\n")
            env2 = dict(os.environ,
                        PYTHONPATH=os.path.dirname(poison) + os.pathsep
                        + os.environ.get("PYTHONPATH", ""))
            c = subprocess.run(
                [sys.executable, str(here / "tools" / "blackbox.py"),
                 bundles[0]], env=env2, capture_output=True, text=True,
                timeout=300)
            checks["CLI renders the bundle (rc 0)"] = \
                c.returncode == 0 and "device_loss" in c.stdout \
                and "ckpt.restored" in c.stdout
            cj = subprocess.run(
                [sys.executable, str(here / "tools" / "blackbox.py"),
                 bundles[0], "--json", "--strict"], env=env2,
                capture_output=True, text=True, timeout=300)
            ok_json = False
            try:
                ok_json = json.loads(cj.stdout)["trigger"]["reason"] \
                    == "device_loss"
            except (ValueError, KeyError, TypeError):
                pass
            # the loss was RECOVERED: --strict must stay green
            checks["--json --strict parses and exits 0"] = \
                cj.returncode == 0 and ok_json
        for name, ok in checks.items():
            print("  %s: %s" % (name, "ok" if ok else "FAIL"),
                  flush=True)
        if all(checks.values()):
            print("==== blackbox smoke passed ====")
            return 0
        print("==== blackbox smoke FAILED ====")
        return 1


def split_smoke() -> int:
    """The --split fast tier (ISSUE 16): two fresh subprocesses on CPU.
    Leg 1 forces the bf16x3 split-gemm backend
    (``SLATE_TPU_SPLIT_GEMM=1``) at interpret-safe dims and proves the
    SHIPPED dispatch takes it — gesv/posv residual-gate clean end to
    end, the mixed-precision wrapper rides the split factor leg, and
    the autotune census pins a ``matmul -> split3`` decision.  Leg 2
    proves the health-demotion path: a seeded demotable (timed) split3
    winner plus one injected NaN under ``SLATE_TPU_HEALTH=retry`` must
    quarantine split3 while the stock re-run answers clean."""
    import tempfile

    here = pathlib.Path(__file__).resolve().parent
    code1 = (
        "import numpy as np, jax.numpy as jnp\n"
        "import slate_tpu as st\n"
        "from slate_tpu.perf import autotune\n"
        "eps = float(np.finfo(np.float32).eps)\n"
        "rng = np.random.default_rng(16)\n"
        "n, nrhs = 256, 3\n"
        "a = (rng.standard_normal((n, n)).astype(np.float32)\n"
        "     + n * np.eye(n, dtype=np.float32))\n"
        "b = rng.standard_normal((n, nrhs)).astype(np.float32)\n"
        "lu, perm, x = st.gesv(st.Matrix.from_array(a, nb=128),\n"
        "                      jnp.asarray(b))\n"
        "xv = np.asarray(x)\n"
        "res = (np.linalg.norm(a @ xv - b)\n"
        "       / (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))\n"
        "assert res < 3.0, res\n"
        "g = rng.standard_normal((n, n)).astype(np.float32)\n"
        "spd = g @ g.T / n + np.eye(n, dtype=np.float32)\n"
        "fac, x2 = st.posv(st.HermitianMatrix(jnp.asarray(spd),\n"
        "                                     uplo=st.Uplo.Lower),\n"
        "                  jnp.asarray(b))\n"
        "x2v = np.asarray(x2)\n"
        "res2 = (np.linalg.norm(spd @ x2v - b)\n"
        "        / (np.linalg.norm(spd) * np.linalg.norm(x2v) * n * eps))\n"
        "assert res2 < 3.0, res2\n"
        "x3, iters = st.posv_mixed(st.HermitianMatrix(jnp.asarray(spd),\n"
        "                                             uplo=st.Uplo.Lower),\n"
        "                          jnp.asarray(b))\n"
        "x3v = np.asarray(x3)\n"
        "res3 = (np.linalg.norm(spd @ x3v - b)\n"
        "        / (np.linalg.norm(spd) * np.linalg.norm(x3v) * n * eps))\n"
        "assert res3 < 3.0, res3\n"
        "dec = autotune.decisions()\n"
        "assert any(k.startswith('matmul|') and v == 'split3'\n"
        "           for k, v in dec.items()), dec\n"
        "print('split smoke: gesv resid %.3g, posv resid %.3g, '\n"
        "      'posv_mixed resid %.3g (iters %d)'\n"
        "      % (res, res2, res3, int(iters)))\n"
    )
    code2 = (
        "import numpy as np, jax.numpy as jnp\n"
        "import slate_tpu as st\n"
        "from slate_tpu.perf import autotune, metrics\n"
        "metrics.on()\n"
        "tab = autotune.table()\n"
        "key = 'matmul|256,256,256,float32,highest'\n"
        "tab._record('matmul', key, 'split3', 'timed')\n"
        "eps = float(np.finfo(np.float32).eps)\n"
        "rng = np.random.default_rng(5)\n"
        "n = 128\n"
        "a = (rng.standard_normal((n, n)).astype(np.float32)\n"
        "     + n * np.eye(n, dtype=np.float32))\n"
        "b = rng.standard_normal((n, 2)).astype(np.float32)\n"
        "lu, perm, x = st.gesv(st.Matrix.from_array(a, nb=64),\n"
        "                      jnp.asarray(b))\n"
        "xv = np.asarray(x)\n"
        "assert np.isfinite(xv).all()\n"
        "res = (np.linalg.norm(a @ xv - b)\n"
        "       / (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))\n"
        "assert res < 3.0, res\n"
        "q = tab.quarantine\n"
        "assert any('split3' in bks for bks in q.values()), q\n"
        "snap = metrics.snapshot()['counters']\n"
        "assert snap.get('resilience.recovered', 0.0) >= 1.0, snap\n"
        "print('SPLIT-DEMOTE-OK')\n"
    )
    checks = {}
    with tempfile.TemporaryDirectory() as td:
        env1 = dict(os.environ, JAX_PLATFORMS="cpu",
                    SLATE_TPU_SPLIT_GEMM="1",
                    SLATE_TPU_AUTOTUNE_CACHE=os.path.join(td, "c1.json"))
        for k in ("SLATE_TPU_AUTOTUNE_FORCE", "SLATE_TPU_AUTOTUNE_BUNDLE",
                  "SLATE_TPU_FAULT_INJECT", "SLATE_TPU_HEALTH"):
            env1.pop(k, None)
        print("=== split tier leg 1: SLATE_TPU_SPLIT_GEMM=1 (forced "
              "bf16x3, residual-gated, census-pinned)", flush=True)
        try:
            r1 = subprocess.run([sys.executable, "-c", code1], env=env1,
                                cwd=str(here), timeout=900)
            checks["forced split3 residual-gates + census pin"] = \
                r1.returncode == 0
        except subprocess.TimeoutExpired:
            checks["forced split3 residual-gates + census pin"] = False
        # count 2: the first fault lands on getrf, whose Matrix-wrapped
        # output the injector leaves alone; the second poisons getrs's
        # raw solution array, which trips the finite gate
        env2 = dict(os.environ, JAX_PLATFORMS="cpu",
                    SLATE_TPU_HEALTH="retry",
                    SLATE_TPU_FAULT_INJECT="driver.output=nan:1:2",
                    SLATE_TPU_FAULT_SEED="3",
                    SLATE_TPU_AUTOTUNE_CACHE=os.path.join(td, "c2.json"))
        for k in ("SLATE_TPU_AUTOTUNE_FORCE", "SLATE_TPU_AUTOTUNE_BUNDLE",
                  "SLATE_TPU_SPLIT_GEMM"):
            env2.pop(k, None)
        print("=== split tier leg 2: SLATE_TPU_FAULT_INJECT="
              + env2["SLATE_TPU_FAULT_INJECT"]
              + " (health gate demotes split3)", flush=True)
        try:
            r2 = subprocess.run([sys.executable, "-c", code2], env=env2,
                                cwd=str(here), capture_output=True,
                                text=True, timeout=900)
            checks["health gate quarantines split3, stock recovers"] = \
                r2.returncode == 0 and "SPLIT-DEMOTE-OK" in r2.stdout
            if r2.returncode != 0:
                print(r2.stdout)
                print(r2.stderr)
        except subprocess.TimeoutExpired:
            checks["health gate quarantines split3, stock recovers"] = False
    for name, ok in checks.items():
        print("  %s: %s" % (name, "ok" if ok else "FAIL"), flush=True)
    if all(checks.values()):
        print("==== split smoke passed ====")
        return 0
    print("==== split smoke FAILED ====")
    return 1


def ooc_smoke() -> int:
    """The --ooc fast tier (ISSUE 17): two fresh subprocesses on CPU.
    Leg 1 forces the out-of-core site (``SLATE_TPU_OOC=1``) with a tiny
    3-tile window at interpret-safe dims and proves the SHIPPED
    dispatch takes it — the forced-window getrf/potrf factors are
    BITWISE identical to their all-resident runs (residency never
    changes arithmetic), gesv/posv residual-gate clean end to end
    through the pool, and the autotune census pins an ``ooc -> pool``
    decision.  Leg 2 composes the pool with the PR 14 checkpoint
    harness: a 2-step cadence plus ONE injected ``device_loss`` at a
    step boundary must rewind to the window-boundary snapshot and
    reproduce the uninterrupted factors bitwise."""
    import tempfile

    here = pathlib.Path(__file__).resolve().parent
    code1 = (
        "import numpy as np, jax.numpy as jnp\n"
        "import slate_tpu as st\n"
        "from slate_tpu.linalg import lu as lu_mod, ooc\n"
        "from slate_tpu.perf import autotune, metrics\n"
        "metrics.on()\n"
        "eps = float(np.finfo(np.float32).eps)\n"
        "rng = np.random.default_rng(17)\n"
        "n, nb = 128, 32\n"
        "a = (rng.standard_normal((n, n)).astype(np.float32)\n"
        "     + 2.0 * np.sqrt(n) * np.eye(n, dtype=np.float32))\n"
        "lu_t, p_t = ooc.getrf_ooc(jnp.asarray(a), nb=nb, capacity=2,\n"
        "                          depth=1)\n"
        "lu_a, p_a = ooc.getrf_ooc(jnp.asarray(a), nb=nb, capacity=64,\n"
        "                          depth=4)\n"
        "assert np.array_equal(np.asarray(lu_t), np.asarray(lu_a))\n"
        "assert np.array_equal(np.asarray(p_t), np.asarray(p_a))\n"
        "lmat = np.tril(np.asarray(lu_t), -1) + np.eye(n,\n"
        "                                              dtype=np.float32)\n"
        "umat = np.triu(np.asarray(lu_t))\n"
        "res_f = (np.abs(a[np.asarray(p_t)] - lmat @ umat).max()\n"
        "         / (np.abs(a).max() * n * eps))\n"
        "assert res_f < 3.0, res_f\n"
        "g = rng.standard_normal((n, n)).astype(np.float32)\n"
        "spd = (g @ g.T / n + np.eye(n)).astype(np.float32)\n"
        "l_t = np.asarray(ooc.potrf_ooc(jnp.asarray(spd), nb=nb,\n"
        "                               capacity=2, depth=1))\n"
        "l_a = np.asarray(ooc.potrf_ooc(jnp.asarray(spd), nb=nb,\n"
        "                               capacity=64, depth=4))\n"
        "assert np.array_equal(l_t, l_a)\n"
        "b = rng.standard_normal((n, 3)).astype(np.float32)\n"
        "lu2, perm2, x = lu_mod.gesv(jnp.asarray(a), jnp.asarray(b))\n"
        "xv = np.asarray(x)\n"
        "res = (np.linalg.norm(a @ xv - b)\n"
        "       / (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))\n"
        "assert res < 3.0, res\n"
        "fac, x2 = st.posv(st.HermitianMatrix(jnp.asarray(spd),\n"
        "                                     uplo=st.Uplo.Lower),\n"
        "                  jnp.asarray(b))\n"
        "x2v = np.asarray(x2)\n"
        "res2 = (np.linalg.norm(spd @ x2v - b)\n"
        "        / (np.linalg.norm(spd) * np.linalg.norm(x2v) * n * eps))\n"
        "assert res2 < 3.0, res2\n"
        "dec = autotune.decisions()\n"
        "assert any(k.startswith('ooc|') and v == 'pool'\n"
        "           for k, v in dec.items()), sorted(dec)\n"
        "snap = metrics.snapshot()['counters']\n"
        "assert snap.get('ooc.host_bytes', 0.0) > 0, snap\n"
        "print('ooc smoke: window parity bitwise, gesv resid %.3g, '\n"
        "      'posv resid %.3g, host GB %.4f'\n"
        "      % (res, res2, snap['ooc.host_bytes'] / 1e9))\n"
        "print('OOC-PARITY-OK')\n"
    )
    code2 = (
        "import numpy as np, jax.numpy as jnp\n"
        "from slate_tpu.linalg import ooc\n"
        "from slate_tpu.perf import metrics\n"
        "from slate_tpu.resilience import inject\n"
        "metrics.on()\n"
        "rng = np.random.default_rng(18)\n"
        "n, nb = 128, 32\n"
        "a = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32)\n"
        "                + 2.0 * np.sqrt(n)\n"
        "                * np.eye(n, dtype=np.float32))\n"
        "inject.clear_plan()\n"
        "lu_c, p_c = ooc.getrf_ooc(a, nb=nb, capacity=3)\n"
        "inject.install(inject.FaultPlan(seed=7).add(\n"
        "    'step.boundary', 'device_loss', rate=1.0, count=1))\n"
        "lu_x, p_x = ooc.getrf_ooc(a, nb=nb, capacity=3)\n"
        "assert np.array_equal(np.asarray(lu_c), np.asarray(lu_x))\n"
        "assert np.array_equal(np.asarray(p_c), np.asarray(p_x))\n"
        "snap = metrics.snapshot()['counters']\n"
        "assert snap.get('ckpt.restored', 0.0) >= 1.0, snap\n"
        "assert snap.get('ckpt.saved', 0.0) >= 1.0, snap\n"
        "print('OOC-CHAOS-OK')\n"
    )
    checks = {}
    with tempfile.TemporaryDirectory() as td:
        env1 = dict(os.environ, JAX_PLATFORMS="cpu",
                    SLATE_TPU_OOC="1",
                    SLATE_TPU_OOC_NB="32",
                    SLATE_TPU_OOC_WINDOW_TILES="3",
                    SLATE_TPU_OOC_PREFETCH_DEPTH="2",
                    SLATE_TPU_AUTOTUNE_CACHE=os.path.join(td, "c1.json"))
        for k in ("SLATE_TPU_AUTOTUNE_FORCE", "SLATE_TPU_AUTOTUNE_BUNDLE",
                  "SLATE_TPU_FAULT_INJECT", "SLATE_TPU_HEALTH",
                  "SLATE_TPU_CKPT_EVERY_STEPS"):
            env1.pop(k, None)
        print("=== ooc tier leg 1: SLATE_TPU_OOC=1 window=3 (forced "
              "pool, bitwise window parity, residual-gated, "
              "census-pinned)", flush=True)
        try:
            r1 = subprocess.run([sys.executable, "-c", code1], env=env1,
                                cwd=str(here), capture_output=True,
                                text=True, timeout=900)
            checks["forced pool: bitwise parity + residual + census"] = \
                r1.returncode == 0 and "OOC-PARITY-OK" in r1.stdout
            if r1.returncode != 0:
                print(r1.stdout)
                print(r1.stderr)
            else:
                print(r1.stdout.strip())
        except subprocess.TimeoutExpired:
            checks["forced pool: bitwise parity + residual + census"] = \
                False
        env2 = dict(os.environ, JAX_PLATFORMS="cpu",
                    SLATE_TPU_CKPT_EVERY_STEPS="2",
                    SLATE_TPU_AUTOTUNE_CACHE=os.path.join(td, "c2.json"))
        for k in ("SLATE_TPU_AUTOTUNE_FORCE", "SLATE_TPU_AUTOTUNE_BUNDLE",
                  "SLATE_TPU_FAULT_INJECT", "SLATE_TPU_HEALTH",
                  "SLATE_TPU_OOC"):
            env2.pop(k, None)
        print("=== ooc tier leg 2: SLATE_TPU_CKPT_EVERY_STEPS=2 + one "
              "injected device_loss (bitwise rewind)", flush=True)
        try:
            r2 = subprocess.run([sys.executable, "-c", code2], env=env2,
                                cwd=str(here), capture_output=True,
                                text=True, timeout=900)
            checks["device_loss rewinds to snapshot, bitwise resume"] = \
                r2.returncode == 0 and "OOC-CHAOS-OK" in r2.stdout
            if r2.returncode != 0:
                print(r2.stdout)
                print(r2.stderr)
        except subprocess.TimeoutExpired:
            checks["device_loss rewinds to snapshot, bitwise resume"] = \
                False
    for name, ok in checks.items():
        print("  %s: %s" % (name, "ok" if ok else "FAIL"), flush=True)
    if all(checks.values()):
        print("==== ooc smoke passed ====")
        return 0
    print("==== ooc smoke FAILED ====")
    return 1


def qdwh_smoke() -> int:
    """The --qdwh fast tier (ISSUE 18): two fresh subprocesses on CPU.
    Leg 1 pins the spectral tier through the SHIPPED dispatch
    (``SLATE_TPU_AUTOTUNE_FORCE=eig_driver=qdwh,svd_driver=qdwh``) at
    interpret-safe dims and proves the QDWH chain end to end: polar
    contract (UᴴU = I, H ⪰ 0, U·H = A), heev eigenvalue parity vs the
    reference dense solver plus residual/orthogonality gates, svd
    reconstruction, and an autotune census carrying ``eig_driver`` /
    ``svd_driver`` -> qdwh plus the per-iteration ``qdwh_step`` keys.
    Leg 2 proves the health-demotion path: a seeded demotable (timed)
    qdwh winner plus one injected NaN under ``SLATE_TPU_HEALTH=retry``
    must quarantine qdwh while the re-run answers clean — and once the
    force pin is gone, the eig_driver site falls back to twostage."""
    import tempfile

    here = pathlib.Path(__file__).resolve().parent
    code1 = (
        "import numpy as np, jax.numpy as jnp\n"
        "import slate_tpu as st\n"
        "from slate_tpu.perf import autotune\n"
        "try:\n"
        "    from scipy.linalg import eigvalsh as _ref_eigvalsh\n"
        "except Exception:\n"
        "    _ref_eigvalsh = np.linalg.eigvalsh\n"
        "eps = float(np.finfo(np.float32).eps)\n"
        "rng = np.random.default_rng(18)\n"
        "n = 96\n"
        "opts = {'qdwh_crossover': 32, 'nb': 32}\n"
        "q, _ = np.linalg.qr(rng.standard_normal((n, n)))\n"
        "w_true = np.concatenate([np.linspace(-3.0, -0.5, n // 2),\n"
        "                         np.linspace(0.25, 2.0, n - n // 2)])\n"
        "a = ((q * w_true) @ q.T).astype(np.float32)\n"
        "a = 0.5 * (a + a.T)\n"
        "u, h = st.polar(st.Matrix.from_array(a, nb=32), opts=opts)\n"
        "uv, hv = np.asarray(u), np.asarray(h)\n"
        "orth_u = (np.linalg.norm(uv.T @ uv - np.eye(n))\n"
        "          / (n * eps))\n"
        "assert orth_u < 50.0, orth_u\n"
        "rec_p = (np.linalg.norm(uv @ hv - a)\n"
        "         / (np.linalg.norm(a) * n * eps))\n"
        "assert rec_p < 50.0, rec_p\n"
        "assert np.linalg.eigvalsh(hv.astype(np.float64)).min() \\\n"
        "    > -50.0 * n * eps * np.linalg.norm(a), 'H not PSD'\n"
        "w, z = st.heev(st.HermitianMatrix(jnp.asarray(a),\n"
        "                                  uplo=st.Uplo.Lower),\n"
        "               jobz=True, opts=opts)\n"
        "wv, zv = np.asarray(w), np.asarray(z)\n"
        "w_ref = _ref_eigvalsh(a.astype(np.float64))\n"
        "par = (np.abs(wv - w_ref).max()\n"
        "       / (np.abs(w_ref).max() * n * eps))\n"
        "assert par < 50.0, par\n"
        "resid = (np.linalg.norm(a @ zv - zv * wv)\n"
        "         / (np.linalg.norm(a) * n * eps))\n"
        "assert resid < 50.0, resid\n"
        "orth = np.linalg.norm(zv.T @ zv - np.eye(n)) / (n * eps)\n"
        "assert orth < 50.0, orth\n"
        "s, us, vh = st.svd(st.Matrix.from_array(a, nb=32), opts=opts)\n"
        "sv, usv, vhv = np.asarray(s), np.asarray(us), np.asarray(vh)\n"
        "assert (np.diff(sv) <= 10 * eps * sv[0]).all(), 'not sorted'\n"
        "rec = (np.linalg.norm((usv * sv) @ vhv - a)\n"
        "       / (np.linalg.norm(a) * n * eps))\n"
        "assert rec < 50.0, rec\n"
        "s_ref = np.sort(np.abs(w_ref))[::-1]\n"
        "spar = np.abs(sv - s_ref).max() / (s_ref[0] * n * eps)\n"
        "assert spar < 50.0, spar\n"
        "dec = autotune.decisions()\n"
        "assert any(k.startswith('eig_driver|') and v == 'qdwh'\n"
        "           for k, v in dec.items()), sorted(dec)\n"
        "assert any(k.startswith('svd_driver|') and v == 'qdwh'\n"
        "           for k, v in dec.items()), sorted(dec)\n"
        "assert any(k.startswith('qdwh_step|')\n"
        "           for k in dec), sorted(dec)\n"
        "print('qdwh smoke: polar orth %.3g rec %.3g, heev parity %.3g '\n"
        "      'resid %.3g orth %.3g, svd rec %.3g parity %.3g '\n"
        "      '(units of n*eps)'\n"
        "      % (orth_u, rec_p, par, resid, orth, rec, spar))\n"
        "print('QDWH-FORCED-OK')\n"
    )
    code2 = (
        "import os\n"
        "import numpy as np, jax.numpy as jnp\n"
        "import slate_tpu as st\n"
        "from slate_tpu.perf import autotune, metrics\n"
        "metrics.on()\n"
        "tab = autotune.table()\n"
        "key = 'eig_driver|256,float32,highest'\n"
        "tab._record('eig_driver', key, 'qdwh', 'timed')\n"
        "eps = float(np.finfo(np.float32).eps)\n"
        "rng = np.random.default_rng(19)\n"
        "n = 96\n"
        "g = rng.standard_normal((n, n)).astype(np.float32)\n"
        "a = 0.5 * (g + g.T)\n"
        "w, z = st.heev(st.HermitianMatrix(jnp.asarray(a),\n"
        "                                  uplo=st.Uplo.Lower),\n"
        "               jobz=True,\n"
        "               opts={'qdwh_crossover': 32, 'nb': 32})\n"
        "wv, zv = np.asarray(w), np.asarray(z)\n"
        "assert np.isfinite(wv).all() and np.isfinite(zv).all()\n"
        "resid = (np.linalg.norm(a @ zv - zv * wv)\n"
        "         / (np.linalg.norm(a) * n * eps))\n"
        "assert resid < 50.0, resid\n"
        "q = tab.quarantine\n"
        "assert any('qdwh' in bks for bks in q.values()), q\n"
        "snap = metrics.snapshot()['counters']\n"
        "assert snap.get('resilience.recovered', 0.0) >= 1.0, snap\n"
        "os.environ.pop('SLATE_TPU_AUTOTUNE_FORCE', None)\n"
        "sel = autotune.select('eig_driver', n=n, dtype=jnp.float32,\n"
        "                      eligible=True)\n"
        "assert sel == 'twostage', sel\n"
        "print('QDWH-DEMOTE-OK')\n"
    )
    checks = {}
    with tempfile.TemporaryDirectory() as td:
        env1 = dict(os.environ, JAX_PLATFORMS="cpu",
                    SLATE_TPU_AUTOTUNE_FORCE="eig_driver=qdwh,"
                                             "svd_driver=qdwh",
                    SLATE_TPU_AUTOTUNE_CACHE=os.path.join(td, "c1.json"))
        for k in ("SLATE_TPU_AUTOTUNE_BUNDLE", "SLATE_TPU_FAULT_INJECT",
                  "SLATE_TPU_HEALTH", "SLATE_TPU_QDWH",
                  "SLATE_TPU_QDWH_CROSSOVER"):
            env1.pop(k, None)
        print("=== qdwh tier leg 1: SLATE_TPU_AUTOTUNE_FORCE="
              + env1["SLATE_TPU_AUTOTUNE_FORCE"]
              + " (forced spectral tier: polar contract, heev parity, "
              "svd reconstruction, census-pinned)", flush=True)
        try:
            r1 = subprocess.run([sys.executable, "-c", code1], env=env1,
                                cwd=str(here), capture_output=True,
                                text=True, timeout=900)
            checks["forced qdwh: polar/heev/svd gates + census pin"] = \
                r1.returncode == 0 and "QDWH-FORCED-OK" in r1.stdout
            if r1.returncode != 0:
                print(r1.stdout)
                print(r1.stderr)
            else:
                print(r1.stdout.strip())
        except subprocess.TimeoutExpired:
            checks["forced qdwh: polar/heev/svd gates + census pin"] = \
                False
        # count 1: heev is the only instrumented facade on the qdwh
        # path (polar/geqrf run through internal helpers), so the
        # first poll poisons heev's eigenpair output and trips the
        # finite gate; the retry re-runs the raw driver injection-free
        env2 = dict(os.environ, JAX_PLATFORMS="cpu",
                    SLATE_TPU_AUTOTUNE_FORCE="eig_driver=qdwh",
                    SLATE_TPU_HEALTH="retry",
                    SLATE_TPU_FAULT_INJECT="driver.output=nan:1:1",
                    SLATE_TPU_FAULT_SEED="3",
                    SLATE_TPU_AUTOTUNE_CACHE=os.path.join(td, "c2.json"))
        for k in ("SLATE_TPU_AUTOTUNE_BUNDLE", "SLATE_TPU_QDWH",
                  "SLATE_TPU_QDWH_CROSSOVER"):
            env2.pop(k, None)
        print("=== qdwh tier leg 2: SLATE_TPU_FAULT_INJECT="
              + env2["SLATE_TPU_FAULT_INJECT"]
              + " (health gate demotes qdwh, dispatch falls back to "
              "twostage)", flush=True)
        try:
            r2 = subprocess.run([sys.executable, "-c", code2], env=env2,
                                cwd=str(here), capture_output=True,
                                text=True, timeout=900)
            checks["health gate quarantines qdwh, twostage fallback"] = \
                r2.returncode == 0 and "QDWH-DEMOTE-OK" in r2.stdout
            if r2.returncode != 0:
                print(r2.stdout)
                print(r2.stderr)
        except subprocess.TimeoutExpired:
            checks["health gate quarantines qdwh, twostage fallback"] = \
                False
    for name, ok in checks.items():
        print("  %s: %s" % (name, "ok" if ok else "FAIL"), flush=True)
    if all(checks.values()):
        print("==== qdwh smoke passed ====")
        return 0
    print("==== qdwh smoke FAILED ====")
    return 1


def fleet_smoke() -> int:
    """The --fleet tier (ISSUE 20): the full fleet-serving suite —
    including the heavy drain/rejoin and throughput tests the fast
    tier skips (``@pytest.mark.slow``) — on an 8-way virtual CPU mesh
    in a fresh subprocess.  Green means the cost-model router, the
    ICI-sharded big-problem lane, priority preemption and the
    device-loss drain → reverify → rejoin ladder all hold end to end."""
    here = pathlib.Path(__file__).resolve().parent
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if "xla_force_host_platform_device_count" \
            not in env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
    for k in ("SLATE_TPU_AUTOTUNE_FORCE", "SLATE_TPU_AUTOTUNE_BUNDLE",
              "SLATE_TPU_FAULT_INJECT", "SLATE_TPU_FLEET_REPLICAS"):
        env.pop(k, None)
    cmd = [sys.executable, "-m", "pytest", "tests/test_fleet.py", "-q",
           "--runslow", "-p", "no:cacheprovider"]
    print("=== fleet tier: " + " ".join(cmd), flush=True)
    try:
        rc = subprocess.run(cmd, env=env, cwd=str(here),
                            timeout=1800).returncode
    except subprocess.TimeoutExpired:
        rc = 124
    if rc == 0:
        print("==== fleet smoke passed ====")
        return 0
    print("==== fleet smoke FAILED (rc=%d) ====" % rc)
    return 1


def sweep_smoke() -> int:
    """The --sweep tier: tiny CPU grid end-to-end through the CLI in a
    subprocess (sweep → versioned bundle artifact), then a second fresh
    process booted with ONLY ``SLATE_TPU_AUTOTUNE_BUNDLE`` set proves
    the ISSUE 11 acceptance criterion: first bucketed request with zero
    timing reps, zero on-demand compiles, zero jit compiles — including
    a shape absent from the sweep grid, resolved by the interpolating
    model — and the analytical pre-pruning cut timing reps ≥2× vs
    exhaustive, every pruned candidate logged with its predicted gap."""
    import json
    import tempfile

    here = pathlib.Path(__file__).resolve().parent
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "bundle.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SLATE_TPU_AUTOTUNE_CACHE=os.path.join(td, "cache.json"))
        env.pop("SLATE_TPU_AUTOTUNE_BUNDLE", None)
        cmd = [sys.executable, str(here / "tools" / "sweep.py"),
               "--grid", "smoke", "--out", out,
               "--checkpoint", os.path.join(td, "ck.json")]
        print("=== sweep: " + " ".join(cmd), flush=True)
        try:
            rc = subprocess.run(cmd, env=env, timeout=1500).returncode
        except subprocess.TimeoutExpired:
            rc = 124
        if rc != 0:
            print("==== sweep smoke FAILED (CLI rc=%d) ====" % rc)
            return 1
        with open(out) as f:
            blob = json.load(f)
        st = blob.get("stats", {})
        checks = {
            "bundle has decisions": bool(blob.get("decisions")),
            "bundle has model points": bool(blob.get("model")),
            "bundle has warm-start specs": bool(blob.get("warm_start")),
            "pruning cut timing reps >= 2x vs exhaustive":
                st.get("reps_exhaustive", 0)
                >= 2 * max(1, st.get("reps_timed", 0)),
            "every pruned candidate logged with predicted gap":
                bool(blob.get("pruned")) and all(
                    isinstance(p.get("predicted_gap"), (int, float))
                    for p in blob["pruned"]),
        }
        code = (
            "import numpy as np\n"
            "from slate_tpu import serve\n"
            "from slate_tpu.perf import autotune, metrics\n"
            "metrics.on()\n"
            "compiled = serve.warm_start()\n"
            "assert compiled >= 1, compiled\n"
            "metrics.reset()\n"
            "rng = np.random.default_rng(0)\n"
            "def spd(n):\n"
            "    g = rng.standard_normal((n, n)).astype(np.float32)\n"
            "    return g @ g.T + n * np.eye(n, dtype=np.float32)\n"
            "serve.submit('posv', spd(64),\n"
            "             np.ones(64, np.float32)).result(timeout=600)\n"
            "serve.submit('posv', spd(96),\n"
            "             np.ones(96, np.float32)).result(timeout=600)\n"
            "serve.shutdown()\n"
            "c = metrics.snapshot()['counters']\n"
            "assert c.get('serve.compile.on_demand', 0) == 0, c\n"
            "assert c.get('jit.backend_compiles', 0) == 0, c\n"
            "assert autotune.timing_reps() == 0\n"
            "src = {v['source'] for k, v in\n"
            "       autotune.table().decisions.items()\n"
            "       if k.startswith('batched_potrf|')}\n"
            "assert 'bundle' in src and 'bundle-model' in src, src\n"
            "print('SWEEP-BOOT-OK')\n")
        env2 = dict(env, SLATE_TPU_AUTOTUNE_BUNDLE=out,
                    SLATE_TPU_AUTOTUNE_CACHE=os.path.join(td, "c2.json"))
        try:
            r2 = subprocess.run([sys.executable, "-c", code], env=env2,
                                capture_output=True, text=True,
                                timeout=900, cwd=str(here))
            boot_ok = r2.returncode == 0 and "SWEEP-BOOT-OK" in r2.stdout
            if not boot_ok:
                print(r2.stdout)
                print(r2.stderr)
        except subprocess.TimeoutExpired:
            boot_ok = False
        checks["fresh process boots probe-free from the bundle "
               "(zero reps/compiles, model resolves unswept shape)"] = \
            boot_ok
        for name, ok in checks.items():
            print("  %s: %s" % (name, "ok" if ok else "FAIL"), flush=True)
        if all(checks.values()):
            print("==== sweep smoke passed ====")
            return 0
        print("==== sweep smoke FAILED ====")
        return 1


def xprof_smoke() -> int:
    """The --xprof fast tier (ISSUE 19): a REAL device-truth capture on
    CPU.  Subprocess A runs a composed-path getrf with
    ``SLATE_TPU_XPROF=<dir>`` set: the capture must emit an artifact
    whose schema round-trips (format/digest/stages), and joining the
    profile into ``attr.attribute`` must flip the compute source to
    ``device_profile`` while the stage seconds still reconcile with the
    routine GFLOP/s at the existing 1%% pin.  The stdlib
    ``tools/xprof_report.py`` CLI then renders the capture dir on a
    jax-poisoned path.  Subprocess B proves the importer is inert: with
    the knob unset, importing/entering xprof never pulls in jax and
    ``capture`` is a no-op."""
    import tempfile

    here = pathlib.Path(__file__).resolve().parent
    code = (
        "import os\n"
        "import numpy as np\n"
        "import jax\n"
        "from slate_tpu.linalg import lu as slu\n"
        "from slate_tpu.perf import attr, xprof\n"
        "assert xprof.enabled(), os.environ.get('SLATE_TPU_XPROF')\n"
        "n, nb = 64, 16\n"
        "rng = np.random.default_rng(0)\n"
        "a = rng.standard_normal((n, n)).astype(np.float32) \\\n"
        "    + n * np.eye(n, dtype=np.float32)\n"
        "with xprof.capture('getrf') as cap:\n"
        "    lu, piv = slu.getrf_scattered(a, nb=nb, step='panel')\n"
        "    jax.block_until_ready(lu)\n"
        "prof = xprof.last_profile()\n"
        "assert prof is not None and not prof.get('error'), \\\n"
        "    prof and prof.get('error')\n"
        "assert prof['format'] == xprof.PROFILE_FORMAT\n"
        "assert prof['digest'] and os.path.exists(prof['artifact'])\n"
        "assert prof['capture_wall_s'] > 0\n"
        "st_map = prof['stages'].get('getrf') or {}\n"
        "assert {'panel', 'trsm', 'update'} <= set(st_map), st_map\n"
        "gf = 1.0   # keeps measured_s well above the 1e-9 rounding\n"
        "rep = attr.attribute('getrf_fp32_n%d_nb%d' % (n, nb), gf,\n"
        "                     platform='cpu', device_profile=prof)\n"
        "assert rep['compute_source'] == 'device_profile', rep\n"
        "assert rep['device_profile']['digest'] == prof['digest']\n"
        "total = sum(s['flops'] for s in rep['stages'])\n"
        "assert abs(total / rep['measured_s'] / 1e9 - gf) / gf < 0.01\n"
        "est = sum(s['measured_s'] for s in rep['stages'])\n"
        "assert abs(est - rep['measured_s']) \\\n"
        "    <= 1e-3 * rep['measured_s'] + 1e-12\n"
        "print('XPROF-RUN-OK digest=' + prof['digest'])\n")
    inert = (
        "import importlib.util\n"
        "import sys\n"
        "spec = importlib.util.spec_from_file_location(\n"
        "    '_xp', 'slate_tpu/perf/xprof.py')\n"
        "xp = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(xp)\n"
        "assert not xp.enabled()\n"
        "with xp.capture('noop'):\n"
        "    pass\n"
        "assert xp.last_profile() is None\n"
        "assert 'jax' not in sys.modules, 'xprof imported jax'\n"
        "print('XPROF-INERT-OK')\n")
    with tempfile.TemporaryDirectory() as td:
        cap_dir = os.path.join(td, "cap")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SLATE_TPU_XPROF=cap_dir)
        print("=== xprof tier: SLATE_TPU_XPROF=" + cap_dir, flush=True)
        try:
            r = subprocess.run([sys.executable, "-c", code], env=env,
                               cwd=str(here), capture_output=True,
                               text=True, timeout=900)
        except subprocess.TimeoutExpired:
            print("==== xprof smoke FAILED (timeout) ====")
            return 1
        checks = {"capture joins device truth into attribution":
                  r.returncode == 0 and "XPROF-RUN-OK" in r.stdout}
        if not checks["capture joins device truth into attribution"]:
            print(r.stdout)
            print(r.stderr)
        # the CLI must render the capture on a jax-free machine
        poison = os.path.join(td, "poison", "jax")
        os.makedirs(poison, exist_ok=True)
        with open(os.path.join(poison, "__init__.py"), "w") as f:
            f.write("raise ImportError('jax poisoned for CLI test')\n")
        env2 = dict(os.environ,
                    PYTHONPATH=os.path.dirname(poison) + os.pathsep
                    + os.environ.get("PYTHONPATH", ""))
        c = subprocess.run(
            [sys.executable, str(here / "tools" / "xprof_report.py"),
             cap_dir, "--routine", "getrf"], env=env2,
            capture_output=True, text=True, timeout=300)
        checks["CLI renders the capture jax-free (rc 0)"] = \
            c.returncode == 0 and "stage rollup: getrf" in c.stdout
        if not checks["CLI renders the capture jax-free (rc 0)"]:
            print(c.stdout)
            print(c.stderr)
        env3 = dict(os.environ, JAX_PLATFORMS="cpu")
        env3.pop("SLATE_TPU_XPROF", None)
        i = subprocess.run([sys.executable, "-c", inert], env=env3,
                           cwd=str(here), capture_output=True,
                           text=True, timeout=300)
        checks["knob unset: capture inert, jax never imported"] = \
            i.returncode == 0 and "XPROF-INERT-OK" in i.stdout
        if not checks["knob unset: capture inert, jax never imported"]:
            print(i.stdout)
            print(i.stderr)
        for name, ok in checks.items():
            print("  %s: %s" % (name, "ok" if ok else "FAIL"),
                  flush=True)
        if all(checks.values()):
            print("==== xprof smoke passed ====")
            return 0
        print("==== xprof smoke FAILED ====")
        return 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("-m", "--medium", action="store_true")
    ap.add_argument("--dist", action="store_true",
                    help="include distributed routines")
    ap.add_argument("--routines", help="comma list (default: all)")
    ap.add_argument("--types", default="s")
    ap.add_argument("--nb", type=int, default=64)
    ap.add_argument("--timeout", type=int, default=600)
    ap.add_argument("--isolate", action="store_true",
                    help="one subprocess per routine (fresh jit cache, "
                    "hard timeout) instead of the shared-process default")
    ap.add_argument("--chaos", action="store_true",
                    help="fast chaos tier: run the suite once at quick "
                    "dims with a canned low-rate deterministic fault "
                    "plan and SLATE_TPU_HEALTH=retry enabled — proves "
                    "the resilience layer detects/degrades/retries "
                    "instead of failing (see docs/usage.md Resilience)")
    ap.add_argument("--telemetry", action="store_true",
                    help="live-telemetry smoke: serve one request with "
                    "telemetry on and scrape the Prometheus endpoint "
                    "once over a real socket (see docs/usage.md Live "
                    "telemetry)")
    ap.add_argument("--sweep", action="store_true",
                    help="offline-autotune smoke: run tools/sweep.py on "
                    "the tiny CPU grid in a subprocess, then boot a "
                    "fresh process from the bundle and assert the "
                    "zero-probe/zero-compile start (see docs/usage.md "
                    "Offline autotune & bundles)")
    ap.add_argument("--blackbox", action="store_true",
                    help="flight-recorder smoke: inject a device_loss "
                    "mid-pgetrf with the recorder on, assert exactly "
                    "one forensic bundle whose event tail names the "
                    "checkpoint-restore rung, and render it with the "
                    "stdlib tools/blackbox.py CLI (see docs/usage.md "
                    "Flight recorder & forensics)")
    ap.add_argument("--full-fused", action="store_true",
                    help="whole-factorization smoke: force "
                    "SLATE_TPU_AUTOTUNE_FORCE=lu_step=full,"
                    "potrf_step=full at interpret-safe dims so CI "
                    "exercises the full-depth mega-kernels on CPU "
                    "every run (see docs/usage.md Whole-factorization "
                    "kernels)")
    ap.add_argument("--split", action="store_true",
                    help="split-precision gemm smoke: force the bf16x3 "
                    "backend (SLATE_TPU_SPLIT_GEMM=1) at interpret-safe "
                    "dims — gesv/posv residual-gated, autotune census "
                    "pinned — then prove the health gate demotes a "
                    "seeded split3 winner under injected corruption "
                    "(see docs/usage.md Split-precision gemm)")
    ap.add_argument("--ooc", action="store_true",
                    help="out-of-core smoke: force the host-DRAM tile "
                    "pool (SLATE_TPU_OOC=1) with a tiny 3-tile window "
                    "at interpret-safe dims — forced-window factors "
                    "bitwise-match all-resident runs, gesv/posv "
                    "residual-gated through the pool, census pinned — "
                    "then compose with the checkpoint harness under an "
                    "injected device_loss (see docs/usage.md "
                    "Out-of-core factorizations)")
    ap.add_argument("--qdwh", action="store_true",
                    help="QDWH spectral-tier smoke: force the "
                    "gemm-rich eig/svd drivers "
                    "(SLATE_TPU_AUTOTUNE_FORCE=eig_driver=qdwh,"
                    "svd_driver=qdwh) at interpret-safe dims — polar "
                    "contract, heev parity vs the dense reference, "
                    "svd reconstruction, census pinned — then prove "
                    "the health gate demotes a seeded qdwh winner "
                    "under injected corruption and dispatch falls "
                    "back to twostage (see docs/usage.md QDWH "
                    "spectral tier)")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet-serving suite: the full "
                    "tests/test_fleet.py sweep (including the heavy "
                    "drain/rejoin and throughput tests the fast tier "
                    "skips) on an 8-way virtual CPU mesh — router, "
                    "sharded lane, preemption, device-loss recovery "
                    "(see docs/usage.md Fleet serving)")
    ap.add_argument("--xprof", action="store_true",
                    help="device-truth profiling smoke: real capture "
                    "around a composed getrf on CPU "
                    "(SLATE_TPU_XPROF=<dir>) — artifact schema, "
                    "device_profile compute source joined into "
                    "attribution at the 1%% reconciliation pin, "
                    "jax-free xprof_report.py render, importer inert "
                    "with the knob unset (see docs/usage.md "
                    "Device-truth profiling)")
    args = ap.parse_args(argv)

    if args.telemetry:
        return telemetry_smoke()

    if args.blackbox:
        return blackbox_smoke()

    if args.sweep:
        return sweep_smoke()

    if args.full_fused:
        return full_fused_smoke()

    if args.split:
        return split_smoke()

    if args.ooc:
        return ooc_smoke()

    if args.qdwh:
        return qdwh_smoke()

    if args.fleet:
        return fleet_smoke()

    if args.xprof:
        return xprof_smoke()

    if args.chaos:
        # setdefault: an explicit operator plan/tier wins over the can
        os.environ.setdefault("SLATE_TPU_FAULT_INJECT", CHAOS_PLAN)
        os.environ.setdefault("SLATE_TPU_FAULT_SEED", CHAOS_SEED)
        os.environ.setdefault("SLATE_TPU_HEALTH", "retry")
        os.environ.setdefault("SLATE_TPU_ABFT", "correct")
        os.environ.setdefault("SLATE_TPU_CKPT_EVERY_STEPS", "2")
        if not args.medium:
            args.quick = True       # "fast" tier: quick dims
        print(f"=== chaos tier: SLATE_TPU_FAULT_INJECT="
              f"{os.environ['SLATE_TPU_FAULT_INJECT']} seed="
              f"{os.environ['SLATE_TPU_FAULT_SEED']} health="
              f"{os.environ['SLATE_TPU_HEALTH']} abft="
              f"{os.environ['SLATE_TPU_ABFT']} ckpt_every="
              f"{os.environ['SLATE_TPU_CKPT_EVERY_STEPS']}", flush=True)

    dims = QUICK if args.quick else (MEDIUM if args.medium else SMALL)
    routines = (args.routines.split(",") if args.routines
                else SINGLE + (DIST if args.dist else []))
    failures, t0 = [], time.time()
    if not args.isolate:
        import tester
    for r in routines:
        d = QUICK if (r in SLOW and not args.quick) else dims
        targv = [r, "--dim", d, "--type", args.types, "--nb", str(args.nb)]
        print(f"=== tester.py {' '.join(targv)}", flush=True)
        if args.isolate:
            tester_path = str(pathlib.Path(__file__).resolve().parent
                              / "tester.py")
            cmd = [sys.executable, tester_path] + targv
            try:
                rc = subprocess.run(cmd, timeout=args.timeout).returncode
            except subprocess.TimeoutExpired:
                rc = 124
        else:
            try:
                rc = tester.main(targv)
            except SystemExit as e:       # argparse or explicit exits
                rc = (e.code if isinstance(e.code, int)
                      else 0 if e.code is None else 1)
            except Exception as e:        # a crashed routine fails alone
                print(f"  CRASH: {type(e).__name__}: {e}", flush=True)
                rc = 3
        if rc != 0:
            failures.append((r, rc))
    dt = time.time() - t0
    print(f"\n==== {len(routines) - len(failures)}/{len(routines)} routine "
          f"suites passed in {dt:.0f}s ====")
    for r, rc in failures:
        print(f"  FAILED: {r} (rc={rc})")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
