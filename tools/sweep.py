#!/usr/bin/env python3
"""Offline autotune sweep CLI — produce a versioned warm-start bundle.

Usage::

    python tools/sweep.py --grid smoke --out bundle.json
    python tools/sweep.py --grid full --out bundle.json \\
        --checkpoint sweep.ck.json --resume
    python tools/sweep.py --grid my_grid.json --sites lu_step,matmul
    python tools/sweep.py --grid smoke --profile /tmp/xprof_cap
    SLATE_TPU_AUTOTUNE_BUNDLE=bundle.json python my_replica.py

Enumerates the candidate space per autotune site — backend, fusion
depth, nb, batch-per-launch — over the grid's shape/dtype lattice,
PRUNES model-predicted losers with the analytical roofline
(``slate_tpu/perf/attr.py``) before a single timing rep runs (every
skip is logged with its predicted gap in the bundle's ``pruned``
list), times the survivors through the autotune decision engine with
resumable checkpointing and classified-infra retries, fits the
interpolating decision model, and writes ONE versioned bundle:
decision table + model + AOT warm-start bucket specs + the
jax/jaxlib/platform/libtpu version key.

A serving replica consumes the bundle with
``SLATE_TPU_AUTOTUNE_BUNDLE=<path>``: its first bucketed request runs
with zero timing reps, zero on-demand compiles and zero jit compiles
— including for shapes the sweep never timed, which resolve through
the fitted model.  Run the sweep ON the hardware generation you will
serve on: the bundle is rejected wholesale on any version-key
mismatch.

A custom ``--grid`` file is a JSON spec::

    {"margin": 0.2,
     "units": [{"site": "lu_step", "m": 4096, "n": 4096, "nb": 512},
               {"site": "batched_potrf", "b": 64, "n": 256}],
     "warm": [{"op": "posv", "batch": 64, "dims": [256],
               "dtype": "float32"}]}
"""

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sweep.py",
        description="Offline autotune sweep: analytical pre-prune, "
                    "timed survivors, interpolating decision model, "
                    "one versioned warm-start bundle.")
    ap.add_argument("--grid", default="smoke",
                    help="named grid (smoke|full) or a JSON grid-spec "
                         "file (default %(default)s)")
    ap.add_argument("--out", default="autotune_bundle.json",
                    help="bundle output path (default %(default)s)")
    ap.add_argument("--checkpoint",
                    help="checkpoint file: each completed unit is "
                         "written here; with --resume, finished units "
                         "are skipped on the next run")
    ap.add_argument("--resume", action="store_true",
                    help="skip units already in --checkpoint")
    ap.add_argument("--margin", type=float, default=None,
                    help="analytical prune margin (fractional gap over "
                         "the predicted best a candidate may carry and "
                         "still be timed; default: the grid's own, "
                         "else 0.25)")
    ap.add_argument("--reps", type=int, default=None,
                    help="timed repetitions per surviving candidate "
                         "(default: the autotuner's)")
    ap.add_argument("--sites", help="comma list: only sweep these sites")
    ap.add_argument("--profile",
                    help="xprof capture dir or xprof_*.json artifact "
                         "(slate_tpu/perf/xprof.py): its measured "
                         "signals replace the launch constant when "
                         "pricing dist_chunk / dist_lookahead / fusion "
                         "candidates, and the bundle records the "
                         "profile digest")
    ap.add_argument("--list", action="store_true",
                    help="print the resolved grid units and exit "
                         "(never imports jax)")
    args = ap.parse_args(argv)

    sys.path.insert(0, os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir)))
    from slate_tpu.perf import sweep as sw

    if args.grid in sw.GRIDS:
        spec = dict(sw.GRIDS[args.grid])
        spec["name"] = args.grid
    elif os.path.exists(args.grid):
        with open(args.grid) as f:
            spec = json.load(f)
        spec.setdefault("name", os.path.basename(args.grid))
    else:
        ap.error(f"unknown grid {args.grid!r} (named: "
                 f"{sorted(sw.GRIDS)}, or a JSON spec file)")
    if args.sites:
        keep = {s.strip() for s in args.sites.split(",") if s.strip()}
        spec["units"] = [u for u in spec.get("units", ())
                         if u.get("site") in keep]
    if args.list:
        print(json.dumps({"name": spec.get("name"),
                          "margin": spec.get("margin"),
                          "units": spec.get("units", [])}, indent=1))
        return 0
    if not spec.get("units"):
        ap.error("grid has no units (check --sites filter)")

    bundle = sw.run_sweep(spec, margin=args.margin, reps=args.reps,
                          checkpoint=args.checkpoint, resume=args.resume,
                          out=args.out, profile=args.profile,
                          log=lambda *a: print(*a, flush=True))
    st = bundle.get("stats", {})
    print(json.dumps({"bundle": args.out, "digest": bundle.get("digest"),
                      "version": bundle.get("version"),
                      "profile": bundle.get("profile"),
                      "decisions": len(bundle.get("decisions") or {}),
                      "warm_start": len(bundle.get("warm_start") or ()),
                      "pruned": len(bundle.get("pruned") or ()),
                      "stats": st}, indent=1))
    ok = st.get("units", 0) + st.get("units_resumed", 0) > 0 \
        and st.get("units_failed", 0) == 0
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
