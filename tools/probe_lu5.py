"""Ablate getrf_scattered's driver stages to find the non-kernel cost.

Variant A: panel blocks only (64 kernel calls + slab writes, no updates)
Variant B: A + inter-block updates (trtri+gemms within each 512 slab)
Variant C: full driver (B + trailing updates + final gather)
"""
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from slate_tpu.ops.pallas_kernels import getrf_block_panel, trtri_panel
from slate_tpu.ops.blocks import matmul, matmul_hi
from slate_tpu.linalg.lu import getrf_scattered


def variant(level, a, nb=512, bb=128):
    m, n = a.shape
    k = min(m, n)
    act = jnp.ones((1, m), jnp.float32)
    pivs = []
    for k0 in range(0, k, nb):
        slab = a[:, k0:k0 + nb]
        panel_pivs = []
        for b0 in range(0, nb, bb):
            blk_t, piv_b, act = getrf_block_panel(
                slab[:, b0:b0 + bb].T, act)
            blk_f = blk_t.T
            slab = slab.at[:, b0:b0 + bb].set(blk_f)
            panel_pivs.append(piv_b)
            if level >= 2 and b0 + bb < nb:
                l11b = (jnp.tril(blk_f[piv_b], -1)
                        + jnp.eye(bb, dtype=a.dtype))
                linv_b = trtri_panel(l11b)
                c1 = slab[piv_b, b0 + bb:]
                u12 = matmul_hi(linv_b, c1)
                u12 = u12 + matmul_hi(linv_b, c1 - matmul_hi(l11b, u12))
                lm = blk_f * act.T
                slab = slab.at[:, b0 + bb:].add(-matmul(lm, u12))
                slab = slab.at[piv_b, b0 + bb:].set(u12)
        a = a.at[:, k0:k0 + nb].set(slab)
        piv = jnp.concatenate(panel_pivs)
        pivs.append(piv)
        if level >= 3 and k0 + nb < n:
            l11 = jnp.tril(slab[piv], -1) + jnp.eye(nb, dtype=a.dtype)
            linv = trtri_panel(l11)
            c1 = a[piv, k0 + nb:]
            u12 = matmul_hi(linv, c1)
            u12 = u12 + matmul_hi(linv, c1 - matmul_hi(l11, u12))
            lm = slab * act.T
            a = a.at[:, k0 + nb:].add(-matmul(lm, u12))
            a = a.at[piv, k0 + nb:].set(u12)
    piv_all = jnp.concatenate(pivs)
    if level >= 3:
        return a[piv_all], piv_all
    return a, piv_all


def qtime(f, am, N=6):
    lu, piv = f(am)
    float(lu[-1, -1])
    t0 = time.perf_counter()
    x = am
    for _ in range(N):
        lu, piv = f(x)
        x = x + lu * jnp.float32(1e-30)
    float(x[-1, -1])
    return (time.perf_counter() - t0) / N


n = 8192
rng = np.random.default_rng(0)
am = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32)
                 + n * np.eye(n, dtype=np.float32))
for lv in (1, 2, 3):
    f = jax.jit(lambda x, lv=lv: variant(lv, x))
    t = qtime(f, am)
    print(f"variant {lv}: {t*1e3:.1f} ms", flush=True)
