#!/usr/bin/env python3
"""Render a JSONL telemetry log into per-op latency/throughput tables.

Usage::

    python tools/telemetry_report.py serve.jsonl
    python tools/telemetry_report.py serve.jsonl --op posv
    python tools/telemetry_report.py serve.jsonl --json
    python tools/telemetry_report.py serve.jsonl --strict   # exit 1 on
                                                 # degradation events

The log is what :func:`slate_tpu.perf.telemetry.start_log` streams
(``SLATE_TPU_TELEMETRY_LOG``): one JSON object per line —

* ``request`` records (op, bucket, latency_ms, error, slo_violation,
  batch) from every resolved serve request,
* ``sentinel`` records (the live sentinel's structured degradation /
  infra events, nested under ``event``),
* periodic ``snapshot`` records (``serve.*``/``telemetry.*``/
  ``resilience.*`` counters and gauges).

The report aggregates requests per (op, bucket): count, error count,
EXACT p50/p95/p99/max latency (the log carries the raw values — finer
than the registry's log2 buckets), SLO-violation count and requests/s
over the record span; then lists the sentinel events.  A rotated
sibling (``<path>.1``) is read first when present so the report spans
the rotation.

``--fleet`` additionally rolls up the fleet-router records
(``fleet_request``/``fleet_breaker``/``fleet_*`` from
:func:`slate_tpu.perf.telemetry.observe_fleet`): per-replica and
sharded-lane req/s + p50/p99, the replica-vs-sharded routed split,
the breaker-transition timeline and incident-event counts
(preempt/drain/rejoin...).

``--blackbox BUNDLE`` joins a flight-recorder bundle
(``slate_tpu.perf.blackbox``; rendered alone by ``tools/blackbox.py``)
onto the sentinel events: for each degradation/infra event the report
lists the recorder's ring events within ``--blackbox-window`` seconds
of it — the decisions, fault firings and breaker moves that surrounded
the degradation, correlated on the shared epoch clock.

Stdlib-only, loadable by file path like ``bench_diff.py`` — it never
imports jax (CI runs it under a jax-poisoned path), so it works on any
machine in milliseconds.
"""

import argparse
import json
import os
import sys
from collections import OrderedDict


def load_records(paths):
    """Parse JSONL records from ``paths`` (each preceded by its rotated
    ``<path>.1`` sibling when one exists), oldest first.  Malformed
    lines are counted, never fatal — a live log may be mid-write."""
    recs, bad = [], 0
    files = []
    for p in paths:
        if os.path.exists(p + ".1"):
            files.append(p + ".1")
        files.append(p)
    for fp in files:
        try:
            with open(fp) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        bad += 1
                        continue
                    if isinstance(rec, dict) and "kind" in rec:
                        recs.append(rec)
                    else:
                        bad += 1
        except OSError as e:
            print("unreadable %s: %s" % (fp, e), file=sys.stderr)
    recs.sort(key=lambda r: r.get("t", 0.0))
    return recs, bad


def quantile(sorted_vals, q):
    """Exact linear-interpolated quantile of a pre-sorted list."""
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def aggregate(recs, op_filter=None):
    """``{(op, bucket): row}`` over the request records + the sentinel
    event list + the last snapshot (None when the log carries none)."""
    rows = OrderedDict()
    events = []
    last_snapshot = None
    for rec in recs:
        kind = rec.get("kind")
        if kind == "request":
            op = str(rec.get("op", "?"))
            if op_filter and op != op_filter:
                continue
            key = (op, str(rec.get("bucket", "?")))
            row = rows.get(key)
            if row is None:
                row = rows[key] = {"op": key[0], "bucket": key[1],
                                   "count": 0, "errors": 0,
                                   "slo_violations": 0, "lat_ms": [],
                                   "t_min": None, "t_max": None}
            row["count"] += 1
            t = rec.get("t")
            if isinstance(t, (int, float)):
                row["t_min"] = t if row["t_min"] is None \
                    else min(row["t_min"], t)
                row["t_max"] = t if row["t_max"] is None \
                    else max(row["t_max"], t)
            if rec.get("error"):
                row["errors"] += 1
            elif isinstance(rec.get("latency_ms"), (int, float)):
                row["lat_ms"].append(float(rec["latency_ms"]))
            if rec.get("slo_violation"):
                row["slo_violations"] += 1
        elif kind == "sentinel":
            ev = rec.get("event")
            if isinstance(ev, dict) \
                    and (not op_filter or ev.get("op") == op_filter):
                events.append(ev)
        elif kind == "snapshot":
            last_snapshot = rec
    for row in rows.values():
        lat = sorted(row.pop("lat_ms"))
        span = ((row["t_max"] - row["t_min"])
                if row["t_min"] is not None
                and row["t_max"] is not None else 0.0)
        row["p50_ms"] = quantile(lat, 0.50)
        row["p95_ms"] = quantile(lat, 0.95)
        row["p99_ms"] = quantile(lat, 0.99)
        row["max_ms"] = lat[-1] if lat else None
        row["req_per_s"] = (row["count"] / span) if span > 0 else None
        del row["t_min"], row["t_max"]
    return rows, events, last_snapshot


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return "%.2f" % v
    return str(v)


def aggregate_fleet(recs):
    """Roll up the fleet-router records (``fleet_request``,
    ``fleet_breaker`` and the free-form ``fleet_*`` incident events
    that :func:`slate_tpu.perf.telemetry.observe_fleet` writes) into
    per-lane rows, the breaker-transition timeline and incident-event
    counts."""
    rows = OrderedDict()
    transitions = []
    incidents = OrderedDict()
    lanes = {"replica": 0, "sharded": 0}
    for rec in recs:
        kind = rec.get("kind")
        if not isinstance(kind, str) or not kind.startswith("fleet_"):
            continue
        event = kind[len("fleet_"):]
        if event == "request":
            lane = str(rec.get("lane", "replica"))
            key = ("replica %s" % rec["replica"]
                   if rec.get("lane") != "sharded"
                   and rec.get("replica") is not None else lane)
            lanes[lane if lane in lanes else "replica"] += 1
            row = rows.get(key)
            if row is None:
                row = rows[key] = {"lane": key, "count": 0,
                                   "errors": 0, "lat_ms": [],
                                   "t_min": None, "t_max": None}
            row["count"] += 1
            t = rec.get("t")
            if isinstance(t, (int, float)):
                row["t_min"] = t if row["t_min"] is None \
                    else min(row["t_min"], t)
                row["t_max"] = t if row["t_max"] is None \
                    else max(row["t_max"], t)
            if rec.get("error"):
                row["errors"] += 1
            elif isinstance(rec.get("latency_ms"), (int, float)):
                row["lat_ms"].append(float(rec["latency_ms"]))
        elif event == "breaker":
            transitions.append((rec.get("t"), rec.get("replica"),
                                str(rec.get("state", "?"))))
        else:
            incidents[event] = incidents.get(event, 0) + 1
    for row in rows.values():
        lat = sorted(row.pop("lat_ms"))
        span = ((row["t_max"] - row["t_min"])
                if row["t_min"] is not None
                and row["t_max"] is not None else 0.0)
        row["p50_ms"] = quantile(lat, 0.50)
        row["p99_ms"] = quantile(lat, 0.99)
        row["req_per_s"] = (row["count"] / span) if span > 0 else None
        del row["t_min"], row["t_max"]
    return rows, transitions, incidents, lanes


def format_fleet(rows, transitions, incidents, lanes):
    out = ["fleet rollup:"]
    heads = ["lane", "count", "err", "p50_ms", "p99_ms", "req/s"]
    body = [[r["lane"], r["count"], r["errors"], _fmt(r["p50_ms"]),
             _fmt(r["p99_ms"]), _fmt(r["req_per_s"])]
            for r in rows.values()]
    if body:
        widths = [max(len(str(row[i])) for row in [heads] + body)
                  for i in range(len(heads))]
        for row in [heads] + body:
            out.append("  " + "  ".join(
                str(c).ljust(w)
                for c, w in zip(row, widths)).rstrip())
    else:
        out.append("  no fleet_request records")
    total = sum(lanes.values())
    if total:
        out.append("")
        out.append("  routed split: replica=%d sharded=%d (%.1f%% "
                   "sharded)" % (lanes["replica"], lanes["sharded"],
                                 100.0 * lanes["sharded"] / total))
    out.append("")
    if transitions:
        out.append("  breaker transitions: %d" % len(transitions))
        for t, replica, state in transitions:
            out.append("    [%s] replica %s -> %s"
                       % (_fmt(t), _fmt(replica), state))
    else:
        out.append("  breaker transitions: none")
    if incidents:
        out.append("  incident events: " + "  ".join(
            "%s=%d" % (k, v) for k, v in incidents.items()))
    return "\n".join(out)


def load_blackbox(path):
    """The bundle's event ring + trigger header (``None`` + a reason on
    any parse problem — the join must degrade, not crash the report)."""
    try:
        with open(path) as f:
            blob = json.load(f)
        events = blob.get("events")
        if not isinstance(events, list):
            return None, "bundle carries no events ring"
        return {"trigger": blob.get("trigger") or {},
                "events": events}, None
    except (OSError, ValueError) as e:
        return None, str(e)


def correlate_blackbox(events, bundle, window_s=5.0):
    """``[(sentinel event, [nearby ring events])]`` — ring events whose
    epoch stamp falls within ``window_s`` of each sentinel event."""
    out = []
    ring = bundle.get("events", []) if bundle else []
    for ev in events:
        t = ev.get("t")
        if not isinstance(t, (int, float)):
            out.append((ev, []))
            continue
        near = [r for r in ring
                if isinstance(r.get("t"), (int, float))
                and abs(r["t"] - t) <= window_s]
        out.append((ev, near))
    return out


def format_blackbox_join(pairs, path, err):
    out = ["", "blackbox correlation (%s):" % path]
    if err:
        out.append("  unreadable bundle: %s" % err)
        return "\n".join(out)
    if not pairs:
        out.append("  no sentinel events to correlate")
        return "\n".join(out)
    for ev, near in pairs:
        out.append("  [%s] %s %s %s/%s:" % (
            ev.get("t", "?"), ev.get("classification", "?"),
            ev.get("kind", "?"), ev.get("op", "?"),
            ev.get("bucket", "?")))
        if not near:
            out.append("    (no recorder events in the window)")
        for r in near:
            dt = float(r.get("t", 0.0)) - float(ev.get("t", 0.0))
            fields = " ".join(
                "%s=%s" % (k, r[k]) for k in sorted(r)
                if k not in ("t", "kind") and r[k] is not None)
            out.append("    %+7.3fs %-20s %s"
                       % (dt, r.get("kind", "?"), fields))
    return "\n".join(out)


def format_tables(rows, events, last_snapshot):
    out = []
    heads = ["op", "bucket", "count", "err", "p50_ms", "p95_ms",
             "p99_ms", "max_ms", "req/s", "slo_viol"]
    body = [[r["op"], r["bucket"], r["count"], r["errors"],
             _fmt(r["p50_ms"]), _fmt(r["p95_ms"]), _fmt(r["p99_ms"]),
             _fmt(r["max_ms"]), _fmt(r["req_per_s"]),
             r["slo_violations"]] for r in rows.values()]
    if body:
        widths = [max(len(str(row[i])) for row in [heads] + body)
                  for i in range(len(heads))]
        for row in [heads] + body:
            out.append("  ".join(str(c).ljust(w)
                                 for c, w in zip(row, widths)).rstrip())
    else:
        out.append("no request records")
    out.append("")
    if events:
        out.append("sentinel events: %d" % len(events))
        for ev in events:
            out.append(
                "  [%s] %s %s %s/%s%s" % (
                    ev.get("t", "?"), ev.get("classification", "?"),
                    ev.get("kind", "?"), ev.get("op", "?"),
                    ev.get("bucket", "?"),
                    (" rise=%s%%" % ev["rise_pct"])
                    if "rise_pct" in ev else
                    (" error_rate=%s" % ev["error_rate"])
                    if "error_rate" in ev else ""))
    else:
        out.append("sentinel events: none")
    if last_snapshot:
        counters = last_snapshot.get("counters") or {}
        serve = {k: v for k, v in sorted(counters.items())
                 if k.startswith("serve.")}
        if serve:
            out.append("")
            out.append("last snapshot (serve.* counters):")
            for k, v in serve.items():
                out.append("  %s = %s" % (k, _fmt(float(v))))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="telemetry_report.py",
        description="Render a slate_tpu JSONL telemetry log into "
                    "per-op latency/throughput tables with "
                    "SLO-violation counts.")
    ap.add_argument("logs", nargs="+",
                    help="JSONL telemetry log file(s) "
                         "(SLATE_TPU_TELEMETRY_LOG), oldest first")
    ap.add_argument("--op", help="only this op (e.g. posv)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of tables")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when the log carries any sentinel "
                         "degradation event")
    ap.add_argument("--fleet", action="store_true",
                    help="also roll up the fleet-router records: "
                         "per-replica req/s + p99, breaker "
                         "transitions, replica-vs-sharded split")
    ap.add_argument("--blackbox",
                    help="flight-recorder bundle to correlate the "
                         "sentinel events against (ring events within "
                         "--blackbox-window seconds of each event)")
    ap.add_argument("--blackbox-window", type=float, default=5.0,
                    help="correlation half-width in seconds "
                         "(default %(default)s)")
    args = ap.parse_args(argv)

    recs, bad = load_records(args.logs)
    rows, events, last_snapshot = aggregate(recs, op_filter=args.op)
    degradations = [e for e in events
                    if e.get("classification") == "degradation"]
    bundle = bb_err = pairs = None
    fleet = aggregate_fleet(recs) if args.fleet else None
    if args.blackbox:
        bundle, bb_err = load_blackbox(args.blackbox)
        pairs = correlate_blackbox(events, bundle,
                                   window_s=args.blackbox_window)
    if args.json:
        blob = {
            "records": len(recs), "malformed": bad,
            "rows": list(rows.values()), "sentinel_events": events,
            "degradations": len(degradations),
        }
        if fleet is not None:
            f_rows, transitions, incidents, lanes = fleet
            blob["fleet"] = {
                "rows": list(f_rows.values()),
                "breaker_transitions": [
                    {"t": t, "replica": r, "state": s}
                    for t, r, s in transitions],
                "incidents": dict(incidents), "lanes": lanes,
            }
        if args.blackbox:
            blob["blackbox"] = {
                "path": args.blackbox, "error": bb_err,
                "trigger": (bundle or {}).get("trigger"),
                "correlated": [
                    {"event": ev, "nearby": near}
                    for ev, near in (pairs or [])]}
        print(json.dumps(blob, indent=1))
    else:
        print(format_tables(rows, events, last_snapshot))
        if fleet is not None:
            print()
            print(format_fleet(*fleet))
        if args.blackbox:
            print(format_blackbox_join(pairs or [], args.blackbox,
                                       bb_err))
        if bad:
            print("\n%d malformed line(s) skipped" % bad)
    return 1 if (args.strict and degradations) else 0


if __name__ == "__main__":
    sys.exit(main())
