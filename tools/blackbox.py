#!/usr/bin/env python3
"""Render a slate_tpu flight-recorder bundle into a postmortem report.

Usage::

    python tools/blackbox.py BUNDLE.json
    python tools/blackbox.py BUNDLE.json --last 30
    python tools/blackbox.py BUNDLE.json --json
    python tools/blackbox.py BUNDLE.json --strict   # exit 1 on
                                  # unrecovered/strict events or a
                                  # malformed/unknown-schema bundle

The bundle is what :func:`slate_tpu.perf.blackbox.trigger` dumps on a
trigger (health strict failure, quarantine, device_loss, breaker
open/trip, bench watchdog/SIGTERM, opt-in excepthook): the event ring +
metrics snapshot + knob/config state + autotune digest + fault-plan
replay log + host keys, schema ``slate_tpu.blackbox/1``.

The report shows, in order: the trigger header (reason, detail, host,
knobs), the **last-events timeline** (relative seconds to the trigger,
one line per ring event), the **trigger chain** (only the
resilience/escalation events — inject firings, health verdicts, ABFT
rungs, checkpoint restores, breaker transitions, quarantines, sentinel
verdicts — the causal spine a postmortem reads first), and per-kind
event counts.

Stdlib-only, loadable by file path like ``bench_diff.py`` — it never
imports jax (CI runs it under a jax-poisoned path), so it works on any
machine in milliseconds.
"""

import argparse
import json
import sys

SCHEMA = "slate_tpu.blackbox/1"

#: event kinds (prefix match) that form the causal escalation spine
CHAIN_PREFIXES = ("inject.", "health.", "abft.", "ckpt.", "breaker.",
                  "autotune.quarantine", "sentinel.", "trigger",
                  "serve.deadline", "serve.backpressure", "serve.error",
                  "bench.")

#: event kinds whose presence means the run ended UNRECOVERED — the
#: ``--strict`` gate (a recovered ladder leaves none of these)
STRICT_KINDS = ("health.unrecovered", "abft.unrecovered")


def load_bundle(path):
    """Parse one bundle; returns (bundle|None, problems list)."""
    problems = []
    try:
        with open(path) as f:
            blob = json.load(f)
    except (OSError, ValueError) as e:
        return None, ["unreadable bundle: %s" % e]
    if not isinstance(blob, dict):
        return None, ["bundle is not a JSON object"]
    if blob.get("schema") != SCHEMA:
        problems.append("unknown schema %r (this tool reads %s)"
                        % (blob.get("schema"), SCHEMA))
    if not isinstance(blob.get("events"), list):
        problems.append("missing events ring")
        blob["events"] = []
    if not isinstance(blob.get("trigger"), dict):
        problems.append("missing trigger block")
        blob["trigger"] = {}
    return blob, problems


def _fields(ev):
    """One compact ``k=v`` tail for an event line (the bookkeeping keys
    are rendered elsewhere)."""
    parts = []
    for k in sorted(ev):
        if k in ("t", "kind"):
            continue
        v = ev[k]
        if v is None:
            continue
        if isinstance(v, float):
            v = "%.6g" % v
        parts.append("%s=%s" % (k, v))
    return " ".join(parts)


def _chain(events):
    return [ev for ev in events
            if str(ev.get("kind", "")).startswith(CHAIN_PREFIXES)]


def _counts(events):
    out = {}
    for ev in events:
        k = str(ev.get("kind", "?"))
        out[k] = out.get(k, 0) + 1
    return out


def strict_findings(blob, problems):
    """The ``--strict`` verdict inputs: bundle problems plus any
    unrecovered-class events on the ring."""
    findings = list(problems)
    for ev in blob.get("events", []):
        if str(ev.get("kind", "")) in STRICT_KINDS:
            findings.append("unrecovered event on the ring: %s (%s)"
                            % (ev.get("kind"), _fields(ev)))
    return findings


def report(blob, problems, last=40):
    trig = blob.get("trigger", {})
    t_trig = trig.get("t") or blob.get("created") or 0.0
    host = blob.get("host", {}) or {}
    out = []
    out.append("flight-recorder bundle (%s)" % blob.get("schema", "?"))
    out.append("trigger: %s%s" % (
        trig.get("reason", "?"),
        (" — " + str(trig.get("detail"))) if trig.get("detail") else ""))
    out.append("host: python %s on %s, pid %s%s" % (
        host.get("python", "?"), host.get("platform", "?"),
        host.get("pid", "?"),
        (", jax %s" % host["jax"]) if host.get("jax") else ""))
    at = blob.get("autotune", {}) or {}
    if at.get("decisions"):
        out.append("autotune table: %d decision(s), sha1 %s, "
                   "%d quarantined"
                   % (at.get("decisions", 0), at.get("sha1", "?"),
                      at.get("quarantined", 0)))
    fp = blob.get("fault_plan")
    if isinstance(fp, dict) and fp:
        out.append("fault plan: seed=%s fired=%s specs=%s" % (
            fp.get("seed"), fp.get("fired"),
            ",".join("%s=%s" % (s.get("site"), s.get("kind"))
                     for s in fp.get("specs", []))))
    knobs = blob.get("knobs", {}) or {}
    set_knobs = sorted(k for k in knobs if k.startswith("SLATE_TPU_"))
    if set_knobs:
        out.append("knobs set: " + " ".join(set_knobs))
    for p in problems:
        out.append("PROBLEM: " + p)
    events = blob.get("events", [])
    out.append("")
    tail = events[-max(1, int(last)):] if events else []
    out.append("last %d event(s) (dt relative to the trigger):"
               % len(tail))
    for ev in tail:
        dt = float(ev.get("t", t_trig) or 0.0) - float(t_trig or 0.0)
        out.append("  %+9.3fs  %-22s %s"
                   % (dt, ev.get("kind", "?"), _fields(ev)))
    if not tail:
        out.append("  (empty ring)")
    chain = _chain(events)
    out.append("")
    out.append("trigger chain (%d escalation event(s)):" % len(chain))
    for ev in chain[-max(1, int(last)):]:
        dt = float(ev.get("t", t_trig) or 0.0) - float(t_trig or 0.0)
        out.append("  %+9.3fs  %-22s %s"
                   % (dt, ev.get("kind", "?"), _fields(ev)))
    if not chain:
        out.append("  (none recorded)")
    counts = _counts(events)
    if counts:
        out.append("")
        out.append("event counts: " + "  ".join(
            "%s=%d" % (k, counts[k]) for k in sorted(counts)))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="blackbox.py",
        description="Render a slate_tpu flight-recorder forensic "
                    "bundle: trigger header, last-events timeline, "
                    "escalation chain.")
    ap.add_argument("bundle", help="bundle JSON dumped by the recorder")
    ap.add_argument("--last", type=int, default=40,
                    help="events shown in the timeline/chain "
                         "(default %(default)s)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when the bundle is malformed, carries "
                         "an unknown schema, or records an "
                         "unrecovered health/ABFT event")
    args = ap.parse_args(argv)

    blob, problems = load_bundle(args.bundle)
    if blob is None:
        print("\n".join(problems), file=sys.stderr)
        return 1
    findings = strict_findings(blob, problems)
    if args.json:
        events = blob.get("events", [])
        print(json.dumps({
            "schema": blob.get("schema"),
            "trigger": blob.get("trigger"),
            "host": blob.get("host"),
            "autotune": blob.get("autotune"),
            "fault_plan": blob.get("fault_plan"),
            "events": events[-max(1, args.last):],
            "chain": _chain(events),
            "counts": _counts(events),
            "problems": problems,
            "strict_findings": findings,
        }, indent=1, default=str))
    else:
        print(report(blob, problems, last=args.last))
        if args.strict and findings:
            for f in findings:
                print("STRICT: " + f)
    return 1 if (args.strict and findings) else 0


if __name__ == "__main__":
    sys.exit(main())
