"""Probe 2: candidate getrf panel + trailing structures, timed honestly.

All operands passed as jit args (no giant closure constants — the axon
remote-compile rejects >~100MB programs); sync via float() scalar pull.
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def timeit(fn, *args, iters=1):
    float(fn(*args))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts) / iters


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    nb = 512
    rng = np.random.default_rng(0)
    a_np = rng.standard_normal((n, n)).astype(np.float32) + n * np.eye(
        n, dtype=np.float32)
    a = jnp.asarray(a_np)

    # ---- realistic trailing step: v <- v - L21 @ (Linv @ v[:nb]) ----
    l21_np = rng.standard_normal((n - nb, nb)).astype(np.float32) * 0.01
    linv_np = np.tril(rng.standard_normal((nb, nb)).astype(np.float32) * .01)
    l21 = jnp.asarray(l21_np)
    linv = jnp.asarray(linv_np)
    reps = 8

    @jax.jit
    def trail(v, l21, linv):
        def body(i, v):
            u12 = jnp.matmul(linv, v[:nb],
                             precision=lax.Precision.HIGHEST)
            upd = jnp.matmul(l21, u12, precision=lax.Precision.HIGH)
            return v.at[nb:].add(-upd)
        return lax.fori_loop(0, reps, body, v)[0, 0]

    t = timeit(trail, a, l21, linv, iters=reps)
    fl = 2 * nb * n * (n - nb) + 2 * nb * nb * n
    print(f"trailing step (k={nb}, n={n}): {t*1e3:8.2f} ms "
          f"{fl/t/1e12:6.2f} TF/s", flush=True)

    # ---- XLA LU panel narrow widths ----
    for wdt in (128, 256):
        pan = jnp.asarray(a_np[:, :wdt])
        it = 20

        @jax.jit
        def panl(x):
            def body(i, v):
                lu, _, pl = lax.linalg.lu(v)
                return x + lu * jnp.float32(1e-30)
            v = lax.fori_loop(0, it - 1, body, x)
            return lax.linalg.lu(v)[0][-1, -1]

        t = timeit(panl, pan, iters=it)
        print(f"xla lu panel {n}x{wdt}: {t*1e3:8.2f} ms", flush=True)

    # ---- _tall_panel_lu_pp at several ib ----
    from slate_tpu.linalg import lu as lumod

    for ib in (32, 64, 128):
        pan = jnp.asarray(a_np[:, :nb])
        it = 8

        @jax.jit
        def panl2(x):
            def body(i, v):
                lu, pl = lumod._tall_panel_lu_pp(v, ib=ib)
                return x + lu * jnp.float32(1e-30)
            v = lax.fori_loop(0, it - 1, body, x)
            return lumod._tall_panel_lu_pp(v, ib=ib)[0][-1, -1]

        t = timeit(panl2, pan, iters=it)
        print(f"pp panel ib={ib} {n}x{nb}: {t*1e3:8.2f} ms", flush=True)

    # ---- per-panel slab gather as used today (fused into consumer?) ----
    perm = jnp.asarray(rng.permutation(n))

    @jax.jit
    def gath2(x, l21, linv):
        def body(i, v):
            vp = v[perm]                      # full-slab row permute
            u12 = jnp.matmul(linv, vp[:nb],
                             precision=lax.Precision.HIGHEST)
            upd = jnp.matmul(l21, u12, precision=lax.Precision.HIGH)
            return vp.at[nb:].add(-upd)
        return lax.fori_loop(0, reps, body, x)[0, 0]

    t = timeit(gath2, a, l21, linv, iters=reps)
    print(f"permute+trailing step: {t*1e3:8.2f} ms "
          f"{fl/t/1e12:6.2f} TF/s", flush=True)

    # ---- scatter-add trailing (deferred pivoting shape) ----
    rows = jnp.asarray(rng.permutation(n)[: n - nb])

    @jax.jit
    def scat2(x, l21, linv):
        def body(i, v):
            rws = v[rows[:nb]]               # gather nb pivot rows
            u12 = jnp.matmul(linv, rws[:, nb:],
                             precision=lax.Precision.HIGHEST)
            upd = jnp.matmul(l21[: n - 2 * nb], u12,
                             precision=lax.Precision.HIGH)
            return v.at[rows[nb:], nb:].add(-upd)
        return lax.fori_loop(0, reps, body, x)[0, 0]

    t = timeit(scat2, a, l21, linv, iters=reps)
    print(f"gather-rows+scatter-add step: {t*1e3:8.2f} ms", flush=True)


if __name__ == "__main__":
    main()
