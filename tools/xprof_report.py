#!/usr/bin/env python3
"""XProf report CLI — render a device-truth profile capture.

Usage::

    python tools/xprof_report.py /tmp/xprof_cap
    python tools/xprof_report.py /tmp/xprof_cap/xprof_getrf.json
    python tools/xprof_report.py trace.json.gz --routine getrf
    python tools/xprof_report.py /tmp/xprof_cap --json

The argument is anything ``slate_tpu/perf/xprof.py`` can load: a
capture directory (``SLATE_TPU_XPROF=<dir>`` — the newest
``xprof_*.json`` artifact wins, falling back to the newest raw trace
underneath), a single ``xprof_*.json`` artifact, or a raw
``*.trace.json[.gz]`` trace-event file straight out of
``jax.profiler.start_trace``.

Printed, in order: the capture header (label, digest, capture wall,
HBM high-water and compile ledger when the artifact carries them), a
per-kernel device-time table ranked by total device seconds with each
kernel's joined (op, stage) bucket, and the per-routine stage rollup —
the same ``stages`` map ``attr.attribute`` joins as its
``device_profile`` compute source.  ``--routine`` filters both tables
to one op; ``--json`` emits the loaded profile verbatim for scripting.

Stdlib-only, like ``bench_diff.py`` / ``gap_report.py``: the parser is
loaded directly by file path, so this tool NEVER imports jax and runs
anywhere in milliseconds.
"""

import argparse
import importlib.util
import json
import os
import sys


def _load_xprof():
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.normpath(os.path.join(
        here, os.pardir, "slate_tpu", "perf", "xprof.py"))
    alias = "_slate_tpu_xprof"
    if alias in sys.modules:
        return sys.modules[alias]
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


def _fmt_s(v) -> str:
    try:
        v = float(v)
    except (TypeError, ValueError):
        return "-"
    if v >= 1.0:
        return "%.3f s" % v
    if v >= 1e-3:
        return "%.3f ms" % (v * 1e3)
    return "%.1f us" % (v * 1e6)


def _header(prof: dict) -> list:
    lines = ["xprof capture: %s" % (prof.get("label") or "(unlabelled)")]
    lines.append("  digest %s  events %s  trace %s"
                 % (prof.get("digest", "-"), prof.get("events", "-"),
                    os.path.basename(str(prof.get("trace_path") or "-"))))
    if prof.get("capture_wall_s") is not None:
        lines.append("  capture wall %s (includes trace start/stop "
                     "overhead)" % _fmt_s(prof["capture_wall_s"]))
    mem = prof.get("memory") or {}
    if mem.get("hbm_peak_gb") is not None:
        lines.append("  hbm high-water +%.3f GB over the capture"
                     % float(mem["hbm_peak_gb"]))
    comp = prof.get("compile") or {}
    if comp.get("events"):
        lines.append("  compiles during capture: %d (%s)"
                     % (comp["events"], _fmt_s(comp.get("total_s"))))
    return lines


def main(argv=None) -> int:
    xp = _load_xprof()
    ap = argparse.ArgumentParser(
        prog="xprof_report.py",
        description="Render an xprof capture: per-kernel device times "
                    "and the per-routine stage rollup.")
    ap.add_argument("path", help="capture dir, xprof_*.json artifact, "
                                 "or raw *.trace.json[.gz]")
    ap.add_argument("--routine", default="",
                    help="only kernels/stages joined to this op")
    ap.add_argument("--kernels", type=int, default=20,
                    help="kernel-table row limit (default %(default)s; "
                         "0 = all)")
    ap.add_argument("--json", action="store_true",
                    help="emit the loaded profile as JSON and exit")
    args = ap.parse_args(argv)

    try:
        prof = xp.load_profile(args.path)
    except Exception as e:
        print("xprof_report: cannot load %s: %s" % (args.path, e),
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(prof, indent=1, sort_keys=True))
        return 0

    for line in _header(prof):
        print(line)

    kernels = [k for k in (prof.get("kernels") or ())
               if not args.routine or k.get("op") == args.routine]
    print()
    if kernels:
        shown = kernels if args.kernels <= 0 else kernels[:args.kernels]
        total = sum(float(k.get("total_s") or 0.0) for k in kernels)
        print("kernels (%d%s, %s device total):"
              % (len(kernels),
                 "" if len(shown) == len(kernels)
                 else ", top %d shown" % len(shown),
                 _fmt_s(total)))
        print("  %10s %6s  %-14s %s"
              % ("device", "count", "op.stage", "kernel"))
        for k in shown:
            bucket = ("%s.%s" % (k["op"], k["stage"])
                      if k.get("op") else "-")
            print("  %10s %6d  %-14s %s"
                  % (_fmt_s(k.get("total_s")), int(k.get("count") or 0),
                     bucket, str(k.get("name", ""))[:60]))
    else:
        print("kernels: none%s" % (" for routine %r" % args.routine
                                   if args.routine else ""))

    stages = prof.get("stages") or {}
    src = prof.get("stage_source") or {}
    print()
    printed = 0
    for op in sorted(stages):
        if args.routine and op != args.routine:
            continue
        m = stages[op]
        op_total = sum(float(v) for v in m.values())
        print("stage rollup: %s (%s device)" % (op, _fmt_s(op_total)))
        for st, v in sorted(m.items(), key=lambda kv: -float(kv[1])):
            tag = (src.get(op) or {}).get(st, "kernels")
            pct = 100.0 * float(v) / op_total if op_total > 0 else 0.0
            print("  %10s %5.1f%%  %-10s [%s]"
                  % (_fmt_s(v), pct, st, tag))
        printed += 1
    if not printed:
        print("stage rollup: none%s" % (" for routine %r" % args.routine
                                        if args.routine else ""))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
