#!/usr/bin/env python3
"""Gap report CLI — render a BENCH artifact's roofline attribution.

Usage::

    python tools/gap_report.py BENCH_r04.json
    python tools/gap_report.py BENCH_r04.json --routine geqrf
    python tools/gap_report.py BENCH_r04.json --json

For every routine submetric in the artifact this prints the per-stage
gap report: analytical flops/bytes per stage (panel / pivot / trsm /
update / …), each stage's MXU-vs-HBM roofline placement and achieved
fraction, and the ranked bottleneck list that sums to the observed
deficit.  Artifacts from bench r7+ carry the measured-timer-joined
``attribution`` blocks and those are rendered verbatim; older artifacts
(r03/r04) get the analytical model derived on the spot from the
submetric labels and autotune tags — so the historical trajectory
explains too.

Each report header names its ``compute_source`` — where the stage
weights came from (``device_profile`` for an XProf capture joined at
bench time, ``timers`` for host stage timers, ``model`` for the pure
analytical split) — and the summary line rolls the sources up so a
device-truth artifact is distinguishable from a host-timer one at a
glance.

Stdlib-only, like ``bench_diff.py``: the attribution engine
(``slate_tpu/perf/attr.py``) and the artifact loader
(``slate_tpu/perf/regress.py``) are loaded directly by file path, so
this tool NEVER imports jax and runs anywhere in milliseconds.

Roofline constants default to the measured-library peaks per platform
and are overridable for new hardware via ``SLATE_TPU_PEAK_TFLOPS[_
<DTYPE>]`` / ``SLATE_TPU_PEAK_HBM_GBS`` / ``SLATE_TPU_PEAK_ICI_GBS``
(see docs/usage.md "Gap reports").
"""

import argparse
import importlib.util
import json
import os
import sys


def _load(modfile: str, alias: str):
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.normpath(os.path.join(
        here, os.pardir, "slate_tpu", "perf", modfile))
    if alias in sys.modules:
        return sys.modules[alias]
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod     # dataclasses resolve __module__ here
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    regress = _load("regress.py", "_slate_tpu_regress")
    attr = _load("attr.py", "_slate_tpu_attr")
    ap = argparse.ArgumentParser(
        prog="gap_report.py",
        description="Render a bench artifact's roofline attribution "
                    "(where the time went, per stage).")
    ap.add_argument("artifact", help="BENCH_r*.json (driver wrapper, "
                    "bare aggregate, or raw bench stdout)")
    ap.add_argument("--routine", default="",
                    help="only labels containing this substring")
    ap.add_argument("--platform", default="tpu", choices=("tpu", "cpu"),
                    help="roofline constant set for derived reports "
                         "(default %(default)s)")
    ap.add_argument("--json", action="store_true",
                    help="emit the reports as JSON instead of tables")
    args = ap.parse_args(argv)

    art = regress.load_artifact(args.artifact)
    if art.infra and not art.submetrics:
        print("INFRA %s: %s" % (art.name, "; ".join(art.infra)),
              file=sys.stderr)
        return 1
    reports = []
    for label in sorted(art.submetrics):
        if args.routine and args.routine not in label:
            continue
        rep = art.attribution.get(label)
        if not (isinstance(rep, dict) and rep.get("stages")):
            rep = attr.attribute(label, art.submetrics.get(label),
                                 autotune=art.autotune or None,
                                 platform=args.platform)
        if rep:
            reports.append(rep)
    if not reports:
        print("no attributable routines in %s" % art.name,
              file=sys.stderr)
        return 1
    srcs = {}
    for rep in reports:
        s = (rep.get("compute_source") or rep.get("backend_source")
             or "model")
        srcs[s] = srcs.get(s, 0) + 1
    if args.json:
        print(json.dumps({"artifact": art.name, "sources": srcs,
                          "reports": reports}, indent=1))
    else:
        print("gap report: %s (%d routines; sources: %s)"
              % (art.name, len(reports),
                 " ".join("%s=%d" % kv for kv in sorted(srcs.items()))))
        for rep in reports:
            print()
            print(attr.format_report(rep))
        if art.infra:
            print()
            print("INFRA %s: %s" % (art.name, "; ".join(art.infra)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
