"""True per-step cost ablation of the transposed masked LU block kernel."""
import functools, time, numpy as np, jax, jax.numpy as jnp
import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

m, bb, ib = 8192, 128, 16
f32 = jnp.float32

def make_kernel(level):
    def kern(slab_in, act_in, out_ref, piv_ref, act_out, ohsub):
        iota_lane = jax.lax.broadcasted_iota(jnp.int32, (1, m), 1)
        iota_sub = jax.lax.broadcasted_iota(jnp.int32, (ib, 1), 0)
        piv_cols = jax.lax.broadcasted_iota(jnp.int32, (1, bb), 1)
        out_ref[:] = slab_in[:]
        act_out[:] = act_in[:]
        piv_ref[:] = jnp.zeros((1, bb), jnp.int32)
        for s in range(bb // ib):
            s0 = s * ib
            def col_step(j, _, s0=s0):
                col = out_ref[pl.ds(s0 + j, 1), :]
                act = act_out[:]
                mag = jnp.abs(col) * act
                if level >= 2:     # argmax reduces
                    mx = jnp.max(mag)
                    cand = jnp.where((mag >= mx) & (act > 0), iota_lane, m)
                    p = jnp.min(cand).astype(jnp.int32)
                else:
                    p = jnp.int32(0)
                piv_ref[:] = jnp.where(piv_cols == s0 + j, p, piv_ref[:])
                oh = (iota_lane == p).astype(f32)
                if level >= 3:     # pval reduce + lrow
                    pval = jnp.sum(col * oh)
                    safe = jnp.where(pval == 0, 1.0, pval)
                    live = (act > 0) & (oh == 0)
                    lrow = jnp.where(live, col / safe, 0.0)
                    newcol = jnp.where(live, lrow, col)
                else:
                    lrow = col; newcol = col
                if level >= 4:     # sub-slab rank-1
                    sub = out_ref[s0:s0 + ib, :]
                    pcol = jnp.sum(sub * oh, axis=1, keepdims=True)
                    out_ref[s0:s0 + ib, :] = jnp.where(
                        iota_sub == j, newcol,
                        sub - jnp.where(iota_sub > j, pcol, 0.0) * lrow)
                if level >= 5:     # ohsub accumulate
                    ohsub[:] = jnp.where(iota_sub == j, oh, ohsub[:])
                act_out[:] = act * (1.0 - oh)
                return 0
            ohsub[:] = jnp.zeros((ib, m), f32)
            jax.lax.fori_loop(0, ib, col_step, 0)
    return kern

rng = np.random.default_rng(0)
slab_t = jnp.asarray(rng.standard_normal((bb, m)).astype(np.float32))
act = jnp.ones((1, m), f32)
ITERS = 512
for level in (1, 2, 3, 4, 5):
    f = pl.pallas_call(
        make_kernel(level),
        out_shape=(jax.ShapeDtypeStruct((bb, m), f32),
                   jax.ShapeDtypeStruct((1, bb), jnp.int32),
                   jax.ShapeDtypeStruct((1, m), f32)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        scratch_shapes=[pltpu.VMEM((ib, m), f32)],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
    )
    @jax.jit
    def chain(s, a, f=f):
        def body(i, carry):
            s2, _, _ = f(carry, a)
            return s + s2 * jnp.float32(1e-30)
        v = lax.fori_loop(0, ITERS - 1, body, s)
        return f(v, a)[0][-1, -1]
    float(chain(slab_t, act))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); float(chain(slab_t, act)); ts.append(time.perf_counter()-t0)
    print(f"level {level}: {min(ts)/ITERS*1e3:.3f} ms/call "
          f"({min(ts)/ITERS/128*1e6:.2f} us/step)", flush=True)
