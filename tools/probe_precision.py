"""What do DEFAULT / HIGH / HIGHEST dot precisions cost on this chip,
and which one does `@` use?  Long chains so the tunnel RT is noise."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def wall(f, args, reps=3):
    np.asarray(f(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(f(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    rng = np.random.default_rng(0)
    n = 4096
    a_np = rng.standard_normal((n, n)).astype(np.float32)
    a = jnp.asarray(a_np)
    iters = 24

    for name, prec in [("default(@)", None),
                       ("DEFAULT", lax.Precision.DEFAULT),
                       ("HIGH", lax.Precision.HIGH),
                       ("HIGHEST", lax.Precision.HIGHEST)]:
        def fn(x, b, prec=prec):
            def body(i, v):
                if prec is None:
                    return (v @ b) * jnp.float32(1e-4)
                return jnp.matmul(v, b, precision=prec) * jnp.float32(1e-4)
            return lax.fori_loop(0, iters, body, x)[0, 0]
        f = jax.jit(fn)
        t = wall(f, (a, a)) / iters
        # accuracy of one product vs float64
        if prec is None:
            c = np.asarray(jax.jit(lambda x, b: x @ b)(a, a))
        else:
            c = np.asarray(jax.jit(
                lambda x, b, p=prec: jnp.matmul(x, b, precision=p))(a, a))
        ref = a_np.astype(np.float64) @ a_np.astype(np.float64)
        err = np.abs(c - ref).max() / np.abs(ref).max()
        print(f"{name:11s}: {t*1e3:6.2f} ms  {2*n**3/t/1e12:6.1f} TF/s  "
              f"maxrel {err:.2e}", flush=True)


if __name__ == "__main__":
    main()
