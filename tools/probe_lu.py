"""Probe: where does getrf time go on one chip?

Times the LU building blocks at the bench config (n=8192 fp32, nb=512)
using the chained-jit pattern (each iteration depends on the previous, so
XLA cannot collapse the chain; tunnel latency amortizes out).

Usage: python tools/probe_lu.py [n]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def timeit(fn, *args, iters=1):
    # float() forces the scalar transfer: block_until_ready on the axon
    # tunnel returns before remote execution finishes (the round-2 lesson
    # baked into bench.py's _timeit)
    float(fn(*args))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts) / iters


def chain(fn, x, iters):
    @jax.jit
    def run(x):
        def body(i, v):
            out = fn(v)
            return x + out * jnp.float32(1e-30)
        v = lax.fori_loop(0, iters - 1, body, x)
        return fn(v)
    return run


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    nb = 512
    rng = np.random.default_rng(0)
    a_np = rng.standard_normal((n, n)).astype(np.float32) + n * np.eye(
        n, dtype=np.float32)
    a = jnp.asarray(a_np)
    results = {}

    # 1. full current getrf_rec
    from slate_tpu.linalg.lu import (getrf_rec, getrf_panels,
                                     _panel_lu_tntpiv)

    it = 6
    f = chain(lambda x: getrf_rec(x, nb)[0][-1, -1], a, it)
    t = timeit(f, a, iters=it)
    results["getrf_rec"] = t
    print(f"getrf_rec      n={n}: {t*1e3:9.2f} ms  "
          f"{2*n**3/3/t/1e12:6.2f} TF/s", flush=True)

    f = chain(lambda x: getrf_panels(x, nb)[0][-1, -1], a, it)
    t = timeit(f, a, iters=it)
    results["getrf_panels"] = t
    print(f"getrf_panels   n={n}: {t*1e3:9.2f} ms  "
          f"{2*n**3/3/t/1e12:6.2f} TF/s", flush=True)

    # 2. XLA fused LU panel at several heights
    for mh in (n, n // 2, n // 4):
        pan = jnp.asarray(a_np[:mh, :nb])
        it = 20
        f = chain(lambda x: lax.linalg.lu(x)[0][-1, -1], pan, it)
        t = timeit(f, pan, iters=it)
        results[f"xla_lu_panel_{mh}"] = t
        print(f"xla lu panel {mh}x{nb}: {t*1e3:9.2f} ms", flush=True)

    # 3. tournament panel, same heights
    for mh in (n, n // 2):
        pan = jnp.asarray(a_np[:mh, :nb])
        it = 20
        f = chain(lambda x: _panel_lu_tntpiv(x, nb)[0][-1, -1], pan, it)
        t = timeit(f, pan, iters=it)
        results[f"tnt_panel_{mh}"] = t
        print(f"tnt panel   {mh}x{nb}: {t*1e3:9.2f} ms", flush=True)

    # 4. full row gather (the per-panel permutation cost today)
    perm = jnp.asarray(rng.permutation(n))

    @jax.jit
    def gath(x):
        def body(i, v):
            return v[perm] * jnp.float32(1.0)
        return lax.fori_loop(0, 20, body, x)[0, 0]

    t = timeit(gath, a, iters=20)
    results["row_gather_full"] = t
    print(f"row gather {n}x{n}: {t*1e3:9.2f} ms "
          f"({2*n*n*4/t/1e9:6.0f} GB/s)", flush=True)

    # 5. scatter-add rows
    upd = jnp.asarray(rng.standard_normal((n // 2, n)).astype(np.float32))
    rows = jnp.asarray(rng.permutation(n)[: n // 2])

    @jax.jit
    def scat(x, u):
        def body(i, v):
            return v.at[rows].add(u * jnp.float32(1e-6))
        return lax.fori_loop(0, 20, body, x)[0, 0]

    t = timeit(scat, a, upd, iters=20)
    results["row_scatter_add_half"] = t
    print(f"row scatter-add {n//2}x{n}: {t*1e3:9.2f} ms "
          f"({3*n/2*n*4/t/1e9:6.0f} GB/s)", flush=True)

    # 6. trsm vs inv-gemm for U12 (512 x n)
    l11 = jnp.tril(jnp.asarray(a_np[:nb, :nb] / n), -1) + jnp.eye(
        nb, dtype=jnp.float32)
    a12 = jnp.asarray(a_np[:nb, :])

    @jax.jit
    def trsm20(x):
        def body(i, v):
            return lax.linalg.triangular_solve(
                l11, v, left_side=True, lower=True, unit_diagonal=True) \
                * jnp.float32(1.0)
        return lax.fori_loop(0, 20, body, x)[0, 0]

    t = timeit(trsm20, a12, iters=20)
    results["trsm_512xn"] = t
    print(f"trsm 512x{n}: {t*1e3:9.2f} ms", flush=True)

    from slate_tpu.ops.pallas_kernels import trtri_panel

    @jax.jit
    def invgemm20(x):
        linv = trtri_panel(l11)
        def body(i, v):
            return (linv @ v) * jnp.float32(1.0)
        return lax.fori_loop(0, 20, body, x)[0, 0]

    t = timeit(invgemm20, a12, iters=20)
    results["invgemm_512xn"] = t
    print(f"trtri+gemm 512x{n}: {t*1e3:9.2f} ms", flush=True)

    # 7. gemm anchor
    b = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32))

    @jax.jit
    def g20(x):
        def body(i, v):
            return jnp.matmul(v, b, precision=lax.Precision.HIGH) \
                * jnp.float32(1e-4)
        return lax.fori_loop(0, 8, body, x)[0, 0]

    t = timeit(g20, a, iters=8)
    print(f"gemm {n}: {t*1e3:9.2f} ms  {2*n**3/t/1e12:6.2f} TF/s",
          flush=True)


if __name__ == "__main__":
    main()
