"""Single-session A/B: getrf_rec pallas-leaf vs XLA panels; geqrf at
the bench config (r4 regression check)."""
import time, sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax, jax.numpy as jnp
from jax import lax
from slate_tpu.linalg.lu import getrf_rec, _panel_lu
from slate_tpu.linalg.qr import geqrf_panels

def P(*a): print(*a, flush=True)

def slope(fbody, x0, *extra, K1=2, K2=10, N=4):
    def mk(K):
        @jax.jit
        def g(x, *e):
            def body(i, xx):
                return fbody(xx, *e)
            return lax.fori_loop(0, K, body, x)
        return g
    res = []
    for K in (K1, K2):
        g = mk(K)
        x = g(x0, *extra); float(jnp.asarray(x).ravel()[-1])
        ts = []
        for _ in range(N):
            t0 = time.perf_counter()
            x = g(x0, *extra); float(jnp.asarray(x).ravel()[-1])
            ts.append(time.perf_counter() - t0)
        res.append(min(ts))
    return (res[1] - res[0]) / (K2 - K1)

n = 8192
key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (n, n), jnp.float32) + n * jnp.eye(n, dtype=jnp.float32)

# gemm anchor same-session (bench's blocks.matmul HIGH)
from slate_tpu.ops import blocks
b2 = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
def gch(x, bb):
    return blocks.matmul(x, bb) * jnp.float32(1e-4)
t = slope(gch, a, b2)
gemm_tf = 2*n**3/t/1e12
P("gemm HIGH anchor            %7.1f ms  %5.1f TF/s" % (t*1e3, gemm_tf))

f = lambda x, *_: x + getrf_rec(x, 512)[0] * jnp.float32(1e-30)
t = slope(f, a)
P("getrf_rec DEFAULT (pallas)  %7.1f ms  %5.1f TF/s (%4.1f%% of anchor)"
  % (t*1e3, 2*n**3/3/t/1e12, 100*(2*n**3/3/t/1e12)/gemm_tf))

f2 = lambda x, *_: x + getrf_rec(x, 512, panel=_panel_lu)[0] * jnp.float32(1e-30)
t = slope(f2, a)
P("getrf_rec XLA panels        %7.1f ms  %5.1f TF/s (%4.1f%% of anchor)"
  % (t*1e3, 2*n**3/3/t/1e12, 100*(2*n**3/3/t/1e12)/gemm_tf))

m2, n2 = 32768, 4096
tall = jax.random.normal(jax.random.PRNGKey(2), (m2, n2), jnp.float32)
def qf(x, *_):
    f3, taus = geqrf_panels(x, 512)
    return x + f3 * jnp.float32(1e-30)
t = slope(qf, tall, K1=2, K2=8)
qr_fl = 2.0*m2*n2**2 - 2.0*n2**3/3.0
P("geqrf m=32768 n=4096        %7.1f ms  %5.1f TF/s (r3: 23.5, r4: 18.9)"
  % (t*1e3, qr_fl/t/1e12))
