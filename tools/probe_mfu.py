"""Decomposition probe: where does potrf/getrf/geqrf time go on the
chip?  Uses SLOPE timing: each op is chained inside one jit at two
different iteration counts and the per-iteration time is the slope
(t_hi - t_lo) / (hi - lo) — this cancels the host↔device tunnel
round-trip (~100 ms/call) that poisons naive small-op timings.
Not part of the test suite."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def wall(f, args, reps=3):
    float(np.asarray(f(*args)).ravel()[0])   # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(np.asarray(f(*args)).ravel()[0])
        ts.append(time.perf_counter() - t0)
    return min(ts)


def slope(step, args, lo, hi):
    """Per-iteration seconds of step(x, aux) via two chain lengths."""
    def chain(iters):
        def fn(x, aux):
            def body(i, v):
                return step(v, aux)
            return lax.fori_loop(0, iters, body, x).ravel()[0]
        return jax.jit(fn)
    t_lo = wall(chain(lo), args)
    t_hi = wall(chain(hi), args)
    return (t_hi - t_lo) / (hi - lo)


def report(name, secs, flops=None):
    msg = f"{name}: {secs*1e6:.0f} us"
    if flops:
        msg += f"  {flops/secs/1e12:.2f} TF/s"
    print(msg, flush=True)


def main():
    rng = np.random.default_rng(0)
    n, nb = 8192, 512

    g = rng.standard_normal((n, n)).astype(np.float32)
    spd = jnp.asarray(g @ g.T + n * np.eye(n, dtype=np.float32))
    spd_small = jnp.asarray((g[:nb, :nb] @ g[:nb, :nb].T
                             + nb * np.eye(nb)).astype(np.float32))

    # call overhead: trivial op
    t = wall(jax.jit(lambda x: (x + 1.0).ravel()[0]),
             (jnp.float32([0.0]),))
    print(f"tunnel round-trip (trivial call): {t*1e3:.1f} ms", flush=True)

    a = jnp.asarray(rng.standard_normal((n, n)).astype(np.float32))
    t = slope(lambda x, aux: (x @ aux) * 1e-4, (a, a), 2, 6)
    report(f"gemm {n}", t, 2 * n**3)

    t = slope(lambda x, aux: jnp.tril(lax.linalg.cholesky(x)) + aux * 1e-30,
              (spd_small, spd_small), 8, 40)
    report(f"xla chol {nb}", t, nb**3 / 3)

    t = slope(lambda x, aux: jnp.tril(lax.linalg.cholesky(x)) + aux * 1e-30,
              (spd, spd), 2, 5)
    report(f"xla chol {n}", t, n**3 / 3)

    from slate_tpu.ops.pallas_kernels import chol_inv_panel

    def pstep(x, aux):
        l, li = chol_inv_panel(x)
        return l + li * 1e-30 + aux * 1e-30
    try:
        t = slope(pstep, (spd_small, spd_small), 8, 40)
        report(f"pallas chol_inv {nb}", t)
    except Exception as e:
        print("pallas chol_inv failed:", repr(e)[:200], flush=True)

    lsm = jnp.asarray(np.linalg.cholesky(np.asarray(spd_small)))
    pan = jnp.asarray(rng.standard_normal((n - nb, nb)).astype(np.float32))

    t = slope(lambda x, aux: lax.linalg.triangular_solve(
        aux, x, left_side=False, lower=True, transpose_a=True)
        * jnp.float32(1.0 + 1e-30), (pan, lsm), 8, 24)
    report(f"xla trsm panel ({n-nb}x{nb})", t, (n - nb) * nb**2)

    t = slope(lambda x, aux: (x @ aux) * jnp.float32(1.0 + 1e-30),
              (pan, lsm), 8, 24)
    report(f"panel gemm ({n-nb}x{nb})@({nb}x{nb})", t, 2 * (n - nb) * nb**2)

    # rank-nb trailing update shape: (n,nb)@(nb,n)
    pb = jnp.asarray(rng.standard_normal((nb, n)).astype(np.float32))

    def tr_step(x, aux):
        return x + 1e-6 * (x[:, :nb] @ aux)
    t = slope(tr_step, (a, pb), 2, 6)
    report(f"trailing gemm ({n}x{nb})@({nb}x{n})", t, 2 * n * n * nb)

    from slate_tpu.linalg.lu import getrf_rec
    am = jnp.asarray((rng.standard_normal((n, n))
                      + n * np.eye(n)).astype(np.float32))

    def lstep(x, aux):
        lu, piv = getrf_rec(x, nb)
        return lu * 1e-30 + aux
    t = slope(lstep, (am, am), 2, 4)
    report(f"getrf_rec {n} nb={nb}", t, 2 * n**3 / 3)

    pan2 = jnp.asarray(rng.standard_normal((n, nb)).astype(np.float32))

    def lupan(x, aux):
        lu, _, perm = lax.linalg.lu(x)
        return lu * 1e-30 + aux
    t = slope(lupan, (pan2, pan2), 2, 6)
    report(f"xla lu panel ({n}x{nb})", t, n * nb**2)

    def qrpan(x, aux):
        h, tau = jnp.linalg.qr(x, mode="raw")
        return jnp.swapaxes(h, -1, -2) * 1e-30 + aux
    t = slope(qrpan, (pan2, pan2), 2, 6)
    report(f"xla qr panel ({n}x{nb})", t, 2 * n * nb**2)

    m2, n2 = 32768, 4096
    tall = jnp.asarray(rng.standard_normal((m2, n2)).astype(np.float32))
    t = slope(qrpan, (tall, tall), 1, 3)
    report(f"xla qr {m2}x{n2}", t, 2 * m2 * n2**2 - 2 * n2**3 / 3)


if __name__ == "__main__":
    main()
