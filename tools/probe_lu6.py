"""Transposed-throughout scattered LU driver: no per-block transposes.
Variant T1: panels only; T3: full driver."""
import time
import numpy as np
import jax
import jax.numpy as jnp
import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from slate_tpu.ops.pallas_kernels import getrf_block_panel, trtri_panel
from slate_tpu.ops.blocks import matmul, matmul_hi


def getrf_scattered_t(a, nb=512, bb=128, level=3):
    m, n = a.shape
    k = min(m, n)
    at = a.T                      # ONE transpose in
    act = jnp.ones((1, m), jnp.float32)
    pivs = []
    for k0 in range(0, k, nb):
        panel_pivs = []
        for b0 in range(0, nb, bb):
            r0 = k0 + b0
            blk_t, piv_b, act = getrf_block_panel(at[r0:r0 + bb, :], act)
            at = at.at[r0:r0 + bb, :].set(blk_t)
            panel_pivs.append(piv_b)
            if level >= 2 and b0 + bb < nb:
                l11t = blk_t[:, piv_b]              # (bb, bb) = L11^T
                l11 = jnp.tril(l11t.T, -1) + jnp.eye(bb, jnp.float32)
                linv = trtri_panel(l11)
                c1t = at[r0 + bb:k0 + nb, :][:, piv_b]   # (rest, bb)
                u12t = matmul_hi(c1t, linv.T)
                u12t = u12t + matmul_hi(c1t - matmul_hi(u12t, l11.T),
                                        linv.T)
                lmt = blk_t * act                    # (bb, m)
                upd = matmul(u12t, lmt)              # (rest, m)
                at = at.at[r0 + bb:k0 + nb, :].add(-upd)
                at = at.at[r0 + bb:k0 + nb, piv_b].set(u12t)
        piv = jnp.concatenate(panel_pivs)
        pivs.append(piv)
        if level >= 3 and k0 + nb < n:
            slab_t = at[k0:k0 + nb, :]               # (nb, m)
            l11t = slab_t[:, piv]
            l11 = jnp.tril(l11t.T, -1) + jnp.eye(nb, jnp.float32)
            linv = trtri_panel(l11)
            c1t = at[k0 + nb:, :][:, piv]            # (rest, nb)
            u12t = matmul_hi(c1t, linv.T)
            u12t = u12t + matmul_hi(c1t - matmul_hi(u12t, l11.T), linv.T)
            lmt = slab_t * act
            at = at.at[k0 + nb:, :].add(-matmul(u12t, lmt))
            at = at.at[k0 + nb:, piv].set(u12t)
    piv_all = jnp.concatenate(pivs)
    if m > k:
        rem = jnp.argsort(act[0, :] < 0.5, stable=True)[: m - k]
        perm = jnp.concatenate([piv_all, rem])
    else:
        perm = piv_all
    return at[:, perm].T, perm    # ONE transpose out


def qtime(f, am, N=6):
    lu, piv = f(am)
    float(lu[-1, -1])
    t0 = time.perf_counter()
    x = am
    for _ in range(N):
        lu, piv = f(x)
        x = x + lu * jnp.float32(1e-30)
    float(x[-1, -1])
    return (time.perf_counter() - t0) / N


n = 8192
rng = np.random.default_rng(0)
a_np = rng.standard_normal((n, n)).astype(np.float32) + n * np.eye(
    n, dtype=np.float32)
am = jnp.asarray(a_np)
for lv in (1, 3):
    f = jax.jit(lambda x, lv=lv: getrf_scattered_t(x, level=lv))
    t = qtime(f, am)
    print(f"T variant {lv}: {t*1e3:.1f} ms "
          f"({2*n**3/3/t/1e12:.2f} TF/s if full)", flush=True)

# correctness of the full driver
f = jax.jit(lambda x: getrf_scattered_t(x, level=3))
lu, perm = f(am)
lu_np, perm_np = np.asarray(lu), np.asarray(perm)
lmat = np.tril(lu_np, -1) + np.eye(n, dtype=np.float32)
x = rng.standard_normal(n).astype(np.float32)
eps = np.finfo(np.float32).eps
res = np.linalg.norm(lmat @ (np.triu(lu_np) @ x) - a_np[perm_np] @ x) / (
    np.linalg.norm(a_np) * np.linalg.norm(x) * eps * n)
print("scaled residual:", res, flush=True)
