"""r5 component probe, v3: ~16 ms fixed dispatch latency per jit call
through the axon relay — amortize with an in-jit fori_loop chain of K
dependent applications; report (t_total - t_overhead)/K."""
import time, sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax, jax.numpy as jnp
from jax import lax

def P(*a): print(*a, flush=True)

def chain(fbody, x0, *extra, K=16, N=4):
    @jax.jit
    def g(x, *e):
        def body(i, xx):
            return fbody(xx, *e) * jnp.float32(0.9999)
        return lax.fori_loop(0, K, body, x)
    t0 = time.perf_counter()
    x = g(x0, *extra); float(jnp.asarray(x).ravel()[-1])
    tc = time.perf_counter() - t0
    ts = []
    for _ in range(N):
        t0 = time.perf_counter()
        x = g(x0, *extra); float(jnp.asarray(x).ravel()[-1])
        ts.append(time.perf_counter() - t0)
    return (min(ts) - 0.016) / K, tc

n = 8192
key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (n, n), jnp.float32)
b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)

t, tc = chain(lambda x, y: x @ y * jnp.float32(1e-4), a, b)
P("gemm n=8192               %7.2f ms  %6.1f TF/s (c %.0fs)" % (t*1e3, 2*n**3/t/1e12, tc))

for m in (8192, 2048):
    pan0 = a[:m, :512] + 0
    def panf(x):
        lu, _, _ = lax.linalg.lu(x)
        return lu
    t, tc = chain(panf, pan0)
    P("lax.linalg.lu (%5d,512)   %7.2f ms (c %.0fs)" % (m, t*1e3, tc))

def updf(x):
    return x.at[:, 512:].add(-(x[:, :512] @ x[:512, 512:]) * jnp.float32(1e-6))
t, tc = chain(updf, a)
P("trailing k=512 8192x7680   %7.2f ms  %5.1f TF/s (c %.0fs)" % (t*1e3, 2*8192*512*7680/t/1e12, tc))

def bigk2(x):
    upd = x[:, :4096] @ x[:4096, :512]
    return x.at[:, :512].add(upd * jnp.float32(1e-8))
t, tc = chain(bigk2, a)
P("panel upd k=4096 8192x512  %7.2f ms  %5.1f TF/s (c %.0fs)" % (t*1e3, 2*8192*4096*512/t/1e12, tc))

perm0 = jax.random.permutation(jax.random.PRNGKey(2), n)
t, tc = chain(lambda x, p: x[p], a, perm0)
P("full row gather 8192x8192  %7.2f ms (c %.0fs)" % (t*1e3, tc))

def trsmf(x):
    y = lax.linalg.triangular_solve(x[:512, :512], x[:512, 512:],
        left_side=True, lower=True, unit_diagonal=True)
    return x.at[:512, 512:].add(y * jnp.float32(1e-30))
t, tc = chain(trsmf, a)
P("trsm 512x(512,7680)        %7.2f ms (c %.0fs)" % (t*1e3, tc))
P("---")
