"""Final timing: in-place transposed getrf_scattered vs getrf_rec."""
import time
import numpy as np
import jax
import jax.numpy as jnp
import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from slate_tpu.linalg.lu import getrf_scattered, getrf_rec


def qtime(f, am, N=8):
    lu, piv = f(am)
    float(lu[-1, -1])
    t0 = time.perf_counter()
    x = am
    for _ in range(N):
        lu, piv = f(x)
        x = x + lu * jnp.float32(1e-30)
    float(x[-1, -1])
    return (time.perf_counter() - t0) / N


n = 8192
rng = np.random.default_rng(0)
a_np = rng.standard_normal((n, n)).astype(np.float32) + n * np.eye(
    n, dtype=np.float32)
am = jnp.asarray(a_np)
f = jax.jit(lambda x: getrf_scattered(x, 512))
t = qtime(f, am)
print(f"getrf_scattered n={n}: {t*1e3:.1f} ms  "
      f"{2*n**3/3/t/1e12:.2f} TF/s", flush=True)
lu, perm = f(am)
lu_np, perm_np = np.asarray(lu), np.asarray(perm)
lmat = np.tril(lu_np, -1) + np.eye(n, dtype=np.float32)
x = rng.standard_normal(n).astype(np.float32)
eps = np.finfo(np.float32).eps
res = np.linalg.norm(lmat @ (np.triu(lu_np) @ x) - a_np[perm_np] @ x) / (
    np.linalg.norm(a_np) * np.linalg.norm(x) * eps * n)
print("scaled residual:", res, flush=True)
g = jax.jit(lambda x: getrf_rec(x, 512))
t = qtime(g, am)
print(f"getrf_rec       n={n}: {t*1e3:.1f} ms  "
      f"{2*n**3/3/t/1e12:.2f} TF/s", flush=True)
