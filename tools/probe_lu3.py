"""Queued-dispatch timing: getrf_scattered vs getrf_rec at n=8192."""
import time, numpy as np, jax, jax.numpy as jnp
import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from slate_tpu.linalg.lu import getrf_scattered, getrf_rec

rng = np.random.default_rng(0)
n = 8192
a_np = rng.standard_normal((n, n)).astype(np.float32) + n*np.eye(n, dtype=np.float32)
am = jnp.asarray(a_np)

for name, fn in (("getrf_scattered", lambda x: getrf_scattered(x, 512)),
                 ("getrf_rec      ", lambda x: getrf_rec(x, 512))):
    f = jax.jit(fn)
    lu, perm = f(am)
    float(lu[-1, -1])
    N = 8
    t0 = time.perf_counter()
    x = am
    for _ in range(N):
        lu, perm = f(x)
        x = x + lu * jnp.float32(1e-30)
    float(x[-1, -1])
    t = (time.perf_counter() - t0) / N
    print(f"{name} n={n}: {t*1e3:.2f} ms  {2*n**3/3/t/1e12:.2f} TF/s",
          flush=True)
