#!/usr/bin/env python3
"""Bench regression sentinel CLI — diff BENCH_r*.json artifacts.

Usage::

    python tools/bench_diff.py BENCH_r03.json BENCH_r04.json
    python tools/bench_diff.py BENCH_r0*.json --threshold 10
    python tools/bench_diff.py BENCH_r05.json            # infra check only

Aligns routines across the artifacts (by routine name, dtype and dims
parsed from the submetric labels), prints a verdict table — including a
``frac`` column with each routine's newest ``frac_of_gemm`` derived
submetric (bench.py r6+: routine TF/s ÷ same-run gemm TF/s, the unit
the ROADMAP fraction targets are written in), a ``frac_split`` column
with the newest ``frac_of_split_gemm`` (ISSUE 16: fp32 routine TF/s ÷
same-run bf16x3 split-gemm TF/s — the fraction of the emulated-fp32
peak; the ``gemm_fp32_split_speedup_over_floor`` sentinel row rides
the generic ``*_over_floor`` floor pin) and the batched serving
throughput rows (``*_solves_per_s``, r8: higher is better, judged with
the rate direction — the sentinel pins serving throughput like any
other metric).  The QDWH spectral tier's ``heev_qdwh_*``/``svd_qdwh_*``
labels (ISSUE 18; forced-dispatch gemm-rich drivers, with
``_qr_s``/``_chol_s``/``_gemm_s`` stage timers) align as their own
routines, distinct from the autotuned plain ``heev_*``/``svd_*`` rows.
Exits nonzero when
any routine regressed more than the threshold between consecutive
artifacts OR when any artifact is infra-shaped (``rc != 0``,
missing/empty/partial aggregate) — the checks that would have flagged
the r3→r4 geqrf drop (23.5 → 18.9 TF/s) and the empty BENCH_r05
(rc=124, parsed null) automatically.

``MULTICHIP_r*.json`` dry-run wrappers load too (ISSUE 13): an artifact
whose tail carries the ``MULTICHIP_CURVE`` weak-scaling line is judged
as per-device-efficiency rows (``multichip_d<nd>_perdev_eff``, higher
is better) plus the ``multichip_min_eff_over_floor`` sentinel row — a
value below 1.0 (a point under the curve's pinned efficiency floor)
fails even with a single artifact, so a collapsing scaling curve fails
CI like any bench regression::

    python tools/bench_diff.py MULTICHIP_r06.json MULTICHIP_r07.json

Stdlib-only: the implementation (``slate_tpu/perf/regress.py``) is
loaded directly by file path so this tool never imports jax and runs in
milliseconds on any machine.
"""

import argparse
import importlib.util
import os
import sys


def _load_regress():
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.normpath(os.path.join(
        here, os.pardir, "slate_tpu", "perf", "regress.py"))
    spec = importlib.util.spec_from_file_location("_slate_tpu_regress", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod     # dataclasses resolve __module__ here
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    regress = _load_regress()
    ap = argparse.ArgumentParser(
        prog="bench_diff.py",
        description="Diff bench artifacts; exit nonzero on regressions "
                    "or infra-shaped artifacts.")
    ap.add_argument("artifacts", nargs="+",
                    help="BENCH_r*.json files (driver wrapper, bare "
                         "aggregate, or raw bench stdout), oldest first")
    ap.add_argument("--threshold", type=float,
                    default=regress.DEFAULT_THRESHOLD_PCT,
                    help="flag drops bigger than this percent between "
                         "consecutive artifacts (default %(default)s)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of a table")
    ap.add_argument("--explain", action="store_true",
                    help="diff the roofline attribution of each "
                         "regressed routine and name the stage whose "
                         "share of the wall time moved (derived "
                         "analytically for artifacts that predate "
                         "embedded attribution blocks)")
    args = ap.parse_args(argv)

    arts = [regress.load_artifact(p) for p in args.artifacts]
    report = regress.diff(arts, threshold_pct=args.threshold)
    explain = regress.explain(report) if args.explain else None
    if args.json:
        import json

        blob = {
            "threshold_pct": report.threshold_pct,
            "rows": [{"label": r.label, "values": r.values,
                      "delta_pct": r.delta_pct, "verdict": r.verdict,
                      "note": r.note,
                      "direction": ("higher_is_better"
                                    if regress.direction(r.label) > 0
                                    else "lower_is_better"),
                      "frac_of_gemm": regress.frac_of_gemm(report,
                                                           r.label),
                      "frac_of_split_gemm": regress.frac_of_split_gemm(
                          report, r.label)}
                     for r in report.rows],
            "infra": [{"artifact": n, "reasons": rs}
                      for n, rs in report.infra],
            "exit_code": report.exit_code,
        }
        if explain is not None:
            blob["explain"] = explain
        print(json.dumps(blob, indent=1))
    else:
        print(regress.format_table(report))
        if explain is not None:
            print()
            if explain:
                for line in explain:
                    print("EXPLAIN " + line)
            else:
                print("EXPLAIN nothing regressed — no attribution "
                      "diff to report")
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
