#!/usr/bin/env python3
"""Generate the C driver API: typed s/d/c/z wrappers over the embedded
-CPython core call, plus the matching Fortran interface module.

The analog of the reference's generated C API
(``/root/reference/tools/c_api/generate_wrappers.py`` →
``include/slate/c_api/slate.h``, ``src/c_api/wrappers.cc``): one table
of drivers drives header, C bodies, and Fortran module generation.

Outputs (checked in; rerun on table changes):
  include/slate_tpu_driver.h   — typed driver declarations
  src/c_api/driver_api.c       — generated bodies over slate_c_call()
  fortran/slate_tpu.f90        — regenerated Fortran interfaces
"""

import os

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

CTYPES = {"s": "float", "d": "double",
          "c": "float _Complex", "z": "double _Complex"}
NPDT = {"s": "f32", "d": "f64", "c": "c64", "z": "c128"}

# (op, kinds, signature, outputs-doc)
# signature kinds:
#   ab_x    : in a(m,n), in b(n,nrhs) -> out0 x(n,nrhs)           [+info]
#   ab_xp   : like ab_x plus out1 ipiv(int64 n)
#   a_f     : in a(m,n) -> out0 factor(m,n)
#   a_fp    : a_f plus out1 ipiv(int64 min(m,n))
#   a_ft    : a_f plus out1 tau(double/complex min(m,n))
#   a_winv  : in a(n,n) -> out0 inverse(n,n)
#   a_eig   : in a(n,n) -> out0 w(double n), out1 z(n,n)
#   a_eigv  : in a(n,n) -> out0 w(double n)
#   a_svd   : in a(m,n) -> out0 s(double k), out1 u(m,k), out2 vt(k,n)
#   a_svdv  : in a(m,n) -> out0 s(double k)
#   ab_c    : in a, in b -> out0 c (gemm-like)
#   a_scal  : in a -> out0 scalar double
DRIVERS = [
    ("gesv", "sdcz", "ab_xp", "x = A^{-1} B, row pivots"),
    ("posv", "sdcz", "ab_x", "x = A^{-1} B, A HPD (uplo)"),
    ("hesv", "sdcz", "ab_x", "x = A^{-1} B, A Hermitian indefinite"),
    ("sysv", "sd", "ab_x", "x = A^{-1} B, A symmetric indefinite"),
    ("gels", "sdcz", "ab_x", "least-squares solution (m >= n)"),
    ("getrf", "sdcz", "a_fp", "packed LU + row permutation"),
    ("potrf", "sdcz", "a_f", "Cholesky factor in the stored triangle"),
    ("geqrf", "sdcz", "a_ft", "packed QR + taus"),
    ("gelqf", "sdcz", "a_ft", "packed LQ + taus"),
    ("getri", "sdcz", "a_winv", "inverse from LU"),
    ("potri", "sdcz", "a_winv", "inverse from Cholesky (uplo)"),
    ("trtri", "sdcz", "a_winv", "triangular inverse (uplo)"),
    ("heev", "sdcz", "a_eig", "eigenvalues + vectors (uplo)"),
    ("syev", "sd", "a_eig", "eigenvalues + vectors (uplo)"),
    ("heev_vals", "sdcz", "a_eigv", "eigenvalues only"),
    ("svd", "sdcz", "a_svd", "singular values + U + V^H"),
    ("svd_vals", "sdcz", "a_svdv", "singular values only"),
    ("gemm", "sdcz", "ab_c", "C = A B"),
    ("symm", "sd", "ab_c", "C = A B, A symmetric (uplo)"),
    ("hemm", "cz", "ab_c", "C = A B, A Hermitian (uplo)"),
    ("syrk", "sd", "a_f", "C = A A^T (uplo stored)"),
    ("herk", "cz", "a_f", "C = A A^H (uplo stored)"),
    ("trsm", "sdcz", "ab_c", "X = A^{-1} B, A triangular (uplo)"),
    ("trmm", "sdcz", "ab_c", "X = A B, A triangular (uplo)"),
    ("lange", "sdcz", "a_scal", "norm (norm char in `uplo` slot: M/1/I/F)"),
    ("gecondest", "sd", "a_scal", "1-norm condition estimate"),
]

SIGS = {
    "ab_x": ("int64_t m, int64_t n, const {T}* a, int64_t lda, "
             "int64_t nrhs, const {T}* b, int64_t ldb, {T}* x, "
             "char uplo",
             "m, n, a, lda, m, nrhs, b, ldb, x, NULL, NULL, uplo"),
    "ab_xp": ("int64_t m, int64_t n, const {T}* a, int64_t lda, "
              "int64_t nrhs, const {T}* b, int64_t ldb, {T}* x, "
              "int64_t* ipiv",
              "m, n, a, lda, m, nrhs, b, ldb, x, ipiv, NULL, 'L'"),
    "a_f": ("int64_t m, int64_t n, const {T}* a, int64_t lda, {T}* f, "
            "char uplo",
            "m, n, a, lda, 0, 0, NULL, 0, f, NULL, NULL, uplo"),
    "a_fp": ("int64_t m, int64_t n, const {T}* a, int64_t lda, {T}* f, "
             "int64_t* ipiv",
             "m, n, a, lda, 0, 0, NULL, 0, f, ipiv, NULL, 'L'"),
    "a_ft": ("int64_t m, int64_t n, const {T}* a, int64_t lda, {T}* f, "
             "{T}* tau",
             "m, n, a, lda, 0, 0, NULL, 0, f, tau, NULL, 'L'"),
    "a_winv": ("int64_t n, const {T}* a, int64_t lda, {T}* inv, char uplo",
               "n, n, a, lda, 0, 0, NULL, 0, inv, NULL, NULL, uplo"),
    "a_eig": ("int64_t n, const {T}* a, int64_t lda, double* w, {T}* z, "
              "char uplo",
              "n, n, a, lda, 0, 0, NULL, 0, w, z, NULL, uplo"),
    "a_eigv": ("int64_t n, const {T}* a, int64_t lda, double* w, char uplo",
               "n, n, a, lda, 0, 0, NULL, 0, w, NULL, NULL, uplo"),
    "a_svd": ("int64_t m, int64_t n, const {T}* a, int64_t lda, double* s, "
              "{T}* u, {T}* vt",
              "m, n, a, lda, 0, 0, NULL, 0, s, u, vt, 'L'"),
    "a_svdv": ("int64_t m, int64_t n, const {T}* a, int64_t lda, double* s",
               "m, n, a, lda, 0, 0, NULL, 0, s, NULL, NULL, 'L'"),
    "ab_c": ("int64_t m, int64_t k, const {T}* a, int64_t lda, int64_t n, "
             "const {T}* b, int64_t ldb, {T}* c, char uplo",
             "m, k, a, lda, k, n, b, ldb, c, NULL, NULL, uplo"),
    "a_scal": ("int64_t m, int64_t n, const {T}* a, int64_t lda, "
               "double* value, char norm",
               "m, n, a, lda, 0, 0, NULL, 0, value, NULL, NULL, norm"),
}


def gen_header():
    lines = [
        "/* slate_tpu driver C API — GENERATED by tools/generate_c_api.py;",
        " * do not edit.  The analog of the reference's generated",
        " * include/slate/c_api/slate.h: every driver callable from C,",
        " * s/d/c/z.  Matrices are COLUMN-major with leading dimension ld*;",
        " * outputs are caller-allocated.  Returns 0 on success.",
        " * Implementation: src/c_api/driver_api.c embeds CPython and runs",
        " * the full JAX/XLA driver (the TPU does the math).  Call",
        " * slate_c_init() once first; slate_c_finalize() at exit. */",
        "",
        "#ifndef SLATE_TPU_DRIVER_H",
        "#define SLATE_TPU_DRIVER_H",
        "",
        "#include <stdint.h>",
        "",
        "#ifdef __cplusplus",
        'extern "C" {',
        "#endif",
        "",
        "int slate_c_init(void);",
        "void slate_c_finalize(void);",
        "",
        "/* generic core: every typed wrapper funnels through this */",
        "int slate_c_call(const char* op, char dtype, int64_t m, int64_t n,",
        "                 const void* a, int64_t lda, int64_t m2, int64_t n2,",
        "                 const void* b, int64_t ldb, void* out0, void* out1,",
        "                 void* out2, char uplo);",
        "",
    ]
    for op, kinds, sig, doc in DRIVERS:
        lines.append(f"/* {op}: {doc} */")
        for kch in kinds:
            T = CTYPES[kch]
            decl = SIGS[sig][0].format(T=T)
            lines.append(f"int slate_{kch}{op}({decl});")
        lines.append("")
    lines += ["#ifdef __cplusplus", "}", "#endif", "",
              "#endif /* SLATE_TPU_DRIVER_H */", ""]
    return "\n".join(lines)


def gen_c_bodies():
    lines = [
        "/* GENERATED by tools/generate_c_api.py — do not edit.",
        " * Typed driver wrappers over slate_c_call() (core in",
        " * c_api_core.c).  Reference analog: src/c_api/wrappers.cc. */",
        "",
        '#include "slate_tpu_driver.h"',
        "#include <stddef.h>",
        "",
    ]
    for op, kinds, sig, _doc in DRIVERS:
        for kch in kinds:
            T = CTYPES[kch]
            decl = SIGS[sig][0].format(T=T)
            args = SIGS[sig][1]
            lines += [
                f"int slate_{kch}{op}({decl}) {{",
                f'    return slate_c_call("{op}", \'{kch}\', {args});',
                "}",
                "",
            ]
    return "\n".join(lines)


def gen_fortran():
    FT = {"s": "real(c_float)", "d": "real(c_double)",
          "c": "complex(c_float_complex)", "z": "complex(c_double_complex)"}
    lines = [
        "! slate_tpu Fortran module — GENERATED by tools/generate_c_api.py",
        "! (the analog of the reference's tools/fortran/",
        "! generate_fortran_module.py output).  Bindings over the C driver",
        "! API; matrices column-major, as Fortran wants them anyway.",
        "module slate_tpu",
        "    use iso_c_binding",
        "    implicit none",
        "",
        "    interface",
        "        function slate_c_init() bind(c, name='slate_c_init')",
        "            use iso_c_binding",
        "            integer(c_int) :: slate_c_init",
        "        end function",
        "        subroutine slate_c_finalize() "
        "bind(c, name='slate_c_finalize')",
        "        end subroutine",
    ]

    fsig = {
        "ab_x": ("m, n, a, lda, nrhs, b, ldb, x, uplo",
                 ["integer(c_int64_t), value :: m, n, lda, nrhs, ldb",
                  "{FT} :: a(lda,*), b(ldb,*), x(n,*)",
                  "character(kind=c_char), value :: uplo"]),
        "ab_xp": ("m, n, a, lda, nrhs, b, ldb, x, ipiv",
                  ["integer(c_int64_t), value :: m, n, lda, nrhs, ldb",
                   "{FT} :: a(lda,*), b(ldb,*), x(n,*)",
                   "integer(c_int64_t) :: ipiv(*)"]),
        "a_f": ("m, n, a, lda, f, uplo",
                ["integer(c_int64_t), value :: m, n, lda",
                 "{FT} :: a(lda,*), f(m,*)",
                 "character(kind=c_char), value :: uplo"]),
        "a_fp": ("m, n, a, lda, f, ipiv",
                 ["integer(c_int64_t), value :: m, n, lda",
                  "{FT} :: a(lda,*), f(m,*)",
                  "integer(c_int64_t) :: ipiv(*)"]),
        "a_ft": ("m, n, a, lda, f, tau",
                 ["integer(c_int64_t), value :: m, n, lda",
                  "{FT} :: a(lda,*), f(m,*), tau(*)"]),
        "a_winv": ("n, a, lda, inv, uplo",
                   ["integer(c_int64_t), value :: n, lda",
                    "{FT} :: a(lda,*), inv(n,*)",
                    "character(kind=c_char), value :: uplo"]),
        "a_eig": ("n, a, lda, w, z, uplo",
                  ["integer(c_int64_t), value :: n, lda",
                   "{FT} :: a(lda,*), z(n,*)",
                   "real(c_double) :: w(*)",
                   "character(kind=c_char), value :: uplo"]),
        "a_eigv": ("n, a, lda, w, uplo",
                   ["integer(c_int64_t), value :: n, lda",
                    "{FT} :: a(lda,*)",
                    "real(c_double) :: w(*)",
                    "character(kind=c_char), value :: uplo"]),
        "a_svd": ("m, n, a, lda, s, u, vt",
                  ["integer(c_int64_t), value :: m, n, lda",
                   "{FT} :: a(lda,*), u(m,*), vt(n,*)",
                   "real(c_double) :: s(*)"]),
        "a_svdv": ("m, n, a, lda, s",
                   ["integer(c_int64_t), value :: m, n, lda",
                    "{FT} :: a(lda,*)",
                    "real(c_double) :: s(*)"]),
        "ab_c": ("m, k, a, lda, n, b, ldb, c, uplo",
                 ["integer(c_int64_t), value :: m, k, lda, n, ldb",
                  "{FT} :: a(lda,*), b(ldb,*), c(m,*)",
                  "character(kind=c_char), value :: uplo"]),
        "a_scal": ("m, n, a, lda, value, norm",
                   ["integer(c_int64_t), value :: m, n, lda",
                    "{FT} :: a(lda,*)",
                    "real(c_double) :: value",
                    "character(kind=c_char), value :: norm"]),
    }

    for op, kinds, sig, _doc in DRIVERS:
        for kch in kinds:
            name = f"slate_{kch}{op}"
            argl, decls = fsig[sig]
            lines.append(f"        function {name}({argl}) &")
            lines.append(f"                bind(c, name='{name}')")
            lines.append("            use iso_c_binding")
            for d in decls:
                lines.append("            " + d.format(FT=FT[kch]))
            lines.append(f"            integer(c_int) :: {name}")
            lines.append("        end function")
    lines += ["    end interface", "end module slate_tpu", ""]
    return "\n".join(lines)


def main():
    with open(os.path.join(ROOT, "include", "slate_tpu_driver.h"), "w") as f:
        f.write(gen_header())
    os.makedirs(os.path.join(ROOT, "src", "c_api"), exist_ok=True)
    with open(os.path.join(ROOT, "src", "c_api", "driver_api.c"), "w") as f:
        f.write(gen_c_bodies())
    with open(os.path.join(ROOT, "fortran", "slate_tpu.f90"), "w") as f:
        f.write(gen_fortran())
    n = sum(len(k) for _, k, _, _ in DRIVERS)
    print(f"generated {len(DRIVERS)} drivers, {n} typed entry points")


if __name__ == "__main__":
    main()
