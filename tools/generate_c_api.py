#!/usr/bin/env python3
"""Generate the C driver API: typed s/d/c/z wrappers over the embedded
-CPython core call, plus the matching Fortran interface module.

The analog of the reference's generated C API
(``/root/reference/tools/c_api/generate_wrappers.py`` →
``include/slate/c_api/slate.h``, ``src/c_api/wrappers.cc``): one table
of drivers drives header, C bodies, and Fortran module generation.

Outputs (checked in; rerun on table changes):
  include/slate_tpu_driver.h   — typed driver declarations
  src/c_api/driver_api.c       — generated bodies over slate_c_call()
  fortran/slate_tpu.f90        — regenerated Fortran interfaces
"""

import os

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

CTYPES = {"s": "float", "d": "double",
          "c": "float _Complex", "z": "double _Complex"}
NPDT = {"s": "f32", "d": "f64", "c": "c64", "z": "c128"}

# (op, kinds, signature, outputs-doc)
# signature kinds:
#   ab_x    : in a(m,n), in b(n,nrhs) -> out0 x(n,nrhs)           [+info]
#   ab_xp   : like ab_x plus out1 ipiv(int64 n)
#   a_f     : in a(m,n) -> out0 factor(m,n)
#   a_fp    : a_f plus out1 ipiv(int64 min(m,n))
#   a_ft    : a_f plus out1 tau(double/complex min(m,n))
#   a_winv  : in a(n,n) -> out0 inverse(n,n)
#   a_eig   : in a(n,n) -> out0 w(double n), out1 z(n,n)
#   a_eigv  : in a(n,n) -> out0 w(double n)
#   a_svd   : in a(m,n) -> out0 s(double k), out1 u(m,k), out2 vt(k,n)
#   a_svdv  : in a(m,n) -> out0 s(double k)
#   ab_c    : in a, in b -> out0 c (gemm-like)
#   a_scal  : in a -> out0 scalar double
DRIVERS = [
    ("gesv", "sdcz", "ab_xp", "x = A^{-1} B, row pivots"),
    ("posv", "sdcz", "ab_x", "x = A^{-1} B, A HPD (uplo)"),
    ("hesv", "sdcz", "ab_x", "x = A^{-1} B, A Hermitian indefinite"),
    ("sysv", "sd", "ab_x", "x = A^{-1} B, A symmetric indefinite"),
    ("gels", "sdcz", "ab_x", "least-squares solution (m >= n)"),
    ("getrf", "sdcz", "a_fp", "packed LU + row permutation"),
    ("potrf", "sdcz", "a_f", "Cholesky factor in the stored triangle"),
    ("geqrf", "sdcz", "a_ft", "packed QR + taus"),
    ("gelqf", "sdcz", "a_ft", "packed LQ + taus"),
    ("getri", "sdcz", "a_winv", "inverse from LU"),
    ("potri", "sdcz", "a_winv", "inverse from Cholesky (uplo)"),
    ("trtri", "sdcz", "a_winv", "triangular inverse (uplo)"),
    ("heev", "sdcz", "a_eig", "eigenvalues + vectors (uplo)"),
    ("syev", "sd", "a_eig", "eigenvalues + vectors (uplo)"),
    ("heev_vals", "sdcz", "a_eigv", "eigenvalues only"),
    ("svd", "sdcz", "a_svd", "singular values + U + V^H"),
    ("svd_vals", "sdcz", "a_svdv", "singular values only"),
    ("gemm", "sdcz", "ab_c", "C = A B"),
    ("symm", "sd", "ab_c", "C = A B, A symmetric (uplo)"),
    ("hemm", "cz", "ab_c", "C = A B, A Hermitian (uplo)"),
    ("syrk", "sd", "a_f", "C = A A^T (uplo stored)"),
    ("herk", "cz", "a_f", "C = A A^H (uplo stored)"),
    ("trsm", "sdcz", "ab_c", "X = A^{-1} B, A triangular (uplo)"),
    ("trmm", "sdcz", "ab_c", "X = A B, A triangular (uplo)"),
    ("lange", "sdcz", "a_scal", "norm (norm char in `uplo` slot: M/1/I/F)"),
    ("gecondest", "sd", "a_scal", "1-norm condition estimate"),
]

SIGS = {
    "ab_x": ("int64_t m, int64_t n, const {T}* a, int64_t lda, "
             "int64_t nrhs, const {T}* b, int64_t ldb, {T}* x, "
             "char uplo",
             "m, n, a, lda, m, nrhs, b, ldb, x, NULL, NULL, uplo"),
    "ab_xp": ("int64_t m, int64_t n, const {T}* a, int64_t lda, "
              "int64_t nrhs, const {T}* b, int64_t ldb, {T}* x, "
              "int64_t* ipiv",
              "m, n, a, lda, m, nrhs, b, ldb, x, ipiv, NULL, 'L'"),
    "a_f": ("int64_t m, int64_t n, const {T}* a, int64_t lda, {T}* f, "
            "char uplo",
            "m, n, a, lda, 0, 0, NULL, 0, f, NULL, NULL, uplo"),
    "a_fp": ("int64_t m, int64_t n, const {T}* a, int64_t lda, {T}* f, "
             "int64_t* ipiv",
             "m, n, a, lda, 0, 0, NULL, 0, f, ipiv, NULL, 'L'"),
    "a_ft": ("int64_t m, int64_t n, const {T}* a, int64_t lda, {T}* f, "
             "{T}* tau",
             "m, n, a, lda, 0, 0, NULL, 0, f, tau, NULL, 'L'"),
    "a_winv": ("int64_t n, const {T}* a, int64_t lda, {T}* inv, char uplo",
               "n, n, a, lda, 0, 0, NULL, 0, inv, NULL, NULL, uplo"),
    "a_eig": ("int64_t n, const {T}* a, int64_t lda, double* w, {T}* z, "
              "char uplo",
              "n, n, a, lda, 0, 0, NULL, 0, w, z, NULL, uplo"),
    "a_eigv": ("int64_t n, const {T}* a, int64_t lda, double* w, char uplo",
               "n, n, a, lda, 0, 0, NULL, 0, w, NULL, NULL, uplo"),
    "a_svd": ("int64_t m, int64_t n, const {T}* a, int64_t lda, double* s, "
              "{T}* u, {T}* vt",
              "m, n, a, lda, 0, 0, NULL, 0, s, u, vt, 'L'"),
    "a_svdv": ("int64_t m, int64_t n, const {T}* a, int64_t lda, double* s",
               "m, n, a, lda, 0, 0, NULL, 0, s, NULL, NULL, 'L'"),
    "ab_c": ("int64_t m, int64_t k, const {T}* a, int64_t lda, int64_t n, "
             "const {T}* b, int64_t ldb, {T}* c, char uplo",
             "m, k, a, lda, k, n, b, ldb, c, NULL, NULL, uplo"),
    "a_scal": ("int64_t m, int64_t n, const {T}* a, int64_t lda, "
               "double* value, char norm",
               "m, n, a, lda, 0, 0, NULL, 0, value, NULL, NULL, norm"),
}


def gen_header():
    lines = [
        "/* slate_tpu driver C API — GENERATED by tools/generate_c_api.py;",
        " * do not edit.  The analog of the reference's generated",
        " * include/slate/c_api/slate.h: every driver callable from C,",
        " * s/d/c/z.  Matrices are COLUMN-major with leading dimension ld*;",
        " * outputs are caller-allocated.  Returns 0 on success.",
        " * Implementation: src/c_api/driver_api.c embeds CPython and runs",
        " * the full JAX/XLA driver (the TPU does the math).  Call",
        " * slate_c_init() once first; slate_c_finalize() at exit. */",
        "",
        "#ifndef SLATE_TPU_DRIVER_H",
        "#define SLATE_TPU_DRIVER_H",
        "",
        "#include <stdint.h>",
        "",
        "#ifdef __cplusplus",
        'extern "C" {',
        "#endif",
        "",
        "int slate_c_init(void);",
        "void slate_c_finalize(void);",
        "",
        "/* generic core: every typed wrapper funnels through this */",
        "int slate_c_call(const char* op, char dtype, int64_t m, int64_t n,",
        "                 const void* a, int64_t lda, int64_t m2, int64_t n2,",
        "                 const void* b, int64_t ldb, void* out0, void* out1,",
        "                 void* out2, char uplo);",
        "",
    ]
    for op, kinds, sig, doc in DRIVERS:
        lines.append(f"/* {op}: {doc} */")
        for kch in kinds:
            T = CTYPES[kch]
            decl = SIGS[sig][0].format(T=T)
            lines.append(f"int slate_{kch}{op}({decl});")
        lines.append("")
    lines += ["#ifdef __cplusplus", "}", "#endif", "",
              "#endif /* SLATE_TPU_DRIVER_H */", ""]
    return "\n".join(lines)


def gen_c_bodies():
    lines = [
        "/* GENERATED by tools/generate_c_api.py — do not edit.",
        " * Typed driver wrappers over slate_c_call() (core in",
        " * c_api_core.c).  Reference analog: src/c_api/wrappers.cc. */",
        "",
        '#include "slate_tpu_driver.h"',
        "#include <stddef.h>",
        "",
    ]
    for op, kinds, sig, _doc in DRIVERS:
        for kch in kinds:
            T = CTYPES[kch]
            decl = SIGS[sig][0].format(T=T)
            args = SIGS[sig][1]
            lines += [
                f"int slate_{kch}{op}({decl}) {{",
                f'    return slate_c_call("{op}", \'{kch}\', {args});',
                "}",
                "",
            ]
    return "\n".join(lines)


def gen_fortran():
    FT = {"s": "real(c_float)", "d": "real(c_double)",
          "c": "complex(c_float_complex)", "z": "complex(c_double_complex)"}
    lines = [
        "! slate_tpu Fortran module — GENERATED by tools/generate_c_api.py",
        "! (the analog of the reference's tools/fortran/",
        "! generate_fortran_module.py output).  Bindings over the C driver",
        "! API; matrices column-major, as Fortran wants them anyway.",
        "module slate_tpu",
        "    use iso_c_binding",
        "    implicit none",
        "",
        "    interface",
        "        function slate_c_init() bind(c, name='slate_c_init')",
        "            use iso_c_binding",
        "            integer(c_int) :: slate_c_init",
        "        end function",
        "        subroutine slate_c_finalize() "
        "bind(c, name='slate_c_finalize')",
        "        end subroutine",
    ]

    fsig = {
        "ab_x": ("m, n, a, lda, nrhs, b, ldb, x, uplo",
                 ["integer(c_int64_t), value :: m, n, lda, nrhs, ldb",
                  "{FT} :: a(lda,*), b(ldb,*), x(n,*)",
                  "character(kind=c_char), value :: uplo"]),
        "ab_xp": ("m, n, a, lda, nrhs, b, ldb, x, ipiv",
                  ["integer(c_int64_t), value :: m, n, lda, nrhs, ldb",
                   "{FT} :: a(lda,*), b(ldb,*), x(n,*)",
                   "integer(c_int64_t) :: ipiv(*)"]),
        "a_f": ("m, n, a, lda, f, uplo",
                ["integer(c_int64_t), value :: m, n, lda",
                 "{FT} :: a(lda,*), f(m,*)",
                 "character(kind=c_char), value :: uplo"]),
        "a_fp": ("m, n, a, lda, f, ipiv",
                 ["integer(c_int64_t), value :: m, n, lda",
                  "{FT} :: a(lda,*), f(m,*)",
                  "integer(c_int64_t) :: ipiv(*)"]),
        "a_ft": ("m, n, a, lda, f, tau",
                 ["integer(c_int64_t), value :: m, n, lda",
                  "{FT} :: a(lda,*), f(m,*), tau(*)"]),
        "a_winv": ("n, a, lda, inv, uplo",
                   ["integer(c_int64_t), value :: n, lda",
                    "{FT} :: a(lda,*), inv(n,*)",
                    "character(kind=c_char), value :: uplo"]),
        "a_eig": ("n, a, lda, w, z, uplo",
                  ["integer(c_int64_t), value :: n, lda",
                   "{FT} :: a(lda,*), z(n,*)",
                   "real(c_double) :: w(*)",
                   "character(kind=c_char), value :: uplo"]),
        "a_eigv": ("n, a, lda, w, uplo",
                   ["integer(c_int64_t), value :: n, lda",
                    "{FT} :: a(lda,*)",
                    "real(c_double) :: w(*)",
                    "character(kind=c_char), value :: uplo"]),
        "a_svd": ("m, n, a, lda, s, u, vt",
                  ["integer(c_int64_t), value :: m, n, lda",
                   "{FT} :: a(lda,*), u(m,*), vt(n,*)",
                   "real(c_double) :: s(*)"]),
        "a_svdv": ("m, n, a, lda, s",
                   ["integer(c_int64_t), value :: m, n, lda",
                    "{FT} :: a(lda,*)",
                    "real(c_double) :: s(*)"]),
        "ab_c": ("m, k, a, lda, n, b, ldb, c, uplo",
                 ["integer(c_int64_t), value :: m, k, lda, n, ldb",
                  "{FT} :: a(lda,*), b(ldb,*), c(m,*)",
                  "character(kind=c_char), value :: uplo"]),
        "a_scal": ("m, n, a, lda, value, norm",
                   ["integer(c_int64_t), value :: m, n, lda",
                    "{FT} :: a(lda,*)",
                    "real(c_double) :: value",
                    "character(kind=c_char), value :: norm"]),
    }

    for op, kinds, sig, _doc in DRIVERS:
        for kch in kinds:
            name = f"slate_{kch}{op}"
            argl, decls = fsig[sig]
            lines.append(f"        function {name}({argl}) &")
            lines.append(f"                bind(c, name='{name}')")
            lines.append("            use iso_c_binding")
            for d in decls:
                lines.append("            " + d.format(FT=FT[kch]))
            lines.append(f"            integer(c_int) :: {name}")
            lines.append("        end function")
    lines += ["    end interface", "end module slate_tpu", ""]
    return "\n".join(lines)




# ---------------------------------------------------------------------------
# Drop-in ScaLAPACK API (reference scalapack_api/: 15 routine families with
# BLACS descriptors, 3 Fortran manglings each, submatrix ia/ja windows).
# ---------------------------------------------------------------------------

SCALAPACK_CORE = r"""/* slate_tpu ScaLAPACK compatibility API — GENERATED by
 * tools/generate_c_api.py; do not edit.
 *
 * Drop-in desc-based symbols over the embedded-CPython driver core,
 * mirroring the reference's scalapack_api/ (scalapack_potrf.cc:27-80,
 * scalapack_getrf.cc, ... — 15 families here: potrf potrs posv getrf
 * getrs gesv getri potri geqrf gels syev/heev gemm trsm trmm lange).
 *
 * SINGLE-CONTROLLER BLACS EMULATION.  The reference runs one MPI rank
 * per grid cell; a JAX/TPU program is a single controller that owns
 * every device.  These stubs therefore implement the BLACS surface for
 * ONE process that plays all p*q ranks in sequence:
 *
 *   - Cblacs_gridinit(&ctxt, order, p, q) creates a virtual p x q grid
 *     (row- OR column-major rank order, honoured everywhere).
 *   - Cblacs_gridinfo(ctxt, ...) reports the coordinates of the grid's
 *     CURRENT virtual rank; Cblacs_barrier advances the rank cursor, so
 *     a loop body may invoke several routines per rank turn.
 *   - Each p? routine call registers the current virtual rank's local
 *     buffer; the FIRST registration captures the full call signature
 *     (descriptors + scalar args) and every later registration is
 *     checked against it — a mismatch (interleaved collectives,
 *     different descs) sets *info = -904 instead of computing garbage.
 *   - When the LAST rank of the grid has called, the routine assembles
 *     the global matrix from the block-cyclic local pieces (numroc
 *     layout), extracts the (ia, ja, m, n) submatrix window, runs the
 *     driver on the accelerator, writes results back into the window
 *     (only the parts the routine contractually writes — e.g. p?potrf
 *     preserves the opposite triangle), scatters every registered
 *     local buffer, and returns the real info.  Earlier (pending)
 *     registration calls return info = 0; their output buffers are
 *     valid once the final rank's call returns.
 *   - On a 1 x 1 grid every call computes immediately: a true drop-in
 *     for serial ScaLAPACK usage.
 *
 * ABI notes: PBLAS routines (p?gemm/p?trsm/p?trmm) have NO info
 * argument, matching the real PBLAS — errors go to stderr and leave
 * outputs untouched.  p?lange returns its double on the call that
 * completes the collective (earlier virtual-rank calls return 0.0).
 * Workspace queries (lwork = -1) answer minimal sizes without
 * registering.  Limits: irsrc/icsrc must be 0; pivoted routines
 * (p?getrf/getrs/gesv/getri) require ia = ja = 1 (the distributed-ipiv
 * layout is defined relative to whole-matrix rows); other routines
 * accept arbitrary in-range ia/ja windows.
 */

#include "slate_tpu_driver.h"
#include <complex.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

/* ---------------- BLACS emulation ---------------- */

#define SLATE_MAX_CTXT 64
#define SLATE_MAX_RANKS 256

typedef struct { int p, q, cur, used; char order; } blacs_ctx;
static blacs_ctx g_ctx[SLATE_MAX_CTXT];

typedef struct pending_s pending_t;
static void pend_abandon_ctxt(int ctxt);

static blacs_ctx* ctx_of(int ic) {
    if (ic < 0 || ic >= SLATE_MAX_CTXT || !g_ctx[ic].used) return 0;
    return &g_ctx[ic];
}

static int rank_row(const blacs_ctx* c, int r) {
    return (c->order == 'R') ? r / c->q : r % c->p;
}
static int rank_col(const blacs_ctx* c, int r) {
    return (c->order == 'R') ? r % c->q : r / c->p;
}

void Cblacs_pinfo(int* mypnum, int* nprocs) {
    if (mypnum) *mypnum = 0;
    if (nprocs) *nprocs = SLATE_MAX_RANKS;
}

void Cblacs_get(int ctxt, int what, int* val) {
    (void)ctxt; (void)what;
    if (val) *val = 0;   /* system default "context" handle */
}

void Cblacs_gridinit(int* ctxt, const char* order, int p, int q) {
    if (p <= 0 || q <= 0 || p * q > SLATE_MAX_RANKS) { *ctxt = -1; return; }
    for (int i = 0; i < SLATE_MAX_CTXT; ++i) {
        if (!g_ctx[i].used) {
            g_ctx[i].used = 1; g_ctx[i].p = p; g_ctx[i].q = q;
            g_ctx[i].cur = 0;
            g_ctx[i].order =
                (order && (order[0] == 'R' || order[0] == 'r')) ? 'R' : 'C';
            *ctxt = i;
            return;
        }
    }
    *ctxt = -1;
}

void Cblacs_gridinfo(int ctxt, int* np_row, int* np_col,
                     int* my_row, int* my_col) {
    blacs_ctx* c = ctx_of(ctxt);
    if (!c) { if (np_row) *np_row = -1; return; }
    if (np_row) *np_row = c->p;
    if (np_col) *np_col = c->q;
    /* the cursor marks WHICH virtual rank the sequential program is
     * currently simulating; it advances on Cblacs_barrier (the natural
     * "end of this rank's turn" marker when an SPMD loop is unrolled),
     * NOT on p? calls — so a loop body may invoke several routines per
     * rank. */
    if (my_row) *my_row = rank_row(c, c->cur);
    if (my_col) *my_col = rank_col(c, c->cur);
}

void Cblacs_gridexit(int ctxt) {
    blacs_ctx* c = ctx_of(ctxt);
    if (c) c->used = 0;
    pend_abandon_ctxt(ctxt);
}

void Cblacs_exit(int notdone) { (void)notdone; }

void Cblacs_barrier(int ctxt, const char* scope) {
    (void)scope;
    blacs_ctx* c = ctx_of(ctxt);
    if (c) c->cur = (c->cur + 1) % (c->p * c->q);
}

/* ---------------- numroc / descinit (3 manglings) ---------------- */

static int numroc_impl(int n, int nb, int iproc, int isrcproc, int nprocs) {
    int mydist = (nprocs + iproc - isrcproc) % nprocs;
    int nblocks = n / nb;
    int out = (nblocks / nprocs) * nb;
    int extra = nblocks % nprocs;
    if (mydist < extra) out += nb;
    else if (mydist == extra) out += n % nb;
    return out;
}

int numroc_(const int* n, const int* nb, const int* iproc,
            const int* isrcproc, const int* nprocs) {
    return numroc_impl(*n, *nb, *iproc, *isrcproc, *nprocs);
}
int numroc(const int* n, const int* nb, const int* iproc,
           const int* isrcproc, const int* nprocs) {
    return numroc_impl(*n, *nb, *iproc, *isrcproc, *nprocs);
}
int NUMROC(const int* n, const int* nb, const int* iproc,
           const int* isrcproc, const int* nprocs) {
    return numroc_impl(*n, *nb, *iproc, *isrcproc, *nprocs);
}

static void descinit_impl(int* desc, int m, int n, int mb, int nb,
                          int irsrc, int icsrc, int ctxt, int lld,
                          int* info) {
    desc[0] = 1; desc[1] = ctxt; desc[2] = m; desc[3] = n;
    desc[4] = mb; desc[5] = nb; desc[6] = irsrc; desc[7] = icsrc;
    desc[8] = lld;
    if (info) *info = 0;
}

void descinit_(int* desc, const int* m, const int* n, const int* mb,
               const int* nb, const int* irsrc, const int* icsrc,
               const int* ctxt, const int* lld, int* info) {
    descinit_impl(desc, *m, *n, *mb, *nb, *irsrc, *icsrc, *ctxt, *lld, info);
}
void descinit(int* desc, const int* m, const int* n, const int* mb,
              const int* nb, const int* irsrc, const int* icsrc,
              const int* ctxt, const int* lld, int* info) {
    descinit_impl(desc, *m, *n, *mb, *nb, *irsrc, *icsrc, *ctxt, *lld, info);
}
void DESCINIT(int* desc, const int* m, const int* n, const int* mb,
              const int* nb, const int* irsrc, const int* icsrc,
              const int* ctxt, const int* lld, int* info) {
    descinit_impl(desc, *m, *n, *mb, *nb, *irsrc, *icsrc, *ctxt, *lld, info);
}

/* ---------------- block-cyclic gather / scatter ---------------- */

#define D_CTXT(d) ((d)[1])
#define D_M(d)    ((d)[2])
#define D_N(d)    ((d)[3])
#define D_MB(d)   ((d)[4])
#define D_NB(d)   ((d)[5])
#define D_RSRC(d) ((d)[6])
#define D_CSRC(d) ((d)[7])
#define D_LLD(d)  ((d)[8])

/* copy between global (col-major, ld = M) and the (pr, pc) rank's local
 * buffer (col-major, ld = lld); dir 0 = local->global, 1 = global->local */
static void cyclic_copy(void* glob, void* loc, const int* desc, int lld,
                        int pr, int pc, int p, int q, int elem, int dir) {
    int M = D_M(desc), N = D_N(desc), MB = D_MB(desc), NB = D_NB(desc);
    int mloc = numroc_impl(M, MB, pr, 0, p);
    int nloc = numroc_impl(N, NB, pc, 0, q);
    char* g = (char*)glob; char* l = (char*)loc;
    for (int jl = 0; jl < nloc; ++jl) {
        int jg = ((jl / NB) * q + pc) * NB + jl % NB;
        for (int il0 = 0; il0 < mloc; il0 += MB) {
            int ig0 = ((il0 / MB) * p + pr) * MB;
            int len = mloc - il0 < MB ? mloc - il0 : MB;
            char* gp = g + ((size_t)jg * M + ig0) * elem;
            char* lp = l + ((size_t)jl * lld + il0) * elem;
            if (dir) memcpy(lp, gp, (size_t)len * elem);
            else memcpy(gp, lp, (size_t)len * elem);
        }
    }
}

/* ---------------- collective registration ---------------- */

/* full call signature, captured on the FIRST registration of a
 * collective and verified on every later one (ADVICE r4: keyed-only-by
 * -routine pending slots silently mixed distinct calls) */
typedef struct {
    int i[10];          /* routine ints: m n k nrhs ia ja ib jb ic jc */
    char ch[6];         /* uplo / trans / side / diag / jobz / norm */
    double s[8];        /* alpha, beta (re, im each) */
    int desc[3][9];
} call_sig;

struct pending_s {
    int tag;                       /* routine id, 0 = slot free */
    int ctxt;
    int nreg;                      /* registrations so far (rank order) */
    int poisoned;                  /* sig mismatch seen: drain, never compute */
    call_sig sig;
    void* bufs[3][SLATE_MAX_RANKS];    /* A / B / C local buffers */
    int   llds[3][SLATE_MAX_RANKS];
    int*  ipivs[SLATE_MAX_RANKS];
    void* wbufs[SLATE_MAX_RANKS];      /* replicated vector outs (w, tau) */
};

static pending_t g_pend[16];

static void pend_abandon_ctxt(int ctxt) {
    for (int i = 0; i < 16; ++i)
        if (g_pend[i].ctxt == ctxt) g_pend[i].tag = 0;
}

static pending_t* pend_get(int tag, int ctxt, const call_sig* sig,
                           int* info) {
    for (int i = 0; i < 16; ++i)
        if (g_pend[i].tag == tag && g_pend[i].ctxt == ctxt) {
            pending_t* pe = &g_pend[i];
            int bad = pe->poisoned
                || (sig && memcmp(&pe->sig, sig, sizeof(call_sig)));
            if (bad) {
                /* interleaved/mismatched collective: poison the slot
                 * and DRAIN the remaining registrations — freeing it
                 * here would let the leftover ranks re-form a slot
                 * with shifted rank indexing and complete a later
                 * same-signature call with garbage */
                blacs_ctx* c = ctx_of(ctxt);
                pe->poisoned = 1;
                pe->nreg += 1;
                if (!c || pe->nreg >= c->p * c->q) pe->tag = 0;
                if (info) *info = -904;
                return 0;
            }
            return pe;
        }
    for (int i = 0; i < 16; ++i)
        if (g_pend[i].tag == 0) {
            memset(&g_pend[i], 0, sizeof(pending_t));
            g_pend[i].tag = tag; g_pend[i].ctxt = ctxt;
            if (sig) g_pend[i].sig = *sig;
            return &g_pend[i];
        }
    if (info) *info = -903;
    return 0;
}

static int elem_of(char dt) {
    switch (dt) { case 's': return 4; case 'd': return 8;
                  case 'c': return 8; case 'z': return 16; }
    return 0;
}

/* register this rank's buffers; returns 1 when the grid is complete */
static int pend_step(pending_t* pe, blacs_ctx* c,
                     void* a, int lda, void* b, int ldb,
                     void* cc, int ldc, int* ipiv, void* w) {
    int r = pe->nreg;
    pe->bufs[0][r] = a; pe->bufs[1][r] = b; pe->bufs[2][r] = cc;
    pe->ipivs[r] = ipiv;
    pe->wbufs[r] = w;
    pe->llds[0][r] = lda; pe->llds[1][r] = ldb; pe->llds[2][r] = ldc;
    pe->nreg += 1;
    return pe->nreg == c->p * c->q;
}

/* ---------------- checked allocation ---------------- */

static void* xm(size_t n, int* ok) {
    void* p = malloc(n ? n : 1);
    if (!p) *ok = 0;
    return p;
}

/* ---------------- gather / scatter over all ranks ---------------- */

static char* gather_all(pending_t* pe, int which, const int* desc,
                        blacs_ctx* c, int elem, int* ok) {
    char* g = (char*)xm((size_t)D_M(desc) * D_N(desc) * elem, ok);
    if (!g) return 0;
    for (int r = 0; r < c->p * c->q; ++r)
        cyclic_copy(g, pe->bufs[which][r], desc, pe->llds[which][r],
                    rank_row(c, r), rank_col(c, r), c->p, c->q, elem, 0);
    return g;
}

static void scatter_all(pending_t* pe, int which, const int* desc,
                        blacs_ctx* c, char* g, int elem) {
    for (int r = 0; r < c->p * c->q; ++r)
        cyclic_copy(g, pe->bufs[which][r], desc, pe->llds[which][r],
                    rank_row(c, r), rank_col(c, r), c->p, c->q, elem, 1);
}

/* ---------------- submatrix windows ---------------- */

static int win_check(const int* desc, int ia, int ja, int m, int n,
                     int* info) {
    if (D_RSRC(desc) != 0 || D_CSRC(desc) != 0) {
        if (info) *info = -906;
        return 1;
    }
    if (ia < 1 || ja < 1 || ia - 1 + m > D_M(desc)
        || ja - 1 + n > D_N(desc)) {
        if (info) *info = -900;
        return 1;
    }
    return 0;
}

static char* win_get(const char* g, const int* desc, int ia, int ja,
                     int m, int n, int elem, int* ok) {
    char* s = (char*)xm((size_t)m * n * elem, ok);
    if (!s) return 0;
    int Mg = D_M(desc);
    for (int j = 0; j < n; ++j)
        memcpy(s + (size_t)j * m * elem,
               g + (((size_t)(ja - 1 + j)) * Mg + (ia - 1)) * elem,
               (size_t)m * elem);
    return s;
}

static void win_put(char* g, const int* desc, int ia, int ja,
                    int m, int n, const char* s, int elem) {
    int Mg = D_M(desc);
    for (int j = 0; j < n; ++j)
        memcpy(g + (((size_t)(ja - 1 + j)) * Mg + (ia - 1)) * elem,
               s + (size_t)j * m * elem, (size_t)m * elem);
}

/* only the `uplo` triangle (with diagonal) of an n x n window — the
 * opposite triangle keeps the caller's data (p?potrf contract) */
static void win_put_tri(char* g, const int* desc, int ia, int ja,
                        int n, char uplo, const char* s, int elem) {
    int Mg = D_M(desc);
    int lower = (uplo == 'L' || uplo == 'l');
    for (int j = 0; j < n; ++j) {
        int i0 = lower ? j : 0;
        int i1 = lower ? n : j + 1;
        memcpy(g + (((size_t)(ja - 1 + j)) * Mg + (ia - 1 + i0)) * elem,
               s + ((size_t)j * n + i0) * elem, (size_t)(i1 - i0) * elem);
    }
}

/* ---------------- distributed pivot vectors ---------------- */

/* ScaLAPACK ipiv: local row il of a process row holds the global
 * 1-based swap target of its global row, replicated across the process
 * columns */
static void scatter_ipiv(pending_t* pe, blacs_ctx* c, const int* desca,
                         const int64_t* piv, int n) {
    int MB = D_MB(desca);
    for (int r = 0; r < c->p * c->q; ++r) {
        if (!pe->ipivs[r]) continue;
        int pr = rank_row(c, r);
        int mloc = numroc_impl(n, MB, pr, 0, c->p);
        for (int il = 0; il < mloc; ++il) {
            int igr = ((il / MB) * c->p + pr) * MB + il % MB;
            if (igr < n) pe->ipivs[r][il] = (int)piv[igr];
        }
    }
}

static void gather_ipiv(pending_t* pe, blacs_ctx* c, const int* desca,
                        int64_t* piv, int n) {
    int MB = D_MB(desca);
    for (int r = 0; r < c->p * c->q; ++r) {
        if (!pe->ipivs[r] || rank_col(c, r) != 0) continue;
        int pr = rank_row(c, r);
        int mloc = numroc_impl(n, MB, pr, 0, c->p);
        for (int il = 0; il < mloc; ++il) {
            int igr = ((il / MB) * c->p + pr) * MB + il % MB;
            if (igr < n) piv[igr] = pe->ipivs[r][il];
        }
    }
}

/* LAPACK-style sequential row swaps on a col-major n x nrhs buffer */
static void row_swaps(char* b, int n, int nrhs, const int64_t* piv,
                      int elem, int reverse) {
    char tmp[16];
    for (int step = 0; step < n; ++step) {
        int i = reverse ? n - 1 - step : step;
        int j = (int)piv[i] - 1;
        if (j == i || j < 0 || j >= n) continue;
        for (int col = 0; col < nrhs; ++col) {
            char* x = b + ((size_t)col * n + i) * elem;
            char* y = b + ((size_t)col * n + j) * elem;
            memcpy(tmp, x, elem); memcpy(x, y, elem); memcpy(y, tmp, elem);
        }
    }
}
"""

SCALAPACK_IMPLS = r"""
/* ---------------- generic p? implementations ----------------
 * Shared pattern: build call_sig -> pend_get (captures/verifies) ->
 * pend_step -> on the grid-completing call: gather, window, driver,
 * write-back, scatter, free.  `info` may be NULL for the PBLAS
 * routines (no info in their ABI) — errors then go to stderr. */

static void set_info(int* info, int v) {
    if (info) *info = v;
    else if (v) fprintf(stderr, "slate_tpu pblas: error %d\n", v);
}

static void sig_desc(call_sig* sg, int which, const int* desc) {
    memcpy(sg->desc[which], desc, 9 * sizeof(int));
    sg->desc[which][8] = 0;   /* lld is legitimately per-rank */
}

static void ppotrf_impl(char dt, const char* uplo, int n,
                        void* a, int ia, int ja, const int* desca,
                        int* info) {
    set_info(info, 0);
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    if (win_check(desca, ia, ja, n, n, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = n; sg.i[4] = ia; sg.i[5] = ja; sg.ch[0] = uplo[0];
    sig_desc(&sg, 0, desca);
    pending_t* pe = pend_get(100 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), 0, 0, 0, 0, 0, 0)) return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    char* glob = gather_all(pe, 0, desca, c, elem, &ok);
    char* win = glob ? win_get(glob, desca, ia, ja, n, n, elem, &ok) : 0;
    char* out = win ? (char*)xm((size_t)n * n * elem, &ok) : 0;
    if (ok && out) {
        rc = slate_c_call("potrf", dt, n, n, win, n, 0, 0, 0, 0,
                          out, 0, 0, uplo[0]);
        win_put_tri(glob, desca, ia, ja, n, uplo[0], out, elem);
        scatter_all(pe, 0, desca, c, glob, elem);
    }
    free(glob); free(win); free(out);
    pe->tag = 0;
    set_info(info, rc);
}

static void ppotrs_impl(char dt, const char* uplo, int n, int nrhs,
                        void* a, int ia, int ja, const int* desca,
                        void* b, int ib, int jb, const int* descb,
                        int* info) {
    set_info(info, 0);
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    if (win_check(desca, ia, ja, n, n, info)
        || win_check(descb, ib, jb, n, nrhs, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = n; sg.i[3] = nrhs; sg.i[4] = ia; sg.i[5] = ja;
    sg.i[6] = ib; sg.i[7] = jb; sg.ch[0] = uplo[0];
    sig_desc(&sg, 0, desca); sig_desc(&sg, 1, descb);
    pending_t* pe = pend_get(200 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), b, D_LLD(descb), 0, 0, 0, 0))
        return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    char* ag = gather_all(pe, 0, desca, c, elem, &ok);
    char* bg = ag ? gather_all(pe, 1, descb, c, elem, &ok) : 0;
    char* aw = bg ? win_get(ag, desca, ia, ja, n, n, elem, &ok) : 0;
    char* bw = aw ? win_get(bg, descb, ib, jb, n, nrhs, elem, &ok) : 0;
    char* x = bw ? (char*)xm((size_t)n * nrhs * elem, &ok) : 0;
    if (ok && x) {
        rc = slate_c_call("potrs", dt, n, n, aw, n, n, nrhs, bw, n,
                          x, 0, 0, uplo[0]);
        win_put(bg, descb, ib, jb, n, nrhs, x, elem);
        scatter_all(pe, 1, descb, c, bg, elem);
    }
    free(ag); free(bg); free(aw); free(bw); free(x);
    pe->tag = 0;
    set_info(info, rc);
}

static void pposv_impl(char dt, const char* uplo, int n, int nrhs,
                       void* a, int ia, int ja, const int* desca,
                       void* b, int ib, int jb, const int* descb,
                       int* info) {
    set_info(info, 0);
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    if (win_check(desca, ia, ja, n, n, info)
        || win_check(descb, ib, jb, n, nrhs, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = n; sg.i[3] = nrhs; sg.i[4] = ia; sg.i[5] = ja;
    sg.i[6] = ib; sg.i[7] = jb; sg.ch[0] = uplo[0];
    sig_desc(&sg, 0, desca); sig_desc(&sg, 1, descb);
    pending_t* pe = pend_get(300 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), b, D_LLD(descb), 0, 0, 0, 0))
        return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    char* ag = gather_all(pe, 0, desca, c, elem, &ok);
    char* bg = ag ? gather_all(pe, 1, descb, c, elem, &ok) : 0;
    char* aw = bg ? win_get(ag, desca, ia, ja, n, n, elem, &ok) : 0;
    char* bw = aw ? win_get(bg, descb, ib, jb, n, nrhs, elem, &ok) : 0;
    char* fac = bw ? (char*)xm((size_t)n * n * elem, &ok) : 0;
    char* x = fac ? (char*)xm((size_t)n * nrhs * elem, &ok) : 0;
    if (ok && x) {
        rc = slate_c_call("posv_full", dt, n, n, aw, n, n, nrhs, bw, n,
                          fac, x, 0, uplo[0]);
        win_put_tri(ag, desca, ia, ja, n, uplo[0], fac, elem);
        win_put(bg, descb, ib, jb, n, nrhs, x, elem);
        scatter_all(pe, 0, desca, c, ag, elem);
        scatter_all(pe, 1, descb, c, bg, elem);
    }
    free(ag); free(bg); free(aw); free(bw); free(fac); free(x);
    pe->tag = 0;
    set_info(info, rc);
}

/* pivoted routines require ia = ja = 1: the distributed-ipiv layout is
 * defined relative to whole-matrix rows */
static int check_sub1(int ia, int ja, int* info) {
    if (ia != 1 || ja != 1) { set_info(info, -900); return 1; }
    return 0;
}

static void pgetrf_impl(char dt, int m, int n,
                        void* a, int ia, int ja, const int* desca,
                        int* ipiv, int* info) {
    set_info(info, 0);
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    if (check_sub1(ia, ja, info)
        || win_check(desca, ia, ja, m, n, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = m; sg.i[1] = n;
    sig_desc(&sg, 0, desca);
    pending_t* pe = pend_get(400 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), 0, 0, 0, 0, ipiv, 0)) return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    int mn = m < n ? m : n;
    char* glob = gather_all(pe, 0, desca, c, elem, &ok);
    char* aw = glob ? win_get(glob, desca, 1, 1, m, n, elem, &ok) : 0;
    char* f = aw ? (char*)xm((size_t)m * n * elem, &ok) : 0;
    /* the bridge returns an m-length swap vector (perm_to_ipiv of the
     * full row permutation) even when m > n */
    int64_t* piv = f ? (int64_t*)xm(sizeof(int64_t) * (size_t)m, &ok) : 0;
    if (ok && piv) {
        rc = slate_c_call("getrf_ipiv", dt, m, n, aw, m, 0, 0, 0, 0,
                          f, piv, 0, 'L');
        win_put(glob, desca, 1, 1, m, n, f, elem);
        scatter_all(pe, 0, desca, c, glob, elem);
        scatter_ipiv(pe, c, desca, piv, mn);
    }
    free(glob); free(aw); free(f); free(piv);
    pe->tag = 0;
    set_info(info, rc);
}

static void pgetrs_impl(char dt, const char* trans, int n, int nrhs,
                        void* a, int ia, int ja, const int* desca,
                        int* ipiv, void* b, int ib, int jb,
                        const int* descb, int* info) {
    set_info(info, 0);
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    if (check_sub1(ia, ja, info) || check_sub1(ib, jb, info)
        || win_check(desca, ia, ja, n, n, info)
        || win_check(descb, ib, jb, n, nrhs, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = n; sg.i[3] = nrhs; sg.ch[0] = trans[0];
    sig_desc(&sg, 0, desca); sig_desc(&sg, 1, descb);
    pending_t* pe = pend_get(500 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), b, D_LLD(descb), 0, 0, ipiv, 0))
        return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    int tn = (trans[0] == 'N' || trans[0] == 'n') ? 1 : 0;
    char* ag = gather_all(pe, 0, desca, c, elem, &ok);
    char* bg = ag ? gather_all(pe, 1, descb, c, elem, &ok) : 0;
    char* aw = bg ? win_get(ag, desca, 1, 1, n, n, elem, &ok) : 0;
    char* bw = aw ? win_get(bg, descb, 1, 1, n, nrhs, elem, &ok) : 0;
    char* x = bw ? (char*)xm((size_t)n * nrhs * elem, &ok) : 0;
    int64_t* piv = x ? (int64_t*)xm(sizeof(int64_t) * (size_t)n, &ok) : 0;
    if (ok && piv) {
        gather_ipiv(pe, c, desca, piv, n);
        if (tn) {
            row_swaps(bw, n, nrhs, piv, elem, 0);
            rc = slate_c_call("lu_solve_factored", dt, n, n, aw, n,
                              n, nrhs, bw, n, x, 0, 0, 'L');
        } else {
            rc = slate_c_call("lu_solve_trans", dt, n, n, aw, n,
                              n, nrhs, bw, n, x, 0, 0,
                              (dt == 'c' || dt == 'z') && (trans[0] == 'C'
                               || trans[0] == 'c') ? 'C' : 'T');
            row_swaps(x, n, nrhs, piv, elem, 1);
        }
        win_put(bg, descb, 1, 1, n, nrhs, x, elem);
        scatter_all(pe, 1, descb, c, bg, elem);
    }
    free(ag); free(bg); free(aw); free(bw); free(x); free(piv);
    pe->tag = 0;
    set_info(info, rc);
}

static void pgesv_impl(char dt, int n, int nrhs,
                       void* a, int ia, int ja, const int* desca,
                       int* ipiv, void* b, int ib, int jb,
                       const int* descb, int* info) {
    set_info(info, 0);
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    if (check_sub1(ia, ja, info) || check_sub1(ib, jb, info)
        || win_check(desca, ia, ja, n, n, info)
        || win_check(descb, ib, jb, n, nrhs, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = n; sg.i[3] = nrhs;
    sig_desc(&sg, 0, desca); sig_desc(&sg, 1, descb);
    pending_t* pe = pend_get(600 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), b, D_LLD(descb), 0, 0, ipiv, 0))
        return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    char* ag = gather_all(pe, 0, desca, c, elem, &ok);
    char* bg = ag ? gather_all(pe, 1, descb, c, elem, &ok) : 0;
    char* aw = bg ? win_get(ag, desca, 1, 1, n, n, elem, &ok) : 0;
    char* bw = aw ? win_get(bg, descb, 1, 1, n, nrhs, elem, &ok) : 0;
    char* lu = bw ? (char*)xm((size_t)n * n * elem, &ok) : 0;
    char* xg = lu ? (char*)xm((size_t)n * nrhs * elem, &ok) : 0;
    int64_t* piv = xg ? (int64_t*)xm(sizeof(int64_t) * (size_t)n, &ok) : 0;
    if (ok && piv) {
        rc = slate_c_call("gesv_full", dt, n, n, aw, n, n, nrhs,
                          bw, n, lu, piv, xg, 'L');
        win_put(ag, desca, 1, 1, n, n, lu, elem);
        win_put(bg, descb, 1, 1, n, nrhs, xg, elem);
        scatter_all(pe, 0, desca, c, ag, elem);
        scatter_all(pe, 1, descb, c, bg, elem);
        scatter_ipiv(pe, c, desca, piv, n);
    }
    free(ag); free(bg); free(aw); free(bw); free(lu); free(xg); free(piv);
    pe->tag = 0;
    set_info(info, rc);
}

static void pgetri_impl(char dt, int n,
                        void* a, int ia, int ja, const int* desca,
                        int* ipiv, int* info) {
    set_info(info, 0);
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    if (check_sub1(ia, ja, info)
        || win_check(desca, ia, ja, n, n, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = n;
    sig_desc(&sg, 0, desca);
    pending_t* pe = pend_get(700 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), 0, 0, 0, 0, ipiv, 0)) return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    char* ag = gather_all(pe, 0, desca, c, elem, &ok);
    char* aw = ag ? win_get(ag, desca, 1, 1, n, n, elem, &ok) : 0;
    char* eye = aw ? (char*)xm((size_t)n * n * elem, &ok) : 0;
    char* x = eye ? (char*)xm((size_t)n * n * elem, &ok) : 0;
    int64_t* piv = x ? (int64_t*)xm(sizeof(int64_t) * (size_t)n, &ok) : 0;
    if (ok && piv) {
        gather_ipiv(pe, c, desca, piv, n);
        /* inv(A) = U^{-1} L^{-1} P: solve the packed LU against P*I */
        memset(eye, 0, (size_t)n * n * elem);
        for (int j = 0; j < n; ++j) {
            unsigned char one_s[16] = {0};
            if (dt == 's') { float v = 1.0f; memcpy(one_s, &v, 4); }
            else if (dt == 'd') { double v = 1.0; memcpy(one_s, &v, 8); }
            else if (dt == 'c') { float v[2] = {1.0f, 0.0f}; memcpy(one_s, v, 8); }
            else { double v[2] = {1.0, 0.0}; memcpy(one_s, v, 16); }
            memcpy(eye + ((size_t)j * n + j) * elem, one_s, elem);
        }
        row_swaps(eye, n, n, piv, elem, 0);
        rc = slate_c_call("lu_solve_factored", dt, n, n, aw, n,
                          n, n, eye, n, x, 0, 0, 'L');
        win_put(ag, desca, 1, 1, n, n, x, elem);
        scatter_all(pe, 0, desca, c, ag, elem);
    }
    free(ag); free(aw); free(eye); free(x); free(piv);
    pe->tag = 0;
    set_info(info, rc);
}

static void ppotri_impl(char dt, const char* uplo, int n,
                        void* a, int ia, int ja, const int* desca,
                        int* info) {
    set_info(info, 0);
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    if (win_check(desca, ia, ja, n, n, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = n; sg.i[4] = ia; sg.i[5] = ja; sg.ch[0] = uplo[0];
    sig_desc(&sg, 0, desca);
    pending_t* pe = pend_get(800 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), 0, 0, 0, 0, 0, 0)) return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    char* glob = gather_all(pe, 0, desca, c, elem, &ok);
    char* win = glob ? win_get(glob, desca, ia, ja, n, n, elem, &ok) : 0;
    char* out = win ? (char*)xm((size_t)n * n * elem, &ok) : 0;
    if (ok && out) {
        rc = slate_c_call("potri_factored", dt, n, n, win, n, 0, 0, 0, 0,
                          out, 0, 0, uplo[0]);
        win_put_tri(glob, desca, ia, ja, n, uplo[0], out, elem);
        scatter_all(pe, 0, desca, c, glob, elem);
    }
    free(glob); free(win); free(out);
    pe->tag = 0;
    set_info(info, rc);
}

static void pgeqrf_impl(char dt, int m, int n,
                        void* a, int ia, int ja, const int* desca,
                        void* tau, int* info) {
    set_info(info, 0);
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    if (win_check(desca, ia, ja, m, n, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = m; sg.i[1] = n; sg.i[4] = ia; sg.i[5] = ja;
    sig_desc(&sg, 0, desca);
    pending_t* pe = pend_get(900 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), 0, 0, 0, 0, 0, tau)) return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    int mn = m < n ? m : n;
    char* glob = gather_all(pe, 0, desca, c, elem, &ok);
    char* win = glob ? win_get(glob, desca, ia, ja, m, n, elem, &ok) : 0;
    char* f = win ? (char*)xm((size_t)m * n * elem, &ok) : 0;
    char* tg = f ? (char*)xm((size_t)mn * elem, &ok) : 0;
    if (ok && tg) {
        rc = slate_c_call("geqrf", dt, m, n, win, m, 0, 0, 0, 0,
                          f, tg, 0, 'L');
        win_put(glob, desca, ia, ja, m, n, f, elem);
        scatter_all(pe, 0, desca, c, glob, elem);
        /* tau: distributed over process columns in the GLOBAL column
         * layout (ScaLAPACK LOCc(JA+...) indexing) — window column jg
         * is global column ja-1+jg, owned by its cyclic process column
         * at that global column's local index */
        int NB = D_NB(desca);
        for (int jg = 0; jg < mn; ++jg) {
            int gcol = ja - 1 + jg;
            int pc = (gcol / NB) % c->q;
            int jl = (gcol / (NB * c->q)) * NB + gcol % NB;
            for (int r = 0; r < c->p * c->q; ++r) {
                if (!pe->wbufs[r] || rank_col(c, r) != pc) continue;
                memcpy((char*)pe->wbufs[r] + (size_t)jl * elem,
                       tg + (size_t)jg * elem, elem);
            }
        }
    }
    free(glob); free(win); free(f); free(tg);
    pe->tag = 0;
    set_info(info, rc);
}

static void pgels_impl(char dt, const char* trans, int m, int n, int nrhs,
                       void* a, int ia, int ja, const int* desca,
                       void* b, int ib, int jb, const int* descb,
                       int* info) {
    set_info(info, 0);
    if (!(trans[0] == 'N' || trans[0] == 'n')) { set_info(info, -907); return; }
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    int mx = m > n ? m : n;
    if (win_check(desca, ia, ja, m, n, info)
        || win_check(descb, ib, jb, mx, nrhs, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = m; sg.i[1] = n; sg.i[3] = nrhs; sg.ch[0] = trans[0];
    sig_desc(&sg, 0, desca); sig_desc(&sg, 1, descb);
    pending_t* pe = pend_get(1000 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), b, D_LLD(descb), 0, 0, 0, 0))
        return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    char* ag = gather_all(pe, 0, desca, c, elem, &ok);
    char* bg = ag ? gather_all(pe, 1, descb, c, elem, &ok) : 0;
    char* aw = bg ? win_get(ag, desca, ia, ja, m, n, elem, &ok) : 0;
    char* bw = aw ? win_get(bg, descb, ib, jb, m, nrhs, elem, &ok) : 0;
    char* x = bw ? (char*)xm((size_t)n * nrhs * elem, &ok) : 0;
    if (ok && x) {
        rc = slate_c_call("gels", dt, m, n, aw, m, m, nrhs, bw, m,
                          x, 0, 0, 'L');
        /* solution occupies the leading n rows of the B window (the
         * QR factors are NOT written back into A — documented drop-in
         * deviation; the reference overwrites A with the factorization) */
        win_put(bg, descb, ib, jb, n, nrhs, x, elem);
        scatter_all(pe, 1, descb, c, bg, elem);
    }
    free(ag); free(bg); free(aw); free(bw); free(x);
    pe->tag = 0;
    set_info(info, rc);
}

static void pheev_impl(char dt, const char* jobz, const char* uplo, int n,
                       void* a, int ia, int ja, const int* desca,
                       void* w, int w_elem, void* z, int iz, int jz,
                       const int* descz, int* info) {
    set_info(info, 0);
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { set_info(info, -901); return; }
    int wantz = (jobz[0] == 'V' || jobz[0] == 'v');
    if (win_check(desca, ia, ja, n, n, info)) return;
    if (wantz && win_check(descz, iz, jz, n, n, info)) return;
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = n; sg.i[4] = ia; sg.i[5] = ja; sg.i[6] = iz; sg.i[7] = jz;
    sg.ch[0] = uplo[0]; sg.ch[1] = jobz[0];
    sig_desc(&sg, 0, desca);
    if (wantz) sig_desc(&sg, 1, descz);
    pending_t* pe = pend_get(1100 + dt, D_CTXT(desca), &sg, info);
    if (!pe) return;
    if (!pend_step(pe, c, a, D_LLD(desca), wantz ? z : 0,
                   wantz ? D_LLD(descz) : 0, 0, 0, 0, w)) return;
    int elem = elem_of(dt), ok = 1, rc = -905;
    char* ag = gather_all(pe, 0, desca, c, elem, &ok);
    char* aw = ag ? win_get(ag, desca, ia, ja, n, n, elem, &ok) : 0;
    double* wd = aw ? (double*)xm(sizeof(double) * (size_t)n, &ok) : 0;
    char* zg = (wantz && wd)
        ? (char*)xm((size_t)n * n * elem, &ok) : 0;
    if (ok && wd && (!wantz || zg)) {
        rc = slate_c_call(wantz ? "heev" : "heev_vals", dt, n, n, aw, n,
                          0, 0, 0, 0, wd, wantz ? zg : 0, 0, uplo[0]);
        if (wantz) {
            char* zfull = gather_all(pe, 1, descz, c, elem, &ok);
            if (zfull) {
                win_put(zfull, descz, iz, jz, n, n, zg, elem);
                scatter_all(pe, 1, descz, c, zfull, elem);
                free(zfull);
            }
        }
        /* eigenvalues are replicated on every rank */
        for (int r = 0; r < c->p * c->q; ++r) {
            if (!pe->wbufs[r]) continue;
            if (w_elem == 8)
                memcpy(pe->wbufs[r], wd, sizeof(double) * (size_t)n);
            else {
                float* wf = (float*)pe->wbufs[r];
                for (int i = 0; i < n; ++i) wf[i] = (float)wd[i];
            }
        }
    }
    free(ag); free(aw); free(wd); free(zg);
    pe->tag = 0;
    set_info(info, rc);
}

static double plange_impl(char dt, const char* norm, int m, int n,
                          void* a, int ia, int ja, const int* desca) {
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) return 0.0;
    int info = 0;
    if (win_check(desca, ia, ja, m, n, &info)) {
        fprintf(stderr, "slate_tpu p?lange: bad window (%d)\n", info);
        return 0.0;
    }
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = m; sg.i[1] = n; sg.i[4] = ia; sg.i[5] = ja;
    sg.ch[0] = norm[0];
    sig_desc(&sg, 0, desca);
    pending_t* pe = pend_get(1200 + dt, D_CTXT(desca), &sg, &info);
    if (!pe) return 0.0;
    if (!pend_step(pe, c, a, D_LLD(desca), 0, 0, 0, 0, 0, 0))
        return 0.0;   /* value is delivered by the completing call */
    int elem = elem_of(dt), ok = 1;
    double val = 0.0;
    char* glob = gather_all(pe, 0, desca, c, elem, &ok);
    char* win = glob ? win_get(glob, desca, ia, ja, m, n, elem, &ok) : 0;
    if (ok && win) {
        char nm = norm[0];
        if (nm == 'O' || nm == 'o' || nm == '1') nm = '1';
        else if (nm == 'I' || nm == 'i') nm = 'I';
        else if (nm == 'F' || nm == 'f' || nm == 'E' || nm == 'e') nm = 'F';
        else nm = 'M';
        slate_c_call("lange", dt, m, n, win, m, 0, 0, 0, 0,
                     &val, 0, 0, nm);
    }
    free(glob); free(win);
    pe->tag = 0;
    return val;
}
"""

# typed PBLAS implementations: gemm / trsm / trmm need alpha/beta and the
# op() transforms, so they are emitted once per dtype
PBLAS_TYPED = r"""
/* typed op(), alpha-scale, and unit-diagonal helpers */
static void opmat_{k}(char tr, int m, int n, const {T}* g, {T}* out) {{
    /* g is (m x n) col-major; out is op(g): N -> copy, T/C -> (n x m) */
    if (tr == 'N' || tr == 'n') {{
        memcpy(out, g, sizeof({T}) * (size_t)m * n);
        return;
    }}
    for (int j = 0; j < n; ++j)
        for (int i = 0; i < m; ++i) {{
            {T} v = g[(size_t)j * m + i];
            out[(size_t)i * n + j] = {CONJ};
        }}
}}

static void scal_{k}({T}* x, size_t cnt, {T} alpha) {{
    if (alpha == ({T})1) return;
    for (size_t i = 0; i < cnt; ++i) x[i] *= alpha;
}}

static void unit_diag_{k}({T}* a, int n) {{
    for (int j = 0; j < n; ++j) a[(size_t)j * n + j] = ({T})1;
}}

static void pgemm_impl_{k}(const char* transa, const char* transb,
                           int m, int n, int k, {T} alpha,
                           {T}* a, int ia, int ja, const int* desca,
                           {T}* b, int ib, int jb, const int* descb,
                           {T} beta,
                           {T}* cc, int ic, int jc, const int* descc) {{
    int info = 0;
    blacs_ctx* c = ctx_of(D_CTXT(descc));
    if (!c) {{ set_info(0, -901); return; }}
    int opa = (transa[0] == 'N' || transa[0] == 'n');
    int opb = (transb[0] == 'N' || transb[0] == 'n');
    int Am = opa ? m : k, An = opa ? k : m;
    int Bm = opb ? k : n, Bn = opb ? n : k;
    if (win_check(desca, ia, ja, Am, An, &info)
        || win_check(descb, ib, jb, Bm, Bn, &info)
        || win_check(descc, ic, jc, m, n, &info)) {{
        set_info(0, info); return;
    }}
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = m; sg.i[1] = n; sg.i[2] = k;
    sg.i[4] = ia; sg.i[5] = ja; sg.i[6] = ib; sg.i[7] = jb;
    sg.i[8] = ic; sg.i[9] = jc;
    sg.ch[0] = transa[0]; sg.ch[1] = transb[0];
    sg.s[0] = {ALPHA_RE}; sg.s[1] = {ALPHA_IM};
    sg.s[2] = {BETA_RE};  sg.s[3] = {BETA_IM};
    sig_desc(&sg, 0, desca); sig_desc(&sg, 1, descb);
    sig_desc(&sg, 2, descc);
    pending_t* pe = pend_get(1300 + '{k}', D_CTXT(descc), &sg, &info);
    if (!pe) {{ set_info(0, info); return; }}
    if (!pend_step(pe, c, a, D_LLD(desca), b, D_LLD(descb),
                   cc, D_LLD(descc), 0, 0)) return;
    int elem = (int)sizeof({T}), ok = 1, rc = -905;
    char* ag = gather_all(pe, 0, desca, c, elem, &ok);
    char* bg = ag ? gather_all(pe, 1, descb, c, elem, &ok) : 0;
    char* cg = bg ? gather_all(pe, 2, descc, c, elem, &ok) : 0;
    {T}* aw = cg ? ({T}*)win_get(ag, desca, ia, ja, Am, An, elem, &ok) : 0;
    {T}* bw = aw ? ({T}*)win_get(bg, descb, ib, jb, Bm, Bn, elem, &ok) : 0;
    {T}* cw = bw ? ({T}*)win_get(cg, descc, ic, jc, m, n, elem, &ok) : 0;
    {T}* oa = cw ? ({T}*)xm(sizeof({T}) * (size_t)m * k, &ok) : 0;
    {T}* ob = oa ? ({T}*)xm(sizeof({T}) * (size_t)k * n, &ok) : 0;
    {T}* pg = ob ? ({T}*)xm(sizeof({T}) * (size_t)m * n, &ok) : 0;
    if (ok && pg) {{
        opmat_{k}(transa[0], Am, An, aw, oa);
        opmat_{k}(transb[0], Bm, Bn, bw, ob);
        rc = slate_c_call("gemm", '{k}', m, k, oa, m, k, n, ob, k,
                          pg, 0, 0, 'L');
        for (size_t i = 0; i < (size_t)m * n; ++i)
            cw[i] = alpha * pg[i] + beta * cw[i];
        win_put(cg, descc, ic, jc, m, n, (char*)cw, elem);
        scatter_all(pe, 2, descc, c, cg, elem);
    }}
    free(ag); free(bg); free(cg); free(aw); free(bw); free(cw);
    free(oa); free(ob); free(pg);
    pe->tag = 0;
    set_info(0, rc);
}}

/* ptrsm/ptrmm: reduce side/trans/diag to the driver's Left/NonUnit
 * solve by explicit transposes — side=R becomes op(A)^T on the left of
 * B^T, transa folds into the materialised operand, diag=U overwrites
 * the stored diagonal with ones. */
static void ptrXm_impl_{k}(int is_trsm, const char* side, const char* uplo,
                           const char* transa, const char* diag,
                           int m, int n, {T} alpha,
                           {T}* a, int ia, int ja, const int* desca,
                           {T}* b, int ib, int jb, const int* descb) {{
    int info = 0;
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) {{ set_info(0, -901); return; }}
    int left = (side[0] == 'L' || side[0] == 'l');
    int kd = left ? m : n;
    if (win_check(desca, ia, ja, kd, kd, &info)
        || win_check(descb, ib, jb, m, n, &info)) {{
        set_info(0, info); return;
    }}
    call_sig sg; memset(&sg, 0, sizeof sg);
    sg.i[0] = m; sg.i[1] = n; sg.i[4] = ia; sg.i[5] = ja;
    sg.i[6] = ib; sg.i[7] = jb;
    sg.ch[0] = side[0]; sg.ch[1] = uplo[0]; sg.ch[2] = transa[0];
    sg.ch[3] = diag[0]; sg.ch[4] = is_trsm ? 's' : 'm';
    sg.s[0] = {ALPHA_RE}; sg.s[1] = {ALPHA_IM};
    sig_desc(&sg, 0, desca); sig_desc(&sg, 1, descb);
    pending_t* pe = pend_get((is_trsm ? 1400 : 1500) + '{k}',
                             D_CTXT(desca), &sg, &info);
    if (!pe) {{ set_info(0, info); return; }}
    if (!pend_step(pe, c, a, D_LLD(desca), b, D_LLD(descb), 0, 0, 0, 0))
        return;
    int elem = (int)sizeof({T}), ok = 1, rc = -905;
    char* ag = gather_all(pe, 0, desca, c, elem, &ok);
    char* bg = ag ? gather_all(pe, 1, descb, c, elem, &ok) : 0;
    {T}* aw = bg ? ({T}*)win_get(ag, desca, ia, ja, kd, kd, elem, &ok) : 0;
    {T}* bw = aw ? ({T}*)win_get(bg, descb, ib, jb, m, n, elem, &ok) : 0;
    {T}* aeff = bw ? ({T}*)xm(sizeof({T}) * (size_t)kd * kd, &ok) : 0;
    int rows = left ? m : n, cols = left ? n : m;
    {T}* beff = aeff ? ({T}*)xm(sizeof({T}) * (size_t)m * n, &ok) : 0;
    {T}* x = beff ? ({T}*)xm(sizeof({T}) * (size_t)m * n, &ok) : 0;
    {T}* atmp = x ? ({T}*)xm(sizeof({T}) * (size_t)kd * kd, &ok) : 0;
    if (ok && atmp) {{
        char u = uplo[0];
        /* fold transa into the materialised operand */
        opmat_{k}(transa[0], kd, kd, aw, aeff);
        if (!(transa[0] == 'N' || transa[0] == 'n'))
            u = (u == 'L' || u == 'l') ? 'U' : 'L';
        if (diag[0] == 'U' || diag[0] == 'u') unit_diag_{k}(aeff, kd);
        if (!left) {{
            /* X op(A) = alpha B  <=>  op(A)^T X^T = alpha B^T */
            opmat_{k}('T', kd, kd, aeff, atmp);
            memcpy(aeff, atmp, sizeof({T}) * (size_t)kd * kd);
            u = (u == 'L' || u == 'l') ? 'U' : 'L';
            opmat_{k}('T', m, n, bw, beff);    /* B^T (n x m) */
        }} else {{
            memcpy(beff, bw, sizeof({T}) * (size_t)m * n);
        }}
        scal_{k}(beff, (size_t)m * n, alpha);
        rc = slate_c_call(is_trsm ? "trsm" : "trmm", '{k}',
                          kd, kd, aeff, kd, rows, cols, beff, rows,
                          x, 0, 0, u);
        if (!left) {{
            opmat_{k}('T', rows, cols, x, beff);
            memcpy(x, beff, sizeof({T}) * (size_t)m * n);
        }}
        win_put(bg, descb, ib, jb, m, n, (char*)x, elem);
        scatter_all(pe, 1, descb, c, bg, elem);
    }}
    free(ag); free(bg); free(aw); free(bw); free(aeff); free(beff);
    free(x); free(atmp);
    pe->tag = 0;
    set_info(0, rc);
}}
"""


def _sc_alpha_exprs(k):
    if k == "s":
        return ("(double)alpha", "0.0", "(double)beta", "0.0")
    if k == "d":
        return ("alpha", "0.0", "beta", "0.0")
    if k == "c":
        return ("(double)crealf(alpha)", "(double)cimagf(alpha)",
                "(double)crealf(beta)", "(double)cimagf(beta)")
    return ("creal(alpha)", "cimag(alpha)", "creal(beta)", "cimag(beta)")


def _sc_one(k):
    return {"s": "1.0f", "d": "1.0", "c": "1.0f", "z": "1.0"}[k]


def _manglings(name):
    return (name.upper(), name, name + "_")


def gen_scalapack():
    parts = [SCALAPACK_CORE, SCALAPACK_IMPLS]
    for k in "sdcz":
        T = CTYPES[k]
        if k == "c":
            conj = "((tr == 'C' || tr == 'c') ? conjf(v) : v)"
        elif k == "z":
            conj = "((tr == 'C' || tr == 'c') ? conj(v) : v)"
        else:
            conj = "v"
        are, aim, bre, bim = _sc_alpha_exprs(k)
        parts.append(PBLAS_TYPED.format(
            k=k, T=T, CONJ=conj, ALPHA_RE=are, ALPHA_IM=aim,
            BETA_RE=bre, BETA_IM=bim))

    w = parts.append
    for k in "sdcz":
        T = CTYPES[k]
        WT = "float" if k in "sc" else "double"      # eigenvalue width
        WE = 4 if k in "sc" else 8
        sy = "syev" if k in "sd" else "heev"
        one = _sc_one(k)

        for mang in _manglings(f"p{k}potrf"):
            w(f"void {mang}(const char* uplo, const int* n, {T}* a, "
              f"const int* ia, const int* ja, const int* desca, int* info)\n"
              f"{{ ppotrf_impl('{k}', uplo, *n, a, *ia, *ja, desca, info); }}\n")
        for mang in _manglings(f"p{k}potrs"):
            w(f"void {mang}(const char* uplo, const int* n, const int* nrhs, "
              f"{T}* a, const int* ia, const int* ja, const int* desca, "
              f"{T}* b, const int* ib, const int* jb, const int* descb, "
              f"int* info)\n"
              f"{{ ppotrs_impl('{k}', uplo, *n, *nrhs, a, *ia, *ja, desca, "
              f"b, *ib, *jb, descb, info); }}\n")
        for mang in _manglings(f"p{k}posv"):
            w(f"void {mang}(const char* uplo, const int* n, const int* nrhs, "
              f"{T}* a, const int* ia, const int* ja, const int* desca, "
              f"{T}* b, const int* ib, const int* jb, const int* descb, "
              f"int* info)\n"
              f"{{ pposv_impl('{k}', uplo, *n, *nrhs, a, *ia, *ja, desca, "
              f"b, *ib, *jb, descb, info); }}\n")
        for mang in _manglings(f"p{k}getrf"):
            w(f"void {mang}(const int* m, const int* n, {T}* a, "
              f"const int* ia, const int* ja, const int* desca, int* ipiv, "
              f"int* info)\n"
              f"{{ pgetrf_impl('{k}', *m, *n, a, *ia, *ja, desca, ipiv, "
              f"info); }}\n")
        for mang in _manglings(f"p{k}getrs"):
            w(f"void {mang}(const char* trans, const int* n, "
              f"const int* nrhs, {T}* a, const int* ia, const int* ja, "
              f"const int* desca, int* ipiv, {T}* b, const int* ib, "
              f"const int* jb, const int* descb, int* info)\n"
              f"{{ pgetrs_impl('{k}', trans, *n, *nrhs, a, *ia, *ja, desca, "
              f"ipiv, b, *ib, *jb, descb, info); }}\n")
        for mang in _manglings(f"p{k}gesv"):
            w(f"void {mang}(const int* n, const int* nrhs, {T}* a, "
              f"const int* ia, const int* ja, const int* desca, int* ipiv, "
              f"{T}* b, const int* ib, const int* jb, const int* descb, "
              f"int* info)\n"
              f"{{ pgesv_impl('{k}', *n, *nrhs, a, *ia, *ja, desca, ipiv, "
              f"b, *ib, *jb, descb, info); }}\n")
        for mang in _manglings(f"p{k}getri"):
            w(f"void {mang}(const int* n, {T}* a, const int* ia, "
              f"const int* ja, const int* desca, int* ipiv, {T}* work, "
              f"const int* lwork, int* iwork, const int* liwork, int* info)\n"
              f"{{ if ((lwork && *lwork == -1) || (liwork && *liwork == -1)) "
              f"{{ if (work) work[0] = {one}; if (iwork) iwork[0] = 1; "
              f"if (info) *info = 0; return; }}\n"
              f"  pgetri_impl('{k}', *n, a, *ia, *ja, desca, ipiv, info); }}\n")
        for mang in _manglings(f"p{k}potri"):
            w(f"void {mang}(const char* uplo, const int* n, {T}* a, "
              f"const int* ia, const int* ja, const int* desca, int* info)\n"
              f"{{ ppotri_impl('{k}', uplo, *n, a, *ia, *ja, desca, info); }}\n")
        for mang in _manglings(f"p{k}geqrf"):
            w(f"void {mang}(const int* m, const int* n, {T}* a, "
              f"const int* ia, const int* ja, const int* desca, {T}* tau, "
              f"{T}* work, const int* lwork, int* info)\n"
              f"{{ if (lwork && *lwork == -1) {{ if (work) work[0] = {one}; "
              f"if (info) *info = 0; return; }}\n"
              f"  pgeqrf_impl('{k}', *m, *n, a, *ia, *ja, desca, tau, "
              f"info); }}\n")
        for mang in _manglings(f"p{k}gels"):
            w(f"void {mang}(const char* trans, const int* m, const int* n, "
              f"const int* nrhs, {T}* a, const int* ia, const int* ja, "
              f"const int* desca, {T}* b, const int* ib, const int* jb, "
              f"const int* descb, {T}* work, const int* lwork, int* info)\n"
              f"{{ if (lwork && *lwork == -1) {{ if (work) work[0] = {one}; "
              f"if (info) *info = 0; return; }}\n"
              f"  pgels_impl('{k}', trans, *m, *n, *nrhs, a, *ia, *ja, "
              f"desca, b, *ib, *jb, descb, info); }}\n")
        # eigen drivers: real -> p?syev, complex -> p?heev (extra rwork)
        if k in "sd":
            for mang in _manglings(f"p{k}{sy}"):
                w(f"void {mang}(const char* jobz, const char* uplo, "
                  f"const int* n, {T}* a, const int* ia, const int* ja, "
                  f"const int* desca, {WT}* w, {T}* z, const int* iz, "
                  f"const int* jz, const int* descz, {T}* work, "
                  f"const int* lwork, int* info)\n"
                  f"{{ if (lwork && *lwork == -1) {{ if (work) work[0] = "
                  f"{one}; if (info) *info = 0; return; }}\n"
                  f"  pheev_impl('{k}', jobz, uplo, *n, a, *ia, *ja, desca, "
                  f"w, {WE}, z, *iz, *jz, descz, info); }}\n")
        else:
            for mang in _manglings(f"p{k}{sy}"):
                w(f"void {mang}(const char* jobz, const char* uplo, "
                  f"const int* n, {T}* a, const int* ia, const int* ja, "
                  f"const int* desca, {WT}* w, {T}* z, const int* iz, "
                  f"const int* jz, const int* descz, {T}* work, "
                  f"const int* lwork, {WT}* rwork, const int* lrwork, "
                  f"int* info)\n"
                  f"{{ if ((lwork && *lwork == -1) || (lrwork && *lrwork == "
                  f"-1)) {{ if (work) work[0] = {one}; if (rwork) rwork[0] "
                  f"= 1; if (info) *info = 0; return; }}\n"
                  f"  pheev_impl('{k}', jobz, uplo, *n, a, *ia, *ja, desca, "
                  f"w, {WE}, z, *iz, *jz, descz, info); }}\n")
        # PBLAS (no info argument, matching the real ABI)
        for mang in _manglings(f"p{k}gemm"):
            w(f"void {mang}(const char* transa, const char* transb, "
              f"const int* m, const int* n, const int* k, const {T}* alpha, "
              f"{T}* a, const int* ia, const int* ja, const int* desca, "
              f"{T}* b, const int* ib, const int* jb, const int* descb, "
              f"const {T}* beta, {T}* c, const int* ic, const int* jc, "
              f"const int* descc)\n"
              f"{{ pgemm_impl_{k}(transa, transb, *m, *n, *k, *alpha, "
              f"a, *ia, *ja, desca, b, *ib, *jb, descb, *beta, "
              f"c, *ic, *jc, descc); }}\n")
        for mang in _manglings(f"p{k}trsm"):
            w(f"void {mang}(const char* side, const char* uplo, "
              f"const char* transa, const char* diag, const int* m, "
              f"const int* n, const {T}* alpha, {T}* a, const int* ia, "
              f"const int* ja, const int* desca, {T}* b, const int* ib, "
              f"const int* jb, const int* descb)\n"
              f"{{ ptrXm_impl_{k}(1, side, uplo, transa, diag, *m, *n, "
              f"*alpha, a, *ia, *ja, desca, b, *ib, *jb, descb); }}\n")
        for mang in _manglings(f"p{k}trmm"):
            w(f"void {mang}(const char* side, const char* uplo, "
              f"const char* transa, const char* diag, const int* m, "
              f"const int* n, const {T}* alpha, {T}* a, const int* ia, "
              f"const int* ja, const int* desca, {T}* b, const int* ib, "
              f"const int* jb, const int* descb)\n"
              f"{{ ptrXm_impl_{k}(0, side, uplo, transa, diag, *m, *n, "
              f"*alpha, a, *ia, *ja, desca, b, *ib, *jb, descb); }}\n")
        for mang in _manglings(f"p{k}lange"):
            w(f"{WT} {mang}(const char* norm, const int* m, const int* n, "
              f"{T}* a, const int* ia, const int* ja, const int* desca, "
              f"{WT}* work)\n"
              f"{{ (void)work; return ({WT})plange_impl('{k}', norm, *m, "
              f"*n, a, *ia, *ja, desca); }}\n")
    return "\n".join(parts)


def main():
    with open(os.path.join(ROOT, "include", "slate_tpu_driver.h"), "w") as f:
        f.write(gen_header())
    os.makedirs(os.path.join(ROOT, "src", "c_api"), exist_ok=True)
    with open(os.path.join(ROOT, "src", "c_api", "driver_api.c"), "w") as f:
        f.write(gen_c_bodies())
    with open(os.path.join(ROOT, "fortran", "slate_tpu.f90"), "w") as f:
        f.write(gen_fortran())
    with open(os.path.join(ROOT, "src", "c_api", "scalapack_api.c"),
              "w") as f:
        f.write(gen_scalapack())
    n = sum(len(k) for _, k, _, _ in DRIVERS)
    print(f"generated {len(DRIVERS)} drivers, {n} typed entry points, "
          f"15 ScaLAPACK families x4 types x3 manglings")


if __name__ == "__main__":
    main()
