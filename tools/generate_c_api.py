#!/usr/bin/env python3
"""Generate the C driver API: typed s/d/c/z wrappers over the embedded
-CPython core call, plus the matching Fortran interface module.

The analog of the reference's generated C API
(``/root/reference/tools/c_api/generate_wrappers.py`` →
``include/slate/c_api/slate.h``, ``src/c_api/wrappers.cc``): one table
of drivers drives header, C bodies, and Fortran module generation.

Outputs (checked in; rerun on table changes):
  include/slate_tpu_driver.h   — typed driver declarations
  src/c_api/driver_api.c       — generated bodies over slate_c_call()
  fortran/slate_tpu.f90        — regenerated Fortran interfaces
"""

import os

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

CTYPES = {"s": "float", "d": "double",
          "c": "float _Complex", "z": "double _Complex"}
NPDT = {"s": "f32", "d": "f64", "c": "c64", "z": "c128"}

# (op, kinds, signature, outputs-doc)
# signature kinds:
#   ab_x    : in a(m,n), in b(n,nrhs) -> out0 x(n,nrhs)           [+info]
#   ab_xp   : like ab_x plus out1 ipiv(int64 n)
#   a_f     : in a(m,n) -> out0 factor(m,n)
#   a_fp    : a_f plus out1 ipiv(int64 min(m,n))
#   a_ft    : a_f plus out1 tau(double/complex min(m,n))
#   a_winv  : in a(n,n) -> out0 inverse(n,n)
#   a_eig   : in a(n,n) -> out0 w(double n), out1 z(n,n)
#   a_eigv  : in a(n,n) -> out0 w(double n)
#   a_svd   : in a(m,n) -> out0 s(double k), out1 u(m,k), out2 vt(k,n)
#   a_svdv  : in a(m,n) -> out0 s(double k)
#   ab_c    : in a, in b -> out0 c (gemm-like)
#   a_scal  : in a -> out0 scalar double
DRIVERS = [
    ("gesv", "sdcz", "ab_xp", "x = A^{-1} B, row pivots"),
    ("posv", "sdcz", "ab_x", "x = A^{-1} B, A HPD (uplo)"),
    ("hesv", "sdcz", "ab_x", "x = A^{-1} B, A Hermitian indefinite"),
    ("sysv", "sd", "ab_x", "x = A^{-1} B, A symmetric indefinite"),
    ("gels", "sdcz", "ab_x", "least-squares solution (m >= n)"),
    ("getrf", "sdcz", "a_fp", "packed LU + row permutation"),
    ("potrf", "sdcz", "a_f", "Cholesky factor in the stored triangle"),
    ("geqrf", "sdcz", "a_ft", "packed QR + taus"),
    ("gelqf", "sdcz", "a_ft", "packed LQ + taus"),
    ("getri", "sdcz", "a_winv", "inverse from LU"),
    ("potri", "sdcz", "a_winv", "inverse from Cholesky (uplo)"),
    ("trtri", "sdcz", "a_winv", "triangular inverse (uplo)"),
    ("heev", "sdcz", "a_eig", "eigenvalues + vectors (uplo)"),
    ("syev", "sd", "a_eig", "eigenvalues + vectors (uplo)"),
    ("heev_vals", "sdcz", "a_eigv", "eigenvalues only"),
    ("svd", "sdcz", "a_svd", "singular values + U + V^H"),
    ("svd_vals", "sdcz", "a_svdv", "singular values only"),
    ("gemm", "sdcz", "ab_c", "C = A B"),
    ("symm", "sd", "ab_c", "C = A B, A symmetric (uplo)"),
    ("hemm", "cz", "ab_c", "C = A B, A Hermitian (uplo)"),
    ("syrk", "sd", "a_f", "C = A A^T (uplo stored)"),
    ("herk", "cz", "a_f", "C = A A^H (uplo stored)"),
    ("trsm", "sdcz", "ab_c", "X = A^{-1} B, A triangular (uplo)"),
    ("trmm", "sdcz", "ab_c", "X = A B, A triangular (uplo)"),
    ("lange", "sdcz", "a_scal", "norm (norm char in `uplo` slot: M/1/I/F)"),
    ("gecondest", "sd", "a_scal", "1-norm condition estimate"),
]

SIGS = {
    "ab_x": ("int64_t m, int64_t n, const {T}* a, int64_t lda, "
             "int64_t nrhs, const {T}* b, int64_t ldb, {T}* x, "
             "char uplo",
             "m, n, a, lda, m, nrhs, b, ldb, x, NULL, NULL, uplo"),
    "ab_xp": ("int64_t m, int64_t n, const {T}* a, int64_t lda, "
              "int64_t nrhs, const {T}* b, int64_t ldb, {T}* x, "
              "int64_t* ipiv",
              "m, n, a, lda, m, nrhs, b, ldb, x, ipiv, NULL, 'L'"),
    "a_f": ("int64_t m, int64_t n, const {T}* a, int64_t lda, {T}* f, "
            "char uplo",
            "m, n, a, lda, 0, 0, NULL, 0, f, NULL, NULL, uplo"),
    "a_fp": ("int64_t m, int64_t n, const {T}* a, int64_t lda, {T}* f, "
             "int64_t* ipiv",
             "m, n, a, lda, 0, 0, NULL, 0, f, ipiv, NULL, 'L'"),
    "a_ft": ("int64_t m, int64_t n, const {T}* a, int64_t lda, {T}* f, "
             "{T}* tau",
             "m, n, a, lda, 0, 0, NULL, 0, f, tau, NULL, 'L'"),
    "a_winv": ("int64_t n, const {T}* a, int64_t lda, {T}* inv, char uplo",
               "n, n, a, lda, 0, 0, NULL, 0, inv, NULL, NULL, uplo"),
    "a_eig": ("int64_t n, const {T}* a, int64_t lda, double* w, {T}* z, "
              "char uplo",
              "n, n, a, lda, 0, 0, NULL, 0, w, z, NULL, uplo"),
    "a_eigv": ("int64_t n, const {T}* a, int64_t lda, double* w, char uplo",
               "n, n, a, lda, 0, 0, NULL, 0, w, NULL, NULL, uplo"),
    "a_svd": ("int64_t m, int64_t n, const {T}* a, int64_t lda, double* s, "
              "{T}* u, {T}* vt",
              "m, n, a, lda, 0, 0, NULL, 0, s, u, vt, 'L'"),
    "a_svdv": ("int64_t m, int64_t n, const {T}* a, int64_t lda, double* s",
               "m, n, a, lda, 0, 0, NULL, 0, s, NULL, NULL, 'L'"),
    "ab_c": ("int64_t m, int64_t k, const {T}* a, int64_t lda, int64_t n, "
             "const {T}* b, int64_t ldb, {T}* c, char uplo",
             "m, k, a, lda, k, n, b, ldb, c, NULL, NULL, uplo"),
    "a_scal": ("int64_t m, int64_t n, const {T}* a, int64_t lda, "
               "double* value, char norm",
               "m, n, a, lda, 0, 0, NULL, 0, value, NULL, NULL, norm"),
}


def gen_header():
    lines = [
        "/* slate_tpu driver C API — GENERATED by tools/generate_c_api.py;",
        " * do not edit.  The analog of the reference's generated",
        " * include/slate/c_api/slate.h: every driver callable from C,",
        " * s/d/c/z.  Matrices are COLUMN-major with leading dimension ld*;",
        " * outputs are caller-allocated.  Returns 0 on success.",
        " * Implementation: src/c_api/driver_api.c embeds CPython and runs",
        " * the full JAX/XLA driver (the TPU does the math).  Call",
        " * slate_c_init() once first; slate_c_finalize() at exit. */",
        "",
        "#ifndef SLATE_TPU_DRIVER_H",
        "#define SLATE_TPU_DRIVER_H",
        "",
        "#include <stdint.h>",
        "",
        "#ifdef __cplusplus",
        'extern "C" {',
        "#endif",
        "",
        "int slate_c_init(void);",
        "void slate_c_finalize(void);",
        "",
        "/* generic core: every typed wrapper funnels through this */",
        "int slate_c_call(const char* op, char dtype, int64_t m, int64_t n,",
        "                 const void* a, int64_t lda, int64_t m2, int64_t n2,",
        "                 const void* b, int64_t ldb, void* out0, void* out1,",
        "                 void* out2, char uplo);",
        "",
    ]
    for op, kinds, sig, doc in DRIVERS:
        lines.append(f"/* {op}: {doc} */")
        for kch in kinds:
            T = CTYPES[kch]
            decl = SIGS[sig][0].format(T=T)
            lines.append(f"int slate_{kch}{op}({decl});")
        lines.append("")
    lines += ["#ifdef __cplusplus", "}", "#endif", "",
              "#endif /* SLATE_TPU_DRIVER_H */", ""]
    return "\n".join(lines)


def gen_c_bodies():
    lines = [
        "/* GENERATED by tools/generate_c_api.py — do not edit.",
        " * Typed driver wrappers over slate_c_call() (core in",
        " * c_api_core.c).  Reference analog: src/c_api/wrappers.cc. */",
        "",
        '#include "slate_tpu_driver.h"',
        "#include <stddef.h>",
        "",
    ]
    for op, kinds, sig, _doc in DRIVERS:
        for kch in kinds:
            T = CTYPES[kch]
            decl = SIGS[sig][0].format(T=T)
            args = SIGS[sig][1]
            lines += [
                f"int slate_{kch}{op}({decl}) {{",
                f'    return slate_c_call("{op}", \'{kch}\', {args});',
                "}",
                "",
            ]
    return "\n".join(lines)


def gen_fortran():
    FT = {"s": "real(c_float)", "d": "real(c_double)",
          "c": "complex(c_float_complex)", "z": "complex(c_double_complex)"}
    lines = [
        "! slate_tpu Fortran module — GENERATED by tools/generate_c_api.py",
        "! (the analog of the reference's tools/fortran/",
        "! generate_fortran_module.py output).  Bindings over the C driver",
        "! API; matrices column-major, as Fortran wants them anyway.",
        "module slate_tpu",
        "    use iso_c_binding",
        "    implicit none",
        "",
        "    interface",
        "        function slate_c_init() bind(c, name='slate_c_init')",
        "            use iso_c_binding",
        "            integer(c_int) :: slate_c_init",
        "        end function",
        "        subroutine slate_c_finalize() "
        "bind(c, name='slate_c_finalize')",
        "        end subroutine",
    ]

    fsig = {
        "ab_x": ("m, n, a, lda, nrhs, b, ldb, x, uplo",
                 ["integer(c_int64_t), value :: m, n, lda, nrhs, ldb",
                  "{FT} :: a(lda,*), b(ldb,*), x(n,*)",
                  "character(kind=c_char), value :: uplo"]),
        "ab_xp": ("m, n, a, lda, nrhs, b, ldb, x, ipiv",
                  ["integer(c_int64_t), value :: m, n, lda, nrhs, ldb",
                   "{FT} :: a(lda,*), b(ldb,*), x(n,*)",
                   "integer(c_int64_t) :: ipiv(*)"]),
        "a_f": ("m, n, a, lda, f, uplo",
                ["integer(c_int64_t), value :: m, n, lda",
                 "{FT} :: a(lda,*), f(m,*)",
                 "character(kind=c_char), value :: uplo"]),
        "a_fp": ("m, n, a, lda, f, ipiv",
                 ["integer(c_int64_t), value :: m, n, lda",
                  "{FT} :: a(lda,*), f(m,*)",
                  "integer(c_int64_t) :: ipiv(*)"]),
        "a_ft": ("m, n, a, lda, f, tau",
                 ["integer(c_int64_t), value :: m, n, lda",
                  "{FT} :: a(lda,*), f(m,*), tau(*)"]),
        "a_winv": ("n, a, lda, inv, uplo",
                   ["integer(c_int64_t), value :: n, lda",
                    "{FT} :: a(lda,*), inv(n,*)",
                    "character(kind=c_char), value :: uplo"]),
        "a_eig": ("n, a, lda, w, z, uplo",
                  ["integer(c_int64_t), value :: n, lda",
                   "{FT} :: a(lda,*), z(n,*)",
                   "real(c_double) :: w(*)",
                   "character(kind=c_char), value :: uplo"]),
        "a_eigv": ("n, a, lda, w, uplo",
                   ["integer(c_int64_t), value :: n, lda",
                    "{FT} :: a(lda,*)",
                    "real(c_double) :: w(*)",
                    "character(kind=c_char), value :: uplo"]),
        "a_svd": ("m, n, a, lda, s, u, vt",
                  ["integer(c_int64_t), value :: m, n, lda",
                   "{FT} :: a(lda,*), u(m,*), vt(n,*)",
                   "real(c_double) :: s(*)"]),
        "a_svdv": ("m, n, a, lda, s",
                   ["integer(c_int64_t), value :: m, n, lda",
                    "{FT} :: a(lda,*)",
                    "real(c_double) :: s(*)"]),
        "ab_c": ("m, k, a, lda, n, b, ldb, c, uplo",
                 ["integer(c_int64_t), value :: m, k, lda, n, ldb",
                  "{FT} :: a(lda,*), b(ldb,*), c(m,*)",
                  "character(kind=c_char), value :: uplo"]),
        "a_scal": ("m, n, a, lda, value, norm",
                   ["integer(c_int64_t), value :: m, n, lda",
                    "{FT} :: a(lda,*)",
                    "real(c_double) :: value",
                    "character(kind=c_char), value :: norm"]),
    }

    for op, kinds, sig, _doc in DRIVERS:
        for kch in kinds:
            name = f"slate_{kch}{op}"
            argl, decls = fsig[sig]
            lines.append(f"        function {name}({argl}) &")
            lines.append(f"                bind(c, name='{name}')")
            lines.append("            use iso_c_binding")
            for d in decls:
                lines.append("            " + d.format(FT=FT[kch]))
            lines.append(f"            integer(c_int) :: {name}")
            lines.append("        end function")
    lines += ["    end interface", "end module slate_tpu", ""]
    return "\n".join(lines)




# ---------------------------------------------------------------------------
# Drop-in ScaLAPACK API (reference scalapack_api/: p?potrf/p?gesv/p?gemm
# with BLACS descriptors, 3 Fortran manglings each).
# ---------------------------------------------------------------------------

SCALAPACK_CORE = r"""/* slate_tpu ScaLAPACK compatibility API — GENERATED by
 * tools/generate_c_api.py; do not edit.
 *
 * Drop-in desc-based symbols (p?potrf / p?gesv / p?gemm, three Fortran
 * manglings each) over the embedded-CPython driver core, mirroring the
 * reference's scalapack_api/ (scalapack_potrf.cc:27-80 etc.).
 *
 * SINGLE-CONTROLLER BLACS EMULATION.  The reference runs one MPI rank
 * per grid cell; a JAX/TPU program is a single controller that owns
 * every device.  These stubs therefore implement the BLACS surface for
 * ONE process that plays all p*q ranks in sequence:
 *
 *   - Cblacs_gridinit(&ctxt, order, p, q) creates a virtual p x q grid.
 *   - Cblacs_gridinfo(ctxt, ...) reports the coordinates of the grid's
 *     CURRENT virtual rank (initially (0,0)).
 *   - Each p? routine call registers the current virtual rank's local
 *     buffer and advances the rank cursor; when the LAST rank of the
 *     grid has called (the SPMD program unrolled sequentially), the
 *     routine assembles the global matrix from the block-cyclic local
 *     pieces (numroc layout), runs the driver on the accelerator,
 *     scatters results back into every registered local buffer, and
 *     returns the real info.  Earlier (pending) registration calls
 *     return info = 0; their output buffers are valid once the final
 *     rank's call returns — the sequential-emulation analog of the
 *     collective completing.
 *   - On a 1 x 1 grid every call computes immediately: a true drop-in
 *     for serial ScaLAPACK usage.
 *
 * Submatrix offsets ia/ja must be 1 (whole-matrix operation), matching
 * the dominant ScaLAPACK usage; other values set *info = -900.
 */

#include "slate_tpu_driver.h"
#include <complex.h>
#include <stdlib.h>
#include <string.h>

/* ---------------- BLACS emulation ---------------- */

#define SLATE_MAX_CTXT 64
#define SLATE_MAX_RANKS 256

typedef struct { int p, q, cur, used; } blacs_ctx;
static blacs_ctx g_ctx[SLATE_MAX_CTXT];

/* forward decl: pending-collective table (defined below) */
typedef struct pending_s pending_t;
static void pend_abandon_ctxt(int ctxt);

static blacs_ctx* ctx_of(int ic) {
    if (ic < 0 || ic >= SLATE_MAX_CTXT || !g_ctx[ic].used) return 0;
    return &g_ctx[ic];
}

void Cblacs_pinfo(int* mypnum, int* nprocs) {
    if (mypnum) *mypnum = 0;
    if (nprocs) *nprocs = SLATE_MAX_RANKS;
}

void Cblacs_get(int ctxt, int what, int* val) {
    (void)ctxt; (void)what;
    if (val) *val = 0;   /* system default "context" handle */
}

void Cblacs_gridinit(int* ctxt, const char* order, int p, int q) {
    (void)order;   /* column-major rank order assumed, BLACS default */
    for (int i = 0; i < SLATE_MAX_CTXT; ++i) {
        if (!g_ctx[i].used) {
            g_ctx[i].used = 1; g_ctx[i].p = p; g_ctx[i].q = q;
            g_ctx[i].cur = 0;
            *ctxt = i;
            return;
        }
    }
    *ctxt = -1;
}

void Cblacs_gridinfo(int ctxt, int* np_row, int* np_col,
                     int* my_row, int* my_col) {
    blacs_ctx* c = ctx_of(ctxt);
    if (!c) { if (np_row) *np_row = -1; return; }
    if (np_row) *np_row = c->p;
    if (np_col) *np_col = c->q;
    /* column-major rank order: rank r -> (r % p, r / p).  The cursor
     * marks WHICH virtual rank the sequential program is currently
     * simulating; it advances on Cblacs_barrier (the natural "end of
     * this rank's turn" marker when an SPMD loop is unrolled), NOT on
     * p? calls — so a loop body may invoke several routines per rank. */
    if (my_row) *my_row = c->cur % c->p;
    if (my_col) *my_col = c->cur / c->p;
}

void Cblacs_gridexit(int ctxt) {
    blacs_ctx* c = ctx_of(ctxt);
    if (c) c->used = 0;
    /* abandon any half-registered collectives on this context so the
     * pending slots cannot leak (pend_get would otherwise return NULL
     * after 8 abandoned collectives) */
    pend_abandon_ctxt(ctxt);
}

void Cblacs_exit(int notdone) { (void)notdone; }

void Cblacs_barrier(int ctxt, const char* scope) {
    (void)scope;
    blacs_ctx* c = ctx_of(ctxt);
    if (c) c->cur = (c->cur + 1) % (c->p * c->q);
}

/* ---------------- numroc / descinit (3 manglings) ---------------- */

static int numroc_impl(int n, int nb, int iproc, int isrcproc, int nprocs) {
    int mydist = (nprocs + iproc - isrcproc) % nprocs;
    int nblocks = n / nb;
    int out = (nblocks / nprocs) * nb;
    int extra = nblocks % nprocs;
    if (mydist < extra) out += nb;
    else if (mydist == extra) out += n % nb;
    return out;
}

int numroc_(const int* n, const int* nb, const int* iproc,
            const int* isrcproc, const int* nprocs) {
    return numroc_impl(*n, *nb, *iproc, *isrcproc, *nprocs);
}
int numroc(const int* n, const int* nb, const int* iproc,
           const int* isrcproc, const int* nprocs) {
    return numroc_impl(*n, *nb, *iproc, *isrcproc, *nprocs);
}
int NUMROC(const int* n, const int* nb, const int* iproc,
           const int* isrcproc, const int* nprocs) {
    return numroc_impl(*n, *nb, *iproc, *isrcproc, *nprocs);
}

static void descinit_impl(int* desc, int m, int n, int mb, int nb,
                          int irsrc, int icsrc, int ctxt, int lld,
                          int* info) {
    desc[0] = 1; desc[1] = ctxt; desc[2] = m; desc[3] = n;
    desc[4] = mb; desc[5] = nb; desc[6] = irsrc; desc[7] = icsrc;
    desc[8] = lld;
    if (info) *info = 0;
}

void descinit_(int* desc, const int* m, const int* n, const int* mb,
               const int* nb, const int* irsrc, const int* icsrc,
               const int* ctxt, const int* lld, int* info) {
    descinit_impl(desc, *m, *n, *mb, *nb, *irsrc, *icsrc, *ctxt, *lld, info);
}
void descinit(int* desc, const int* m, const int* n, const int* mb,
              const int* nb, const int* irsrc, const int* icsrc,
              const int* ctxt, const int* lld, int* info) {
    descinit_impl(desc, *m, *n, *mb, *nb, *irsrc, *icsrc, *ctxt, *lld, info);
}
void DESCINIT(int* desc, const int* m, const int* n, const int* mb,
              const int* nb, const int* irsrc, const int* icsrc,
              const int* ctxt, const int* lld, int* info) {
    descinit_impl(desc, *m, *n, *mb, *nb, *irsrc, *icsrc, *ctxt, *lld, info);
}

/* ---------------- block-cyclic gather / scatter ---------------- */

#define D_CTXT(d) ((d)[1])
#define D_M(d)    ((d)[2])
#define D_N(d)    ((d)[3])
#define D_MB(d)   ((d)[4])
#define D_NB(d)   ((d)[5])
#define D_LLD(d)  ((d)[8])

/* copy between global (col-major, ld = M) and the (pr, pc) rank's local
 * buffer (col-major, ld = lld); dir 0 = local->global, 1 = global->local */
static void cyclic_copy(void* glob, void* loc, const int* desc, int lld,
                        int pr, int pc, int p, int q, int elem, int dir) {
    int M = D_M(desc), N = D_N(desc), MB = D_MB(desc), NB = D_NB(desc);
    int mloc = numroc_impl(M, MB, pr, 0, p);
    int nloc = numroc_impl(N, NB, pc, 0, q);
    char* g = (char*)glob; char* l = (char*)loc;
    for (int jl = 0; jl < nloc; ++jl) {
        int jg = ((jl / NB) * q + pc) * NB + jl % NB;
        for (int il0 = 0; il0 < mloc; il0 += MB) {
            int ig0 = ((il0 / MB) * p + pr) * MB;
            int len = mloc - il0 < MB ? mloc - il0 : MB;
            char* gp = g + ((size_t)jg * M + ig0) * elem;
            char* lp = l + ((size_t)jl * lld + il0) * elem;
            if (dir) memcpy(lp, gp, (size_t)len * elem);
            else memcpy(gp, lp, (size_t)len * elem);
        }
    }
}

/* ---------------- collective registration ---------------- */

struct pending_s {
    int tag;                       /* routine id, 0 = slot free */
    int ctxt;
    int nreg;                      /* registrations so far (rank order) */
    void* locals[SLATE_MAX_RANKS];     /* A local buffers, rank order */
    void* locals2[SLATE_MAX_RANKS];    /* B local buffers (solvers) */
    void* locals3[SLATE_MAX_RANKS];    /* C local buffers (gemm) */
    int*  ipivs[SLATE_MAX_RANKS];
    /* lld is the one per-rank descriptor field — captured per call */
    int llds[SLATE_MAX_RANKS];
    int llds2[SLATE_MAX_RANKS];
    int llds3[SLATE_MAX_RANKS];
};

static pending_t g_pend[8];

static void pend_abandon_ctxt(int ctxt) {
    for (int i = 0; i < 8; ++i)
        if (g_pend[i].ctxt == ctxt) g_pend[i].tag = 0;
}

static pending_t* pend_get(int tag, int ctxt) {
    for (int i = 0; i < 8; ++i)
        if (g_pend[i].tag == tag && g_pend[i].ctxt == ctxt)
            return &g_pend[i];
    for (int i = 0; i < 8; ++i)
        if (g_pend[i].tag == 0) {
            memset(&g_pend[i], 0, sizeof(pending_t));
            g_pend[i].tag = tag; g_pend[i].ctxt = ctxt;
            return &g_pend[i];
        }
    return 0;
}

static int elem_of(char dt) {
    switch (dt) { case 's': return 4; case 'd': return 8;
                  case 'c': return 8; case 'z': return 16; }
    return 0;
}

/* register this rank's buffers under the routine's OWN registration
 * counter (virtual ranks register in column-major rank order, the
 * natural unrolled-SPMD loop order); returns 1 when the grid is
 * complete — time to compute */
static int pend_step(pending_t* pe, blacs_ctx* c,
                     void* a, int lda, void* b, int ldb,
                     void* cc, int ldc, int* ipiv) {
    int r = pe->nreg;
    pe->locals[r] = a; pe->locals2[r] = b; pe->locals3[r] = cc;
    pe->ipivs[r] = ipiv;
    pe->llds[r] = lda; pe->llds2[r] = ldb; pe->llds3[r] = ldc;
    pe->nreg += 1;
    return pe->nreg == c->p * c->q;
}

/* ---------------- generic p? implementations ---------------- */

static int check_sub(int ia, int ja, int* info) {
    if (ia != 1 || ja != 1) { if (info) *info = -900; return 1; }
    return 0;
}

static void ppotrf_impl(char dt, const char* uplo, int n,
                        void* a, int ia, int ja, const int* desca,
                        int* info) {
    if (check_sub(ia, ja, info)) return;
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { *info = -901; return; }
    if (D_M(desca) != n || D_N(desca) != n) { *info = -902; return; }
    pending_t* pe = pend_get(1000 + dt, D_CTXT(desca));
    if (!pe) { *info = -903; return; }
    *info = 0;
    if (!pend_step(pe, c, a, D_LLD(desca), 0, 0, 0, 0, 0))
        return;   /* wait for the full grid */
    int elem = elem_of(dt);
    size_t gsz = (size_t)D_M(desca) * D_N(desca) * elem;
    char* glob = (char*)malloc(gsz);
    char* gout = (char*)malloc(gsz);
    for (int r = 0; r < c->p * c->q; ++r)
        cyclic_copy(glob, pe->locals[r], desca, pe->llds[r],
                    r % c->p, r / c->p, c->p, c->q, elem, 0);
    int rc = slate_c_call("potrf", dt, n, n, glob, n, 0, 0, 0, 0,
                          gout, 0, 0, uplo[0]);
    for (int r = 0; r < c->p * c->q; ++r)
        cyclic_copy(gout, pe->locals[r], desca, pe->llds[r],
                    r % c->p, r / c->p, c->p, c->q, elem, 1);
    free(glob); free(gout);
    pe->tag = 0;
    *info = rc;
}

static void pgesv_impl(char dt, int n, int nrhs,
                       void* a, int ia, int ja, const int* desca,
                       int* ipiv, void* b, int ib, int jb,
                       const int* descb, int* info) {
    if (check_sub(ia, ja, info) || check_sub(ib, jb, info)) return;
    blacs_ctx* c = ctx_of(D_CTXT(desca));
    if (!c) { *info = -901; return; }
    if (D_M(desca) != n || D_N(desca) != n
        || D_M(descb) != n || D_N(descb) != nrhs) { *info = -902; return; }
    pending_t* pe = pend_get(2000 + dt, D_CTXT(desca));
    if (!pe) { *info = -903; return; }
    *info = 0;
    if (!pend_step(pe, c, a, D_LLD(desca), b, D_LLD(descb), 0, 0, ipiv))
        return;
    int elem = elem_of(dt);
    size_t asz = (size_t)D_M(desca) * D_N(desca) * elem;
    size_t bsz = (size_t)D_M(descb) * D_N(descb) * elem;
    char* ag = (char*)malloc(asz); char* bg = (char*)malloc(bsz);
    char* lu = (char*)malloc(asz); char* xg = (char*)malloc(bsz);
    int64_t* piv = (int64_t*)malloc(sizeof(int64_t) * (size_t)n);
    for (int r = 0; r < c->p * c->q; ++r) {
        cyclic_copy(ag, pe->locals[r], desca, pe->llds[r],
                    r % c->p, r / c->p, c->p, c->q, elem, 0);
        cyclic_copy(bg, pe->locals2[r], descb, pe->llds2[r],
                    r % c->p, r / c->p, c->p, c->q, elem, 0);
    }
    int rc = slate_c_call("gesv_full", dt, n, n, ag, n, n, nrhs,
                          bg, n, lu, piv, xg, 'L');
    for (int r = 0; r < c->p * c->q; ++r) {
        int pr = r % c->p, pc_ = r / c->p;
        cyclic_copy(lu, pe->locals[r], desca, pe->llds[r], pr, pc_,
                    c->p, c->q, elem, 1);
        cyclic_copy(xg, pe->locals2[r], descb, pe->llds2[r], pr, pc_,
                    c->p, c->q, elem, 1);
        if (pe->ipivs[r]) {
            /* distributed ipiv: local row il of this process row holds
             * the global 1-based swap target of its global row */
            int MB = D_MB(desca);
            int mloc = numroc_impl(n, MB, pr, 0, c->p);
            for (int il = 0; il < mloc; ++il) {
                int igr = ((il / MB) * c->p + pr) * MB + il % MB;
                if (igr < n) pe->ipivs[r][il] = (int)piv[igr];
            }
        }
    }
    free(ag); free(bg); free(lu); free(xg); free(piv);
    pe->tag = 0;
    *info = rc;
}
"""

PGEMM_IMPL = r"""
/* typed alpha*op(A)*op(B) + beta*C combine + op() builders */
static void opmat_{k}(char tr, int m, int n, const {T}* g, {T}* out) {{
    /* g is (m x n) col-major; out is op(g): N -> copy, T/C -> (n x m) */
    if (tr == 'N' || tr == 'n') {{
        memcpy(out, g, sizeof({T}) * (size_t)m * n);
        return;
    }}
    for (int j = 0; j < n; ++j)
        for (int i = 0; i < m; ++i) {{
            {T} v = g[(size_t)j * m + i];
            out[(size_t)i * n + j] = {CONJ};
        }}
}}

static void pgemm_impl_{k}(const char* transa, const char* transb,
                           int m, int n, int k, {T} alpha,
                           {T}* a, int ia, int ja, const int* desca,
                           {T}* b, int ib, int jb, const int* descb,
                           {T} beta,
                           {T}* cc, int ic, int jc, const int* descc,
                           int* info) {{
    if (check_sub(ia, ja, info) || check_sub(ib, jb, info)
        || check_sub(ic, jc, info)) return;
    blacs_ctx* c = ctx_of(D_CTXT(descc));
    if (!c) {{ *info = -901; return; }}
    int opa = (transa[0] == 'N' || transa[0] == 'n');
    int opb = (transb[0] == 'N' || transb[0] == 'n');
    if (D_M(desca) != (opa ? m : k) || D_N(desca) != (opa ? k : m)
        || D_M(descb) != (opb ? k : n) || D_N(descb) != (opb ? n : k)
        || D_M(descc) != m || D_N(descc) != n) {{ *info = -902; return; }}
    pending_t* pe = pend_get(3000 + (int)'{k}', D_CTXT(descc));
    if (!pe) {{ *info = -903; return; }}
    *info = 0;
    if (!pend_step(pe, c, a, D_LLD(desca), b, D_LLD(descb),
                   cc, D_LLD(descc), 0)) return;
    int elem = (int)sizeof({T});
    int Am = D_M(desca), An = D_N(desca);
    int Bm = D_M(descb), Bn = D_N(descb);
    {T}* ag = ({T}*)malloc(sizeof({T}) * (size_t)Am * An);
    {T}* bg = ({T}*)malloc(sizeof({T}) * (size_t)Bm * Bn);
    {T}* cg = ({T}*)malloc(sizeof({T}) * (size_t)m * n);
    {T}* oa = ({T}*)malloc(sizeof({T}) * (size_t)m * k);
    {T}* ob = ({T}*)malloc(sizeof({T}) * (size_t)k * n);
    {T}* pg = ({T}*)malloc(sizeof({T}) * (size_t)m * n);
    for (int r = 0; r < c->p * c->q; ++r) {{
        cyclic_copy(ag, pe->locals[r], desca, pe->llds[r],
                    r % c->p, r / c->p, c->p, c->q, elem, 0);
        cyclic_copy(bg, pe->locals2[r], descb, pe->llds2[r],
                    r % c->p, r / c->p, c->p, c->q, elem, 0);
        cyclic_copy(cg, pe->locals3[r], descc, pe->llds3[r],
                    r % c->p, r / c->p, c->p, c->q, elem, 0);
    }}
    opmat_{k}(transa[0], Am, An, ag, oa);
    opmat_{k}(transb[0], Bm, Bn, bg, ob);
    int rc = slate_c_call("gemm", '{k}', m, k, oa, m, k, n, ob, k,
                          pg, 0, 0, 'L');
    for (size_t i = 0; i < (size_t)m * n; ++i)
        cg[i] = alpha * pg[i] + beta * cg[i];
    for (int r = 0; r < c->p * c->q; ++r)
        cyclic_copy(cg, pe->locals3[r], descc, pe->llds3[r],
                    r % c->p, r / c->p, c->p, c->q, elem, 1);
    free(ag); free(bg); free(cg); free(oa); free(ob); free(pg);
    pe->tag = 0;
    *info = rc;
}}
"""


def gen_scalapack():
    lines = [SCALAPACK_CORE]
    for k in "sdcz":
        T = CTYPES[k]
        if k == "c":
            conj = "((tr == 'C' || tr == 'c') ? conjf(v) : v)"
        elif k == "z":
            conj = "((tr == 'C' || tr == 'c') ? conj(v) : v)"
        else:
            conj = "v"
        lines.append(PGEMM_IMPL.format(k=k, T=T, CONJ=conj))
    # the 3-mangled typed wrappers
    for k in "sdcz":
        T = CTYPES[k]
        for name in (f"p{k}potrf",):
            for mang in (name.upper(), name, name + "_"):
                lines.append(
                    f"void {mang}(const char* uplo, const int* n, {T}* a, "
                    f"const int* ia, const int* ja, const int* desca, "
                    f"int* info)\n"
                    f"{{ ppotrf_impl('{k}', uplo, *n, a, *ia, *ja, desca, "
                    f"info); }}\n")
        for name in (f"p{k}gesv",):
            for mang in (name.upper(), name, name + "_"):
                lines.append(
                    f"void {mang}(const int* n, const int* nrhs, {T}* a, "
                    f"const int* ia, const int* ja, const int* desca, "
                    f"int* ipiv, {T}* b, const int* ib, const int* jb, "
                    f"const int* descb, int* info)\n"
                    f"{{ pgesv_impl('{k}', *n, *nrhs, a, *ia, *ja, desca, "
                    f"ipiv, b, *ib, *jb, descb, info); }}\n")
        for name in (f"p{k}gemm",):
            for mang in (name.upper(), name, name + "_"):
                lines.append(
                    f"void {mang}(const char* transa, const char* transb, "
                    f"const int* m, const int* n, const int* k, "
                    f"const {T}* alpha, {T}* a, const int* ia, "
                    f"const int* ja, const int* desca, {T}* b, "
                    f"const int* ib, const int* jb, const int* descb, "
                    f"const {T}* beta, {T}* c, const int* ic, "
                    f"const int* jc, const int* descc, int* info)\n"
                    f"{{ pgemm_impl_{k}(transa, transb, *m, *n, *k, *alpha, "
                    f"a, *ia, *ja, desca, b, *ib, *jb, descb, *beta, "
                    f"c, *ic, *jc, descc, info); }}\n")
    return "\n".join(lines)


def main():
    with open(os.path.join(ROOT, "include", "slate_tpu_driver.h"), "w") as f:
        f.write(gen_header())
    os.makedirs(os.path.join(ROOT, "src", "c_api"), exist_ok=True)
    with open(os.path.join(ROOT, "src", "c_api", "driver_api.c"), "w") as f:
        f.write(gen_c_bodies())
    with open(os.path.join(ROOT, "fortran", "slate_tpu.f90"), "w") as f:
        f.write(gen_fortran())
    with open(os.path.join(ROOT, "src", "c_api", "scalapack_api.c"),
              "w") as f:
        f.write(gen_scalapack())
    n = sum(len(k) for _, k, _, _ in DRIVERS)
    print(f"generated {len(DRIVERS)} drivers, {n} typed entry points")


if __name__ == "__main__":
    main()
