"""getrf_rec with pallas panels: end-to-end slope timing."""
import time, sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax, jax.numpy as jnp
from jax import lax
from slate_tpu.linalg.lu import getrf_rec, _panel_lu

def P(*a): print(*a, flush=True)

def slope(fbody, x0, K1=2, K2=10, N=4):
    def mk(K):
        @jax.jit
        def g(x):
            def body(i, xx):
                return fbody(xx)
            return lax.fori_loop(0, K, body, x)
        return g
    res = []
    for K in (K1, K2):
        g = mk(K)
        x = g(x0); float(jnp.asarray(x).ravel()[-1])
        ts = []
        for _ in range(N):
            t0 = time.perf_counter()
            x = g(x0); float(jnp.asarray(x).ravel()[-1])
            ts.append(time.perf_counter() - t0)
        res.append(min(ts))
    return (res[1] - res[0]) / (K2 - K1)

n = 8192
key = jax.random.PRNGKey(0)
a = jax.random.normal(key, (n, n), jnp.float32) + n * jnp.eye(n, dtype=jnp.float32)

for nb in (512,):
    f = lambda x: x + getrf_rec(x, nb)[0] * jnp.float32(1e-30)
    t = slope(f, a)
    P("getrf_rec nb=%-4d pallas-leaf  %7.1f ms  %5.1f TF/s (%4.1f%% of 53.4)"
      % (nb, t*1e3, 2*n**3/3/t/1e12, 100*2*n**3/3/t/53.4e12))
