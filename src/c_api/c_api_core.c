/* slate_tpu C API core: one generic entry funnels every generated typed
 * wrapper (driver_api.c) into the Python bridge
 * (slate_tpu.api.c_bridge.call), which runs the full JAX/XLA driver.
 * Reference analog: src/c_api/wrappers.cc calls the C++ templates; here
 * the compute path is JAX, so the shim embeds CPython — the accelerator
 * still does the math.
 *
 * build:  gcc -shared -fPIC c_api_core.c driver_api.c -I../../include \
 *             $(python3-config --includes --embed --ldflags) \
 *             -o libslate_tpu_c.so
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static PyObject* g_call = NULL;   /* slate_tpu.api.c_bridge.call */
static int g_we_initialized = 0;

int slate_c_init(void) {
    if (g_call) return 0;
    if (!Py_IsInitialized()) {
        Py_Initialize();
        g_we_initialized = 1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject* mod = PyImport_ImportModule("slate_tpu.api.c_bridge");
    if (!mod) { PyErr_Print(); PyGILState_Release(st); return 1; }
    g_call = PyObject_GetAttrString(mod, "call");
    Py_DECREF(mod);
    PyGILState_Release(st);
    return g_call ? 0 : 1;
}

void slate_c_finalize(void) {
    if (g_call) { Py_XDECREF(g_call); g_call = NULL; }
    if (g_we_initialized && Py_IsInitialized()) Py_Finalize();
}

/* dtype char -> (numpy letter code, element bytes) */
static int dt_info(char d, char* np_code, int64_t* elem) {
    switch (d) {
        case 's': *np_code = 'f'; *elem = 4; return 0;   /* float32 */
        case 'd': *np_code = 'd'; *elem = 8; return 0;
        case 'c': *np_code = 'F'; *elem = 8; return 0;   /* complex64 */
        case 'z': *np_code = 'D'; *elem = 16; return 0;
    }
    return 1;
}

/* Build a numpy array (copy) from a column-major C buffer: produced as
 * np.ndarray of shape (n, m)? No: we hand the bridge an array of shape
 * (m, n) in Fortran order by building from a transposed C-order copy. */
static PyObject* np_from_colmajor(char np_code, int64_t m, int64_t n,
                                  const void* a, int64_t lda,
                                  int64_t elem) {
    /* make a contiguous (n, m) C-order buffer = the transpose view the
     * bridge expects (it transposes back to logical (m, n)) */
    PyObject* np = PyImport_ImportModule("numpy");
    if (!np) return NULL;
    char code[2] = {np_code, 0};
    PyObject* dt = PyObject_CallMethod(np, "dtype", "s", code);
    PyObject* bytes = PyBytes_FromStringAndSize(NULL, (Py_ssize_t)(m * n * elem));
    if (!bytes || !dt) { Py_XDECREF(dt); Py_XDECREF(bytes); Py_DECREF(np); return NULL; }
    char* dst = PyBytes_AS_STRING(bytes);
    const char* src = (const char*)a;
    for (int64_t c = 0; c < n; ++c)
        memcpy(dst + c * m * elem, src + c * lda * elem, (size_t)(m * elem));
    /* frombuffer -> shape (n, m) C-order == (m, n) column-major data */
    PyObject* flat = PyObject_CallMethod(np, "frombuffer", "OO", bytes, dt);
    Py_DECREF(bytes); Py_DECREF(dt);
    if (!flat) { Py_DECREF(np); return NULL; }
    PyObject* shaped = PyObject_CallMethod(flat, "reshape", "(LL)",
                                           (long long)n, (long long)m);
    Py_DECREF(flat); Py_DECREF(np);
    return shaped;   /* bridge receives the (n, m) transpose view */
}

/* Copy one returned array (any shape, C-order) into the caller's buffer.
 * The bridge returns arrays already transposed so that a flat C-order
 * copy IS the caller's column-major layout. */
static int copy_out(PyObject* arr, void* out) {
    if (!out || arr == Py_None) return 0;
    PyObject* np = PyImport_ImportModule("numpy");
    PyObject* contig = PyObject_CallMethod(np, "ascontiguousarray", "O", arr);
    Py_DECREF(np);
    if (!contig) return 1;
    PyObject* tob = PyObject_CallMethod(contig, "tobytes", NULL);
    Py_DECREF(contig);
    if (!tob) return 1;
    memcpy(out, PyBytes_AS_STRING(tob), (size_t)PyBytes_GET_SIZE(tob));
    Py_DECREF(tob);
    return 0;
}

int slate_c_call(const char* op, char dtype, int64_t m, int64_t n,
                 const void* a, int64_t lda, int64_t m2, int64_t n2,
                 const void* b, int64_t ldb, void* out0, void* out1,
                 void* out2, char uplo) {
    if (slate_c_init()) return -1;
    char np_code; int64_t elem;
    if (dt_info(dtype, &np_code, &elem)) return -2;
    PyGILState_STATE st = PyGILState_Ensure();
    int rc = 0;
    PyObject *pa = NULL, *pb = NULL, *res = NULL;
    pa = np_from_colmajor(np_code, m, n, a, lda ? lda : m, elem);
    if (!pa) { rc = -3; goto done; }
    if (b) {
        pb = np_from_colmajor(np_code, m2, n2, b, ldb ? ldb : m2, elem);
        if (!pb) { rc = -3; goto done; }
    } else {
        pb = Py_None; Py_INCREF(pb);
    }
    {
        char us[2] = {uplo ? uplo : 'L', 0};
        res = PyObject_CallFunction(g_call, "sOOss", op, pa, pb, us, us);
    }
    if (!res) { PyErr_Print(); rc = -4; goto done; }
    {
        void* outs[3] = {out0, out1, out2};
        Py_ssize_t cnt = PyTuple_Check(res) ? PyTuple_GET_SIZE(res) : 0;
        for (Py_ssize_t i = 0; i < cnt && i < 3; ++i)
            if (copy_out(PyTuple_GET_ITEM(res, i), outs[i])) { rc = -5; break; }
    }
done:
    Py_XDECREF(pa); Py_XDECREF(pb); Py_XDECREF(res);
    PyGILState_Release(st);
    return rc;
}
