#!/usr/bin/env python
"""testsweeper-style routine tester for slate_tpu.

The analog of the reference's ``./tester`` binary (``test/test.cc:83,783``
driven by testsweeper): one registered tester per routine, a parameter
sweep over dims/types/blocking, wall-clock + model-GFLOP/s reporting, and
a residual gate per routine (the reference's ``≤ 3ε`` criterion,
``test/test_gemm.cc:248-260``), with optional ``--ref`` comparison
against NumPy/SciPy (standing in for ScaLAPACK, ``test/test_gemm.cc:263``).

Usage:
  python tester.py gemm --dim 512:2048:512 --type s,d --nb 256
  python tester.py potrf --dim 1024 --type s --repeat 3
  python tester.py gesv --dim 100,300 --type d --check y --ref y
  python tester.py --list
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

# ---------------------------------------------------------------------------
# Parameter sweep plumbing (testsweeper's --dim start:stop:step grammar)
# ---------------------------------------------------------------------------

TYPE_MAP = {"s": "float32", "d": "float64", "c": "complex64", "z": "complex128"}


def parse_dims(spec: str):
    out = []
    for part in spec.split(","):
        if ":" in part:
            pieces = [int(x) for x in part.split(":")]
            start, stop = pieces[0], pieces[1]
            step = pieces[2] if len(pieces) > 2 else max(1, stop - start)
            out.extend(range(start, stop + 1, step))
        else:
            out.append(int(part))
    return out


def eps_of(dtype):
    return np.finfo(np.dtype(dtype).name.replace("complex64", "float32")
                    .replace("complex128", "float64")).eps


# ---------------------------------------------------------------------------
# Flop models (the reference's params.gflops() counts)
# ---------------------------------------------------------------------------

def fl_gemm(m, n, k):
    return 2.0 * m * n * k


FLOPS = {
    "gemm": lambda p: fl_gemm(p["m"], p["n"], p["k"]),
    "symm": lambda p: fl_gemm(p["m"], p["n"], p["m"]),
    "hemm": lambda p: fl_gemm(p["m"], p["n"], p["m"]),
    "syrk": lambda p: p["n"] * p["n"] * p["k"],
    "herk": lambda p: p["n"] * p["n"] * p["k"],
    "syr2k": lambda p: 2.0 * p["n"] * p["n"] * p["k"],
    "her2k": lambda p: 2.0 * p["n"] * p["n"] * p["k"],
    "trmm": lambda p: p["m"] * p["m"] * p["n"],
    "trsm": lambda p: p["m"] * p["m"] * p["n"],
    "potrf": lambda p: p["n"] ** 3 / 3.0,
    "potrs": lambda p: 2.0 * p["n"] ** 2 * p["nrhs"],
    "posv": lambda p: p["n"] ** 3 / 3.0 + 2.0 * p["n"] ** 2 * p["nrhs"],
    "getrf": lambda p: 2.0 * p["n"] ** 3 / 3.0,
    "trtri": lambda p: p["n"] ** 3 / 3.0,
    "potri": lambda p: 2.0 * p["n"] ** 3 / 3.0,
    "posv_mixed": lambda p: p["n"] ** 3 / 3.0,
    "gelqf": lambda p: 2.0 * p["n"] * p["m"] ** 2 - 2.0 * p["m"] ** 3 / 3.0,
    "gesv": lambda p: 2.0 * p["n"] ** 3 / 3.0 + 2.0 * p["n"] ** 2 * p["nrhs"],
    "gesv_mixed": lambda p: 2.0 * p["n"] ** 3 / 3.0,
    "getri": lambda p: 2.0 * p["n"] ** 3,
    "geqrf": lambda p: 2.0 * p["m"] * p["n"] ** 2 - 2.0 * p["n"] ** 3 / 3.0,
    "gels": lambda p: 2.0 * p["m"] * p["n"] ** 2,
    "cholqr": lambda p: p["m"] * p["n"] ** 2 + p["n"] ** 3 / 3.0,
    "heev": lambda p: 4.0 * p["n"] ** 3 / 3.0,
    "svd": lambda p: 8.0 * p["n"] ** 3 / 3.0,
    "hesv": lambda p: p["n"] ** 3 / 3.0,
    "gbsv": lambda p: 2.0 * p["n"] * p["kl"] * p["ku"],
    "norm": lambda p: p["m"] * p["n"],
    "pgemm": lambda p: fl_gemm(p["m"], p["n"], p["k"]),
    "unmqr": lambda p: 4.0 * p["m"] * p["n"] * p["n"],
    "unmlq": lambda p: 4.0 * p["m"] * p["n"] * p["n"],
    "ungqr": lambda p: 4.0 * p["m"] * p["n"] * p["n"] / 2.0,
    "hegv": lambda p: 14.0 * p["n"] ** 3 / 3.0,
    "hegst": lambda p: p["n"] ** 3,
    "heev_vals": lambda p: 4.0 * p["n"] ** 3 / 3.0,
    "svd_vals": lambda p: 8.0 * p["n"] ** 3 / 3.0,
    "gbmm": lambda p: 2.0 * p["m"] * p["n"] * (2 * p["kl"] + 1),
    "hbmm": lambda p: 2.0 * p["n"] * p["n"] * (2 * p["kl"] + 1),
    "tbsm": lambda p: 2.0 * p["m"] * p["kl"] * p["nrhs"],
    "gemmA": lambda p: fl_gemm(p["m"], p["n"], p["k"]),
    "trsmA": lambda p: p["m"] * p["m"] * p["n"],
    "he2hb": lambda p: 4.0 * p["n"] ** 3 / 3.0,
    "ge2tb": lambda p: 4.0 * p["n"] ** 3 / 3.0,
    "hb2st": lambda p: 6.0 * p["n"] ** 2 * p["nb"],
    "tb2bd": lambda p: 6.0 * p["n"] ** 2 * p["nb"],
    "gecondest": lambda p: 2.0 * p["n"] ** 2,
    "pocondest": lambda p: 2.0 * p["n"] ** 2,
    "trcondest": lambda p: p["n"] ** 2,
    "getrf_nopiv": lambda p: 2.0 * p["n"] ** 3 / 3.0,
    "getrf_tntpiv": lambda p: 2.0 * p["n"] ** 3 / 3.0,
    "pbsv": lambda p: 2.0 * p["n"] * p["kl"] ** 2,
    "gels_qr": lambda p: 2.0 * p["m"] * p["n"] ** 2,
    "gels_cholqr": lambda p: p["m"] * p["n"] ** 2,
    "ptrsm": lambda p: p["m"] * p["m"] * p["nrhs"],
    "pgelqf": lambda p: 2.0 * p["m"] * p["n"] ** 2 - 2.0 * p["n"] ** 3 / 3.0,
    "pgetri": lambda p: 2.0 * p["n"] ** 3,
    "pgbsv": lambda p: 2.0 * p["n"] * p["kl"] ** 2,
    "ppbsv": lambda p: 2.0 * p["n"] * p["kl"] ** 2,
    "pgecondest": lambda p: 2.0 * p["n"] ** 2,
    "ppotrf": lambda p: p["n"] ** 3 / 3.0,
    "pgesv": lambda p: 2.0 * p["n"] ** 3 / 3.0,
    "pgeqrf": lambda p: 2.0 * p["m"] * p["n"] ** 2 - 2.0 * p["n"] ** 3 / 3.0,
    "pheev": lambda p: 4.0 * p["n"] ** 3 / 3.0,
    "psvd": lambda p: 8.0 * p["n"] ** 3 / 3.0,
}


# ---------------------------------------------------------------------------
# Testers: each returns (run_fn, check_fn, ref_fn)
#   run_fn()            -> result (jax pytree; timed)
#   check_fn(result)    -> scaled residual (gate: < 3, in units of eps*n)
#   ref_fn(result)      -> max abs diff vs NumPy/SciPy reference or None
# ---------------------------------------------------------------------------

def _norms(*arrays):
    return [np.linalg.norm(np.asarray(x)) for x in arrays]


def make_tester(routine, p, jnp, st):
    dt = p["dtype"]
    m, n, k, nrhs, nb = p["m"], p["n"], p["k"], p["nrhs"], p["nb"]
    eps = eps_of(dt)
    opts = {"nb": nb}
    rng = np.random.default_rng(p["seed"])

    def arr(x):
        return np.asarray(x)

    def randn(shape):
        a = rng.standard_normal(shape)
        if np.dtype(dt).kind == "c":
            a = a + 1j * rng.standard_normal(shape)
        return jnp.asarray(a.astype(dt))

    def herm(nn):
        a = randn((nn, nn))
        return (a + jnp.conj(a.T)) / 2 + nn * jnp.eye(nn, dtype=dt)

    if routine == "gemm":
        a, b, c = randn((m, k)), randn((k, n)), randn((m, n))
        run = lambda: st.gemm(1.0, a, b, 1.0, c, opts)
        def check(out):
            na, nb_, nc = _norms(a, b, c)
            r = np.linalg.norm(arr(out) - (arr(a) @ arr(b) + arr(c)))
            return r / ((na * nb_ + nc) * eps * k)
        return run, check, None

    if routine in ("symm", "hemm"):
        if routine == "symm":
            x = randn((m, m))
            a = (x + x.T) / 2
        else:
            a = herm(m)
        b, c = randn((m, n)), randn((m, n))
        fn = getattr(st, routine)
        run = lambda: fn(st.Side.Left, 1.0, a, b, 1.0, c, opts)
        def check(out):
            na, nb_, nc = _norms(a, b, c)
            r = np.linalg.norm(arr(out) - (arr(a) @ arr(b) + arr(c)))
            return r / ((na * nb_ + nc) * eps * m)
        return run, check, None

    if routine in ("syrk", "herk", "syr2k", "her2k"):
        a, b = randn((n, k)), randn((n, k))
        if routine.startswith("her"):
            c0 = herm(n)
        else:
            x = randn((n, n))
            c0 = (x + x.T) / 2
        fn = getattr(st, routine)
        two = routine.endswith("2k")
        tr = (lambda x: np.conj(x.T)) if routine.startswith("her") else (lambda x: x.T)
        run = (lambda: fn(1.0, a, b, 1.0, c0, opts)) if two else \
              (lambda: fn(1.0, a, 1.0, c0, opts))
        def check(out):
            an, cn = _norms(a, c0)
            if two:
                ref = arr(a) @ tr(arr(b)) + arr(b) @ tr(arr(a)) + arr(c0)
            else:
                ref = arr(a) @ tr(arr(a)) + arr(c0)
            got = arr(getattr(out, "array", out))
            # rank-k drivers update only the stored (lower) triangle
            r = np.linalg.norm(np.tril(got) - np.tril(ref))
            return r / ((an * an + cn) * eps * k)
        return run, check, None

    if routine in ("trmm", "trsm"):
        a = jnp.tril(randn((m, m))) + 2 * m * jnp.eye(m, dtype=dt)
        b = randn((m, n))
        A = st.TriangularMatrix(a, uplo=st.Uplo.Lower, diag=st.Diag.NonUnit,
                                mb=nb, nb=nb)
        fn = getattr(st, routine)
        run = lambda: fn(st.Side.Left, 1.0, A, b, opts)
        def check(out):
            o = arr(getattr(out, "array", out))
            if routine == "trsm":
                r = np.linalg.norm(arr(a) @ o - arr(b))
            else:
                r = np.linalg.norm(o - arr(a) @ arr(b))
            na, nb_ = _norms(a, b)
            return r / (na * max(np.linalg.norm(o), nb_) * eps * m)
        return run, check, None

    if routine == "norm":
        a = randn((m, n))
        run = lambda: [st.norm(w, a) for w in
                       (st.Norm.Max, st.Norm.One, st.Norm.Inf, st.Norm.Fro)]
        def check(out):
            mx, one, inf, fro = [float(x) for x in out]
            refs = [np.abs(arr(a)).max(), np.linalg.norm(arr(a), 1),
                    np.linalg.norm(arr(a), np.inf), np.linalg.norm(arr(a))]
            return max(abs(g - r) / (r + 1e-300) for g, r in
                       zip((mx, one, inf, fro), refs)) / eps
        return run, check, None

    if routine == "trtri":
        a = jnp.tril(randn((n, n))) + 2 * n * jnp.eye(n, dtype=dt)
        A = st.TriangularMatrix(a, uplo=st.Uplo.Lower, diag=st.Diag.NonUnit,
                                mb=nb, nb=nb)
        run = lambda: st.trtri(A, opts)
        def check(out):
            inv = arr(getattr(out, "array", out))
            r = np.linalg.norm(np.tril(inv) @ arr(a) - np.eye(n))
            return r / (eps * n * np.linalg.cond(arr(a), 1))
        return run, check, None

    if routine == "potri":
        a = herm(n)
        A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=nb, nb=nb)
        fac = st.potrf(A, opts)
        run = lambda: st.potri(fac, opts)
        def check(out):
            inv = arr(out.array)
            inv = np.tril(inv) + np.conj(np.tril(inv, -1)).T
            r = np.linalg.norm(inv @ arr(a) - np.eye(n))
            return r / (eps * n * np.linalg.cond(arr(a), 1))
        return run, check, None

    if routine == "posv_mixed":
        a = herm(n)
        b = randn((n, nrhs))
        A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=nb, nb=nb)
        run = lambda: st.posv_mixed(A, b, opts)
        def check(out):
            x = arr(out[0])
            r = np.linalg.norm(arr(a) @ x - arr(b))
            return r / (np.linalg.norm(arr(a)) * np.linalg.norm(x) * eps * n)
        return run, check, None

    if routine == "gelqf":
        a = randn((m, n))
        run = lambda: st.gelqf(a, opts)
        def check(out):
            packed, taus = out
            pv = arr(getattr(packed, "array", packed))
            k = min(m, n)
            lfac = np.tril(pv)[:k, :k]
            lref = np.linalg.qr(np.conj(arr(a).T))[1]
            return (np.abs(np.abs(lfac) - np.abs(np.conj(lref.T))[:k, :k]).max()
                    / (np.linalg.norm(arr(a)) * eps * max(n, 1)))
        return run, check, None

    if routine in ("potrf", "posv", "potrs"):
        a = herm(n)
        b = randn((n, nrhs))
        A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=nb, nb=nb)
        if routine == "potrf":
            run = lambda: st.potrf(A, opts)
            def check(out):
                l = arr(out.array)
                r = np.linalg.norm(np.tril(l) @ np.conj(np.tril(l)).T - arr(a))
                return r / (np.linalg.norm(arr(a)) * eps * n)
            ref = lambda out: np.abs(np.tril(arr(out.array))
                                     - np.linalg.cholesky(arr(a))).max()
            return run, check, ref
        if routine == "potrs":
            fac = st.potrf(A, opts)
            run = lambda: st.potrs(fac, b, opts)
            def check(out):
                x = arr(getattr(out, "array", out))
                r = np.linalg.norm(arr(a) @ x - arr(b))
                return r / (np.linalg.norm(arr(a)) * np.linalg.norm(x)
                            * eps * n)
            return run, check, None
        run = lambda: st.posv(A, b, opts)
        def check(out):
            x = arr(out[1])
            r = np.linalg.norm(arr(a) @ x - arr(b))
            nx, nb_ = _norms(x, b)
            return r / (np.linalg.norm(arr(a)) * nx * eps * n)
        ref = lambda out: np.abs(arr(out[1])
                                 - np.linalg.solve(arr(a), arr(b))).max()
        return run, check, ref

    if routine in ("getrf", "gesv", "gesv_mixed", "getri"):
        a = randn((n, n)) + n * jnp.eye(n, dtype=dt)
        b = randn((n, nrhs))
        if routine == "getrf":
            run = lambda: st.getrf(a, opts)
            def check(out):
                lu, perm = out
                luv = arr(getattr(lu, "array", lu))
                l = np.tril(luv, -1) + np.eye(n)
                u = np.triu(luv)
                r = np.linalg.norm(arr(a)[np.asarray(perm)] - l @ u)
                return r / (np.linalg.norm(arr(a)) * eps * n)
            return run, check, None
        if routine == "getri":
            lu, perm = st.getrf(a, opts)
            run = lambda: st.getri(lu, perm, opts)
            def check(out):
                r = np.linalg.norm(arr(getattr(out, "array", out)) @ arr(a)
                                   - np.eye(n))
                return r / (eps * n * np.linalg.cond(arr(a), 1))
            return run, check, None
        fn = st.gesv if routine == "gesv" else st.gesv_mixed
        run = lambda: fn(a, b, opts)
        def check(out):
            x = arr(out[-1] if routine == "gesv" else out[0])
            r = np.linalg.norm(arr(a) @ x - arr(b))
            return r / (np.linalg.norm(arr(a)) * np.linalg.norm(x) * eps * n)
        ref = lambda out: np.abs(arr(out[-1] if routine == "gesv" else out[0])
                                 - np.linalg.solve(arr(a), arr(b))).max()
        return run, check, ref

    if routine in ("geqrf", "cholqr", "gels"):
        a = randn((m, n))
        b = randn((m, nrhs))
        if routine == "geqrf":
            run = lambda: st.geqrf(a, opts)
            def check(out):
                packed, taus = out
                pv = arr(getattr(packed, "array", packed))
                rfac = np.triu(pv)[:n, :n]
                _, rref = np.linalg.qr(arr(a))
                return (np.abs(np.abs(rfac) - np.abs(rref)).max()
                        / (np.linalg.norm(arr(a)) * eps * max(m, 1)))
            return run, check, None
        if routine == "cholqr":
            # CholQR squares the condition number: meaningful only for
            # tall-skinny panels (reference gels method selection)
            if m <= n:
                m_t = 4 * n
                a = randn((m_t, n))
                p["m"] = m = m_t
            run = lambda: st.cholqr(a, opts)
            def check(out):
                qf, rf = arr(out[0]), arr(out[1])
                r = np.linalg.norm(qf @ rf - arr(a))
                o = np.linalg.norm(np.conj(qf.T) @ qf - np.eye(n))
                return max(r / (np.linalg.norm(arr(a)) * eps * m), o / (eps * m))
            return run, check, None
        run = lambda: st.gels(a, b, opts)
        def check(out):
            x = arr(getattr(out, "array", out))
            # normal-equations residual: A^H (A x - b) == 0
            r = np.linalg.norm(np.conj(arr(a).T) @ (arr(a) @ x - arr(b)))
            return r / (np.linalg.norm(arr(a)) ** 2
                        * np.linalg.norm(x) * eps * m)
        ref = lambda out: np.abs(arr(getattr(out, "array", out))
                                 - np.linalg.lstsq(arr(a), arr(b),
                                                   rcond=None)[0]).max()
        return run, check, ref

    if routine in ("heev", "svd"):
        if routine == "heev":
            a = herm(n)
            A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=nb, nb=nb)
            run = lambda: st.heev(A, True, opts)
            def check(out):
                w, z = arr(out[0]), arr(out[1])
                r = np.linalg.norm(arr(a) @ z - z * w[None, :])
                return r / (np.linalg.norm(arr(a)) * eps * n)
            ref = lambda out: np.abs(arr(out[0])
                                     - np.linalg.eigvalsh(arr(a))).max()
            return run, check, ref
        a = randn((m, n))
        run = lambda: st.svd(a, True, True, opts)
        def check(out):
            s, u, vh = arr(out[0]), arr(out[1]), arr(out[2])
            r = np.linalg.norm(u @ np.diag(s.astype(u.dtype)) @ vh - arr(a))
            return r / (np.linalg.norm(arr(a)) * eps * max(m, n))
        ref = lambda out: np.abs(np.sort(arr(out[0]))[::-1]
                                 - np.linalg.svd(arr(a), compute_uv=False)).max()
        return run, check, ref

    if routine == "hesv":
        a = herm(n)
        b = randn((n, nrhs))
        A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=nb, nb=nb)
        run = lambda: st.hesv(A, b, opts)
        def check(out):
            x = arr(out[1])
            r = np.linalg.norm(arr(a) @ x - arr(b))
            return r / (np.linalg.norm(arr(a)) * np.linalg.norm(x) * eps * n)
        return run, check, None

    if routine == "gbsv":
        kl = ku = min(p["kl"], n - 1)
        full = np.asarray(randn((n, n)))
        mask = np.abs(np.arange(n)[:, None] - np.arange(n)[None, :])
        full = np.where(mask <= max(kl, ku), full, 0) + n * np.eye(n)
        a = jnp.asarray(full.astype(dt))
        b = randn((n, nrhs))
        A = st.BandMatrix(a, kl=kl, ku=ku, mb=nb, nb=nb)
        run = lambda: st.gbsv(A, b, opts)
        def check(out):
            x = arr(out[-1])
            r = np.linalg.norm(full @ x - arr(b))
            return r / (np.linalg.norm(full) * np.linalg.norm(x) * eps * n)
        return run, check, None

    if routine in ("unmqr", "unmlq", "ungqr"):
        a = randn((m, n)) if routine != "unmlq" else randn((n, m))
        c = randn((m, nrhs))
        if routine == "unmlq":
            f, taus = st.gelqf(a, opts)
            c0 = randn((n, nrhs))
            run = lambda: st.unmlq(st.Side.Left, st.Op.NoTrans, f, taus,
                                   c0, opts)
            def check(out):
                # Q is unitary: QᴴQ·C = C round-trips through two applies
                q = arr(getattr(out, "array", out))
                rt = st.unmlq(st.Side.Left, st.Op.ConjTrans, f, taus,
                              jnp.asarray(q), opts)
                back = arr(getattr(rt, "array", rt))
                r = np.linalg.norm(back - arr(c0))
                return r / (np.linalg.norm(arr(c0)) * eps * n)
            return run, check, None
        f, taus = st.geqrf(a, opts)
        if routine == "ungqr":
            run = lambda: st.ungqr(f, taus, n, opts)
            def check(out):
                q = arr(getattr(out, "array", out))[:, :min(m, n)]
                o = np.abs(np.conj(q.T) @ q - np.eye(q.shape[1])).max()
                return o / (eps * m)
            return run, check, None
        run = lambda: st.unmqr(st.Side.Left, st.Op.ConjTrans, f, taus, c,
                               opts)
        def check(out):
            # QᴴC preserves norms and Qᴴ·(QR's Q column span of A) = R-ish:
            # verify via norm preservation (unitarity)
            got = arr(getattr(out, "array", out))
            return abs(np.linalg.norm(got) - np.linalg.norm(arr(c))) \
                / (np.linalg.norm(arr(c)) * eps * m)
        return run, check, None

    if routine in ("hegv", "hegst"):
        a = herm(n)
        bm = herm(n)
        B = st.HermitianMatrix(bm, uplo=st.Uplo.Lower, mb=nb, nb=nb)
        A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=nb, nb=nb)
        if routine == "hegst":
            fac = st.potrf(B, opts)
            run = lambda: st.hegst(1, A, fac, opts)
            def check(out):
                got = arr(getattr(out, "array", out))
                l = np.tril(arr(fac.data))
                ref = np.linalg.solve(l, np.linalg.solve(l, arr(a)).conj().T)
                return (np.abs(np.tril(got) - np.tril(ref)).max()
                        / (np.linalg.norm(arr(a)) * eps * n))
            return run, check, None
        run = lambda: st.hegv(A, B, 1, True, opts)
        def check(out):
            w, z = arr(out[0]), arr(out[1])
            r = np.linalg.norm(arr(a) @ z - arr(bm) @ z * w[None, :])
            return r / (np.linalg.norm(arr(a)) * eps * n * n)
        return run, check, None

    if routine in ("heev_vals", "svd_vals"):
        if routine == "heev_vals":
            a = herm(n)
            A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=nb, nb=nb)
            run = lambda: st.heev_vals(A, opts)
            def check(out):
                return (np.abs(arr(out) - np.linalg.eigvalsh(arr(a))).max()
                        / (np.linalg.norm(arr(a)) * eps * n))
            return run, check, None
        a = randn((m, n))
        run = lambda: st.svd_vals(a, opts)
        def check(out):
            ref = np.linalg.svd(arr(a), compute_uv=False)
            return (np.abs(np.sort(arr(out))[::-1] - ref).max()
                    / (np.linalg.norm(arr(a)) * eps * max(m, n)))
        return run, check, None

    if routine in ("gbmm", "hbmm", "tbsm", "pbsv"):
        kl = ku = max(1, min(p["kl"], n - 1))
        full = np.asarray(randn((n, n)))
        mask = np.abs(np.arange(n)[:, None] - np.arange(n)[None, :])
        full = np.where(mask <= kl, full, 0)
        if routine == "hbmm" or routine == "pbsv":
            full = (full + np.conj(full).T) / 2 + n * np.eye(n)
        a = jnp.asarray(full.astype(dt))
        b = randn((n, nrhs))
        if routine == "gbmm":
            A = st.BandMatrix(a, kl=kl, ku=ku, mb=nb, nb=nb)
            c0 = randn((n, nrhs))
            run = lambda: st.gbmm(1.0, A, b, 1.0, c0, opts)
            def check(out):
                got = arr(getattr(out, "array", out))
                return (np.linalg.norm(got - (full @ arr(b) + arr(c0)))
                        / (np.linalg.norm(full) * np.linalg.norm(arr(b))
                           * eps * n))
            return run, check, None
        if routine == "hbmm":
            A = st.HermitianBandMatrix(a, kd=kl, uplo=st.Uplo.Lower,
                                       mb=nb, nb=nb)
            c0 = randn((n, nrhs))
            run = lambda: st.hbmm(st.Side.Left, 1.0, A, b, 1.0, c0, opts)
            def check(out):
                got = arr(getattr(out, "array", out))
                return (np.linalg.norm(got - (full @ arr(b) + arr(c0)))
                        / (np.linalg.norm(full) * np.linalg.norm(arr(b))
                           * eps * n))
            return run, check, None
        if routine == "pbsv":
            A = st.HermitianBandMatrix(a, kd=kl, uplo=st.Uplo.Lower,
                                       mb=nb, nb=nb)
            run = lambda: st.pbsv(A, b, opts)
            def check(out):
                x = arr(out[-1])
                return (np.linalg.norm(full @ x - arr(b))
                        / (np.linalg.norm(full) * np.linalg.norm(x)
                           * eps * n))
            return run, check, None
        tfull = np.tril(full) + 2 * n * np.eye(n)
        A = st.TriangularBandMatrix(jnp.asarray(tfull.astype(dt)), kd=kl,
                                    uplo=st.Uplo.Lower, mb=nb, nb=nb)
        run = lambda: st.tbsm(st.Side.Left, 1.0, A, b, None, opts)
        def check(out):
            x = arr(getattr(out, "array", out))
            return (np.linalg.norm(tfull @ x - arr(b))
                    / (np.linalg.norm(tfull) * np.linalg.norm(x) * eps * n))
        return run, check, None

    if routine in ("gemmA", "trsmA"):
        if routine == "gemmA":
            a, b, c = randn((m, k)), randn((k, n)), randn((m, n))
            run = lambda: st.gemmA(1.0, a, b, 1.0, c, opts)
            def check(out):
                got = arr(getattr(out, "array", out))
                na, nb_, nc = _norms(a, b, c)
                r = np.linalg.norm(got - (arr(a) @ arr(b) + arr(c)))
                return r / ((na * nb_ + nc) * eps * k)
            return run, check, None
        a = jnp.tril(randn((m, m))) + 2 * m * jnp.eye(m, dtype=dt)
        A = st.TriangularMatrix(a, uplo=st.Uplo.Lower, diag=st.Diag.NonUnit,
                                mb=nb, nb=nb)
        b = randn((m, n))
        run = lambda: st.trsmA(st.Side.Left, 1.0, A, b, opts)
        def check(out):
            o = arr(getattr(out, "array", out))
            r = np.linalg.norm(arr(a) @ o - arr(b))
            na, nb_ = _norms(a, b)
            return r / (na * max(np.linalg.norm(o), nb_) * eps * m)
        return run, check, None

    if routine in ("he2hb", "ge2tb", "hb2st", "tb2bd"):
        if routine == "he2hb":
            a = herm(n)
            A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=nb, nb=nb)
            run = lambda: st.he2hb(A, opts)
            def check(out):
                # similarity: band eigenvalues == A eigenvalues
                band = np.asarray(out.band)
                wb = np.linalg.eigvalsh(
                    np.tril(band) + np.conj(np.tril(band, -1)).T)
                wa = np.linalg.eigvalsh(arr(a))
                return np.abs(np.sort(wb) - np.sort(wa)).max() \
                    / (np.linalg.norm(arr(a)) * eps * n)
            return run, check, None
        if routine == "ge2tb":
            a = randn((m, n))
            run = lambda: st.ge2tb(a, opts)
            def check(out):
                band = np.asarray(out.band)[:n]
                sb = np.linalg.svd(np.triu(band), compute_uv=False)
                sa = np.linalg.svd(arr(a), compute_uv=False)
                return np.abs(sb - sa).max() \
                    / (np.linalg.norm(arr(a)) * eps * max(m, n))
            return run, check, None
        # chase sub-steps operate on a host band matrix
        kd = max(2, min(nb, n - 1))
        bandf = np.asarray(randn((n, n)))
        maskb = np.arange(n)[None, :] - np.arange(n)[:, None]
        if routine == "hb2st":
            bandl = np.where((maskb <= 0) & (maskb >= -kd), bandf, 0)
            sym = bandl + np.tril(bandl, -1).T
            run = lambda: st.hb2st(bandl.astype(np.float64), kd,
                                   want_rots=False)
            def check(out):
                d_t, e_t, _ = out
                wt = np.linalg.eigvalsh(np.diag(d_t) + np.diag(e_t, 1)
                                        + np.diag(e_t, -1))
                wa = np.linalg.eigvalsh(sym)
                return np.abs(np.sort(wt) - np.sort(wa)).max() \
                    / (np.linalg.norm(sym) * eps * n)
            return run, check, None
        bandu = np.where((maskb >= 0) & (maskb <= kd), bandf, 0)
        run = lambda: st.tb2bd(bandu.astype(np.float64), kd,
                               want_rots=False)
        def check(out):
            d_t, e_t, _ = out
            bid = np.diag(d_t) + np.diag(e_t, 1)
            sb = np.linalg.svd(bid, compute_uv=False)
            sa = np.linalg.svd(bandu, compute_uv=False)
            return np.abs(np.sort(sb) - np.sort(sa)).max() \
                / (np.linalg.norm(bandu) * eps * n)
        return run, check, None

    if routine in ("gecondest", "pocondest", "trcondest"):
        if routine == "pocondest":
            a = herm(n)
            A = st.HermitianMatrix(a, uplo=st.Uplo.Lower, mb=nb, nb=nb)
            fac = st.potrf(A, opts)
            anorm = float(st.norm(st.Norm.One, a))
            run = lambda: st.pocondest(st.Norm.One, fac, anorm, opts)
            def check(out):
                true_rc = 1.0 / (anorm * np.linalg.norm(
                    np.linalg.inv(arr(a)), 1))
                got = float(out)
                return 0.0 if got <= 3 * true_rc * 10 and got > 0 else 99.0
            return run, check, None
        if routine == "trcondest":
            a = jnp.tril(randn((n, n))) + 2 * n * jnp.eye(n, dtype=dt)
            run = lambda: st.trcondest(st.Norm.One, a, st.Uplo.Lower,
                                       st.Diag.NonUnit, opts)
            def check(out):
                return 0.0 if float(out) > 0 else 99.0
            return run, check, None
        a = randn((n, n)) + n * jnp.eye(n, dtype=dt)
        lu, perm = st.getrf(a, opts)
        anorm = float(st.norm(st.Norm.One, a))
        run = lambda: st.gecondest(st.Norm.One, lu, perm, anorm, opts)
        def check(out):
            true_rc = 1.0 / (anorm * np.linalg.norm(np.linalg.inv(arr(a)), 1))
            got = float(out)
            # condition estimates are order-of-magnitude quantities
            return 0.0 if 0 < got <= 30 * true_rc else 99.0
        return run, check, None

    if routine in ("getrf_nopiv", "getrf_tntpiv"):
        a = randn((n, n)) + n * jnp.eye(n, dtype=dt)
        fn = getattr(st, routine)
        run = lambda: fn(a, opts)
        def check(out):
            if routine == "getrf_nopiv":
                luv = arr(getattr(out, "array", out))
                perm = np.arange(n)
            else:
                lu, pv = out
                luv = arr(getattr(lu, "array", lu))
                perm = np.asarray(pv)
            l = np.tril(luv, -1) + np.eye(n)
            u = np.triu(luv)
            r = np.linalg.norm(arr(a)[perm] - l @ u)
            return r / (np.linalg.norm(arr(a)) * eps * n)
        return run, check, None

    if routine in ("gels_qr", "gels_cholqr"):
        mm = max(m, 2 * n)
        a = randn((mm, n))
        b = randn((mm, nrhs))
        fn = getattr(st, routine)
        run = lambda: fn(a, b, opts)
        def check(out):
            x = arr(getattr(out, "array", out))
            r = np.linalg.norm(np.conj(arr(a).T) @ (arr(a) @ x - arr(b)))
            return r / (np.linalg.norm(arr(a)) ** 2
                        * max(np.linalg.norm(x), 1) * eps * mm)
        return run, check, None

    if routine.startswith("p"):  # distributed testers on the active mesh
        import jax
        from slate_tpu import parallel as par
        mesh = par.make_grid_mesh()
        if routine == "ppotrf":
            a = np.asarray(herm(n))
            run = lambda: par.pposv(a, np.asarray(randn((n, nrhs))), mesh, nb)
            def check(out):
                l, x = out
                lh = np.tril(np.asarray(par.undistribute(l)))
                r = np.linalg.norm(lh @ np.conj(lh).T - a)
                return r / (np.linalg.norm(a) * eps * n)
            return run, check, None
        if routine == "pgesv":
            a = np.asarray(randn((n, n))) + n * np.eye(n, dtype=dt)
            bb = np.asarray(randn((n, nrhs)))
            run = lambda: par.pgesv(a, bb, mesh, nb)
            def check(out):
                x = np.asarray(par.undistribute(out[2]))
                r = np.linalg.norm(a @ x - bb)
                return r / (np.linalg.norm(a) * np.linalg.norm(x) * eps * n)
            return run, check, None
        if routine == "pgeqrf":
            a = np.asarray(randn((m, n)))
            bb = np.asarray(randn((m, nrhs)))
            run = lambda: par.pgels(a, bb, mesh, nb)
            def check(out):
                x = np.asarray(par.undistribute(out[2]))
                r = np.linalg.norm(np.conj(a.T) @ (a @ x - bb))
                return r / (np.linalg.norm(a) ** 2
                            * max(np.linalg.norm(x), 1) * eps * m)
            return run, check, None
        if routine == "pheev":
            a = np.asarray(herm(n))
            run = lambda: par.pheev(a, mesh, nb)
            def check(out):
                w, zd = out
                z = np.asarray(par.undistribute(zd))
                r = np.linalg.norm(a @ z - z * np.asarray(w)[None, :])
                return r / (np.linalg.norm(a) * eps * n * n)
            return run, check, None
        if routine == "psvd":
            a = np.asarray(randn((m, n)))
            run = lambda: par.psvd(a, mesh, nb)
            def check(out):
                s, ud, vd = out
                u = np.asarray(par.undistribute(ud))[:, :n]
                v = np.asarray(par.undistribute(vd))
                rec = u @ np.diag(np.asarray(s)) @ np.conj(v.T)
                return (np.linalg.norm(a - rec)
                        / (np.linalg.norm(a) * eps * n))
            return run, check, None
        import math as _math
        pq, qq = par.mesh_grid_shape(mesh) if hasattr(par, "mesh_grid_shape") \
            else (mesh.shape["p"], mesh.shape["q"])
        if routine == "ptrsm":
            af = np.asarray(jnp.tril(randn((m, m)))
                            + 2 * m * jnp.eye(m, dtype=dt))
            bf = np.asarray(randn((m, nrhs)))
            ad = par.distribute(af, mesh, nb, row_mult=qq, col_mult=pq)
            bd = par.distribute(bf, mesh, nb, row_mult=qq)
            run = lambda: par.ptrsm(st.Side.Left, st.Uplo.Lower,
                                    st.Op.NoTrans, st.Diag.NonUnit, ad, bd)
            def check(out):
                x = np.asarray(par.undistribute(out))
                return (np.linalg.norm(af @ x - bf)
                        / (np.linalg.norm(af) * np.linalg.norm(x) * eps * m))
            return run, check, None
        if routine == "pgelqf":
            a = np.asarray(randn((n, m)))   # wide
            ad = par.distribute(a, mesh, nb, diag_pad=1.0, row_mult=qq,
                                col_mult=pq)
            run = lambda: par.pgelqf(ad)
            def check(out):
                lq = np.asarray(par.undistribute(out[0]))
                lfac = np.tril(lq)[:n, :n]
                return (np.abs(np.abs(lfac)
                               - np.abs(np.linalg.qr(a.T)[1].T[:n, :n])).max()
                        / (np.linalg.norm(a) * eps * max(m, 1)))
            return run, check, None
        if routine == "pgetri":
            a = np.asarray(randn((n, n))) + n * np.eye(n, dtype=dt)
            ad = par.distribute(a, mesh, nb, diag_pad=1.0, row_mult=qq,
                                col_mult=pq)
            run = lambda: par.pgetri(ad)
            def check(out):
                inv = np.asarray(par.undistribute(out))
                return (np.linalg.norm(inv @ a - np.eye(n))
                        / (eps * n * np.linalg.cond(a, 1)))
            return run, check, None
        if routine in ("pgbsv", "ppbsv"):
            kl = max(1, min(p["kl"], n - 1))
            full = np.asarray(randn((n, n)))
            maskb = np.abs(np.arange(n)[:, None] - np.arange(n)[None, :])
            full = np.where(maskb <= kl, full, 0)
            if routine == "ppbsv":
                full = (full + np.conj(full).T) / 2 + n * np.eye(n)
            else:
                full = full + n * np.eye(n)
            bf = np.asarray(randn((n, nrhs)))
            ad = par.distribute(full.astype(dt), mesh, nb, row_mult=qq,
                                col_mult=pq)
            bd = par.distribute(bf, mesh, nb, row_mult=qq)
            if routine == "pgbsv":
                run = lambda: par.pgbsv(ad, kl, kl, bd)
            else:
                run = lambda: par.ppbsv(ad, kl, bd)
            def check(out):
                x = np.asarray(par.undistribute(out))
                return (np.linalg.norm(full @ x - bf)
                        / (np.linalg.norm(full) * np.linalg.norm(x)
                           * eps * n))
            return run, check, None
        if routine == "pgecondest":
            a = np.asarray(randn((n, n))) + n * np.eye(n, dtype=dt)
            ad = par.distribute(a, mesh, nb, diag_pad=1.0, row_mult=qq,
                                col_mult=pq)
            lu, gperm = par.pgetrf(ad)
            anorm = float(np.linalg.norm(a, 1))
            run = lambda: par.pgecondest(lu, gperm, anorm)
            def check(out):
                true_rc = 1.0 / (anorm
                                 * np.linalg.norm(np.linalg.inv(a), 1))
                got = float(out[0])    # (rcond, est)
                return 0.0 if 0 < got <= 30 * true_rc else 99.0
            return run, check, None

    raise KeyError(routine)


ROUTINES = sorted(set(FLOPS) - {"pgemm"})


# ---------------------------------------------------------------------------
# Main sweep loop
# ---------------------------------------------------------------------------

def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("routine", nargs="?", help="routine to test")
    ap.add_argument("--list", action="store_true", help="list routines")
    ap.add_argument("--dim", default="256", help="n (and m=k=n) sweep, "
                    "start:stop:step or comma list")
    ap.add_argument("--m", type=int, help="override m")
    ap.add_argument("--k", type=int, help="override k")
    ap.add_argument("--nrhs", type=int, default=8)
    ap.add_argument("--type", default="s", help="comma list of s,d,c,z")
    ap.add_argument("--nb", type=int, default=256)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--repeat", type=int, default=1, help="timed repeats "
                    "(first extra run warms the jit cache)")
    ap.add_argument("--check", default="y", choices=["y", "n"])
    ap.add_argument("--ref", default="n", choices=["y", "n"],
                    help="also compare against NumPy/SciPy")
    ap.add_argument("--tol", type=float, default=3.0,
                    help="residual gate in units of the scaled check")
    args = ap.parse_args(argv)

    if args.list or not args.routine:
        print("routines:", " ".join(ROUTINES))
        return 0

    types = [t.strip() for t in args.type.split(",")]
    if any(t in ("d", "z") for t in types):
        import jax
        jax.config.update("jax_enable_x64", True)

    import jax
    import jax.numpy as jnp
    import slate_tpu as st

    dims = parse_dims(args.dim)
    header = (f"{'type':>4} {'m':>7} {'n':>7} {'k':>7} {'nb':>5} "
              f"{'time(s)':>10} {'GFLOP/s':>10} {'error':>10}  status")
    print(header)
    print("-" * len(header))
    failures = 0
    for t in types:
        dt = TYPE_MAP[t]
        for n in dims:
            p = dict(m=args.m or n, n=n, k=args.k or n, nrhs=args.nrhs,
                     nb=args.nb, dtype=dt, seed=args.seed,
                     kl=args.nb, ku=args.nb)
            try:
                run, check, ref = make_tester(args.routine, p, jnp, st)
            except KeyError:
                print(f"unknown routine {args.routine!r}; --list to see all")
                return 2
            out = jax.block_until_ready(run())     # warm the jit cache
            times = []
            for _ in range(args.repeat):
                t0 = time.perf_counter()
                out = jax.block_until_ready(run())
                times.append(time.perf_counter() - t0)
            tbest = min(times)
            gflops = FLOPS[args.routine](p) / tbest / 1e9
            err = float(check(out)) if args.check == "y" else float("nan")
            ok = (args.check == "n") or (err < args.tol)
            status = "ok" if ok else "FAILED"
            if args.ref == "y" and ref is not None:
                status += f"  |ref diff|={float(ref(out)):.2e}"
            failures += 0 if ok else 1
            print(f"{t:>4} {p['m']:>7} {n:>7} {p['k']:>7} {args.nb:>5} "
                  f"{tbest:>10.4f} {gflops:>10.1f} {err:>10.2e}  {status}")
    print(f"\n{'all tests passed' if failures == 0 else f'{failures} FAILED'}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
