"""In-place ScaLAPACK path: p? routines run distributed straight from
per-rank locals — the global array is NEVER materialized (reference
``scalapack_api/scalapack_potrf.cc:27-80`` zero-copy ``fromScaLAPACK``).

The no-gather property is asserted by poisoning ``from_local`` for the
duration of each mesh-path call.
"""

import contextlib

import jax
import numpy as np
import pytest

from slate_tpu.api import scalapack as sc
from slate_tpu.parallel import make_grid_mesh


@pytest.fixture(scope="module")
def mesh24():
    return make_grid_mesh(2, 4)


GRID = sc.BlacsGrid(2, 4)


@contextlib.contextmanager
def no_gather(monkeypatch):
    def boom(*a, **k):
        raise AssertionError("global array materialized (from_local called)")
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(sc, "from_local", boom)
        yield


def _mk(m, n, seed=0, spd=False):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((m, n))
    if spd:
        a = a @ a.T + m * np.eye(m)
    return a


def test_roundtrip_dist_locals(mesh24):
    a = _mk(90, 70, 1)
    desc = sc.Desc(90, 70, 16, 16)
    lg = sc.to_local(a, GRID, desc)
    dm = sc.dist_from_locals(lg, GRID, desc, mesh24)
    from slate_tpu.parallel import undistribute
    assert np.allclose(np.asarray(undistribute(dm)), a)
    lg2 = sc.locals_from_dist(dm, GRID, desc)
    for r in range(2):
        for c in range(4):
            assert np.allclose(lg2[r][c], lg[r][c])


def test_ppotrf_ppotrs_inplace(mesh24, monkeypatch):
    n, nb = 96, 16
    a = _mk(n, n, 2, spd=True)
    b = _mk(n, 8, 3)
    desc = sc.Desc(n, n, nb, nb)
    descb = sc.Desc(n, 8, nb, nb)
    a_lg = sc.to_local(a, GRID, desc)
    b_lg = sc.to_local(b, GRID, descb)
    with no_gather(monkeypatch):
        fac_lg, x_lg = sc.pposv("L", a_lg, desc, b_lg, descb, GRID,
                                mesh=mesh24)
    x = sc.from_local(x_lg, GRID, descb)
    assert np.linalg.norm(a @ x - b) / np.linalg.norm(b) < 1e-10
    l = np.tril(sc.from_local(fac_lg, GRID, desc))
    assert np.linalg.norm(l @ l.T - a) / np.linalg.norm(a) < 1e-10


def test_pgesv_inplace(mesh24, monkeypatch):
    n, nb = 80, 16
    a = _mk(n, n, 4) + n * np.eye(n)
    b = _mk(n, 4, 5)
    desc = sc.Desc(n, n, nb, nb)
    descb = sc.Desc(n, 4, nb, nb)
    a_lg = sc.to_local(a, GRID, desc)
    b_lg = sc.to_local(b, GRID, descb)
    with no_gather(monkeypatch):
        x_lg, gperm = sc.pgesv(a_lg, desc, b_lg, descb, GRID, mesh=mesh24)
    x = sc.from_local(x_lg, GRID, descb)
    assert np.linalg.norm(a @ x - b) / np.linalg.norm(b) < 1e-10


def test_pgeqrf_pgels_inplace(mesh24, monkeypatch):
    m, n, nb = 128, 48, 16
    a = _mk(m, n, 6)
    b = _mk(m, 3, 7)
    desca = sc.Desc(m, n, nb, nb)
    descb = sc.Desc(m, 3, nb, nb)
    a_lg = sc.to_local(a, GRID, desca)
    b_lg = sc.to_local(b, GRID, descb)
    with no_gather(monkeypatch):
        qr_lg, tmats = sc.pgeqrf(a_lg, desca, GRID, mesh=mesh24)
        x_lg = sc.pgels(a_lg, desca, b_lg, descb, GRID, mesh=mesh24)
    r = np.triu(sc.from_local(qr_lg, GRID, desca)[:n])
    # Gram identity A^T A = R^T R
    assert np.allclose(r.T @ r, a.T @ a, atol=1e-8 * np.linalg.norm(a) ** 2)
    x = sc.from_local(x_lg, GRID, sc.Desc(n, 3, nb, nb))
    xref = np.linalg.lstsq(a, b, rcond=None)[0]
    assert np.allclose(x, xref, atol=1e-8)


def test_pheev_inplace(mesh24, monkeypatch):
    n, nb = 96, 16
    a = _mk(n, n, 8)
    a = (a + a.T) / 2
    desc = sc.Desc(n, n, nb, nb)
    a_lg = sc.to_local(a, GRID, desc)
    with no_gather(monkeypatch):
        w, z_lg = sc.pheev("V", "L", a_lg, desc, GRID, mesh=mesh24)
    z = sc.from_local(z_lg, GRID, desc)
    assert np.allclose(np.asarray(w), np.linalg.eigvalsh(a), atol=1e-9)
    assert np.linalg.norm(a @ z - z * np.asarray(w)[None, :]) < 1e-9 * n


def test_pgemm_inplace(mesh24, monkeypatch):
    m, k, n, nb = 64, 80, 48, 16
    a, b, c = _mk(m, k, 9), _mk(k, n, 10), _mk(m, n, 11)
    da, db, dc = sc.Desc(m, k, nb, nb), sc.Desc(k, n, nb, nb), \
        sc.Desc(m, n, nb, nb)
    a_lg = sc.to_local(a, GRID, da)
    b_lg = sc.to_local(b, GRID, db)
    c_lg = sc.to_local(c, GRID, dc)
    with no_gather(monkeypatch):
        out_lg = sc.pgemm("N", "N", 2.0, a_lg, da, b_lg, db, 0.5, c_lg,
                          dc, GRID, mesh=mesh24)
    out = sc.from_local(out_lg, GRID, dc)
    assert np.allclose(out, 2.0 * a @ b + 0.5 * c, atol=1e-10)


def test_plange_inplace(mesh24, monkeypatch):
    a = _mk(70, 90, 12)
    desc = sc.Desc(70, 90, 16, 16)
    a_lg = sc.to_local(a, GRID, desc)
    with no_gather(monkeypatch):
        for ch, ref in (("F", np.linalg.norm(a)),
                        ("M", np.abs(a).max()),
                        ("1", np.abs(a).sum(0).max()),
                        ("I", np.abs(a).sum(1).max())):
            assert np.isclose(sc.plange(ch, a_lg, desc, GRID, mesh=mesh24),
                              ref)


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_ppotrf_uplo_single_triangle(mesh24, monkeypatch, uplo):
    """Only the stored triangle is referenced (ScaLAPACK contract); the
    other triangle carries garbage.  'U' returns the factor in the upper
    triangle."""
    n, nb = 80, 16
    a = _mk(n, n, 20, spd=True)
    stored = np.tril(a) if uplo == "L" else np.triu(a)
    garbage = stored + (np.triu(np.full((n, n), 7.0), 1) if uplo == "L"
                        else np.tril(np.full((n, n), 7.0), -1))
    desc = sc.Desc(n, n, nb, nb)
    a_lg = sc.to_local(garbage, GRID, desc)
    with no_gather(monkeypatch):
        fac_lg = sc.ppotrf(uplo, a_lg, desc, GRID, mesh=mesh24)
    fac = sc.from_local(fac_lg, GRID, desc)
    if uplo == "L":
        l = np.tril(fac)
        assert np.linalg.norm(l @ l.T - a) / np.linalg.norm(a) < 1e-10
    else:
        u = np.triu(fac)
        assert np.linalg.norm(u.T @ u - a) / np.linalg.norm(a) < 1e-10


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_ppotrf_preserves_other_triangle(mesh24, monkeypatch, uplo):
    """The unreferenced triangle comes back bit-identical — ScaLAPACK
    leaves it untouched and callers rely on that (ADVICE r2)."""
    n, nb = 80, 16
    a = _mk(n, n, 24, spd=True)
    sentinel = (np.triu(np.full((n, n), 7.25), 1) if uplo == "L"
                else np.tril(np.full((n, n), 7.25), -1))
    stored = (np.tril(a) if uplo == "L" else np.triu(a)) + sentinel
    desc = sc.Desc(n, n, nb, nb)
    a_lg = sc.to_local(stored, GRID, desc)
    with no_gather(monkeypatch):
        fac_lg = sc.ppotrf(uplo, a_lg, desc, GRID, mesh=mesh24)
    fac = sc.from_local(fac_lg, GRID, desc)
    untouched = (np.triu(fac, 1) if uplo == "L" else np.tril(fac, -1))
    assert np.array_equal(untouched, sentinel)
    # gather path honors the same contract
    fac_lg2 = sc.ppotrf(uplo, sc.to_local(stored, GRID, desc), desc, GRID,
                        mesh=None)
    fac2 = sc.from_local(fac_lg2, GRID, desc)
    untouched2 = (np.triu(fac2, 1) if uplo == "L" else np.tril(fac2, -1))
    assert np.array_equal(untouched2, sentinel)


def test_pgetrf_pivots_same_both_paths(mesh24, monkeypatch):
    """Mesh and gather paths return the same global-perm representation
    (ADVICE r2 asked for unified pivot semantics)."""
    n, nb = 80, 16
    a = _mk(n, n, 25)   # no diagonal dominance: real pivoting happens
    desc = sc.Desc(n, n, nb, nb)
    with no_gather(monkeypatch):
        _, piv_mesh = sc.pgetrf(sc.to_local(a, GRID, desc), desc, GRID,
                                mesh=mesh24)
    _, piv_gather = sc.pgetrf(sc.to_local(a, GRID, desc), desc, GRID,
                              mesh=None)
    assert np.array_equal(np.asarray(piv_mesh), np.asarray(piv_gather))
    assert not np.array_equal(np.asarray(piv_mesh), np.arange(n))


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_pposv_uplo_roundtrip(mesh24, monkeypatch, uplo):
    n, nb = 64, 16
    a = _mk(n, n, 21, spd=True)
    b = _mk(n, 5, 22)
    stored = np.tril(a) if uplo == "L" else np.triu(a)
    desc = sc.Desc(n, n, nb, nb)
    descb = sc.Desc(n, 5, nb, nb)
    a_lg = sc.to_local(stored, GRID, desc)
    b_lg = sc.to_local(b, GRID, descb)
    with no_gather(monkeypatch):
        _, x_lg = sc.pposv(uplo, a_lg, desc, b_lg, descb, GRID,
                           mesh=mesh24)
    x = sc.from_local(x_lg, GRID, descb)
    assert np.linalg.norm(a @ x - b) / np.linalg.norm(b) < 1e-9


def test_pheev_uplo_upper(mesh24, monkeypatch):
    n, nb = 64, 16
    a = _mk(n, n, 23)
    a = (a + a.T) / 2
    stored = np.triu(a) + np.tril(np.full((n, n), 9.0), -1)  # garbage low
    desc = sc.Desc(n, n, nb, nb)
    a_lg = sc.to_local(stored, GRID, desc)
    with no_gather(monkeypatch):
        w, z_lg = sc.pheev("V", "U", a_lg, desc, GRID, mesh=mesh24)
    z = sc.from_local(z_lg, GRID, desc)
    assert np.allclose(np.asarray(w), np.linalg.eigvalsh(a), atol=1e-9)
    assert np.linalg.norm(a @ z - z * np.asarray(w)[None, :]) < 1e-9 * n
