"""Trace subsystem + simplified-API tests — mirroring the reference's
``trace::Block``/SVG contract (``Trace.hh:24-108``, ``Trace.cc:330-448``)
and ``simplified_api.hh`` forwarding."""

import os

import numpy as np
import jax.numpy as jnp
import pytest

import slate_tpu as st
from slate_tpu import trace
from slate_tpu.api import simplified as simp
from slate_tpu.enums import Norm, Op, Side


def test_trace_block_and_svg(tmp_path):
    trace.clear()
    trace.on()
    with trace.Block("gemm"):
        pass
    with trace.Block("potrf", lane="device0"):
        with trace.Block("panel"):
            pass
    trace.off()
    evts = trace.events()
    assert [e.name for e in evts] == ["gemm", "panel", "potrf"]
    path = str(tmp_path / "trace.svg")
    out = trace.finish(path)
    assert out == path and os.path.exists(path)
    svg = open(path).read()
    assert svg.startswith("<svg") and "potrf" in svg and "device0" in svg
    assert trace.events() == []          # finish resets


def test_trace_off_records_nothing():
    trace.clear()
    trace.off()
    with trace.Block("hidden"):
        pass
    assert trace.events() == []
    assert trace.finish() is None


def test_trace_decorator():
    trace.clear()
    trace.on()

    @trace.Block("decorated")
    def f(x):
        return x + 1

    assert f(1) == 2
    trace.off()
    assert trace.events()[0].name == "decorated"


def test_trace_decorator_lane_resolved_at_call_time():
    """Regression: the decorator used to pin self.lane at decoration
    time, so a decorated function invoked from a worker thread recorded
    the DECORATING thread's lane.  With no explicit lane, the lane must
    be the calling thread's name."""
    import threading

    trace.clear()
    trace.on()

    @trace.Block("work")
    def f():
        return 1

    t = threading.Thread(target=f, name="worker-lane-7")
    t.start()
    t.join()
    trace.off()
    evts = trace.events()
    assert [e.name for e in evts] == ["work"]
    assert evts[0].lane == "worker-lane-7"


def test_colliding_thread_names_get_distinct_stable_lanes():
    """ISSUE 10 satellite: two live threads SHARING a name (e.g. two
    BatchQueues' dispatcher threads, both named "slate-serve-dispatch")
    must land in distinct, stably-named lanes — before the fix their
    spans collapsed into one Perfetto track."""
    import json
    import threading

    trace.clear()
    trace.on()
    bar = threading.Barrier(2, timeout=30)
    done = threading.Barrier(3, timeout=30)

    def work():
        bar.wait()                  # both alive: distinct idents
        with trace.Block("span"):
            pass
        done.wait()

    threads = [threading.Thread(target=work, name="dup-lane-9")
               for _ in range(2)]
    for t in threads:
        t.start()
    done.wait()
    for t in threads:
        t.join()
    trace.off()
    evts = trace.events()
    lanes = sorted(e.lane for e in evts)
    assert len(evts) == 2
    assert lanes[0] == "dup-lane-9" and lanes[1] == "dup-lane-9#2", lanes
    # and the Perfetto export gives them distinct, named tids
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        path = trace.finish_perfetto(os.path.join(td, "lanes.json"))
        d = json.load(open(path))
    metas = {m["args"]["name"]: m["tid"] for m in d["traceEvents"]
             if m["ph"] == "M"}
    assert metas["dup-lane-9"] != metas["dup-lane-9#2"]


def test_same_thread_keeps_one_lane_across_blocks():
    """A thread's lane is stable: repeated blocks from one thread never
    fork new '#k' lanes."""
    trace.clear()
    trace.on()
    for _ in range(3):
        with trace.Block("rep"):
            pass
    trace.off()
    assert len({e.lane for e in trace.events()}) == 1


def test_trace_decorator_explicit_lane_sticks():
    """An explicitly-given lane keeps overriding the calling thread."""
    import threading

    trace.clear()
    trace.on()

    @trace.Block("pinned", lane="lane-X")
    def f():
        return 1

    t = threading.Thread(target=f, name="worker-lane-8")
    t.start()
    t.join()
    trace.off()
    assert trace.events()[0].lane == "lane-X"


def test_simplified_multiply_and_solves():
    rng = np.random.default_rng(0)
    n = 24
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, 2))
    c = rng.standard_normal((n, 2))
    out = simp.multiply(2.0, jnp.asarray(a), jnp.asarray(b), 0.0,
                        jnp.asarray(c))
    assert np.abs(np.asarray(out) - 2 * a @ b).max() < 1e-12

    x = simp.lu_solve(jnp.asarray(a + n * np.eye(n)), jnp.asarray(b))
    assert np.abs((a + n * np.eye(n)) @ np.asarray(x) - b).max() < 1e-10

    spd = a @ a.T + n * np.eye(n)
    x = simp.chol_solve(jnp.asarray(spd), jnp.asarray(b))
    assert np.abs(spd @ np.asarray(x) - b).max() < 1e-10

    sym = (a + a.T) / 2
    x = simp.indefinite_solve(jnp.asarray(sym), jnp.asarray(b))
    assert np.abs(sym @ np.asarray(x) - b).max() < 1e-9


def test_simplified_factor_roundtrips():
    rng = np.random.default_rng(1)
    n = 20
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    lu, piv = simp.lu_factor(jnp.asarray(a))
    inv = simp.lu_inverse_using_factor(lu, piv)
    assert np.abs(np.asarray(inv) @ a - np.eye(n)).max() < 1e-10

    f, taus = simp.qr_factor(jnp.asarray(a))
    q = st.ungqr(f, taus)
    r = np.triu(np.asarray(f if not hasattr(f, "data") else f.data))
    assert np.abs(np.asarray(q) @ r - a).max() < 1e-10


def test_simplified_eig_svd():
    rng = np.random.default_rng(2)
    n = 24
    a = rng.standard_normal((n, n))
    sym = (a + a.T) / 2
    w = simp.eig_vals(jnp.asarray(sym), {"block_size": 8})
    assert np.abs(np.sort(np.asarray(w)) - np.linalg.eigvalsh(sym)).max() < 1e-10
    s = simp.svd_vals(jnp.asarray(a), {"block_size": 8})
    assert np.abs(np.asarray(s) - np.linalg.svd(a, compute_uv=False)).max() < 1e-10
    nrm = simp.norm(Norm.Fro, jnp.asarray(a))
    assert abs(float(nrm) - np.linalg.norm(a)) < 1e-10
