"""OpenMP wavefront chase (native/runtime.cc hb2st_hh_wave /
tb2bd_hh_wave) vs the serial chase: BITWISE identity.

The wavefront schedules task (sweep j, window w) at stagger t = 3j + w;
same-t tasks touch disjoint band rows and every dependence crosses a t
boundary (reference: the task-DAG of ``src/hb2st.cc:23-90``), so the
parallel schedule must reproduce the serial chase exactly — band, logs,
and counts — at every thread count.  Correctness of the SCHEDULE is
verifiable on a 1-core host (the tasks execute in a different order
than serial even with one thread); true-concurrency races need a
multicore host, which is why the identity is pinned for 1/2/4 threads.
"""

import os

import numpy as np
import pytest

from slate_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native runtime unavailable")


def _restore_env(prev):
    if prev is None:
        os.environ.pop("SLATE_TPU_CHASE_SERIAL", None)
    else:
        os.environ["SLATE_TPU_CHASE_SERIAL"] = prev


def _band_wide(n, kd, seed, dtype=np.float64):
    rng = np.random.default_rng(seed)
    abw = np.zeros((n, 2 * kd + 2), dtype=dtype)
    for d in range(kd + 1):
        v = rng.standard_normal(n - d)
        if np.issubdtype(dtype, np.complexfloating) and d > 0:
            v = v + 1j * rng.standard_normal(n - d)
        abw[:n - d, d] = v      # Hermitian band: real diagonal
    return abw


def _hb2st_full(abw, n, kd):
    """Full chase via the dtype-generic range entry (the f64-only
    ``hb2st_hh_banded`` fast path has no c128 twin; sweeping the whole
    range runs the identical wavefront schedule)."""
    return native.hb2st_hh_banded_range(abw, n, kd, 0, max(n - 2, 0))


def _tb_band(n, kd, seed):
    rng = np.random.default_rng(seed)
    ldw = 3 * kd + 2
    st = np.zeros((n, ldw), dtype=np.float64)
    for r in range(n):
        for c in range(r, min(r + kd + 1, n)):
            st[r, c - r + kd] = rng.standard_normal()
    return st


@pytest.mark.parametrize("dtype", [np.float64, np.complex128],
                         ids=["f64", "c128"])
@pytest.mark.parametrize("nthreads", [1, 2, 4])
def test_hb2st_wavefront_bitwise_identity(nthreads, dtype):
    """Both dtypes: a complex-only scheduling bug (the c128 chase is a
    separate template instantiation) must not hide behind the loose
    end-to-end pheev residual gates."""
    n, kd = 2048, 64
    ab_ser = _band_wide(n, kd, 0, dtype)
    ab_par = ab_ser.copy()

    prev = os.environ.get("SLATE_TPU_CHASE_SERIAL")
    os.environ["SLATE_TPU_CHASE_SERIAL"] = "1"
    try:
        vs, ts, rs, ls = _hb2st_full(ab_ser, n, kd)
    finally:
        _restore_env(prev)

    prev_thr = native.num_threads()
    native.set_num_threads(nthreads)
    try:
        vp, tp, rp, lp = _hb2st_full(ab_par, n, kd)
    finally:
        native.set_num_threads(prev_thr)

    np.testing.assert_array_equal(ab_par, ab_ser)
    np.testing.assert_array_equal(vp, vs)
    np.testing.assert_array_equal(tp, ts)
    np.testing.assert_array_equal(rp, rs)
    np.testing.assert_array_equal(lp, ls)


def test_hb2st_full_entry_matches_range_entry():
    """The f64-only fast entry and the range entry over [0, n-2) must
    produce the same chase (guards the shared schedule staying shared)."""
    n, kd = 512, 32
    ab_a = _band_wide(n, kd, 3)
    ab_b = ab_a.copy()
    out_a = native.hb2st_hh_banded(ab_a, n, kd)
    out_b = _hb2st_full(ab_b, n, kd)
    np.testing.assert_array_equal(ab_a, ab_b)
    for a, b in zip(out_a, out_b):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128],
                         ids=["f64", "c128"])
def test_hb2st_wavefront_range_identity(dtype):
    """The checkpointed sweep-range path uses the wavefront too."""
    n, kd = 512, 32
    ab_ser = _band_wide(n, kd, 1, dtype)
    ab_par = ab_ser.copy()
    chunks = [(0, 100), (100, 317), (317, n - 2)]

    prev = os.environ.get("SLATE_TPU_CHASE_SERIAL")
    os.environ["SLATE_TPU_CHASE_SERIAL"] = "1"
    try:
        ser = [native.hb2st_hh_banded_range(ab_ser, n, kd, j0, j1)
               for j0, j1 in chunks]
    finally:
        _restore_env(prev)
    prev_thr = native.num_threads()
    native.set_num_threads(2)
    try:
        par = [native.hb2st_hh_banded_range(ab_par, n, kd, j0, j1)
               for j0, j1 in chunks]
    finally:
        native.set_num_threads(prev_thr)
    np.testing.assert_array_equal(ab_par, ab_ser)
    for s, p in zip(ser, par):
        for a, b in zip(s, p):
            np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("nthreads", [1, 2, 4])
def test_tb2bd_wavefront_bitwise_identity(nthreads):
    n, kd = 1024, 48
    st_ser = _tb_band(n, kd, 2)
    st_par = st_ser.copy()

    prev = os.environ.get("SLATE_TPU_CHASE_SERIAL")
    os.environ["SLATE_TPU_CHASE_SERIAL"] = "1"
    try:
        ser = native.tb2bd_hh_banded(st_ser, n, kd)
    finally:
        _restore_env(prev)
    prev_thr = native.num_threads()
    native.set_num_threads(nthreads)
    try:
        par = native.tb2bd_hh_banded(st_par, n, kd)
    finally:
        native.set_num_threads(prev_thr)

    np.testing.assert_array_equal(st_par, st_ser)
    for log_s, log_p in zip(ser, par):
        for a, b in zip(log_s, log_p):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# Device wavefront chase (ops.pallas_kernels.hb2st_wavefront /
# tb2bd_wavefront, interpret mode on CPU) vs the native host chase: the
# SAME schedule runs as ONE Pallas invocation with the reflector log
# written directly into the padded (nsweeps, tmax, kd) device layout, so
# parity here pins band, log layout AND the layout actually consumed by
# unmtr_hb2st_hh.  f64/c128 compare against the native chase on the
# same operand (tight); f32 runs the kernel in f32 against the f64
# native reference (the native chase has no f32 instantiation).
# ---------------------------------------------------------------------------

import jax.numpy as jnp

from slate_tpu.perf.autotune import kernel as _kernel


def _native_packed(abw, n, kd, j0=0, j1=None):
    from slate_tpu.linalg.eig import _hb_sweep_counts, _pack_hh_log
    if j1 is None:
        j1 = max(n - 2, 0)
    log = native.hb2st_hh_banded_range(abw, n, kd, j0, j1)
    counts = _hb_sweep_counts(n, kd, j0, j1)
    return _pack_hh_log(*log, n, kd, counts=counts)


@pytest.mark.parametrize("kd", [8, 48])
@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex128],
                         ids=["f32", "f64", "c128"])
def test_hb2st_pallas_wavefront_parity(dtype, kd):
    n = 96 if kd == 8 else 128
    ref_dt = np.complex128 if dtype == np.complex128 else np.float64
    ab_ref = _band_wide(n, kd, 7, ref_dt)
    ab_dev = ab_ref.astype(dtype)
    v3, t2, s0 = _native_packed(ab_ref, n, kd)

    out_ab, vt = _kernel("hb2st_wavefront")(ab_dev, kd)
    out_ab = np.asarray(out_ab)
    vt = np.asarray(vt)
    # the kernel's padded log IS _pack_hh_log's layout (tau-prefixed)
    assert vt.shape == (v3.shape[0], v3.shape[1], kd + 1)
    tol = 5e-3 if dtype == np.float32 else 1e-8
    scale = np.max(np.abs(ab_ref))
    np.testing.assert_allclose(out_ab, ab_ref.astype(ref_dt),
                               atol=tol * scale, rtol=0)
    np.testing.assert_allclose(vt[:, :, 1:], v3, atol=tol, rtol=0)
    np.testing.assert_allclose(vt[:, :, 0], t2, atol=tol, rtol=0)
    # the consumed layout: back-transform a probe through both logs
    from slate_tpu.linalg.eig import unmtr_hb2st_hh
    rng = np.random.default_rng(8)
    z = rng.standard_normal((n, 4))
    z_ref = np.asarray(unmtr_hb2st_hh(v3, t2, s0, z, kd))
    z_dev = np.asarray(unmtr_hb2st_hh(vt[:, :, 1:], vt[:, :, 0], s0,
                                      z, kd))
    np.testing.assert_allclose(z_dev, z_ref, atol=tol * 10, rtol=0)


def test_hb2st_pallas_wavefront_range_chunks():
    """The checkpointed sweep-range chunks (the distributed drivers'
    middle) reproduce the native chunked chase: the band is the full
    inter-chunk state."""
    n, kd = 96, 8
    ab_ref = _band_wide(n, kd, 9)
    ab_dev = ab_ref.copy()
    chunks = [(0, 30), (30, 70), (70, n - 2)]
    hb = _kernel("hb2st_wavefront")
    for j0, j1 in chunks:
        v3, t2, s0 = _native_packed(ab_ref, n, kd, j0, j1)
        ab_j, vt = hb(jnp.asarray(ab_dev), kd, j0, j1)
        ab_dev = np.asarray(ab_j)
        vt = np.asarray(vt)
        np.testing.assert_allclose(ab_dev, ab_ref, atol=1e-8, rtol=0)
        np.testing.assert_allclose(vt[:, :, 1:], v3, atol=1e-8, rtol=0)
        np.testing.assert_allclose(vt[:, :, 0], t2, atol=1e-8, rtol=0)
        assert list(s0) == list(range(j0 + 1, j1 + 1))


@pytest.mark.parametrize("kd", [8, 48])
def test_tb2bd_pallas_wavefront_parity(kd):
    from slate_tpu.linalg.eig import _pack_hh_log
    from slate_tpu.linalg.svd import _bd_sweep_counts
    n = 96 if kd == 8 else 128
    st_ref = _tb_band(n, kd, 11)
    st_dev = st_ref.copy()
    ulog, vlog = native.tb2bd_hh_banded(st_ref, n, kd)
    counts = _bd_sweep_counts(n, kd)
    pu = _pack_hh_log(*ulog, n, kd, counts=counts)
    pv = _pack_hh_log(*vlog, n, kd, counts=counts)
    out_st, ut, vt = map(np.asarray, _kernel("tb2bd_wavefront")(st_dev, kd))
    assert ut.shape == (pu[0].shape[0], pu[0].shape[1], kd + 1)
    np.testing.assert_allclose(out_st, st_ref, atol=1e-8, rtol=0)
    np.testing.assert_allclose(ut[:, :, 1:], pu[0], atol=1e-8, rtol=0)
    np.testing.assert_allclose(ut[:, :, 0], pu[1], atol=1e-8, rtol=0)
    np.testing.assert_allclose(vt[:, :, 1:], pv[0], atol=1e-8, rtol=0)
    np.testing.assert_allclose(vt[:, :, 0], pv[1], atol=1e-8, rtol=0)


def test_device_chase_zero_host_bytes(monkeypatch):
    """Acceptance pin: on the device-chase path metrics.snapshot()
    reports chase.host_bytes == 0 — the band, reflector log and WY
    back-transform never cross the host↔device boundary (only the O(n)
    tridiagonal does, which is stage 3's handoff, not the tunnel)."""
    import jax
    import slate_tpu as st
    from slate_tpu.enums import Uplo
    from slate_tpu.perf import metrics

    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                       "chase=pallas_wavefront")
    was_on = metrics.enabled()
    metrics.reset()
    metrics.on()
    try:
        n = 48
        rng = np.random.default_rng(5)
        g = rng.standard_normal((n, n))
        herm = (g + g.T) / 2
        hm = st.HermitianMatrix(jnp.asarray(herm, jnp.float64),
                                uplo=Uplo.Lower)
        w, z = st.heev(hm, jobz=True, opts={"block_size": 8})
        w = np.asarray(w)
        z = np.asarray(z)
        resid = (np.linalg.norm(herm @ z - z * w[None, :])
                 / (np.linalg.norm(herm) * n * np.finfo(np.float64).eps))
        assert resid < 50, resid
        snap = metrics.snapshot()["counters"]
        assert snap.get("chase.dispatch.pallas_wavefront", 0) >= 1
        assert snap.get("chase.host_bytes") == 0.0
    finally:
        metrics.reset()
        if not was_on:
            metrics.off()
