"""OpenMP wavefront chase (native/runtime.cc hb2st_hh_wave /
tb2bd_hh_wave) vs the serial chase: BITWISE identity.

The wavefront schedules task (sweep j, window w) at stagger t = 3j + w;
same-t tasks touch disjoint band rows and every dependence crosses a t
boundary (reference: the task-DAG of ``src/hb2st.cc:23-90``), so the
parallel schedule must reproduce the serial chase exactly — band, logs,
and counts — at every thread count.  Correctness of the SCHEDULE is
verifiable on a 1-core host (the tasks execute in a different order
than serial even with one thread); true-concurrency races need a
multicore host, which is why the identity is pinned for 1/2/4 threads.
"""

import os

import numpy as np
import pytest

from slate_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native runtime unavailable")


def _restore_env(prev):
    if prev is None:
        os.environ.pop("SLATE_TPU_CHASE_SERIAL", None)
    else:
        os.environ["SLATE_TPU_CHASE_SERIAL"] = prev


def _band_wide(n, kd, seed, dtype=np.float64):
    rng = np.random.default_rng(seed)
    abw = np.zeros((n, 2 * kd + 2), dtype=dtype)
    for d in range(kd + 1):
        v = rng.standard_normal(n - d)
        if np.issubdtype(dtype, np.complexfloating) and d > 0:
            v = v + 1j * rng.standard_normal(n - d)
        abw[:n - d, d] = v      # Hermitian band: real diagonal
    return abw


def _hb2st_full(abw, n, kd):
    """Full chase via the dtype-generic range entry (the f64-only
    ``hb2st_hh_banded`` fast path has no c128 twin; sweeping the whole
    range runs the identical wavefront schedule)."""
    return native.hb2st_hh_banded_range(abw, n, kd, 0, max(n - 2, 0))


def _tb_band(n, kd, seed):
    rng = np.random.default_rng(seed)
    ldw = 3 * kd + 2
    st = np.zeros((n, ldw), dtype=np.float64)
    for r in range(n):
        for c in range(r, min(r + kd + 1, n)):
            st[r, c - r + kd] = rng.standard_normal()
    return st


@pytest.mark.parametrize("dtype", [np.float64, np.complex128],
                         ids=["f64", "c128"])
@pytest.mark.parametrize("nthreads", [1, 2, 4])
def test_hb2st_wavefront_bitwise_identity(nthreads, dtype):
    """Both dtypes: a complex-only scheduling bug (the c128 chase is a
    separate template instantiation) must not hide behind the loose
    end-to-end pheev residual gates."""
    n, kd = 2048, 64
    ab_ser = _band_wide(n, kd, 0, dtype)
    ab_par = ab_ser.copy()

    prev = os.environ.get("SLATE_TPU_CHASE_SERIAL")
    os.environ["SLATE_TPU_CHASE_SERIAL"] = "1"
    try:
        vs, ts, rs, ls = _hb2st_full(ab_ser, n, kd)
    finally:
        _restore_env(prev)

    prev_thr = native.num_threads()
    native.set_num_threads(nthreads)
    try:
        vp, tp, rp, lp = _hb2st_full(ab_par, n, kd)
    finally:
        native.set_num_threads(prev_thr)

    np.testing.assert_array_equal(ab_par, ab_ser)
    np.testing.assert_array_equal(vp, vs)
    np.testing.assert_array_equal(tp, ts)
    np.testing.assert_array_equal(rp, rs)
    np.testing.assert_array_equal(lp, ls)


def test_hb2st_full_entry_matches_range_entry():
    """The f64-only fast entry and the range entry over [0, n-2) must
    produce the same chase (guards the shared schedule staying shared)."""
    n, kd = 512, 32
    ab_a = _band_wide(n, kd, 3)
    ab_b = ab_a.copy()
    out_a = native.hb2st_hh_banded(ab_a, n, kd)
    out_b = _hb2st_full(ab_b, n, kd)
    np.testing.assert_array_equal(ab_a, ab_b)
    for a, b in zip(out_a, out_b):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128],
                         ids=["f64", "c128"])
def test_hb2st_wavefront_range_identity(dtype):
    """The checkpointed sweep-range path uses the wavefront too."""
    n, kd = 512, 32
    ab_ser = _band_wide(n, kd, 1, dtype)
    ab_par = ab_ser.copy()
    chunks = [(0, 100), (100, 317), (317, n - 2)]

    prev = os.environ.get("SLATE_TPU_CHASE_SERIAL")
    os.environ["SLATE_TPU_CHASE_SERIAL"] = "1"
    try:
        ser = [native.hb2st_hh_banded_range(ab_ser, n, kd, j0, j1)
               for j0, j1 in chunks]
    finally:
        _restore_env(prev)
    prev_thr = native.num_threads()
    native.set_num_threads(2)
    try:
        par = [native.hb2st_hh_banded_range(ab_par, n, kd, j0, j1)
               for j0, j1 in chunks]
    finally:
        native.set_num_threads(prev_thr)
    np.testing.assert_array_equal(ab_par, ab_ser)
    for s, p in zip(ser, par):
        for a, b in zip(s, p):
            np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("nthreads", [1, 2, 4])
def test_tb2bd_wavefront_bitwise_identity(nthreads):
    n, kd = 1024, 48
    st_ser = _tb_band(n, kd, 2)
    st_par = st_ser.copy()

    prev = os.environ.get("SLATE_TPU_CHASE_SERIAL")
    os.environ["SLATE_TPU_CHASE_SERIAL"] = "1"
    try:
        ser = native.tb2bd_hh_banded(st_ser, n, kd)
    finally:
        _restore_env(prev)
    prev_thr = native.num_threads()
    native.set_num_threads(nthreads)
    try:
        par = native.tb2bd_hh_banded(st_par, n, kd)
    finally:
        native.set_num_threads(prev_thr)

    np.testing.assert_array_equal(st_par, st_ser)
    for log_s, log_p in zip(ser, par):
        for a, b in zip(log_s, log_p):
            np.testing.assert_array_equal(a, b)
