"""MULTICHIP artifact schema: the base dry-run wrapper fields plus the
r7 per-device overlap/efficiency block (``MULTICHIP_ATTR`` tail line,
produced by ``dist_util.overlap_summary``) that graduates the artifacts
from smoke markers to the scaling-curve input of ROADMAP item 3.

Old artifacts (r01–r05) predate the overlap block and must validate
WITHOUT it; any artifact that carries one must carry it complete."""

import glob
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from slate_tpu.perf import metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BASE_KEYS = {"n_devices": int, "rc": int}

_OVERLAP_KEYS = {
    "n_devices": int,
    "platform": str,
    "ici_gbs": (int, float),
    "collective_count": (int, float),
    "collective_bytes": (int, float),
    "collective_min_s": (int, float),
    "overlapped_collective_s": (int, float),
    "exposed_collective_s": (int, float),
    "overlap_efficiency": (int, float),
    "per_device": list,
}

_PER_DEVICE_KEYS = {
    "device": int,
    "collective_bytes": (int, float),
    "overlapped_collective_s": (int, float),
    "exposed_collective_s": (int, float),
    "overlap_efficiency": (int, float),
}


def _check_overlap_block(blk):
    for key, typ in _OVERLAP_KEYS.items():
        assert key in blk, f"overlap block missing {key}"
        assert isinstance(blk[key], typ), (key, blk[key])
    assert blk["n_devices"] >= 1
    assert len(blk["per_device"]) == blk["n_devices"]
    assert 0.0 <= blk["overlap_efficiency"] <= 1.0
    assert blk["overlapped_collective_s"] + blk["exposed_collective_s"] \
        == pytest.approx(blk["collective_min_s"], rel=1e-6, abs=1e-12)
    for i, dev in enumerate(blk["per_device"]):
        for key, typ in _PER_DEVICE_KEYS.items():
            assert key in dev, f"per-device entry missing {key}"
            assert isinstance(dev[key], typ), (key, dev[key])
        assert dev["device"] == i
        assert 0.0 <= dev["overlap_efficiency"] <= 1.0


def _overlap_blocks_in_tail(tail: str):
    out = []
    for line in tail.splitlines():
        if line.startswith("MULTICHIP_ATTR "):
            out.append(json.loads(line[len("MULTICHIP_ATTR "):]))
    return out


def test_checked_in_multichip_artifacts_validate():
    paths = sorted(glob.glob(os.path.join(_REPO, "MULTICHIP_r*.json")))
    assert paths, "no MULTICHIP artifacts checked in"
    for path in paths:
        with open(path) as f:
            blob = json.load(f)
        for key, typ in _BASE_KEYS.items():
            assert key in blob, f"{path}: missing {key}"
            assert isinstance(blob[key], typ), (path, key)
        assert isinstance(blob.get("tail", ""), str)
        # the overlap block is OPTIONAL (r01-r05 predate it) but must be
        # complete wherever it appears
        for blk in _overlap_blocks_in_tail(blob.get("tail", "")):
            _check_overlap_block(blk)


def test_overlap_summary_schema_from_live_counters(mesh8):
    """Run one fused panel broadcast on the virtual mesh with the
    registry on, then validate ``overlap_summary`` end to end — the
    block ``dryrun_multichip`` prints as the MULTICHIP_ATTR line."""
    from slate_tpu._jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from slate_tpu.parallel import dist_util
    from slate_tpu.parallel.mesh import AXIS_P, AXIS_Q

    metrics.off()
    metrics.reset()
    metrics.on()
    try:
        p, nb, mlb = 2, 2, 2
        M = mlb * nb * p

        def kernel(col):
            r = jax.lax.axis_index(AXIS_P)
            grows = dist_util.local_grows(mlb, nb, p, r)
            own = jnp.ones((mlb * nb, 1), jnp.float32)
            return dist_util.bcast_block_col(col, grows, own, M)

        fn = shard_map(kernel, mesh=mesh8,
                       in_specs=(P(AXIS_P, None),),
                       out_specs=P(None, None))
        col = jnp.ones((mlb * nb * p, 3), jnp.float32)
        np.asarray(jax.jit(fn)(col))

        # no compute signal -> conservatively fully exposed
        blk = _check_and_return(dist_util.overlap_summary(n_devices=8))
        assert blk["collective_bytes"] >= M * 3 * 4
        assert blk["exposed_collective_s"] == pytest.approx(
            blk["collective_min_s"])
        assert blk["overlap_efficiency"] == 0.0

        # with an explicit overlap budget the collectives hide under it
        blk2 = _check_and_return(
            dist_util.overlap_summary(n_devices=8, compute_s=10.0))
        assert blk2["overlap_efficiency"] == 1.0
        assert blk2["exposed_collective_s"] == 0.0
        json.loads(json.dumps(blk2))   # the artifact line is JSON-clean
    finally:
        metrics.reset()
        metrics.off()


def _check_and_return(blk):
    _check_overlap_block(blk)
    return blk


def test_overlap_summary_without_traffic_is_clean():
    """A mesh-free process (empty registry) still emits a valid block:
    zero bytes, efficiency 1.0 (nothing to expose)."""
    metrics.off()
    metrics.reset()
    metrics.on()
    try:
        from slate_tpu.parallel import dist_util

        blk = dist_util.overlap_summary(n_devices=4)
        _check_overlap_block(blk)
        assert blk["collective_bytes"] == 0.0
        assert blk["overlap_efficiency"] == 1.0
    finally:
        metrics.reset()
        metrics.off()
