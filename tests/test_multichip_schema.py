"""MULTICHIP artifact schema: the base dry-run wrapper fields, the
per-device overlap/efficiency block (``MULTICHIP_ATTR`` tail line,
produced by ``dist_util.overlap_summary``), and the ISSUE 13
scaling-curve artifact (``MULTICHIP_POINT`` lines + the
``MULTICHIP_CURVE`` line assembled by ``dist_util.scaling_curve``:
per-point device count, per-device efficiency normalized to the
1-device point, overlap split per point, pinned efficiency floor) that
``perf/regress.py`` judges across rounds like BENCH_r* — including the
pinned failure on an injected efficiency collapse.

Old artifacts (r01–r05) predate the overlap block AND the curve and
must keep loading; any artifact that carries either must carry it
complete."""

import glob
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from slate_tpu.perf import metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BASE_KEYS = {"n_devices": int, "rc": int}

_OVERLAP_KEYS = {
    "n_devices": int,
    "platform": str,
    "ici_gbs": (int, float),
    "collective_count": (int, float),
    "collective_bytes": (int, float),
    "collective_min_s": (int, float),
    "overlapped_collective_s": (int, float),
    "exposed_collective_s": (int, float),
    "overlap_efficiency": (int, float),
    "per_device": list,
}

_PER_DEVICE_KEYS = {
    "device": int,
    "collective_bytes": (int, float),
    "overlapped_collective_s": (int, float),
    "exposed_collective_s": (int, float),
    "overlap_efficiency": (int, float),
}


_MEASURED_STEP_KEYS = {
    "driver": str,
    "k0": int,
    "k1": int,
    "wall_s": (int, float),
    "bcast_bytes": (int, float),
    "bcast_count": (int, float),
}


def _check_overlap_block(blk):
    for key, typ in _OVERLAP_KEYS.items():
        assert key in blk, f"overlap block missing {key}"
        assert isinstance(blk[key], typ), (key, blk[key])
    # ISSUE 15: blocks name their compute-budget provenance; the
    # measured per-step rows must be complete wherever they appear
    if "compute_source" in blk:
        assert blk["compute_source"] in ("measured_steps", "explicit",
                                         "timers", "none")
        assert (blk["compute_source"] == "measured_steps") \
            == ("measured_steps" in blk)
    if "measured_steps" in blk:
        ms = blk["measured_steps"]
        assert ms["count"] == len(ms["per_step"]) >= 1
        for row in ms["per_step"]:
            for key, typ in _MEASURED_STEP_KEYS.items():
                assert key in row, f"measured step missing {key}"
                assert isinstance(row[key], typ), (key, row[key])
        assert ms["wall_s_total"] == pytest.approx(
            sum(r["wall_s"] for r in ms["per_step"]), rel=1e-6)
    assert blk["n_devices"] >= 1
    assert len(blk["per_device"]) == blk["n_devices"]
    assert 0.0 <= blk["overlap_efficiency"] <= 1.0
    assert blk["overlapped_collective_s"] + blk["exposed_collective_s"] \
        == pytest.approx(blk["collective_min_s"], rel=1e-6, abs=1e-12)
    for i, dev in enumerate(blk["per_device"]):
        for key, typ in _PER_DEVICE_KEYS.items():
            assert key in dev, f"per-device entry missing {key}"
            assert isinstance(dev[key], typ), (key, dev[key])
        assert dev["device"] == i
        assert 0.0 <= dev["overlap_efficiency"] <= 1.0


def _overlap_blocks_in_tail(tail: str):
    out = []
    for line in tail.splitlines():
        if line.startswith("MULTICHIP_ATTR "):
            out.append(json.loads(line[len("MULTICHIP_ATTR "):]))
    return out


_CURVE_POINT_KEYS = {
    "n_devices": int,
    "n": int,
    "nb": int,
    "wall_s": (int, float),
    "gflops": (int, float),
    "per_device_gflops": (int, float),
    "per_device_efficiency": (int, float),
}


def _check_curve(curve):
    """The scaling-curve block: sorted points, the 1-device anchor at
    efficiency 1.0 when present, a positive pinned floor, and a
    COMPLETE overlap block wherever one is attached."""
    assert isinstance(curve, dict)
    assert isinstance(curve.get("efficiency_floor"), (int, float))
    assert curve["efficiency_floor"] > 0
    pts = curve.get("points")
    assert isinstance(pts, list) and pts
    devs = []
    for pt in pts:
        for key, typ in _CURVE_POINT_KEYS.items():
            assert key in pt, f"curve point missing {key}"
            assert isinstance(pt[key], typ), (key, pt[key])
        assert "overlap" in pt, "curve point missing overlap split"
        if isinstance(pt["overlap"], dict):
            _check_overlap_block(pt["overlap"])
        devs.append(pt["n_devices"])
        if pt["n_devices"] == 1 and pt["gflops"] > 0:
            assert pt["per_device_efficiency"] == pytest.approx(1.0)
    assert devs == sorted(devs)


def _curves_in_tail(tail: str):
    return [json.loads(ln[len("MULTICHIP_CURVE "):])
            for ln in tail.splitlines()
            if ln.startswith("MULTICHIP_CURVE ")]


def test_checked_in_multichip_artifacts_validate():
    paths = sorted(glob.glob(os.path.join(_REPO, "MULTICHIP_r*.json")))
    assert paths, "no MULTICHIP artifacts checked in"
    for path in paths:
        with open(path) as f:
            blob = json.load(f)
        for key, typ in _BASE_KEYS.items():
            assert key in blob, f"{path}: missing {key}"
            assert isinstance(blob[key], typ), (path, key)
        assert isinstance(blob.get("tail", ""), str)
        # the overlap block and the scaling curve are OPTIONAL
        # (r01-r05 predate both) but must be complete wherever they
        # appear
        for blk in _overlap_blocks_in_tail(blob.get("tail", "")):
            _check_overlap_block(blk)
        for curve in _curves_in_tail(blob.get("tail", "")):
            _check_curve(curve)


def test_overlap_summary_schema_from_live_counters(mesh8):
    """Run one fused panel broadcast on the virtual mesh with the
    registry on, then validate ``overlap_summary`` end to end — the
    block ``dryrun_multichip`` prints as the MULTICHIP_ATTR line."""
    from slate_tpu._jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from slate_tpu.parallel import dist_util
    from slate_tpu.parallel.mesh import AXIS_P, AXIS_Q

    metrics.off()
    metrics.reset()
    metrics.on()
    try:
        p, nb, mlb = 2, 2, 2
        M = mlb * nb * p

        def kernel(col):
            r = jax.lax.axis_index(AXIS_P)
            grows = dist_util.local_grows(mlb, nb, p, r)
            own = jnp.ones((mlb * nb, 1), jnp.float32)
            return dist_util.bcast_block_col(col, grows, own, M)

        fn = shard_map(kernel, mesh=mesh8,
                       in_specs=(P(AXIS_P, None),),
                       out_specs=P(None, None))
        col = jnp.ones((mlb * nb * p, 3), jnp.float32)
        np.asarray(jax.jit(fn)(col))

        # no compute signal -> conservatively fully exposed
        blk = _check_and_return(dist_util.overlap_summary(n_devices=8))
        assert blk["collective_bytes"] >= M * 3 * 4
        assert blk["exposed_collective_s"] == pytest.approx(
            blk["collective_min_s"])
        assert blk["overlap_efficiency"] == 0.0

        # with an explicit overlap budget the collectives hide under it
        blk2 = _check_and_return(
            dist_util.overlap_summary(n_devices=8, compute_s=10.0))
        assert blk2["overlap_efficiency"] == 1.0
        assert blk2["exposed_collective_s"] == 0.0
        json.loads(json.dumps(blk2))   # the artifact line is JSON-clean
    finally:
        metrics.reset()
        metrics.off()


def _check_and_return(blk):
    _check_overlap_block(blk)
    return blk


def test_overlap_summary_window_isolates_back_to_back_runs(mesh8):
    """ISSUE 15 satellite: the overlap budget and byte totals must be
    windowable — a long-lived process accumulates ``driver.*`` timers
    and collective counters across every run it ever made, and the old
    lifetime-snapshot read inflated a later run's overlap block with
    the earlier runs' signal."""
    from slate_tpu._jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from slate_tpu.parallel import dist_util
    from slate_tpu.parallel.mesh import AXIS_P, AXIS_Q

    metrics.off()
    metrics.reset()
    metrics.on()
    try:
        p, nb, mlb = 2, 2, 2
        M = mlb * nb * p

        def kernel(col):
            r = jax.lax.axis_index(AXIS_P)
            grows = dist_util.local_grows(mlb, nb, p, r)
            own = jnp.ones((mlb * nb, 1), jnp.float32)
            return dist_util.bcast_block_col(col, grows, own, M)

        fn = shard_map(kernel, mesh=mesh8,
                       in_specs=(P(AXIS_P, None),),
                       out_specs=P(None, None))
        # a stale compute signal from "an earlier run" of this process
        metrics.observe_time("driver.stale_earlier_run", 123.0)
        # run 1 (w=3) traces and counts its bytes; run 2 (w=5) is a new
        # shape, so it traces and counts its own — the window around
        # run 2 must carry run 2's bytes only
        np.asarray(jax.jit(fn)(jnp.ones((mlb * nb * p, 3),
                                        jnp.float32)))
        snap1 = metrics.snapshot()
        np.asarray(jax.jit(fn)(jnp.ones((mlb * nb * p, 5),
                                        jnp.float32)))
        window = metrics.snapshot_delta(snap1, metrics.snapshot())

        blk = _check_and_return(
            dist_util.overlap_summary(n_devices=8, window=window))
        assert blk["collective_bytes"] == M * 5 * 4   # run 2 only
        # the stale lifetime timer must NOT leak into the window's
        # budget: no in-window compute signal -> fully exposed
        assert blk["compute_source"] == "none"
        assert blk["overlap_efficiency"] == 0.0

        life = _check_and_return(dist_util.overlap_summary(n_devices=8))
        assert life["collective_bytes"] == M * 3 * 4 + M * 5 * 4
        assert life["compute_source"] == "timers"   # the stale timer
        assert life["overlap_efficiency"] == 1.0    # ...inflates it
    finally:
        metrics.reset()
        metrics.off()


def test_overlap_block_measured_fields_under_timeline_knob(
        mesh8, monkeypatch):
    """ISSUE 15 pin: under ``SLATE_TPU_DIST_TIMELINE=1`` the overlap
    block's efficiency comes from MEASURED per-step walls (rows
    present, sums reconciling with the driver wall); with the knob
    unset the conservative ladder stands and no measured fields
    appear."""
    import time as _time

    from slate_tpu.parallel import dist_util, distribute, ppotrf

    metrics.off()
    metrics.reset()
    metrics.on()
    monkeypatch.setenv("SLATE_TPU_DIST_TIMELINE", "1")
    try:
        p, q = 2, 4
        n, nb = 32, 4
        rng = np.random.default_rng(5)
        g = rng.standard_normal((n, n)).astype(np.float32)
        a = g @ g.T + n * np.eye(n, dtype=np.float32)
        ad = distribute(a, mesh8, nb, diag_pad=1.0, row_mult=q,
                        col_mult=p)
        snap0 = metrics.snapshot()
        t0 = _time.perf_counter()
        ppotrf(ad)
        wall = _time.perf_counter() - t0
        window = metrics.snapshot_delta(snap0, metrics.snapshot())
        blk = _check_and_return(
            dist_util.overlap_summary(
                n_devices=8, compute_s=wall, window=window,
                measured_steps=dist_util.timeline_steps()))
        assert blk["compute_source"] == "measured_steps"
        ms = blk["measured_steps"]
        assert ms["count"] == 8                    # nt = 32/4, window 1
        # the per-step span sums reconcile with the driver wall: they
        # are measured INSIDE it, within the chunked-dispatch overhead
        assert 0.0 < ms["wall_s_total"] <= wall * 1.001
        assert blk["compute_s"] == pytest.approx(ms["wall_s_total"])

        # no measured rows passed -> conservative ladder, no measured
        # fields (the rows are never sniffed off module state: stale
        # steps from an earlier run must not misprice a later block)
        blk2 = _check_and_return(
            dist_util.overlap_summary(n_devices=8, compute_s=wall,
                                      window=window))
        assert "measured_steps" not in blk2
        assert blk2["compute_source"] == "explicit"
    finally:
        dist_util.clear_timeline()
        metrics.reset()
        metrics.off()


def test_overlap_summary_without_traffic_is_clean():
    """A mesh-free process (empty registry) still emits a valid block:
    zero bytes, efficiency 1.0 (nothing to expose)."""
    metrics.off()
    metrics.reset()
    metrics.on()
    try:
        from slate_tpu.parallel import dist_util

        blk = dist_util.overlap_summary(n_devices=4)
        _check_overlap_block(blk)
        assert blk["collective_bytes"] == 0.0
        assert blk["overlap_efficiency"] == 1.0
    finally:
        metrics.reset()
        metrics.off()


# ---------------------------------------------------------------------------
# ISSUE 13: the scaling-curve artifact and its regression judge
# ---------------------------------------------------------------------------

def _mk_points(effs):
    """Synthetic weak-scaling points shaped exactly like the
    ``MULTICHIP_POINT`` lines ``__graft_entry__._scaling_point``
    emits: per-device GFLOP/s = ``eff`` relative to the 1-device
    anchor's 2.0."""
    return [{"n_devices": nd, "n": 32 * nd, "nb": 8, "wall_s": 0.25,
             "gflops": 2.0 * nd * eff, "overlap": None}
            for nd, eff in effs]


def test_scaling_curve_assembly_normalizes_to_one_device():
    from slate_tpu.parallel import dist_util

    curve = dist_util.scaling_curve(
        _mk_points([(4, 0.7), (1, 1.0), (2, 0.9), (8, 0.6)]))
    _check_curve(curve)
    pts = curve["points"]
    assert [p["n_devices"] for p in pts] == [1, 2, 4, 8]
    assert [round(p["per_device_efficiency"], 6) for p in pts] \
        == [1.0, 0.9, 0.7, 0.6]
    json.loads(json.dumps(curve))        # the artifact line is JSON-clean


def _wrap_curve(path, curve):
    tail = "DRYRUN_MULTICHIP_OK r6\nMULTICHIP_CURVE " \
        + json.dumps(curve) + "\n"
    with open(path, "w") as f:
        json.dump({"n_devices": 8, "rc": 0, "ok": True,
                   "skipped": False, "tail": tail}, f)
    return str(path)


def test_regress_judges_curve_and_fails_on_injected_collapse(tmp_path):
    """The acceptance pin: a healthy curve passes the sentinel; an
    injected per-device-efficiency collapse (a point under the pinned
    floor) fails CI like any bench regression — even as the ONLY
    artifact, via the ``*_over_floor`` sentinel row."""
    from slate_tpu.parallel import dist_util
    from slate_tpu.perf import regress

    good = dist_util.scaling_curve(
        _mk_points([(1, 1.0), (2, 0.9), (4, 0.8), (8, 0.75)]),
        floor=0.5)
    bad = dist_util.scaling_curve(
        _mk_points([(1, 1.0), (2, 0.9), (4, 0.3), (8, 0.05)]),
        floor=0.5)
    ga = regress.load_artifact(_wrap_curve(tmp_path / "good.json", good))
    assert not ga.infra
    assert ga.submetrics["multichip_d8_perdev_eff"] \
        == pytest.approx(0.75)
    assert regress.diff([ga]).exit_code == 0

    ba = regress.load_artifact(_wrap_curve(tmp_path / "bad.json", bad))
    rep = regress.diff([ba])
    assert rep.exit_code == 1
    floor_rows = [r for r in rep.rows
                  if r.label == "multichip_min_eff_over_floor"]
    assert floor_rows and floor_rows[0].verdict == "REGRESS"
    assert "below pinned floor" in floor_rows[0].note
    # across rounds the per-device rows diff like any BENCH metric
    pair = regress.diff([ga, ba])
    assert pair.exit_code == 1
    assert any(r.label == "multichip_d8_perdev_eff"
               and r.verdict == "REGRESS" for r in pair.rows)


def test_old_multichip_artifacts_load_clean_in_regress():
    """r03–r05 (rc=0, no curve) are provenance-noted, never
    infra-shaped; red rounds (r01/r02, rc=1) stay infra-shaped."""
    from slate_tpu.perf import regress

    for name, want_infra in (("MULTICHIP_r05.json", False),
                             ("MULTICHIP_r03.json", False),
                             ("MULTICHIP_r01.json", True)):
        art = regress.load_artifact(os.path.join(_REPO, name))
        assert bool(art.infra) == want_infra, (name, art.infra)
        if not want_infra:
            assert "predates scaling curve" in art.notes


def test_dryrun_default_sweep_covers_1_2_4_8():
    """The driver-facing default: the weak-scaling sweep covers at
    least 1, 2, 4 and 8 simulated devices."""
    import inspect

    import __graft_entry__ as g

    sig = inspect.signature(g.dryrun_multichip)
    assert tuple(sig.parameters["scale_counts"].default) == (1, 2, 4, 8)


@pytest.mark.slow
def test_dryrun_emits_scaling_curve_end_to_end(capfd):
    """Reduced-scale end-to-end: the real subprocess sweep emits one
    MULTICHIP_POINT per device count (each with a complete overlap
    block) and a schema-valid MULTICHIP_CURVE whose 1-device anchor is
    efficiency 1.0."""
    import __graft_entry__ as g

    g.dryrun_multichip(2, scale_counts=(1, 2))
    out = capfd.readouterr().out
    points = [json.loads(ln[len("MULTICHIP_POINT "):])
              for ln in out.splitlines()
              if ln.startswith("MULTICHIP_POINT ")]
    assert [p["n_devices"] for p in points] == [1, 2]
    for p in points:
        _check_overlap_block(p["overlap"])
    curves = _curves_in_tail(out)
    assert len(curves) == 1
    _check_curve(curves[0])
    assert [p["n_devices"] for p in curves[0]["points"]] == [1, 2]
