"""Autotuned backend dispatch (slate_tpu/perf/autotune.py): decision
engine, cache round-trip (a fresh importlib-reloaded module must reuse
the on-disk winner with ZERO timing repetitions), stale-cache
invalidation on version-key change, forced-choice env overrides, and
default-config (``auto``) driver correctness."""

import importlib
import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.perf import autotune
from slate_tpu.perf.autotune import Candidate


@pytest.fixture
def atab(tmp_path, monkeypatch):
    """A fresh table bound to a tmp cache file; restored after."""
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    autotune.reset_table()
    yield autotune
    autotune.reset_table()


def _toy(name, delay, result="out"):
    def setup():
        def run():
            time.sleep(delay)
            return result
        return run
    return Candidate(name, setup)


class TestEngine:
    def test_times_picks_winner_and_persists(self, atab, monkeypatch):
        monkeypatch.setattr(atab, "_on_tpu", lambda: True)
        got = atab.decide("toyop", (1, 2), [_toy("slow", 0.02),
                                            _toy("fast", 0.0)])
        assert got == "fast"
        assert atab.timing_reps() > 0
        blob = json.load(open(atab.table().path))
        assert blob["version"] == atab._version_key()
        assert blob["decisions"]["toyop|1,2"]["backend"] == "fast"
        assert "slow" in blob["decisions"]["toyop|1,2"]["times"]

    def test_cache_roundtrip_zero_timing_reps(self, atab, monkeypatch):
        monkeypatch.setattr(atab, "_on_tpu", lambda: True)
        atab.decide("toyop", (1, 2), [_toy("slow", 0.02), _toy("fast", 0.0)])
        # "second process": drop the in-memory table, re-read the disk
        # cache, and re-resolve the same key — no clock may start
        atab.reset_table()
        got = atab.decide("toyop", (1, 2),
                          [_toy("slow", 0.02), _toy("fast", 0.0)])
        assert got == "fast"
        assert atab.timing_reps() == 0
        assert atab.table().decisions["toyop|1,2"]["source"] == "cache"

    def test_importlib_reloaded_module_reuses_cache(self, atab, monkeypatch):
        monkeypatch.setattr(atab, "_on_tpu", lambda: True)
        atab.decide("toyop", (3, 4), [_toy("slow", 0.02), _toy("fast", 0.0)])
        # fresh module state, same env: the closest in-process stand-in
        # for a new interpreter
        mod = importlib.reload(importlib.import_module(
            "slate_tpu.perf.autotune"))
        try:
            got = mod.decide("toyop", (3, 4),
                             [_toy("slow", 0.02), _toy("fast", 0.0)])
            assert got == "fast"
            assert mod.timing_reps() == 0
        finally:
            mod.reset_table()

    def test_stale_version_invalidates(self, atab, monkeypatch):
        monkeypatch.setattr(atab, "_on_tpu", lambda: True)
        atab.decide("toyop", (1, 2), [_toy("slow", 0.02), _toy("fast", 0.0)])
        path = atab.table().path
        blob = json.load(open(path))
        blob["version"]["jax"] = "0.0.older"
        json.dump(blob, open(path, "w"))
        atab.reset_table()
        atab.decide("toyop", (1, 2), [_toy("slow", 0.02), _toy("fast", 0.0)])
        assert atab.timing_reps() > 0, \
            "a version-key mismatch must retime, not reuse"

    def test_forced_choice_env_override(self, atab, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "toyop=slow")
        monkeypatch.setattr(atab, "_on_tpu", lambda: True)
        got = atab.decide("toyop", (1, 2),
                          [_toy("slow", 0.02), _toy("fast", 0.0)])
        assert got == "slow"
        assert atab.timing_reps() == 0

    def test_disabled_falls_back_to_heuristic_default(self, atab,
                                                      monkeypatch):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE", "0")
        monkeypatch.setattr(atab, "_on_tpu", lambda: True)
        got = atab.decide("toyop", (1, 2),
                          [_toy("preferred", 0.02), _toy("fast", 0.0)])
        assert got == "preferred"
        assert atab.timing_reps() == 0

    def test_accuracy_guard_prunes(self, atab, monkeypatch):
        monkeypatch.setattr(atab, "_on_tpu", lambda: True)
        bad = Candidate("bad", _toy("bad", 0.0).setup, lambda out: False)
        good = Candidate("good", _toy("good", 0.01).setup, lambda out: True)
        assert atab.decide("toyop2", (1,), [bad, good]) == "good"
        info = atab.table().decisions["toyop2|1"]
        assert "accuracy-guard" in str(info.get("times", {}))

    def test_compile_failure_prunes(self, atab, monkeypatch):
        monkeypatch.setattr(atab, "_on_tpu", lambda: True)

        def boom():
            raise RuntimeError("Mosaic: VMEM overflow")

        assert atab.decide("toyop3", (1,),
                           [Candidate("broken", boom),
                            _toy("good", 0.0)]) == "good"

    def test_all_pruned_prefers_stock_xla(self, atab, monkeypatch):
        monkeypatch.setattr(atab, "_on_tpu", lambda: True)

        def boom():
            raise RuntimeError("no")

        got = atab.decide("toyop4", (1,), [Candidate("a", boom),
                                           Candidate("xla", boom)])
        assert got == "xla"
        # xla-first ordering (matmul/trtri shape) must ALSO fall back to
        # xla, not the pruned pallas candidate
        got = atab.decide("toyop5", (1,), [Candidate("xla", boom),
                                           Candidate("pallas", boom)])
        assert got == "xla"

    def test_lu_panel_force_on_skips_timing(self, atab, monkeypatch):
        from slate_tpu import config as cfg
        monkeypatch.setattr(cfg, "use_pallas", True)
        monkeypatch.setattr(atab, "_on_tpu", lambda: True)
        got = atab.choose_lu_panel(4096, 512, jnp.float32, eligible=True)
        assert got == "pallas"
        assert atab.timing_reps() == 0


class TestBenchWatchdog:
    def test_deadline_fires_and_passthrough(self):
        bench = pytest.importorskip("bench")
        assert bench._run_with_deadline(lambda: 42, 5) == 42

        def hang():
            time.sleep(3)
            return "never"

        t0 = time.perf_counter()
        with pytest.raises(bench._RoutineTimeout):
            bench._run_with_deadline(hang, 0.2)
        assert time.perf_counter() - t0 < 2.5, \
            "the watchdog must interrupt, not wait the routine out"

    def test_partial_aggregate_is_parseable_last_line(self):
        bench = pytest.importorskip("bench")
        agg = bench._partial_aggregate(
            {"gemm_fp32_n1024": 100.0, "potrf_fp32_n1024": 50.0,
             "gemm_fp64_n512": 7.0}, [], ["potrf_fp64: hard-hung"])
        assert agg["metric"] == "factor_suite_fp32_geomean"
        assert agg["partial"] is True
        # fp32 headline geomean only, like the full aggregate
        assert agg["value"] == round(float(np.sqrt(100.0 * 50.0)), 1)
        assert any("hard-hung" in f for f in agg["failed"])
        json.dumps(agg)          # a tail-reading parser must accept it

    def test_timeout_is_infra_not_residual_and_no_retry(self, capsys):
        bench = pytest.importorskip("bench")
        calls = []

        def routine():
            calls.append(1)
            raise bench._RoutineTimeout("deadline")

        sub, fails, infra = {}, [], []
        got = bench._run_routine("hung", routine, sub, fails, infra)
        assert got is None and not fails and len(infra) == 1
        assert len(calls) == 1, "a deadline hit must not retry"
        line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert line["routine"] == "hung" and "infra" in line["error"]
        assert "autotune" in line


class TestConfigTriState:
    def test_env_parse(self, monkeypatch):
        from slate_tpu import config as cfg
        for raw, want in (("auto", "auto"), ("1", True), ("on", True),
                          ("0", False), ("off", False), ("", False)):
            monkeypatch.setenv("SLATE_TPU_USE_PALLAS", raw)
            mod = importlib.reload(cfg)
            assert mod.use_pallas == want, raw
        monkeypatch.delenv("SLATE_TPU_USE_PALLAS")
        mod = importlib.reload(cfg)
        assert mod.use_pallas == "auto"
        assert mod.use_pallas_mode() == "auto"
        assert mod.f64_mxu_mode() in ("auto", "on", "off")

    def test_monkeypatched_bool_still_works(self, monkeypatch):
        from slate_tpu import config as cfg
        monkeypatch.setattr(cfg, "use_pallas", True)
        assert cfg.use_pallas_mode() == "on"
        monkeypatch.setattr(cfg, "use_pallas", False)
        assert cfg.use_pallas_mode() == "off"


class TestDispatchSites:
    def test_matmul_force_on_routes_pallas(self, atab, monkeypatch):
        from slate_tpu import config as cfg
        from slate_tpu.ops import blocks
        monkeypatch.setattr(cfg, "use_pallas", True)
        rng = np.random.default_rng(0)
        a = jnp.asarray(rng.standard_normal((128, 128)).astype(np.float32))
        b = jnp.asarray(rng.standard_normal((128, 128)).astype(np.float32))
        c = np.asarray(blocks.matmul(a, b))
        ref = np.asarray(a) @ np.asarray(b)
        assert np.abs(c - ref).max() / np.abs(ref).max() < 1e-5
        key = "matmul|128,128,128,float32,HIGH"
        assert atab.decisions().get(key) == "pallas"

    def test_matmul_force_off_routes_xla(self, atab, monkeypatch):
        from slate_tpu import config as cfg
        from slate_tpu.ops import blocks
        monkeypatch.setattr(cfg, "use_pallas", False)
        a = jnp.zeros((128, 128), jnp.float32)
        blocks.matmul(a, a)
        key = "matmul|128,128,128,float32,HIGH"
        assert atab.decisions().get(key) == "xla"

    def test_auto_default_drivers_correct_and_zero_timing(self, atab):
        """Tier-1-style proof: with the default config (tri-state
        ``auto`` everywhere) the drivers stay correct and, off-TPU, the
        autotuner performs ZERO timing repetitions — the acceptance
        criterion for a cache-warm second process holds vacuously on
        every non-TPU host."""
        rng = np.random.default_rng(1)
        n = 96
        g = rng.standard_normal((n, n)).astype(np.float32)
        spd = g @ g.T + n * np.eye(n, dtype=np.float32)
        eps = np.finfo(np.float32).eps

        fac = st.potrf(st.HermitianMatrix(jnp.asarray(spd),
                                          uplo=st.Uplo.Lower))
        l = np.tril(np.asarray(fac.data))
        r = np.linalg.norm(l @ l.T - spd) / (np.linalg.norm(spd) * eps * n)
        assert r < 3

        a = (rng.standard_normal((n, n)).astype(np.float32)
             + n * np.eye(n, dtype=np.float32))
        lu, perm = st.getrf(jnp.asarray(a))
        luv = np.asarray(getattr(lu, 'array', lu))
        lmat = np.tril(luv, -1) + np.eye(n, dtype=np.float32)
        r = (np.linalg.norm(lmat @ np.triu(luv) - a[np.asarray(perm)])
             / (np.linalg.norm(a) * eps * n))
        assert r < 3

        t = rng.standard_normal((2 * n, n)).astype(np.float32)
        packed, taus = st.geqrf(jnp.asarray(t))
        rmat = np.triu(np.asarray(getattr(packed, 'array', packed))[:n])
        r = (np.linalg.norm(t.T @ t - rmat.T @ rmat)
             / (np.linalg.norm(t) ** 2 * eps * np.sqrt(2 * n)))
        assert r < 3

        dec = atab.decisions()
        assert any(k.startswith("potrf_panel|") for k in dec)
        assert any(k.startswith("geqrf_panel|") for k in dec)
        assert any(k.startswith("lu_panel|") for k in dec)
        assert atab.timing_reps() == 0

    def test_potri_highest_precision_gate(self, atab):
        """The potri precision fix: both stages pinned to HIGHEST keep
        the scaled residual inside the reference gate (the on-chip
        failure was the 3-pass-bf16 library default leaking into the
        inverse composition; on CPU this asserts the plumbing holds the
        true-f32 grade)."""
        rng = np.random.default_rng(2)
        n = 64
        g = rng.standard_normal((n, n)).astype(np.float32)
        spd = g @ g.T + n * np.eye(n, dtype=np.float32)
        fac = st.potrf(st.HermitianMatrix(jnp.asarray(spd),
                                          uplo=st.Uplo.Lower))
        inv = st.potri(fac)
        iv = np.asarray(inv.array)
        iv = np.tril(iv) + np.tril(iv, -1).T
        eps = np.finfo(np.float32).eps
        r = (np.linalg.norm(iv @ spd - np.eye(n))
             / (eps * n * np.linalg.cond(spd)))
        assert r < 3
