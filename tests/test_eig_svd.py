"""Eigensolver + SVD tests — mirroring the reference testers
``test/test_heev.cc`` / ``test_hegv.cc`` / ``test_svd.cc``: residual
identities ‖A·Z − Z·Λ‖, orthogonality ‖ZᴴZ − I‖, and comparison against
host LAPACK (numpy/scipy standing in for the ScaLAPACK ``--ref`` path).
"""

import numpy as np
import pytest
import jax.numpy as jnp

import slate_tpu as st
from slate_tpu.enums import MethodEig, Op, Side
from slate_tpu.linalg import eig as eigmod
from slate_tpu.linalg import svd as svdmod


def _herm(rng, n, dtype):
    a = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        a = a + 1j * rng.standard_normal((n, n))
    a = (a + a.conj().T) / 2
    return a.astype(dtype)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb", [(32, 8), (45, 16)])
def test_he2hb_preserves_spectrum(dtype, n, nb):
    rng = np.random.default_rng(42)
    a = _herm(rng, n, dtype)
    f = eigmod.he2hb(jnp.asarray(a), {"block_size": nb})
    band = np.asarray(f.band)
    i, j = np.indices(band.shape)
    assert np.abs(band[np.abs(i - j) > nb]).max() < 1e-12
    ref = np.linalg.eigvalsh(a)
    got = np.linalg.eigvalsh(band)
    assert np.abs(got - ref).max() < 1e-10 * max(1, np.abs(ref).max())
    # Q1 · band · Q1ᴴ = A
    q1 = np.asarray(eigmod.unmtr_he2hb(
        Side.Left, Op.NoTrans, f, jnp.eye(n, dtype=dtype)))
    assert np.abs(q1 @ band @ q1.conj().T - a).max() < 1e-12 * n


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_hb2st(dtype):
    rng = np.random.default_rng(3)
    n, kd = 40, 6
    a = _herm(rng, n, dtype)
    i, j = np.indices(a.shape)
    a[np.abs(i - j) > kd] = 0
    d, e, rots = eigmod.hb2st(a, kd)
    t = np.diag(d) + np.diag(e, -1) + np.diag(e, 1)
    ref = np.linalg.eigvalsh(a)
    assert np.abs(np.linalg.eigvalsh(t) - ref).max() < 1e-11
    # back-transform reproduces band eigenvectors
    w, z_tri = np.linalg.eigh(t)
    z_band = eigmod.unmtr_hb2st(rots, z_tri)
    resid = a @ z_band - z_band * w[None, :]
    assert np.abs(resid).max() < 1e-11


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.complex128])
@pytest.mark.parametrize("method", [MethodEig.DC, MethodEig.QR,
                                    MethodEig.MRRR])
def test_heev(dtype, method):
    rng = np.random.default_rng(7)
    n, nb = 36, 8
    a = _herm(rng, n, dtype)
    w, z = st.heev(jnp.asarray(a), True,
                   {"block_size": nb, "method_eig": method})
    w, z = np.asarray(w), np.asarray(z)
    eps = np.finfo(np.dtype(dtype).char.lower() if np.dtype(dtype).kind == "c"
                   else dtype).eps
    tol = 50 * n * eps * max(1, np.abs(w).max())
    ref = np.linalg.eigvalsh(a.astype(np.complex128 if np.dtype(dtype).kind == "c"
                                      else np.float64))
    assert np.abs(np.sort(w) - np.sort(ref)).max() < tol
    assert np.abs(a @ z - z * w[None, :]).max() < tol
    assert np.abs(z.conj().T @ z - np.eye(n)).max() < tol


def test_heev_vals_only():
    rng = np.random.default_rng(11)
    a = _herm(rng, 30, np.float64)
    w, z = st.heev(jnp.asarray(a), False, {"block_size": 8})
    assert z is None
    assert np.abs(np.sort(np.asarray(w)) - np.linalg.eigvalsh(a)).max() < 1e-11


@pytest.mark.parametrize("itype", [1, 2, 3])
def test_hegv(itype):
    import scipy.linalg as sla
    rng = np.random.default_rng(5)
    n, nb = 28, 8
    a = _herm(rng, n, np.float64)
    b = rng.standard_normal((n, n))
    b = b @ b.T + n * np.eye(n)
    w, z = st.hegv(jnp.asarray(a), jnp.asarray(b), itype, True,
                   {"block_size": nb})
    w, z = np.asarray(w), np.asarray(z)
    ref = sla.eigh(a, b, type=itype, eigvals_only=True)
    assert np.abs(np.sort(w) - np.sort(ref)).max() < 1e-9
    if itype == 1:
        assert np.abs(a @ z - b @ z * w[None, :]).max() < 1e-9


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("m,n", [(40, 40), (56, 32), (32, 56)])
def test_svd(dtype, m, n):
    rng = np.random.default_rng(9)
    a = rng.standard_normal((m, n))
    if np.dtype(dtype).kind == "c":
        a = a + 1j * rng.standard_normal((m, n))
    a = a.astype(dtype)
    s, u, vh = st.svd(jnp.asarray(a), opts={"block_size": 8})
    s, u, vh = np.asarray(s), np.asarray(u), np.asarray(vh)
    k = min(m, n)
    sref = np.linalg.svd(a, compute_uv=False)
    assert np.abs(s - sref).max() < 1e-11 * max(1, sref.max())
    assert np.abs((u * s[None, :]) @ vh - a).max() < 1e-11 * sref.max()
    assert np.abs(u.conj().T @ u - np.eye(k)).max() < 1e-11
    assert np.abs(vh @ vh.conj().T - np.eye(k)).max() < 1e-11


def test_svd_vals():
    rng = np.random.default_rng(13)
    a = rng.standard_normal((48, 24))
    s = np.asarray(st.svd_vals(jnp.asarray(a), {"block_size": 8}))
    assert np.abs(s - np.linalg.svd(a, compute_uv=False)).max() < 1e-11


def test_svd_float32():
    rng = np.random.default_rng(17)
    a = rng.standard_normal((36, 36)).astype(np.float32)
    s, u, vh = st.svd(jnp.asarray(a), opts={"block_size": 8})
    s, u, vh = np.asarray(s), np.asarray(u), np.asarray(vh)
    sref = np.linalg.svd(a.astype(np.float64), compute_uv=False)
    assert np.abs(s - sref).max() < 1e-3
    assert np.abs((u * s[None, :]) @ vh - a).max() < 1e-3


class TestHeevBandFastPath:
    """The Auto-method band fast path (host hbevd) — normally n > 512."""

    def _run(self, n, nb, complex_=False, monkey_thresh=64):
        from slate_tpu.linalg import eig as eig_mod
        rng = np.random.default_rng(99)
        a = rng.standard_normal((n, n))
        if complex_:
            a = a + 1j * rng.standard_normal((n, n))
        a = (a + np.conj(a.T)) / 2
        A = st.HermitianMatrix(jnp.asarray(a), uplo=st.Uplo.Lower,
                               mb=nb, nb=nb)
        saved = eig_mod._BAND_SOLVER_MIN_N
        eig_mod._BAND_SOLVER_MIN_N = monkey_thresh
        try:
            w, z = st.heev(A)
            wv_only, _ = st.heev(A, jobz=False)
        finally:
            eig_mod._BAND_SOLVER_MIN_N = saved
        wv, zv = np.asarray(w), np.asarray(z)
        res = np.linalg.norm(a @ zv - zv * wv[None, :]) / np.linalg.norm(a)
        assert res < 1e-5, f"band fast path residual {res}"
        np.testing.assert_allclose(wv, np.linalg.eigvalsh(a), atol=2e-4)
        np.testing.assert_allclose(np.asarray(wv_only), wv, atol=1e-6)

    def test_real(self):
        self._run(96, 32)

    def test_complex(self):
        self._run(80, 16, complex_=True)

    def test_kd_not_less_than_n(self):
        # nb >= n makes he2hb's kd >= n: the banded conversion must clamp
        self._run(72, 96, monkey_thresh=16)


class TestSvdBandFastPath:
    """The Auto-method SVD band fast path (host gesdd) — normally n > 512."""

    def _run(self, m, n, nb, complex_=False):
        import sys
        svd_mod = sys.modules["slate_tpu.linalg.svd"]
        rng = np.random.default_rng(101)
        a = rng.standard_normal((m, n))
        if complex_:
            a = a + 1j * rng.standard_normal((m, n))
        saved = svd_mod._BAND_SOLVER_MIN_N
        svd_mod._BAND_SOLVER_MIN_N = 16
        try:
            s, u, vh = st.svd(jnp.asarray(a), opts={"nb": nb})
            s_only = st.svd_vals(jnp.asarray(a), opts={"nb": nb})
        finally:
            svd_mod._BAND_SOLVER_MIN_N = saved
        sv = np.asarray(s)
        uv, vhv = np.asarray(u), np.asarray(vh)
        k = min(m, n)
        rec = uv @ np.diag(sv.astype(uv.dtype)) @ vhv
        res = np.linalg.norm(rec - a) / np.linalg.norm(a)
        assert res < 1e-5, f"svd fast path residual {res}"
        sref = np.linalg.svd(a, compute_uv=False)
        np.testing.assert_allclose(sv, sref, atol=2e-4 * sref[0])
        np.testing.assert_allclose(np.asarray(s_only), sv,
                                   atol=1e-6 * sref[0])
        # orthogonality of the factors
        assert np.linalg.norm(np.conj(uv.T) @ uv - np.eye(k)) < 1e-4
        assert np.linalg.norm(vhv @ np.conj(vhv.T) - np.eye(k)) < 1e-4

    def test_square(self):
        self._run(96, 96, 32)

    def test_tall(self):
        self._run(160, 64, 32)

    def test_wide(self):
        self._run(64, 144, 32)

    def test_complex(self):
        self._run(80, 80, 16, complex_=True)


class TestHouseholderChase:
    """Round-3 Householder stage 2 (hebr/gebr schedules) + batched WY
    device appliers — unit-level (the drivers gate this path to
    accelerator backends, so CI exercises it directly)."""

    def test_hb2st_hh_eig_roundtrip(self):
        from slate_tpu import native
        if not native.available():
            pytest.skip("no native toolchain")
        from slate_tpu.linalg.eig import (_hb2st_hh_ab, unmtr_hb2st_hh,
                                          _tridiag_solve)
        rng = np.random.default_rng(11)
        n, kd = 150, 16
        ab = np.zeros((n, 2 * kd + 2))
        ab[:, 0] = rng.standard_normal(n)
        for d in range(1, kd + 1):
            ab[:n - d, d] = rng.standard_normal(n - d)
        a = np.zeros((n, n))
        for d in range(kd + 1):
            for c in range(n - d):
                a[c + d, c] = ab[c, d]
        a = a + np.tril(a, -1).T
        d_t, e_t, log = _hb2st_hh_ab(ab.copy(), kd)
        w, z_tri = _tridiag_solve(d_t, e_t, True, "stevd")
        z = np.asarray(unmtr_hb2st_hh(*log, z_tri, kd))
        assert np.linalg.norm(a @ z - z * w[None, :]) / np.linalg.norm(a) \
            < 1e-13
        assert np.abs(z.T @ z - np.eye(n)).max() < 1e-13

    def test_tb2bd_hh_svd_roundtrip(self):
        from slate_tpu import native
        if not native.available():
            pytest.skip("no native toolchain")
        from slate_tpu.linalg.svd import _band_svd_hh_ab
        rng = np.random.default_rng(12)
        n, kd = 120, 8
        b = np.zeros((n, n))
        for d in range(kd + 1):
            b += np.diag(rng.standard_normal(n - d), d)
        st = np.zeros((n, 3 * kd + 2))
        for r in range(n):
            for c in range(max(0, r - kd), min(n, r + 2 * kd + 2)):
                st[r, c - r + kd] = b[r, c]
        from slate_tpu.enums import MethodSVD
        s, u_b, vh_b = _band_svd_hh_ab(st, kd, True, True,
                                       MethodSVD.Auto, True)
        assert np.linalg.norm(u_b @ np.diag(s) @ vh_b - b) \
            / np.linalg.norm(b) < 1e-13
        assert np.abs(u_b.T @ u_b - np.eye(n)).max() < 1e-13
        assert np.abs(vh_b @ vh_b.T - np.eye(n)).max() < 1e-13
