"""The bench regression sentinel (perf/regress.py + tools/bench_diff.py)
over synthetic BENCH fixtures — a regression, an improvement, and an
infra failure — plus the checked-in r3→r4 geqrf regression.

The CLI is driven via subprocess (it must run WITHOUT importing jax —
that property is part of the contract) and the library directly."""

import json
import os
import subprocess
import sys

import pytest

from slate_tpu.perf import regress

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CLI = os.path.join(_REPO, "tools", "bench_diff.py")
_GAP_CLI = os.path.join(_REPO, "tools", "gap_report.py")


def _wrapper(tmp_path, name, submetrics, rc=0, parsed=True, autotune=None):
    agg = None
    if parsed:
        agg = {"metric": "factor_suite_fp32_geomean", "value": 1.0,
               "unit": "GFLOP/s", "vs_baseline": 1.0,
               "submetrics": submetrics}
        if autotune is not None:
            agg["autotune"] = autotune
    blob = {"n": 1, "cmd": "bench", "rc": rc, "tail": "", "parsed": agg}
    p = tmp_path / name
    p.write_text(json.dumps(blob))
    return str(p)


_BASE = {"gemm_fp32_n8192": 50000.0, "geqrf_fp32_m32768_n4096": 23525.9}


def _run_cli(*args):
    return subprocess.run([sys.executable, _CLI, *args],
                          capture_output=True, text=True)


# ---------------------------------------------------------------------------
# CLI over synthetic fixtures
# ---------------------------------------------------------------------------

def test_cli_flags_regression_nonzero_exit(tmp_path):
    old = _wrapper(tmp_path, "r1.json", _BASE)
    new = _wrapper(tmp_path, "r2.json",
                   {"gemm_fp32_n8192": 50100.0,
                    "geqrf_fp32_m32768_n4096": 18905.2})
    r = _run_cli(old, new)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESS" in r.stdout
    assert "geqrf_fp32_m32768_n4096" in r.stdout
    assert "FAIL" in r.stdout


def test_cli_improvement_exits_zero(tmp_path):
    old = _wrapper(tmp_path, "r1.json", _BASE)
    new = _wrapper(tmp_path, "r2.json",
                   {"gemm_fp32_n8192": 50100.0,
                    "geqrf_fp32_m32768_n4096": 30000.0})
    r = _run_cli(old, new)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "IMPROVE" in r.stdout
    assert "PASS" in r.stdout


def test_cli_infra_artifact_nonzero_exit(tmp_path):
    old = _wrapper(tmp_path, "r1.json", _BASE)
    bad = _wrapper(tmp_path, "r2.json", {}, rc=124, parsed=False)
    r = _run_cli(old, bad)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "INFRA" in r.stdout and "rc=124" in r.stdout


def test_cli_threshold_knob(tmp_path):
    old = _wrapper(tmp_path, "r1.json", {"gemm_fp32_n8192": 100.0})
    new = _wrapper(tmp_path, "r2.json", {"gemm_fp32_n8192": 92.0})
    assert _run_cli(old, new).returncode == 1            # -8% > 5%
    assert _run_cli(old, new, "--threshold", "10").returncode == 0


def test_cli_json_output(tmp_path):
    old = _wrapper(tmp_path, "r1.json", _BASE)
    new = _wrapper(tmp_path, "r2.json",
                   {"geqrf_fp32_m32768_n4096": 18905.2})
    r = _run_cli(old, new, "--json")
    blob = json.loads(r.stdout)
    verdicts = {row["label"]: row["verdict"] for row in blob["rows"]}
    assert verdicts["geqrf_fp32_m32768_n4096"] == "REGRESS"
    assert verdicts["gemm_fp32_n8192"] == "GONE"
    assert blob["exit_code"] == 1


def _poison_env(tmp_path):
    poison = tmp_path / "poison"
    poison.mkdir(exist_ok=True)
    (poison / "jax").mkdir(exist_ok=True)
    (poison / "jax" / "__init__.py").write_text(
        "raise ImportError('offline tool must not import jax')")
    return dict(os.environ,
                PYTHONPATH=str(poison) + os.pathsep
                + os.environ.get("PYTHONPATH", ""))


def test_cli_does_not_import_jax(tmp_path):
    """The sentinel must stay runnable on jax-free machines: poison the
    path so any jax import explodes — --explain included (it loads the
    attribution engine by file path)."""
    old = _wrapper(tmp_path, "r1.json", _BASE)
    new = _wrapper(tmp_path, "r2.json", _BASE)
    env = _poison_env(tmp_path)
    r = subprocess.run([sys.executable, _CLI, old, new, "--explain"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# CI: the offline tools over the CHECKED-IN artifacts (subprocess,
# stdlib interpreter) — the gap-report toolchain cannot rot unseen
# ---------------------------------------------------------------------------

def test_cli_explain_attributes_r03_r04_geqrf_to_update_stage(tmp_path):
    """Acceptance: `bench_diff.py --explain` on the checked-in r03→r04
    pair attributes the known geqrf 23.5→18.9 TF/s regression to the
    update stage — no hand-tuned special case, no jax import."""
    r = subprocess.run([sys.executable, _CLI,
                        os.path.join(_REPO, "BENCH_r03.json"),
                        os.path.join(_REPO, "BENCH_r04.json"),
                        "--explain"],
                       capture_output=True, text=True,
                       env=_poison_env(tmp_path))
    assert r.returncode == 1, r.stdout + r.stderr
    explain = [l for l in r.stdout.splitlines()
               if l.startswith("EXPLAIN ")]
    assert len(explain) == 1, r.stdout
    assert "geqrf_fp32_m32768_n4096" in explain[0]
    assert "update stage" in explain[0]


def test_cli_explain_json_carries_lines(tmp_path):
    old = _wrapper(tmp_path, "r1.json", _BASE)
    new = _wrapper(tmp_path, "r2.json",
                   {"gemm_fp32_n8192": 50100.0,
                    "geqrf_fp32_m32768_n4096": 18905.2})
    r = _run_cli(old, new, "--explain", "--json")
    blob = json.loads(r.stdout)
    assert len(blob["explain"]) == 1
    assert "update stage" in blob["explain"][0]


def test_gap_report_cli_renders_checked_in_artifacts(tmp_path):
    """`gap_report.py` renders the roofline table of both checked-in
    r03/r04 artifacts (derived analytically — they predate embedded
    attribution blocks) on a jax-poisoned path."""
    env = _poison_env(tmp_path)
    for name in ("BENCH_r03.json", "BENCH_r04.json"):
        r = subprocess.run([sys.executable, _GAP_CLI,
                            os.path.join(_REPO, name)],
                           capture_output=True, text=True, env=env)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "gap report: " + name in r.stdout
        assert "getrf_fp32_n8192_nb512" in r.stdout
        assert "bottlenecks:" in r.stdout
        assert "update" in r.stdout


def test_gap_report_cli_json_and_routine_filter():
    r = subprocess.run([sys.executable, _GAP_CLI,
                        os.path.join(_REPO, "BENCH_r04.json"),
                        "--routine", "geqrf", "--json"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    blob = json.loads(r.stdout)
    labels = [rep["label"] for rep in blob["reports"]]
    assert labels == ["geqrf_fp32_m32768_n4096"]
    stages = {s["stage"] for s in blob["reports"][0]["stages"]}
    assert stages == {"panel", "update"}


def test_gap_report_cli_infra_artifact_nonzero():
    r = subprocess.run([sys.executable, _GAP_CLI,
                        os.path.join(_REPO, "BENCH_r05.json")],
                       capture_output=True, text=True)
    assert r.returncode == 1
    assert "INFRA" in r.stderr


# ---------------------------------------------------------------------------
# Library-level semantics
# ---------------------------------------------------------------------------

def test_checked_in_r03_r04_geqrf_regression():
    """Acceptance: the sentinel flags the real r3→r4 geqrf 23.5→18.9
    TF/s drop on the checked-in artifacts."""
    arts = [regress.load_artifact(os.path.join(_REPO, f))
            for f in ("BENCH_r03.json", "BENCH_r04.json")]
    report = regress.diff(arts)
    assert report.exit_code != 0
    reg = {r.label for r in report.regressions}
    assert reg == {"geqrf_fp32_m32768_n4096"}
    row = report.regressions[0]
    assert row.values == [23525.9, 18905.2]
    assert row.delta_pct == pytest.approx(-19.6, abs=0.1)


def test_checked_in_r05_is_infra():
    art = regress.load_artifact(os.path.join(_REPO, "BENCH_r05.json"))
    assert not art.ok
    assert any("rc=124" in r for r in art.infra)
    report = regress.diff([art])
    assert report.exit_code != 0


def test_partial_aggregate_is_infra(tmp_path):
    p = tmp_path / "p.json"
    p.write_text(json.dumps({
        "rc": 0,
        "parsed": {"metric": "m", "partial": True,
                   "submetrics": {"gemm_fp32_n8192": 1.0}}}))
    art = regress.load_artifact(str(p))
    assert any("partial" in r for r in art.infra)


def test_raw_bench_stdout_loads(tmp_path):
    """Raw bench.py output (JSON lines, aggregate LAST) parses too."""
    p = tmp_path / "raw.json"
    p.write_text("\n".join([
        json.dumps({"routine": "gemm", "label": "gemm_fp32_n8192",
                    "gflops": 123.0}),
        "# a stray log line",
        json.dumps({"metric": "factor_suite_fp32_geomean",
                    "submetrics": {"gemm_fp32_n8192": 123.0}}),
    ]))
    art = regress.load_artifact(str(p))
    assert art.ok and art.submetrics == {"gemm_fp32_n8192": 123.0}


def test_label_parsing_and_alignment():
    assert regress.parse_label("geqrf_fp32_m32768_n4096") == \
        ("geqrf", "fp32", "m32768_n4096")
    assert regress.parse_label("getrf_fp32_n8192_nb512") == \
        ("getrf", "fp32", "n8192_nb512")
    assert regress.parse_label("mxu_bf16_n8192") == \
        ("mxu", "bf16", "n8192")


def test_backend_tag_change_noted(tmp_path):
    a1 = _wrapper(tmp_path, "a1.json",
                  {"getrf_fp32_n8192_nb512": 7000.0},
                  autotune={"lu_driver|8192,8192,512,float32,HIGH": "rec"})
    a2 = _wrapper(tmp_path, "a2.json",
                  {"getrf_fp32_n8192_nb512": 7100.0},
                  autotune={"lu_driver|8192,8192,512,float32,HIGH":
                            "scattered"})
    report = regress.diff([regress.load_artifact(a1),
                           regress.load_artifact(a2)])
    row = [r for r in report.rows
           if r.label == "getrf_fp32_n8192_nb512"][0]
    assert "backend changed" in row.note
    assert "rec" in row.note and "scattered" in row.note


def test_dropout_with_history_reads_gone_not_ok(tmp_path):
    """A routine with ≥2 prior values that vanishes from the NEWEST
    artifact must read GONE (silent dropout), never OK."""
    files = [
        _wrapper(tmp_path, "g1.json", {"heev_fp32_n8192": 100.0,
                                       "gemm_fp32_n8192": 1.0}),
        _wrapper(tmp_path, "g2.json", {"heev_fp32_n8192": 100.0,
                                       "gemm_fp32_n8192": 1.0}),
        _wrapper(tmp_path, "g3.json", {"gemm_fp32_n8192": 1.0}),
    ]
    report = regress.diff([regress.load_artifact(f) for f in files])
    verdicts = {r.label: r.verdict for r in report.rows}
    assert verdicts["heev_fp32_n8192"] == "GONE"
    # ... but a drop past threshold stays the more severe verdict
    files[1] = _wrapper(tmp_path, "g2.json", {"heev_fp32_n8192": 50.0,
                                              "gemm_fp32_n8192": 1.0})
    report = regress.diff([regress.load_artifact(f) for f in files])
    verdicts = {r.label: r.verdict for r in report.rows}
    assert verdicts["heev_fp32_n8192"] == "REGRESS"


def test_consecutive_regression_not_masked_by_recovery(tmp_path):
    """A mid-chain drop is a regression even if a later round wins it
    back (first→last delta alone would hide it)."""
    files = [
        _wrapper(tmp_path, "c1.json", {"gemm_fp32_n8192": 100.0}),
        _wrapper(tmp_path, "c2.json", {"gemm_fp32_n8192": 80.0}),
        _wrapper(tmp_path, "c3.json", {"gemm_fp32_n8192": 101.0}),
    ]
    report = regress.diff([regress.load_artifact(f) for f in files])
    assert [r.verdict for r in report.rows] == ["REGRESS"]


def test_hbm_roundtrips_zero_to_n_reads_regress():
    """ISSUE 12 structural family: the round-trip count's expected
    steady state IS 0, which the ratio-based judge skips (prev > 0) —
    a 0 -> N rise (materialized intermediates reappearing) must still
    read REGRESS, and the N -> 0 win must not."""
    a1 = regress.Artifact(path="r1", name="r1", submetrics={
        "gemm_fp32_n8192": 50000.0,
        "getrf_fp32_n8192_nb512_hbm_roundtrips": 0.0})
    a2 = regress.Artifact(path="r2", name="r2", submetrics={
        "gemm_fp32_n8192": 50000.0,
        "getrf_fp32_n8192_nb512_hbm_roundtrips": 3.0})
    by = {r.label: r.verdict for r in regress.diff([a1, a2]).rows}
    assert by["getrf_fp32_n8192_nb512_hbm_roundtrips"] == "REGRESS"
    by2 = {r.label: r.verdict for r in regress.diff([a2, a1]).rows}
    assert by2["getrf_fp32_n8192_nb512_hbm_roundtrips"] in ("OK",
                                                            "IMPROVE")
    # an all-zero history (the steady state) stays OK
    by3 = {r.label: r.verdict for r in regress.diff([a1, a1]).rows}
    assert by3["getrf_fp32_n8192_nb512_hbm_roundtrips"] == "OK"


def test_stage_time_submetrics_are_lower_is_better():
    """The per-stage eig/SVD submetrics are wall SECONDS (suffix
    ``_s``): the device bulge chase shrinking stage2_chase must read
    IMPROVE, and a chase slowdown must read REGRESS — not the other
    way around (every other submetric is GFLOP/s, higher-is-better)."""
    a1 = regress.Artifact(path="r1", name="r1", submetrics={
        "gemm_fp32_n8192": 50000.0,
        "heev_fp64_n1024_stage2_chase_s": 4.0})
    a2 = regress.Artifact(path="r2", name="r2", submetrics={
        "gemm_fp32_n8192": 50000.0,
        "heev_fp64_n1024_stage2_chase_s": 0.4})
    rep = regress.diff([a1, a2])
    by = {r.label: r.verdict for r in rep.rows}
    assert by["heev_fp64_n1024_stage2_chase_s"] == "IMPROVE"
    assert by["gemm_fp32_n8192"] == "OK"
    rep2 = regress.diff([a2, a1])
    by2 = {r.label: r.verdict for r in rep2.rows}
    assert by2["heev_fp64_n1024_stage2_chase_s"] == "REGRESS"
