"""The r5 Pallas LU panel leaf (getrf_panel_linv) and the inverse-based
u12 composition, exercised in interpret mode on CPU so the TPU default
path has CI parity coverage (review finding: zero coverage otherwise)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from slate_tpu.ops.pallas_kernels import getrf_panel_linv
from slate_tpu.linalg.lu import getrf_rec, _panel_lu_pallas


def test_panel_linv_kernel_interpret():
    """Kernel contract on CPU interpret: a[perm]=L·U, exact one-hot
    pivots, linv inverts the unit-lower pivot block."""
    rng = np.random.default_rng(0)
    bb, m = 64, 256
    slab = rng.standard_normal((bb, m)).astype(np.float32)
    act = np.ones((1, m), np.float32)
    out, piv, act_out, linv = jax.jit(
        lambda s, a: getrf_panel_linv(s, a, ib=32))(
        jnp.asarray(slab), jnp.asarray(act))
    out, piv, act_out, linv = map(np.asarray, (out, piv, act_out, linv))
    assert len(set(piv.tolist())) == bb, "pivots must be distinct"
    rem = np.argsort(act_out[0] < 0.5, kind="stable")[: m - bb]
    perm = np.concatenate([piv, rem])
    lu = out[:, perm].T                      # (m, bb) packed
    L = np.tril(lu, -1) + np.vstack([np.eye(bb, dtype=np.float32),
                                     np.zeros((m - bb, bb), np.float32)])
    U = np.triu(lu[:bb])
    a_np = slab.T
    res = np.linalg.norm(L @ U - a_np[perm]) / (
        np.linalg.norm(a_np) * np.finfo(np.float32).eps * m)
    assert res < 60, res
    l11 = np.tril(lu[:bb], -1) + np.eye(bb, dtype=np.float32)
    assert np.linalg.norm(l11 @ linv - np.eye(bb)) < 1e-3
    # pivots are true partial pivots: each pivot is the max |.| of the
    # updated column over the still-active rows (check column 0 exactly)
    assert piv[0] == np.argmax(np.abs(slab[0]))


def test_panel_lu_pallas_wrapper_interpret(monkeypatch):
    """The lu.py wrapper (pad-to-bucket + perm assembly + linv) matches
    scipy on CPU interpret mode."""
    import scipy.linalg as sla
    rng = np.random.default_rng(1)
    m, w = 200, 64                            # forces padding to 512
    a_np = rng.standard_normal((m, w)).astype(np.float32)
    lu, perm, linv = _panel_lu_pallas(jnp.asarray(a_np))
    lu, perm = np.asarray(lu), np.asarray(perm)
    assert sorted(perm.tolist()) == list(range(m))
    L = np.tril(lu, -1) + np.vstack([np.eye(w, dtype=np.float32),
                                     np.zeros((m - w, w), np.float32)])
    U = np.triu(lu[:w])
    res = np.linalg.norm(L @ U - a_np[perm]) / (
        np.linalg.norm(a_np) * np.finfo(np.float32).eps * m)
    assert res < 60, res


def test_getrf_rec_linv_u12_path(monkeypatch):
    """Force the TPU dispatch gate open on CPU so the full getrf_rec
    composition (pallas leaf + inverse-based u12) runs in interpret
    mode and matches the plain path."""
    from slate_tpu.linalg import lu as lu_mod
    monkeypatch.setattr(lu_mod, "_use_pallas_panel",
                        lambda m, w, dtype: dtype == jnp.float32
                        and w % 32 == 0 and m >= w)
    n, nb = 192, 64
    rng = np.random.default_rng(2)
    a_np = (rng.standard_normal((n, n)).astype(np.float32)
            + n * np.eye(n, dtype=np.float32))
    lu, perm = lu_mod.getrf_rec(jnp.asarray(a_np), nb)
    lu, perm = np.asarray(lu), np.asarray(perm)
    L = np.tril(lu, -1) + np.eye(n, dtype=np.float32)
    U = np.triu(lu)
    res = np.linalg.norm(L @ U - a_np[perm]) / (
        np.linalg.norm(a_np) * np.finfo(np.float32).eps * n)
    assert res < 3, res
