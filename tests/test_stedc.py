"""Divide-and-conquer tridiagonal eigensolver — the analog of the
reference's stedc stack tests (``unit_test/``, ``test/test_heev.cc``
with D&C method).  Validates the full solver on varied spectra and the
individual stages (deflate / secular / z_vector / sort)."""

import numpy as np
import pytest
from scipy.linalg import eigvalsh_tridiagonal

import slate_tpu as st
from slate_tpu.linalg import _stedc as dc


def _check(d, e):
    w, q = dc.stedc(d, e)
    n = d.size
    t = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    res = np.linalg.norm(t @ q - q * w[None, :]) / (np.linalg.norm(t)
                                                    + 1e-300)
    orth = np.linalg.norm(q.T @ q - np.eye(n))
    wref = eigvalsh_tridiagonal(d, e)
    werr = np.abs(w - wref).max() / (np.abs(wref).max() + 1e-300)
    assert res < 5e-14, f"residual {res}"
    assert orth < 5e-13, f"orthogonality {orth}"
    assert werr < 1e-12, f"eigenvalue error {werr}"


class TestStedc:
    def test_random(self):
        rng = np.random.default_rng(0)
        _check(rng.standard_normal(100), rng.standard_normal(99))

    def test_random_odd(self):
        rng = np.random.default_rng(1)
        _check(rng.standard_normal(513), rng.standard_normal(512))

    def test_clustered(self):
        rng = np.random.default_rng(2)
        d = np.ones(200) + 1e-14 * rng.standard_normal(200)
        _check(d, 1e-13 * rng.standard_normal(199))

    def test_decoupled(self):
        rng = np.random.default_rng(3)
        _check(rng.standard_normal(64), np.zeros(63))

    def test_toeplitz(self):
        # known analytic spectrum, maximal eigenvalue symmetry
        n = 256
        _check(2 * np.ones(n), -np.ones(n - 1))

    def test_large_magnitude(self):
        # scale 1e9 entries: catches tolerance tests that accidentally
        # scale by the matrix norm twice (the deflation criterion must
        # be absolute, as in dlaed2)
        rng = np.random.default_rng(8)
        _check(1e9 * rng.standard_normal(100),
               1e9 * rng.standard_normal(99))

    def test_large_magnitude_close_eigs(self):
        # well-separated-by-1.0 eigenvalues at scale 1e9 must NOT deflate
        d = np.concatenate([-1e9 + np.arange(50.0), 1e9 + np.arange(50.0)])
        e = 10.0 * np.ones(99)
        _check(d, e)

    def test_graded(self):
        # 12 decades of grading: stresses the under/overflow safety of
        # the Gu-Eisenstat ratio products
        d = np.logspace(0, -12, 128)
        e = np.logspace(-1, -10, 127)
        _check(d, e)

    def test_want_z_false(self):
        rng = np.random.default_rng(4)
        d, e = rng.standard_normal(80), rng.standard_normal(79)
        w = dc.stedc(d, e, want_z=False)
        wref = eigvalsh_tridiagonal(d, e)
        np.testing.assert_allclose(w, wref, atol=1e-12)


class TestStages:
    def test_sort(self):
        d = np.array([3.0, 1.0, 2.0])
        q = np.eye(3)
        ds, qs = st.stedc_sort(d, q)
        np.testing.assert_allclose(ds, [1.0, 2.0, 3.0])
        np.testing.assert_allclose(qs, np.eye(3)[:, [1, 2, 0]])

    def test_z_vector_unit_norm(self):
        rng = np.random.default_rng(5)
        q1 = np.linalg.qr(rng.standard_normal((6, 6)))[0]
        q2 = np.linalg.qr(rng.standard_normal((4, 4)))[0]
        z = st.stedc_z_vector(q1[-1], q2[0])
        assert abs(np.linalg.norm(z) - 1.0) < 1e-14

    def test_deflate_tiny_coupling(self):
        d = np.array([0.0, 1.0, 2.0, 3.0])
        z = np.array([0.5, 1e-20, 0.5, 1e-20])
        keep, d_u, z_u, givens = st.stedc_deflate(d, z, rho=1.0)
        np.testing.assert_array_equal(keep, [True, False, True, False])
        np.testing.assert_allclose(d_u[keep], [0.0, 2.0])
        assert not givens

    def test_deflate_duplicate_poles(self):
        d = np.array([1.0, 1.0 + 1e-18, 2.0])
        z = np.array([0.6, 0.8, 0.1])
        keep, d_u, z_u, givens = st.stedc_deflate(d, z, rho=1.0)
        assert keep.sum() == 2 and len(givens) == 1
        # the rotated coupling keeps the combined weight
        np.testing.assert_allclose(z_u[keep][0], np.hypot(0.6, 0.8))

    def test_deflate_separated_poles_survive(self):
        # poles 1.0 apart at scale 1e9: the absolute dlaed2 criterion
        # must keep them (a norm-scaled tolerance would not)
        d = np.array([-1e9, -1e9 + 1.0, 1e9])
        z = np.array([0.6, 0.7, 0.38])
        z = z / np.linalg.norm(z)
        keep, d_u, z_u, givens = st.stedc_deflate(d, z, rho=2.0)
        assert keep.all() and not givens

    def test_secular_roots_interlace(self):
        dk = np.array([0.0, 1.0, 2.0])
        zk = np.array([0.5, 0.5, 0.5]) / np.sqrt(0.75)
        rho = 0.3
        lam, dmat = st.stedc_secular(dk, zk, rho)
        # interlacing: d_i < lam_i < d_{i+1} (last above d_k)
        assert np.all(lam[:2] > dk[:2]) and np.all(lam[:2] < dk[1:])
        assert lam[2] > dk[2]
        # each root satisfies the secular equation
        f = 1.0 + rho * (zk[None, :] ** 2
                         / (dk[None, :] - lam[:, None])).sum(axis=1)
        assert np.abs(f).max() < 1e-10
        # difference matrix consistency
        np.testing.assert_allclose(dmat, dk[:, None] - lam[None, :],
                                   atol=1e-12)

    def test_merge_matches_dense_eig(self):
        rng = np.random.default_rng(6)
        n = 24
        d = rng.standard_normal(n)
        e = rng.standard_normal(n - 1)
        m = n // 2
        em = e[m - 1]
        d1, d2 = d[:m].copy(), d[m:].copy()
        d1[-1] -= abs(em)
        d2[0] -= abs(em)
        w1, q1 = dc._steqr_base(d1, e[:m - 1])
        w2, q2 = dc._steqr_base(d2, e[m:])
        w, q = st.stedc_merge(w1, q1, w2, q2, em)
        t = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
        np.testing.assert_allclose(w, np.linalg.eigvalsh(t), atol=1e-12)
        assert np.linalg.norm(t @ q - q * w[None, :]) < 1e-12


def test_heev_dc_uses_stedc():
    """heev with MethodEig.DC goes through the in-house D&C solver."""
    import jax.numpy as jnp
    rng = np.random.default_rng(7)
    n = 48
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    A = st.HermitianMatrix(jnp.asarray(a), uplo=st.Uplo.Lower, mb=16, nb=16)
    w, z = st.heev(A, True, {"method_eig": st.MethodEig.DC})
    wv, zv = np.asarray(w), np.asarray(z)
    res = np.linalg.norm(a @ zv - zv * wv[None, :]) / np.linalg.norm(a)
    assert res < 1e-6
    np.testing.assert_allclose(wv, np.linalg.eigvalsh(a), atol=1e-6)
