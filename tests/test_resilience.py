"""The resilience layer (slate_tpu/resilience): deterministic fault
injection replay, health gates with backend quarantine, the hardened
serving path, and the no-faults bit-identity pins.

Acceptance criteria exercised here:

* deterministic injection replay — same seed ⇒ same fault sequence;
* autotune quarantine round-trip — a poisoned winner is demoted, a
  cache reload keeps the demotion, TTL expiry and a version bump
  re-probe;
* serve chaos — N threads × mixed shapes at a 10% dispatch fault rate:
  every future resolves, non-faulted answers are residual-gated, the
  circuit breaker opens and half-open recovers;
* no-faults bit-identity — with every resilience knob unset the traced
  programs (and the autotune behavior) are unchanged.
"""

import json
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from slate_tpu.exceptions import SlateError, check_info
from slate_tpu.perf import autotune, metrics
from slate_tpu.perf.autotune import Candidate
from slate_tpu.resilience import breaker, health, inject, retry
from slate_tpu.serve.queue import Backpressure, BatchQueue, ServeConfig


@pytest.fixture(autouse=True)
def _fresh(tmp_path, monkeypatch):
    """Per-test isolation: tmp autotune cache, metrics on+clean, no
    fault plan, no health knobs."""
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    for var in ("SLATE_TPU_FAULT_INJECT", "SLATE_TPU_FAULT_SEED",
                "SLATE_TPU_HEALTH", "SLATE_TPU_CHECK_FINITE"):
        monkeypatch.delenv(var, raising=False)
    inject.clear_plan()
    autotune.reset_table()
    was = metrics.enabled()
    metrics.on()
    metrics.reset()
    yield
    inject.clear_plan()
    metrics.reset()
    if not was:
        metrics.off()
    autotune.reset_table()


def _spd(n, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((n, n)).astype(np.float32)
    return g @ g.T + n * np.eye(n, dtype=np.float32)


def _spd_batch(b, n, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((b, n, n)).astype(np.float32)
    return (np.einsum("bij,bkj->bik", g, g)
            + n * np.eye(n, dtype=np.float32))


def _toy(name):
    def setup():
        def run():
            return np.ones((2, 2), np.float32) * 2.0
        return run
    return Candidate(name, setup)


# ---------------------------------------------------------------------------
# Deterministic injection
# ---------------------------------------------------------------------------

class TestInjectDeterminism:
    def test_same_seed_replays_same_faults(self):
        p1 = inject.FaultPlan(seed=7).add("s", "error", rate=0.3)
        p2 = inject.FaultPlan(seed=7).add("s", "error", rate=0.3)
        k1 = [p1.poll("s") for _ in range(200)]
        k2 = [p2.poll("s") for _ in range(200)]
        assert k1 == k2
        assert p1.log == p2.log
        fired = sum(1 for k in k1 if k)
        assert 0 < fired < 200          # the rate actually bites
        # ~30%: a seeded schedule, not all-or-nothing
        assert 30 <= fired <= 90

    def test_different_seed_differs(self):
        p1 = inject.FaultPlan(seed=7).add("s", "error", rate=0.3)
        p3 = inject.FaultPlan(seed=8).add("s", "error", rate=0.3)
        assert [p1.poll("s") for _ in range(100)] != \
            [p3.poll("s") for _ in range(100)]

    def test_count_caps_fired_faults(self):
        p = inject.FaultPlan(seed=1).add("s", "nan", rate=1.0, count=3)
        kinds = [p.poll("s") for _ in range(10)]
        assert kinds[:3] == ["nan"] * 3
        assert kinds[3:] == [None] * 7
        assert p.fired("s") == 3

    def test_env_plan_parse_and_poll(self, monkeypatch):
        monkeypatch.setenv(inject.ENV_PLAN,
                           "serve.dispatch=error:1.0:2,x.y=inf:0.5")
        monkeypatch.setenv(inject.ENV_SEED, "42")
        assert inject.active()
        plan = inject.get_plan()
        assert plan.specs["serve.dispatch"].count == 2
        assert plan.specs["x.y"].kind == "inf"
        assert inject.poll("serve.dispatch") == "error"
        # the env plan's counters persist across polls (cached instance)
        assert inject.get_plan() is plan
        assert plan.fired("serve.dispatch") == 1

    def test_malformed_env_plan_raises(self, monkeypatch):
        monkeypatch.setenv(inject.ENV_PLAN, "oops")
        with pytest.raises(ValueError):
            inject.get_plan()

    def test_unknown_site_never_fires(self):
        p = inject.install(inject.FaultPlan(seed=1).add("a", "error"))
        assert p.poll("other-site") is None

    def test_fault_here_raises_on_error_kind(self):
        inject.install(inject.FaultPlan(seed=1).add("s", "error"))
        with pytest.raises(inject.InjectedFault) as ei:
            inject.fault_here("s")
        assert "s" in str(ei.value)
        assert retry.transient_infra(ei.value)

    def test_injected_fault_counter(self):
        inject.install(inject.FaultPlan(seed=1).add("s", "error"))
        with pytest.raises(inject.InjectedFault):
            inject.fault_here("s")
        assert metrics.snapshot()["counters"]["resilience.inject.s"] == 1

    def test_corrupt_outputs_first_float_leaf_only(self):
        out = (np.ones((3, 3), np.float32), np.arange(3))
        c = inject.corrupt_outputs(out, "nan")
        assert np.isnan(c[0][0, 0])
        assert np.isfinite(c[0]).sum() == 8
        assert (c[1] == np.arange(3)).all()     # int leaf untouched


# ---------------------------------------------------------------------------
# check_info batched contract (satellite)
# ---------------------------------------------------------------------------

class TestCheckInfoBatched:
    def test_scalar_contract_preserved(self):
        check_info(0)
        check_info(np.int32(0))
        with pytest.raises(SlateError, match="info = 3"):
            check_info(3, "getrf")

    def test_batched_zero_passes(self):
        check_info(np.zeros(8, np.int32), "getrf_batched")

    def test_batched_reports_first_index_and_count(self):
        info = np.array([0, 2, 0, 5])
        with pytest.raises(SlateError) as ei:
            check_info(info, "getrf_batched")
        msg = str(ei.value)
        assert "2 of 4" in msg
        assert "index 1" in msg
        assert "info = 2" in msg

    def test_batched_device_array(self):
        with pytest.raises(SlateError):
            check_info(jnp.asarray([0, 0, 7]), "posv_batched")


# ---------------------------------------------------------------------------
# Health gates (SLATE_TPU_HEALTH ladder)
# ---------------------------------------------------------------------------

class TestHealthGates:
    def test_mode_resolution_and_check_finite_fold(self, monkeypatch):
        assert health.mode() == "off"
        monkeypatch.setenv("SLATE_TPU_HEALTH", "retry")
        assert health.mode() == "retry"
        monkeypatch.delenv("SLATE_TPU_HEALTH")
        monkeypatch.setenv("SLATE_TPU_CHECK_FINITE", "2")
        assert health.mode() == "strict"
        monkeypatch.setenv("SLATE_TPU_CHECK_FINITE", "1")
        assert health.mode() == "off"   # =1 keeps the legacy warn path

    def test_injection_corrupts_driver_output_when_health_off(self):
        from slate_tpu.linalg import batched

        inject.install(inject.FaultPlan(seed=1).add(
            "driver.output", "nan", rate=1.0, count=1))
        out = batched.potrf_batched(jnp.asarray(_spd_batch(2, 16)))
        assert np.isnan(np.asarray(out)[0, 0, 0])

    def test_retry_recovers_from_injected_corruption(self, monkeypatch):
        from slate_tpu.linalg import batched

        monkeypatch.setenv("SLATE_TPU_HEALTH", "retry")
        inject.install(inject.FaultPlan(seed=1).add(
            "driver.output", "nan", rate=1.0, count=1))
        out = batched.potrf_batched(jnp.asarray(_spd_batch(2, 16)))
        assert np.isfinite(np.asarray(out)).all()
        c = metrics.snapshot()["counters"]
        assert c.get("resilience.health.fail", 0) >= 1
        assert c.get("resilience.recovered", 0) >= 1

    def test_warn_warns_and_passes_through(self, monkeypatch):
        from slate_tpu.linalg import batched

        monkeypatch.setenv("SLATE_TPU_HEALTH", "warn")
        bad = _spd_batch(2, 16).copy()
        bad[0, 0, 0] = np.nan
        with pytest.warns(RuntimeWarning, match="health gate"):
            out = batched.potrf_batched(jnp.asarray(bad))
        assert not np.isfinite(np.asarray(out)).all()

    def test_strict_raises_when_unrecoverable(self, monkeypatch):
        from slate_tpu.linalg import batched

        monkeypatch.setenv("SLATE_TPU_HEALTH", "strict")
        bad = _spd_batch(2, 16).copy()
        bad[0, 0, 0] = np.nan           # NaN input: both backends fail
        with pytest.raises(SlateError, match="health gate"):
            batched.potrf_batched(jnp.asarray(bad))
        c = metrics.snapshot()["counters"]
        assert c.get("resilience.unrecovered", 0) >= 1

    def test_check_finite_2_raises_like_strict(self, monkeypatch):
        from slate_tpu.linalg import batched

        monkeypatch.setenv("SLATE_TPU_CHECK_FINITE", "2")
        bad = _spd_batch(2, 16).copy()
        bad[0, 0, 0] = np.nan
        with pytest.raises(SlateError, match="health gate"):
            batched.potrf_batched(jnp.asarray(bad))

    def test_gate_demotes_winner_when_safe_rerun_recovers(self,
                                                          monkeypatch):
        """A failed gate quarantines the driver's settled non-safe
        winners ONLY when the stock-backend re-run produces a clean
        answer — evidence the fast path (not the input) was at fault."""
        from slate_tpu.linalg import batched

        tab = autotune.table()
        # a settled timed winner for the driver's site at ANOTHER shape
        # bucket (the gate can't know which bucketed key the call hit,
        # so it demotes every suspect winner of the driver's sites)
        key = "batched_potrf|8,64,float32,HIGH"
        tab._record("batched_potrf", key, "grid", "timed", persist=True)
        monkeypatch.setenv("SLATE_TPU_HEALTH", "retry")
        # injected corruption of the fast call's output; the safe
        # re-run (which bypasses the wrapped facade) comes back clean
        inject.install(inject.FaultPlan(seed=5).add(
            "driver.output", "nan", rate=1.0, count=1))
        out = batched.potrf_batched(jnp.asarray(_spd_batch(2, 16)))
        assert np.isfinite(np.asarray(out)).all()
        assert "grid" in tab.quarantine.get(key, {})
        assert tab.decisions.get(key, {}).get("backend") != "grid"
        c = metrics.snapshot()["counters"]
        assert c.get("resilience.demotions", 0) >= 1
        assert c.get("resilience.recovered", 0) >= 1

    def test_bad_input_does_not_demote_backends(self, monkeypatch):
        """When BOTH backends fail (a NaN operand — the data is the
        problem), no winner is quarantined: healthy hardware must not
        be demoted for 24h because one caller sent garbage."""
        from slate_tpu.linalg import batched

        tab = autotune.table()
        key = "batched_potrf|8,64,float32,HIGH"
        tab._record("batched_potrf", key, "grid", "timed", persist=True)
        monkeypatch.setenv("SLATE_TPU_HEALTH", "retry")
        bad = _spd_batch(2, 16).copy()
        bad[0, 0, 0] = np.nan
        with pytest.warns(RuntimeWarning):
            batched.potrf_batched(jnp.asarray(bad))
        assert tab.quarantine.get(key) is None
        assert tab.decisions[key]["backend"] == "grid"
        c = metrics.snapshot()["counters"]
        assert c.get("resilience.demotions", 0) == 0
        assert c.get("resilience.unrecovered", 0) >= 1

    def test_programming_errors_never_classify_transient(self):
        assert not retry.transient_infra(
            TypeError("__init__() missing 1 required positional "
                      "argument"))
        assert not retry.transient_infra(KeyError("worker"))
        assert retry.transient_infra(
            RuntimeError("failed to initialize TPU worker: UNAVAILABLE"))
        assert retry.transient_infra(OSError("connection reset"))

    def test_safe_window_preserves_settled_decisions(self, monkeypatch):
        """The degraded re-run's temporarily-forced knobs must not
        clobber settled timed winners (a clobbered record would
        re-probe at serving time after the knobs are restored)."""
        from slate_tpu.perf.autotune import _static

        tab = autotune.table()
        key = "matmul|128,128,128,float32,HIGH"
        tab._record("matmul", key, "pallas", "timed", persist=True)
        with health.safe_backend():
            got = _static("matmul", (128, 128, 128, "float32", "HIGH"),
                          "xla", "forced-config")
        assert got == "xla"              # the resolution itself holds
        assert tab.decisions[key]["backend"] == "pallas", \
            "the settled winner must survive the safe window"
        assert tab.decisions[key]["source"] == "timed"

    def test_gate_skips_under_jit_trace(self, monkeypatch):
        """Inside a jit trace the gate must not act (tracers can't be
        checked; the compiled program must not change)."""
        from slate_tpu.linalg import batched

        monkeypatch.setenv("SLATE_TPU_HEALTH", "strict")
        bad = _spd_batch(2, 16).copy()
        bad[0, 0, 0] = np.nan
        # tracing must succeed even though the value is unhealthy
        jitted = jax.jit(batched.potrf_batched)
        out = jitted(jnp.asarray(bad))   # gate skipped: no raise
        assert not np.isfinite(np.asarray(out)).all()


# ---------------------------------------------------------------------------
# Quarantine round-trip (autotune demotions)
# ---------------------------------------------------------------------------

class TestQuarantine:
    def test_demotion_reload_and_version_bump(self, monkeypatch):
        cands = lambda: [_toy("pallas"), _toy("xla")]     # noqa: E731
        assert autotune.decide("toyop", (1, 2), cands()) == "pallas"
        autotune.quarantine("toyop", (1, 2), "pallas", reason="poisoned")
        assert autotune.decide("toyop", (1, 2), cands()) == "xla"
        # "fresh process": reload from disk keeps the demotion
        autotune.reset_table()
        assert autotune.decide("toyop", (1, 2), cands()) == "xla"
        blob = json.load(open(autotune.table().quarantine_path))
        assert "pallas" in blob["entries"]["toyop|1,2"]
        # version bump: the whole quarantine is dropped — re-probe
        monkeypatch.setattr(autotune, "_version_key",
                            lambda: {"jax": "vNEXT"})
        autotune.reset_table()
        assert autotune.decide("toyop", (1, 2), cands()) == "pallas"

    def test_ttl_expiry_reprobes(self):
        cands = lambda: [_toy("pallas"), _toy("xla")]     # noqa: E731
        autotune.quarantine("toyop", (9,), "pallas", ttl_s=30.0)
        assert autotune.decide("toyop", (9,), cands()) == "xla"
        # deterministic expiry: rewind the entry instead of sleeping
        tab = autotune.table()
        tab.quarantine["toyop|9"]["pallas"]["until"] = time.time() - 1
        assert autotune.decide("toyop", (9,), cands()) == "pallas"
        assert not tab.quarantine.get("toyop|9")
        c = metrics.snapshot()["counters"]
        assert c.get("resilience.quarantine.expired", 0) >= 1

    def test_quarantined_cache_hit_is_refused(self, monkeypatch):
        """A persisted timed winner that gets quarantined afterwards
        (e.g. by another process) must not be served from the hit
        path."""
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        autotune.decide("toyop", (3,), [_toy("slow"), _toy("fast")])
        tab = autotune.table()
        won = tab.decisions["toyop|3"]["backend"]
        # quarantine WITHOUT dropping the decision (simulates a stale
        # in-process hit): write the entry directly
        tab.quarantine.setdefault("toyop|3", {})[won] = {
            "until": time.time() + 60, "reason": "x"}
        other = "slow" if won == "fast" else "fast"
        got = autotune.decide("toyop", (3,), [_toy("slow"), _toy("fast")])
        assert got == other

    def test_forced_pin_overrides_quarantine(self, monkeypatch):
        autotune.quarantine("toyop", (4,), "pallas")
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "toyop=pallas")
        got = autotune.decide("toyop", (4,), [_toy("pallas"),
                                              _toy("xla")])
        assert got == "pallas"

    def test_safe_backend_never_filtered(self):
        # quarantining the safe candidate itself must not strand the key
        autotune.quarantine("toyop", (5,), "xla")
        autotune.quarantine("toyop", (5,), "pallas")
        got = autotune.decide("toyop", (5,), [_toy("pallas"),
                                              _toy("xla")])
        assert got == "xla"             # the safe name always survives

    def test_probe_injection_prunes_candidate(self, monkeypatch):
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        inject.install(inject.FaultPlan(seed=1).add(
            "autotune.probe", "error", rate=1.0, count=1))
        got = autotune.decide("toyop", (6,), [_toy("a"), _toy("b")])
        assert got == "b"               # first candidate's probe faulted
        info = autotune.table().decisions["toyop|6"]
        assert "InjectedFault" in str(info.get("times", {}))
        c = metrics.snapshot()["counters"]
        assert c.get("resilience.inject.autotune.probe") == 1


# ---------------------------------------------------------------------------
# Serve hardening
# ---------------------------------------------------------------------------

class TestServeHardening:
    def test_close_fails_queued_futures(self):
        srv = BatchQueue(ServeConfig(max_wait_s=30.0))
        srv._ensure_thread = lambda: None        # dead dispatcher
        f = srv.submit("potrf", _spd(16))
        srv.close()
        with pytest.raises(SlateError, match="closed"):
            f.result(timeout=1)
        c = metrics.snapshot()["counters"]
        assert c.get("serve.closed_undispatched") == 1

    def test_flush_timeout_raises(self):
        srv = BatchQueue(ServeConfig(max_wait_s=30.0))
        srv._ensure_thread = lambda: None
        srv.submit("potrf", _spd(16))
        with pytest.raises(TimeoutError, match="still pending"):
            srv.flush(timeout=0.05)
        srv.close()

    def test_flush_without_timeout_drains(self):
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.005))
        futs = [srv.submit("potrf", _spd(16, seed=i)) for i in range(3)]
        srv.flush(timeout=120.0)
        assert all(f.done() for f in futs)
        srv.close()

    def test_backpressure_bound(self):
        srv = BatchQueue(ServeConfig(max_wait_s=30.0, max_queue_depth=2))
        srv._ensure_thread = lambda: None
        srv.submit("potrf", _spd(16))
        srv.submit("potrf", _spd(16, seed=1))
        with pytest.raises(Backpressure):
            srv.submit("potrf", _spd(16, seed=2))
        c = metrics.snapshot()["counters"]
        assert c.get("serve.backpressure") == 1
        srv.close()

    def test_deadline_expired_request_gets_timeout(self):
        srv = BatchQueue(ServeConfig(max_wait_s=0.05))
        f = srv.submit("potrf", _spd(16), deadline_s=0.0)
        with pytest.raises(TimeoutError):
            f.result(timeout=30)
        c = metrics.snapshot()["counters"]
        assert c.get("serve.deadline_expired") == 1
        srv.close()

    def test_transient_dispatch_error_retries(self):
        inject.install(inject.FaultPlan(seed=2).add(
            "serve.dispatch", "error", rate=1.0, count=1))
        srv = BatchQueue(ServeConfig(max_batch=4, max_wait_s=0.005,
                                     retry_backoff_s=0.001))
        spd = _spd(16)
        b = np.ones(16, np.float32)
        x = srv.submit("posv", spd, b).result(timeout=120)
        eps = float(np.finfo(np.float32).eps)
        assert (np.linalg.norm(spd @ x - b)
                / (np.linalg.norm(spd) * np.linalg.norm(b)
                   * eps * 16)) < 3
        c = metrics.snapshot()["counters"]
        assert c.get("serve.retries") == 1
        assert c.get("serve.fallback.singles", 0) == 0
        srv.close()

    def test_exhausted_retries_fall_back_to_singles(self):
        inject.install(inject.FaultPlan(seed=2).add(
            "serve.dispatch", "error", rate=1.0, count=10))
        srv = BatchQueue(ServeConfig(max_batch=4, max_wait_s=0.005,
                                     max_retries=1,
                                     retry_backoff_s=0.001))
        spd = _spd(16)
        b = np.ones(16, np.float32)
        x = srv.submit("posv", spd, b).result(timeout=120)
        assert np.isfinite(x).all()
        c = metrics.snapshot()["counters"]
        assert c.get("serve.fallback.singles") == 1
        assert c.get("serve.singles") == 1
        srv.close()

    def test_nonfinite_batch_never_resolves_futures(self, monkeypatch):
        """An injected NaN in the batch result under an active health
        mode is treated as a dispatch failure: the caller gets the
        clean singles answer, never the poisoned batch."""
        monkeypatch.setenv("SLATE_TPU_HEALTH", "warn")
        inject.install(inject.FaultPlan(seed=4).add(
            "serve.dispatch", "nan", rate=1.0, count=5))
        srv = BatchQueue(ServeConfig(max_batch=4, max_wait_s=0.005,
                                     max_retries=1,
                                     retry_backoff_s=0.001))
        spd = _spd(16)
        b = np.ones(16, np.float32)
        x = srv.submit("posv", spd, b).result(timeout=120)
        assert np.isfinite(x).all()
        eps = float(np.finfo(np.float32).eps)
        assert (np.linalg.norm(spd @ x - b)
                / (np.linalg.norm(spd) * np.linalg.norm(b)
                   * eps * 16)) < 3
        c = metrics.snapshot()["counters"]
        assert c.get("serve.health.batch_nonfinite", 0) >= 1
        srv.close()

    def test_breaker_opens_and_half_open_recovers(self):
        inject.install(inject.FaultPlan(seed=3).add(
            "serve.dispatch", "error", rate=1.0, count=3))
        srv = BatchQueue(ServeConfig(
            max_batch=1, max_wait_s=0.001, max_retries=0,
            breaker_threshold=2, breaker_cooldown_s=0.3,
            retry_backoff_s=0.001))
        b = np.ones(16, np.float32)
        # two consecutive batch failures (each resolves via singles)
        for i in range(2):
            x = srv.submit("posv", _spd(16, seed=i), b).result(timeout=120)
            assert np.isfinite(x).all()
        c = metrics.snapshot()["counters"]
        assert c.get("serve.breaker.open") == 1
        # open: straight to singles without touching the batch path
        srv.submit("posv", _spd(16, seed=5), b).result(timeout=120)
        c = metrics.snapshot()["counters"]
        assert c.get("serve.breaker.short_circuit", 0) >= 1
        # cool-down → half-open trial; one injected fault remains, so
        # the first trial re-opens, the next (faults exhausted) closes
        time.sleep(0.35)
        srv.submit("posv", _spd(16, seed=6), b).result(timeout=120)
        time.sleep(0.35)
        srv.submit("posv", _spd(16, seed=7), b).result(timeout=120)
        c = metrics.snapshot()["counters"]
        assert c.get("serve.breaker.half_open") == 2
        assert c.get("serve.breaker.close") == 1
        assert c.get("serve.breaker.open") == 2    # the failed trial
        # recovered: a fresh dispatch runs the batch fast path clean
        metrics.reset()
        srv.submit("posv", _spd(16, seed=8), b).result(timeout=120)
        c = metrics.snapshot()["counters"]
        assert c.get("serve.fallback.singles", 0) == 0
        assert c.get("serve.breaker.short_circuit", 0) == 0
        srv.close()


class TestServeChaos:
    def test_chaos_threads_mixed_shapes_ten_pct_faults(self, monkeypatch):
        """The chaos gate: N threads × mixed shapes at a ≥10% dispatch
        fault rate PLUS NaN corruption of driver outputs (fires on the
        eager singles fallback; the health gate recovers it) — every
        future resolves, every answer passes its residual gate, and the
        resilience counters match the plan."""
        monkeypatch.setenv("SLATE_TPU_HEALTH", "retry")
        plan = inject.install(inject.FaultPlan(seed=11)
                              .add("serve.dispatch", "error", rate=0.10)
                              .add("driver.output", "nan", rate=0.25))
        srv = BatchQueue(ServeConfig(max_batch=4, max_wait_s=0.01,
                                     max_retries=1,
                                     retry_backoff_s=0.001))
        cases = []
        rng = np.random.default_rng(13)
        for i, n in enumerate((16, 24, 33, 16, 24, 33, 16, 24)):
            spd = _spd(n, seed=i)
            b = rng.standard_normal(n).astype(np.float32)
            cases.append(("posv", (spd, b)))
        for i, n in enumerate((20, 40, 20, 40)):
            a = (rng.standard_normal((n, n)).astype(np.float32)
                 + n * np.eye(n, dtype=np.float32))
            b = rng.standard_normal(n).astype(np.float32)
            cases.append(("gesv", (a, b)))
        futs = [None] * len(cases)

        def worker(lo, hi):
            for i in range(lo, hi):
                op, operands = cases[i]
                futs[i] = srv.submit(op, *operands)

        threads = [threading.Thread(target=worker, args=(i, i + 3))
                   for i in range(0, len(cases), 3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        eps = float(np.finfo(np.float32).eps)
        for (op, (a, b)), fut in zip(cases, futs):
            x = fut.result(timeout=180)      # EVERY future resolves
            n = a.shape[0]
            r = (np.linalg.norm(a @ x - b)
                 / (np.linalg.norm(a) * np.linalg.norm(b) * eps * n))
            assert r < 3, (op, n, r)
        srv.close()
        c = metrics.snapshot()["counters"]
        # the injected-fault counters match the plan's replay log
        assert c.get("resilience.inject.serve.dispatch", 0) \
            == plan.fired("serve.dispatch")
        assert c.get("resilience.inject.driver.output", 0) \
            == plan.fired("driver.output")
        assert c["serve.requests"] == len(cases)
        # corrupted driver outputs on the singles path were recovered,
        # not served: recovered count covers every driver.output hit
        # that landed outside a jit trace
        if plan.fired("driver.output"):
            assert c.get("resilience.recovered", 0) >= 0
        assert c.get("serve.errors", 0) == c.get("serve.fallback.singles",
                                                 0)

    def test_chaos_same_seed_same_fault_schedule(self):
        """Deterministic replay at the plan level: the poll schedule
        driving a chaos run is a pure function of the seed."""
        p1 = inject.FaultPlan(seed=11).add("serve.dispatch", "error",
                                           rate=0.10)
        p2 = inject.FaultPlan(seed=11).add("serve.dispatch", "error",
                                           rate=0.10)
        s1 = [p1.poll("serve.dispatch") for _ in range(64)]
        s2 = [p2.poll("serve.dispatch") for _ in range(64)]
        assert s1 == s2 and p1.log == p2.log


# ---------------------------------------------------------------------------
# No-faults bit-identity pins
# ---------------------------------------------------------------------------

class TestBitIdentity:
    def test_traced_program_identical_with_knobs_unset(self):
        """The dist.bcast seam (the one TRACE-TIME seam) must vanish
        from the traced program when no plan is installed: the lowered
        text is bit-identical across lowerings, identical under a plan
        naming only OTHER sites, and different only when a plan
        actually targets the seam."""
        from slate_tpu.parallel import dist_util

        x = jnp.ones((4, 4), jnp.float32)

        def lower():
            # a FRESH function object per lowering: jax caches traces
            # by function identity, and a cached trace would hide (or
            # fake) the seam
            def f(v):
                return dist_util._inject_bcast(v * 2.0)

            return jax.jit(f).lower(x).as_text()

        base = lower()
        assert lower() == base
        inject.install(inject.FaultPlan(seed=1).add(
            "serve.dispatch", "error", rate=1.0))   # unrelated site
        assert lower() == base
        inject.install(inject.FaultPlan(seed=1).add(
            "dist.bcast", "nan", rate=1.0))
        assert lower() != base, "an active dist.bcast plan must show"
        inject.clear_plan()
        assert lower() == base

    def test_driver_lowering_identical_under_host_side_knobs(self,
                                                             monkeypatch):
        """The driver/serve seams are HOST-side: health knobs and fault
        plans must not change the compiled program of a driver facade
        (the serve executables' zero-compile warm start depends on
        it)."""
        from slate_tpu.linalg import batched

        a = jnp.asarray(_spd_batch(2, 16))

        def lower():
            def f(v):         # fresh function: defeat the trace cache
                return batched.potrf_batched(v)

            return jax.jit(f).lower(a).as_text()

        base = lower()
        monkeypatch.setenv("SLATE_TPU_HEALTH", "strict")
        monkeypatch.setenv("SLATE_TPU_FAULT_INJECT",
                           "serve.dispatch=error:0.5,driver.output=nan:0.5")
        assert lower() == base

    def test_autotune_behavior_identical_with_knobs_unset(self):
        """No quarantine file, no knobs ⇒ decide() resolves exactly as
        before the resilience layer existed (and loads nothing)."""
        tab = autotune.table()
        assert tab.quarantine == {}
        got = autotune.decide("toyop", (1,), [_toy("pallas"),
                                              _toy("xla")])
        assert got == "pallas"
        assert tab.decisions["toyop|1"]["source"] == "default"
        snap = metrics.snapshot()["counters"]
        assert "autotune.quarantine.filtered" not in snap


# ---------------------------------------------------------------------------
# Bench / multichip infra retry (satellite)
# ---------------------------------------------------------------------------

class TestBenchInfraRetry:
    def test_init_retry_absorbs_one_transient_failure(self):
        bench = pytest.importorskip("bench")
        inject.install(inject.FaultPlan(seed=1).add(
            "infra.init", "error", rate=1.0, count=1))
        platform, retried, err = bench._init_backend_with_retry()
        assert platform == "cpu" and retried and err is None
        c = metrics.snapshot()["counters"]
        assert c.get("resilience.retries") == 1

    def test_init_failure_after_retry_reports_error(self, monkeypatch):
        bench = pytest.importorskip("bench")
        monkeypatch.setenv("SLATE_TPU_INIT_BACKOFF_S", "0.001")
        inject.install(inject.FaultPlan(seed=1).add(
            "infra.init", "error", rate=1.0))
        platform, retried, err = bench._init_backend_with_retry()
        assert platform is None and retried
        assert isinstance(err, inject.InjectedFault)

    def test_routine_startup_fault_is_retried_as_infra(self, capsys):
        bench = pytest.importorskip("bench")
        inject.install(inject.FaultPlan(seed=1).add(
            "bench.startup", "error", rate=1.0, count=1))
        calls = []

        def routine():
            calls.append(1)
            return "lbl_fp32_n8", 10.0, 0.0

        sub, fails, infra = {}, [], []
        got = bench._run_routine("chaotic", routine, sub, fails, infra)
        assert got == 10.0 and not fails and not infra
        assert len(calls) == 1, \
            "the startup fault fires before the routine body"
        line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert line["gflops"] == 10.0

    def test_retried_infra_tag_surfaces_in_sentinel(self, tmp_path):
        from slate_tpu.perf import regress

        agg = {"metric": "factor_suite_fp32_geomean", "value": 10.0,
               "unit": "GFLOP/s", "vs_baseline": 0.01,
               "submetrics": {"gemm_fp32_n1024": 10.0},
               "retried_infra": True}
        p = tmp_path / "BENCH_rX.json"
        p.write_text(json.dumps(agg))
        art = regress.load_artifact(str(p))
        assert art.ok                        # tagged, NOT an infra fail
        assert "retried_infra=true" in art.notes
        rep = regress.diff([art])
        table = regress.format_table(rep)
        assert "retried_infra=true" in table
        assert rep.exit_code == 0
