"""Live serving telemetry (ISSUE 10): per-request trace-id propagation
under concurrent submitters, SLO histogram percentile math vs a
sorted-sample oracle, Prometheus scrape round-trip over a real socket,
the rotating JSONL log, the live sentinel firing on an injected
slowdown (with the opt-in breaker trip), Perfetto flow export, and the
off-by-default zero-overhead / bit-identity pins."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from slate_tpu import trace
from slate_tpu.perf import autotune, metrics, telemetry
from slate_tpu.resilience import inject
from slate_tpu.serve.queue import BatchQueue, ServeConfig, _bucket

SPAN_NAMES = ("queue_wait", "dispatch", "post_check")


@pytest.fixture(autouse=True)
def _fresh(tmp_path, monkeypatch):
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    for knob in ("SLATE_TPU_TELEMETRY", "SLATE_TPU_METRICS_PORT",
                 "SLATE_TPU_TELEMETRY_LOG", "SLATE_TPU_SLO_MS",
                 "SLATE_TPU_SENTINEL_TRIP", "SLATE_TPU_FAULT_INJECT"):
        monkeypatch.delenv(knob, raising=False)
    autotune.reset_table()
    was_m, was_t = metrics.enabled(), telemetry.enabled()
    metrics.on()
    metrics.reset()
    telemetry.on()
    telemetry.drain_spans()
    telemetry.configure_sentinel()
    yield
    telemetry.close()
    telemetry.stop_exporter()
    telemetry.drain_spans()
    telemetry.configure_sentinel()
    trace.clear()
    metrics.reset()
    if not was_t:
        telemetry.off()
    if not was_m:
        metrics.off()
    inject.clear_plan()
    autotune.reset_table()


def _spd(n, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((n, n)).astype(np.float32)
    return g @ g.T + n * np.eye(n, dtype=np.float32)


def _spans_by_id():
    out = {}
    for tid, name, t0, t1, lane, args in telemetry.spans():
        out.setdefault(tid, []).append((name, t0, t1, lane, args))
    return out


class TestTraceIdPropagation:
    def test_trace_ids_under_four_concurrent_submitters(self):
        """Each of 4 threads' requests keeps its own trace id through
        bucket → pad → dispatch → resolution; every id carries the
        full queue_wait/dispatch/post_check chain whose sum is the
        future-observed latency (the acceptance tolerance)."""
        srv = BatchQueue(ServeConfig(max_batch=4, max_wait_s=0.002))
        n = 16
        spd = _spd(n)
        rhs = np.ones(n, np.float32)
        srv.submit("posv", spd, rhs).result(timeout=300)     # warm
        telemetry.drain_spans()

        per_thread = 3
        futs = [[None] * per_thread for _ in range(4)]
        t_sub = [[None] * per_thread for _ in range(4)]
        t_done = [[None] * per_thread for _ in range(4)]

        def worker(k):
            for i in range(per_thread):
                t_sub[k][i] = time.perf_counter()
                f = srv.submit("posv", spd, rhs)

                def _cb(fut, k=k, i=i):
                    t_done[k][i] = time.perf_counter()

                f.add_done_callback(_cb)
                futs[k][i] = f
                f.result(timeout=300)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        srv.close()

        ids = [futs[k][i].trace_id for k in range(4)
               for i in range(per_thread)]
        assert all(isinstance(x, int) for x in ids)
        assert len(set(ids)) == 12, "trace ids must be unique"
        chains = _spans_by_id()
        for k in range(4):
            for i in range(per_thread):
                tid = futs[k][i].trace_id
                assert tid in chains, "no spans for trace id %s" % tid
                names = [s[0] for s in chains[tid]]
                for want in SPAN_NAMES:
                    assert want in names, (tid, names)
                span_sum = sum(t1 - t0 for name, t0, t1, _, _
                               in chains[tid] if name in SPAN_NAMES)
                measured = t_done[k][i] - t_sub[k][i]
                assert abs(span_sum - measured) \
                    <= 0.05 + 0.10 * measured, \
                    ("per-request spans must sum to the future-"
                     "observed latency", span_sum, measured)

    def test_spans_are_contiguous_and_on_dispatcher_lane(self):
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002))
        n = 16
        f = srv.submit("posv", _spd(n), np.ones(n, np.float32))
        f.result(timeout=300)
        srv.close()
        chain = sorted(
            (s for s in _spans_by_id()[f.trace_id]
             if s[0] in SPAN_NAMES), key=lambda s: s[1])
        assert [s[0] for s in chain] == list(SPAN_NAMES)
        for a, b in zip(chain, chain[1:]):
            assert abs(a[2] - b[1]) < 1e-6, "spans must be contiguous"
        lanes = {s[3] for s in chain}
        assert len(lanes) == 1
        assert next(iter(lanes)).startswith("slate-serve-dispatch")

    def test_no_trace_ids_when_telemetry_off(self):
        telemetry.off()
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002))
        n = 16
        f = srv.submit("posv", _spd(n), np.ones(n, np.float32))
        f.result(timeout=300)
        srv.close()
        assert not hasattr(f, "trace_id")
        assert telemetry.spans() == []


class TestHistogramQuantiles:
    def test_quantiles_vs_sorted_sample_oracle(self):
        rng = np.random.default_rng(5)
        vals = np.exp(rng.normal(2.0, 1.5, size=500)).tolist()
        name = "test.latency_q"
        for v in vals:
            metrics.observe(name, v)
        qs = metrics.hist_quantiles(name, (0.5, 0.95, 0.99))
        s = sorted(vals)
        for q, est in qs.items():
            oracle = s[min(len(s) - 1, int(np.ceil(q * len(s))) - 1)]
            # log2 buckets: the estimate lands in the oracle's bucket,
            # i.e. within a factor of two of the exact order statistic
            assert oracle / 2.0 <= est <= oracle * 2.0, (q, est, oracle)

    def test_quantiles_monotone_and_bounded(self):
        name = "test.latency_mono"
        for v in (1.0, 2.0, 4.0, 80.0, 90.0, 100.0):
            metrics.observe(name, v)
        qs = metrics.hist_quantiles(name, (0.5, 0.95, 0.99))
        assert qs[0.5] <= qs[0.95] <= qs[0.99] <= 128.0

    def test_empty_and_unknown_hist(self):
        assert metrics.hist_quantiles("never.recorded") == {}
        assert metrics.quantiles_from_buckets(None) == {}
        assert metrics.quantiles_from_buckets({"buckets": {}}) == {}

    def test_bucket_bounds(self):
        assert metrics.bucket_bounds("le_0") == (0.0, 0.0)
        assert metrics.bucket_bounds("le_2^3") == (4.0, 8.0)
        assert metrics.bucket_bounds("le_2^-1") == (0.25, 0.5)
        assert metrics.bucket_bounds("nonsense") is None


class TestSLOHistograms:
    def test_latency_histogram_and_slo_violations(self):
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002,
                                     slo_ms=0.0001))
        n = 16
        for _ in range(3):
            srv.submit("posv", _spd(n), np.ones(n, np.float32)) \
               .result(timeout=300)
        srv.close()
        snap = metrics.snapshot()
        hname = "serve.latency_ms.posv.fp32.n%d" % _bucket(n)
        assert snap["hists"][hname]["count"] == 3
        # a 100 ns SLO: every CPU request violates
        assert snap["counters"]["serve.slo.violations"] == 3
        assert snap["counters"]["serve.slo.violations.posv"] == 3

    def test_env_slo_fallback(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_SLO_MS", "0.0001")
        assert telemetry.default_slo_ms() == 0.0001
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002))
        n = 16
        srv.submit("posv", _spd(n), np.ones(n, np.float32)) \
           .result(timeout=300)
        srv.close()
        assert metrics.snapshot()["counters"][
            "serve.slo.violations"] == 1


class TestPrometheusExporter:
    def test_scrape_round_trip_over_real_socket(self):
        metrics.inc("serve.requests", 5)
        for v in (1.0, 3.0, 200.0):
            metrics.observe("serve.latency_ms.posv.fp32.n16", v)
        port = telemetry.start_exporter(0)
        assert telemetry.exporter_port() == port
        body = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % port, timeout=30) \
            .read().decode()
        assert "slate_tpu_serve_requests 5" in body
        mn = "slate_tpu_serve_latency_ms_posv_fp32_n16"
        # cumulative histogram series + count/sum + quantile gauges
        lines = [ln for ln in body.splitlines() if ln.startswith(mn)]
        cums = [int(ln.rsplit(" ", 1)[1]) for ln in lines
                if "_bucket{le=" in ln and "+Inf" not in ln]
        assert cums == sorted(cums) and cums[-1] == 3
        assert "%s_bucket{le=\"+Inf\"} 3" % mn in body
        assert "%s_count 3" % mn in body
        assert '%s_quantile{quantile="0.99"}' % mn in body

    def test_404_off_path_and_idempotent_start(self):
        port = telemetry.start_exporter(0)
        assert telemetry.start_exporter(0) == port
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                "http://127.0.0.1:%d/nope" % port, timeout=30)


class TestJsonlLog:
    def test_records_flush_on_interval_and_at_close(self, tmp_path):
        path = str(tmp_path / "tel.jsonl")
        telemetry.start_log(path, flush_s=0.05)
        telemetry.log_record("request", op="posv", latency_ms=1.5)
        time.sleep(0.3)
        telemetry.log_record("request", op="posv", latency_ms=2.5)
        telemetry.close()       # final flush, no interval wait needed
        recs = [json.loads(ln) for ln in open(path)]
        reqs = [r for r in recs if r["kind"] == "request"]
        assert [r["latency_ms"] for r in reqs] == [1.5, 2.5]
        assert all("t" in r for r in recs)
        # interval flushes append snapshot records
        assert any(r["kind"] == "snapshot" for r in recs)

    def test_rotation_keeps_one_sibling(self, tmp_path):
        path = str(tmp_path / "rot.jsonl")
        telemetry.start_log(path, flush_s=30.0, max_mb=0.001)  # ~1 KB
        for i in range(40):
            telemetry.log_record("request", op="posv", i=i,
                                 pad="x" * 64)
            if i % 10 == 9:
                telemetry._flush_log()
        telemetry.close()
        assert (tmp_path / "rot.jsonl.1").exists()
        # both generations parse; no record is lost across the
        # rotation boundary (the tail lives in one of the two)
        recs = [json.loads(ln)
                for fp in (path + ".1", path) for ln in open(fp)]
        reqs = [r for r in recs if r["kind"] == "request"]
        assert reqs[-1]["i"] == 39

    def test_serve_requests_stream_into_log(self, tmp_path):
        path = str(tmp_path / "serve.jsonl")
        telemetry.start_log(path, flush_s=30.0)
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002))
        n = 16
        srv.submit("posv", _spd(n), np.ones(n, np.float32)) \
           .result(timeout=300)
        srv.close()
        telemetry.close()
        recs = [json.loads(ln) for ln in open(path)]
        req = next(r for r in recs if r["kind"] == "request")
        assert req["op"] == "posv" and req["latency_ms"] > 0
        assert req["bucket"] == "fp32.n%d" % _bucket(n)


class TestLiveSentinel:
    def test_sustained_latency_rise_fires_exactly_once(self):
        s = telemetry.LiveSentinel(baseline=8, window=4,
                                   threshold_pct=50, cooldown_s=60)
        for _ in range(8):
            assert s.observe("posv", "fp32.n64", 0.010, batch=4,
                             n=64) is None
        evs = [s.observe("posv", "fp32.n64", 0.200, batch=4, n=64)
               for _ in range(8)]
        fired = [e for e in evs if e is not None]
        assert len(fired) == 1, "one sustained drop → exactly one event"
        ev = fired[0]
        assert ev["classification"] == "degradation"
        assert ev["kind"] == "latency"
        assert ev["rise_pct"] > 50
        # the attribution block rides along (attr.attribute_live)
        att = ev.get("attribution")
        assert att and att["label"] == "posv_batched_fp32_n64_b4"
        assert att["bottlenecks"]
        assert metrics.snapshot()["counters"][
            "telemetry.sentinel.degradation"] == 1

    def test_error_burst_classified_infra_not_degradation(self):
        s = telemetry.LiveSentinel(baseline=8, window=4,
                                   threshold_pct=50, cooldown_s=60)
        for _ in range(8):
            s.observe("gesv", "fp32.n32", 0.010)
        fired = [e for e in (s.observe("gesv", "fp32.n32", 0.010,
                                       error=True) for _ in range(4))
                 if e]
        assert len(fired) == 1
        assert fired[0]["classification"] == "infra"
        assert fired[0]["kind"] == "errors"

    def test_single_blip_does_not_fire(self):
        s = telemetry.LiveSentinel(baseline=8, window=4,
                                   threshold_pct=50, cooldown_s=60)
        for _ in range(8):
            assert s.observe("posv", "fp32.n64", 0.010) is None
        # one slow sample inside a fast window: median barely moves
        assert s.observe("posv", "fp32.n64", 0.500) is None
        for _ in range(4):
            assert s.observe("posv", "fp32.n64", 0.010) is None
        assert s.events == []

    def test_throughput_drop_kind(self):
        s = telemetry.LiveSentinel(baseline=8, window=4,
                                   threshold_pct=50, cooldown_s=60)
        for _ in range(8):
            s.observe("posv", "fp32.n64", 0.010, batch=16)
        # same latency, occupancy collapsed: solves/s fell 16×
        fired = [e for e in (s.observe("posv", "fp32.n64", 0.010,
                                       batch=1) for _ in range(4)) if e]
        assert len(fired) == 1 and fired[0]["kind"] == "throughput"


class TestSentinelServeIntegration:
    def _run_baseline(self, srv, spd, rhs, count):
        for _ in range(count):
            srv.submit("posv", spd, rhs).result(timeout=300)

    def test_injected_slowdown_fires_one_degradation(self, monkeypatch):
        """The acceptance path: a threaded serve workload under a
        SLATE_TPU_FAULT_INJECT slowdown produces exactly one live
        degradation event with the correct classification, a Perfetto
        trace whose flow spans join on the future's trace id, and a
        Prometheus scrape exposing the p99 histogram."""
        telemetry.configure_sentinel(baseline=6, window=3,
                                     threshold_pct=50, cooldown_s=300)
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002))
        n = 16
        spd, rhs = _spd(n), np.ones(n, np.float32)
        self._run_baseline(srv, spd, rhs, 8)
        monkeypatch.setenv("SLATE_TPU_FAULT_SLOW_S", "0.2")
        inject.install(inject.FaultPlan(seed=3).add(
            "serve.dispatch", "slow", rate=1.0))
        futs = []
        threads = [threading.Thread(
            target=lambda: futs.append(srv.submit("posv", spd, rhs)))
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for f in list(futs):
            f.result(timeout=300)
        inject.clear_plan()
        evs = telemetry.sentinel().events
        assert len(evs) == 1, evs
        assert evs[0]["classification"] == "degradation"
        assert evs[0]["kind"] == "latency"
        assert evs[0]["op"] == "posv"
        # Prometheus: the p99 of the degraded histogram is scrapeable
        port = telemetry.start_exporter(0)
        body = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % port, timeout=30) \
            .read().decode()
        mn = "slate_tpu_serve_latency_ms_posv_fp32_n%d" % _bucket(n)
        assert ("%s_quantile{quantile=\"0.99\"}" % mn) in body
        # Perfetto: flow events join the request chain on the trace id
        sample = futs[0]
        path = trace.finish_perfetto("/tmp/_tel_accept.perfetto.json")
        d = json.load(open(path))
        flows = [e for e in d["traceEvents"]
                 if e["ph"] in ("s", "t", "f")
                 and e["id"] == sample.trace_id]
        assert any(e["ph"] == "s" for e in flows) \
            and any(e["ph"] == "f" for e in flows)
        xs = [e for e in d["traceEvents"] if e["ph"] == "X"
              and e.get("args", {}).get("trace_id") == sample.trace_id]
        assert {e["name"] for e in xs} >= set(SPAN_NAMES)
        srv.close()

    def test_opt_in_trip_opens_breaker_and_serves_singles(self,
                                                          monkeypatch):
        telemetry.configure_sentinel(baseline=6, window=3,
                                     threshold_pct=50, cooldown_s=300)
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002,
                                     breaker_cooldown_s=3600.0,
                                     sentinel_trip=True))
        n = 16
        spd, rhs = _spd(n), np.ones(n, np.float32)
        self._run_baseline(srv, spd, rhs, 8)
        key = srv.bucket_key("posv", (spd, rhs))
        monkeypatch.setenv("SLATE_TPU_FAULT_SLOW_S", "0.2")
        inject.install(inject.FaultPlan(seed=3).add(
            "serve.dispatch", "slow", rate=1.0))
        self._run_baseline(srv, spd, rhs, 3)
        inject.clear_plan()
        assert telemetry.sentinel().events, "sentinel must have fired"
        assert srv._breakers[key].state == "open"
        c = metrics.snapshot()["counters"]
        assert c.get("serve.sentinel.trip", 0) >= 1
        assert c.get("serve.breaker.tripped", 0) >= 1
        # the open breaker degrades the NEXT dispatch to safe singles —
        # and the future still resolves correctly
        x = np.asarray(srv.submit("posv", spd, rhs).result(timeout=300))
        eps = float(np.finfo(np.float32).eps)
        assert (np.linalg.norm(spd @ x - rhs)
                / (np.linalg.norm(spd) * np.linalg.norm(rhs)
                   * eps * n)) < 3
        assert metrics.snapshot()["counters"][
            "serve.breaker.short_circuit"] >= 1
        srv.close()

    def test_no_trip_without_opt_in(self, monkeypatch):
        telemetry.configure_sentinel(baseline=6, window=3,
                                     threshold_pct=50, cooldown_s=300)
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002))
        n = 16
        spd, rhs = _spd(n), np.ones(n, np.float32)
        self._run_baseline(srv, spd, rhs, 8)
        key = srv.bucket_key("posv", (spd, rhs))
        monkeypatch.setenv("SLATE_TPU_FAULT_SLOW_S", "0.2")
        inject.install(inject.FaultPlan(seed=3).add(
            "serve.dispatch", "slow", rate=1.0))
        self._run_baseline(srv, spd, rhs, 3)
        inject.clear_plan()
        assert telemetry.sentinel().events
        assert srv._breakers[key].state == "closed", \
            "without the opt-in an event must only observe, not act"
        srv.close()


class TestReviewRegressions:
    """Pins for the r10 review findings: single-count accounting on
    the singles fallback, deadline-expiry telemetry samples, and the
    dropped-queue hook leak."""

    def test_transient_fallback_counts_each_request_once(self):
        """A transient dispatch failure recovered by loop-of-singles
        must record ONE final outcome per request — one histogram
        sample, one queue_wait span — with the dispatch error feeding
        only the sentinel's error channel."""
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002,
                                     max_retries=0))
        n = 16
        inject.install(inject.FaultPlan(seed=2).add(
            "serve.dispatch", "error", rate=1.0, count=1))
        f = srv.submit("posv", _spd(n), np.ones(n, np.float32))
        x = np.asarray(f.result(timeout=300))
        inject.clear_plan()
        srv.close()
        assert x.shape == (n,)
        snap = metrics.snapshot()
        hname = "serve.latency_ms.posv.fp32.n%d" % _bucket(n)
        assert snap["hists"][hname]["count"] == 1, \
            "the recovered request must not be double-counted"
        assert snap["counters"]["telemetry.dispatch.errors"] == 1
        chain = _spans_by_id()[f.trace_id]
        names = [s[0] for s in chain]
        assert names.count("queue_wait") == 1, names
        assert names.count("dispatch_single") == 1, names

    def test_deadline_expiry_lands_as_error_sample_and_slo_violation(
            self):
        """A timed-out request is the worst-possible latency: it must
        land in the telemetry feed as an error sample AND count as an
        SLO violation, not vanish (survivorship bias under overload —
        100% timeouts must not read as perfect SLO compliance)."""
        srv = BatchQueue(ServeConfig(max_batch=8, max_wait_s=0.05,
                                     slo_ms=1000.0))
        n = 16
        f = srv.submit("posv", _spd(n), np.ones(n, np.float32),
                       deadline_s=0.0)
        with pytest.raises(TimeoutError):
            f.result(timeout=300)
        srv.close()
        c = metrics.snapshot()["counters"]
        assert c["telemetry.request.errors"] == 1
        assert c["serve.slo.violations"] == 1

    def test_dropped_queue_without_close_is_collectable(self):
        """close() is documented as polite, not required: the sentinel
        hook must not pin a dropped BatchQueue forever through the
        module-global hook list."""
        import gc
        import weakref

        q = BatchQueue()
        ref = weakref.ref(q)
        del q
        gc.collect()
        assert ref() is None, \
            "sentinel hook registration leaked the queue"

    def test_bench_serve_restores_metrics_opt_out(self):
        import bench

        metrics.off()
        telemetry.off()
        try:
            bench.bench_serve(False, n=16, nreq=4, max_batch=2)
            assert not metrics.enabled(), \
                "bench_serve must not override a metrics opt-out"
            assert not telemetry.enabled()
        finally:
            metrics.on()
            telemetry.on()


class TestSlowFaultKind:
    def test_parse_and_poll(self):
        plan = inject.parse_plan("serve.dispatch=slow:1.0", seed=9)
        assert plan.poll("serve.dispatch") == "slow"

    def test_slow_seconds_env(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_FAULT_SLOW_S", "0.123")
        assert inject.slow_seconds() == 0.123
        monkeypatch.setenv("SLATE_TPU_FAULT_SLOW_S", "junk")
        assert inject.slow_seconds() == 0.05

    def test_fault_here_sleeps_instead_of_raising(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_FAULT_SLOW_S", "0.05")
        inject.install(inject.FaultPlan(seed=1).add(
            "bench.startup", "slow", rate=1.0))
        t0 = time.perf_counter()
        assert inject.fault_here("bench.startup") is None
        assert time.perf_counter() - t0 >= 0.04
        inject.clear_plan()


class TestOffByDefault:
    def test_lowered_text_bit_identical_with_telemetry_on(self):
        """Telemetry is host-side only: the traced/compiled program of
        a batched driver is byte-identical whether telemetry is on or
        off (the PR 4 contract extended to ISSUE 10's knobs)."""
        import jax

        from slate_tpu.linalg import batched

        a = np.stack([_spd(8, seed=s) for s in range(2)])

        def lower():
            return jax.jit(
                lambda x: batched.potrf_batched(x)).lower(a).as_text()

        telemetry.off()
        base = lower()
        telemetry.on()
        assert lower() == base
        telemetry.configure_sentinel(baseline=2, window=2)
        assert lower() == base

    def test_submit_path_records_nothing_when_off(self):
        telemetry.off()
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002))
        n = 16
        srv.submit("posv", _spd(n), np.ones(n, np.float32)) \
           .result(timeout=300)
        srv.close()
        snap = metrics.snapshot()
        assert not any(k.startswith("serve.latency_ms")
                       for k in snap["hists"])
        assert "serve.slo.violations" not in snap["counters"]
        assert telemetry.spans() == []
        assert telemetry.sentinel().events == []

    def test_observe_request_is_noop_when_off(self):
        telemetry.off()
        telemetry.observe_request("posv", "fp32.n16", 0.001,
                                  slo_ms=0.0001)
        assert metrics.snapshot()["hists"] == {}


class TestRegressDirection:
    def test_serve_percentiles_judged_lower_is_better(self):
        from slate_tpu.perf import regress

        assert regress.direction("serve_posv_fp32_n256_p99_ms") == -1.0
        assert regress.direction("serve_posv_fp32_n256_p50_ms") == -1.0
        assert regress.direction("posv_batched_fp32_n256_b64"
                                 "_solves_per_s") == 1.0
        assert regress.direction("getrf_fp32_n8192") == 1.0

    def test_percentile_rows_have_no_gemm_fraction(self):
        from slate_tpu.perf import regress

        rep = regress.Report(rows=[], artifacts=[], threshold_pct=5.0)
        assert regress.frac_of_gemm(
            rep, "serve_posv_fp32_n256_p99_ms") is None


class TestBenchServeRoutine:
    def test_bench_serve_emits_percentile_submetrics(self):
        import bench

        label, gf, resid, extra = bench.bench_serve(
            False, n=24, nreq=8, max_batch=4)
        assert label == "serve_posv_fp32_n24"
        assert gf > 0 and resid < 3
        assert extra["serve_posv_fp32_n24_p50_ms"] > 0
        assert extra["serve_posv_fp32_n24_p99_ms"] \
            >= extra["serve_posv_fp32_n24_p50_ms"]


class TestHealthQuarantineHook:
    def test_quarantine_driver_public_wrapper(self):
        from slate_tpu.resilience import health

        # no timed/cached decisions on a fresh table: nothing demotable
        assert health.quarantine_driver(
            "posv_batched", reason="test") == 0


class TestTelemetryReportCLI:
    """tools/telemetry_report.py: stdlib-only, by-path loadable, never
    imports jax (driven under a jax-poisoned PYTHONPATH like the
    bench_diff tests)."""

    def _write_log(self, path):
        recs = (
            [{"t": 100.0 + i, "kind": "request", "op": "posv",
              "bucket": "fp32.n256", "latency_ms": 2.0 + i,
              "error": False, "slo_violation": i > 6, "batch": 4}
             for i in range(10)]
            + [{"t": 105.0, "kind": "request", "op": "posv",
                "bucket": "fp32.n256", "latency_ms": 0.0,
                "error": True, "slo_violation": False, "batch": 4},
               {"t": 111.0, "kind": "sentinel",
                "event": {"classification": "degradation",
                          "kind": "latency", "op": "posv",
                          "bucket": "fp32.n256", "rise_pct": 120.0}},
               {"t": 112.0, "kind": "snapshot",
                "counters": {"serve.requests": 11.0}}])
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
            f.write("not json — a live log may be mid-write\n")

    def _run(self, tmp_path, *args):
        import os
        import subprocess
        import sys

        poison = tmp_path / "poison"
        (poison / "jax").mkdir(parents=True, exist_ok=True)
        (poison / "jax" / "__init__.py").write_text(
            "raise ImportError('offline tool must not import jax')")
        env = dict(os.environ, PYTHONPATH=str(poison) + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        cli = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "telemetry_report.py")
        return subprocess.run([sys.executable, cli, *args],
                              capture_output=True, text=True, env=env,
                              timeout=120)

    def test_tables_with_slo_and_sentinel(self, tmp_path):
        log = str(tmp_path / "serve.jsonl")
        self._write_log(log)
        r = self._run(tmp_path, log)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "posv" in r.stdout and "fp32.n256" in r.stdout
        assert "degradation" in r.stdout
        assert "serve.requests" in r.stdout
        assert "1 malformed line(s) skipped" in r.stdout

    def test_json_and_strict_exit(self, tmp_path):
        log = str(tmp_path / "serve.jsonl")
        self._write_log(log)
        r = self._run(tmp_path, log, "--json")
        blob = json.loads(r.stdout)
        row = blob["rows"][0]
        # exact percentiles from the raw values + counted outcomes
        assert row["count"] == 11 and row["errors"] == 1
        assert row["slo_violations"] == 3
        assert abs(row["p50_ms"] - 6.5) < 1e-9
        assert blob["degradations"] == 1
        assert self._run(tmp_path, log, "--strict").returncode == 1

    def test_fleet_rollup(self, tmp_path):
        """The ISSUE 20 --fleet rollup: per-replica req/s + p99, the
        breaker-transition timeline, incident counts and the
        replica-vs-sharded split — still jax-free."""
        log = str(tmp_path / "fleet.jsonl")
        recs = (
            [{"t": 100.0 + i, "kind": "fleet_request", "replica": i % 2,
              "lane": "replica", "op": "posv",
              "latency_ms": 5.0 + i, "error": False}
             for i in range(8)]
            + [{"t": 109.0, "kind": "fleet_request", "lane": "sharded",
                "op": "gesv", "latency_ms": 250.0, "error": False},
               {"t": 110.0, "kind": "fleet_breaker", "replica": 1,
                "state": "open"},
               {"t": 110.1, "kind": "fleet_drain", "replica": 1,
                "requests": 3},
               {"t": 111.0, "kind": "fleet_breaker", "replica": 1,
                "state": "half_open"},
               {"t": 111.5, "kind": "fleet_breaker", "replica": 1,
                "state": "closed"},
               {"t": 111.6, "kind": "fleet_rejoin", "replica": 1}])
        with open(log, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        r = self._run(tmp_path, log, "--fleet")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "fleet rollup:" in r.stdout
        assert "replica 0" in r.stdout and "sharded" in r.stdout
        assert "breaker transitions: 3" in r.stdout
        assert "drain=1" in r.stdout and "rejoin=1" in r.stdout
        blob = json.loads(
            self._run(tmp_path, log, "--fleet", "--json").stdout)
        fleet = blob["fleet"]
        assert fleet["lanes"] == {"replica": 8, "sharded": 1}
        assert [t["state"] for t in fleet["breaker_transitions"]] \
            == ["open", "half_open", "closed"]
        rows = {row["lane"]: row for row in fleet["rows"]}
        assert rows["replica 0"]["count"] == 4
        assert rows["replica 1"]["p99_ms"] is not None
        assert fleet["incidents"] == {"drain": 1, "rejoin": 1}
