"""The serving front door (slate_tpu/serve): bucketing/padding
correctness, threaded mixed-shape submission with residual-gated
futures, queue metrics in metrics.snapshot(), and the warm-start
acceptance criterion — a cache-primed fresh process serves its first
bucketed request with ZERO autotune timing reps and ZERO on-demand /
jit compiles (asserted via the metrics compile-watch counters)."""

import importlib
import threading

import numpy as np
import jax.numpy as jnp
import pytest

from slate_tpu.perf import autotune, metrics
from slate_tpu import serve
from slate_tpu.serve.queue import (BatchQueue, ServeConfig, _bucket,
                                   _pad_square, _pad_tall)


@pytest.fixture(autouse=True)
def _fresh(tmp_path, monkeypatch):
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    autotune.reset_table()
    was = metrics.enabled()
    metrics.on()
    metrics.reset()
    yield
    metrics.reset()
    if not was:
        metrics.off()
    autotune.reset_table()


def _spd(n, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((n, n)).astype(dtype)
    return g @ g.T + n * np.eye(n, dtype=dtype)


class TestBucketsAndPadding:
    def test_bucket_floors(self):
        assert _bucket(5) == 8 and _bucket(9) == 16 and _bucket(64) == 64
        assert _bucket(3, floor=1) == 4 and _bucket(1, floor=1) == 1
        assert _bucket(37, "exact") == 37

    def test_pad_square_preserves_solution(self):
        n, big = 20, 32
        spd = _spd(n, dtype=np.float64)
        padded = _pad_square(spd, big)
        assert padded.shape == (big, big)
        # padded block is the identity: the leading solve is unchanged
        rng = np.random.default_rng(1)
        b = rng.standard_normal(n)
        bp = np.zeros(big)
        bp[:n] = b
        xp = np.linalg.solve(padded, bp)
        assert np.allclose(xp[:n], np.linalg.solve(spd, b))
        assert np.allclose(xp[n:], 0)

    def test_pad_tall_preserves_least_squares(self):
        m, n, big_m, big_n = 40, 17, 64, 32
        rng = np.random.default_rng(2)
        a = rng.standard_normal((m, n))
        b = rng.standard_normal(m)
        ap = _pad_tall(a, big_m, big_n)
        bp = np.zeros(big_m)
        bp[:m] = b
        xp = np.linalg.lstsq(ap, bp, rcond=None)[0]
        x = np.linalg.lstsq(a, b, rcond=None)[0]
        assert np.allclose(xp[:n], x)
        assert np.allclose(xp[n:], 0, atol=1e-10)

    def test_gels_bucket_bumps_rows_for_padded_columns(self):
        q = BatchQueue()
        # m already a power of two but n needs padding: M must grow so
        # the padded columns' anchor rows exist (full column rank)
        key = q.bucket_key("gels", (np.zeros((64, 17), np.float32),
                                    np.zeros((64,), np.float32)))
        op, dt, big_m, big_n, k = key
        assert big_n == 32 and big_m - 64 >= big_n - 17
        q.close()


class TestServeCorrectness:
    def test_threaded_mixed_shape_submission(self):
        """Futures resolve with residual-gated results under concurrent
        mixed-shape submission — the acceptance criterion's threaded
        CPU test."""
        srv = BatchQueue(ServeConfig(max_batch=8, max_wait_s=0.01))
        cases = []
        rng = np.random.default_rng(3)
        for i, n in enumerate((20, 33, 48, 20, 64, 33)):
            spd = _spd(n, seed=i)
            b = rng.standard_normal(n).astype(np.float32)
            cases.append(("posv", (spd, b)))
        for i, n in enumerate((24, 40)):
            a = (rng.standard_normal((n, n)).astype(np.float32)
                 + n * np.eye(n, dtype=np.float32))
            b2 = rng.standard_normal((n, 2)).astype(np.float32)
            cases.append(("gesv", (a, b2)))

        futs = [None] * len(cases)

        def worker(lo, hi):
            for i in range(lo, hi):
                op, operands = cases[i]
                futs[i] = srv.submit(op, *operands)

        threads = [threading.Thread(target=worker, args=(i, i + 2))
                   for i in range(0, len(cases), 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        eps = float(np.finfo(np.float32).eps)
        for (op, operands), fut in zip(cases, futs):
            x = fut.result(timeout=120)
            a, b = operands
            n = a.shape[0]
            r = (np.linalg.norm(a @ x - b)
                 / (np.linalg.norm(a) * np.linalg.norm(b) * eps * n))
            assert r < 3, (op, n, r)
        srv.close()

        # queue metrics present in metrics.snapshot()
        snap = metrics.snapshot()
        assert snap["counters"]["serve.requests"] == len(cases)
        assert snap["counters"]["serve.dispatches"] >= 1
        assert "serve.queue.depth" in snap["gauges"]
        assert "serve.wait" in snap["timers"]
        assert "serve.dispatch" in snap["timers"]
        assert "serve.batch.occupancy" in snap["hists"]

    def test_max_batch_dispatches_immediately(self):
        srv = BatchQueue(ServeConfig(max_batch=4, max_wait_s=30.0))
        spd = _spd(16)
        b = np.ones(16, np.float32)
        futs = [srv.submit("posv", spd, b) for _ in range(4)]
        # max_wait is 30 s: only the occupancy trigger can fire this
        for f in futs:
            f.result(timeout=60)
        srv.close()
        occ = metrics.snapshot()["hists"]["serve.batch.occupancy"]
        assert occ["total"] >= 4

    def test_factor_ops_roundtrip(self):
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.005))
        rng = np.random.default_rng(4)
        n = 24
        a = (rng.standard_normal((n, n)).astype(np.float32)
             + n * np.eye(n, dtype=np.float32))
        lu, perm = srv.submit("getrf", a).result(timeout=60)
        lmat = np.tril(lu, -1) + np.eye(n, dtype=np.float32)
        eps = float(np.finfo(np.float32).eps)
        r = (np.linalg.norm(lmat @ np.triu(lu) - a[perm])
             / (np.linalg.norm(a) * eps * n))
        assert r < 3
        l = srv.submit("potrf", _spd(n, seed=9)).result(timeout=60)
        assert l.shape == (n, n)
        tall = rng.standard_normal((50, 10)).astype(np.float32)
        pk, taus = srv.submit("geqrf", tall).result(timeout=60)
        assert pk.shape == (50, 10) and taus.shape == (10,)
        bb = rng.standard_normal(50).astype(np.float32)
        x = srv.submit("gels", tall, bb).result(timeout=60)
        rr = tall.T @ (tall @ x - bb)
        assert (np.linalg.norm(rr)
                / (np.linalg.norm(tall) ** 2 * np.linalg.norm(x)
                   * eps * np.sqrt(50))) < 3
        srv.close()

    def test_heev_served_eigenpairs_survive_padding(self):
        """Served heev (ISSUE 20): two odd sizes in one queue, both
        bucket-padded, so the [[A,0],[0,αI]] embedding is exercised —
        the answers must be A's OWN eigenpairs (residual-gated,
        ascending, orthonormal), not the padded block's."""
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.005))
        eps = float(np.finfo(np.float32).eps)
        rng = np.random.default_rng(11)
        futs = []
        for n in (12, 20, 12):
            g = rng.standard_normal((n, n)).astype(np.float32)
            a = 0.5 * (g + g.T)
            futs.append((a, srv.submit("heev", a)))
        for a, fut in futs:
            n = a.shape[0]
            w, z = fut.result(timeout=60)
            assert w.shape == (n,) and z.shape == (n, n)
            assert (np.diff(w) >= 0).all(), "eigenvalues not ascending"
            r = (np.linalg.norm(a @ z - z * w)
                 / (np.linalg.norm(a) * eps * n))
            assert r < 3, r
            orth = np.linalg.norm(z.T @ z - np.eye(n)) / (eps * n)
            assert orth < 3, orth
            ref = np.linalg.eigvalsh(a.astype(np.float64))
            assert np.allclose(w, ref, atol=100 * eps * np.abs(ref).max())
        srv.close()

    def test_unknown_op_and_arity_rejected(self):
        srv = BatchQueue()
        with pytest.raises(KeyError):
            srv.submit("sv", np.eye(4, dtype=np.float32))
        with pytest.raises(TypeError):
            srv.submit("posv", np.eye(4, dtype=np.float32))
        srv.close()


class TestWarmStart:
    def test_warm_start_zero_reps_zero_compiles(self, tmp_path,
                                                monkeypatch):
        """The warm-start acceptance criterion, in-process analog of a
        fresh serving process (the importlib-reload pattern of
        test_autotune.py): prime the autotune cache, reload the module
        state, warm-start, then assert the FIRST bucketed request runs
        zero timing reps, zero on-demand executable compiles and zero
        jit backend compiles."""
        n, bsz = 64, 4
        # --- process 1: serve once so the autotune table records the
        # batched sites (heuristic on CPU; a TPU box would persist
        # timed winners the same way)
        srv1 = BatchQueue(ServeConfig(max_batch=bsz, max_wait_s=0.005))
        spd = _spd(n)
        b = np.ones(n, np.float32)
        srv1.submit("posv", spd, b).result(timeout=60)
        srv1.close()
        dec = autotune.decisions()
        assert any(k.startswith("batched_potrf|") for k in dec)

        # --- "fresh process": reloaded autotune module state, new
        # server, warm start from explicit specs (the cache-derived
        # path is covered below)
        mod = importlib.reload(importlib.import_module(
            "slate_tpu.perf.autotune"))
        try:
            srv2 = BatchQueue(ServeConfig(max_batch=bsz,
                                          max_wait_s=0.005))
            compiled = serve.warm_start(srv2, specs=[
                {"op": "posv", "batch": bsz, "dims": (64,),
                 "dtype": "float32"}])
            assert compiled >= 1
            metrics.reset()
            x = srv2.submit("posv", spd, b).result(timeout=60)
            eps = float(np.finfo(np.float32).eps)
            assert (np.linalg.norm(spd @ x - b)
                    / (np.linalg.norm(spd) * np.linalg.norm(b)
                       * eps * n)) < 3
            counters = metrics.snapshot()["counters"]
            assert counters.get("serve.compile.on_demand", 0) == 0, \
                "warm-started bucket must not compile on the serving path"
            assert counters.get("jit.backend_compiles", 0) == 0, \
                "warm-started bucket must not jit-compile on first request"
            assert mod.timing_reps() == 0, \
                "a cache-primed process must run zero probe reps"
            srv2.close()
        finally:
            mod.reset_table()

    def test_specs_derived_from_autotune_cache(self):
        # record a batched decision, then derive warm-start specs from it
        from slate_tpu.linalg import batched
        batched.potrf_batched(jnp.asarray(
            np.stack([_spd(64, seed=s) for s in range(4)])))
        specs = serve.specs_from_autotune_cache()
        ops = {s["op"] for s in specs}
        assert "potrf" in ops and "posv" in ops
        sp = next(s for s in specs if s["op"] == "posv")
        # the cache key carries the BUCKETED batch (pow2, floor 8)
        assert sp["dims"] == (64,) and sp["batch"] == 8

    def test_default_server_submit_and_shutdown(self):
        fut = serve.submit("potrf", _spd(16))
        assert fut.result(timeout=60).shape == (16, 16)
        serve.shutdown()


class TestReviewRegressions:
    """Pins for the r8 review findings: geqrf row-bump, warm/serve key
    agreement, single-rhs bucket floor, warm() cache-hit counting."""

    def test_geqrf_pow2_rows_bucket_bumps_and_serves(self):
        # m already a power of two, n needs padding: without the row
        # bump _pad_tall's column anchors land out of bounds (crash)
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.005))
        rng = np.random.default_rng(11)
        a = rng.standard_normal((64, 17)).astype(np.float32)
        key = srv.bucket_key("geqrf", (a,))
        assert key[2] - 64 >= key[3] - 17
        pk, taus = srv.submit("geqrf", a).result(timeout=60)
        assert pk.shape == (64, 17) and taus.shape == (17,)
        # and the factor is the unpadded one: R reproduces the Gram
        r = np.triu(pk[:17])
        eps = float(np.finfo(np.float32).eps)
        assert (np.linalg.norm(a.T @ a - r.T @ r)
                / (np.linalg.norm(a) ** 2 * eps * np.sqrt(64))) < 3
        srv.close()

    def test_warm_key_matches_serving_key_for_every_op(self):
        """warm() must derive the exact key bucket_key will compute for
        a request of the same RAW dims — incl. the gels/geqrf row
        bump (a mismatch silently defeats the zero-compile start)."""
        from slate_tpu.serve.queue import _exec_key
        srv = BatchQueue()
        f32 = np.float32
        cases = [
            ("potrf", (np.zeros((50, 50), f32),), (50,), 1),
            ("posv", (np.zeros((50, 50), f32), np.zeros(50, f32)),
             (50,), 1),
            ("gesv", (np.zeros((50, 50), f32), np.zeros((50, 3), f32)),
             (50,), 3),
            ("geqrf", (np.zeros((64, 17), f32),), (64, 17), 1),
            ("gels", (np.zeros((256, 250), f32), np.zeros(256, f32)),
             (256, 250), 1),
        ]
        for op, operands, dims, nrhs in cases:
            assert srv.bucket_key(op, operands) == _exec_key(
                op, "float32", srv.config.bucket, dims, nrhs), op
        srv.close()

    def test_single_rhs_buckets_to_one_column(self):
        srv = BatchQueue()
        key = srv.bucket_key("posv", (np.zeros((50, 50), np.float32),
                                      np.zeros(50, np.float32)))
        assert key[3] == 1, "a single rhs must not pad to 8 columns"
        srv.close()

    def test_warm_counts_only_new_compiles(self):
        srv = BatchQueue(ServeConfig(max_batch=4))
        first = srv.warm("potrf", 4, 32)
        assert first >= 1
        assert srv.warm("potrf", 4, 32) == 0, \
            "already-cached executables must count zero"
        srv.close()

    def test_vmem_override_moves_pallas_call_limit_too(self, monkeypatch):
        from slate_tpu.ops import vmem
        assert vmem.pallas_call_limit_bytes() == \
            vmem.PALLAS_CALL_LIMIT_BYTES
        monkeypatch.setenv("SLATE_TPU_VMEM_BUDGET_MB", "200")
        assert vmem.budget_bytes() == 200 * 1024 * 1024
        assert vmem.pallas_call_limit_bytes() == \
            200 * 1024 * 1024 + (vmem.PALLAS_CALL_LIMIT_BYTES
                                 - vmem.BUDGET_BYTES)
