"""The round-6 fused LU panel mega-kernel (getrf_panel_fused: ONE Pallas
invocation owns the panel's column-block loop) and the scattered driver
it powers, exercised in interpret mode on CPU — the same program the TPU
compiles, so pivot parity and residuals here certify the default-capable
path (ISSUE 3 acceptance: off-chip, interpret-mode pivot parity is
scipy-exact).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import scipy.linalg as sla

import slate_tpu as st
from slate_tpu.linalg.lu import getrf_scattered, _panel_lu_fused
from slate_tpu.ops.pallas_kernels import getrf_panel_fused


def _scipy_perm(a):
    """Replay scipy's swap sequence into a permutation vector."""
    _, piv = sla.lu_factor(np.asarray(a, np.float64)
                           if a.dtype == np.float64 else np.asarray(a),
                           check_finite=False)
    want = np.arange(a.shape[0])
    for k, p in enumerate(piv):
        want[k], want[p] = want[p], want[k]
    return want


def _check_scattered(a, nb, pivot_parity=True, tol=3.0):
    """Residual gate + (optionally) scipy-exact pivots for the fused
    scattered driver."""
    m, n = a.shape
    lu, perm = jax.jit(lambda x: getrf_scattered(x, nb))(jnp.asarray(a))
    lu, perm = np.asarray(lu), np.asarray(perm)
    k = min(m, n)
    assert sorted(perm.tolist()) == list(range(m)), "perm not a permutation"
    lmat = np.tril(lu[:, :k], -1) + np.eye(m, k, dtype=a.dtype)
    umat = np.triu(lu[:k])
    eps = np.finfo(a.dtype).eps
    res = (np.abs(a[perm] - lmat @ umat).max()
           / (np.abs(a).max() * max(m, n) * eps))
    assert res < tol, f"scaled residual {res}"
    # TRUE partial pivoting: |L| ≤ 1 up to roundoff
    assert np.abs(np.tril(lu[:, :k], -1)).max() <= 1.0 + 100 * eps
    if pivot_parity:
        want = _scipy_perm(a)
        np.testing.assert_array_equal(perm[:k], want[:k])
    return lu, perm


class TestFusedPanelKernel:
    """Kernel-level contract of the single-invocation panel."""

    def test_panel_contract_and_linv(self):
        rng = np.random.default_rng(0)
        nb, bb, m = 64, 32, 256
        a = rng.standard_normal((m, m)).astype(np.float32)
        at = jnp.asarray(a.T.copy())
        act = jnp.ones((1, m), jnp.float32)
        out, piv, act_out, linv = jax.jit(
            lambda t, c: getrf_panel_fused(t, c, 0, nb=nb, bb=bb, ib=16))(
            at, act)
        out, piv, act_out, linv = map(np.asarray,
                                      (out, piv, act_out, linv))
        assert len(set(piv.tolist())) == nb, "pivots must be distinct"
        # rows outside the panel pass through the aliased carry untouched
        np.testing.assert_array_equal(out[nb:], a.T[nb:])
        rem = np.argsort(act_out[0] < 0.5, kind="stable")[: m - nb]
        perm = np.concatenate([piv, rem])
        lu = out[:nb, perm].T                       # (m, nb) packed
        L = np.tril(lu, -1) + np.vstack(
            [np.eye(nb, dtype=np.float32),
             np.zeros((m - nb, nb), np.float32)])
        U = np.triu(lu[:nb])
        pan = a[:, :nb]
        res = np.linalg.norm(L @ U - pan[perm]) / (
            np.linalg.norm(pan) * np.finfo(np.float32).eps * m)
        assert res < 60, res
        # linv inverts the unit-lower pivot block (pivot-gathered form)
        l11 = np.tril(lu[:nb], -1) + np.eye(nb, dtype=np.float32)
        assert np.linalg.norm(l11 @ linv - np.eye(nb)) < 1e-3
        # scipy-exact pivots for the panel
        np.testing.assert_array_equal(piv, _scipy_perm(pan)[:nb])

    def test_k0_offset_factors_in_place(self):
        """k0 is a scalar operand: the second panel factors at its
        offset through the SAME kernel, leaving earlier rows alone."""
        rng = np.random.default_rng(1)
        m = 128
        a = rng.standard_normal((m, m)).astype(np.float32)
        at = jnp.asarray(a.T.copy())
        act = jnp.ones((1, m), jnp.float32)
        out1, piv0, act1, _ = getrf_panel_fused(at, act, 0,
                                                nb=64, bb=32, ib=16)
        out2, piv1, act2, _ = getrf_panel_fused(out1, act1, 64,
                                                nb=64, bb=32, ib=16)
        np.testing.assert_array_equal(np.asarray(out2)[:64],
                                      np.asarray(out1)[:64])
        both = (set(np.asarray(piv0).tolist())
                | set(np.asarray(piv1).tolist()))
        assert len(both) == m, "panel pivots must be disjoint"

    def test_panel_lu_fused_wrapper_matches_scipy(self):
        """The lu.py lu_panel-candidate wrapper (pad-to-bucket + perm
        assembly + linv) on a tall panel."""
        rng = np.random.default_rng(2)
        m, w = 200, 64                       # forces padding to 512
        a_np = rng.standard_normal((m, w)).astype(np.float32)
        lu, perm, linv = _panel_lu_fused(jnp.asarray(a_np))
        lu, perm = np.asarray(lu), np.asarray(perm)
        assert sorted(perm.tolist()) == list(range(m))
        L = np.tril(lu, -1) + np.vstack(
            [np.eye(w, dtype=np.float32),
             np.zeros((m - w, w), np.float32)])
        U = np.triu(lu[:w])
        res = np.linalg.norm(L @ U - a_np[perm]) / (
            np.linalg.norm(a_np) * np.finfo(np.float32).eps * m)
        assert res < 60, res
        np.testing.assert_array_equal(perm[:w], _scipy_perm(a_np)[:w])


class TestScatteredFusedParity:
    """Driver-level pivot parity vs scipy.linalg.lu_factor across
    square/tall/wide shapes, f32/f64, and the nb sweep the ISSUE names."""

    @pytest.mark.parametrize("m,n", [(256, 256), (384, 128), (128, 256)])
    def test_shapes_f32(self, m, n):
        a = np.random.default_rng(m + n).standard_normal(
            (m, n)).astype(np.float32)
        _check_scattered(a, 128)

    @pytest.mark.parametrize("m,n", [(256, 256), (384, 128), (128, 256)])
    def test_shapes_f64(self, m, n):
        a = np.random.default_rng(2 * m + n + 7).standard_normal((m, n))
        _check_scattered(a, 128)

    @pytest.mark.parametrize("nb", [128, 256, 512])
    def test_nb_sweep(self, nb):
        n = max(256, nb)
        a = np.random.default_rng(nb).standard_normal(
            (n, n)).astype(np.float32)
        _check_scattered(a, nb)

    def test_many_tied_pivots(self):
        """Adversarial ±1 matrix: every column's pivot search hits an
        m-way exact magnitude tie.  On a tie the scattered kernel takes
        the lowest still-active PHYSICAL row while LAPACK takes the
        first max in swapped order, so pivot equality is not defined —
        the factor must still be a valid partial-pivot LU (distinct
        pivots, |L| ≤ 1, residual-gated)."""
        rng = np.random.default_rng(13)
        a = np.sign(rng.standard_normal((256, 256))).astype(np.float32)
        a += np.eye(256, dtype=np.float32) * 0.0   # keep exact ±1 ties
        _check_scattered(a, 128, pivot_parity=False)


class TestEndToEndThroughFusedPath:
    """getrf/gesv routed through the fused scattered driver by the
    autotune table (knob forced on), residual-gated end to end."""

    @pytest.fixture(autouse=True)
    def _force_scattered(self, monkeypatch):
        from slate_tpu.linalg import lu as lu_mod
        from slate_tpu.perf import autotune
        monkeypatch.setattr("slate_tpu.config.scattered_lu", True)
        monkeypatch.setattr(lu_mod, "_SCATTERED_NB", 128)
        autotune.reset_table()
        yield
        autotune.reset_table()

    def test_getrf(self):
        rng = np.random.default_rng(3)
        n = 256
        a = rng.standard_normal((n, n)).astype(np.float32)
        lu, perm = st.getrf(st.Matrix.from_array(a, nb=128))
        lu, perm = np.asarray(lu.array), np.asarray(perm)
        L = np.tril(lu, -1) + np.eye(n, dtype=np.float32)
        U = np.triu(lu)
        eps = np.finfo(np.float32).eps
        res = np.linalg.norm(a[perm] - L @ U) / (
            np.linalg.norm(a) * n * eps)
        assert res < 30, res
        np.testing.assert_array_equal(perm, _scipy_perm(a))

    def test_gesv(self):
        rng = np.random.default_rng(4)
        n, nrhs = 256, 3
        a = (rng.standard_normal((n, n)).astype(np.float32)
             + n * np.eye(n, dtype=np.float32))
        b = rng.standard_normal((n, nrhs)).astype(np.float32)
        lu, perm, x = st.gesv(st.Matrix.from_array(a, nb=128),
                              jnp.asarray(b))
        xv = np.asarray(x)
        eps = np.finfo(np.float32).eps
        res = (np.linalg.norm(a @ xv - b)
               / (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))
        assert res < 3, f"solve residual {res}"
