"""ISSUE 14 — algorithm-based fault tolerance: checksum-carried
factorizations, the detect → correct → recompute → restart ladder, and
step checkpoint/restart.

Four structural guarantees under test:

* **exact location** — a single corrupted trailing element shows the
  SAME syndrome on the row and column checksum; `classify` names its
  exact coordinates and the in-place correction restores the value to
  roundoff (unit-level, f32/f64, hand-injected deltas);
* **end-to-end recovery** — a seeded exponent-bit flip injected at the
  `driver.update` seam of getrf/potrf (composed loop AND the
  scattered/fused/full envelope rungs through the SHIPPED dispatch) is
  detected and corrected/recomputed, final residuals passing the
  existing gates, with ladder counters exact;
* **bitwise restart** — an injected `device_loss` mid-`pgetrf` on the
  CPU mesh resumes from the `SLATE_TPU_CKPT_EVERY_STEPS` snapshot and
  reproduces the uninterrupted factors bitwise (tie-free pivots); the
  chunked runner itself is bitwise against the monolithic build;
* **inertness** — with every new knob unset, compiled programs are
  bit-identical (lowered-text pin) and no ABFT module loads at package
  import (the registry-side pin lives in test_backend_registry).
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import slate_tpu as st
from slate_tpu.linalg import cholesky as chol_mod
from slate_tpu.linalg import lu as lu_mod
from slate_tpu.perf import autotune, metrics, regress
from slate_tpu.perf import attr
from slate_tpu.resilience import abft, checkpoint, inject


@pytest.fixture(autouse=True)
def _clean_state():
    metrics.reset()
    metrics.on()
    inject.clear_plan()
    yield
    inject.clear_plan()
    metrics.reset()


def _abft_counters():
    snap = metrics.snapshot()["counters"]
    return {k: v for k, v in snap.items()
            if k.startswith(("abft.", "ckpt."))}


def _lu_mat(n, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)) + 2.0 * np.sqrt(n) * np.eye(n)
    return a.astype(dtype)


def _spd_mat(n, dtype=np.float32, seed=1):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((n, n))
    return (g @ g.T / n + np.eye(n)).astype(dtype)


def _lu_resid(a, lu, perm):
    n = a.shape[0]
    lmat = np.tril(lu, -1) + np.eye(n, dtype=a.dtype)
    umat = np.triu(lu)
    eps = np.finfo(a.dtype).eps
    return float(np.abs(a[perm] - lmat @ umat).max()
                 / (np.abs(a).max() * n * eps))


def _chol_resid(a, l):
    n = a.shape[0]
    eps = np.finfo(a.dtype).eps
    return float(np.linalg.norm(np.tril(l) @ np.tril(l).T - a)
                 / (np.linalg.norm(a) * eps * n))


# ---------------------------------------------------------------------------
# Checksum arithmetic: syndromes, exact location, exact correction
# ---------------------------------------------------------------------------

class TestChecksumCore:

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_clean_block_classifies_clean(self, dtype):
        rng = np.random.default_rng(2)
        s = rng.standard_normal((96, 96)).astype(dtype)
        cs_row, cs_col = abft.checksums(s)
        kind, i, j, _ = abft.classify(s, cs_row, cs_col)
        assert kind == "clean" and (i, j) == (-1, -1)

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    @pytest.mark.parametrize("ij", [(0, 0), (17, 83), (95, 1)])
    def test_single_corruption_located_exactly_and_corrected(
            self, dtype, ij):
        rng = np.random.default_rng(3)
        s0 = rng.standard_normal((96, 96)).astype(dtype)
        cs_row, cs_col = abft.checksums(s0)
        i0, j0 = ij
        s = s0.copy()
        s[i0, j0] += dtype(7.5)
        kind, i, j, delta = abft.classify(s, cs_row, cs_col)
        assert kind == "single"
        assert (i, j) == (i0, j0), "syndrome pair must locate exactly"
        fixed = abft.correct_single(s, i, j, delta)
        # correction restores to checksum-roundoff, far under eps·n gate
        tol = 200 * np.finfo(dtype).eps * 96
        assert abs(float(fixed[i0, j0] - s0[i0, j0])) < tol

    def test_multi_corruption_classifies_multi(self):
        rng = np.random.default_rng(4)
        s = rng.standard_normal((64, 64)).astype(np.float32)
        cs_row, cs_col = abft.checksums(s)
        s[3, 9] += 5.0
        s[40, 41] -= 11.0
        kind = abft.classify(s, cs_row, cs_col)[0]
        assert kind == "multi"

    def test_nonfinite_syndrome_detected(self):
        rng = np.random.default_rng(5)
        s = rng.standard_normal((32, 32)).astype(np.float32)
        cs_row, cs_col = abft.checksums(s)
        s[2, 2] = np.inf
        assert abft.classify(s, cs_row, cs_col)[0] != "clean"

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_bitflip_is_an_involution(self, dtype):
        x = dtype(1.3), dtype(-271.25), dtype(3e-4)
        for v in x:
            f = inject.flip_exponent_bit(v)
            assert f != v
            assert inject.flip_exponent_bit(f) == v

    def test_corrupt_bitflip_seeded_deterministic(self):
        a = np.arange(64, dtype=np.float32).reshape(8, 8) + 1.0
        inject.install(inject.FaultPlan(seed=42))
        out1, ij1 = inject.corrupt_bitflip(a, "driver.update")
        out2, ij2 = inject.corrupt_bitflip(a, "driver.update")
        inject.clear_plan()
        assert ij1 == ij2 and np.array_equal(out1, out2)

    def test_augment_lu_layout(self):
        a = np.arange(12, dtype=np.float32).reshape(4, 3)
        w = abft.augment_lu(a)
        from slate_tpu.ops import vmem

        cb = vmem.checksum_block_rows(np.float32)
        assert w.shape == (4 + cb, 3 + cb)
        np.testing.assert_allclose(w[4, :3], a.sum(axis=0))
        np.testing.assert_allclose(w[:4, 3], a.sum(axis=1))
        assert w[4, 3] == a.sum()
        # pad lanes beyond the checksum lane ride as exact zeros
        assert not w[5:, :].any() and not w[:, 4:].any()


# ---------------------------------------------------------------------------
# Checksum-carried composed step loops
# ---------------------------------------------------------------------------

class TestComposedLoops:

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    @pytest.mark.parametrize("nb", [128, 256])
    def test_getrf_abft_clean(self, dtype, nb):
        n = 256
        a = _lu_mat(n, dtype)
        lu, perm = map(np.asarray, abft.getrf_abft(jnp.asarray(a), nb))
        assert sorted(perm.tolist()) == list(range(n))
        assert _lu_resid(a, lu, perm) < 3.0
        c = _abft_counters()
        assert c.get("abft.checks", 0) == max(0, n // nb - 1)
        assert "abft.detected" not in c, "clean run must not false-alarm"

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    @pytest.mark.parametrize("nb", [128, 256])
    def test_potrf_abft_clean(self, dtype, nb):
        n = 256
        a = _spd_mat(n, dtype)
        l = np.asarray(abft.potrf_abft(jnp.asarray(a), nb))
        assert _chol_resid(a, l) < 3.0
        c = _abft_counters()
        assert c.get("abft.checks", 0) == max(0, n // nb - 1)
        assert "abft.detected" not in c

    def test_getrf_nb512_single_panel(self):
        # nb covers the whole matrix: no trailing block, zero verifies,
        # still the correct factorization
        n = 512
        a = _lu_mat(n)
        lu, perm = map(np.asarray,
                       abft.getrf_abft(jnp.asarray(a), 512))
        assert _lu_resid(a, lu, perm) < 3.0
        assert "abft.checks" not in _abft_counters()

    def test_getrf_bitflip_detected_corrected_counters_exact(
            self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        n, nb = 256, 64
        a = _lu_mat(n)
        clean = np.asarray(abft.getrf_abft(jnp.asarray(a), nb)[0])
        metrics.reset()
        metrics.on()
        plan = inject.install(
            inject.FaultPlan(seed=7).add("driver.update", "bitflip",
                                         rate=1.0, count=1))
        lu, perm = map(np.asarray, abft.getrf_abft(jnp.asarray(a), nb))
        assert plan.fired("driver.update") == 1
        assert _lu_resid(a, lu, perm) < 3.0
        c = _abft_counters()
        assert c.get("abft.detected") == 1
        assert c.get("abft.corrected") == 1
        assert "abft.recomputed" not in c and "abft.restarted" not in c
        # in-place correction restores the element to checksum
        # roundoff, so the final factors match the clean run tightly
        np.testing.assert_allclose(lu, clean, rtol=1e-4, atol=1e-4)

    def test_potrf_bitflip_detected_corrected(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        n, nb = 256, 64
        a = _spd_mat(n)
        inject.install(
            inject.FaultPlan(seed=3).add("driver.update", "bitflip",
                                         rate=1.0, count=1))
        l = np.asarray(abft.potrf_abft(jnp.asarray(a), nb))
        assert _chol_resid(a, l) < 3.0
        c = _abft_counters()
        assert c.get("abft.detected") == 1
        assert c.get("abft.corrected") == 1

    def test_verify_tier_counts_but_never_acts(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_ABFT", "verify")
        n, nb = 256, 64
        a = _lu_mat(n)
        inject.install(
            inject.FaultPlan(seed=7).add("driver.update", "bitflip",
                                         rate=1.0, count=1))
        lu, perm = map(np.asarray, abft.getrf_abft(jnp.asarray(a), nb))
        c = _abft_counters()
        # the uncorrected corruption propagates, so every later step's
        # verify re-detects it — the tier counts, it never acts
        assert c.get("abft.detected", 0) >= 1
        assert "abft.corrected" not in c and "abft.recomputed" not in c

    def test_non_spd_info_signal_not_treated_as_corruption(
            self, monkeypatch):
        # review finding: a non-SPD potrf input propagating NaN is the
        # DOCUMENTED info signal (health-gate domain) — ABFT must let
        # it flow, never burn recomputes or feed the sentinel
        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        n = 128
        a = _spd_mat(n, seed=16)
        a[0, 0] = -1000.0                 # decisively indefinite
        l = np.asarray(abft.potrf_abft(jnp.asarray(a), 32))
        assert not np.isfinite(l).all(), "info signal must flow out"
        c = _abft_counters()
        assert "abft.detected" not in c and "abft.recomputed" not in c
        assert c.get("abft.nonfinite_input", 0) >= 1

    def test_tall_panel_rung_dispatches(self, monkeypatch):
        # panels past XLA's fused-LU VMEM limit must take the
        # tall-panel rungs, exactly like getrf_panels (review finding:
        # the first cut sent them to the fused XLA panel) — pinned
        # cheaply by shrinking the limit instead of factoring n>8192
        monkeypatch.setattr(lu_mod, "_MAX_LU_PANEL_ROWS", 128)
        n, nb = 256, 64
        a = _lu_mat(n, seed=14)
        lu, perm = map(np.asarray,
                       abft.getrf_abft(jnp.asarray(a), nb))
        assert _lu_resid(a, lu, perm) < 3.0
        from slate_tpu.enums import MethodLU

        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        lu2, perm2 = map(np.asarray, abft.getrf_guarded(
            jnp.asarray(a), nb, MethodLU.PartialPiv))
        assert _lu_resid(a, lu2, perm2) < 3.0

    def test_health_probe_accepts_upper_factor(self, monkeypatch):
        # review finding: the potrf health probe's uplo detection used
        # tril(f) (diagonal included) and mis-probed Upper factors
        from slate_tpu.resilience import health

        a = _spd_mat(64, seed=15)
        hm = st.HermitianMatrix(jnp.asarray(np.triu(a)),
                                uplo=st.Uplo.Upper, nb=32)
        fac = st.potrf(hm)
        r = health._resid_potrf((hm,), {}, fac)
        assert r < 100.0, r

    def test_getrf_device_loss_restart_bitwise(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_CKPT_EVERY_STEPS", "2")
        n, nb = 256, 64
        a = _lu_mat(n)
        base_lu, base_perm = map(np.asarray,
                                 abft.getrf_abft(jnp.asarray(a), nb))
        metrics.reset()
        metrics.on()
        inject.install(
            inject.FaultPlan(seed=1).add("step.boundary", "device_loss",
                                         rate=1.0, count=1))
        lu, perm = map(np.asarray, abft.getrf_abft(jnp.asarray(a), nb))
        c = _abft_counters()
        assert c.get("abft.restarted") == 1
        assert c.get("ckpt.restored") == 1
        assert c.get("ckpt.saved", 0) >= 1
        np.testing.assert_array_equal(lu, base_lu)
        np.testing.assert_array_equal(perm, base_perm)


# ---------------------------------------------------------------------------
# The shipped dispatch end to end: gesv/posv with ABFT on, and the
# scattered/fused/full envelope rungs
# ---------------------------------------------------------------------------

class TestShippedDispatch:

    def test_gesv_bitflip_residual_gated(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        rng = np.random.default_rng(6)
        n, nrhs = 256, 3
        a = _lu_mat(n, seed=6)
        b = rng.standard_normal((n, nrhs)).astype(np.float32)
        inject.install(
            inject.FaultPlan(seed=7).add("driver.update", "bitflip",
                                         rate=1.0, count=1))
        lu, perm, x = st.gesv(st.Matrix.from_array(a, nb=64),
                              jnp.asarray(b))
        xv = np.asarray(x)
        eps = np.finfo(np.float32).eps
        res = (np.linalg.norm(a @ xv - b)
               / (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))
        assert res < 3, res
        assert _abft_counters().get("abft.detected") == 1

    def test_posv_bitflip_residual_gated(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        rng = np.random.default_rng(8)
        n, nrhs = 256, 2
        a = _spd_mat(n, seed=8)
        b = rng.standard_normal((n, nrhs)).astype(np.float32)
        inject.install(
            inject.FaultPlan(seed=3).add("driver.update", "bitflip",
                                         rate=1.0, count=1))
        fac, x = st.posv(st.HermitianMatrix(jnp.asarray(a),
                                            uplo=st.Uplo.Lower, nb=64),
                         jnp.asarray(b))
        xv = np.asarray(x)
        eps = np.finfo(np.float32).eps
        res = (np.linalg.norm(a @ xv - b)
               / (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))
        assert res < 3, res
        assert _abft_counters().get("abft.detected") == 1


class TestEnvelopeRungs:
    """The fused/full Pallas rungs through the SHIPPED `_getrf_partial`
    dispatch (forced sites, interpret mode — the test_step_fused
    pattern), wrapped by the ABFT checksum envelope."""

    @pytest.fixture(autouse=True)
    def _force(self, monkeypatch):
        monkeypatch.setattr("slate_tpu.config.scattered_lu", True)
        monkeypatch.setattr(lu_mod, "_SCATTERED_NB", 128)
        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        autotune.reset_table()
        yield
        autotune.reset_table()

    @pytest.mark.parametrize("depth", ["composed", "fused_trsm",
                                       "fused", "full"])
    def test_bitflip_detected_recomputed_every_depth(self, depth,
                                                     monkeypatch):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                           "lu_step=%s" % depth)
        autotune.reset_table()
        n = 256
        a = _lu_mat(n, seed=11)
        inject.install(
            inject.FaultPlan(seed=11).add("driver.update", "bitflip",
                                          rate=1.0, count=1))
        lu, perm = map(np.asarray,
                       lu_mod._getrf_partial(jnp.asarray(a), 128))
        assert _lu_resid(a, lu, perm) < 3.0
        c = _abft_counters()
        assert c.get("abft.detected") == 1
        assert c.get("abft.recomputed") == 1
        assert "abft.unrecovered" not in c

    def test_clean_envelope_no_false_alarm(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "lu_step=fused")
        autotune.reset_table()
        a = _lu_mat(256, seed=11)
        lu, perm = map(np.asarray,
                       lu_mod._getrf_partial(jnp.asarray(a), 128))
        assert _lu_resid(a, lu, perm) < 3.0
        c = _abft_counters()
        assert c.get("abft.checks") == 1
        assert "abft.detected" not in c

    def test_potrf_envelope_bitflip(self):
        # the potrf envelope mechanics directly (branch says the
        # kernel-owned path): corruption lands on the finished factor,
        # the identity sweep detects, the invocation recomputes
        n = 256
        a = _spd_mat(n, seed=12)
        from slate_tpu.ops import blocks

        inject.install(
            inject.FaultPlan(seed=13).add("driver.update", "bitflip",
                                          rate=1.0, count=1))
        l = np.asarray(abft.potrf_guarded(
            jnp.asarray(a), 128, "fused",
            lambda: jnp.tril(jax.lax.linalg.cholesky(jnp.asarray(a)))))
        assert _chol_resid(a, l) < 3.0
        c = _abft_counters()
        assert c.get("abft.detected") == 1
        assert c.get("abft.recomputed") == 1


# ---------------------------------------------------------------------------
# Checkpoint/restart: the generic runner and pgetrf on the CPU mesh
# ---------------------------------------------------------------------------

class TestCheckpointRunner:

    def test_run_checkpointed_plain(self):
        log = []

        def chunk(carry, k0, k1):
            log.append((k0, k1))
            return (carry or 0) + (k1 - k0)

        out = checkpoint.run_checkpointed(10, 4, chunk)
        assert out == 10
        assert log == [(0, 4), (4, 8), (8, 10)]
        assert _abft_counters().get("ckpt.saved") == 2

    def test_run_checkpointed_restores_on_device_loss(self):
        log = []
        inject.install(
            inject.FaultPlan(seed=2).add("step.boundary", "device_loss",
                                         rate=1.0, count=1))

        def chunk(carry, k0, k1):
            log.append((k0, k1))
            return (carry or 0) + (k1 - k0)

        out = checkpoint.run_checkpointed(10, 4, chunk)
        assert out == 10
        # the first chunk's result was lost at the boundary poll and
        # recomputed from scratch
        assert log[0] == (0, 4) and log[1] == (0, 4)
        c = _abft_counters()
        assert c.get("ckpt.restored") == 1
        assert c.get("abft.restarted") == 1

    def test_nontransient_failure_propagates(self):
        def chunk(carry, k0, k1):
            raise TypeError("programming error, never retried")

        with pytest.raises(TypeError):
            checkpoint.run_checkpointed(4, 2, chunk)

    def test_restart_storm_capped(self):
        inject.install(
            inject.FaultPlan(seed=2).add("step.boundary",
                                         "device_loss", rate=1.0))
        with pytest.raises(inject.DeviceLoss):
            checkpoint.run_checkpointed(4, 2, lambda c, a, b: 0,
                                        max_restarts=2)
        assert _abft_counters().get("ckpt.restored") == 2


class TestPgetrfCheckpoint:

    @pytest.fixture()
    def operands(self, mesh8):
        from slate_tpu.parallel import distribute

        n, nb = 64, 8
        a = _lu_mat(n, seed=0)
        ad = distribute(jnp.asarray(a), mesh8, nb, diag_pad=1.0,
                        row_mult=4, col_mult=2)
        return a, ad

    def test_chunked_bitwise_vs_monolithic(self, operands, monkeypatch):
        from slate_tpu.parallel.dist_lu import pgetrf

        a, ad = operands
        lu0, gp0 = pgetrf(ad)
        monkeypatch.setenv("SLATE_TPU_CKPT_EVERY_STEPS", "3")
        lu1, gp1 = pgetrf(ad)
        np.testing.assert_array_equal(np.asarray(lu1.data),
                                      np.asarray(lu0.data))
        np.testing.assert_array_equal(np.asarray(gp1), np.asarray(gp0))
        assert _abft_counters().get("ckpt.saved", 0) >= 1

    def test_device_loss_mid_pgetrf_resumes_bitwise(self, operands,
                                                    monkeypatch):
        from slate_tpu.parallel.dist_lu import pgetrf

        a, ad = operands
        monkeypatch.setenv("SLATE_TPU_CKPT_EVERY_STEPS", "3")
        base_lu, base_gp = pgetrf(ad)
        metrics.reset()
        metrics.on()
        inject.install(
            inject.FaultPlan(seed=5).add("step.boundary", "device_loss",
                                         rate=1.0, count=1))
        lu, gp = pgetrf(ad)
        c = _abft_counters()
        assert c.get("abft.restarted") == 1
        assert c.get("ckpt.restored") == 1
        np.testing.assert_array_equal(np.asarray(lu.data),
                                      np.asarray(base_lu.data))
        np.testing.assert_array_equal(np.asarray(gp),
                                      np.asarray(base_gp))

    def test_pgetrf_abft_verify_clean_and_detects(self, operands,
                                                  monkeypatch):
        from slate_tpu.parallel import dist_lu

        a, ad = operands
        lu0, gp0 = dist_lu.pgetrf(ad)
        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        lu1, gp1 = dist_lu.pgetrf(ad)
        c = _abft_counters()
        assert c.get("abft.checks") == 1
        assert "abft.detected" not in c
        np.testing.assert_array_equal(np.asarray(lu1.data),
                                      np.asarray(lu0.data))
        # corrupt one factor element -> the identity sweep detects and
        # the envelope recomputes to the clean factors
        bad = np.asarray(lu0.data).copy()
        bad[3, 5] = inject.flip_exponent_bit(bad[3, 5])
        from slate_tpu.grid import ceildiv
        from slate_tpu.parallel.mesh import mesh_grid_shape

        p, q = mesh_grid_shape(ad.mesh)
        metrics.reset()
        metrics.on()
        knobs = ("xla", "maxloc", 1, 1)
        lu2, gp2 = dist_lu._pgetrf_abft_check(
            ad, jnp.asarray(bad), gp0, knobs,
            ceildiv(ad.n, ad.nb), ad.mtp // p, ad.ntp // q)
        c = _abft_counters()
        assert c.get("abft.detected") == 1
        assert c.get("abft.recomputed") == 1
        np.testing.assert_array_equal(np.asarray(lu2),
                                      np.asarray(lu0.data))

    def test_ppotrf_abft_verify_clean(self, mesh8, monkeypatch):
        from slate_tpu.parallel import distribute
        from slate_tpu.parallel.dist_factor import ppotrf

        n, nb = 64, 8
        a = _spd_mat(n, seed=9)
        ad = distribute(jnp.asarray(a), mesh8, nb, diag_pad=1.0,
                        row_mult=4, col_mult=2)
        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        from slate_tpu.parallel import undistribute

        l = np.tril(np.asarray(undistribute(ppotrf(ad))))
        assert _chol_resid(a, l) < 3.0
        c = _abft_counters()
        assert c.get("abft.checks") == 1
        assert "abft.detected" not in c


# ---------------------------------------------------------------------------
# Inertness: bit-identical programs, env grammar, replay determinism
# ---------------------------------------------------------------------------

class TestInertAndDeterminism:

    def test_lowering_bit_identical_with_and_without_abft(self,
                                                          monkeypatch):
        a = jnp.asarray(_lu_mat(128))

        def lower():
            def f(v):        # fresh function: defeat the trace cache
                return lu_mod._getrf_partial(v, 64)

            return jax.jit(f).lower(a).as_text()

        base = lower()
        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        monkeypatch.setenv("SLATE_TPU_CKPT_EVERY_STEPS", "2")
        monkeypatch.setenv("SLATE_TPU_FAULT_INJECT",
                           "driver.update=bitflip:1.0,"
                           "step.boundary=device_loss:1.0")
        assert lower() == base, (
            "ABFT is host-side/eager-only: under a trace the knobs "
            "must not change the compiled program")

    def test_env_grammar_parses_new_kinds(self):
        plan = inject.parse_plan(
            "driver.update=bitflip:0.5:3,step.boundary=device_loss:1.0",
            seed=9)
        assert plan.specs["driver.update"].kind == "bitflip"
        assert plan.specs["driver.update"].count == 3
        assert plan.specs["step.boundary"].kind == "device_loss"

    def test_unknown_kind_still_rejected(self):
        with pytest.raises(ValueError):
            inject.parse_plan("driver.update=gamma_ray:1.0")

    def test_replay_log_deterministic(self):
        def run(seed):
            plan = inject.FaultPlan(seed=seed).add(
                "driver.update", "bitflip", rate=0.5)
            for _ in range(40):
                plan.poll("driver.update")
            return list(plan.log)

        assert run(123) == run(123)
        assert run(123) != run(124)

    def test_device_loss_is_classified_transient(self):
        from slate_tpu.resilience.retry import transient_infra

        assert transient_infra(inject.DeviceLoss("step.boundary"))

    def test_serve_device_loss_counter(self):
        from slate_tpu.serve.queue import BatchQueue, ServeConfig

        inject.install(
            inject.FaultPlan(seed=4).add("serve.dispatch",
                                         "device_loss", rate=1.0,
                                         count=1))
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.001))
        try:
            n = 16
            a = _spd_mat(n)
            b = np.ones(n, np.float32)
            x = np.asarray(srv.submit("posv", a, b).result(timeout=300))
        finally:
            srv.close()
        res = (np.linalg.norm(a @ x - b)
               / (np.linalg.norm(a) * np.linalg.norm(b)
                  * np.finfo(np.float32).eps * n))
        assert res < 3
        snap = metrics.snapshot()["counters"]
        assert snap.get("serve.device_loss") == 1


# ---------------------------------------------------------------------------
# Pricing + sentinel satellites: attr model, bench submetric, regress
# ceiling
# ---------------------------------------------------------------------------

class TestModelAndSentinel:

    def test_attr_checksum_rows_agree_with_vmem(self):
        # attr.py is stdlib-only so it carries the sublane map as a
        # literal — this pin keeps it from drifting off the one true
        # definition in ops/vmem.py
        from slate_tpu.ops import vmem

        assert attr._CHECKSUM_ROWS == vmem._SUBLANE_ROWS
        for isz in (4, 8):
            assert attr._CHECKSUM_ROWS[isz] \
                == vmem.checksum_block_rows(np.dtype("f%d" % isz))

    @pytest.mark.parametrize("routine", ["getrf", "potrf"])
    def test_stage_model_abft_reconciles_and_adds_verify(self, routine):
        dims = {"n": 2048, "nb": 256}
        off = attr.stage_model(routine, dims, "fp32", abft=False)
        on = attr.stage_model(routine, dims, "fp32", abft=True)
        total = attr.model_flops(routine, dims)
        for stages, _ in (off, on):
            got = sum(s["flops"] for s in stages)
            assert abs(got - total) / total < 1e-9, (
                "stage flops must reconcile with the model count")
        names_on = {s["stage"] for s in on[0]}
        assert "verify" in names_on
        assert "verify" not in {s["stage"] for s in off[0]}

    @pytest.mark.parametrize("routine", ["getrf", "potrf"])
    def test_predict_seconds_sees_abft_overhead(self, routine):
        dims = {"n": 4096, "nb": 512}
        t_off = attr.predict_seconds(routine, dims, abft=False)
        t_on = attr.predict_seconds(routine, dims, abft=True)
        assert t_on > t_off
        # and the env default resolves the same flag (the sweep's path)
        os.environ["SLATE_TPU_ABFT"] = "correct"
        try:
            assert attr.predict_seconds(routine, dims) == t_on
        finally:
            os.environ.pop("SLATE_TPU_ABFT")

    def test_attribute_reconciles_with_abft_env(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_ABFT", "correct")
        rep = attr.attribute("getrf_fp32_n2048_nb256", 1000.0)
        got = rep["total_flops"] / rep["measured_s"] / 1e9
        assert abs(got - 1000.0) / 1000.0 < 0.01
        assert any(s["stage"] == "verify" for s in rep["stages"])

    def test_regress_direction_and_num(self):
        assert regress.direction("getrf_fp32_n8192_abft_overhead_pct") \
            == -1.0
        # zero / negative overheads are measurements, not placeholders
        assert regress._num(-0.4, "x_abft_overhead_pct") == -0.4
        assert regress._num(0.0, "x_abft_overhead_pct") == 0.0

    def test_regress_ceiling_single_artifact(self):
        label = "getrf_fp32_n8192_nb512_abft_overhead_pct"
        art = regress.Artifact(path="r1", name="r1",
                               aggregate={"metric": "x"},
                               submetrics={label: 12.5,
                                           "getrf_fp32_n8192_nb512": 100.0})
        rep = regress.diff([art])
        row = next(r for r in rep.rows if r.label == label)
        assert row.verdict == "REGRESS"
        assert "ceiling" in row.note
        assert rep.exit_code == 1

    def test_regress_overhead_not_ratio_judged(self):
        # review finding: a 2.0% -> 2.3% move is a "-15%" ratio in name
        # only; the family is judged by the pinned ceiling alone
        label = "getrf_fp32_n8192_nb512_abft_overhead_pct"
        arts = [regress.Artifact(
            path=nm, name=nm, aggregate={"metric": "x"},
            submetrics={label: v, "getrf_fp32_n8192_nb512": 100.0})
            for nm, v in (("r1", 2.0), ("r2", 2.3))]
        rep = regress.diff(arts)
        row = next(r for r in rep.rows if r.label == label)
        assert row.verdict == "OK"
        assert rep.exit_code == 0

    def test_regress_ceiling_passes_under_10pct(self):
        label = "getrf_fp32_n8192_nb512_abft_overhead_pct"
        arts = []
        for name, v in (("r1", 4.0), ("r2", 3.0)):
            arts.append(regress.Artifact(
                path=name, name=name, aggregate={"metric": "x"},
                submetrics={label: v,
                            "getrf_fp32_n8192_nb512": 100.0}))
        rep = regress.diff(arts)
        row = next(r for r in rep.rows if r.label == label)
        assert row.verdict in ("OK", "IMPROVE")
        assert rep.exit_code == 0

    def test_bench_overhead_helper_restores_env(self, monkeypatch):
        import bench

        monkeypatch.setenv("SLATE_TPU_ABFT", "verify")
        calls = []
        out = bench._abft_overhead_pct(lambda: calls.append(1),
                                       reps=1)
        assert isinstance(out, float)
        assert os.environ["SLATE_TPU_ABFT"] == "verify"
        assert len(calls) == 4            # (warm + 1 rep) x two modes

    def test_bench_overhead_helper_none_on_failure(self):
        import bench

        def boom():
            raise RuntimeError("driver exploded")

        assert bench._abft_overhead_pct(boom) is None
        assert "SLATE_TPU_ABFT" not in os.environ
