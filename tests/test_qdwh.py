"""QDWH spectral tier tests (ISSUE 18) — the polar decomposition
contract across conditioning regimes, the QDWH-eig / QDWH-SVD drivers
through the SHIPPED ``eig_driver`` / ``svd_driver`` dispatch (forced
pins honored off-TPU), crossover consistency against the two-stage
leaf, and the roofline model's gemm-rich attribution pin: ≥80% of a
QDWH label's model flops land on qr/chol/gemm stages and the
attribution reconciles with the reported GFLOP/s at 1%.
"""

import numpy as np
import pytest
import jax.numpy as jnp

import slate_tpu as st
from slate_tpu.linalg import heev_qdwh, polar, svd_qdwh
from slate_tpu.linalg.condest import spectral_interval
from slate_tpu.perf import attr, autotune

try:
    from scipy.linalg import eigvalsh as _ref_eigvalsh
except Exception:                                  # pragma: no cover
    _ref_eigvalsh = np.linalg.eigvalsh


def _eps(dtype):
    return float(np.finfo(np.dtype(dtype)).eps)


def _orthobasis(rng, n):
    q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    return q


#: name -> singular spectrum (polar tests) at size n
_SV_SPECTRA = {
    "well": lambda n: np.linspace(1.0, 2.0, n),
    "ill": lambda n: np.logspace(-6.0, 0.0, n),        # kappa = 1e6
    "clustered": lambda n: np.concatenate(
        [np.full(n // 2, 1.0), np.full(n - n // 2, 1.0 + 1e-4)]),
}

#: name -> eigenvalue spectrum (heev tests) at size n
_EW_SPECTRA = {
    "well": lambda n: np.linspace(0.5, 2.0, n),
    "sign-split": lambda n: np.concatenate(
        [np.linspace(-2.0, -0.5, n // 2),
         np.linspace(0.3, 1.7, n - n // 2)]),
    "clustered": lambda n: np.concatenate(
        [np.full(n // 2, -1.0), np.full(n - n // 2, 1.0 + 1e-4)]),
    "ill": lambda n: np.concatenate(
        [np.logspace(-5.0, 0.0, n // 2), -np.logspace(-5.0, 0.0,
                                                      n - n // 2)]),
}


def _sv_matrix(rng, n, spectrum, dtype):
    """Nonsymmetric n×n with prescribed singular values."""
    u = _orthobasis(rng, n)
    v = _orthobasis(rng, n)
    return ((u * _SV_SPECTRA[spectrum](n)) @ v.T).astype(dtype)


def _ew_matrix(rng, n, spectrum, dtype):
    """Hermitian n×n with prescribed eigenvalues."""
    q = _orthobasis(rng, n)
    a = (q * _EW_SPECTRA[spectrum](n)) @ q.T
    a = 0.5 * (a + a.T)
    return a.astype(dtype)


# ---------------------------------------------------------------------------
# polar(): the QDWH contract  A = U·H,  UᴴU = I,  H ⪰ 0
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("spectrum", sorted(_SV_SPECTRA))
def test_polar_contract(dtype, spectrum):
    rng = np.random.default_rng(7)
    n = 64
    a = _sv_matrix(rng, n, spectrum, dtype)
    u, h = polar(st.Matrix.from_array(a, nb=32))
    uv = np.asarray(u, dtype=np.float64)
    hv = np.asarray(h, dtype=np.float64)
    tol = 50.0 * n * _eps(dtype)
    assert np.linalg.norm(uv.T @ uv - np.eye(n)) < tol
    assert np.linalg.norm(uv @ hv - a) < tol * np.linalg.norm(a)
    assert np.linalg.norm(hv - hv.T) == 0.0          # symmetrized exactly
    assert np.linalg.eigvalsh(hv).min() > -tol * np.linalg.norm(a)
    # H carries A's singular values
    sv_ref = np.linalg.svd(a.astype(np.float64), compute_uv=False)
    sv_h = np.sort(np.linalg.eigvalsh(hv))[::-1]
    assert np.abs(sv_h - sv_ref).max() < tol * sv_ref[0]


def test_polar_rectangular_and_interval():
    """m > n partial isometry, and a caller-supplied condest interval
    must land the same factorization as the internally estimated one."""
    rng = np.random.default_rng(8)
    m, n = 96, 48
    a = rng.standard_normal((m, n)).astype(np.float64)
    u1, h1 = polar(st.Matrix.from_array(a, nb=32))
    iv = spectral_interval(jnp.asarray(a))
    sv = np.linalg.svd(a, compute_uv=False)
    assert iv[0] >= sv[0] * (1.0 - 1e-10)            # alpha >= sigma_max
    assert iv[1] <= sv[-1] * (1.0 + 1e-10)           # deliberately low
    u2, h2 = polar(st.Matrix.from_array(a, nb=32), interval=iv)
    tol = 50.0 * m * _eps(np.float64)
    for uv, hv in ((np.asarray(u1), np.asarray(h1)),
                   (np.asarray(u2), np.asarray(h2))):
        assert uv.shape == (m, n) and hv.shape == (n, n)
        assert np.linalg.norm(uv.T @ uv - np.eye(n)) < tol
        assert np.linalg.norm(uv @ hv - a) < tol * np.linalg.norm(a)


@pytest.mark.parametrize("variant", ["qr", "chol"])
def test_polar_forced_step_variants_agree(variant, monkeypatch):
    """A forced per-iteration Halley variant (the ``qdwh_step`` site)
    still converges to the same polar factor on a well-conditioned
    operand — the variant switch changes flop mix, not the answer."""
    rng = np.random.default_rng(9)
    n = 48
    a = _sv_matrix(rng, n, "well", np.float64)
    u_ref, _ = polar(st.Matrix.from_array(a, nb=16))
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "qdwh_step=" + variant)
    autotune.reset_table()
    u_f, h_f = polar(st.Matrix.from_array(a, nb=16))
    dec = autotune.decisions()
    assert any(k.startswith("qdwh_step|") and v == variant
               for k, v in dec.items()), sorted(dec)
    tol = 50.0 * n * _eps(np.float64)
    assert np.linalg.norm(np.asarray(u_f) - np.asarray(u_ref)) < tol
    assert np.linalg.norm(
        np.asarray(u_f) @ np.asarray(h_f) - a) < tol * np.linalg.norm(a)
    autotune.reset_table()


# ---------------------------------------------------------------------------
# heev_qdwh / svd_qdwh: spectral divide and conquer
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("spectrum", sorted(_EW_SPECTRA))
def test_heev_qdwh_spectra(dtype, spectrum):
    rng = np.random.default_rng(10)
    n = 96
    a = _ew_matrix(rng, n, spectrum, dtype)
    w, z = heev_qdwh(jnp.asarray(a), jobz=True,
                     opts={"qdwh_crossover": 32, "nb": 32})
    wv = np.asarray(w, dtype=np.float64)
    zv = np.asarray(z, dtype=np.float64)
    tol = 300.0 * n * _eps(dtype)
    w_ref = _ref_eigvalsh(a.astype(np.float64))
    scale = np.abs(w_ref).max()
    assert (np.diff(wv) >= -tol * scale).all()       # ascending
    assert np.abs(wv - w_ref).max() < tol * scale
    assert np.linalg.norm(a @ zv - zv * wv) < tol * np.linalg.norm(a)
    assert np.linalg.norm(zv.T @ zv - np.eye(n)) < tol


def test_heev_qdwh_novectors():
    rng = np.random.default_rng(11)
    n = 64
    a = _ew_matrix(rng, n, "sign-split", np.float64)
    w, z = heev_qdwh(jnp.asarray(a), jobz=False,
                     opts={"qdwh_crossover": 32})
    assert z is None
    w_ref = np.linalg.eigvalsh(a)
    assert np.abs(np.asarray(w) - w_ref).max() \
        < 50.0 * n * _eps(np.float64) * np.abs(w_ref).max()


def test_crossover_consistency():
    """The D&C answer must not depend on where the recursion bottoms
    out: a deep recursion (crossover 16), the default, and a crossover
    at n (pure two-stage leaf — zero divide steps) agree to the same
    eigenvalues."""
    rng = np.random.default_rng(12)
    n = 96
    a = _ew_matrix(rng, n, "sign-split", np.float64)
    w_ref = np.linalg.eigvalsh(a)
    tol = 50.0 * n * _eps(np.float64) * np.abs(w_ref).max()
    for crossover in (16, 48, n):
        w, z = heev_qdwh(jnp.asarray(a), jobz=True,
                         opts={"qdwh_crossover": crossover, "nb": 32})
        assert np.abs(np.asarray(w) - w_ref).max() < tol, crossover
        zv = np.asarray(z)
        assert np.linalg.norm(a @ zv - zv * np.asarray(w)) < tol
        assert np.linalg.norm(zv.T @ zv - np.eye(n)) \
            < 50.0 * n * _eps(np.float64)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_svd_qdwh_contract(dtype):
    rng = np.random.default_rng(13)
    n = 96
    a = _sv_matrix(rng, n, "well", dtype)
    s, u, vh = svd_qdwh(jnp.asarray(a), opts={"qdwh_crossover": 32,
                                              "nb": 32})
    sv = np.asarray(s, dtype=np.float64)
    uv = np.asarray(u, dtype=np.float64)
    vhv = np.asarray(vh, dtype=np.float64)
    tol = 300.0 * n * _eps(dtype)
    s_ref = np.linalg.svd(a.astype(np.float64), compute_uv=False)
    assert (np.diff(sv) <= tol * s_ref[0]).all()     # descending
    assert np.abs(sv - s_ref).max() < tol * s_ref[0]
    assert np.linalg.norm((uv * sv) @ vhv - a) < tol * s_ref[0]
    assert np.linalg.norm(uv.T @ uv - np.eye(n)) < tol
    assert np.linalg.norm(vhv @ vhv.T - np.eye(n)) < tol


# ---------------------------------------------------------------------------
# Shipped dispatch: the forced eig_driver/svd_driver pins (acceptance)
# ---------------------------------------------------------------------------

def _heev_e2e(n, dtype):
    rng = np.random.default_rng(n)
    a = _ew_matrix(rng, n, "sign-split", dtype)
    w, z = st.heev(st.HermitianMatrix(jnp.asarray(a), uplo=st.Uplo.Lower),
                   jobz=True)
    wv = np.asarray(w, dtype=np.float64)
    zv = np.asarray(z, dtype=np.float64)
    tol = 300.0 * n * _eps(dtype)
    w_ref = _ref_eigvalsh(a.astype(np.float64))
    scale = np.abs(w_ref).max()
    assert np.abs(wv - w_ref).max() < tol * scale
    assert np.linalg.norm(a @ zv - zv * wv) < tol * np.linalg.norm(a)
    assert np.linalg.norm(zv.T @ zv - np.eye(n)) < tol


def _svd_e2e(n, dtype):
    rng = np.random.default_rng(n + 1)
    a = _sv_matrix(rng, n, "well", dtype)
    s, u, vh = st.svd(st.Matrix.from_array(a))
    sv, uv, vhv = (np.asarray(x, dtype=np.float64) for x in (s, u, vh))
    tol = 300.0 * n * _eps(dtype)
    s_ref = np.linalg.svd(a.astype(np.float64), compute_uv=False)
    assert np.abs(sv - s_ref).max() < tol * s_ref[0]
    assert np.linalg.norm((uv * sv) @ vhv - a) < tol * np.linalg.norm(a)
    assert np.linalg.norm(uv.T @ uv - np.eye(n)) < tol
    assert np.linalg.norm(vhv @ vhv.T - np.eye(n)) < tol


@pytest.fixture
def _forced_qdwh(monkeypatch):
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                       "eig_driver=qdwh,svd_driver=qdwh")
    autotune.reset_table()
    yield
    autotune.reset_table()


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_heev_svd_dispatch_n256(dtype, _forced_qdwh):
    """Acceptance: n=256 f32/f64 through the shipped autotune dispatch
    (forced pins honored off-TPU) — residual, orthogonality, and
    eigenvalue/singular-value parity against the dense reference."""
    _heev_e2e(256, dtype)
    _svd_e2e(256, dtype)
    dec = autotune.decisions()
    assert any(k.startswith("eig_driver|") and v == "qdwh"
               for k, v in dec.items()), sorted(dec)
    assert any(k.startswith("svd_driver|") and v == "qdwh"
               for k, v in dec.items()), sorted(dec)


@pytest.mark.slow
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_heev_svd_dispatch_n1024(dtype, _forced_qdwh):
    """Acceptance at the large dim (slow tier: ~2 min per dtype on one
    CPU core)."""
    _heev_e2e(1024, dtype)
    _svd_e2e(1024, dtype)


# ---------------------------------------------------------------------------
# attr: the gemm-rich stage model (acceptance pin)
# ---------------------------------------------------------------------------

def test_qdwh_label_parsing():
    routine, dtype, dims = attr.parse_label("heev_qdwh_fp32_n1024")
    assert (routine, dtype) == ("heev", "fp32")
    assert dims["n"] == 1024 and dims.get("qdwh") == 1
    routine, dtype, dims = attr.parse_label("svd_qdwh_fp64_n512")
    assert (routine, dtype) == ("svd", "fp64")
    assert dims["n"] == 512 and dims.get("qdwh") == 1
    # plain labels stay on the two-stage model
    routine, _, dims = attr.parse_label("heev_fp32_n1024")
    assert routine == "heev" and not dims.get("qdwh")


@pytest.mark.parametrize("routine", ["heev", "svd"])
def test_qdwh_stage_model_gemm_rich(routine):
    """≥80% of the QDWH model flops are qr/chol/gemm — the tier's whole
    premise — and the stage split reconciles exactly with the
    routine's model flop count."""
    dims = {"n": 1024, "qdwh": 1}
    stages, _ = attr.stage_model(routine, dims)
    total = sum(s["flops"] for s in stages)
    assert total == pytest.approx(attr.model_flops(routine, dims),
                                  rel=1e-9)
    factor = sum(s["flops"] for s in stages
                 if s["stage"] in ("qr", "chol", "gemm"))
    assert factor / total >= 0.80
    assert {s["stage"] for s in stages} == {"qr", "chol", "gemm",
                                            "stage1"}


@pytest.mark.parametrize("label,gf",
                         [("heev_qdwh_fp32_n1024", 4200.0),
                          ("svd_qdwh_fp32_n1024", 3100.0)])
def test_qdwh_attribution_reconciles_at_1pct(label, gf):
    rep = attr.attribute(label, gf)
    assert rep is not None
    total = sum(s["flops"] for s in rep["stages"])
    assert abs(total / rep["measured_s"] / 1e9 - gf) / gf < 0.01
    names = {s["stage"] for s in rep["stages"]}
    assert {"qr", "chol", "gemm"} <= names
    factor = sum(s["flops"] for s in rep["stages"]
                 if s["stage"] in ("qr", "chol", "gemm"))
    assert factor / total >= 0.80


def test_plain_label_with_qdwh_autotune_tag():
    """A plain ``heev_*`` label whose embedded autotune census carries
    ``eig_driver -> qdwh`` attributes on the QDWH model, not the
    two-stage chain."""
    rep = attr.attribute("heev_fp32_n1024", 4200.0,
                         autotune={"eig_driver|1024,float32,HIGH": "qdwh"})
    assert rep is not None
    assert {"qr", "chol", "gemm"} <= {s["stage"] for s in rep["stages"]}
