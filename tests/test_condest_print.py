"""Condition-estimator + printing/redistribute tests — mirroring the
reference testers ``test/test_gecondest.cc``, ``test_trcondest.cc`` and
the ``print.cc`` verbosity contract."""

import numpy as np
import jax.numpy as jnp
import pytest

import slate_tpu as st
from slate_tpu.enums import Diag, Norm, Uplo
from slate_tpu.linalg import condest
from slate_tpu.printing import redistribute, sprint_matrix


def test_gecondest():
    rng = np.random.default_rng(0)
    n = 48
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    lu, perm = st.getrf(jnp.asarray(a))
    anorm = float(st.norm(Norm.One, jnp.asarray(a)))
    rcond = condest.gecondest(Norm.One, lu, perm, anorm)
    true_rcond = 1.0 / (np.linalg.norm(a, 1) * np.linalg.norm(np.linalg.inv(a), 1))
    assert 0.1 * true_rcond < rcond < 10 * true_rcond


def test_pocondest():
    rng = np.random.default_rng(1)
    n = 40
    a = rng.standard_normal((n, n))
    a = a @ a.T + n * np.eye(n)
    fac = st.potrf(jnp.asarray(a))
    anorm = float(np.linalg.norm(a, 1))
    rcond = condest.pocondest(Norm.One, fac, anorm)
    true_rcond = 1.0 / (anorm * np.linalg.norm(np.linalg.inv(a), 1))
    assert 0.1 * true_rcond < rcond < 10 * true_rcond


def test_trcondest():
    rng = np.random.default_rng(2)
    n = 32
    r = np.triu(rng.standard_normal((n, n))) + n * np.eye(n)
    rcond = condest.trcondest(Norm.One, jnp.asarray(r), uplo=Uplo.Upper,
                              diag=Diag.NonUnit)
    true_rcond = 1.0 / (np.linalg.norm(r, 1) * np.linalg.norm(np.linalg.inv(r), 1))
    assert 0.05 * true_rcond < rcond < 20 * true_rcond


def test_print_verbosity():
    rng = np.random.default_rng(3)
    a = st.Matrix.from_array(jnp.asarray(rng.standard_normal((8, 6))),
                             mb=4, nb=4)
    assert sprint_matrix("A", a, verbose=0) == ""
    h = sprint_matrix("A", a, verbose=1)
    assert "Matrix 8x6" in h and "A = [" not in h
    full = sprint_matrix("A", a, verbose=3)
    assert full.count("\n") == 3 + 8  # header + open/close brackets + 8 rows
    tiled = sprint_matrix("A", a, verbose=4)
    assert "|" in tiled and "---" in tiled
    abbrev = sprint_matrix("B", np.arange(400.0).reshape(20, 20), verbose=2)
    assert "..." in abbrev


def test_redistribute(mesh8):
    rng = np.random.default_rng(4)
    from slate_tpu.parallel.dist import distribute, undistribute
    a = rng.standard_normal((40, 24))
    dm = distribute(jnp.asarray(a), mesh8, nb=8)
    dm2 = redistribute(dm, nb=4)
    assert dm2.nb == 4
    assert np.abs(np.asarray(undistribute(dm2)) - a).max() == 0
