"""Roofline attribution engine (slate_tpu/perf/attr.py): the stage
flop/byte model's conservation properties, the round-trip model against
the live ``step.hbm_roundtrips`` counter, the measured-timer join (and
its namespaced-key collision regression), the report's
self-reconciliation with the routine's GFLOP/s, and the sentinel's
golden canned-artifact explanation."""

import json
import math
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from slate_tpu.perf import attr, metrics, regress

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.off()
    metrics.reset()
    yield
    metrics.off()
    metrics.reset()


# ---------------------------------------------------------------------------
# Property: stage flops sum to the driver's model flop count
# ---------------------------------------------------------------------------

_SHAPES = [
    ("getrf", {"m": 256, "n": 256, "nb": 32}),
    ("getrf", {"m": 512, "n": 512, "nb": 128}),
    ("getrf", {"m": 8192, "n": 8192, "nb": 512}),
    ("getrf", {"m": 384, "n": 256, "nb": 64}),
    ("potrf", {"n": 256, "nb": 64}),
    ("potrf", {"n": 8192, "nb": 512}),
    ("potrf", {"n": 1024, "nb": 128}),
    ("geqrf", {"m": 32768, "n": 4096, "nb": 512}),
    ("geqrf", {"m": 512, "n": 256, "nb": 64}),
    ("geqrf", {"m": 256, "n": 256, "nb": 128}),
    ("gels", {"m": 32768, "n": 4096}),
    ("gemm", {"n": 8192}),
    ("heev", {"n": 8192}),
    ("svd", {"n": 1024}),
]


@pytest.mark.parametrize("routine,dims",
                         _SHAPES, ids=[f"{r}-{d}" for r, d in _SHAPES])
def test_stage_flops_sum_to_model_count(routine, dims):
    stages, _ = attr.stage_model(routine, dims)
    total = sum(s["flops"] for s in stages)
    assert math.isclose(total, attr.model_flops(routine, dims),
                        rel_tol=1e-9)
    assert all(s["flops"] >= 0 and s["bytes"] >= 0 for s in stages)


def test_model_flop_counts_match_bench_conventions():
    # the counts bench.py divides wall time by
    n = 8192
    assert attr.model_flops("getrf", {"n": n}) == \
        pytest.approx(2.0 * n ** 3 / 3.0)
    assert attr.model_flops("potrf", {"n": n}) == \
        pytest.approx(n ** 3 / 3.0)
    m2, n2 = 32768, 4096
    assert attr.model_flops("geqrf", {"m": m2, "n": n2}) == \
        pytest.approx(2.0 * m2 * n2 ** 2 - 2.0 * n2 ** 3 / 3.0)
    assert attr.model_flops("gels", {"m": m2, "n": n2}) == \
        pytest.approx(2.0 * m2 * n2 ** 2 - 2.0 * n2 ** 3 / 3.0
                      + 4.0 * m2 * n2)
    assert attr.model_flops("gemm", {"n": n}) == pytest.approx(2.0 * n ** 3)


def test_label_parsing():
    assert attr.parse_label("getrf_fp32_n8192_nb512") == \
        ("getrf", "fp32", {"n": 8192, "nb": 512})
    assert attr.parse_label("geqrf_fp32_m32768_n4096") == \
        ("geqrf", "fp32", {"m": 32768, "n": 4096})
    assert attr.parse_label("not-a-bench-label") == \
        ("not-a-bench-label", "", {})


# ---------------------------------------------------------------------------
# Bytes/round-trip model vs the live step.hbm_roundtrips counter
# ---------------------------------------------------------------------------

def _live_roundtrips(fn, *args):
    metrics.reset()
    metrics.on()
    jax.make_jaxpr(fn)(*args)   # trace-time counters fire here
    snap = metrics.snapshot()["counters"]
    return snap.get(metrics.STEP_HBM_ROUNDTRIPS, 0.0)


@pytest.mark.parametrize("n,nb", [(256, 128), (384, 128)])
@pytest.mark.parametrize("fusion", ["composed", "fused_trsm", "fused"])
def test_getrf_roundtrip_model_matches_counter(n, nb, fusion):
    from slate_tpu.linalg.lu import getrf_scattered

    a = jnp.zeros((n, n), jnp.float32)
    live = _live_roundtrips(
        lambda x: getrf_scattered(x, nb, step=fusion), a)
    assert live == attr.expected_hbm_roundtrips(
        "getrf", {"m": n, "n": n, "nb": nb}, fusion)


@pytest.mark.parametrize("n,nb", [(256, 128), (512, 128)])
def test_potrf_roundtrip_model_matches_counter(n, nb):
    from slate_tpu.ops import blocks

    a = jnp.zeros((n, n), jnp.float32)
    live = _live_roundtrips(lambda x: blocks.potrf_panels(x, nb), a)
    assert live == attr.expected_hbm_roundtrips(
        "potrf", {"n": n, "nb": nb}, "composed")
    fused = _live_roundtrips(lambda x: blocks.potrf_steps(x, nb), a)
    assert fused == 0.0 == attr.expected_hbm_roundtrips(
        "potrf", {"n": n, "nb": nb}, "fused")


# ---------------------------------------------------------------------------
# attribute(): reconciliation, roofline placement, bottleneck ranking
# ---------------------------------------------------------------------------

_R04_SUBMETRICS = {
    "gemm_fp32_n8192": 53421.5,
    "potrf_fp32_n8192": 16476.9,
    "getrf_fp32_n8192_nb512": 7185.9,
    "geqrf_fp32_m32768_n4096": 18905.2,
    "gels_fp32_m32768_n4096": 28781.4,
    "mxu_bf16_n8192": 103095.9,
}


@pytest.mark.parametrize("label,gf", sorted(_R04_SUBMETRICS.items()))
def test_attribution_reconciles_with_reported_gflops(label, gf):
    """Acceptance pin: stage-flop totals ÷ measured seconds reproduce
    the routine's reported GFLOP/s to within 1% on every BENCH_r04
    submetric."""
    rep = attr.attribute(label, gf)
    assert rep is not None
    total = sum(s["flops"] for s in rep["stages"])
    assert abs(total / rep["measured_s"] / 1e9 - gf) / gf < 0.01
    # stage wall-time estimates sum back to the measured total
    est = sum(s["measured_s"] for s in rep["stages"])
    assert est == pytest.approx(rep["measured_s"], rel=1e-3)
    # gap shares sum to the observed deficit (1 - model/measured)
    deficit = sum(s["gap_share"] for s in rep["stages"])
    assert deficit == pytest.approx(
        1.0 - rep["model_s"] / rep["measured_s"], abs=2e-3)
    for s in rep["stages"]:
        assert 0.0 < s["roofline_frac"] <= 1.0
        assert s["bound"] in ("mxu", "hbm", "ici")
    json.loads(json.dumps(rep))     # block must be JSON-clean


def test_attribution_skips_derived_and_invalid_labels():
    assert attr.attribute("heev_fp64_n1024_stage2_chase_s", 0.5) is None
    assert attr.attribute("getrf_fp32_n8192_nb512_frac_of_gemm",
                          0.136) is None
    assert attr.attribute("getrf_fp32_n8192_nb512", 0.0) is None
    assert attr.attribute("unknownroutine_fp32_n64", 5.0) is None
    # the throughput family is a rate, not GFLOP/s — no roofline block
    assert attr.attribute("posv_batched_fp32_n256_b64_solves_per_s",
                          20000.0) is None


# ---------------------------------------------------------------------------
# Leading-batch-dim shapes (ISSUE 8): batched labels parse, scale by b,
# and still reconcile with model flops at 1% — the CI round-trip pin
# ---------------------------------------------------------------------------

_BATCHED_LABELS = {
    "posv_batched_fp32_n256_b64": 1234.5,
    "gesv_batched_fp32_n256_b64": 987.0,
    "potrf_batched_fp32_n128_b64": 456.0,
    "getrf_batched_fp32_n64_b8": 88.0,
    "posv_batched_fp32_n48_b8": 0.62,    # the CPU bench shape
}


def test_batched_label_parsing():
    assert attr.parse_label("posv_batched_fp32_n256_b64") == \
        ("posv", "fp32", {"n": 256, "b": 64})
    assert attr.parse_label("gesv_batched_fp32_n64_b7") == \
        ("gesv", "fp32", {"n": 64, "b": 7})
    # non-batched labels are untouched
    assert attr.parse_label("getrf_fp32_n8192_nb512") == \
        ("getrf", "fp32", {"n": 8192, "nb": 512})


def test_batched_model_scales_with_batch():
    one = attr.model_flops("posv", {"n": 256, "b": 1})
    many = attr.model_flops("posv", {"n": 256, "b": 64})
    assert many == pytest.approx(64 * one)
    # and the stage bytes scale with the batch too
    s1, _ = attr.stage_model("posv", {"n": 256, "b": 1})
    s64, _ = attr.stage_model("posv", {"n": 256, "b": 64})
    by1 = {s["stage"]: s["bytes"] for s in s1}
    for s in s64:
        assert s["bytes"] == pytest.approx(64 * by1[s["stage"]])


@pytest.mark.parametrize("label,gf", sorted(_BATCHED_LABELS.items()))
def test_batched_attribution_reconciles_at_1pct(label, gf):
    """The batched CI round-trip pin: stage-flop totals ÷ measured
    seconds reproduce the batched routine's GFLOP/s within 1%."""
    rep = attr.attribute(label, gf)
    assert rep is not None
    assert rep["dims"].get("b", 1) > 1
    total = sum(s["flops"] for s in rep["stages"])
    assert abs(total / rep["measured_s"] / 1e9 - gf) / gf < 0.01
    assert total == pytest.approx(
        attr.model_flops(rep["routine"], rep["dims"]), rel=1e-9)
    json.loads(json.dumps(rep))


def test_bottlenecks_ranked_and_dominant_stage_first():
    rep = attr.attribute("getrf_fp32_n8192_nb512", 7293.8)
    gaps = [b["gap_s"] for b in rep["bottlenecks"]]
    assert gaps == sorted(gaps, reverse=True)
    # getrf at 13.6% of gemm: the trailing update dominates the gap
    assert rep["bottlenecks"][0]["stage"] == "update"


def test_fusion_depth_from_autotune_tags():
    tags = {"lu_step|8192,8192,512,float32,HIGH": "fused"}
    assert attr.fusion_from_autotune("getrf", tags) == "fused"
    assert attr.fusion_from_autotune("getrf", {}) == "composed"
    rep = attr.attribute("getrf_fp32_n8192_nb512", 7293.8, autotune=tags)
    assert rep["fusion"] == "fused"
    assert rep["hbm_roundtrips"]["model"] == 0.0


# ---------------------------------------------------------------------------
# The ``full`` depth tag (ISSUE 12): lookahead overlap credit +
# reconciliation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("label,gf,op", [
    ("getrf_fp32_n8192_nb512", 7185.9, "lu_step"),
    ("potrf_fp32_n8192", 16476.9, "potrf_step"),
])
def test_full_depth_reconciles_and_credits_lookahead(label, gf, op):
    """The full-depth stage model still reconciles stage flops with the
    reported GFLOP/s at 1% (flop conservation is untouched by the
    overlap credit), models ZERO hbm round trips, and carries the
    lookahead split — panel time hidden under the trailing update's
    roofline minimum, exposed + overlapped summing to the panel's
    uncredited minimum (the dist_util exposed-vs-overlapped shape)."""
    tags = {op + "|whatever,512,float32,HIGH": "full"}
    rep = attr.attribute(label, gf, autotune=tags)
    assert rep["fusion"] == "full"
    total = sum(s["flops"] for s in rep["stages"])
    assert abs(total / rep["measured_s"] / 1e9 - gf) / gf < 0.01
    assert rep["hbm_roundtrips"]["model"] == 0.0
    la = rep["lookahead"]
    assert la["overlapped_s"] + la["exposed_s"] == \
        pytest.approx(la["panel_min_s"], rel=1e-6)
    assert la["overlapped_s"] == pytest.approx(
        min(la["panel_min_s"], la["overlap_budget_s"]), rel=1e-6)
    # the panel stage's critical-path minimum shrank by the credit
    pmin = sum(s["min_s"] for s in rep["stages"]
               if s["stage"] == "panel")
    assert pmin == pytest.approx(la["exposed_s"], abs=1e-9)
    # fused (no credit) carries no lookahead block
    rep_fused = attr.attribute(label, gf)
    assert "lookahead" not in rep_fused
    json.loads(json.dumps(rep))


def test_full_depth_predicts_faster_than_fused():
    """predict_seconds prices the full depth BELOW the per-step fused
    depth (the overlap credit) and both below composed (the round-trip
    term) — the ordering the sweep's analytical pruning relies on."""
    dims = {"m": 8192, "n": 8192, "nb": 512}
    t = {f: attr.predict_seconds("getrf", dims, "fp32", fusion=f)
         for f in ("composed", "fused", "full")}
    assert t["full"] < t["fused"] < t["composed"]
    dims_p = {"n": 8192, "nb": 512}
    tp = {f: attr.predict_seconds("potrf", dims_p, "fp32", fusion=f)
          for f in ("composed", "fused", "full")}
    assert tp["full"] < tp["fused"] < tp["composed"]


def test_full_roundtrip_model_matches_live_counter():
    from slate_tpu.linalg.lu import getrf_scattered
    from slate_tpu.ops import blocks

    a = jnp.zeros((256, 256), jnp.float32)
    metrics.reset()
    metrics.on()
    try:
        jax.make_jaxpr(lambda x: getrf_scattered(x, 128, step="full"))(a)
        jax.make_jaxpr(lambda x: blocks.potrf_full(x, 128))(a)
        live = metrics.snapshot()["counters"].get(
            metrics.STEP_HBM_ROUNDTRIPS, 0.0)
    finally:
        metrics.reset()
        metrics.off()
    assert live == 0.0
    assert attr.expected_hbm_roundtrips(
        "getrf", {"m": 256, "n": 256, "nb": 128}, "full") == 0.0
    assert attr.expected_hbm_roundtrips(
        "potrf", {"n": 256, "nb": 128}, "full") == 0.0


def test_peak_env_overrides(monkeypatch):
    base = attr.peaks("tpu", "fp32")
    monkeypatch.setenv("SLATE_TPU_PEAK_TFLOPS_FP32", "220.0")
    monkeypatch.setenv("SLATE_TPU_PEAK_HBM_GBS", "1600")
    pk = attr.peaks("tpu", "fp32")
    assert pk["tflops"] == 220.0 and pk["hbm_gbs"] == 1600.0
    assert pk["tflops"] != base["tflops"]
    # generic fallback applies when no per-dtype knob is set
    monkeypatch.delenv("SLATE_TPU_PEAK_TFLOPS_FP32")
    monkeypatch.setenv("SLATE_TPU_PEAK_TFLOPS", "42.0")
    assert attr.peaks("tpu", "fp32")["tflops"] == 42.0


def test_collective_stage_exposed_vs_overlapped():
    rep = attr.attribute("getrf_fp32_n8192_nb512", 7185.9,
                         n_devices=8, collective_bytes=8 * 2 ** 30)
    coll = rep["collective"]
    assert coll["bytes"] == 8 * 2 ** 30
    assert coll["overlapped_s"] + coll["exposed_s"] == \
        pytest.approx(coll["min_s"], rel=1e-6)
    assert any(s["stage"] == "collective" for s in rep["stages"])


def test_hlo_collective_census_feeds_attribution(mesh8):
    """The compiled-HLO byte census (hlo_profile.collective_byte_census)
    is the mesh-side ``collective_bytes`` input of the attribution
    engine: profile a fused panel broadcast, census its collectives,
    join the bytes into a gap report."""
    from slate_tpu._jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from slate_tpu.perf.hlo_profile import (COLLECTIVE_KINDS,
                                            collective_byte_census,
                                            profile_fn)
    from slate_tpu.parallel import dist_util
    from slate_tpu.parallel.mesh import AXIS_P, AXIS_Q

    p, nb, mlb = 2, 2, 2
    M = mlb * nb * p

    def kernel(col):
        r = jax.lax.axis_index(AXIS_P)
        grows = dist_util.local_grows(mlb, nb, p, r)
        own = jnp.ones((mlb * nb, 1), jnp.float32)
        return dist_util.bcast_block_col(col, grows, own, M)

    fn = shard_map(kernel, mesh=mesh8,
                   in_specs=(P(AXIS_P, None),), out_specs=P(None, None))
    prof = profile_fn(fn, jnp.ones((mlb * nb * p, 3), jnp.float32))
    census = collective_byte_census(prof)
    assert census["count"] >= 1
    assert census["bytes"] >= M * 3 * 4
    assert set(census["by_kind"]) <= set(COLLECTIVE_KINDS)
    assert census["bytes"] == sum(census["by_kind"].values())
    # the stepped form at 1 trip per communicating body matches the
    # flat census restricted to entry + step loops
    stepped = collective_byte_census(
        prof, trip_counts=[1] * len(prof.step_loops))
    want = prof.entry.collective_count + sum(
        b.collective_count for b in prof.step_loops)
    assert stepped["count"] == want
    with pytest.raises(ValueError):
        collective_byte_census(prof, trip_counts=[1] * 99)
    rep = attr.attribute("getrf_fp32_n8192_nb512", 7185.9, n_devices=8,
                         collective_bytes=census["bytes"])
    assert rep["collective"]["bytes"] == census["bytes"]


def test_twostage_stage2_timer_joins_as_chase():
    """The drivers record the eig/SVD middle stage as
    ``stage.<op>.stage2``; the model names it ``chase`` — the join must
    alias them or a chase regression gets misattributed to the outer
    stages."""
    snap = _fake_snapshot({"stage.heev.stage1": 0.5,
                           "stage.heev.stage2": 9.0,
                           "stage.heev.stage3": 0.5})
    rep = attr.attribute("heev_fp32_n8192", 1000.0,
                         metrics_snapshot=snap)
    assert rep["backend_source"] == "timers"
    by = {s["stage"]: s for s in rep["stages"]}
    # stage2 owns 90% of the timed weight -> the chase stage owns the gap
    assert by["chase"]["measured_s"] > by["stage1"]["measured_s"]
    assert by["chase"]["measured_s"] > by["stage3"]["measured_s"]
    assert rep["bottlenecks"][0]["stage"] == "chase"


# ---------------------------------------------------------------------------
# Measured-timer join + the namespaced-key collision regression
# ---------------------------------------------------------------------------

def _fake_snapshot(timer_totals):
    return {"enabled": True,
            "counters": {},
            "timers": {k: {"count": 1, "total_s": v, "min_s": v,
                           "max_s": v}
                       for k, v in timer_totals.items()}}


def test_timer_join_apportions_measured_time():
    snap = _fake_snapshot({"step.getrf.panel": 8.0,
                           "step.getrf.trsm": 1.0,
                           "step.getrf.update": 1.0})
    rep = attr.attribute("getrf_fp32_n8192_nb512", 7185.9,
                         metrics_snapshot=snap)
    assert rep["backend_source"] == "timers"
    by = {s["stage"]: s for s in rep["stages"]}
    # panel got 80% of the timed weight -> it owns the gap now
    assert by["panel"]["measured_s"] > by["update"]["measured_s"]
    assert rep["bottlenecks"][0]["stage"] == "panel"


def test_two_ops_same_stage_name_do_not_collide():
    """The r7 fix: getrf and potrf both firing an ``update`` stage in
    one routine keep distinct namespaced timers, and the join consumes
    ONLY the requested op's keys — a bare ``step.update`` key (the
    pre-fix collision shape) never joins."""
    metrics.on()
    with metrics.step_timer("getrf", "update"):
        pass
    with metrics.step_timer("potrf", "update"):
        pass
    with metrics.step_timer("potrf", "update"):
        pass
    metrics.observe_time("step.update", 99.0)    # bare legacy key
    snap = metrics.snapshot()
    assert snap["timers"]["step.getrf.update"]["count"] == 1
    assert snap["timers"]["step.potrf.update"]["count"] == 2
    got = attr.stage_timers(snap, "getrf")
    assert set(got) == {"update"} and got["update"]["count"] == 1
    pot = attr.stage_timers(snap, "potrf")
    assert pot["update"]["count"] == 2
    assert pot["update"]["total_s"] < 99.0       # bare key excluded
    assert attr.stage_timers(snap, "update") == {}


def test_step_timer_keys_survive_dotted_names():
    """Dots in op/stage would shift the ``step.<op>.<stage>`` split and
    collide into another op's attribution — metrics sanitizes them."""
    metrics.on()
    with metrics.step_timer("ge.trf", "up.date"):
        pass
    snap = metrics.snapshot()
    assert "step.ge_trf.up_date" in snap["timers"]
    got = attr.stage_timers(snap, "ge_trf")
    assert set(got) == {"up_date"}
    assert attr.stage_timers(snap, "trf") == {}


# ---------------------------------------------------------------------------
# Golden canned-artifact: the sentinel names the injected stage
# ---------------------------------------------------------------------------

def _artifact_with_attr(tmp_path, name, label, gflops, timer_totals):
    rep = attr.attribute(label, gflops,
                         metrics_snapshot=_fake_snapshot(timer_totals))
    agg = {"metric": "factor_suite_fp32_geomean", "value": gflops,
           "unit": "GFLOP/s", "vs_baseline": 1.0,
           "submetrics": {label: gflops},
           "attribution": {label: rep}}
    p = tmp_path / name
    p.write_text(json.dumps({"n": 1, "cmd": "bench", "rc": 0,
                             "tail": "", "parsed": agg}))
    return str(p)


def test_sentinel_explanation_names_injected_regressing_stage(tmp_path):
    """Inject a PANEL-stage blow-up (via measured timers) into an
    otherwise update-dominated getrf: the explanation must name panel —
    proof the diff reads the measured join, not just the flop shares."""
    label = "getrf_fp32_n1024_nb128"
    old = _artifact_with_attr(tmp_path, "r1.json", label, 5000.0,
                              {"step.getrf.panel": 0.1,
                               "step.getrf.trsm": 0.1,
                               "step.getrf.update": 0.8})
    new = _artifact_with_attr(tmp_path, "r2.json", label, 3000.0,
                              {"step.getrf.panel": 5.0,
                               "step.getrf.trsm": 0.1,
                               "step.getrf.update": 0.8})
    report = regress.diff([regress.load_artifact(old),
                           regress.load_artifact(new)])
    assert [r.label for r in report.regressions] == [label]
    lines = regress.explain(report)
    assert len(lines) == 1
    assert "panel stage" in lines[0]
    assert label in lines[0]


def test_checked_in_r03_r04_explanation_names_update_stage():
    """Acceptance: on the real r3→r4 artifacts (which carry NO
    attribution blocks — the model derives from labels alone) the
    geqrf 23.5→18.9 TF/s drop is attributed to the update stage with
    no hand-tuned special case."""
    arts = [regress.load_artifact(os.path.join(_REPO, f))
            for f in ("BENCH_r03.json", "BENCH_r04.json")]
    report = regress.diff(arts)
    lines = regress.explain(report)
    assert len(lines) == 1
    assert lines[0].startswith("geqrf_fp32_m32768_n4096")
    assert "update stage" in lines[0]


def test_explain_empty_when_nothing_regressed(tmp_path):
    a = regress.Artifact(path="a", name="a",
                         submetrics={"gemm_fp32_n8192": 100.0})
    b = regress.Artifact(path="b", name="b",
                         submetrics={"gemm_fp32_n8192": 101.0})
    assert regress.explain(regress.diff([a, b])) == []


# ---------------------------------------------------------------------------
# Roofline gauges -> Perfetto counter tracks
# ---------------------------------------------------------------------------

def test_record_rooflines_feeds_perfetto_counter_tracks(tmp_path):
    from slate_tpu import trace

    trace.clear()
    metrics.on()
    rep = attr.attribute("getrf_fp32_n8192_nb512", 7185.9)
    assert attr.record_rooflines(rep) is True
    path = trace.finish_perfetto(str(tmp_path / "r.json"))
    blob = json.loads(open(path).read())
    roof = [e for e in blob["traceEvents"]
            if e["ph"] == "C" and e["name"].startswith("roofline.")]
    assert roof and all(e["cat"] == "roofline" for e in roof)
    names = {e["name"] for e in roof}
    assert "roofline.getrf_fp32_n8192_nb512.update" in names
    vals = [e["args"]["value"] for e in roof]
    assert all(0.0 < v <= 1.0 for v in vals)


def test_record_rooflines_noop_when_registry_off():
    rep = attr.attribute("getrf_fp32_n8192_nb512", 7185.9)
    assert attr.record_rooflines(rep) is False
    assert metrics.snapshot()["gauges"] == {}
