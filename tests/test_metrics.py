"""Runtime metrics registry (slate_tpu/perf/metrics.py): registry
semantics, off-by-default zero recording, snapshot round-trip through a
bench JSON line, driver-facade instrumentation, the opt-in finite
check, autotune counters, and the Perfetto counter-track export."""

import importlib.util
import json
import os
import sys
import threading

import numpy as np
import jax.numpy as jnp
import pytest

import slate_tpu as st
from slate_tpu import trace
from slate_tpu.perf import metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.off()
    metrics.reset()
    yield
    metrics.off()
    metrics.reset()


def _load_bench():
    path = os.path.join(_REPO, "bench.py")
    spec = importlib.util.spec_from_file_location("_bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------

def test_counters_gauges_timers_hists():
    metrics.on()
    metrics.inc("c")
    metrics.inc("c", 2.5)
    metrics.set_gauge("g", 7.0)
    with metrics.timer("t"):
        pass
    metrics.observe_time("t", 0.5)
    metrics.observe("h", 3.0)
    metrics.observe("h", 100.0)
    snap = metrics.snapshot()
    assert snap["enabled"] is True
    assert snap["counters"]["c"] == 3.5
    assert snap["gauges"]["g"] == 7.0
    t = snap["timers"]["t"]
    assert t["count"] == 2 and t["max_s"] >= 0.5 >= t["min_s"]
    h = snap["hists"]["h"]
    assert h["count"] == 2 and h["total"] == 103.0
    assert sum(h["buckets"].values()) == 2


def test_off_by_default_records_nothing():
    assert not metrics.enabled()
    metrics.inc("c")
    metrics.set_gauge("g", 1.0)
    with metrics.timer("t"):
        pass
    metrics.observe("h", 1.0)
    snap = metrics.snapshot()
    assert snap["counters"] == {} and snap["gauges"] == {}
    assert snap["timers"] == {} and snap["hists"] == {}
    assert metrics.counter_series() == []


def test_env_gate_enables_at_import(monkeypatch):
    """SLATE_TPU_METRICS=1 turns the registry on at import (checked on a
    standalone spec-load of the module so the shared singleton is
    untouched)."""
    path = os.path.join(_REPO, "slate_tpu", "perf", "metrics.py")
    for val, want in (("1", True), ("", False)):
        monkeypatch.setenv("SLATE_TPU_METRICS", val)
        spec = importlib.util.spec_from_file_location(
            "_metrics_env_probe_%s" % (val or "unset"), path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        assert mod.enabled() is want, (val, want)


def test_reset_keeps_enabled_flag():
    metrics.on()
    metrics.inc("x")
    metrics.reset()
    assert metrics.enabled()
    assert metrics.snapshot()["counters"] == {}


def test_thread_safety_under_contention():
    metrics.on()
    n, reps = 8, 500

    def worker():
        for _ in range(reps):
            metrics.inc("contended")

    threads = [threading.Thread(target=worker) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert metrics.snapshot()["counters"]["contended"] == n * reps


def test_snapshot_is_json_round_trippable():
    metrics.on()
    metrics.inc("a.b.c")
    metrics.observe("h", 0.25)
    with metrics.timer("t"):
        pass
    blob = json.dumps(metrics.snapshot())
    back = json.loads(blob)
    assert back["counters"]["a.b.c"] == 1.0


# ---------------------------------------------------------------------------
# The bench JSON line carries the snapshot
# ---------------------------------------------------------------------------

def test_snapshot_rides_every_bench_line(capsys):
    """Per-routine lines carry the metrics DELTA for that routine only
    (r7: the registry accumulates across the process, so a cumulative
    snapshot on a late routine's line would drag every earlier
    routine's counters along); the aggregate stays cumulative."""
    bench = _load_bench()
    metrics.on()
    metrics.inc("marker")                # recorded BEFORE the routine

    def probe():
        metrics.inc("inner.marker")      # recorded DURING the routine
        return ("probe_fp32_n1", 12.0, 0.0)

    sub, fails, infra = {}, [], []
    bench._run_routine("probe", probe, sub, fails, infra)
    bench._run_routine("boom", lambda: (_ for _ in ()).throw(OSError("x")),
                       sub, fails, infra)
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()
             if l.strip()]
    ok = [l for l in lines if l.get("routine") == "probe"][0]
    err = [l for l in lines if l.get("routine") == "boom"][0]
    assert ok["metrics"]["delta"] is True
    assert ok["metrics"]["counters"]["inner.marker"] == 1.0
    assert "marker" not in ok["metrics"]["counters"]   # pre-routine noise
    assert "metrics" in err and err["error"].startswith("infra:")
    agg = bench._partial_aggregate(sub, fails, infra)
    assert agg["metrics"]["counters"]["marker"] == 1.0   # cumulative
    assert agg["metrics"]["counters"]["inner.marker"] == 1.0
    json.loads(json.dumps(agg))          # aggregate stays JSON-clean


def test_attribution_block_rides_bench_line(capsys):
    """A routine whose label has a stage model gets an ``attribution``
    block next to ``metrics`` — and the aggregate collects it."""
    bench = _load_bench()
    metrics.on()
    sub, fails, infra = {}, [], []
    attr_map = {}
    bench._run_routine("getrf",
                       lambda: ("getrf_fp32_n1024_nb128", 500.0, 0.0),
                       sub, fails, infra, attr_sink=attr_map)
    line = [json.loads(l) for l in capsys.readouterr().out.splitlines()
            if l.strip()][0]
    rep = line["attribution"]
    assert rep["routine"] == "getrf"
    assert {s["stage"] for s in rep["stages"]} >= {"panel", "update"}
    total = sum(s["flops"] for s in rep["stages"])
    assert abs(total / rep["measured_s"] / 1e9 - 500.0) / 500.0 < 0.01
    assert attr_map["getrf_fp32_n1024_nb128"] == rep
    agg = bench._partial_aggregate(sub, fails, infra,
                                   attribution=attr_map)
    assert agg["attribution"]["getrf_fp32_n1024_nb128"] == rep


def test_hbm_roundtrips_submetric_rides_bench_line(capsys):
    """ISSUE 12 satellite: getrf/potrf routines derive a structural
    ``<label>_hbm_roundtrips`` submetric from their own metrics DELTA
    (0 on the full-fused depth — the sentinel judges it
    lower-is-better), and it never enters the headline geomean."""
    bench = _load_bench()
    metrics.on()
    sub, fails, infra = {}, [], []

    def composed():
        metrics.count_hbm_roundtrips(3.0)
        return "getrf_fp32_n1024_nb128", 500.0, 0.0

    def full():
        metrics.inc("step.potrf.steps", 2.0)   # traced, zero roundtrips
        return "potrf_fp32_n1024", 700.0, 0.0

    bench._run_routine("getrf", composed, sub, fails, infra)
    bench._run_routine("potrf", full, sub, fails, infra)
    capsys.readouterr()
    assert sub["getrf_fp32_n1024_nb128_hbm_roundtrips"] == 3.0
    assert sub["potrf_fp32_n1024_hbm_roundtrips"] == 0.0

    # a lu_step decision landing INSIDE the delta only contaminates the
    # counter when candidates were actually TIMED (decide() traces the
    # losing depths into this routine's delta): then the shipped
    # depth's model count stands in.  A forced/static/bundle decision
    # runs zero candidates — the raw counter is clean and stays
    # authoritative, so a kernel bug reintroducing round trips on the
    # bundle-warm path is measured, not masked by the model.
    from slate_tpu.perf import autotune

    def forced_cold():
        autotune._static("lu_step", (256, 256, 128, "float32", "HIGH"),
                         "full", "forced")
        metrics.count_hbm_roundtrips(7.0)     # real — must survive
        return "getrf_fp32_n256_nb128", 400.0, 0.0

    def probed_cold():
        autotune._static("lu_step", (512, 512, 128, "float32", "HIGH"),
                         "full", "timed")     # candidates really timed
        metrics.count_hbm_roundtrips(7.0)     # the losing probes' trace
        return "getrf_fp32_n512_nb128", 400.0, 0.0

    autotune.reset_table()
    try:
        bench._run_routine("getrf_cold", forced_cold, sub, fails, infra)
        bench._run_routine("getrf_probe", probed_cold, sub, fails, infra)
    finally:
        autotune.reset_table()
    capsys.readouterr()
    assert sub["getrf_fp32_n256_nb128_hbm_roundtrips"] == 7.0
    assert sub["getrf_fp32_n512_nb128_hbm_roundtrips"] == 0.0
    agg = bench._partial_aggregate(sub, fails, infra)
    # the structural counts stay out of the GFLOP/s geomean (the four
    # GFLOP/s labels only): all still ride the aggregate's submetrics
    assert agg["value"] == pytest.approx(
        float((500.0 * 700.0 * 400.0 * 400.0) ** (1.0 / 4.0)), rel=1e-3)
    assert "getrf_fp32_n1024_nb128_hbm_roundtrips" in agg["submetrics"]
    # the sentinel judges the family lower-is-better
    from slate_tpu.perf import regress
    assert regress.direction("getrf_fp32_n1024_nb128_hbm_roundtrips") \
        == -1.0


def test_snapshot_delta_semantics():
    metrics.on()
    metrics.inc("kept")
    metrics.inc("grown", 2.0)
    metrics.observe_time("t.old", 1.0)
    metrics.observe("h", 1.0)
    before = metrics.snapshot()
    metrics.inc("grown", 3.0)
    metrics.inc("fresh")
    metrics.set_gauge("g", 7.0)
    metrics.observe_time("t.new", 0.25)
    metrics.observe_time("t.new", 0.75)
    metrics.observe("h", 4.0)
    delta = metrics.snapshot_delta(before, metrics.snapshot())
    assert delta["delta"] is True
    assert delta["counters"] == {"grown": 3.0, "fresh": 1.0}
    assert "kept" not in delta["counters"]
    assert delta["gauges"] == {"g": 7.0}
    assert set(delta["timers"]) == {"t.new"}
    t = delta["timers"]["t.new"]
    assert t["count"] == 2 and t["total_s"] == pytest.approx(1.0)
    h = delta["hists"]["h"]
    assert h["count"] == 1 and h["total"] == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# Driver-facade instrumentation
# ---------------------------------------------------------------------------

def _spd(n=16):
    rng = np.random.default_rng(0)
    g = rng.standard_normal((n, n)).astype(np.float32)
    return g @ g.T + n * np.eye(n, dtype=np.float32)


def test_driver_calls_and_wall_time_counted():
    metrics.on()
    st.potrf(st.HermitianMatrix(jnp.asarray(_spd()), uplo=st.Uplo.Lower))
    snap = metrics.snapshot()
    assert snap["counters"]["driver.potrf.calls"] == 1.0
    assert snap["timers"]["driver.potrf"]["count"] == 1
    assert snap["timers"]["driver.potrf"]["total_s"] > 0


def test_instrumentation_off_means_empty_registry():
    st.potrf(st.HermitianMatrix(jnp.asarray(_spd()), uplo=st.Uplo.Lower))
    assert metrics.snapshot()["counters"] == {}


def test_composed_drivers_count_each_facade():
    metrics.on()
    n = 16
    b = np.ones((n, 2), np.float32)
    st.posv(st.HermitianMatrix(jnp.asarray(_spd(n)), uplo=st.Uplo.Lower),
            jnp.asarray(b))
    snap = metrics.snapshot()["counters"]
    # posv = potrf + potrs, all three facades instrumented
    assert snap["driver.posv.calls"] == 1.0
    assert snap["driver.potrf.calls"] == 1.0
    assert snap["driver.potrs.calls"] == 1.0


def test_check_finite_counts_instead_of_raising(monkeypatch):
    monkeypatch.setenv("SLATE_TPU_CHECK_FINITE", "1")
    n = 8
    bad = jnp.asarray(np.full((n, n), np.nan, np.float32))
    with pytest.warns(RuntimeWarning, match="non-finite"):
        out = st.potrf(st.HermitianMatrix(bad, uplo=st.Uplo.Lower))
    assert out is not None               # counted, not raised
    snap = metrics.snapshot()
    assert snap["counters"]["checks.nonfinite"] >= 1.0
    assert snap["counters"]["checks.runs"] >= 1.0


def test_check_finite_quiet_on_finite_outputs(monkeypatch):
    monkeypatch.setenv("SLATE_TPU_CHECK_FINITE", "1")
    st.potrf(st.HermitianMatrix(jnp.asarray(_spd()), uplo=st.Uplo.Lower))
    snap = metrics.snapshot()
    assert "checks.nonfinite" not in snap["counters"]
    assert snap["counters"]["checks.runs"] >= 1.0


# ---------------------------------------------------------------------------
# Autotune + dispatch counters
# ---------------------------------------------------------------------------

def test_autotune_miss_then_hit_counters():
    from slate_tpu.perf import autotune

    autotune.reset_table()
    metrics.on()
    cand = [autotune.Candidate("xla", lambda: (lambda: None))]
    autotune.decide("probeop", (1, 2), cand)
    first = metrics.snapshot()["counters"]
    assert first.get("autotune.miss", 0) >= 1
    assert first.get("dispatch.probeop.xla", 0) >= 1
    autotune.decide("probeop", (1, 2), cand)   # sticky "only" → table hit
    second = metrics.snapshot()["counters"]
    assert second.get("autotune.table.hit", 0) >= 1
    assert second.get("dispatch.probeop.xla", 0) >= 2
    autotune.reset_table()


def test_matmul_dispatch_counter():
    from slate_tpu.perf import autotune
    from slate_tpu.ops import blocks

    autotune.reset_table()
    metrics.on()
    a = jnp.asarray(np.random.default_rng(0)
                    .standard_normal((128, 128)).astype(np.float32))
    blocks.matmul(a, a)
    snap = metrics.snapshot()["counters"]
    assert any(k.startswith("dispatch.matmul.") for k in snap)
    autotune.reset_table()


def test_lu_fallback_device_counter(monkeypatch):
    """SLATE_TPU_METRICS_DEVICE=1 traces a debug callback into the
    _u12_with_linv guard; the fast branch increments lu.u12_linv.fast."""
    monkeypatch.setenv("SLATE_TPU_METRICS_DEVICE", "1")
    metrics.on()
    from slate_tpu.linalg import lu as lu_mod

    n1, nc = 4, 3
    rng = np.random.default_rng(1)
    lo = np.tril(rng.standard_normal((n1, n1)), -1).astype(np.float64) \
        + np.eye(n1)
    lu_top = jnp.asarray(lo + np.triu(np.ones((n1, n1))))
    linv = jnp.asarray(np.linalg.inv(lo))
    c = jnp.asarray(rng.standard_normal((n1, nc)))
    out = lu_mod._u12_with_linv(lu_top, linv, c)
    np.testing.assert_allclose(
        np.asarray(out),
        np.linalg.solve(lo, np.asarray(c)), rtol=1e-10)
    snap = metrics.snapshot()["counters"]
    assert snap.get("lu.u12_linv.sites", 0) >= 1
    assert snap.get("lu.u12_linv.fast", 0) >= 1


def test_pallas_census_records_gauge():
    metrics.on()
    n = metrics.pallas_census("identity", lambda x: x + 1, jnp.ones(4))
    assert n == 0
    assert metrics.snapshot()["gauges"]["pallas.launches.identity"] == 0.0


def test_collective_bcast_counters(mesh8):
    """The dist_util panel broadcasts count calls and bytes at trace
    time when the registry is on."""
    import jax
    from slate_tpu._jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from slate_tpu.parallel import dist_util
    from slate_tpu.parallel.mesh import AXIS_P, AXIS_Q

    metrics.on()
    p, q = 2, 4
    nb, mlb = 2, 2
    M = mlb * nb * p

    def kernel(col):
        r = jax.lax.axis_index(AXIS_P)
        grows = dist_util.local_grows(mlb, nb, p, r)
        own = jnp.ones((mlb * nb, 1), jnp.float32)
        return dist_util.bcast_block_col(col, grows, own, M)

    fn = shard_map(kernel, mesh=mesh8,
                   in_specs=(P(AXIS_P, None),), out_specs=P(None, None))
    col = jnp.ones((mlb * nb * p, 3), jnp.float32)
    np.asarray(jax.jit(fn)(col))
    snap = metrics.snapshot()["counters"]
    assert snap.get("collective.bcast_col.count", 0) >= 1
    assert snap.get("collective.bcast_col.bytes", 0) >= M * 3 * 4


# ---------------------------------------------------------------------------
# Perfetto export (trace spans + metrics counter tracks)
# ---------------------------------------------------------------------------

def test_finish_perfetto_valid_chrome_trace(tmp_path):
    trace.clear()
    trace.on()
    metrics.on()
    with trace.Block("gemm"):
        metrics.inc("probe.counter")
    with trace.Block("potrf", lane="device0"):
        pass
    trace.off()
    path = str(tmp_path / "t.perfetto.json")
    out = trace.finish_perfetto(path)
    assert out == path
    blob = json.loads(open(path).read())
    evts = blob["traceEvents"]
    for e in evts:                       # required Chrome-trace keys
        assert "ph" in e and "pid" in e
        assert "ts" in e or e["ph"] == "M"
    spans = [e for e in evts if e["ph"] == "X"]
    assert {s["name"] for s in spans} == {"gemm", "potrf"}
    counters = [e for e in evts if e["ph"] == "C"]
    assert any(c["name"] == "probe.counter" for c in counters)
    assert all("value" in c["args"] for c in counters)
    lanes = [e for e in evts if e["ph"] == "M"]
    assert any(m["args"]["name"] == "device0" for m in lanes)
    # export consumed both buffers
    assert trace.events() == []
    assert metrics.counter_series() == []


def test_finish_perfetto_empty_returns_none(tmp_path):
    trace.clear()
    metrics.reset()
    assert trace.finish_perfetto(str(tmp_path / "x.json")) is None


def test_finish_perfetto_no_negative_timestamps(tmp_path):
    """Samples recorded BEFORE trace.on() set the origin must not
    export with negative ts (Perfetto clips them); the earliest sample
    re-anchors t=0 and block events shift with it."""
    trace.clear()
    metrics.on()
    metrics.inc("early.counter")         # before tracing starts
    trace.on()
    with trace.Block("late-span"):
        metrics.inc("late.counter")
    trace.off()
    path = trace.finish_perfetto(str(tmp_path / "n.json"))
    blob = json.loads(open(path).read())
    tss = [e["ts"] for e in blob["traceEvents"] if "ts" in e]
    assert tss and min(tss) >= 0.0
    span = [e for e in blob["traceEvents"] if e["ph"] == "X"][0]
    early = [e for e in blob["traceEvents"]
             if e["ph"] == "C" and e["name"] == "early.counter"][0]
    assert early["ts"] <= span["ts"]     # ordering survives the shift


def test_finish_perfetto_counters_only(tmp_path):
    """Counter samples alone (tracing never enabled) still export."""
    trace.clear()
    metrics.on()
    metrics.inc("lonely")
    path = trace.finish_perfetto(str(tmp_path / "c.json"))
    blob = json.loads(open(path).read())
    counters = [e for e in blob["traceEvents"] if e["ph"] == "C"]
    assert counters and counters[0]["ts"] >= 0
