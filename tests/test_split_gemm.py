"""Split-precision bf16 gemm (ops/split_gemm.py): bf16x3/bf16x6 fp32.

The CPU build exercises the same bf16 slice products and fp32
accumulation as the chip (lax.dot with preferred_element_type is
platform-agnostic), so these componentwise bounds pin the scheme's
arithmetic against an fp64 oracle, not just a residual — the fp32
sibling of test_ozaki.py."""

import numpy as np
import jax.numpy as jnp
import pytest

import slate_tpu as st
from slate_tpu import config
from slate_tpu.ops.split_gemm import (
    matmul_split3, matmul_split6, split_slices,
)
from slate_tpu.perf import autotune

EPS32 = float(np.finfo(np.float32).eps)


@pytest.fixture
def rng():
    return np.random.default_rng(1632)


@pytest.fixture
def fresh_table():
    autotune.reset_table()
    yield
    autotune.reset_table()


def _rel_err(fn, a, b):
    """max |fn(a,b) − ab| / (|a||b|) against the fp64 oracle."""
    c = np.asarray(fn(jnp.asarray(a), jnp.asarray(b))).astype(np.float64)
    true = a.astype(np.float64) @ b.astype(np.float64)
    env = np.abs(a).astype(np.float64) @ np.abs(b).astype(np.float64)
    return (np.abs(c - true) / np.maximum(env, 1e-300)).max()


def _tol(fn, k):
    """The documented componentwise contract with 4× headroom:
    (2⁷ + 3k)·ε₃₂ for the 3-pass grade (the 2⁷ term is the dropped
    ≤2⁻¹⁶ slice pairs), 3k·ε₃₂ for the 6-pass grade."""
    floor = 2.0 ** 7 if fn is matmul_split3 else 0.0
    return 4 * (floor + 3 * k) * EPS32


@pytest.mark.parametrize("fn", [matmul_split3, matmul_split6])
@pytest.mark.parametrize("m,k,n", [(64, 64, 64), (96, 256, 64),
                                   (128, 1000, 64)])
def test_componentwise_fp32_grade(rng, fn, m, k, n):
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((k, n)).astype(np.float32)
    # |C − AB| ≤ tol · |A||B| componentwise
    assert _rel_err(fn, a, b) < _tol(fn, k)


def test_wide_dynamic_range_and_zero_rows(rng):
    # adversarial exponent spreads within fp32 range: no pow2 scaling
    # exists to get wrong (bf16 shares the exponent), but mixed-scale
    # rows stress the residual recurrence and slice alignment
    m = k = n = 96
    a = (rng.standard_normal((m, k))
         * np.exp2(rng.integers(-40, 40, size=(m, 1)).astype(np.float64))
         ).astype(np.float32)
    b = (rng.standard_normal((k, n))
         * np.exp2(rng.integers(-40, 40, size=(1, n)).astype(np.float64))
         ).astype(np.float32)
    a[3, :] = 0.0
    b[:, 5] = 0.0
    for fn in (matmul_split3, matmul_split6):
        c = np.asarray(fn(jnp.asarray(a), jnp.asarray(b)))
        assert _rel_err(fn, a, b) < _tol(fn, k)
        assert np.all(c[3, :] == 0.0)
        assert np.all(c[:, 5] == 0.0)


def test_exact_powers_of_two():
    # power-of-two values live entirely in slice 0: the product must
    # come back bit-exact through both grades
    a = np.full((32, 32), 0.5, dtype=np.float32)
    b = np.eye(32, dtype=np.float32)
    for fn in (matmul_split3, matmul_split6):
        c = np.asarray(fn(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_array_equal(c, a)


def test_long_contraction_correlated():
    # all-positive correlated operands past any √k error cancellation:
    # pins the fp32 k-accumulation against the 3k·ε₃₂ envelope
    k = 3000
    a = np.full((2, k), np.float32(1 - 2 ** -12), dtype=np.float32)
    assert _rel_err(matmul_split3, a, a.T) < _tol(matmul_split3, k)
    assert _rel_err(matmul_split6, a, a.T) < _tol(matmul_split6, k)


def test_extreme_exponent_scales():
    # huge-scale rows against tiny-scale columns: the product is in
    # fp32 range even though the slices span ~2⁻²⁴ below each operand
    a = np.full((4, 4), 2.0 ** 120, dtype=np.float32)
    b = np.full((4, 4), 2.0 ** -100, dtype=np.float32)
    c = np.asarray(matmul_split3(jnp.asarray(a), jnp.asarray(b)))
    assert np.isfinite(c).all()
    assert c[0, 0] == np.float32(4 * 2.0 ** 20)
    # inputs at/below the fp32 subnormal boundary: low slices flush on
    # TPU (DAZ/FTZ, the ozaki.py contract) — either way never NaN/Inf
    a = np.full((4, 4), 2.0 ** -130, dtype=np.float32)
    b = np.full((4, 4), 2.0 ** 100, dtype=np.float32)
    c = np.asarray(matmul_split3(jnp.asarray(a), jnp.asarray(b)))
    assert np.isfinite(c).all()


def test_bitwise_determinism(rng):
    a = rng.standard_normal((64, 96)).astype(np.float32)
    b = rng.standard_normal((96, 64)).astype(np.float32)
    for fn in (matmul_split3, matmul_split6):
        c1 = np.asarray(fn(jnp.asarray(a), jnp.asarray(b)))
        c2 = np.asarray(fn(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_array_equal(c1.view(np.int32), c2.view(np.int32))


def test_split_commutes_with_slicing(rng):
    # the property panel folding rests on: the split is elementwise, so
    # window-then-split == split-then-window bit-for-bit
    x = (rng.standard_normal((96, 64))
         * np.exp2(rng.integers(-30, 30, size=(96, 1)).astype(np.float64))
         ).astype(np.float32)
    whole = split_slices(jnp.asarray(x))
    rows, cols = slice(17, 53), slice(5, 60)
    window = split_slices(jnp.asarray(x[rows, cols]))
    for sw, sv in zip(whole, window):
        np.testing.assert_array_equal(
            np.asarray(sw[rows, cols]).view(np.int16),
            np.asarray(sv).view(np.int16))


def test_slices_reconstruct(rng):
    x = rng.standard_normal((64, 64)).astype(np.float32)
    s = split_slices(jnp.asarray(x))
    back = sum(np.asarray(si).astype(np.float64) for si in s)
    assert np.abs(back - x).max() <= 2.0 ** -24 * np.abs(x).max()


def test_type_and_shape_guards(rng):
    a32 = jnp.asarray(rng.standard_normal((8, 8)).astype(np.float32))
    with pytest.raises(TypeError):
        matmul_split3(a32.astype(jnp.float64), a32.astype(jnp.float64))
    with pytest.raises(TypeError):
        matmul_split6(a32.astype(jnp.bfloat16), a32.astype(jnp.bfloat16))
    with pytest.raises(ValueError):
        matmul_split3(a32[None], a32[None])


# ---------------------------------------------------------------------------
# Dispatch integration: forced-site e2e drivers + census + lowering pin
# ---------------------------------------------------------------------------

def test_forced_split_gesv_posv_residual_gates(rng, fresh_table,
                                               monkeypatch):
    """SLATE_TPU_SPLIT_GEMM=1 end to end: the SHIPPED blocked drivers
    take the split3 backend at every fp32 matmul site, residual-gate
    clean, and the autotune census records the decision."""
    monkeypatch.setattr(config, "split_gemm", True)
    n, nrhs = 128, 2
    a = (rng.standard_normal((n, n)).astype(np.float32)
         + n * np.eye(n, dtype=np.float32))
    b = rng.standard_normal((n, nrhs)).astype(np.float32)
    lu, perm, x = st.gesv(st.Matrix.from_array(a, nb=64), jnp.asarray(b))
    xv = np.asarray(x)
    res = (np.linalg.norm(a @ xv - b)
           / (np.linalg.norm(a) * np.linalg.norm(xv) * n * EPS32))
    assert res < 3.0, f"gesv residual {res}"

    g = rng.standard_normal((n, n)).astype(np.float32)
    spd = g @ g.T / n + np.eye(n, dtype=np.float32)
    fac, x2 = st.posv(st.HermitianMatrix(jnp.asarray(spd),
                                         uplo=st.Uplo.Lower, mb=64, nb=64),
                      jnp.asarray(b))
    x2v = np.asarray(x2)
    res2 = (np.linalg.norm(spd @ x2v - b)
            / (np.linalg.norm(spd) * np.linalg.norm(x2v) * n * EPS32))
    assert res2 < 3.0, f"posv residual {res2}"

    dec = autotune.decisions()
    assert any(k.startswith("matmul|") and v == "split3"
               for k, v in dec.items()), dec


def test_forced_split6_census(rng, fresh_table, monkeypatch):
    # the env pin (SLATE_TPU_AUTOTUNE_FORCE=matmul=split6) is the way
    # to select the 6-pass grade off-TPU — the tri-state knob's "on"
    # heuristically prefers split3
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "matmul=split6")
    from slate_tpu.ops import blocks
    a = jnp.asarray(rng.standard_normal((128, 128)).astype(np.float32))
    c = np.asarray(blocks.matmul(a, a))
    assert np.isfinite(c).all()
    dec = autotune.decisions()
    assert any(k.startswith("matmul|") and v == "split6"
               for k, v in dec.items()), dec


def test_mixed_wrappers_split_leg(rng, fresh_table, monkeypatch):
    """posv_mixed / gels_mixed ride the bf16-split low-precision factor
    leg when forced on and still refine to fp64-grade residuals — the
    split error lives entirely in the lo factor, where IR absorbs
    it."""
    monkeypatch.setattr(config, "split_gemm", True)
    n = 96
    g = rng.standard_normal((n, n))
    a = g @ g.T + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    A = st.HermitianMatrix(jnp.asarray(a), uplo=st.Uplo.Lower,
                           mb=32, nb=32)
    x, iters = st.posv_mixed(A, jnp.asarray(b))
    assert iters >= 0, "mixed solver fell back unexpectedly"
    xv = np.asarray(x)
    res = np.linalg.norm(a @ xv - b) / (np.linalg.norm(a)
                                        * np.linalg.norm(xv))
    assert res < 1e-13, f"refined residual {res}"

    m = 160
    am = rng.standard_normal((m, n))
    bm = rng.standard_normal((m, 2))
    xq, qiters = st.gels_mixed(jnp.asarray(am), jnp.asarray(bm))
    xqv = np.asarray(xq)
    # least-squares optimality: the residual is orthogonal to range(A)
    grad = am.T @ (am @ xqv - bm)
    rel = np.linalg.norm(grad) / (np.linalg.norm(am) ** 2
                                  * np.linalg.norm(xqv))
    assert rel < 1e-12, f"normal-equations residual {rel}"


def test_gels_mixed_stock_matches_gels(rng):
    # without the split leg the mixed wrapper must still refine to
    # fp64 grade and agree with the one-shot QR solve
    m, n = 96, 48
    a = rng.standard_normal((m, n))
    b = rng.standard_normal(m)
    x, iters = st.gels_mixed(jnp.asarray(a), jnp.asarray(b))
    xref = np.linalg.lstsq(a, b, rcond=None)[0]
    assert np.asarray(x).shape == (n,)
    np.testing.assert_allclose(np.asarray(x), xref, rtol=1e-9, atol=1e-9)
    with pytest.raises(ValueError):
        st.gels_mixed(jnp.asarray(a.T), jnp.asarray(b[:n]))


def test_off_by_default_lowering_bit_identity(fresh_table, monkeypatch):
    """PR 4 contract: with the knob unset on CPU the auto mode resolves
    to stock — compiled programs are bit-identical to forced-off."""
    import jax

    a = jnp.asarray(np.eye(64, dtype=np.float32) * 4
                    + np.ones((64, 64), np.float32))

    def lower():
        return jax.jit(lambda x: st.getrf(x)[0]).lower(a).as_text()

    monkeypatch.setattr(config, "split_gemm", False)
    base = lower()
    autotune.reset_table()
    monkeypatch.setattr(config, "split_gemm", None)      # unset / auto
    assert lower() == base
