"""Flight recorder (ISSUE 15): the bounded ring, the trigger ladder's
forensic bundles, the measured distributed timeline's bitwise parity,
the serve trace-id join, and the stdlib CLIs — ``tools/blackbox.py``
and the ``telemetry_report.py --blackbox`` correlation — on a
jax-poisoned path like the other offline tools."""

import glob
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from slate_tpu.perf import blackbox, metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CLI = os.path.join(_REPO, "tools", "blackbox.py")
_TELE_CLI = os.path.join(_REPO, "tools", "telemetry_report.py")


@pytest.fixture
def recorder(tmp_path, monkeypatch):
    """Recorder on, dumping into tmp_path; always restored off+empty."""
    monkeypatch.setenv("SLATE_TPU_BLACKBOX_DIR", str(tmp_path))
    blackbox.reset()
    blackbox.on()
    yield tmp_path
    blackbox.off()
    blackbox.reset()


def _poison_env(tmp_path):
    poison = tmp_path / "poison"
    (poison / "jax").mkdir(parents=True, exist_ok=True)
    (poison / "jax" / "__init__.py").write_text(
        "raise ImportError('jax must not be imported by this CLI')\n")
    return dict(os.environ,
                PYTHONPATH=str(poison) + os.pathsep
                + os.environ.get("PYTHONPATH", ""))


# ---------------------------------------------------------------------------
# The ring
# ---------------------------------------------------------------------------

class TestRing:
    def test_off_is_a_no_op(self):
        blackbox.off()
        blackbox.reset()
        blackbox.record("x", a=1)
        assert blackbox.events() == []
        assert blackbox.trigger("nope") is None
        assert blackbox.dump("nope") is None

    def test_bounded_oldest_dropped(self, recorder):
        blackbox.on(ring=4)
        try:
            for i in range(10):
                blackbox.record("k", i=i)
            evs = blackbox.events()
            assert len(evs) == 4
            assert [e["i"] for e in evs] == [6, 7, 8, 9]
        finally:
            blackbox.on(ring=512)

    def test_events_are_stamped_and_typed(self, recorder):
        t0 = time.time()
        blackbox.record("health.fail", driver="potrf", mode="retry")
        (ev,) = blackbox.events()
        assert ev["kind"] == "health.fail"
        assert ev["driver"] == "potrf"
        assert abs(ev["t"] - t0) < 5.0


# ---------------------------------------------------------------------------
# Bundles
# ---------------------------------------------------------------------------

class TestBundle:
    def test_trigger_dumps_versioned_bundle(self, recorder):
        blackbox.record("abft.detected", driver="getrf", detail="syn")
        info = blackbox.trigger("quarantine", "unit-test detail")
        assert info and os.path.exists(info["path"])
        with open(info["path"]) as f:
            text = f.read()
        import hashlib

        assert info["digest"] == \
            hashlib.sha256(text.encode()).hexdigest()[:16]
        blob = json.loads(text)
        assert blob["schema"] == blackbox.SCHEMA
        assert blob["trigger"]["reason"] == "quarantine"
        kinds = [e["kind"] for e in blob["events"]]
        assert kinds[-1] == "trigger" and "abft.detected" in kinds
        # every bundle section present (content best-effort)
        for key in ("host", "knobs", "config", "autotune",
                    "fault_plan", "metrics"):
            assert key in blob, key
        assert blackbox.last_bundle()["path"] == info["path"]

    def test_bundle_carries_fault_plan_log(self, recorder):
        from slate_tpu.resilience import inject

        inject.install(inject.FaultPlan(seed=3).add("driver.output",
                                                    "nan", rate=1.0))
        try:
            assert inject.poll("driver.output") == "nan"
            info = blackbox.trigger("health.strict")
            with open(info["path"]) as f:
                blob = json.load(f)
            fp = blob["fault_plan"]
            assert fp["seed"] == 3 and fp["fired"] == 1
            assert fp["log"][0]["site"] == "driver.output"
            # the firing also entered the ring as an event
            assert any(e["kind"] == "inject.fired"
                       for e in blob["events"])
        finally:
            inject.clear_plan()

    def test_dump_cap_honoured(self, recorder, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_BLACKBOX_MAX_DUMPS", "2")
        assert blackbox.trigger("breaker.open") is not None
        assert blackbox.trigger("breaker.open") is not None
        assert blackbox.trigger("breaker.open") is None  # capped
        assert len(glob.glob(str(recorder / "*.json"))) == 2
        # capped triggers still reference the last bundle written
        assert blackbox.last_bundle() is not None

    def test_breaker_trip_triggers_bundle(self, recorder):
        from slate_tpu.resilience.breaker import CircuitBreaker

        CircuitBreaker(name="unit/bucket").trip()
        bundles = glob.glob(str(recorder / "*.json"))
        assert len(bundles) == 1
        with open(bundles[0]) as f:
            blob = json.load(f)
        assert blob["trigger"]["reason"] == "breaker.trip"
        assert any(e["kind"] == "breaker.trip"
                   and e.get("name") == "unit/bucket"
                   for e in blob["events"])

    def test_health_strict_failure_triggers_bundle(self, recorder,
                                                   monkeypatch):
        from slate_tpu.exceptions import SlateError
        from slate_tpu.resilience import health

        monkeypatch.setenv("SLATE_TPU_HEALTH", "strict")
        bad = np.full((2, 2), np.nan, np.float32)
        with pytest.raises(SlateError):
            health.driver_gate("gemm", lambda: bad, (), {}, bad)
        bundles = glob.glob(str(recorder / "*.json"))
        assert len(bundles) == 1
        with open(bundles[0]) as f:
            blob = json.load(f)
        assert blob["trigger"]["reason"] == "health.strict"
        kinds = [e["kind"] for e in blob["events"]]
        assert "health.fail" in kinds and "health.retry" in kinds \
            and "health.unrecovered" in kinds

    def test_excepthook_optin_dumps_on_uncaught(self, tmp_path):
        code = (
            "from slate_tpu.perf import blackbox\n"
            "blackbox.record('bench.routine', name='x')\n"
            "raise RuntimeError('uncaught-unit-test')\n")
        env = dict(os.environ, SLATE_TPU_BLACKBOX="1",
                   SLATE_TPU_BLACKBOX_EXCEPTHOOK="1",
                   SLATE_TPU_BLACKBOX_DIR=str(tmp_path),
                   JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode != 0
        bundles = glob.glob(str(tmp_path / "slate_tpu_blackbox_*.json"))
        assert len(bundles) == 1, (r.stdout, r.stderr)
        with open(bundles[0]) as f:
            blob = json.load(f)
        assert blob["trigger"]["reason"] == "excepthook"
        assert "uncaught-unit-test" in blob["trigger"]["detail"]


# ---------------------------------------------------------------------------
# Serve join: dispatch events carry the PR 10 trace ids
# ---------------------------------------------------------------------------

def test_serve_dispatch_events_carry_trace_ids(recorder):
    from slate_tpu.perf import telemetry
    from slate_tpu.serve.queue import BatchQueue, ServeConfig

    was_tele, was_metrics = telemetry.enabled(), metrics.enabled()
    telemetry.on()
    srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.002))
    try:
        n = 8
        rng = np.random.default_rng(0)
        g = rng.standard_normal((n, n)).astype(np.float32)
        spd = g @ g.T + n * np.eye(n, dtype=np.float32)
        fut = srv.submit("posv", spd, np.ones(n, np.float32))
        fut.result(timeout=300)
        disp = [e for e in blackbox.events()
                if e["kind"] == "serve.dispatch"]
        assert disp, blackbox.events()
        assert fut.trace_id in (disp[-1].get("trace_ids") or [])
        assert disp[-1]["op"] == "posv"
    finally:
        srv.close()
        # the served request buffered telemetry spans: drain them so a
        # later finish_perfetto test exports only its own events
        telemetry.drain_spans()
        metrics.drain_samples()
        if not was_tele:
            telemetry.off()
        if not was_metrics:
            metrics.off()


# ---------------------------------------------------------------------------
# Measured distributed timeline (SLATE_TPU_DIST_TIMELINE)
# ---------------------------------------------------------------------------

class TestDistTimeline:
    def _spd(self, n):
        rng = np.random.default_rng(1)
        g = rng.standard_normal((n, n)).astype(np.float32)
        return g @ g.T + n * np.eye(n, dtype=np.float32)

    def test_ppotrf_timeline_bitwise_and_measured(self, mesh8,
                                                  monkeypatch,
                                                  recorder):
        from slate_tpu.parallel import dist_util, distribute, ppotrf

        p, q = 2, 4
        n, nb = 32, 4
        a = self._spd(n)

        def dist():
            return distribute(a, mesh8, nb, diag_pad=1.0, row_mult=q,
                              col_mult=p)

        mono = np.asarray(ppotrf(dist()).data)
        monkeypatch.setenv("SLATE_TPU_DIST_TIMELINE", "1")
        try:
            timed = np.asarray(ppotrf(dist()).data)
            # the chunked step windows run the SAME staged bodies: the
            # measured timeline never changes the numbers
            assert np.array_equal(mono, timed)
            steps = dist_util.timeline_steps()
            assert steps and steps[0]["driver"] == "ppotrf"
            # default window = 1: one measured row per step, windows
            # contiguous over [0, nt)
            assert steps[0]["k0"] == 0
            assert all(a["k1"] == b["k0"]
                       for a, b in zip(steps, steps[1:]))
            assert steps[-1]["k1"] == 8          # nt = 32 / nb=4
            assert all(s["wall_s"] > 0 for s in steps)
            # the per-step events entered the flight-recorder ring
            kinds = [e["kind"] for e in blackbox.events()]
            assert kinds.count("dist.step") >= len(steps)
        finally:
            dist_util.clear_timeline()

    def test_pgetrf_timeline_matches_monolithic(self, mesh8,
                                                monkeypatch):
        from slate_tpu.parallel import dist_util, pgesv, undistribute

        n, nb = 32, 4
        rng = np.random.default_rng(2)
        a = rng.standard_normal((n, n)).astype(np.float32) \
            + n * np.eye(n, dtype=np.float32)
        b = rng.standard_normal((n, 4)).astype(np.float32)
        _, _, x0 = pgesv(a, b, mesh8, nb)
        x0 = np.asarray(undistribute(x0))
        monkeypatch.setenv("SLATE_TPU_DIST_TIMELINE", "1")
        try:
            _, _, x1 = pgesv(a, b, mesh8, nb)
            x1 = np.asarray(undistribute(x1))
            assert np.array_equal(x0, x1)
            steps = dist_util.timeline_steps()
            assert steps and steps[0]["driver"] == "pgetrf"
        finally:
            dist_util.clear_timeline()


# ---------------------------------------------------------------------------
# The sentinel NOTE rows and the stdlib CLIs (jax-poisoned)
# ---------------------------------------------------------------------------

def test_regress_renders_bundle_note_rows(tmp_path):
    from slate_tpu.perf import regress

    agg = {"metric": "factor_suite_fp32_geomean", "value": 1.0,
           "unit": "GFLOP/s", "vs_baseline": 0.0,
           "submetrics": {"gemm_fp32_n1024": 10.0},
           "blackbox_bundles": [
               {"routine": "potrf", "path": "/tmp/bb.json",
                "digest": "abcd1234"}]}
    p = tmp_path / "BENCH_bb.json"
    p.write_text(json.dumps(agg))
    art = regress.load_artifact(str(p))
    assert any("blackbox bundle [potrf]" in note
               and "abcd1234" in note for note in art.notes)
    table = regress.format_table(regress.diff([art]))
    assert "NOTE BENCH_bb.json: blackbox bundle [potrf]" in table


def _write_bundle(path, events, reason="device_loss"):
    blob = {"schema": "slate_tpu.blackbox/1", "created": 100.0,
            "trigger": {"reason": reason, "detail": "", "t": 100.0},
            "host": {"python": "3", "platform": "linux", "pid": 1},
            "knobs": {}, "config": {}, "autotune": {"decisions": 0},
            "fault_plan": None, "metrics": {}, "events": events}
    with open(path, "w") as f:
        json.dump(blob, f)
    return str(path)


class TestCli:
    def test_render_and_strict_clean(self, tmp_path):
        p = _write_bundle(tmp_path / "b.json", [
            {"t": 99.0, "kind": "inject.fired", "site": "step.boundary",
             "fault": "device_loss"},
            {"t": 99.5, "kind": "ckpt.restored", "label": "pgetrf",
             "resume_step": 2},
            {"t": 100.0, "kind": "trigger", "reason": "device_loss"}])
        r = subprocess.run([sys.executable, _CLI, p, "--strict"],
                           capture_output=True, text=True,
                           env=_poison_env(tmp_path), timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "trigger: device_loss" in r.stdout
        assert "ckpt.restored" in r.stdout
        assert "trigger chain" in r.stdout

    def test_strict_flags_unrecovered(self, tmp_path):
        p = _write_bundle(tmp_path / "b.json", [
            {"t": 99.0, "kind": "abft.unrecovered", "driver": "getrf"}])
        r = subprocess.run([sys.executable, _CLI, p, "--strict"],
                           capture_output=True, text=True,
                           env=_poison_env(tmp_path), timeout=300)
        assert r.returncode == 1
        assert "unrecovered" in r.stdout

    def test_strict_flags_malformed(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text("{not json")
        r = subprocess.run([sys.executable, _CLI, str(p), "--strict"],
                           capture_output=True, text=True,
                           env=_poison_env(tmp_path), timeout=300)
        assert r.returncode == 1

    def test_json_output(self, tmp_path):
        p = _write_bundle(tmp_path / "b.json", [
            {"t": 99.5, "kind": "health.fail", "driver": "potrf"}])
        r = subprocess.run([sys.executable, _CLI, p, "--json"],
                           capture_output=True, text=True,
                           env=_poison_env(tmp_path), timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        blob = json.loads(r.stdout)
        assert blob["trigger"]["reason"] == "device_loss"
        assert blob["counts"] == {"health.fail": 1}
        assert blob["chain"][0]["kind"] == "health.fail"

    def test_telemetry_report_blackbox_join(self, tmp_path):
        log = tmp_path / "serve.jsonl"
        recs = [
            {"t": 100.0, "kind": "request", "op": "posv",
             "bucket": "fp32.n64", "latency_ms": 3.0, "error": False,
             "slo_violation": False, "batch": 4},
            {"t": 102.0, "kind": "sentinel", "event": {
                "t": 102.0, "classification": "degradation",
                "kind": "latency", "op": "posv",
                "bucket": "fp32.n64", "rise_pct": 80.0}},
        ]
        log.write_text("".join(json.dumps(r) + "\n" for r in recs))
        p = _write_bundle(tmp_path / "b.json", [
            {"t": 101.5, "kind": "serve.dispatch", "op": "posv",
             "batch": 4, "trace_ids": [7]},
            {"t": 102.2, "kind": "breaker.trip", "name": "posv/64"},
            {"t": 300.0, "kind": "bench.routine", "name": "far-away"}],
            reason="breaker.trip")
        r = subprocess.run(
            [sys.executable, _TELE_CLI, str(log), "--blackbox", p],
            capture_output=True, text=True, env=_poison_env(tmp_path),
            timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "blackbox correlation" in r.stdout
        assert "serve.dispatch" in r.stdout
        assert "breaker.trip" in r.stdout
        assert "far-away" not in r.stdout          # outside the window
        rj = subprocess.run(
            [sys.executable, _TELE_CLI, str(log), "--blackbox", p,
             "--json"],
            capture_output=True, text=True, env=_poison_env(tmp_path),
            timeout=300)
        blob = json.loads(rj.stdout)
        corr = blob["blackbox"]["correlated"]
        assert len(corr) == 1
        kinds = {e["kind"] for e in corr[0]["nearby"]}
        assert kinds == {"serve.dispatch", "breaker.trip"}
