"""Every multi-backend op site must dispatch through the autotune table
(pattern of test_driver_wrapping.py: the kernel registry is easy to
bypass by accident; this test catches a new call site that imports
``pallas_kernels``/``ozaki``/``split_gemm`` directly instead of going through
``slate_tpu.perf.autotune`` / ``method.select_backend``)."""

import pathlib
import re

import jax.numpy as jnp
import numpy as np

import slate_tpu as st

_PKG = pathlib.Path(st.__file__).resolve().parent

#: modules allowed to name the kernel modules in import statements:
#: the op layer itself (the kernels live there and ops/blocks.py IS the
#: dispatch call site), the autotune table (it times the kernels and
#: serves them to registered backends via ``autotune.kernel``), and the
#: offline sweep engine (the measurement layer's batch mode: it times
#: the same candidates the table would, just offline — lazily, inside
#: its jax-side builders only).
_ALLOWED = {"ops", "perf/autotune.py", "perf/sweep.py"}

_IMPORT_RE = re.compile(
    r"^\s*(?:from\s+[\w.]*\s+import\s+.*\b(pallas_kernels|ozaki|split_gemm)\b"
    r"|from\s+[\w.]*(pallas_kernels|ozaki|split_gemm)\s+import"
    r"|import\s+[\w.]*(pallas_kernels|ozaki|split_gemm)\b)")


def _is_allowed(rel: str) -> bool:
    return rel.startswith("ops/") or rel in _ALLOWED


def test_no_kernel_imports_outside_dispatch_layer():
    offenders = []
    for path in sorted(_PKG.rglob("*.py")):
        rel = str(path.relative_to(_PKG)).replace("\\", "/")
        if _is_allowed(rel):
            continue
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if _IMPORT_RE.match(line):
                offenders.append(f"slate_tpu/{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "kernel modules imported outside the autotune dispatch layer "
        "(route the site through perf.autotune / method.select_backend, "
        "or fetch the leaf via autotune.kernel()):\n" + "\n".join(offenders))


#: private-surface access patterns for the metrics registry: importing
#: or touching ``_registry`` (the singleton), or any ``metrics._x``
#: attribute — non-perf modules must go through the public facade
#: functions of ``slate_tpu.perf.metrics`` only, so the instrumentation
#: seams stay enumerable (and swappable) behind one API.
_METRICS_PRIVATE_RE = re.compile(
    r"(\b_registry\b"
    r"|from\s+[\w.]*\bmetrics\b\s+import\s+[^#\n]*\b_\w+"
    r"|\bmetrics\._\w+)")


def test_no_private_metrics_registry_access_outside_perf():
    offenders = []
    for path in sorted(_PKG.rglob("*.py")):
        rel = str(path.relative_to(_PKG)).replace("\\", "/")
        if rel.startswith("perf/"):
            continue                    # the registry lives there
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if _METRICS_PRIVATE_RE.search(line):
                offenders.append(f"slate_tpu/{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "metrics registry reached outside the public perf.metrics facade "
        "(use metrics.inc/snapshot/instrument_driver/... instead):\n"
        + "\n".join(offenders))


#: the serving layer must stay backend-agnostic: it may call ONLY the
#: driver facades (linalg/, api/), never the ops/ kernel layer — a
#: serve/ module importing ops would bypass the autotune dispatch
#: (``autotune.kernel()``) that makes every backend choice visible.
_SERVE_OPS_IMPORT_RE = re.compile(
    r"^\s*(?:from\s+[.\w]*\bops\b[.\w]*\s+import"    # from ..ops.x import
    r"|from\s+[.\w]+\s+import\s+[^#\n]*\bops\b"      # from .. import ops
    r"|import\s+[.\w]*\bops\b)")                     # import slate_tpu.ops


def test_serve_never_imports_ops_layer():
    offenders = []
    for path in sorted((_PKG / "serve").rglob("*.py")):
        rel = str(path.relative_to(_PKG)).replace("\\", "/")
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if _SERVE_OPS_IMPORT_RE.match(line):
                offenders.append(f"slate_tpu/{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "serve/ reached into the ops/ backend layer (route through the "
        "batched driver facades so every backend choice goes through "
        "the autotune table):\n" + "\n".join(offenders))


def test_telemetry_and_serve_use_public_metrics_api_only():
    """ISSUE 10 guard: the telemetry module lives in perf/ (so the
    general private-access scan above exempts it) but it is a CONSUMER
    of the registry like serve/, not part of it — both must reach
    metrics only through the public facade."""
    offenders = []
    paths = [_PKG / "perf" / "telemetry.py"] \
        + sorted((_PKG / "serve").rglob("*.py"))
    for path in paths:
        rel = str(path.relative_to(_PKG)).replace("\\", "/")
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if _METRICS_PRIVATE_RE.search(line):
                offenders.append(f"slate_tpu/{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "telemetry/serve reached the private metrics registry surface "
        "(use metrics.inc/observe/hist_quantiles/... instead):\n"
        + "\n".join(offenders))


def test_telemetry_exporters_never_started_by_import():
    """ISSUE 10 guard: importing the telemetry/serve modules — even
    with every exporter env knob SET — must not bind a socket or spawn
    exporter/log threads.  Only the front door's constructor
    (telemetry.maybe_start) or an explicit start may.  Run in a
    subprocess so this process's own exporters can't contaminate."""
    import os
    import subprocess
    import sys
    import tempfile

    code = (
        "import threading\n"
        "import slate_tpu.perf.telemetry, slate_tpu.serve\n"
        "bad = [t.name for t in threading.enumerate()\n"
        "       if t.name.startswith('slate-telemetry')]\n"
        "assert not bad, bad\n"
        "from slate_tpu.perf import telemetry\n"
        "assert telemetry.exporter_port() is None\n"
        "print('OK')\n")
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SLATE_TPU_METRICS_PORT="0",
                   SLATE_TPU_TELEMETRY_LOG=os.path.join(td, "t.jsonl"),
                   SLATE_TPU_TELEMETRY="1")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=300)
    assert out.returncode == 0 and "OK" in out.stdout, \
        (out.stdout, out.stderr)


#: the dispatch layer must never depend on the OFFLINE layer's sweep
#: engine: ops/ (and the linalg drivers) importing sweep would put the
#: sweep's jax-side builders on the serving import path.  Only
#: perf/autotune.py (bundle consumption), perf/__init__.py (lazy
#: export) and serve/queue.py (the shared pow2 bucket helper) may name
#: it.
_SWEEP_IMPORT_RE = re.compile(
    r"^\s*(?:from\s+[.\w]*\bsweep\b\s+import"
    r"|from\s+[.\w]+\s+import\s+[^#\n]*\bsweep\b"
    r"|import\s+[.\w]*\bsweep\b)")

_SWEEP_ALLOWED = {"perf/autotune.py", "perf/__init__.py",
                  "serve/queue.py"}


def test_sweep_never_imported_outside_consumers():
    offenders = []
    for path in sorted(_PKG.rglob("*.py")):
        rel = str(path.relative_to(_PKG)).replace("\\", "/")
        if rel in _SWEEP_ALLOWED or rel == "perf/sweep.py":
            continue
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if _SWEEP_IMPORT_RE.match(line):
                offenders.append(f"slate_tpu/{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "perf/sweep.py imported outside its consumers (the offline "
        "sweep layer must stay off the dispatch import path — consume "
        "bundles through perf.autotune):\n" + "\n".join(offenders))


def test_bundle_loading_inert_at_import():
    """ISSUE 11 guard: with SLATE_TPU_AUTOTUNE_BUNDLE (and every
    exporter env knob) SET, importing the autotune/serve modules must
    not read the bundle, construct the decision table, start exporter
    threads, or run a probe — bundle consumption begins at the first
    table() use, never at import.  Subprocess, like the exporter
    guard above."""
    import os
    import subprocess
    import sys
    import tempfile

    code = (
        "import threading\n"
        "import slate_tpu.perf.autotune as at\n"
        "import slate_tpu.perf.sweep\n"
        "import slate_tpu.serve\n"
        "assert at._table is None, 'table constructed at import'\n"
        "assert at.timing_reps.__call__ is not None\n"
        "bad = [t.name for t in threading.enumerate()\n"
        "       if t.name.startswith(('slate-telemetry',\n"
        "                             'slate-serve'))]\n"
        "assert not bad, bad\n"
        "from slate_tpu.perf import telemetry\n"
        "assert telemetry.exporter_port() is None\n"
        "print('OK')\n")
    with tempfile.TemporaryDirectory() as td:
        # the bundle file is deliberately MALFORMED: if any import-time
        # code path tried to read it, the table would count it
        # unreadable — but nothing may even open it before table()
        bundle = os.path.join(td, "bundle.json")
        with open(bundle, "w") as f:
            f.write("{not json")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SLATE_TPU_AUTOTUNE_BUNDLE=bundle,
                   SLATE_TPU_METRICS_PORT="0",
                   SLATE_TPU_TELEMETRY_LOG=os.path.join(td, "t.jsonl"),
                   SLATE_TPU_TELEMETRY="1")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=300)
    assert out.returncode == 0 and "OK" in out.stdout, \
        (out.stdout, out.stderr)


def test_abft_and_checkpoint_inert_at_import():
    """ISSUE 14 guard: with the ABFT/checkpoint knobs SET, importing
    the package (and the driver modules that consult the layer) must
    not load ``resilience.abft`` / ``resilience.checkpoint`` or act on
    the knobs — the ladder engages at the first ELIGIBLE eager driver
    call, never at import.  Subprocess, like the exporter/bundle
    guards above."""
    import os
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import slate_tpu as st\n"
        "import slate_tpu.linalg.lu\n"
        "import slate_tpu.linalg.cholesky\n"
        "import slate_tpu.parallel.dist_lu\n"
        "assert 'slate_tpu.resilience.abft' not in sys.modules, \\\n"
        "    'abft loaded at import'\n"
        "assert 'slate_tpu.resilience.checkpoint' not in sys.modules, \\\n"
        "    'checkpoint loaded at import'\n"
        "from slate_tpu.resilience import abft, checkpoint\n"
        "assert abft.mode() == 'correct'\n"
        "assert checkpoint.every_steps() == 4\n"
        "print('OK')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SLATE_TPU_ABFT="correct",
               SLATE_TPU_CKPT_EVERY_STEPS="4")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0 and "OK" in out.stdout, \
        (out.stdout, out.stderr)


def test_tilepool_inert_at_import():
    """ISSUE 17 guard: with every out-of-core knob SET, importing the
    package (and the lu/cholesky drivers that consult the ``ooc``
    dispatch gate, and the gate module itself) must not load
    ``ops.tilepool`` — the pool loads at the first pool-routed driver
    call, never at import.  Subprocess, like the guards above."""
    import os
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import slate_tpu as st\n"
        "import slate_tpu.linalg.lu\n"
        "import slate_tpu.linalg.cholesky\n"
        "import slate_tpu.linalg.ooc\n"
        "assert 'slate_tpu.ops.tilepool' not in sys.modules, \\\n"
        "    'tilepool loaded at import'\n"
        "from slate_tpu.ops import tilepool\n"
        "assert tilepool.window_tiles() == 3\n"
        "assert tilepool.ooc_nb() == 32\n"
        "print('OK')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SLATE_TPU_OOC="1", SLATE_TPU_OOC_NB="32",
               SLATE_TPU_OOC_WINDOW_TILES="3",
               SLATE_TPU_OOC_PREFETCH_DEPTH="2")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0 and "OK" in out.stdout, \
        (out.stdout, out.stderr)


def test_ooc_knobs_documented():
    """The out-of-core knobs must be registered in the user-facing knob
    table (docs/usage.md) — an undocumented residency knob is an
    invisible one."""
    docs = (_PKG.parent / "docs" / "usage.md").read_text()
    for knob in ("SLATE_TPU_OOC", "SLATE_TPU_OOC_NB",
                 "SLATE_TPU_OOC_WINDOW_TILES",
                 "SLATE_TPU_OOC_PREFETCH_DEPTH",
                 "SLATE_TPU_OOC_HBM_MB", "SLATE_TPU_PCIE_GBS"):
        assert knob in docs, f"{knob} missing from docs/usage.md"


def test_qdwh_knobs_documented():
    """The QDWH spectral-tier knobs must be registered in the
    user-facing knob table (docs/usage.md) — an undocumented driver
    knob is an invisible one."""
    docs = (_PKG.parent / "docs" / "usage.md").read_text()
    for knob in ("SLATE_TPU_QDWH", "SLATE_TPU_QDWH_CROSSOVER",
                 "SLATE_TPU_QDWH_SWITCH_C"):
        assert knob in docs, f"{knob} missing from docs/usage.md"


def test_abft_knobs_documented():
    """The new knobs must be registered in the user-facing knob table
    (docs/usage.md ABFT section) — an undocumented resilience knob is
    an invisible one."""
    docs = (_PKG.parent / "docs" / "usage.md").read_text()
    for knob in ("SLATE_TPU_ABFT", "SLATE_TPU_ABFT_TOL",
                 "SLATE_TPU_CKPT_EVERY_STEPS"):
        assert knob in docs, f"{knob} missing from docs/usage.md"


#: private-surface access patterns for the flight recorder (ISSUE 15):
#: touching the ``_rec`` singleton, the ``_ring`` deque, or any
#: ``blackbox._x`` attribute outside perf/ — every seam must go
#: through the public facade (``blackbox.record``/``trigger``/...)
#: so the recorder stays swappable and its one-attribute-read no-op
#: contract stays enforceable in one place.
_BLACKBOX_PRIVATE_RE = re.compile(
    r"(\bblackbox\._\w+"
    r"|from\s+[\w.]*\bblackbox\b\s+import\s+[^#\n]*\b_\w+"
    r"|\b_ring\b|\b_rec\b)")


def test_no_private_blackbox_access_outside_perf():
    offenders = []
    for path in sorted(_PKG.rglob("*.py")):
        rel = str(path.relative_to(_PKG)).replace("\\", "/")
        if rel.startswith("perf/"):
            continue                    # the recorder lives there
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if _BLACKBOX_PRIVATE_RE.search(line):
                offenders.append(f"slate_tpu/{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "flight recorder reached outside the public perf.blackbox "
        "facade (use blackbox.record/trigger/events/... instead):\n"
        + "\n".join(offenders))


def test_blackbox_recorder_inert_at_import():
    """ISSUE 15 guard: with every recorder env knob SET, importing the
    package (and the serve/telemetry surfaces that record into it)
    must not write a bundle, install the excepthook, or record an
    event — the recorder starts at the first seam event or an explicit
    on(), never at import.  Subprocess, like the exporter guards."""
    import os
    import subprocess
    import sys
    import tempfile

    code = (
        "import sys\n"
        "import slate_tpu\n"
        "import slate_tpu.serve\n"
        "from slate_tpu.perf import blackbox\n"
        "import glob, os\n"
        "assert blackbox.enabled()\n"
        "assert blackbox.events() == [], 'events recorded at import'\n"
        "assert sys.excepthook is sys.__excepthook__, \\\n"
        "    'excepthook installed at import'\n"
        "assert not glob.glob(os.path.join(\n"
        "    os.environ['SLATE_TPU_BLACKBOX_DIR'], '*')), \\\n"
        "    'bundle written at import'\n"
        "print('OK')\n")
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SLATE_TPU_BLACKBOX="1",
                   SLATE_TPU_BLACKBOX_EXCEPTHOOK="1",
                   SLATE_TPU_BLACKBOX_DIR=td)
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=300)
    assert out.returncode == 0 and "OK" in out.stdout, \
        (out.stdout, out.stderr)


def test_blackbox_off_by_default_lowering_bit_identity():
    """ISSUE 15 pin: the recorder is host-side only — with every knob
    unset, enabling it must leave compiled programs bit-identical (the
    PR 4 contract every observability layer carries)."""
    import numpy as np

    from slate_tpu.perf import blackbox

    a = jnp.asarray(np.eye(32, dtype=np.float32) * 4
                    + np.ones((32, 32), np.float32))

    def lower():
        import jax

        return jax.jit(lambda x: st.getrf(x)[0]).lower(a).as_text()

    base = lower()
    blackbox.on()
    try:
        blackbox.record("unit", probe=1)
        assert lower() == base
    finally:
        blackbox.off()
        blackbox.reset()
    assert lower() == base


#: raw environment access in the distributed layer: every scale-out
#: knob (panel backend, pivot strategy, broadcast chunking, lookahead
#: depth) must resolve through ``method.select_backend`` / the autotune
#: table so the decision is recorded, forceable, quarantine-maskable
#: and part of the lru_cached build key — an ``os.environ`` read inside
#: parallel/ would be an invisible, unforceable knob.
_ENV_READ_RE = re.compile(r"\bos\.environ\b|\bos\.getenv\b|\bgetenv\(")


def test_no_raw_env_reads_in_parallel_layer():
    """ISSUE 13 guard: every dist_* collective/schedule decision
    resolves through autotune — no raw env reads in parallel/."""
    offenders = []
    for path in sorted((_PKG / "parallel").rglob("*.py")):
        rel = str(path.relative_to(_PKG)).replace("\\", "/")
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if _ENV_READ_RE.search(line):
                offenders.append(f"slate_tpu/{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "raw environment reads in the parallel/ layer (route the knob "
        "through perf.autotune / method.select_backend so it is "
        "recorded, forceable and part of the shard_map build key):\n"
        + "\n".join(offenders))


def test_multi_backend_sites_populate_autotune_table():
    """Exercising each tunable op site must leave a decision entry —
    proof the site consults the table rather than hard-coding a
    backend.  On CPU every decision resolves heuristically (zero timing
    reps), so this is cheap enough for the fast tier."""
    from slate_tpu.perf import autotune
    from slate_tpu.ops import blocks
    from slate_tpu.enums import Diag, Uplo

    autotune.reset_table()
    rng = np.random.default_rng(0)

    # tile/trailing-update matmul (f32, tile-grid aligned)
    a32 = jnp.asarray(rng.standard_normal((128, 128)).astype(np.float32))
    blocks.matmul(a32, a32)
    # fp64 matmul (Ozaki vs emulated dot site)
    a64 = jnp.asarray(rng.standard_normal((8, 8)), jnp.float64)
    blocks.matmul(a64, a64)

    n = 64
    g = rng.standard_normal((n, n)).astype(np.float32)
    spd = g @ g.T + n * np.eye(n, dtype=np.float32)
    fac = st.potrf(st.HermitianMatrix(jnp.asarray(spd), uplo=st.Uplo.Lower))

    # trtri panel site (lower non-unit f32 power-of-two tile)
    st.trtri(st.TriangularMatrix(jnp.asarray(np.tril(g) + 2 * n * np.eye(
        n, dtype=np.float32)), uplo=Uplo.Lower, diag=Diag.NonUnit))

    # LU panel site
    st.getrf(jnp.asarray(g + n * np.eye(n, dtype=np.float32)))

    # LU step-composition site (consulted by the scattered driver; an
    # eligible shape so the decision records as "default", not
    # "ineligible")
    from slate_tpu.linalg.lu import getrf_scattered
    getrf_scattered(jnp.asarray(np.random.default_rng(1).standard_normal(
        (256, 256)).astype(np.float32)), 128)

    # distributed per-step panel site (resolved by ppotrf/pgetrf/pgeqrf
    # before their shard_map builders run), plus the ISSUE 13 scale-out
    # knobs: pivot strategy, broadcast chunking, lookahead-ring depth —
    # every dist_* collective/schedule decision goes through the table
    from slate_tpu.parallel.dist_util import (dist_chunk_slices,
                                              dist_lookahead_depth,
                                              dist_panel_backend,
                                              dist_pivot_backend)
    from slate_tpu.parallel.mesh import make_grid_mesh
    dist_panel_backend("potrf", 64, jnp.float32)
    dist_panel_backend("geqrf", 64, jnp.float32)
    dist_pivot_backend(64, 2, jnp.float32)
    dist_lookahead_depth("getrf", 16, 64, jnp.float32)
    dist_chunk_slices("getrf", 64, jnp.float32, make_grid_mesh(2, 4))

    # QR panel site
    st.geqrf(jnp.asarray(rng.standard_normal((2 * n, n)).astype(np.float32)))

    # stage-2 bulge-chase site (heev consults it before any stage-2
    # backend runs; on CPU it resolves heuristically to host_native) —
    # and the whole-driver eig_driver site (ISSUE 18: twostage vs
    # QDWH-eig) resolved before the chain is entered
    herm = ((g + g.T) / 2).astype(np.float64)
    st.heev(st.HermitianMatrix(jnp.asarray(herm), uplo=st.Uplo.Lower),
            opts={"block_size": 16})

    # whole-driver svd_driver site (ISSUE 18: twostage vs QDWH-SVD)
    st.svd(jnp.asarray(rng.standard_normal((n, n)).astype(np.float32)),
           jobu=False, jobvt=False, opts={"block_size": 16})

    # batched many-problem sites (ISSUE 8): the leading-batch-dim
    # drivers must each leave a grid-vs-vmapped (or vmapped-only)
    # decision keyed by the pow2-bucketed (B, n)
    from slate_tpu.linalg import batched
    spd_b = jnp.asarray(np.stack([spd] * 3))
    batched.potrf_batched(spd_b)
    batched.getrf_batched(jnp.asarray(
        np.stack([g + n * np.eye(n, dtype=np.float32)] * 3)))
    batched.geqrf_batched(jnp.asarray(
        rng.standard_normal((3, 2 * n, n)).astype(np.float32)))

    dec = autotune.decisions()
    for op in ("matmul|128,128,128,float32",
               "matmul|8,8,8,float64",
               "potrf_panel|", "trtri_panel|", "lu_panel|", "lu_driver|",
               "lu_step|", "potrf_step|", "dist_panel|potrf",
               "dist_panel|geqrf", "dist_pivot|", "dist_chunk|",
               "dist_lookahead|",
               "geqrf_panel|", "chase|hb2st", "ooc|",
               "eig_driver|", "svd_driver|",
               "batched_potrf|", "batched_lu|", "batched_qr|"):
        assert any(k.startswith(op) for k in dec), \
            f"no autotune decision recorded for op site {op!r}: {sorted(dec)}"
    autotune.reset_table()


def test_xprof_inert_at_import():
    """ISSUE 19 guard: with SLATE_TPU_XPROF SET, importing the package
    (and perf.xprof itself) must not start a trace, write into the
    capture dir, install the annotation hook, or touch jax.profiler —
    capture begins at the first ``xprof.capture(...)`` enter, never at
    import.  Subprocess, like the exporter guards."""
    import os
    import subprocess
    import sys
    import tempfile

    code = (
        "import os\n"
        "import slate_tpu\n"
        "from slate_tpu import trace\n"
        "from slate_tpu.perf import metrics, xprof\n"
        "assert xprof.enabled()\n"
        "assert xprof.last_profile() is None, 'profile at import'\n"
        "assert not os.path.exists(os.environ['SLATE_TPU_XPROF']), \\\n"
        "    'capture dir written at import'\n"
        "assert not trace._annotations_forced, \\\n"
        "    'annotations forced at import'\n"
        "assert metrics._annotation_hook[0] is None, \\\n"
        "    'annotation hook installed at import'\n"
        "print('OK')\n")
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SLATE_TPU_XPROF=os.path.join(td, "cap"))
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True, timeout=300)
    assert out.returncode == 0 and "OK" in out.stdout, \
        (out.stdout, out.stderr)


def test_xprof_off_by_default_lowering_bit_identity(tmp_path,
                                                    monkeypatch):
    """ISSUE 19 pin: the profiling layer is host-side only — programs
    lowered INSIDE an active capture (env set, trace running,
    annotation hook installed) are bit-identical to the knob-unset
    lowering (the PR 4 contract every observability layer carries)."""
    import numpy as np

    from slate_tpu.perf import xprof

    a = jnp.asarray(np.eye(32, dtype=np.float32) * 4
                    + np.ones((32, 32), np.float32))

    def lower():
        import jax

        return jax.jit(lambda x: st.getrf(x)[0]).lower(a).as_text()

    monkeypatch.delenv(xprof.ENV_DIR, raising=False)
    base = lower()
    monkeypatch.setenv(xprof.ENV_DIR, str(tmp_path / "cap"))
    xprof.clear()
    with xprof.capture("lowering-pin"):
        assert lower() == base
    assert lower() == base


def test_xprof_knob_documented():
    """The device-truth profiling knob must be registered in the
    user-facing knob table (docs/usage.md) — an undocumented capture
    knob is an invisible one."""
    docs = (_PKG.parent / "docs" / "usage.md").read_text()
    assert "SLATE_TPU_XPROF" in docs, \
        "SLATE_TPU_XPROF missing from docs/usage.md"
    assert "Device-truth profiling" in docs


# ---------------------------------------------------------------------------
# ISSUE 20: the fleet router (serve/fleet.py)
# ---------------------------------------------------------------------------

#: every intra-package module fleet.py may import: the public serve /
#: perf / resilience facades plus the parallel package facade (the
#: sharded lane's p* drivers).  Reaching past these — linalg drivers,
#: ops kernels, private registry modules — would bypass the autotune
#: table and the health ladder.
_FLEET_ALLOWED_IMPORTS = {
    "exceptions", "parallel", "perf.attr", "perf.autotune",
    "perf.blackbox", "perf.metrics", "perf.telemetry",
    "resilience.health", "serve.queue",
}

_FLEET_FROM_RE = re.compile(
    r"^\s*from\s+(\.+|slate_tpu\.?)([\w.]*)\s+import\s+(.+)")
_FLEET_IMPORT_RE = re.compile(r"^\s*import\s+slate_tpu([\w.]*)")


def test_fleet_imports_public_facades_only():
    """ISSUE 20 guard: serve/fleet.py composes EXISTING subsystems —
    it may touch only the public serve/perf/resilience/parallel
    facades, never the linalg/ops layers underneath them."""
    offenders = []
    path = _PKG / "serve" / "fleet.py"
    src = path.read_text().splitlines()
    for lineno, line in enumerate(src, 1):
        m = _FLEET_IMPORT_RE.match(line)
        if m:
            name = m.group(1).lstrip(".")
            if name and name not in _FLEET_ALLOWED_IMPORTS:
                offenders.append(f"fleet.py:{lineno}: {line.strip()}")
            continue
        m = _FLEET_FROM_RE.match(line)
        if not m:
            continue
        dots, base, names = m.groups()
        # one leading dot = the serve package; more (or slate_tpu) =
        # the package root
        prefix = "serve." if dots == "." else ""
        base = (prefix + base).strip(".")
        if base in _FLEET_ALLOWED_IMPORTS:
            continue                   # e.g. from .queue import ...
        # from <pkg> import <submodule>: each imported name must land
        # on an allowlisted module (handles multi-line paren imports
        # only for the single-name case fleet.py uses)
        for name in names.split(","):
            name = name.split(" as ")[0].strip(" ()\\")
            if not name:
                continue
            full = (base + "." + name).strip(".")
            if full not in _FLEET_ALLOWED_IMPORTS:
                offenders.append(f"fleet.py:{lineno}: {line.strip()}")
                break
    assert not offenders, (
        "serve/fleet.py imported outside its facade allowlist "
        f"({sorted(_FLEET_ALLOWED_IMPORTS)}):\n" + "\n".join(offenders))


def test_fleet_inert_at_import_and_construction():
    """ISSUE 20 guard: with every fleet knob SET, importing the serve
    package — and even CONSTRUCTING a Router — must spawn no threads
    and start no exporters.  Each replica's dispatcher starts on its
    first submit; the sharded lane's worker on its first sharded
    request.  Subprocess so this process's own threads can't
    contaminate."""
    import os
    import subprocess
    import sys

    code = (
        "import threading\n"
        "before = {t.name for t in threading.enumerate()}\n"
        "from slate_tpu.serve import FleetConfig, Router\n"
        "fleet = Router(FleetConfig(replicas=2))\n"
        "after = {t.name for t in threading.enumerate()}\n"
        "assert after == before, after - before\n"
        "assert fleet.replica_states() == ['closed', 'closed']\n"
        "fleet.close()\n"
        "print('OK')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SLATE_TPU_FLEET_REPLICAS="2",
               SLATE_TPU_FLEET_SHARD_MS="10",
               SLATE_TPU_FLEET_PREEMPT_DEPTH="4",
               SLATE_TPU_FLEET_COOLDOWN_S="0.1")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         cwd=str(_PKG.parent), capture_output=True,
                         text=True, timeout=300)
    assert out.returncode == 0 and "OK" in out.stdout, \
        (out.stdout, out.stderr)


def test_fleet_knobs_documented():
    """The fleet-serving knobs must be registered in the user-facing
    knob table (docs/usage.md) — an undocumented routing knob is an
    invisible one."""
    docs = (_PKG.parent / "docs" / "usage.md").read_text()
    for knob in ("SLATE_TPU_FLEET_REPLICAS", "SLATE_TPU_FLEET_SHARD_MS",
                 "SLATE_TPU_FLEET_PREEMPT_DEPTH",
                 "SLATE_TPU_FLEET_COOLDOWN_S"):
        assert knob in docs, f"{knob} missing from docs/usage.md"
    assert "Fleet serving" in docs
