"""Norms + utility drivers + method selection.

Mirrors the reference's norm testers (``test/test_gbnorm.cc`` etc.:
compare against LAPACK ``lange``-style references) with numpy as the
reference implementation.
"""

import numpy as np
import jax.numpy as jnp
import pytest

import slate_tpu as st
from slate_tpu import linalg
from slate_tpu.enums import Diag, Norm, Uplo
from slate_tpu import method
from slate_tpu.enums import (MethodCholQR, MethodEig, MethodGels, MethodGemm,
                             MethodLU, MethodTrsm)


def _ref_norm(norm, a):
    a = np.abs(np.asarray(a))
    if norm is Norm.Max:
        return a.max()
    if norm is Norm.One:
        return a.sum(axis=0).max()
    if norm is Norm.Inf:
        return a.sum(axis=1).max()
    return np.sqrt((a ** 2).sum())


NORMS = [Norm.Max, Norm.One, Norm.Inf, Norm.Fro]


@pytest.mark.parametrize("norm", NORMS)
def test_genorm(norm):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((53, 41))
    m = st.Matrix.from_array(a, mb=16, nb=16)
    got = float(linalg.norm(norm, m))
    assert np.isclose(got, _ref_norm(norm, a), rtol=1e-6)


@pytest.mark.parametrize("norm", NORMS)
@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
def test_synorm_mirrors(norm, uplo):
    rng = np.random.default_rng(1)
    a = rng.standard_normal((37, 37))
    sym = a + a.T
    stored = np.tril(sym) if uplo is Uplo.Lower else np.triu(sym)
    m = st.SymmetricMatrix(jnp.asarray(stored), uplo=uplo, mb=8, nb=8)
    got = float(linalg.norm(norm, m))
    assert np.isclose(got, _ref_norm(norm, sym), rtol=1e-6)


@pytest.mark.parametrize("norm", NORMS)
def test_trnorm_unit_diag(norm):
    rng = np.random.default_rng(2)
    a = np.tril(rng.standard_normal((29, 29)))
    ref = a.copy()
    np.fill_diagonal(ref, 1.0)
    m = st.TriangularMatrix(jnp.asarray(a), uplo=Uplo.Lower, diag=Diag.Unit)
    got = float(linalg.norm(norm, m))
    assert np.isclose(got, _ref_norm(norm, ref), rtol=1e-6)


@pytest.mark.parametrize("norm", NORMS)
def test_gbnorm_masks_band(norm):
    rng = np.random.default_rng(3)
    a = rng.standard_normal((31, 31))
    kl, ku = 3, 5
    i, j = np.indices(a.shape)
    banded = np.where((j - i <= ku) & (i - j <= kl), a, 0.0)
    m = st.BandMatrix(jnp.asarray(a), kl=kl, ku=ku)
    got = float(linalg.norm(norm, m))
    assert np.isclose(got, _ref_norm(norm, banded), rtol=1e-6)


def test_col_norms():
    rng = np.random.default_rng(4)
    a = rng.standard_normal((20, 7))
    got = np.asarray(linalg.col_norms(Norm.Max, st.Matrix.from_array(a)))
    np.testing.assert_allclose(got, np.abs(a).max(axis=0), rtol=1e-6)


def test_fro_norm_no_overflow():
    a = np.full((4, 4), 1e30)
    got = float(linalg.norm(Norm.Fro, st.Matrix.from_array(jnp.asarray(a))))
    assert np.isclose(got, np.sqrt(16) * 1e30, rtol=1e-6)


def test_add_scale_set_copy():
    rng = np.random.default_rng(5)
    a = rng.standard_normal((12, 9))
    b = rng.standard_normal((12, 9))
    out = linalg.add(2.0, st.Matrix.from_array(a), 0.5, st.Matrix.from_array(b))
    np.testing.assert_allclose(np.asarray(out.array), 2 * a + 0.5 * b, rtol=1e-6)

    s = linalg.scale(3.0, 2.0, st.Matrix.from_array(a))
    np.testing.assert_allclose(np.asarray(s.array), 1.5 * a, rtol=1e-6)

    r, c = rng.standard_normal(12), rng.standard_normal(9)
    sc = linalg.scale_row_col(r, c, st.Matrix.from_array(a))
    np.testing.assert_allclose(np.asarray(sc.array), a * r[:, None] * c[None, :],
                               rtol=1e-6)

    z = linalg.set(0.0, 1.0, st.Matrix.from_array(a))
    np.testing.assert_allclose(np.asarray(z.array), np.eye(12, 9))

    cv = linalg.copy(st.Matrix.from_array(a), dtype=jnp.float32)
    assert cv.dtype == jnp.float32


def test_tzadd_preserves_other_triangle():
    rng = np.random.default_rng(6)
    a = rng.standard_normal((8, 8))
    b = rng.standard_normal((8, 8))
    bt = st.TrapezoidMatrix(jnp.asarray(b), uplo=Uplo.Lower)
    out = linalg.add(1.0, st.Matrix.from_array(a), 1.0, bt)
    got = np.asarray(out.array)
    np.testing.assert_allclose(np.tril(got), np.tril(a + b), rtol=1e-6)
    np.testing.assert_allclose(np.triu(got, 1), np.triu(b, 1), rtol=1e-6)


def test_method_selection():
    assert method.select_gemm(MethodGemm.Auto, 1) is MethodGemm.GemmA
    assert method.select_gemm(MethodGemm.Auto, 8) is MethodGemm.GemmC
    assert method.select_gemm(MethodGemm.GemmA, 8) is MethodGemm.GemmA
    assert method.select_trsm(MethodTrsm.Auto, 1) is MethodTrsm.TrsmA
    assert method.select_gels(MethodGels.Auto, 9000, 100) is MethodGels.CholQR
    assert method.select_gels(MethodGels.Auto, 100, 90) is MethodGels.QR
    assert method.select_lu(MethodLU.Auto) is MethodLU.PartialPiv
    assert method.select_lu(MethodLU.Auto, distributed=True) is MethodLU.CALU
    assert method.select_eig(MethodEig.Auto, 100, True) is MethodEig.DC
    assert method.select_cholqr(MethodCholQR.Auto, 4000, 100) is MethodCholQR.HerkC


class TestDebugInvariants:
    """slate_tpu.debug — the reference's Debug.cc invariant checks."""

    def test_check_finite_passes(self):
        from slate_tpu import debug
        debug.check_finite(jnp.ones((64, 64)), nb=32)

    def test_check_finite_locates_tile(self):
        from slate_tpu import debug
        from slate_tpu.exceptions import SlateError
        a = np.ones((64, 64))
        a[40, 10] = np.nan
        with pytest.raises(SlateError) as ei:
            debug.check_finite(jnp.asarray(a), nb=32, name="X")
        assert "(1, 0)" in str(ei.value)

    def test_check_pool_leaks(self):
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        from slate_tpu import debug
        from slate_tpu.exceptions import SlateError
        pool = native.MemoryPool(4096)
        b = pool.alloc()
        with pytest.raises(SlateError):
            debug.check_pool_leaks(pool)
        pool.free(b)
        debug.check_pool_leaks(pool)
        pool.close()

    def test_check_dist_layout(self):
        import jax
        from slate_tpu import debug
        from slate_tpu.parallel import distribute, make_grid_mesh
        mesh = make_grid_mesh(2, 4)
        dm = distribute(np.ones((60, 60)), mesh, nb=16)
        debug.check_dist_layout(dm)


def test_tzcopy():
    from slate_tpu.ops.tile_ops import tzcopy
    import slate_tpu as st
    a = jnp.arange(16.0).reshape(4, 4)
    b = -jnp.ones((4, 4))
    out = np.asarray(tzcopy(st.Uplo.Lower, a, b))
    ref = np.where(np.tril(np.ones((4, 4))) > 0, np.arange(16.0).reshape(4, 4),
                   -1.0)
    np.testing.assert_allclose(out, ref)
    # precision-converting variant (reference gecopy/tzcopy s<->d)
    out32 = tzcopy(st.Uplo.Upper, a.astype(jnp.float64), b, dtype=jnp.float32)
    assert out32.dtype == jnp.float32
