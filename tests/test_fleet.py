"""Fleet serving (slate_tpu/serve/fleet.py, ISSUE 20): the cost-model
Router over per-device BatchQueue replicas — placement + residual-gated
answers, the autotuned replica/sharded route site, priority preemption
through the PR 9 backpressure machinery, the device-loss drain →
reverify → rejoin ladder with its exactly-one-bundle contract, and the
bundle-grade cold start (zero reps / zero compiles on every replica).

Heavy ladder/throughput tests are ``@pytest.mark.slow`` — the fast
tier keeps one representative of each surface; ``run_tests.py --fleet``
runs the full sweep.
"""

import concurrent.futures
import glob
import importlib
import json
import time

import numpy as np
import pytest

import jax

from slate_tpu import serve
from slate_tpu.exceptions import SlateError
from slate_tpu.perf import autotune, blackbox, metrics
from slate_tpu.resilience import inject
from slate_tpu.serve.fleet import FleetConfig, Router
from slate_tpu.serve.queue import (Backpressure, BatchQueue, Preempted,
                                   ServeConfig)


@pytest.fixture(autouse=True)
def _fresh(tmp_path, monkeypatch):
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    monkeypatch.delenv("SLATE_TPU_FLEET_REPLICAS", raising=False)
    monkeypatch.delenv("SLATE_TPU_AUTOTUNE_FORCE", raising=False)
    autotune.reset_table()
    was = metrics.enabled()
    metrics.on()
    metrics.reset()
    inject.clear_plan()
    yield
    inject.clear_plan()
    metrics.reset()
    if not was:
        metrics.off()
    autotune.reset_table()


def _spd(n, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((n, n)).astype(dtype)
    return g @ g.T + n * np.eye(n, dtype=dtype)


def _gen(n, seed=1, dtype=np.float32):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, n)).astype(dtype)
            + n * np.eye(n, dtype=dtype))


def _resid_ok(a, x, b, n):
    eps = float(np.finfo(np.float32).eps)
    return (np.linalg.norm(a @ x - b)
            / (np.linalg.norm(a) * np.linalg.norm(b) * eps * n)) < 3


class TestRouterPlacement:
    def test_mixed_ops_residual_gated_across_replicas(self):
        """Small problems data-parallel over 2 replicas: every answer
        residual-gated, every replica stays closed."""
        fleet = Router(FleetConfig(
            replicas=2, enable_sharded=False,
            serve=ServeConfig(max_batch=2, max_wait_s=0.005)))
        try:
            n = 24
            futs = []
            for i in range(6):
                spd = _spd(n, seed=i)
                rhs = np.ones(n, np.float32)
                futs.append((spd, rhs, fleet.submit("posv", spd, rhs)))
            g = _gen(n, seed=9)
            rhs2 = np.ones(n, np.float32)
            xg = fleet.submit("gesv", g, rhs2).result(timeout=60)
            assert _resid_ok(g, xg, rhs2, n)
            for spd, rhs, fut in futs:
                assert _resid_ok(spd, fut.result(timeout=60), rhs, n)
            assert fleet.replica_states() == ["closed", "closed"]
            c = metrics.snapshot()["counters"]
            assert c.get("fleet.routed.replica", 0) == 7
            assert c.get("fleet.routed.sharded", 0) == 0
        finally:
            fleet.close()

    def test_cost_model_spreads_backlog(self):
        """Shortest-predicted-completion placement: two equal-cost
        picks with nothing settled must land on DIFFERENT replicas."""
        fleet = Router(FleetConfig(
            replicas=2, enable_sharded=False))
        try:
            r1 = fleet._pick_replica(1.0)
            r2 = fleet._pick_replica(1.0)
            assert {r1.idx, r2.idx} == {0, 1}
            assert fleet.backlog_seconds() == [1.0, 1.0]
            fleet._settle(r1, 1.0)
            fleet._settle(r2, 1.0)
            assert fleet.backlog_seconds() == [0.0, 0.0]
        finally:
            fleet.close()

    def test_predict_positive_for_every_op(self):
        fleet = Router(FleetConfig(replicas=1, enable_sharded=False))
        try:
            n = 32
            rhs = np.ones(n, np.float32)
            tall = np.ones((48, 16), np.float32)
            cases = [("posv", (_spd(n), rhs)), ("gesv", (_gen(n), rhs)),
                     ("potrf", (_spd(n),)), ("getrf", (_gen(n),)),
                     ("geqrf", (tall,)),
                     ("gels", (tall, np.ones(48, np.float32))),
                     ("heev", (_spd(n),))]
            for op, operands in cases:
                assert fleet._predict(op, operands) > 0.0, op
        finally:
            fleet.close()

    def test_unknown_op_and_arity_rejected(self):
        fleet = Router(FleetConfig(replicas=1, enable_sharded=False))
        try:
            with pytest.raises(KeyError):
                fleet.submit("sv", np.eye(4, dtype=np.float32))
            with pytest.raises(TypeError):
                fleet.submit("posv", np.eye(4, dtype=np.float32))
        finally:
            fleet.close()


class TestRouteSite:
    """The autotuned ``route`` chooser: analytic crossover, force pin,
    ineligibility."""

    def test_small_goes_replica_large_goes_sharded(self, monkeypatch):
        # a sky-high crossover keeps even big problems data-parallel;
        # a near-zero one shards everything eligible
        monkeypatch.setenv("SLATE_TPU_FLEET_SHARD_MS", "60000")
        assert autotune.select("route", serve_op="posv", n=32, ndev=4,
                               dtype=np.float32) == "replica"
        monkeypatch.setenv("SLATE_TPU_FLEET_SHARD_MS", "0.0001")
        assert autotune.select("route", serve_op="posv", n=4096, ndev=4,
                               dtype=np.float32) == "sharded"

    def test_force_pin_wins(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "route=sharded")
        assert autotune.select("route", serve_op="gesv", n=16, ndev=2,
                               dtype=np.float32) == "sharded"

    def test_factor_ops_ineligible(self):
        # only posv/gesv/gels have a p* sharded lane
        assert autotune.select("route", serve_op="potrf", n=8192,
                               ndev=8, dtype=np.float32) == "replica"

    def test_single_device_router_never_shards(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "route=sharded")
        fleet = Router(FleetConfig(replicas=1, enable_sharded=True))
        try:
            assert fleet._route("posv", (_spd(16),)) == "replica"
        finally:
            fleet.close()


class TestShardedLane:
    # posv is the fast-tier representative; gesv/gels ride the slow
    # sweep (run_tests.py --fleet) — same lane, ~2 s each on one core
    @pytest.mark.parametrize("op", [
        "posv",
        pytest.param("gesv", marks=pytest.mark.slow),
        pytest.param("gels", marks=pytest.mark.slow)])
    def test_forced_sharded_residual_gated(self, op, monkeypatch,
                                           mesh8):
        """SLATE_TPU_AUTOTUNE_FORCE=route=sharded: each eligible op
        runs ONE ICI-sharded p* solve on the process mesh and the
        undistributed answer residual-gates clean."""
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "route=sharded")
        fleet = Router(FleetConfig(replicas=2, shard_nb=16),
                       mesh=mesh8)
        try:
            n, k = 64, 3
            rng = np.random.default_rng(13)
            a = _spd(n, seed=13) if op == "posv" else _gen(n, seed=13)
            b = rng.standard_normal((n, k)).astype(np.float32)
            x = fleet.submit(op, a, b).result(timeout=300)
            assert x.shape == (n, k)
            ref = np.linalg.solve(a.astype(np.float64),
                                  b.astype(np.float64))
            assert np.allclose(x, ref, atol=1e-2), \
                np.abs(x - ref).max()
            c = metrics.snapshot()["counters"]
            assert c.get("fleet.routed.sharded", 0) == 1
            assert c.get("fleet.sharded.solves", 0) == 1
        finally:
            fleet.close()

    def test_sharded_1d_rhs_roundtrip(self, monkeypatch, mesh8):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "route=sharded")
        fleet = Router(FleetConfig(replicas=2, shard_nb=16),
                       mesh=mesh8)
        try:
            n = 64
            a = _spd(n, seed=3)
            b = np.ones(n, np.float32)
            x = fleet.submit("posv", a, b).result(timeout=300)
            assert x.shape == (n,)
            assert np.allclose(
                x, np.linalg.solve(a.astype(np.float64),
                                   b.astype(np.float64)), atol=1e-2)
        finally:
            fleet.close()


class TestPreemption:
    def test_high_priority_evicts_and_lands(self):
        """A full replica queue + a priority-1 submit: queued
        priority-0 work fails with the RETRYABLE Preempted signal and
        the high-priority request is served."""
        # max_wait far above the submit burst + max_batch high: the
        # queue fills to the backpressure bound before any dispatch
        fleet = Router(FleetConfig(
            replicas=1, enable_sharded=False, preempt_depth=4,
            serve=ServeConfig(max_batch=64, max_wait_s=0.5,
                              max_queue_depth=4)))
        try:
            n = 16
            spd = _spd(n)
            rhs = np.ones(n, np.float32)
            low = [fleet.submit("posv", spd, rhs, priority=0)
                   for _ in range(4)]
            with pytest.raises(Backpressure):
                fleet.submit("posv", spd, rhs, priority=0)
            hi = fleet.submit("posv", spd, rhs, priority=1)
            x = hi.result(timeout=60)
            assert _resid_ok(spd, x, rhs, n)
            preempted = [f for f in low
                         if isinstance(f.exception(timeout=60),
                                       Preempted)]
            assert preempted, "eviction must fail victims, not drop"
            for f in preempted:
                e = f.exception()
                assert getattr(e, "retryable", False), \
                    "Preempted must be a retryable signal"
            c = metrics.snapshot()["counters"]
            assert c.get("fleet.preempt.evicted", 0) >= 1
        finally:
            fleet.close()

    def test_preempted_is_transient_for_retry_ladder(self):
        from slate_tpu.resilience.retry import transient_infra
        assert transient_infra(Preempted("evicted"))
        assert not transient_infra(ValueError("boom"))

    def test_equal_priority_never_preempts(self):
        fleet = Router(FleetConfig(
            replicas=1, enable_sharded=False,
            serve=ServeConfig(max_batch=64, max_wait_s=0.5,
                              max_queue_depth=2)))
        try:
            n = 16
            spd = _spd(n)
            rhs = np.ones(n, np.float32)
            low = [fleet.submit("posv", spd, rhs, priority=1)
                   for _ in range(2)]
            # same priority class: nothing to evict, backpressure wins
            with pytest.raises(Backpressure):
                fleet.submit("posv", spd, rhs, priority=1)
            for f in low:
                assert f.exception(timeout=60) is None
        finally:
            fleet.close()


class TestElasticDegradation:
    @pytest.mark.slow
    def test_device_loss_drains_rejoins_one_bundle(self, tmp_path,
                                                   monkeypatch):
        """The acceptance ladder: an injected device_loss on replica 1
        mid-burst strands ZERO futures (drained work re-files on
        healthy replicas, chained into the original futures), the
        replica re-verifies and rejoins, and the flight recorder dumps
        EXACTLY ONE bundle naming the device_loss → drain → rejoin
        chain."""
        bdir = tmp_path / "bundles"
        monkeypatch.setenv(blackbox.ENV_DIR, str(bdir))
        blackbox.on()
        blackbox.reset()
        try:
            fleet = Router(FleetConfig(
                replicas=3, enable_sharded=False, cooldown_s=0.02,
                serve=ServeConfig(max_batch=2, max_wait_s=0.002)))
            n = 24
            spd = _spd(n)
            rhs = np.ones(n, np.float32)
            # two losses on replica 1's dispatch: the first trips the
            # fleet breaker, the second is absorbed by the queue's own
            # retry ladder while the replica is already draining
            inject.install(inject.FaultPlan(seed=7).add(
                "fleet.replica1", "device_loss", rate=1.0, count=2))
            futs = [fleet.submit("posv", spd, rhs) for _ in range(24)]
            for f in futs:
                assert _resid_ok(spd, f.result(timeout=120), rhs, n)
            inject.clear_plan()
            deadline = time.time() + 30.0
            while time.time() < deadline:
                if fleet.replica_states() == ["closed"] * 3:
                    break
                time.sleep(0.05)
            assert fleet.replica_states() == ["closed"] * 3, \
                fleet.replica_states()
            # post-recovery wave lands clean on the rejoined fleet
            for f in [fleet.submit("posv", spd, rhs)
                      for _ in range(6)]:
                assert _resid_ok(spd, f.result(timeout=60), rhs, n)
            fleet.close()
            bundles = sorted(glob.glob(
                str(bdir / "slate_tpu_blackbox_*.json")))
            assert len(bundles) == 1, bundles
            with open(bundles[0]) as f:
                blob = json.load(f)
            assert blob["trigger"]["reason"] == "fleet.recovered"
            kinds = [e.get("kind") for e in blob.get("events", [])]
            for rung in ("fleet.device_loss", "fleet.drain",
                         "fleet.rejoin"):
                assert rung in kinds, (rung, kinds)
            c = metrics.snapshot()["counters"]
            assert c.get("fleet.device_loss", 0) == 1
            assert c.get("fleet.rejoin", 0) == 1
        finally:
            blackbox.reset()
            blackbox.off()

    def test_all_replicas_lost_raises_retryable_posture(self):
        """No replica available: submit must fail loudly (SlateError),
        not hang or silently drop."""
        fleet = Router(FleetConfig(replicas=1, enable_sharded=False))
        try:
            fleet._replicas[0].state = "open"
            with pytest.raises(SlateError):
                fleet.submit("posv", _spd(16), np.ones(16, np.float32))
        finally:
            fleet.close()

    @pytest.mark.slow
    def test_fleet_overlaps_emulated_device_walls(self, monkeypatch):
        """4 replicas under an emulated 20 ms device wall
        (``serve.dispatch=slow`` — a GIL-released sleep standing in
        for the per-chip dispatch wall a 1-core CI host can't show)
        must finish an open-loop burst materially faster than one
        replica; the bench's ≥2× acceptance run is
        ``bench.py serve_fleet``."""
        monkeypatch.setenv("SLATE_TPU_FAULT_SLOW_S", "0.02")
        n = 16
        spd = _spd(n)
        rhs = np.ones(n, np.float32)
        cfg = ServeConfig(max_batch=2, max_wait_s=0.001)

        def run(replicas, nreq=16):
            fleet = Router(FleetConfig(
                replicas=replicas, enable_sharded=False, serve=cfg))
            try:
                fleet.warm_start(specs=[{"op": "posv", "batch": 2,
                                         "dims": (n,),
                                         "dtype": "float32"}])
                inject.install(inject.parse_plan(
                    "serve.dispatch=slow:1.0", seed=1))
                t0 = time.perf_counter()
                futs = [fleet.submit("posv", spd, rhs)
                        for _ in range(nreq)]
                for f in futs:
                    f.result(timeout=120)
                return time.perf_counter() - t0
            finally:
                inject.clear_plan()
                fleet.close()

        t_single = run(1)
        t_fleet = run(4)
        assert t_fleet < 0.8 * t_single, (t_fleet, t_single)


class TestColdStart:
    @pytest.mark.slow
    def test_fleet_warm_start_zero_reps_zero_compiles(self,
                                                      monkeypatch):
        """The fleet cold-start acceptance: after Router.warm_start
        from explicit bucket specs (the PR 11 bundle's shape), the
        FIRST bucketed request on EVERY replica runs zero timing reps,
        zero on-demand compiles, zero jit backend compiles."""
        n, bsz = 64, 4
        spd = _spd(n)
        b = np.ones(n, np.float32)
        mod = importlib.reload(importlib.import_module(
            "slate_tpu.perf.autotune"))
        try:
            fleet = Router(FleetConfig(
                replicas=2, enable_sharded=False,
                serve=ServeConfig(max_batch=bsz, max_wait_s=0.005)))
            compiled = fleet.warm_start(specs=[
                {"op": "posv", "batch": bsz, "dims": (n,),
                 "dtype": "float32"}])
            assert compiled >= 2, "every replica must be warmed"
            metrics.reset()
            # one request per replica: pin both lanes compile-free
            futs = [fleet.submit("posv", spd, b) for _ in range(2 * bsz)]
            for f in futs:
                assert _resid_ok(spd, f.result(timeout=60), b, n)
            counters = metrics.snapshot()["counters"]
            assert counters.get("serve.compile.on_demand", 0) == 0
            assert counters.get("jit.backend_compiles", 0) == 0
            assert mod.timing_reps() == 0
            fleet.close()
        finally:
            mod.reset_table()


class TestLifecycle:
    def test_flush_settles_backlog(self):
        fleet = Router(FleetConfig(
            replicas=2, enable_sharded=False,
            serve=ServeConfig(max_batch=4, max_wait_s=0.002)))
        try:
            n = 16
            spd = _spd(n)
            rhs = np.ones(n, np.float32)
            futs = [fleet.submit("posv", spd, rhs) for _ in range(8)]
            fleet.flush(timeout=60)
            assert all(f.done() for f in futs)
            assert fleet.backlog_seconds() == pytest.approx(
                [0.0, 0.0], abs=1e-9)
        finally:
            fleet.close()

    def test_closed_router_rejects(self):
        fleet = Router(FleetConfig(replicas=1, enable_sharded=False))
        fleet.close()
        with pytest.raises(RuntimeError):
            fleet.submit("posv", _spd(16), np.ones(16, np.float32))

    def test_replica_cap_env(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_FLEET_REPLICAS", "1")
        fleet = Router(FleetConfig(enable_sharded=False))
        try:
            assert len(fleet.replica_states()) == 1
        finally:
            fleet.close()
