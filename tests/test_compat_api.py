"""LAPACK/ScaLAPACK compat-API tests — the reference smoke-tests these
shims via ``lapack_api/example_dgetrf.c`` and the ``scalapack_api``
drop-in path; here: numerical round-trips through both shims."""

import numpy as np
import pytest

from slate_tpu import native
from slate_tpu.api import lapack as lp


def test_typed_names_exist():
    for l in "sdcz":
        for base in ("gesv", "getrf", "potrf", "geqrf", "gesvd", "lange"):
            assert hasattr(lp, l + base), l + base
    assert hasattr(lp, "dsyev") and hasattr(lp, "zheev")
    assert not hasattr(lp, "zsyev")


def test_dgesv_dgetrf():
    rng = np.random.default_rng(0)
    n = 24
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    lu, piv, x, info = lp.dgesv(a, b)
    assert info == 0
    assert np.abs(a @ x - b).max() < 1e-10
    lu2, piv2, info = lp.dgetrf(a)
    x2, info = lp.dgetrs(lu2, piv2, b)
    assert np.abs(a @ x2 - b).max() < 1e-10
    inv, info = lp.dgetri(lu2, piv2)
    assert np.abs(inv @ a - np.eye(n)).max() < 1e-10


def test_sposv_zpotrf():
    rng = np.random.default_rng(1)
    n = 16
    a = rng.standard_normal((n, n))
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    b = rng.standard_normal((n, 1)).astype(np.float32)
    f, x, info = lp.sposv(spd, b)
    assert info == 0 and np.abs(spd @ x - b).max() < 1e-3
    c = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    hpd = c @ c.conj().T + n * np.eye(n)
    f, info = lp.zpotrf(hpd)
    l = np.tril(f)
    assert np.abs(l @ l.conj().T - hpd).max() < 1e-10


def test_dsyev_dgesvd_dlange():
    rng = np.random.default_rng(2)
    n = 20
    a = rng.standard_normal((n, n))
    sym = (a + a.T) / 2
    w, z, info = lp.dsyev(sym)
    assert np.abs(np.sort(w) - np.linalg.eigvalsh(sym)).max() < 1e-10
    u, s, vh, info = lp.dgesvd(a)
    assert np.abs(s - np.linalg.svd(a, compute_uv=False)).max() < 1e-10
    assert abs(lp.dlange("F", a) - np.linalg.norm(a)) < 1e-10
    assert abs(lp.dlange("1", a) - np.linalg.norm(a, 1)) < 1e-10


@pytest.mark.skipif(not native.available(), reason="needs native runtime")
class TestScalapackApi:
    def _setup(self, m=32, n=32, mb=8, nb=8, p=2, q=2, seed=3):
        from slate_tpu.api import scalapack as sc
        rng = np.random.default_rng(seed)
        grid = sc.BlacsGrid(p, q)
        desc = sc.Desc(m, n, mb, nb)
        a = rng.standard_normal((m, n))
        return sc, grid, desc, a

    def test_roundtrip(self):
        sc, grid, desc, a = self._setup()
        lg = sc.to_local(a, grid, desc)
        assert np.abs(sc.from_local(lg, grid, desc) - a).max() == 0

    def test_ppotrf_pposv(self):
        sc, grid, desc, a = self._setup()
        spd = a @ a.T + desc.m * np.eye(desc.m)
        b = np.random.default_rng(4).standard_normal((desc.m, 2))
        descb = sc.Desc(desc.m, 2, desc.mb, desc.nb)
        a_lg = sc.to_local(spd, grid, desc)
        b_lg = sc.to_local(b, grid, descb)
        _, x_lg = sc.pposv("L", a_lg, desc, b_lg, descb, grid)
        x = sc.from_local(x_lg, grid, descb)
        assert np.abs(spd @ x - b).max() < 1e-10

    def test_pgemm(self):
        sc, grid, desc, a = self._setup()
        b = np.random.default_rng(5).standard_normal((desc.m, desc.n))
        c = np.zeros((desc.m, desc.n))
        out = sc.pgemm("N", "N", 1.0, sc.to_local(a, grid, desc), desc,
                       sc.to_local(b, grid, desc), desc, 0.0,
                       sc.to_local(c, grid, desc), desc, grid)
        assert np.abs(sc.from_local(out, grid, desc) - a @ b).max() < 1e-11

    def test_pgesv_pheev(self):
        sc, grid, desc, a = self._setup()
        n = desc.m
        sys_a = a + n * np.eye(n)
        b = np.random.default_rng(6).standard_normal((n, 1))
        descb = sc.Desc(n, 1, desc.mb, desc.nb)
        x_lg, piv = sc.pgesv(sc.to_local(sys_a, grid, desc), desc,
                             sc.to_local(b, grid, descb), descb, grid)
        assert np.abs(sys_a @ sc.from_local(x_lg, grid, descb) - b).max() < 1e-9
        sym = (a + a.T) / 2
        w, z_lg = sc.pheev("V", "L", sc.to_local(sym, grid, desc), desc, grid)
        assert np.abs(np.sort(w) - np.linalg.eigvalsh(sym)).max() < 1e-9


def test_simplified_nopiv_and_indefinite_factor_verbs():
    """The remaining simplified_api.hh verbs (lu_*_nopiv,
    indefinite_solve_using_factor, lu_inverse_using_factor_out_of_place)."""
    import jax.numpy as jnp

    import slate_tpu as st
    from slate_tpu.api import simplified as sapi
    rng = np.random.default_rng(61)
    n = 48
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal((n, 2))
    lu = sapi.lu_factor_nopiv(jnp.asarray(a), {"nb": 16})
    x = sapi.lu_solve_using_factor_nopiv(lu, jnp.asarray(b), {"nb": 16})
    np.testing.assert_allclose(a @ np.asarray(x), b, atol=1e-8)
    x2 = sapi.lu_solve_nopiv(jnp.asarray(a), jnp.asarray(b), {"nb": 16})
    np.testing.assert_allclose(a @ np.asarray(x2), b, atol=1e-8)
    lu2, piv = sapi.lu_factor(jnp.asarray(a), {"nb": 16})
    inv = sapi.lu_inverse_using_factor_out_of_place(lu2, piv, {"nb": 16})
    np.testing.assert_allclose(np.asarray(inv) @ a, np.eye(n), atol=1e-8)
    h = rng.standard_normal((n, n))
    h = (h + h.T) / 2 + n * np.eye(n)
    fac = sapi.indefinite_factor(
        st.HermitianMatrix(jnp.asarray(h), uplo=st.Uplo.Lower, mb=16, nb=16))
    xh = sapi.indefinite_solve_using_factor(fac, jnp.asarray(b))
    np.testing.assert_allclose(h @ np.asarray(xh), b, atol=1e-7)
