"""BLAS-3 driver tests — residual checks in the reference tester's style
(``test/test_gemm.cc:190-260``: ‖computed − reference‖ scaled ≤ 3ε)."""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.testing import generate_matrix

DTYPES = [jnp.float32, jnp.float64, jnp.complex64, jnp.complex128]


def tol(dtype, factor=50):
    return factor * jnp.finfo(dtype).eps


def relerr(x, y):
    x = np.asarray(x); y = np.asarray(y)
    d = np.linalg.norm(x - y)
    s = max(np.linalg.norm(y), 1.0)
    return d / s


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("opA,opB", [("n", "n"), ("t", "n"), ("n", "c"), ("c", "t")])
def test_gemm(dtype, opA, opB):
    m, n, k = 93, 71, 58
    a = generate_matrix("randn", m, k, dtype=dtype, seed=1)
    b = generate_matrix("randn", k, n, dtype=dtype, seed=2)
    c = generate_matrix("randn", m, n, dtype=dtype, seed=3)
    alpha, beta = 1.5, -0.5

    def make_view(x, op):
        """Store x under the given op so the logical (op-applied) matrix is x."""
        x = np.asarray(x)
        if op == "t":
            return st.Matrix.from_array(x.T, mb=32, nb=32).transpose()
        if op == "c":
            return st.Matrix.from_array(np.conj(x.T), mb=32, nb=32).conj_transpose()
        return st.Matrix.from_array(x, mb=32, nb=32)

    A = make_view(a, opA)
    B = make_view(b, opB)
    C = st.Matrix.from_array(c, mb=32, nb=32)

    out = st.gemm(alpha, A, B, beta, C)
    ref = alpha * np.asarray(a) @ np.asarray(b) + beta * np.asarray(c)
    assert relerr(out.array, ref) < tol(dtype)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.complex128])
@pytest.mark.parametrize("side", [st.Side.Left, st.Side.Right])
@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
def test_trsm_trmm(dtype, side, uplo):
    n, m = 96, 77
    a = np.asarray(generate_matrix("randn", n, n, dtype=dtype, seed=4))
    a = a + n * np.eye(n)  # well-conditioned
    tri = np.tril(a) if uplo is st.Uplo.Lower else np.triu(a)
    # Left: A (n×n) acts on B (n×m); Right: B (m×n) multiplied by A (n×n)
    b = np.asarray(generate_matrix("randn",
                                   n if side is st.Side.Left else m,
                                   m if side is st.Side.Left else n,
                                   dtype=dtype, seed=5))
    A = st.TriangularMatrix(jnp.asarray(a), uplo=uplo, mb=32, nb=32)

    x = np.asarray(st.trsm(side, 2.0, A, jnp.asarray(b)))
    if side is st.Side.Left:
        assert relerr(tri @ x, 2.0 * b) < tol(dtype, 200)
    else:
        assert relerr(x @ tri, 2.0 * b) < tol(dtype, 200)

    y = np.asarray(st.trmm(side, 0.5, A, jnp.asarray(b)))
    ref = 0.5 * (tri @ b if side is st.Side.Left else b @ tri)
    assert relerr(y, ref) < tol(dtype)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.complex128])
@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
def test_herk_syrk(dtype, uplo):
    n, k = 64, 40
    a = np.asarray(generate_matrix("randn", n, k, dtype=dtype, seed=6))
    c0 = np.asarray(generate_matrix("randn", n, n, dtype=dtype, seed=7))
    C = st.HermitianMatrix(jnp.asarray(c0), uplo=uplo, mb=16, nb=16)
    out = np.asarray(st.herk(1.25, jnp.asarray(a), 0.5, C).data)
    ref = 1.25 * a @ np.conj(a.T) + 0.5 * c0
    mask = np.tril(np.ones((n, n), bool)) if uplo is st.Uplo.Lower else np.triu(np.ones((n, n), bool))
    assert relerr(out[mask], ref[mask]) < tol(dtype)
    # untouched triangle preserved
    assert np.array_equal(out[~mask], c0[~mask])

    Cs = st.SymmetricMatrix(jnp.asarray(c0), uplo=uplo, mb=16, nb=16)
    outs = np.asarray(st.syrk(1.25, jnp.asarray(a), 0.5, Cs).data)
    refs = 1.25 * a @ a.T + 0.5 * c0
    assert relerr(outs[mask], refs[mask]) < tol(dtype)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.complex128])
def test_her2k_syr2k(dtype):
    n, k = 48, 33
    a = np.asarray(generate_matrix("randn", n, k, dtype=dtype, seed=8))
    b = np.asarray(generate_matrix("randn", n, k, dtype=dtype, seed=9))
    c0 = np.asarray(generate_matrix("randn", n, n, dtype=dtype, seed=10))
    mask = np.tril(np.ones((n, n), bool))
    C = st.HermitianMatrix(jnp.asarray(c0), uplo=st.Uplo.Lower, mb=16, nb=16)
    alpha = (1.0 + 0.5j) if np.iscomplexobj(a) else 1.5
    out = np.asarray(st.her2k(alpha, jnp.asarray(a), jnp.asarray(b), 0.25, C).data)
    ref = alpha * a @ np.conj(b.T) + np.conj(alpha) * b @ np.conj(a.T) + 0.25 * c0
    assert relerr(out[mask], ref[mask]) < tol(dtype)

    Cs = st.SymmetricMatrix(jnp.asarray(c0), uplo=st.Uplo.Lower, mb=16, nb=16)
    outs = np.asarray(st.syr2k(alpha, jnp.asarray(a), jnp.asarray(b), 0.25, Cs).data)
    refs = alpha * a @ b.T + alpha * b @ a.T + 0.25 * c0
    assert relerr(outs[mask], refs[mask]) < tol(dtype)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.complex128])
@pytest.mark.parametrize("side", [st.Side.Left, st.Side.Right])
def test_symm_hemm(dtype, side):
    n, m = 52, 37
    a = np.asarray(generate_matrix("randn", n, n, dtype=dtype, seed=11))
    herm = (a + np.conj(a.T)) / 2
    b = np.asarray(generate_matrix("randn",
                                   n if side is st.Side.Left else m,
                                   m if side is st.Side.Left else n,
                                   dtype=dtype, seed=12))
    c = np.asarray(generate_matrix("randn",
                                   n if side is st.Side.Left else m,
                                   m if side is st.Side.Left else n,
                                   dtype=dtype, seed=13))
    A = st.HermitianMatrix(jnp.asarray(herm), uplo=st.Uplo.Lower, mb=16, nb=16)
    out = np.asarray(st.hemm(side, 1.5, A, jnp.asarray(b), -0.5, jnp.asarray(c)))
    ref = 1.5 * (herm @ b if side is st.Side.Left else b @ herm) - 0.5 * c
    assert relerr(out, ref) < tol(dtype)

    sym = (a + a.T) / 2
    As = st.SymmetricMatrix(jnp.asarray(sym), uplo=st.Uplo.Upper, mb=16, nb=16)
    outs = np.asarray(st.symm(side, 1.5, As, jnp.asarray(b), -0.5, jnp.asarray(c)))
    refs = 1.5 * (sym @ b if side is st.Side.Left else b @ sym) - 0.5 * c
    assert relerr(outs, refs) < tol(dtype)
