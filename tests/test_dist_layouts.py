"""Arbitrary distributions (VERDICT r4 Next #9): GridOrder on the mesh,
user tile maps on DistMatrix, and rectangular tiles — the reference's
``tileRank``/``tileMb`` lambdas + ``GridOrder`` (``BaseMatrix.hh:765-771``,
``enums.hh:127``) realised as mesh construction order, separable
storage-permutation maps, and mb≠nb layouts."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from slate_tpu.parallel import (distribute, undistribute, make_grid_mesh,
                                ppotrf, pgetrf, pgemm)
from slate_tpu.parallel.dist import canonicalize


@pytest.fixture(scope="module")
def mesh_col():
    """2×4 grid with BLACS-'C' (column-major) device order."""
    return make_grid_mesh(2, 4, grid_order="col")


def _spd(n, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((n, n))
    return (g @ g.T + n * np.eye(n)).astype(np.float64)


def test_grid_order_col_ppotrf_pgetrf(mesh_col):
    """The SPMD drivers are mesh-order-independent: same residuals on a
    column-major-ordered grid."""
    n, nb = 96, 16
    a = _spd(n, seed=3)
    ad = distribute(a, mesh_col, nb, diag_pad=1.0, row_mult=4, col_mult=2)
    l = np.tril(np.asarray(undistribute(ppotrf(ad))))
    assert np.linalg.norm(l @ l.T - a) / np.linalg.norm(a) < 1e-12

    rng = np.random.default_rng(5)
    g = rng.standard_normal((n, n)) + n * np.eye(n)
    gd = distribute(g, mesh_col, nb, diag_pad=1.0, row_mult=4, col_mult=2)
    lu, gperm = pgetrf(gd)
    lu = np.asarray(undistribute(lu))
    perm = np.asarray(gperm)[:n]
    lmat = np.tril(lu, -1) + np.eye(n)
    assert np.linalg.norm(lmat @ np.triu(lu) - g[perm]) \
        / np.linalg.norm(g) < 1e-12


def test_grid_order_col_pgemm(mesh_col):
    m, k, n, nb = 80, 64, 112, 16
    rng = np.random.default_rng(7)
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    ad = distribute(a, mesh_col, nb)
    bd = distribute(b, mesh_col, nb)
    c = np.asarray(undistribute(pgemm(1.0, ad, bd)))
    assert np.linalg.norm(c - a @ b) / np.linalg.norm(a @ b) < 1e-12


def test_user_tile_map_roundtrip(mesh8):
    """distribute/undistribute with custom separable tile maps."""
    m, n, nb = 96, 128, 16
    rng = np.random.default_rng(11)
    a = rng.standard_normal((m, n))
    p, q = 2, 4
    # reversed-cyclic rows, blocked columns — both balanced after pad
    row_map = lambda i: (p - 1) - (i % p)
    ntp = -(-(-(-n // nb)) // q) * q  # padded col blocks (8 here)
    def col_map(j, ntp=ntp):
        return j // (ntp // q)
    ad = distribute(a, mesh8, nb, row_map=row_map, col_map=col_map)
    back = np.asarray(undistribute(ad))
    assert np.array_equal(back, a)
    # canonicalize re-grids to cyclic with identical contents
    can = canonicalize(ad)
    assert can.row_map is None and can.col_map is None
    assert np.array_equal(np.asarray(undistribute(can)), a)


def test_user_tile_map_drivers(mesh8):
    """ppotrf / pgetrf / pgemm accept user-mapped operands (auto
    re-grid, reference redistribute-before-driver practice)."""
    n, nb = 96, 16
    p, q = 2, 4
    row_map = lambda i: (p - 1) - (i % p)
    col_map = lambda j: (q - 1) - (j % q)
    a = _spd(n, seed=13)
    ad = distribute(a, mesh8, nb, diag_pad=1.0, row_mult=4, col_mult=2,
                    row_map=row_map, col_map=col_map)
    l = np.tril(np.asarray(undistribute(ppotrf(ad))))
    assert np.linalg.norm(l @ l.T - a) / np.linalg.norm(a) < 1e-12

    rng = np.random.default_rng(17)
    g = rng.standard_normal((n, n)) + n * np.eye(n)
    gd = distribute(g, mesh8, nb, diag_pad=1.0, row_mult=4, col_mult=2,
                    row_map=row_map, col_map=col_map)
    lu, gperm = pgetrf(gd)
    lu = np.asarray(undistribute(lu))
    perm = np.asarray(gperm)[:n]
    lmat = np.tril(lu, -1) + np.eye(n)
    assert np.linalg.norm(lmat @ np.triu(lu) - g[perm]) \
        / np.linalg.norm(g) < 1e-12

    b = rng.standard_normal((n, 64))
    bd = distribute(b, mesh8, nb, row_mult=4,
                    col_map=lambda j: (j // 1) % q)
    c = np.asarray(undistribute(pgemm(1.0, gd, bd)))
    assert np.linalg.norm(c - g @ b) / np.linalg.norm(g @ b) < 1e-12


def test_user_tile_map_unbalanced_raises(mesh8):
    with pytest.raises(ValueError, match="unbalanced"):
        distribute(np.zeros((64, 64)), mesh8, 16,
                   row_map=lambda i: 0)


def test_rect_tiles_pgemm(mesh8):
    """mb≠nb rectangular tiles through pgemm (reference tileMb lambda)."""
    m, k, n = 96, 64, 80
    rng = np.random.default_rng(19)
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    ad = distribute(a, mesh8, nb=16, mb=32)
    bd = distribute(b, mesh8, nb=8, mb=16)
    c = np.asarray(undistribute(pgemm(1.0, ad, bd)))
    assert np.linalg.norm(c - a @ b) / np.linalg.norm(a @ b) < 1e-12
