"""LU family tests.

Mirrors the reference tester's validation (``test/test_gesv.cc``):
residual gate ‖LU − PA‖/(‖A‖·n·ε) ≤ 3 and solve residual
‖AX − B‖/(‖A‖·‖X‖·n·ε) ≤ 3.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import slate_tpu as st
from slate_tpu.enums import MethodLU, Norm, Op
from slate_tpu.linalg import lu as lu_mod
from slate_tpu.linalg.lu import (gesv, gesv_mixed, gesv_mixed_gmres, getrf,
                                 getrf_nopiv, getrf_tntpiv, getri, getrs,
                                 ipiv_to_perm, perm_to_ipiv)
from slate_tpu.testing.matgen import generate_matrix


def _unpack(lu):
    lu = np.asarray(lu)
    m, n = lu.shape
    k = min(m, n)
    l = np.tril(lu[:, :k], -1) + np.eye(m, k)
    u = np.triu(lu[:k, :])
    return l, u


def _check_factor(a, lu, perm, tol_eps=30.0):
    # the reference gate is 3ε on the *solve* residual; the factor
    # reconstruction gate is looser (growth factor enters), hence 30
    a = np.asarray(a)
    m, n = a.shape
    l, u = _unpack(lu)
    pa = a[np.asarray(perm)]
    eps = np.finfo(a.dtype).eps
    res = np.linalg.norm(pa - l @ u) / (np.linalg.norm(a) * max(m, n) * eps)
    assert res < tol_eps, f"factor residual {res}"


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("n", [48, 130])
def test_getrf_partial(dtype, n):
    a = np.asarray(generate_matrix("randn", n, dtype=jnp.dtype(dtype), seed=1))
    lu, perm = getrf(st.Matrix.from_array(a, nb=32))
    _check_factor(a, lu.array, perm)
    # partial pivoting ⇒ |L| ≤ 1
    l = np.tril(np.asarray(lu.array), -1)
    assert np.abs(l).max() <= 1.0 + 1e-5


def test_getrf_rectangular():
    a = np.asarray(generate_matrix("randn", 100, 40, dtype=jnp.float64, seed=2))
    lu, perm = getrf(st.Matrix.from_array(a, nb=16))
    _check_factor(a, lu.array, perm)


def test_getrf_wide():
    a = np.asarray(generate_matrix("randn", 40, 100, dtype=jnp.float64, seed=2))
    lu, perm = getrf(st.Matrix.from_array(a, nb=16))
    _check_factor(a, lu.array, perm)


def test_getrf_unsupported_method_raises():
    a = np.eye(8)
    with pytest.raises(NotImplementedError):
        getrf(st.Matrix.from_array(a, nb=4), {"method_lu": MethodLU.RBT})


def test_getrs_and_gesv():
    n, nrhs = 96, 5
    a = np.asarray(generate_matrix("randn", n, dtype=jnp.float64, seed=3))
    b = np.random.default_rng(3).standard_normal((n, nrhs))
    lu, perm, x = gesv(st.Matrix.from_array(a, nb=32), jnp.asarray(b))
    xv = np.asarray(x)
    eps = np.finfo(np.float64).eps
    res = (np.linalg.norm(a @ xv - b) /
           (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))
    assert res < 3, f"solve residual {res}"


def test_getrs_trans():
    n = 64
    a = np.asarray(generate_matrix("randn", n, dtype=jnp.float64, seed=4))
    b = np.random.default_rng(4).standard_normal((n, 3))
    lu, perm = getrf(st.Matrix.from_array(a, nb=16))
    x = np.asarray(getrs(lu, perm, jnp.asarray(b), op=Op.Trans))
    np.testing.assert_allclose(a.T @ x, b, atol=1e-8)


def test_getrf_nopiv_dominant():
    n = 80
    a = np.asarray(generate_matrix("rand_dominant", n, dtype=jnp.float64, seed=5))
    f = getrf_nopiv(st.Matrix.from_array(a, nb=32))
    l, u = _unpack(np.asarray(f.array))
    eps = np.finfo(np.float64).eps
    res = np.linalg.norm(a - l @ u) / (np.linalg.norm(a) * n * eps)
    assert res < 30, f"nopiv residual {res}"


@pytest.mark.parametrize("n,nb", [(64, 16), (100, 32)])
def test_getrf_tntpiv(n, nb):
    a = np.asarray(generate_matrix("randn", n, dtype=jnp.float64, seed=6))
    lu, perm = getrf_tntpiv(st.Matrix.from_array(a, nb=nb))
    _check_factor(a, lu.array, perm)
    # tournament pivoting still bounds |L| (weaker than partial, but the
    # factor must reconstruct PA exactly — checked above)
    b = np.random.default_rng(6).standard_normal((n, 2))
    x = np.asarray(getrs(lu, perm, jnp.asarray(b)))
    np.testing.assert_allclose(a @ x, b, atol=1e-7)


def test_getri():
    n = 72
    a = np.asarray(generate_matrix("randn", n, dtype=jnp.float64, seed=7))
    lu, perm = getrf(st.Matrix.from_array(a, nb=24))
    inv = np.asarray(getri(lu, perm).array)
    np.testing.assert_allclose(a @ inv, np.eye(n), atol=1e-9)


def test_gesv_mixed_converges():
    n = 128
    a = np.asarray(generate_matrix("cond", n, dtype=jnp.float64, seed=8,
                                   cond=1e3))
    b = np.random.default_rng(8).standard_normal((n, 2))
    x, iters = gesv_mixed(st.Matrix.from_array(a, nb=32), jnp.asarray(b))
    assert iters >= 0, "mixed solver fell back unexpectedly"
    xv = np.asarray(x)
    res = np.linalg.norm(a @ xv - b) / (np.linalg.norm(a) * np.linalg.norm(xv))
    assert res < 1e-13, f"refined residual {res}"  # fp64-grade despite fp32 factor


def test_gesv_mixed_gmres():
    n = 96
    a = np.asarray(generate_matrix("cond", n, dtype=jnp.float64, seed=9,
                                   cond=1e4))
    b = np.random.default_rng(9).standard_normal(n)
    x, iters = gesv_mixed_gmres(st.Matrix.from_array(a, nb=32), jnp.asarray(b))
    xv = np.asarray(x)
    res = np.linalg.norm(a @ xv - b) / (np.linalg.norm(a) * np.linalg.norm(xv))
    assert res < 1e-12, f"gmres-ir residual {res}"


def test_gesv_mixed_gmres_complex():
    n = 48
    rng = np.random.default_rng(12)
    a = (rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n)))
    a = a + n * np.eye(n)
    b = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    x, iters = gesv_mixed_gmres(
        st.Matrix.from_array(jnp.asarray(a, jnp.complex128), nb=16),
        jnp.asarray(b, jnp.complex128))
    assert iters >= 0, "complex GMRES-IR fell back"
    xv = np.asarray(x)
    res = np.linalg.norm(a @ xv - b) / (np.linalg.norm(a) * np.linalg.norm(xv))
    assert res < 1e-12, f"complex gmres-ir residual {res}"


def test_pivot_conversions_roundtrip():
    rng = np.random.default_rng(10)
    perm = rng.permutation(17)
    ipiv = perm_to_ipiv(perm)
    back = np.asarray(ipiv_to_perm(np.asarray(ipiv), 17))
    np.testing.assert_array_equal(back, perm)


def test_method_option_dispatch():
    n = 40
    a = np.asarray(generate_matrix("rand_dominant", n, dtype=jnp.float64, seed=11))
    lu, perm = getrf(st.Matrix.from_array(a, nb=16),
                     {"method_lu": MethodLU.NoPiv})
    np.testing.assert_array_equal(np.asarray(perm), np.arange(n))


def test_tall_panel_lu_pp_true_partial_pivot():
    """_tall_panel_lu_pp must produce a genuine partial-pivot factor:
    pan[pl] = L·U with every |L| entry ≤ 1 (the growth guarantee the
    tournament panel cannot make)."""
    from slate_tpu.linalg.lu import _tall_panel_lu_pp
    rng = np.random.default_rng(3)
    pan = jnp.asarray(rng.standard_normal((300, 64)))
    lu_p, pl = _tall_panel_lu_pp(pan, ib=16)
    lu_np, pl_np = np.asarray(lu_p), np.asarray(pl)
    l = np.tril(lu_np, -1)[:, :64]
    l[np.arange(64), np.arange(64)] = 1.0
    u = np.triu(lu_np[:64])
    np.testing.assert_allclose(np.asarray(pan)[pl_np], l @ u,
                               atol=1e-12, rtol=0)
    assert np.max(np.abs(np.tril(lu_np, -1))) <= 1.0 + 1e-12
    # same pivots as LAPACK partial pivoting (argmax of updated column):
    # replay scipy's swap sequence and demand the identical permutation
    import scipy.linalg as sla
    _, piv = sla.lu_factor(np.asarray(pan), check_finite=False)
    want = np.arange(300)
    for k, p in enumerate(piv):
        want[k], want[p] = want[p], want[k]
    np.testing.assert_array_equal(pl_np, want)


class TestScatteredLU:
    """Coverage for the scattered-row (no-swap) LU driver + Pallas
    masked panel kernel, in interpret mode (the same code path the TPU
    compiles; ADVICE r4: the default-capable path must not be
    test-invisible)."""

    @pytest.mark.parametrize("m,n,nb", [(128, 128, 32), (192, 64, 32),
                                        (64, 128, 32)])
    def test_residual_and_pivots(self, m, n, nb):
        from slate_tpu.linalg.lu import getrf_scattered
        import scipy.linalg as sla
        rng = np.random.default_rng(5)
        a = rng.standard_normal((m, n)).astype(np.float32)
        lu, perm = jax.jit(lambda x: getrf_scattered(x, nb))(
            jnp.asarray(a))
        lu, perm = np.asarray(lu), np.asarray(perm)
        k = min(m, n)
        lmat = np.tril(lu[:, :k], -1) + np.eye(m, k, dtype=np.float32)
        umat = np.triu(lu[:k])
        eps = np.finfo(np.float32).eps
        res = (np.abs(a[perm] - lmat @ umat).max()
               / (np.abs(a).max() * max(m, n) * eps))
        assert res < 3, f"scaled residual {res}"
        # TRUE partial pivoting: first-k pivots must equal scipy's
        _, piv = sla.lu_factor(a, check_finite=False)
        want = np.arange(m)
        for kk, p in enumerate(piv):
            want[kk], want[p] = want[p], want[kk]
        np.testing.assert_array_equal(perm[:k], want[:k])

    def test_wide_f32_residual_gate(self):
        """The reviewer-measured failure config pre-fix: wide f32 panel
        whose U12 came from a bare explicit inverse (residual 4.2 > 3);
        the residual-correction step must hold the 3-eps gate."""
        from slate_tpu.linalg.lu import getrf_scattered
        rng = np.random.default_rng(7)
        m, n, nb = 128, 256, 32
        a = rng.standard_normal((m, n)).astype(np.float32)
        lu, perm = jax.jit(lambda x: getrf_scattered(x, nb))(
            jnp.asarray(a))
        lu, perm = np.asarray(lu), np.asarray(perm)
        lmat = np.tril(lu[:, :m], -1) + np.eye(m, dtype=np.float32)
        eps = np.finfo(np.float32).eps
        res = (np.abs(a[perm] - lmat @ np.triu(lu[:m])).max()
               / (np.abs(a).max() * n * eps))
        assert res < 3, f"scaled residual {res}"

    def test_use_scattered_gating(self, monkeypatch):
        """_use_scattered is shape/VMEM ELIGIBILITY only; whether the
        driver runs is the autotune table's lu_driver decision, forced
        through the tri-state config.scattered_lu knob (the raw
        SLATE_TPU_SCATTERED_LU env read is gone from lu.py)."""
        from slate_tpu.linalg.lu import _use_scattered
        from slate_tpu.perf import autotune
        z = jnp.zeros((1024, 1024), jnp.float32)
        assert _use_scattered(z, 512)
        # shapes the kernel cannot take are ineligible
        assert not _use_scattered(jnp.zeros((1000, 1000), jnp.float32),
                                  512)
        assert not _use_scattered(          # too tall for VMEM (shape only)
            jax.ShapeDtypeStruct((17408, 17408), jnp.float32), 512)
        assert not _use_scattered(z.astype(jnp.float64), 512)
        # force-off escape hatch wins over everything
        monkeypatch.setattr("slate_tpu.config.use_pallas", False)
        assert not _use_scattered(z, 512)
        monkeypatch.undo()

        # the decision: off-TPU auto default is the recursion; the
        # tri-state knob forces the scattered driver on/off
        autotune.reset_table()
        try:
            assert autotune.choose_lu_driver(
                1024, 1024, 512, jnp.float32, eligible=True) == "rec"
            monkeypatch.setattr("slate_tpu.config.scattered_lu", True)
            assert autotune.choose_lu_driver(
                1024, 1024, 512, jnp.float32, eligible=True) == "scattered"
            monkeypatch.setattr("slate_tpu.config.scattered_lu", False)
            assert autotune.choose_lu_driver(
                1024, 1024, 512, jnp.float32, eligible=True) == "rec"
            # ineligible shapes never take the driver, even forced on
            monkeypatch.setattr("slate_tpu.config.scattered_lu", True)
            assert autotune.choose_lu_driver(
                1000, 1000, 512, jnp.float32, eligible=False) == "rec"
            assert autotune.timing_reps() == 0   # all knob-resolved
        finally:
            autotune.reset_table()

    def test_getrf_dispatches_scattered_when_forced(self, monkeypatch):
        """End-to-end: with the knob forced on, st.getrf routes an
        eligible f32 matrix through the fused scattered driver and the
        decision lands in the autotune table."""
        from slate_tpu.linalg import lu as lu_mod
        from slate_tpu.perf import autotune
        monkeypatch.setattr("slate_tpu.config.scattered_lu", True)
        monkeypatch.setattr(lu_mod, "_SCATTERED_NB", 64)
        autotune.reset_table()
        try:
            rng = np.random.default_rng(11)
            n = 128
            a = (rng.standard_normal((n, n)).astype(np.float32)
                 + n * np.eye(n, dtype=np.float32))
            lu, perm = getrf(st.Matrix.from_array(a, nb=64))
            _check_factor(a, lu.array, perm)
            dec = autotune.decisions()
            hit = [k for k in dec if k.startswith("lu_driver|")]
            assert hit and dec[hit[0]] == "scattered", dec
        finally:
            autotune.reset_table()
