"""Cholesky family tests — residual identities in the reference tester's
style (``test/test_posv.cc``: ‖b − A·x‖ / (‖A‖·‖x‖·n) ≤ 3ε)."""

import jax.numpy as jnp
import numpy as np
import pytest

import slate_tpu as st
from slate_tpu.testing import generate_matrix, random_spd

DTYPES = [jnp.float32, jnp.float64, jnp.complex64, jnp.complex128]


def eps(dtype):
    return jnp.finfo(dtype).eps


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
def test_potrf(dtype, uplo):
    n = 120
    a = np.asarray(random_spd(n, dtype=dtype, seed=1))
    A = st.HermitianMatrix(jnp.asarray(a), uplo=uplo, mb=32, nb=32)
    F = st.potrf(A)
    f = np.asarray(F.data)
    if uplo is st.Uplo.Lower:
        rec = f @ np.conj(f.T)
    else:
        rec = np.conj(f.T) @ f
    err = np.linalg.norm(rec - a) / (np.linalg.norm(a) * n)
    assert err < 3 * eps(dtype)


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
def test_posv(dtype, uplo):
    n, nrhs = 130, 7
    a = np.asarray(random_spd(n, dtype=dtype, seed=2))
    b = np.asarray(generate_matrix("randn", n, nrhs, dtype=dtype, seed=3))
    A = st.HermitianMatrix(jnp.asarray(a), uplo=uplo, mb=32, nb=32)
    _, x = st.posv(A, jnp.asarray(b))
    x = np.asarray(x)
    err = np.linalg.norm(b - a @ x) / (np.linalg.norm(a) * np.linalg.norm(x) * n)
    assert err < 3 * eps(dtype)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.complex128])
@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
def test_potri(dtype, uplo):
    n = 96
    a = np.asarray(random_spd(n, dtype=dtype, seed=4))
    A = st.HermitianMatrix(jnp.asarray(a), uplo=uplo, mb=32, nb=32)
    F = st.potrf(A)
    Inv = st.potri(F)
    from slate_tpu.ops.tile_ops import hermitize
    inv_full = np.asarray(hermitize(uplo, Inv.data))
    err = np.linalg.norm(inv_full @ a - np.eye(n)) / n
    assert err < 100 * eps(dtype) * np.linalg.cond(a)


@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
@pytest.mark.parametrize("diag", [st.Diag.NonUnit, st.Diag.Unit])
def test_trtri(uplo, diag):
    n = 80
    dtype = jnp.float64
    a = np.asarray(generate_matrix("randn", n, n, dtype=dtype, seed=5))
    if diag is st.Diag.Unit:
        # keep the strict triangle small: inv(unit + S) = Σ(−S)^k blows up
        # exponentially for ‖S‖ ≳ 1, which would swamp any solver
        a = a / (2 * np.linalg.norm(a, 2))
    a = a + n * np.eye(n)
    A = st.TriangularMatrix(jnp.asarray(a), uplo=uplo, diag=diag, mb=32, nb=32)
    inv = np.asarray(st.trtri(A).data)
    tri = np.tril(a) if uplo is st.Uplo.Lower else np.triu(a)
    if diag is st.Diag.Unit:
        np.fill_diagonal(tri, 1.0)
    err = np.linalg.norm(inv @ tri - np.eye(n)) / n
    assert err < 100 * eps(dtype)


@pytest.mark.parametrize("dtype", [jnp.float64, jnp.complex128])
@pytest.mark.parametrize("uplo", [st.Uplo.Lower, st.Uplo.Upper])
def test_trtrm_lauum(dtype, uplo):
    n = 64
    a = np.asarray(generate_matrix("randn", n, n, dtype=dtype, seed=6))
    A = st.TriangularMatrix(jnp.asarray(a), uplo=uplo, mb=16, nb=16)
    out = np.asarray(st.trtrm(A).data)
    if uplo is st.Uplo.Lower:
        t = np.tril(a)
        ref = np.conj(t.T) @ t
        mask = np.tril(np.ones((n, n), bool))
    else:
        t = np.triu(a)
        ref = t @ np.conj(t.T)
        mask = np.triu(np.ones((n, n), bool))
    err = np.linalg.norm(out[mask] - ref[mask]) / max(np.linalg.norm(ref), 1)
    assert err < 50 * eps(dtype)


def test_matrix_views():
    """sub/slice/transpose view algebra (reference Matrix.hh:131-135)."""
    a = np.arange(64, dtype=np.float64).reshape(8, 8)
    A = st.Matrix.from_array(a, mb=2, nb=2)
    assert A.mt == 4 and A.nt == 4
    s = A.sub(1, 2, 0, 1)
    assert np.array_equal(np.asarray(s.array), a[2:6, 0:4])
    sl = A.slice(1, 3, 2, 5)
    assert np.array_equal(np.asarray(sl.array), a[1:4, 2:6])
    At = A.transpose()
    assert np.array_equal(np.asarray(At.array), a.T)
    assert At.m == 8 and At.n == 8
    t = A.tile(1, 2)
    assert np.array_equal(np.asarray(t), a[2:4, 4:6])


class TestMixedPrecision:
    """posv_mixed / posv_mixed_gmres (reference src/posv_mixed*.cc)."""

    def _spd(self, n, seed, dtype=np.float64):
        rng = np.random.default_rng(seed)
        g = rng.standard_normal((n, n)).astype(dtype)
        return g @ g.T + n * np.eye(n, dtype=dtype)

    def test_posv_mixed_converges(self):
        n = 128
        a = self._spd(n, 21)
        b = np.random.default_rng(21).standard_normal((n, 2))
        A = st.HermitianMatrix(jnp.asarray(a), uplo=st.Uplo.Lower,
                               mb=32, nb=32)
        x, iters = st.posv_mixed(A, jnp.asarray(b))
        assert iters >= 0, "mixed solver fell back unexpectedly"
        xv = np.asarray(x)
        res = np.linalg.norm(a @ xv - b) / (np.linalg.norm(a)
                                            * np.linalg.norm(xv))
        assert res < 1e-13, f"refined residual {res}"  # fp64-grade

    def test_posv_mixed_gmres(self):
        n = 96
        a = self._spd(n, 22)
        b = np.random.default_rng(22).standard_normal(n)
        A = st.HermitianMatrix(jnp.asarray(a), uplo=st.Uplo.Lower,
                               mb=32, nb=32)
        x, iters = st.posv_mixed_gmres(A, jnp.asarray(b))
        xv = np.asarray(x)
        res = np.linalg.norm(a @ xv - b) / (np.linalg.norm(a)
                                            * np.linalg.norm(xv))
        assert res < 1e-12, f"gmres-ir residual {res}"


def test_gesv_nopiv_and_variant_aliases():
    """gesv_nopiv/getrs_nopiv (src/gesv_nopiv.cc) + method-variant names."""
    n = 64
    rng = np.random.default_rng(23)
    a = rng.standard_normal((n, n)) + n * np.eye(n)   # diagonally dominant
    b = rng.standard_normal((n, 3))
    lu, x = st.gesv_nopiv(st.Matrix.from_array(jnp.asarray(a), nb=16),
                          jnp.asarray(b))
    np.testing.assert_allclose(a @ np.asarray(x), b, atol=1e-9)
    # method variants share the standard lowering
    c = np.zeros((n, n))
    out_a = np.asarray(st.gemmA(1.0, jnp.asarray(a), jnp.asarray(a), 0.0,
                                jnp.asarray(c)))
    out_c = np.asarray(st.gemmC(1.0, jnp.asarray(a), jnp.asarray(a), 0.0,
                                jnp.asarray(c)))
    np.testing.assert_allclose(out_a, out_c)


def test_posv_mixed_vector_rhs():
    n = 64
    rng = np.random.default_rng(24)
    g = rng.standard_normal((n, n))
    a = g @ g.T + n * np.eye(n)
    b = rng.standard_normal(n)
    A = st.HermitianMatrix(jnp.asarray(a), uplo=st.Uplo.Lower, mb=16, nb=16)
    x, iters = st.posv_mixed(A, jnp.asarray(b))
    xv = np.asarray(x)
    assert xv.shape == (n,)
    res = np.linalg.norm(a @ xv - b) / (np.linalg.norm(a) * np.linalg.norm(xv))
    assert res < 1e-13, f"vector-rhs refined residual {res}"
