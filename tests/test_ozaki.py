"""Ozaki-split fp64 matmul (ops/ozaki.py): exactness-based MXU fp64.

The CPU build exercises the same int8 slice products and s32/f64
accumulation as the chip (lax.dot with preferred_element_type is
platform-agnostic), so these componentwise bounds pin the scheme's
arithmetic, not just a residual."""

import numpy as np
import jax.numpy as jnp
import pytest

from slate_tpu.ops.ozaki import matmul_f64


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.mark.parametrize("m,k,n", [(64, 64, 64), (96, 256, 64),
                                   (128, 1000, 64)])
def test_componentwise_fp64_grade(rng, m, k, n):
    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, n))
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    # |C − AB| ≤ tol · |A||B| componentwise, tol well inside k·eps64
    err = np.abs(c - a @ b)
    env = np.abs(a) @ np.abs(b)
    assert err.max() == 0 or (err / np.maximum(env, 1e-300)).max() < 1e-12


def test_wide_dynamic_range_and_zero_rows(rng):
    m = k = n = 96
    a = rng.standard_normal((m, k)) * np.exp2(
        rng.integers(-180, 180, size=(m, 1)).astype(np.float64))
    b = rng.standard_normal((k, n)) * np.exp2(
        rng.integers(-180, 180, size=(1, n)).astype(np.float64))
    a[3, :] = 0.0
    b[:, 5] = 0.0
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    env = np.abs(a) @ np.abs(b)
    rel = np.abs(c - a @ b) / np.maximum(env, 1e-300)
    assert rel.max() < 1e-12
    assert np.all(c[3, :] == 0.0)
    assert np.all(c[:, 5] == 0.0)


def test_exact_powers_of_two(rng):
    # rows whose max is an exact power of two hit the log2-fixup path
    a = np.full((32, 32), 0.5)
    b = np.eye(32)
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_array_equal(c, a)


def test_long_contraction_correlated(rng):
    # k beyond the per-chunk s32 exactness cap with all-positive
    # operands: pins the chunked accumulation (a single unchunked
    # diagonal group would silently wrap int32 here)
    k = (1 << 16) * 3 + 17
    a = np.full((2, k), 1 - 2 ** -24)
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(a.T)))
    true = float((a[0] * a[0]).sum())
    assert abs(c[0, 0] - true) / true < 1e-12


def test_extreme_exponent_scales():
    # huge-scale rows against tiny-scale columns: the product is in
    # range even though a single exp2 of either scale would be Inf
    a = np.full((4, 4), 2.0 ** 1023)
    b = np.full((4, 4), 2.0 ** -1000)
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    assert np.isfinite(c).all()
    assert abs(c[0, 0] - 4 * 2.0 ** 23) <= 1.0
    # subnormal inputs flush to zero (DAZ/FTZ semantics), never NaN/Inf
    a = np.full((4, 4), 2.0 ** -1060)
    b = np.full((4, 4), 2.0 ** 1000)
    c = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
    assert np.isfinite(c).all()


def test_type_and_shape_guards(rng):
    a64 = jnp.asarray(rng.standard_normal((8, 8)))
    with pytest.raises(TypeError):
        matmul_f64(a64.astype(jnp.float32), a64.astype(jnp.float32))
    with pytest.raises(ValueError):
        matmul_f64(a64[None], a64[None])
