"""Distributed-path tests on the 8-virtual-device CPU mesh.

Mirrors the reference's mpirun-on-one-box CI (SURVEY §4): the same SPMD
code that targets a TPU pod runs here on 8 host devices; checks are
rank-count-independent residuals like ``test/test_gemm.cc:248-260``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.parallel import (DistMatrix, distribute, make_grid_mesh,
                                pgemm, pposv, ppotrf, ppotrs, undistribute)
from slate_tpu.parallel.dist_blas3 import pgemm_auto


def _rng(seed=0):
    return np.random.default_rng(seed)


@pytest.fixture(scope="module")
def mesh24():
    return make_grid_mesh(2, 4)


@pytest.fixture(scope="module")
def mesh11():
    return make_grid_mesh(1, 1, devices=jax.devices()[:1])


class TestDistribute:
    @pytest.mark.parametrize("shape", [(96, 96), (100, 52), (16, 160)])
    def test_roundtrip(self, mesh24, shape):
        a = _rng(1).standard_normal(shape)
        dm = distribute(a, mesh24, nb=16)
        assert dm.data.shape[0] % (2 * 16) == 0
        assert dm.data.shape[1] % (4 * 16) == 0
        np.testing.assert_allclose(np.asarray(undistribute(dm)), a)

    def test_square_padding(self, mesh24):
        a = _rng(2).standard_normal((80, 80))
        dm = distribute(a, mesh24, nb=16, diag_pad=1.0, row_mult=4, col_mult=2)
        assert dm.mtp == dm.ntp
        full = np.zeros((dm.mtp * 16, dm.ntp * 16))
        full[:80, :80] = a
        np.fill_diagonal(full[80:, 80:], 1.0)
        # undistribute slices back to the logical matrix
        np.testing.assert_allclose(np.asarray(undistribute(dm)), a)

    def test_local_shards_are_residue_classes(self, mesh24):
        """Device (r,c) must own exactly tiles {i%p==r} x {j%q==c},
        the reference's tileRank map (MatrixStorage.hh:556-570)."""
        nb, p, q = 8, 2, 4
        mt = nt = 8
        a = np.arange(mt * nb * nt * nb, dtype=np.float64).reshape(mt * nb, nt * nb)
        dm = distribute(a, mesh24, nb=nb)
        ml, nl = mt // p, nt // q
        for shard in dm.data.addressable_shards:
            r = shard.index[0].start // (ml * nb)
            c = shard.index[1].start // (nl * nb)
            loc = np.asarray(shard.data)
            for il in range(ml):
                for jl in range(nl):
                    gi, gj = il * p + r, jl * q + c
                    np.testing.assert_array_equal(
                        loc[il * nb:(il + 1) * nb, jl * nb:(jl + 1) * nb],
                        a[gi * nb:(gi + 1) * nb, gj * nb:(gj + 1) * nb])


class TestPgemm:
    @pytest.mark.parametrize("m,k,n", [(64, 64, 64), (100, 60, 36), (33, 70, 9)])
    def test_matches_numpy(self, mesh24, m, k, n):
        r = _rng(3)
        a, b = r.standard_normal((m, k)), r.standard_normal((k, n))
        dc = pgemm_auto(1.0, a, b, mesh24, nb=16)
        np.testing.assert_allclose(np.asarray(undistribute(dc)), a @ b,
                                   rtol=1e-12, atol=1e-12)

    def test_alpha_beta(self, mesh24):
        r = _rng(4)
        a, b = r.standard_normal((64, 64)), r.standard_normal((64, 64))
        c = r.standard_normal((64, 64))
        da, db = distribute(a, mesh24, nb=16), distribute(b, mesh24, nb=16)
        dc = distribute(c, mesh24, nb=16)
        out = pgemm(2.0, da, db, beta=-1.0, c=dc)
        np.testing.assert_allclose(np.asarray(undistribute(out)),
                                   2.0 * a @ b - c, rtol=1e-12, atol=1e-12)

    def test_serial_mesh(self, mesh11):
        r = _rng(5)
        a, b = r.standard_normal((40, 24)), r.standard_normal((24, 56))
        da, db = distribute(a, mesh11, nb=16), distribute(b, mesh11, nb=16)
        out = pgemm(1.0, da, db)
        np.testing.assert_allclose(np.asarray(undistribute(out)), a @ b,
                                   rtol=1e-12, atol=1e-12)


def _spd(n, seed):
    a = _rng(seed).standard_normal((n, n))
    return a @ a.T + n * np.eye(n)


class TestPpotrf:
    @pytest.mark.parametrize("n,nb", [(64, 16), (96, 16), (100, 16), (48, 32)])
    def test_matches_numpy(self, mesh24, n, nb):
        a = _spd(n, 6)
        da = distribute(a, mesh24, nb=nb, diag_pad=1.0,
                        row_mult=4, col_mult=2)
        l = ppotrf(da)
        lh = np.tril(np.asarray(undistribute(l)))
        np.testing.assert_allclose(lh, np.linalg.cholesky(a),
                                   rtol=1e-10, atol=1e-10)

    def test_serial_mesh(self, mesh11):
        a = _spd(48, 7)
        da = distribute(a, mesh11, nb=16, diag_pad=1.0)
        l = ppotrf(da)
        lh = np.tril(np.asarray(undistribute(l)))
        np.testing.assert_allclose(lh, np.linalg.cholesky(a),
                                   rtol=1e-10, atol=1e-10)


class TestPposv:
    @pytest.mark.parametrize("n,nrhs,nb", [(96, 16, 16), (100, 7, 16)])
    def test_residual(self, mesh24, n, nrhs, nb):
        a = _spd(n, 8)
        b = _rng(9).standard_normal((n, nrhs))
        l, x = pposv(a, b, mesh24, nb=nb)
        xh = np.asarray(undistribute(x))
        # reference-style residual gate (test/test_gemm.cc:248-260 analog)
        res = np.linalg.norm(a @ xh - b) / (
            np.linalg.norm(a) * np.linalg.norm(xh) + np.linalg.norm(b))
        assert res < 3 * np.finfo(np.float64).eps * n

    def test_ppotrs_separately(self, mesh24):
        n, nb = 64, 16
        a = _spd(n, 10)
        b = _rng(11).standard_normal((n, 8))
        ad = distribute(a, mesh24, nb=nb, diag_pad=1.0, row_mult=4, col_mult=2)
        bd = distribute(b, mesh24, nb=nb, row_mult=4)
        l = ppotrf(ad)
        x = ppotrs(l, bd)
        xh = np.asarray(undistribute(x))
        np.testing.assert_allclose(xh, np.linalg.solve(a, b),
                                   rtol=1e-8, atol=1e-8)


class TestPgetrf:
    @pytest.mark.parametrize("n,nb", [(64, 16), (96, 16), (100, 16)])
    def test_factor_matches_pivoted_product(self, mesh24, n, nb):
        a = _rng(12).standard_normal((n, n))
        from slate_tpu.parallel import pgetrf
        da = distribute(a, mesh24, nb=nb, diag_pad=1.0, row_mult=4, col_mult=2)
        lu, gperm = pgetrf(da)
        luh = np.asarray(undistribute(lu))
        gp = np.asarray(gperm)
        l = np.tril(luh, -1) + np.eye(n)
        u = np.triu(luh)
        # A[gperm] = L U on the leading n rows
        np.testing.assert_allclose(a[gp[:n]], l @ u, rtol=1e-10, atol=1e-10)

    def test_serial_mesh(self, mesh11):
        a = _rng(13).standard_normal((48, 48))
        from slate_tpu.parallel import pgetrf
        da = distribute(a, mesh11, nb=16, diag_pad=1.0)
        lu, gperm = pgetrf(da)
        luh = np.asarray(undistribute(lu))
        gp = np.asarray(gperm)
        l = np.tril(luh, -1) + np.eye(48)
        u = np.triu(luh)
        np.testing.assert_allclose(a[gp[:48]], l @ u, rtol=1e-10, atol=1e-10)


class TestPgesv:
    @pytest.mark.parametrize("n,nrhs,nb", [(96, 16, 16), (100, 7, 16)])
    def test_residual(self, mesh24, n, nrhs, nb):
        from slate_tpu.parallel import pgesv
        a = _rng(14).standard_normal((n, n))
        b = _rng(15).standard_normal((n, nrhs))
        lu, gperm, x = pgesv(a, b, mesh24, nb=nb)
        xh = np.asarray(undistribute(x))
        res = np.linalg.norm(a @ xh - b) / (
            np.linalg.norm(a) * np.linalg.norm(xh) + np.linalg.norm(b))
        assert res < 3 * np.finfo(np.float64).eps * n

    def test_matches_numpy(self, mesh24):
        from slate_tpu.parallel import pgesv
        a = _rng(16).standard_normal((64, 64))
        b = _rng(17).standard_normal((64, 8))
        _, _, x = pgesv(a, b, mesh24, nb=16)
        np.testing.assert_allclose(np.asarray(undistribute(x)),
                                   np.linalg.solve(a, b), rtol=1e-8, atol=1e-8)


class TestPgeqrf:
    @pytest.mark.parametrize("m,n,nb", [(96, 96, 16), (128, 64, 16), (100, 52, 16)])
    def test_r_matches_numpy(self, mesh24, m, n, nb):
        from slate_tpu.parallel import pgeqrf
        a = _rng(18).standard_normal((m, n))
        da = distribute(a, mesh24, nb=nb, diag_pad=1.0, row_mult=4, col_mult=2)
        qr, tmats, taus = pgeqrf(da)
        rh = np.triu(np.asarray(undistribute(qr)))[:n, :n]
        _, rref = np.linalg.qr(a)
        # R is unique up to column signs
        np.testing.assert_allclose(np.abs(rh), np.abs(rref), rtol=1e-9,
                                   atol=1e-9)

    def test_orthogonality_via_solve(self, mesh24):
        """Q^H applied twice must reproduce norms: check ||Q^H b|| == ||b||."""
        from slate_tpu.parallel import pgeqrf, punmqr_conj
        m, n, nb = 96, 48, 16
        a = _rng(19).standard_normal((m, n))
        b = _rng(20).standard_normal((m, 5))
        da = distribute(a, mesh24, nb=nb, diag_pad=1.0, row_mult=4, col_mult=2)
        qr, tmats, _ = pgeqrf(da)
        db = distribute(b, mesh24, nb=nb, row_mult=4)
        qb = np.asarray(undistribute(punmqr_conj(qr, tmats, db)))
        np.testing.assert_allclose(np.linalg.norm(qb, axis=0),
                                   np.linalg.norm(b, axis=0), rtol=1e-10)


class TestPgels:
    @pytest.mark.parametrize("m,n,nrhs,nb", [(96, 96, 8, 16), (128, 60, 7, 16)])
    def test_matches_lstsq(self, mesh24, m, n, nrhs, nb):
        from slate_tpu.parallel import pgels
        a = _rng(21).standard_normal((m, n))
        b = _rng(22).standard_normal((m, nrhs))
        _, _, x = pgels(a, b, mesh24, nb=nb)
        xh = np.asarray(undistribute(x))
        xref = np.linalg.lstsq(a, b, rcond=None)[0]
        np.testing.assert_allclose(xh, xref, rtol=1e-8, atol=1e-8)

    def test_serial_mesh(self, mesh11):
        from slate_tpu.parallel import pgels
        a = _rng(23).standard_normal((64, 32))
        b = _rng(24).standard_normal((64, 4))
        _, _, x = pgels(a, b, mesh11, nb=16)
        xh = np.asarray(undistribute(x))
        xref = np.linalg.lstsq(a, b, rcond=None)[0]
        np.testing.assert_allclose(xh, xref, rtol=1e-8, atol=1e-8)


class TestPnorm:
    @pytest.mark.parametrize("which,ref", [
        ("Max", lambda a: np.max(np.abs(a))),
        ("One", lambda a: np.linalg.norm(a, 1)),
        ("Inf", lambda a: np.linalg.norm(a, np.inf)),
        ("Fro", lambda a: np.linalg.norm(a, "fro")),
    ])
    def test_matches_numpy(self, mesh24, which, ref):
        from slate_tpu.enums import Norm
        from slate_tpu.parallel import pnorm
        a = _rng(25).standard_normal((100, 52))
        # diag_pad would corrupt unmasked norms; use padded dist with it
        dm = distribute(a, mesh24, nb=16, diag_pad=1.0, row_mult=4, col_mult=2)
        got = float(pnorm(dm, getattr(Norm, which)))
        np.testing.assert_allclose(got, ref(a), rtol=1e-12)


class TestPherk:
    def test_herk_matches(self, mesh24):
        from slate_tpu.parallel import pherk
        a = _rng(26).standard_normal((64, 48)) + 1j * _rng(27).standard_normal((64, 48))
        da = distribute(a, mesh24, nb=16, row_mult=4, col_mult=2)
        c = pherk(1.0, da)
        np.testing.assert_allclose(np.asarray(undistribute(c)), a @ a.conj().T,
                                   rtol=1e-12, atol=1e-12)

    def test_syrk_beta(self, mesh24):
        from slate_tpu.parallel import psyrk
        a = _rng(28).standard_normal((64, 32))
        c0 = _rng(29).standard_normal((64, 64))
        da = distribute(a, mesh24, nb=16, row_mult=4, col_mult=2)
        dc = distribute(c0, mesh24, nb=16, row_mult=4, col_mult=2)
        c = psyrk(2.0, da, beta=-1.0, c=dc)
        np.testing.assert_allclose(np.asarray(undistribute(c)),
                                   2.0 * a @ a.T - c0, rtol=1e-12, atol=1e-12)


class TestPtrsm:
    def test_left_lower_combinations(self, mesh24):
        from slate_tpu.enums import Diag, Op, Side, Uplo
        from slate_tpu.parallel import ptrsm
        n, nrhs, nb = 64, 8, 16
        l = np.tril(_rng(30).standard_normal((n, n))) + n * np.eye(n)
        b = _rng(31).standard_normal((n, nrhs))
        dl = distribute(l, mesh24, nb=nb, diag_pad=1.0, row_mult=4, col_mult=2)
        db = distribute(b, mesh24, nb=nb, row_mult=4)
        x = ptrsm(Side.Left, Uplo.Lower, Op.NoTrans, Diag.NonUnit, dl, db)
        np.testing.assert_allclose(np.asarray(undistribute(x)),
                                   np.linalg.solve(l, b), rtol=1e-10, atol=1e-10)
        x = ptrsm(Side.Left, Uplo.Lower, Op.ConjTrans, Diag.NonUnit, dl, db)
        np.testing.assert_allclose(np.asarray(undistribute(x)),
                                   np.linalg.solve(l.T, b), rtol=1e-10, atol=1e-10)
        # keep off-diagonal mass small: unit-lower solves with O(1) dense
        # entries grow like 2^n and would swamp any solver's accuracy
        lu = np.tril(l, -1) / n + np.eye(n)
        dlu = distribute(lu, mesh24, nb=nb, diag_pad=1.0, row_mult=4, col_mult=2)
        x = ptrsm(Side.Left, Uplo.Lower, Op.NoTrans, Diag.Unit, dlu, db)
        np.testing.assert_allclose(np.asarray(undistribute(x)),
                                   np.linalg.solve(lu, b), rtol=1e-10, atol=1e-10)
        u = np.triu(_rng(32).standard_normal((n, n))) + n * np.eye(n)
        du = distribute(u, mesh24, nb=nb, diag_pad=1.0, row_mult=4, col_mult=2)
        x = ptrsm(Side.Left, Uplo.Upper, Op.NoTrans, Diag.NonUnit, du, db)
        np.testing.assert_allclose(np.asarray(undistribute(x)),
                                   np.linalg.solve(u, b), rtol=1e-10, atol=1e-10)


class TestDistBlas3Extended:
    """pher2k/psyr2k, ptrmm, phemm/psymm (reference src/her2k.cc,
    src/trmm.cc, src/hemm.cc over the mesh)."""

    def test_pher2k_matches(self, mesh24):
        n, k, nb = 64, 48, 16
        rng = _rng(31)
        a = rng.standard_normal((n, k))
        b = rng.standard_normal((n, k))
        from slate_tpu.parallel import pher2k
        da = distribute(a, mesh24, nb=nb, row_mult=4)
        db = distribute(b, mesh24, nb=nb, row_mult=4)
        out = np.asarray(undistribute(pher2k(2.0, da, db)))[:n, :n]
        ref = 2.0 * (a @ b.T) + 2.0 * (b @ a.T)
        np.testing.assert_allclose(out, ref, atol=1e-10)

    def test_psyr2k_beta(self, mesh24):
        n, k, nb = 48, 32, 16
        rng = _rng(32)
        a = rng.standard_normal((n, k))
        b = rng.standard_normal((n, k))
        c = rng.standard_normal((n, n))
        from slate_tpu.parallel import psyr2k
        da = distribute(a, mesh24, nb=nb, row_mult=4)
        db = distribute(b, mesh24, nb=nb, row_mult=4)
        dcm = distribute(c, mesh24, nb=nb, row_mult=4, col_mult=2)
        out = np.asarray(undistribute(psyr2k(1.5, da, db, beta=-1.0,
                                             c=dcm)))[:n, :n]
        ref = 1.5 * (a @ b.T + b @ a.T) - c
        np.testing.assert_allclose(out, ref, atol=1e-10)

    def test_ptrmm(self, mesh24):
        import slate_tpu as st
        n, nrhs, nb = 64, 32, 16
        rng = _rng(33)
        a = np.tril(rng.standard_normal((n, n)))
        b = rng.standard_normal((n, nrhs))
        from slate_tpu.parallel import ptrmm
        # feed the full matrix: ptrmm must only read the triangle
        full = a + np.triu(rng.standard_normal((n, n)), 1)
        da = distribute(full, mesh24, nb=nb, col_mult=2)
        db = distribute(b, mesh24, nb=nb)
        out = np.asarray(undistribute(
            ptrmm(st.Uplo.Lower, st.Diag.NonUnit, da, db)))
        np.testing.assert_allclose(out, a @ b, atol=1e-11)

    def test_ptrmm_unit_diag(self, mesh24):
        import slate_tpu as st
        n, nb = 48, 16
        rng = _rng(34)
        a = np.tril(rng.standard_normal((n, n)), -1) + np.eye(n)
        b = rng.standard_normal((n, 8))
        from slate_tpu.parallel import ptrmm
        # only the strictly-lower part + unit diagonal may be read
        da = distribute(np.triu(rng.standard_normal((n, n)), 1)
                        + np.tril(a, -1), mesh24, nb=nb, col_mult=2)
        db = distribute(b, mesh24, nb=nb)
        out = np.asarray(undistribute(
            ptrmm(st.Uplo.Lower, st.Diag.Unit, da, db)))
        np.testing.assert_allclose(out, a @ b, atol=1e-11)

    def test_phemm(self, mesh24):
        n, nrhs, nb = 64, 16, 16
        rng = _rng(35)
        g = rng.standard_normal((n, n))
        a = (g + g.T) / 2
        b = rng.standard_normal((n, nrhs))
        c = rng.standard_normal((n, nrhs))
        from slate_tpu.parallel import phemm
        da = distribute(a, mesh24, nb=nb, col_mult=2)
        db = distribute(b, mesh24, nb=nb)
        dcm = distribute(c, mesh24, nb=nb)
        out = np.asarray(undistribute(phemm(1.0, da, db, beta=2.0, c=dcm)))
        np.testing.assert_allclose(out, a @ b + 2.0 * c, atol=1e-11)


class TestPgesvMixed:
    """Distributed mixed-precision IR (reference gesv_mixed over ranks)."""

    def test_fp64_result_from_fp32_factor(self, mesh24):
        n, nrhs, nb = 96, 4, 16
        rng = _rng(71)
        a = rng.standard_normal((n, n)) + n * np.eye(n)
        b = rng.standard_normal((n, nrhs))
        from slate_tpu.parallel import pgesv_mixed
        x, iters = pgesv_mixed(a, b, mesh24, nb)
        assert iters >= 0, "distributed mixed solver fell back"
        xv = np.asarray(undistribute(x))
        res = np.linalg.norm(a @ xv - b) / (np.linalg.norm(a)
                                            * np.linalg.norm(xv))
        assert res < 1e-13, f"refined residual {res}"   # fp64-grade

    def test_vector_rhs(self, mesh24):
        n, nb = 64, 16
        rng = _rng(72)
        a = rng.standard_normal((n, n)) + n * np.eye(n)
        b = rng.standard_normal(n)
        from slate_tpu.parallel import pgesv_mixed
        x, iters = pgesv_mixed(a, b, mesh24, nb)
        xv = np.asarray(undistribute(x))[:, 0]
        res = np.linalg.norm(a @ xv - b) / np.linalg.norm(b)
        assert res < 1e-12


class TestRectangularTiles:
    """mb != nb DistMatrix support (reference lambda tile ctor,
    ``BaseMatrix.hh:765-771``) — VERDICT r2 item 10."""

    @pytest.mark.parametrize("m,n,mb,nb", [(90, 70, 32, 16), (64, 96, 8, 24)])
    def test_roundtrip_rect(self, mesh24, m, n, mb, nb):
        rng = np.random.default_rng(40)
        a = rng.standard_normal((m, n))
        dm = distribute(a, mesh24, nb=nb, mb=mb)
        assert dm.row_nb == mb and dm.nb == nb
        assert np.allclose(np.asarray(undistribute(dm)), a)

    def test_pgemm_rect_tiles(self, mesh24):
        """SUMMA with A (mb=32, nb=16), B (mb=16, nb=24): contraction
        tiles match (16), row/col tiles differ."""
        from slate_tpu.parallel.dist_blas3 import pgemm
        rng = np.random.default_rng(41)
        m, k, n = 96, 80, 72
        a = rng.standard_normal((m, k))
        b = rng.standard_normal((k, n))
        p, q = 2, 4
        da = distribute(a, mesh24, nb=16, mb=32, col_mult=p)
        db = distribute(b, mesh24, nb=24, mb=16, row_mult=q)
        dc = pgemm(2.0, da, db)
        assert dc.row_nb == 32 and dc.nb == 24
        assert np.allclose(np.asarray(undistribute(dc)), 2.0 * a @ b,
                           atol=1e-12)

    def test_pgemm_rect_mismatch_raises(self, mesh24):
        rng = np.random.default_rng(42)
        da = distribute(rng.standard_normal((32, 32)), mesh24, nb=16, mb=32)
        db = distribute(rng.standard_normal((32, 32)), mesh24, nb=16, mb=32)
        from slate_tpu.parallel.dist_blas3 import pgemm
        with pytest.raises(ValueError, match="row tiles"):
            pgemm(1.0, da, db)


class TestPgemmA:
    def test_gemm_a_matches_summa(self, mesh8):
        """A-stationary and SUMMA layouts must agree numerically."""
        from slate_tpu.parallel.dist import distribute, undistribute
        from slate_tpu.parallel.dist_blas3 import pgemm, pgemm_a
        rng = np.random.default_rng(11)
        m, k, n, nb = 96, 80, 16, 16
        a = rng.standard_normal((m, k))
        b = rng.standard_normal((k, n))
        da = distribute(jnp.asarray(a), mesh8, nb, col_mult=2)
        db = distribute(jnp.asarray(b), mesh8, nb, row_mult=4)
        want = a @ b
        got_c = np.asarray(undistribute(
            pgemm(1.0, da, db, method="C")))[:m, :n]
        got_a = np.asarray(undistribute(pgemm_a(1.0, da, db)))[:m, :n]
        np.testing.assert_allclose(got_c, want, rtol=0, atol=1e-10)
        np.testing.assert_allclose(got_a, want, rtol=0, atol=1e-10)
        # auto picks A for a single-column-tile B (method.hh:77-126)
        from slate_tpu.parallel.dist_blas3 import select_pgemm
        assert select_pgemm(da, db) == "A"
        wide = distribute(jnp.asarray(rng.standard_normal((k, 96))),
                          mesh8, nb, row_mult=4)
        assert select_pgemm(da, wide) == "C"

    def test_gemm_a_collective_profile(self, mesh8):
        """gemmA must move B/C-sized data only: no collective in its
        lowered HLO may touch an A-sized (m×k) operand, while SUMMA's
        profile does move A panels.  Pins Missing #5 of VERDICT r3 so a
        regression to gather-everything cannot pass silently."""
        import re
        from slate_tpu.parallel.dist import distribute
        from slate_tpu.parallel.dist_blas3 import (_build_pgemm,
                                                   _build_pgemm_a)
        rng = np.random.default_rng(12)
        m, k, n, nb = 1024, 1024, 16, 16
        a = rng.standard_normal((m, k)).astype(np.float32)
        b = rng.standard_normal((k, n)).astype(np.float32)
        da = distribute(jnp.asarray(a), mesh8, nb, col_mult=2)
        db = distribute(jnp.asarray(b), mesh8, nb, row_mult=4)
        from slate_tpu.parallel.dist_blas3 import pgemm_a, pgemm
        alpha = jnp.asarray(1.0, da.dtype)
        lowered = jax.jit(
            lambda x, y, z: pgemm_a(1.0, type(da)(x, da.m, da.n, da.nb,
                                                  da.mesh),
                                    type(db)(y, db.m, db.n, db.nb,
                                             db.mesh)).data
        ).lower(da.data, db.data, jnp.zeros(())).as_text()
        # every collective shape in the gemmA program must be
        # B/C-sized: fewer elements than one A shard
        a_shard_elems = (da.data.shape[0] // 2) * (da.data.shape[1] // 4)
        coll_lines = [
            ln for ln in lowered.splitlines()
            if re.search(r"stablehlo\.(all_reduce|all_gather|"
                         r"collective_permute|reduce_scatter)", ln)]
        assert coll_lines, "expected collectives in the lowered gemmA"
        for ln in coll_lines:
            for dims in re.findall(r"tensor<([0-9x]+)xf32>", ln):
                elems = int(np.prod([int(d) for d in dims.split("x")]))
                assert elems < a_shard_elems, \
                    f"gemmA moved an A-sized array: tensor<{dims}>"


class TestCollectiveProfiles:
    """Pin the lowered collective profile of one driver per family so a
    silent regression to gather-and-compute-locally fails CI (VERDICT
    r3 Next #10).  A gather-everything implementation needs an
    all-gather whose result is the FULL matrix on every device; the
    real SPMD drivers only ever materialize panel-sized collectives."""

    def _collective_shapes(self, lowered: str):
        """Collective result sizes from StableHLO (shard_map programs)
        or post-SPMD HLO (jit-with-shardings programs).  StableHLO
        parsing rides :mod:`slate_tpu.perf.hlo_profile` — all_reduce
        prints as a multi-line region, which a line-based scan misses."""
        import re

        from slate_tpu.perf.hlo_profile import (profile_hlo_text,
                                                stablehlo_collective_shapes)
        shapes = [elems for _, elems
                  in stablehlo_collective_shapes(lowered)]
        if shapes or "stablehlo" in lowered:
            return shapes
        prof = profile_hlo_text(lowered)
        return [op.elems for op in prof.all_collectives]

    def _assert_no_full_gather(self, lowered, full_elems, label):
        shapes = self._collective_shapes(lowered)
        assert shapes, f"{label}: expected collectives in the program"
        biggest = max(shapes)
        assert biggest < full_elems, \
            f"{label}: a collective materializes the full matrix " \
            f"({biggest} >= {full_elems} elements)"

    def test_pgetrf_profile(self, mesh8):
        from slate_tpu.parallel.dist_lu import _build_pgetrf
        n, nb = 256, 16
        p, q = 2, 4
        nt = n // nb
        fn = _build_pgetrf(mesh8, nb, nt, nt // p, nt // q, "float64")
        data = jnp.zeros((n, n), jnp.float64)
        lowered = jax.jit(fn).lower(data).as_text()
        self._assert_no_full_gather(lowered, n * n, "pgetrf")

    def test_pgeqrf_profile(self, mesh8):
        from slate_tpu.parallel.dist import distribute
        from slate_tpu.parallel.dist_qr import pgeqrf
        n, nb = 256, 16
        rng = np.random.default_rng(0)
        da = distribute(jnp.asarray(rng.standard_normal((n, n))),
                        mesh8, nb, row_mult=4, col_mult=2)

        def run(x):
            import dataclasses
            dm = dataclasses.replace(da, data=x)
            fac = pgeqrf(dm)
            return fac[0].data if isinstance(fac, tuple) else fac.data

        lowered = jax.jit(run).lower(da.data).as_text()
        self._assert_no_full_gather(lowered, n * n, "pgeqrf")

    def test_pstedc_merge_profile(self, mesh8):
        """The distributed stedc merge gemms must shard: no collective
        may carry the full (n, n) combine operand."""
        from slate_tpu.parallel.dist_stedc import _combine, _shard_rows
        n = 512
        q1 = jax.device_put(
            jnp.zeros((n // 2, n // 2)),
            jax.sharding.NamedSharding(
                mesh8, jax.sharding.PartitionSpec(('p', 'q'), None)))
        q2 = jax.device_put(jnp.zeros((n // 2, n // 2)), q1.sharding)
        r = jax.device_put(
            jnp.zeros((n, n)),
            jax.sharding.NamedSharding(
                mesh8, jax.sharding.PartitionSpec(('p', 'q'), None)))
        lowered = jax.jit(
            lambda a, b, c: _shard_rows(_combine(a, b, c), mesh8)
        ).lower(q1, q2, r).compile().as_text()
        # row-sharded gemms against a row-sharded R need column-space
        # collectives but must never all-GATHER an n x n operand.  The
        # contraction dim is sharded, so an all-REDUCE of the product is
        # inherent (GSPMD may emit it at the concatenated (n, n) shape —
        # same bytes as two (n/2, n) reduces); only a gather at full
        # size would mean gather-everything-and-compute-locally.
        from slate_tpu.perf.hlo_profile import profile_hlo_text
        prof = profile_hlo_text(lowered)
        ops = prof.all_collectives
        assert ops, "pstedc merge: expected collectives in the program"
        gathers = [op.elems for op in ops if op.kind == "all-gather"]
        assert max(gathers, default=0) < n * n, \
            "pstedc merge: an all-gather materializes the full matrix"
