"""Batched many-problem drivers (slate_tpu/linalg/batched.py) + the
grid-batched Pallas kernels + the shared VMEM budget helper.

Parity contract (ISSUE 8): the batched drivers must be BITWISE equal to
a Python loop of the composed single-problem functions they vmap (vmap
reorders nothing on CPU), and residual-gated against scipy; the
grid-batched Pallas path (forced through SLATE_TPU_AUTOTUNE_FORCE in
interpret mode) must match scipy pivots exactly and pass the same
residual gates, with EXACTLY ONE pallas_call per launch (jaxpr census).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import scipy.linalg as sla

from slate_tpu.linalg import batched
from slate_tpu.perf import autotune

BATCHES = (1, 7, 64)
DTYPES = (np.float32, np.float64)


@pytest.fixture(autouse=True)
def _fresh_table(tmp_path, monkeypatch):
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    autotune.reset_table()
    yield
    autotune.reset_table()


def _spd_batch(b, n, dtype, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((b, n, n)).astype(dtype)
    return np.einsum("bij,bkj->bik", g, g) + n * np.eye(n, dtype=dtype)


def _gen_batch(b, n, dtype, seed=1):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((b, n, n)).astype(dtype)
            + n * np.eye(n, dtype=dtype))


def _eps(dtype):
    return float(np.finfo(dtype).eps)


class TestVmappedLoopedParity:
    """The vmapped-composed backend must be bitwise the loop of the
    single-problem composed function it vmaps."""

    @pytest.mark.parametrize("b", BATCHES)
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_potrf_bitwise(self, b, dtype):
        n = 32
        spd = jnp.asarray(_spd_batch(b, n, dtype))
        got = np.asarray(batched.potrf_batched(spd))
        want = np.stack([np.asarray(batched._potrf_single_composed(spd[i]))
                         for i in range(b)])
        assert np.array_equal(got, want)

    @pytest.mark.parametrize("b", BATCHES)
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_getrf_bitwise(self, b, dtype):
        n = 32
        a = jnp.asarray(_gen_batch(b, n, dtype))
        lu, perm = batched.getrf_batched(a)
        for i in range(b):
            lu1, perm1 = batched._getrf_single_composed(a[i])
            assert np.array_equal(np.asarray(lu[i]), np.asarray(lu1))
            assert np.array_equal(np.asarray(perm[i]), np.asarray(perm1))

    @pytest.mark.parametrize("shape", [(48, 48), (96, 32)])
    def test_geqrf_bitwise_square_and_tall(self, shape):
        m, n = shape
        rng = np.random.default_rng(3)
        a = jnp.asarray(rng.standard_normal((7, m, n)).astype(np.float32))
        pk, taus = batched.geqrf_batched(a)
        for i in range(7):
            pk1, taus1 = batched._geqrf_single_composed(a[i])
            assert np.array_equal(np.asarray(pk[i]), np.asarray(pk1))
            assert np.array_equal(np.asarray(taus[i]), np.asarray(taus1))


class TestResidualVsScipy:
    @pytest.mark.parametrize("b", BATCHES)
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_potrf(self, b, dtype):
        n = 48
        spd = _spd_batch(b, n, dtype)
        l = np.asarray(batched.potrf_batched(jnp.asarray(spd)))
        for i in range(b):
            ref = sla.cholesky(spd[i], lower=True)
            r = (np.linalg.norm(l[i] @ l[i].T - spd[i])
                 / (np.linalg.norm(spd[i]) * _eps(dtype) * n))
            assert r < 3, (i, r)
            assert np.allclose(l[i], ref,
                               atol=100 * _eps(dtype) * np.abs(ref).max())

    @pytest.mark.parametrize("b", BATCHES)
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_gesv(self, b, dtype):
        n = 48
        a = _gen_batch(b, n, dtype)
        rng = np.random.default_rng(5)
        rhs = rng.standard_normal((b, n)).astype(dtype)
        lu, perm, x = batched.gesv_batched(jnp.asarray(a),
                                           jnp.asarray(rhs))
        x = np.asarray(x)
        for i in range(b):
            ref = sla.solve(a[i], rhs[i])
            r = (np.linalg.norm(a[i] @ x[i] - rhs[i])
                 / (np.linalg.norm(a[i]) * np.linalg.norm(rhs[i])
                    * _eps(dtype) * n))
            assert r < 3, (i, r)
            assert np.allclose(x[i], ref, atol=1e-2 if dtype == np.float32
                               else 1e-8)

    @pytest.mark.parametrize("shape", [(48, 48), (96, 32)])
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_gels_square_and_tall(self, shape, dtype):
        m, n = shape
        b = 7
        rng = np.random.default_rng(6)
        a = rng.standard_normal((b, m, n)).astype(dtype)
        rhs = rng.standard_normal((b, m)).astype(dtype)
        x = np.asarray(batched.gels_batched(jnp.asarray(a),
                                            jnp.asarray(rhs)))
        for i in range(b):
            ref = sla.lstsq(a[i], rhs[i])[0]
            # normal-equations residual, the reference tester's gate
            r = (np.linalg.norm(a[i].T @ (a[i] @ x[i] - rhs[i]))
                 / (np.linalg.norm(a[i]) ** 2 * np.linalg.norm(x[i])
                    * _eps(dtype) * np.sqrt(m)))
            assert r < 3, (i, r)
            assert np.allclose(x[i], ref, atol=1e-2 if dtype == np.float32
                               else 1e-7)

    @pytest.mark.parametrize("b", (1, 7))
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_heev(self, b, dtype):
        n = 32
        rng = np.random.default_rng(20)
        g = rng.standard_normal((b, n, n)).astype(dtype)
        a = 0.5 * (g + np.swapaxes(g, -1, -2))
        w, z = batched.heev_batched(jnp.asarray(a))
        w, z = np.asarray(w), np.asarray(z)
        for i in range(b):
            assert (np.diff(w[i]) >= 0).all(), "eigenvalues not ascending"
            r = (np.linalg.norm(a[i] @ z[i] - z[i] * w[i])
                 / (np.linalg.norm(a[i]) * _eps(dtype) * n))
            assert r < 3, (i, r)
            orth = (np.linalg.norm(z[i].T @ z[i] - np.eye(n))
                    / (_eps(dtype) * n))
            assert orth < 3, (i, orth)
            ref = sla.eigvalsh(a[i].astype(np.float64))
            assert np.allclose(w[i], ref, atol=100 * _eps(dtype)
                               * np.abs(ref).max())

    @pytest.mark.parametrize("b", BATCHES)
    def test_posv_rhs_matrix(self, b):
        n, k = 32, 3
        spd = _spd_batch(b, n, np.float64)
        rng = np.random.default_rng(7)
        rhs = rng.standard_normal((b, n, k))
        l, x = batched.posv_batched(jnp.asarray(spd), jnp.asarray(rhs))
        x = np.asarray(x)
        for i in range(b):
            assert np.allclose(spd[i] @ x[i], rhs[i], atol=1e-8)


class TestGridBatchedPallas:
    """The grid-batched Pallas kernels, forced in interpret mode."""

    @pytest.mark.parametrize("b", (1, 4))
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_potrf_grid_forced(self, b, dtype, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                           "batched_potrf=grid")
        n = 64
        spd = _spd_batch(b, n, dtype)
        l = np.asarray(batched.potrf_batched(jnp.asarray(spd)))
        key = [k for k in autotune.decisions()
               if k.startswith("batched_potrf|")]
        assert key and autotune.decisions()[key[0]] == "grid"
        for i in range(b):
            r = (np.linalg.norm(l[i] @ l[i].T - spd[i])
                 / (np.linalg.norm(spd[i]) * _eps(np.float32) * n))
            assert r < 3, (i, r)

    @pytest.mark.parametrize("b", (1, 4))
    @pytest.mark.parametrize("dtype", DTYPES)
    def test_getrf_grid_forced_scipy_pivot_parity(self, b, dtype,
                                                  monkeypatch):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "batched_lu=grid")
        from slate_tpu.linalg.lu import ipiv_to_perm
        n = 64
        a = _gen_batch(b, n, dtype)
        lu, perm = batched.getrf_batched(jnp.asarray(a))
        lu, perm = np.asarray(lu), np.asarray(perm)
        for i in range(b):
            lu_ref, piv_ref = sla.lu_factor(a[i])
            perm_ref = np.asarray(ipiv_to_perm(piv_ref + 1, n))
            assert np.array_equal(perm[i], perm_ref), i
            tol = 1e-3 if dtype == np.float32 else 1e-10
            assert np.abs(lu[i] - lu_ref).max() < tol * np.abs(
                lu_ref).max(), i

    def test_grid_launch_census_one_pallas_call(self, monkeypatch):
        """Exactly 1 pallas_call per grid-batched launch — the
        many-problems-per-launch claim, pinned via the jaxpr census."""
        from slate_tpu.perf.hlo_profile import count_pallas_calls
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                           "batched_potrf=grid,batched_lu=grid")
        spd = jnp.asarray(_spd_batch(4, 64, np.float32))
        assert count_pallas_calls(batched.potrf_batched, spd) == 1
        a = jnp.asarray(_gen_batch(4, 64, np.float32))
        assert count_pallas_calls(
            lambda x: batched.getrf_batched(x)[0], a) == 1

    def test_grid_ineligible_shapes_fall_back(self):
        # n not on the ib=32 grid → vmapped, and the decision records
        a = jnp.asarray(_gen_batch(2, 48, np.float32))
        lu, perm = batched.getrf_batched(a)
        key = [k for k in autotune.decisions()
               if k.startswith("batched_lu|")]
        assert key and autotune.decisions()[key[0]] == "vmapped"


class TestBucketedKeys:
    def test_pow2_bucketing_batch_and_n(self):
        """One decision serves the whole (B, n) bucket: 60- and 64-batch
        calls at n 224/256 must share a key."""
        batched.potrf_batched(jnp.asarray(_spd_batch(60, 224, np.float32)))
        batched.potrf_batched(jnp.asarray(_spd_batch(64, 256, np.float32)))
        keys = {k for k in autotune.decisions()
                if k.startswith("batched_potrf|")}
        assert len(keys) == 1, keys
        assert keys.pop().startswith("batched_potrf|64,256,")


class TestVmemBudgetHelper:
    """The shared VMEM budget arithmetic (slate_tpu/ops/vmem.py) — one
    helper, reused by the single-problem fused gates AND the batched
    B-per-launch gates instead of copy-pasted constants."""

    def test_defaults_and_fits(self):
        from slate_tpu.ops import vmem
        assert vmem.budget_bytes() == vmem.BUDGET_BYTES
        assert vmem.fits(vmem.BUDGET_BYTES)
        assert not vmem.fits(vmem.BUDGET_BYTES + 1)

    def test_env_override_moves_every_gate(self, monkeypatch):
        from slate_tpu.ops import vmem
        monkeypatch.setenv("SLATE_TPU_VMEM_BUDGET_MB", "1")
        assert vmem.budget_bytes() == 1024 * 1024
        # the batched gate shrinks with the budget
        assert vmem.batch_per_launch(3 * 256 * 256 * 4) == 1
        assert vmem.batch_per_launch(3 * 1024 * 1024 * 4) == 0

    def test_batch_per_launch(self):
        from slate_tpu.ops import vmem
        per = 3 * 256 * 256 * 4
        bt = vmem.batch_per_launch(per)
        assert bt == vmem.BUDGET_BYTES // per
        assert vmem.batch_per_launch(per, cap=4) == 4
        assert vmem.batch_per_launch(0, cap=9) == 9
        # fixed overhead eats into the budget
        assert vmem.batch_per_launch(per,
                                     fixed_bytes=vmem.BUDGET_BYTES) == 0

    def test_grid_bt_divides_batch(self):
        assert batched._grid_bt(64, 256) >= 1
        for b in (1, 7, 64):
            bt = batched._grid_bt(b, 128)
            assert bt >= 1 and b % bt == 0

    def test_single_problem_gates_still_consistent(self):
        """The refactored fused-step gates must agree with the budget
        helper (regression for the shared-constant extraction)."""
        from slate_tpu.linalg import lu as lumod
        from slate_tpu.ops import blocks, vmem
        tc = lumod._fused_step_tc(8192, 8192, 512)
        assert tc >= 128 and 512 % tc == 0
        assert vmem.fits(lumod._fused_step_bytes(8192, 512, tc))
        tc2 = blocks.potrf_step_tc(8192, 512)
        assert tc2 >= 128 and 512 % tc2 == 0
        assert vmem.fits(blocks._potrf_step_bytes(8192, 512, tc2))


class TestBatchedBenchRoutines:
    def test_bench_batched_posv_families(self):
        bench = pytest.importorskip("bench")
        label, gf, resid, extra = bench.bench_batched_posv(
            False, nbat=48, bsz=8)
        assert label == "posv_batched_fp32_n48_b8"
        assert gf > 0 and resid < 3
        assert set(extra) == {
            "posv_batched_fp32_n48_b8_solves_per_s",
            "posv_loop_fp32_n48_solves_per_s",
            "posv_batched_fp32_n48_b8_speedup_vs_loop"}
        assert extra["posv_batched_fp32_n48_b8_solves_per_s"] > 0


class TestSimplifiedBatchedVerbs:
    def test_verbs_forward_to_batched_drivers(self):
        from slate_tpu.api import simplified as S
        rng = np.random.default_rng(8)
        b, n = 3, 32
        spd = jnp.asarray(_spd_batch(b, n, np.float64))
        rhs = jnp.asarray(rng.standard_normal((b, n)))
        x = np.asarray(S.chol_solve_batched(spd, rhs))
        assert np.allclose(np.einsum("bij,bj->bi", np.asarray(spd), x),
                           np.asarray(rhs), atol=1e-8)
        a = jnp.asarray(_gen_batch(b, n, np.float64))
        x2 = np.asarray(S.lu_solve_batched(a, rhs))
        assert np.allclose(np.einsum("bij,bj->bi", np.asarray(a), x2),
                           np.asarray(rhs), atol=1e-8)
        lu, perm = S.lu_factor_batched(a)
        assert lu.shape == (b, n, n) and perm.shape == (b, n)
        l = S.chol_factor_batched(spd)
        assert l.shape == (b, n, n)
        tall = jnp.asarray(rng.standard_normal((b, 2 * n, n)))
        assert S.least_squares_solve_batched(tall, jnp.asarray(
            rng.standard_normal((b, 2 * n)))).shape == (b, n)
        pk, taus = S.qr_factor_batched(tall)
        assert pk.shape == (b, 2 * n, n) and taus.shape == (b, n)
