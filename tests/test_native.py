"""Native host-runtime tests — mirroring the reference unit tests
``unit_test/test_Memory.cc`` (pool), the ``scalapack_api`` marshaling,
``test_Tile.cc`` layout conversion, and the HostTask driver checks."""

import pathlib

import numpy as np
import pytest

from slate_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native runtime unavailable: {native.build_error()}")


def test_memory_pool_reuse():
    pool = native.MemoryPool(64 * 64 * 8)
    b1 = pool.alloc()
    b2 = pool.alloc()
    assert b1 != b2 and pool.num_allocated == 2
    pool.free(b1)
    assert pool.num_free == 1
    assert pool.alloc() == b1          # LIFO reuse like Memory.cc stacks
    assert pool.num_free == 0
    pool.free(b1)
    pool.free(b2)
    pool.close()


def test_numroc():
    # ScaLAPACK numroc oracle values
    assert native.numroc(100, 16, 0, 2) == 52
    assert native.numroc(100, 16, 1, 2) == 48
    assert native.numroc(10, 3, 2, 4) == 3
    assert sum(native.numroc(37, 5, r, 3) for r in range(3)) == 37


@pytest.mark.parametrize("m,n,mb,nb,p,q", [
    (37, 23, 8, 5, 2, 3), (64, 64, 16, 16, 2, 2), (10, 90, 3, 32, 3, 1)])
def test_scalapack_pack_roundtrip(m, n, mb, nb, p, q):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((m, n))
    lg = [[native.scalapack_pack(a, mb, nb, p, q, pr, pc)
           for pc in range(q)] for pr in range(p)]
    for pr in range(p):
        for pc in range(q):
            assert lg[pr][pc].shape == (native.numroc(m, mb, pr, p),
                                        native.numroc(n, nb, pc, q))
    back = native.scalapack_unpack(lg, m, n, mb, nb, p, q)
    assert np.abs(back - a).max() == 0


def test_batch_transpose():
    rng = np.random.default_rng(1)
    t = rng.standard_normal((5, 33, 17))
    tt = native.batch_transpose(t)
    assert np.abs(tt - t.transpose(0, 2, 1)).max() == 0


def test_host_potrf():
    rng = np.random.default_rng(2)
    n = 300
    s = rng.standard_normal((n, n))
    s = s @ s.T + n * np.eye(n)
    l = native.host_potrf(s, nb=64)
    assert np.abs(l @ l.T - s).max() < 1e-11 * n
    with pytest.raises(np.linalg.LinAlgError):
        native.host_potrf(-np.eye(8), nb=4)


def test_host_gemm():
    rng = np.random.default_rng(3)
    a = rng.standard_normal((130, 70))
    b = rng.standard_normal((70, 90))
    c = rng.standard_normal((130, 90))
    out = native.host_gemm(a, b, nb=32, alpha=2.0, beta=-1.0, c=c)
    assert np.abs(out - (2 * a @ b - c)).max() < 1e-12 * 70


class TestHostSolvers:
    def test_host_potrs(self):
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        rng = np.random.default_rng(40)
        n = 96
        g = rng.standard_normal((n, n))
        a = g @ g.T + n * np.eye(n)
        b = rng.standard_normal((n, 5))
        l = native.host_potrf(a, nb=32)
        x = native.host_potrs(l, b, nb=32)
        np.testing.assert_allclose(a @ x, b, rtol=1e-9, atol=1e-9)

    def test_host_gesv(self):
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        rng = np.random.default_rng(41)
        n = 64
        a = rng.standard_normal((n, n)) + n * np.eye(n)
        b = rng.standard_normal((n, 3))
        x, ipiv = native.host_gesv(a, b)
        np.testing.assert_allclose(a @ x, b, rtol=1e-9, atol=1e-9)

    def test_c_header_compiles_and_runs(self, tmp_path):
        """Compile the C smoke example against include/slate_tpu.h and run
        it — the reference's lapack_api/example_dgetrf.c smoke test."""
        import shutil
        import subprocess
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        if shutil.which("gcc") is None:
            pytest.skip("no gcc")
        root = pathlib.Path(__file__).resolve().parents[1]
        so_dir = root / "slate_tpu" / "native"
        exe = tmp_path / "c_smoke"
        r = subprocess.run(
            ["gcc", str(root / "examples" / "c_api_smoke.c"),
             "-I" + str(root / "include"),
             str(so_dir / "_slate_host.so"),
             "-Wl,-rpath," + str(so_dir), "-lm", "-o", str(exe)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        out = subprocess.run([str(exe)], capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "ok: C API smoke" in out.stdout
