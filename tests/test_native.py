"""Native host-runtime tests — mirroring the reference unit tests
``unit_test/test_Memory.cc`` (pool), the ``scalapack_api`` marshaling,
``test_Tile.cc`` layout conversion, and the HostTask driver checks."""

import pathlib

import numpy as np
import pytest

from slate_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native runtime unavailable: {native.build_error()}")


def test_memory_pool_reuse():
    pool = native.MemoryPool(64 * 64 * 8)
    b1 = pool.alloc()
    b2 = pool.alloc()
    assert b1 != b2 and pool.num_allocated == 2
    pool.free(b1)
    assert pool.num_free == 1
    assert pool.alloc() == b1          # LIFO reuse like Memory.cc stacks
    assert pool.num_free == 0
    pool.free(b1)
    pool.free(b2)
    pool.close()


def test_numroc():
    # ScaLAPACK numroc oracle values
    assert native.numroc(100, 16, 0, 2) == 52
    assert native.numroc(100, 16, 1, 2) == 48
    assert native.numroc(10, 3, 2, 4) == 3
    assert sum(native.numroc(37, 5, r, 3) for r in range(3)) == 37


@pytest.mark.parametrize("m,n,mb,nb,p,q", [
    (37, 23, 8, 5, 2, 3), (64, 64, 16, 16, 2, 2), (10, 90, 3, 32, 3, 1)])
def test_scalapack_pack_roundtrip(m, n, mb, nb, p, q):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((m, n))
    lg = [[native.scalapack_pack(a, mb, nb, p, q, pr, pc)
           for pc in range(q)] for pr in range(p)]
    for pr in range(p):
        for pc in range(q):
            assert lg[pr][pc].shape == (native.numroc(m, mb, pr, p),
                                        native.numroc(n, nb, pc, q))
    back = native.scalapack_unpack(lg, m, n, mb, nb, p, q)
    assert np.abs(back - a).max() == 0


def test_batch_transpose():
    rng = np.random.default_rng(1)
    t = rng.standard_normal((5, 33, 17))
    tt = native.batch_transpose(t)
    assert np.abs(tt - t.transpose(0, 2, 1)).max() == 0


def test_host_potrf():
    rng = np.random.default_rng(2)
    n = 300
    s = rng.standard_normal((n, n))
    s = s @ s.T + n * np.eye(n)
    l = native.host_potrf(s, nb=64)
    assert np.abs(l @ l.T - s).max() < 1e-11 * n
    with pytest.raises(np.linalg.LinAlgError):
        native.host_potrf(-np.eye(8), nb=4)


def test_host_gemm():
    rng = np.random.default_rng(3)
    a = rng.standard_normal((130, 70))
    b = rng.standard_normal((70, 90))
    c = rng.standard_normal((130, 90))
    out = native.host_gemm(a, b, nb=32, alpha=2.0, beta=-1.0, c=c)
    assert np.abs(out - (2 * a @ b - c)).max() < 1e-12 * 70


class TestHostSolvers:
    def test_host_potrs(self):
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        rng = np.random.default_rng(40)
        n = 96
        g = rng.standard_normal((n, n))
        a = g @ g.T + n * np.eye(n)
        b = rng.standard_normal((n, 5))
        l = native.host_potrf(a, nb=32)
        x = native.host_potrs(l, b, nb=32)
        np.testing.assert_allclose(a @ x, b, rtol=1e-9, atol=1e-9)

    def test_host_gesv(self):
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        rng = np.random.default_rng(41)
        n = 64
        a = rng.standard_normal((n, n)) + n * np.eye(n)
        b = rng.standard_normal((n, 3))
        x, ipiv = native.host_gesv(a, b)
        np.testing.assert_allclose(a @ x, b, rtol=1e-9, atol=1e-9)

    def test_c_header_compiles_and_runs(self, tmp_path):
        """Compile the C smoke example against include/slate_tpu.h and run
        it — the reference's lapack_api/example_dgetrf.c smoke test."""
        import shutil
        import subprocess
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        if shutil.which("gcc") is None:
            pytest.skip("no gcc")
        root = pathlib.Path(__file__).resolve().parents[1]
        so_dir = root / "slate_tpu" / "native"
        exe = tmp_path / "c_smoke"
        r = subprocess.run(
            ["gcc", str(root / "examples" / "c_api_smoke.c"),
             "-I" + str(root / "include"),
             str(so_dir / "_slate_host.so"),
             "-Wl,-rpath," + str(so_dir), "-lm", "-o", str(exe)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        out = subprocess.run([str(exe)], capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "ok: C API smoke" in out.stdout


class TestStage2:
    """Compiled band→tridiag/bidiag bulge chase + back-transforms
    (``slate_hb2st_* / slate_tb2bd_* / slate_apply_rot_* / slate_bdsdc``)."""

    @pytest.mark.parametrize("dtype", [np.float64, np.complex128])
    @pytest.mark.parametrize("n,kd", [(37, 5), (64, 8), (97, 16)])
    def test_hb2st_matches_spectrum_and_vectors(self, dtype, n, kd):
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        from slate_tpu.linalg import eig as E
        rng = np.random.default_rng(7)
        a = rng.standard_normal((n, n))
        if np.issubdtype(np.dtype(dtype), np.complexfloating):
            a = a + 1j * rng.standard_normal((n, n))
        a = (a + a.conj().T)
        dm = np.subtract.outer(np.arange(n), np.arange(n))
        band = np.where(np.abs(dm) <= kd, a, 0).astype(dtype)
        d, e, rots = E._hb2st_native(band, kd)
        assert rots.kd == min(kd, n - 1)
        w, ztri = E._tridiag_solve(d, e, True, "stevd")
        assert np.allclose(np.sort(w), np.linalg.eigvalsh(band), atol=1e-10)
        zb = E.unmtr_hb2st(rots, ztri)
        r = np.linalg.norm(band @ zb - zb * w[None, :])
        assert r < 1e-10 * n

    def test_hb2st_values_only_skips_log(self):
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        from slate_tpu.linalg import eig as E
        rng = np.random.default_rng(9)
        n, kd = 50, 6
        a = rng.standard_normal((n, n)); a = a + a.T
        dm = np.subtract.outer(np.arange(n), np.arange(n))
        band = np.where(np.abs(dm) <= kd, a, 0)
        d, e, rots = E._hb2st_native(band, kd, want_rots=False)
        assert len(rots.planes) == 0
        from scipy.linalg import eigvalsh_tridiagonal
        w = eigvalsh_tridiagonal(d, e)
        assert np.allclose(np.sort(w), np.linalg.eigvalsh(band), atol=1e-10)

    @pytest.mark.parametrize("dtype", [np.float64, np.complex128])
    def test_tb2bd_bdsdc_roundtrip(self, dtype):
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        import importlib
        from slate_tpu.enums import Side
        S = importlib.import_module("slate_tpu.linalg.svd")
        rng = np.random.default_rng(11)
        n, kd = 61, 7
        a = rng.standard_normal((n, n))
        if np.issubdtype(np.dtype(dtype), np.complexfloating):
            a = a + 1j * rng.standard_normal((n, n))
        dm = np.subtract.outer(np.arange(n), np.arange(n))
        bu = np.where((dm <= 0) & (dm >= -kd), a, 0).astype(dtype)
        d, e, rots = S._tb2bd_native(bu.copy(), kd)
        u_bd, s, vh_bd = native.bdsdc(d, e)
        assert np.allclose(np.sort(s),
                           np.sort(np.linalg.svd(bu, compute_uv=False)),
                           atol=1e-10)
        u2 = S.unmbr_tb2bd(Side.Left, rots, np.ascontiguousarray(u_bd))
        v2 = S.unmbr_tb2bd(Side.Right, rots,
                           np.ascontiguousarray(vh_bd.conj().T))
        rec = u2 @ np.diag(s) @ v2.conj().T
        assert np.linalg.norm(rec - bu) / np.linalg.norm(bu) < 1e-12

    def test_rot_count_matches_kernel(self):
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        from slate_tpu.linalg import eig as E
        rng = np.random.default_rng(13)
        for n, kd in [(11, 2), (30, 29), (40, 3)]:
            a = rng.standard_normal((n, n)); a = a + a.T
            dm = np.subtract.outer(np.arange(n), np.arange(n))
            band = np.where(np.abs(dm) <= kd, a, 0)
            d, e, rots = E._hb2st_native(band, kd)
            # capacity formula agreed with the C++ loop (asserted inside);
            # spectrum preserved
            assert np.allclose(
                np.sort(np.linalg.eigvalsh(band)),
                np.sort(np.linalg.eigvalsh(
                    np.diag(d) + np.diag(e, 1) + np.diag(e, -1))),
                atol=1e-9)


def test_scalapack_api_smoke(tmp_path):
    """Build + run the drop-in ScaLAPACK API smoke: pdpotrf_/pdgesv_/
    pdgemm_ round-trip a 2x2-grid block-cyclic layout through the
    single-controller BLACS emulation (reference
    scalapack_api/scalapack_potrf.cc:27-80)."""
    import os
    import shutil
    import subprocess
    import sys
    import sysconfig
    if shutil.which("gcc") is None:
        pytest.skip("no gcc")
    root = pathlib.Path(__file__).resolve().parents[1]
    inc = sysconfig.get_paths()["include"]
    cfg = f"python3.{sys.version_info.minor}-config"
    if shutil.which(cfg) is None:
        cfg = "python3-config"
    if shutil.which(cfg) is None:
        pytest.skip("no python3-config on PATH")
    ldflags = subprocess.run(
        [cfg, "--ldflags", "--embed"],
        capture_output=True, text=True).stdout.split()
    exe = tmp_path / "scal_smoke"
    r = subprocess.run(
        ["gcc", str(root / "examples" / "scalapack_smoke.c"),
         str(root / "src" / "c_api" / "c_api_core.c"),
         str(root / "src" / "c_api" / "driver_api.c"),
         str(root / "src" / "c_api" / "scalapack_api.c"),
         "-I" + str(root / "include"), "-I" + inc]
        + ldflags + ["-O2", "-lm", "-o", str(exe)],
        capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip("embed build unavailable: " + r.stderr[-500:])
    env = dict(os.environ)
    env["PYTHONPATH"] = str(root) + ":" + ":".join(
        p for p in sys.path if p)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    out = subprocess.run([str(exe)], capture_output=True, text=True,
                         env=env, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ok: ScaLAPACK API smoke" in out.stdout
