"""Device-truth profiling (slate_tpu/perf/xprof.py, ISSUE 19): trace
parsing against a canned XProf trace-event fixture — stage-vocabulary
bucketing, the innermost-wins kernel→annotation join, the annotation
fallback rung — plus the artifact round trip through ``load_profile``,
the HBM high-water window semantics, the measured sweep signals, the
``attr.attribute`` / ``dist_util.overlap_summary`` compute-source
ladder rungs, and the no-op contract with the knob unset.  The REAL
capture (jax.profiler on CPU) lives in ``run_tests.py --xprof`` and a
slow-tier test here."""

import gzip
import importlib.util
import json
import os
import sys

import pytest

from slate_tpu.perf import attr, xprof


# ---------------------------------------------------------------------------
# Canned trace fixture: one getrf with panel/update kernels, a pivot
# annotation no kernel lands in, a driver catch-all, and host/infra
# events the parser must skip.
# ---------------------------------------------------------------------------

_EVENTS = [
    {"ph": "M", "pid": 1, "name": "process_name",
     "args": {"name": "/device:TPU:0 (pid 1)"}},
    {"ph": "M", "pid": 1, "tid": 7, "name": "thread_name",
     "args": {"name": "XLA Op"}},
    {"ph": "M", "pid": 2, "name": "process_name",
     "args": {"name": "python"}},
    {"ph": "M", "pid": 2, "tid": 3, "name": "thread_name",
     "args": {"name": "main"}},
    # annotation spans (host lane, repo vocabulary; ts/dur in us)
    {"ph": "X", "pid": 2, "tid": 3, "name": "driver.getrf",
     "ts": 0, "dur": 5000},
    {"ph": "X", "pid": 2, "tid": 3, "name": "step.getrf.panel",
     "ts": 0, "dur": 1000},
    {"ph": "X", "pid": 2, "tid": 3, "name": "step.getrf.update",
     "ts": 1000, "dur": 2000},
    {"ph": "X", "pid": 2, "tid": 3, "name": "step.getrf.pivot",
     "ts": 3000, "dur": 500},
    # device kernels
    {"ph": "X", "pid": 1, "tid": 7, "name": "fusion.1",
     "ts": 100, "dur": 500},
    {"ph": "X", "pid": 1, "tid": 7, "name": "custom-call.lu",
     "ts": 1500, "dur": 1000},
    {"ph": "X", "pid": 1, "tid": 7, "name": "fusion.1",
     "ts": 2600, "dur": 300},
    {"ph": "X", "pid": 1, "tid": 7, "name": "copy.3",
     "ts": 4200, "dur": 100},          # inside driver.getrf only
    # skipped: python host frame, XLA runtime infra
    {"ph": "X", "pid": 2, "tid": 3, "name": "$python.call",
     "ts": 0, "dur": 4000},
    {"ph": "X", "pid": 1, "tid": 7, "name": "xla::infra",
     "ts": 0, "dur": 4000},
]


@pytest.fixture
def trace_dir(tmp_path):
    """A capture dir shaped like jax.profiler's output tree."""
    d = tmp_path / "cap" / "plugins" / "profile" / "2026_08_07"
    d.mkdir(parents=True)
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": _EVENTS}, f)
    return str(tmp_path / "cap")


def test_stage_bucket_vocabulary():
    assert xprof.stage_bucket("step.getrf.panel") == ("getrf", "panel")
    assert xprof.stage_bucket("stage.heev.stage2") == ("heev", "stage2")
    assert xprof.stage_bucket("dist.pgetrf.k3") == ("pgetrf", "dist")
    assert xprof.stage_bucket("driver.potrf") == ("potrf", "driver")
    assert xprof.stage_bucket("dot.3") is None
    assert xprof.stage_bucket("fusion.1") is None
    assert xprof.stage_bucket("$python.call") is None


def test_parse_trace_joins_kernels_innermost(trace_dir):
    prof = xprof.parse_trace(trace_dir, label="t")
    assert prof["format"] == xprof.PROFILE_FORMAT
    assert prof["label"] == "t" and prof["digest"]
    st = prof["stages"]["getrf"]
    # panel: fusion.1@100+500us; update: custom-call@1000us +
    # fusion.1@300us; driver catch-all: copy.3@100us; pivot: no kernel
    # inside, the annotation wall (500us) stands in
    assert st["panel"] == pytest.approx(500e-6)
    assert st["update"] == pytest.approx(1300e-6)
    assert st["driver"] == pytest.approx(100e-6)
    assert st["pivot"] == pytest.approx(500e-6)
    src = prof["stage_source"]["getrf"]
    assert src["update"] == "kernels" and src["pivot"] == "annotation"
    # kernel rows carry the joined bucket; skipped events never appear
    by = {(k["name"], k["stage"]): k for k in prof["kernels"]}
    assert by[("custom-call.lu", "update")]["count"] == 1
    assert by[("fusion.1", "panel")]["total_s"] == pytest.approx(500e-6)
    assert by[("fusion.1", "update")]["total_s"] == pytest.approx(300e-6)
    names = {k["name"] for k in prof["kernels"]}
    assert "xla::infra" not in names and "$python.call" not in names
    ann = prof["annotations"]["getrf.update"]
    assert ann["count"] == 1 and ann["wall_s"] == pytest.approx(2000e-6)
    json.loads(json.dumps(prof))        # artifact must be JSON-clean


def test_profile_digest_covers_decisions(trace_dir):
    prof = xprof.parse_trace(trace_dir)
    d0 = xprof.profile_digest(prof)
    assert prof["digest"] == d0
    relabeled = dict(prof, label="other")
    assert xprof.profile_digest(relabeled) == d0
    bumped = dict(prof, stages={"getrf": {"panel": 1.0}})
    assert xprof.profile_digest(bumped) != d0


def test_load_profile_artifact_and_raw_trace(trace_dir, tmp_path):
    raw = xprof.load_profile(trace_dir)      # no artifact yet: re-parse
    assert raw["stages"]["getrf"]["update"] == pytest.approx(1300e-6)
    art = dict(raw, label="from-artifact", memory={"hbm_peak_gb": 0.5})
    apath = os.path.join(trace_dir, "xprof_t.json")
    with open(apath, "w") as f:
        json.dump(art, f)
    got = xprof.load_profile(trace_dir)      # artifact now outranks
    assert got["label"] == "from-artifact"
    assert got["memory"]["hbm_peak_gb"] == 0.5
    assert xprof.load_profile(apath)["label"] == "from-artifact"
    tr = xprof.find_trace_file(trace_dir)
    assert tr and xprof.load_profile(tr)["stages"]["getrf"]


def test_attr_join_device_profile(trace_dir):
    """The compute-source ladder: a parsed profile outranks host
    timers, stamps the report, and the stage split follows the DEVICE
    weights while total seconds still reconcile with the GFLOP/s."""
    prof = xprof.parse_trace(trace_dir)
    gf = 1.0
    rep = attr.attribute("getrf_fp32_n64_nb16", gf, platform="cpu",
                         device_profile=prof)
    assert rep["compute_source"] == "device_profile"
    assert rep["backend_source"] == "device_profile"
    assert rep["device_profile"]["digest"] == prof["digest"]
    assert "update" in rep["device_profile"]["stages"]
    total = sum(s["flops"] for s in rep["stages"])
    assert abs(total / rep["measured_s"] / 1e9 - gf) / gf < 0.01
    est = sum(s["measured_s"] for s in rep["stages"])
    assert est == pytest.approx(rep["measured_s"], rel=1e-3)
    by = {s["stage"]: s for s in rep["stages"]}
    # device truth: update carried 1300us vs panel's 500us
    assert by["update"]["measured_s"] > by["panel"]["measured_s"]
    assert "[source device_profile]" in attr.explain_pair(rep, rep)
    # flat {stage: seconds} maps join too (artifact-less callers)
    rep2 = attr.attribute("getrf_fp32_n64_nb16", gf, platform="cpu",
                          device_profile={"panel": 1.0, "update": 3.0})
    assert rep2["compute_source"] == "device_profile"


def test_overlap_summary_device_profile_rung(trace_dir):
    from slate_tpu.parallel import dist_util

    prof = xprof.parse_trace(trace_dir)
    out = dist_util.overlap_summary(n_devices=4, platform="cpu",
                                    window={"counters": {}},
                                    device_profile=prof)
    assert out["compute_source"] == "device_profile"
    assert out["device_profile"]["compute_s"] == pytest.approx(
        sum(prof["stages"]["getrf"].values()))
    assert out["device_profile"]["digest"] == prof["digest"]
    # explicit compute_s loses to the measured rung
    out2 = dist_util.overlap_summary(n_devices=4, compute_s=9.9,
                                     platform="cpu",
                                     window={"counters": {}},
                                     device_profile=prof)
    assert out2["compute_source"] == "device_profile"
    out3 = dist_util.overlap_summary(n_devices=4, compute_s=9.9,
                                     platform="cpu",
                                     window={"counters": {}})
    assert out3["compute_source"] == "explicit"


def test_hbm_peak_delta_gb_window_semantics():
    before = {"devices": [{"device": "0", "bytes_in_use": 4e9,
                           "peak_bytes_in_use": 6e9}]}
    # window advanced the process peak: after.peak - before.live
    after = {"devices": [{"device": "0", "bytes_in_use": 5e9,
                          "peak_bytes_in_use": 9e9}]}
    assert xprof.hbm_peak_delta_gb(before, after) == pytest.approx(5.0)
    # peak untouched: live delta floored at zero stands in
    flat = {"devices": [{"device": "0", "bytes_in_use": 3e9,
                         "peak_bytes_in_use": 6e9}]}
    assert xprof.hbm_peak_delta_gb(before, flat) == pytest.approx(0.0)
    up = {"devices": [{"device": "0", "bytes_in_use": 4.5e9,
                       "peak_bytes_in_use": 6e9}]}
    assert xprof.hbm_peak_delta_gb(before, up) == pytest.approx(0.5)
    # no device reports the API (CPU): None, never a lying zero
    assert xprof.hbm_peak_delta_gb({"devices": []}, {"devices": []}) \
        is None
    assert xprof.hbm_peak_delta_gb({}, {}) is None


def test_signals_from_launch_median():
    rows = [{"wall_s": 2e-3, "bcast_bytes": 1e9, "bcast_count": 2},
            {"wall_s": 3e-3, "bcast_bytes": 1e9, "bcast_count": 2},
            {"wall_s": 50e-3, "bcast_bytes": 1e9, "bcast_count": 2},
            {"wall_s": 1e-3, "bcast_bytes": 0, "bcast_count": 0}]
    sig = xprof.signals_from({"digest": "d", "stages": {}},
                             measured_steps=rows, ici_gbs=100.0)
    # wire = 1e9/100e9 = 10ms swamps the 2-3ms walls (exposed 0); the
    # 50ms row exposes (50-10)/2 = 20ms; the zero-collective row is
    # excluded from the median: median([0, 0, 0.02]) = 0 -> no signal
    # beats a zero guess
    assert sig["digest"] == "d" and sig["measured_steps"] == 4
    assert sig["launch_s"] is None or sig["launch_s"] >= 0
    sig2 = xprof.signals_from(
        {"digest": "d", "stages": {}},
        measured_steps=[{"wall_s": 2e-3, "bcast_bytes": 1e8,
                         "bcast_count": 2}], ici_gbs=100.0)
    # wire = 1e8/1e11 = 1ms; exposed (2-1)ms over 2 collectives
    assert sig2["launch_s"] == pytest.approx(0.5e-3)
    # a pre-embedded synthetic signal wins over row distillation
    sig3 = xprof.signals_from({"signals": {"launch_s": 7e-4}},
                              measured_steps=rows, ici_gbs=100.0)
    assert sig3["launch_s"] == pytest.approx(7e-4)
    # nothing usable -> explicit "no signal", not a guess
    empty = xprof.signals_from({})
    assert empty["launch_s"] is None and empty["stages"] == {}


def test_capture_noop_without_env(monkeypatch):
    monkeypatch.delenv(xprof.ENV_DIR, raising=False)
    xprof.clear()
    assert not xprof.enabled()
    with xprof.capture("noop") as cap:
        pass
    assert cap.profile is None and xprof.last_profile() is None


@pytest.mark.slow
def test_capture_real_cpu(tmp_path, monkeypatch):
    """A REAL jax.profiler capture on CPU round-trips: composed getrf
    stages land in the rollup and the artifact is reloadable."""
    import jax
    import numpy as np

    from slate_tpu.linalg import lu as slu

    monkeypatch.setenv(xprof.ENV_DIR, str(tmp_path / "cap"))
    xprof.clear()
    n, nb = 64, 16
    rng = np.random.default_rng(0)
    a = rng.standard_normal((n, n)).astype(np.float32) \
        + n * np.eye(n, dtype=np.float32)
    with xprof.capture("getrf"):
        lu, _piv = slu.getrf_scattered(a, nb=nb, step="panel")
        jax.block_until_ready(lu)
    prof = xprof.last_profile()
    assert prof is not None and not prof.get("error"), prof
    assert {"panel", "trsm", "update"} <= set(prof["stages"]["getrf"])
    assert prof["capture_wall_s"] > 0
    again = xprof.load_profile(str(tmp_path / "cap"))
    assert again["digest"] == prof["digest"]


def test_xprof_report_cli_renders(trace_dir, capsys):
    """The stdlib CLI renders a capture dir: header, kernel table,
    stage rollup (and --json round-trips)."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "tools", "xprof_report.py")
    spec = importlib.util.spec_from_file_location("_xprof_report", path)
    cli = importlib.util.module_from_spec(spec)
    sys.modules["_xprof_report"] = cli
    spec.loader.exec_module(cli)
    assert cli.main([trace_dir, "--routine", "getrf"]) == 0
    out = capsys.readouterr().out
    assert "stage rollup: getrf" in out
    assert "custom-call.lu" in out and "[annotation]" in out
    assert cli.main([trace_dir, "--json"]) == 0
    blob = json.loads(capsys.readouterr().out)
    assert blob["stages"]["getrf"]["update"] == pytest.approx(1300e-6)
    assert cli.main([trace_dir, "--routine", "nosuch"]) == 1
    capsys.readouterr()
