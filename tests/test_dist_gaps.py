"""Distributed coverage gaps from the round-1 review: ptrsm Right/trans
sides, pgelqf/punmlq, pgetri, pgbsv/ppbsv, pcolnorms, pgecondest, and
mesh↔mesh / nb↔nb redistribute — each validated on the 2×4 mesh and the
serial-stub 1×1 mesh (SURVEY §4 rank-count-independent checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.enums import Diag, Norm, Op, Side, Uplo
from slate_tpu.parallel import (distribute, make_grid_mesh, pcolnorms,
                                pgbsv, pgecondest, pgelqf, pgetrf, pgetri,
                                ppbsv, predistribute, ptranspose, ptrsm,
                                punmlq, undistribute, pnorm, peye)


@pytest.fixture(scope="module", params=[(2, 4), (1, 1)],
                ids=["mesh24", "mesh11"])
def mesh(request):
    p, q = request.param
    return make_grid_mesh(p, q, devices=jax.devices()[:p * q])


def _sq(n, seed=0, dom=True):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n))
    return a + n * np.eye(n) if dom else a


def _tri(n, uplo, unit, seed=0):
    a = _sq(n, seed)
    t = np.tril(a) if uplo is Uplo.Lower else np.triu(a)
    if unit:
        # keep the unit-triangular well conditioned (a random one has
        # cond ~ 2^n): shrink the off-diagonal couplings
        t = t * (0.5 / n)
        np.fill_diagonal(t, 1.0)
    return t


@pytest.mark.parametrize("side", [Side.Left, Side.Right])
@pytest.mark.parametrize("uplo", [Uplo.Lower, Uplo.Upper])
@pytest.mark.parametrize("op", [Op.NoTrans, Op.Trans, Op.ConjTrans])
@pytest.mark.parametrize("diag", [Diag.NonUnit, Diag.Unit])
def test_ptrsm_all_combinations(mesh, side, uplo, op, diag):
    n, nrhs, nb = 64, 48, 16
    p, q = mesh.shape["p"], mesh.shape["q"]
    t = _tri(n, uplo, diag is Diag.Unit, seed=3)
    b = np.random.default_rng(4).standard_normal(
        (n, nrhs) if side is Side.Left else (nrhs, n))
    ta = distribute(t, mesh, nb, diag_pad=1.0, row_mult=q, col_mult=p)
    tb = distribute(b, mesh, nb, row_mult=q, col_mult=p)
    x = np.asarray(undistribute(ptrsm(side, uplo, op, diag, ta, tb)))
    opt = {Op.NoTrans: t, Op.Trans: t.T, Op.ConjTrans: t.conj().T}[op]
    lhs = opt @ x if side is Side.Left else x @ opt
    assert np.linalg.norm(lhs - b) / np.linalg.norm(b) < 1e-10


def test_pgelqf_punmlq(mesh):
    m, n, nb = 48, 96, 16
    p, q = mesh.shape["p"], mesh.shape["q"]
    a = np.random.default_rng(5).standard_normal((m, n))
    da = distribute(a, mesh, nb, diag_pad=1.0, row_mult=q, col_mult=p)
    lq, tmats, taus = pgelqf(da)
    lqh = np.asarray(undistribute(lq))
    l = np.tril(lqh[:, :m])
    # Gram identity A·Aᴴ = L·Lᴴ
    assert np.allclose(l @ l.T, a @ a.T, atol=1e-8 * np.linalg.norm(a) ** 2)
    # Q̃ᴴ·(Q̃·B) = B round trip through the reflectors
    bvec = np.random.default_rng(6).standard_normal((n, 8))
    bd = distribute(bvec, mesh, nb, row_mult=q)
    qb = punmlq(lq, tmats, bd)
    rt = np.asarray(undistribute(punmlq(lq, tmats, qb, adjoint=True)))
    assert np.allclose(rt, bvec, atol=1e-9)
    # L·Q̃ reconstructs A:  A·x == L·(Q̃·x)
    x = np.random.default_rng(7).standard_normal((n, 4))
    qx = np.asarray(undistribute(
        punmlq(lq, tmats, distribute(x, mesh, nb, row_mult=q))))[:m]
    assert np.allclose(l @ qx, a @ x, atol=1e-8)


def test_pgetri(mesh):
    n, nb = 80, 16
    p, q = mesh.shape["p"], mesh.shape["q"]
    a = _sq(n, 8)
    da = distribute(a, mesh, nb, diag_pad=1.0, row_mult=q, col_mult=p)
    inv = np.asarray(undistribute(pgetri(da)))
    assert np.linalg.norm(a @ inv - np.eye(n)) < 1e-9 * n


def test_pgecondest(mesh):
    n, nb = 64, 16
    p, q = mesh.shape["p"], mesh.shape["q"]
    a = _sq(n, 9)
    da = distribute(a, mesh, nb, diag_pad=1.0, row_mult=q, col_mult=p)
    lu, gperm = pgetrf(da)
    anorm = float(pnorm(da, Norm.One))
    rcond, est = pgecondest(lu, gperm, anorm)
    true_cond = np.linalg.norm(a, 1) * np.linalg.norm(np.linalg.inv(a), 1)
    # Hager's estimate is a lower bound within a small factor
    assert 0 < 1.0 / rcond <= 3.0 * true_cond
    assert 1.0 / rcond >= 0.1 * true_cond


def test_pgbsv(mesh):
    n, nb, kl, ku = 96, 16, 3, 5
    p, q = mesh.shape["p"], mesh.shape["q"]
    rng = np.random.default_rng(10)
    d = np.subtract.outer(np.arange(n), np.arange(n))
    a = np.where((d >= -ku) & (d <= kl), rng.standard_normal((n, n)), 0)
    a += n * np.eye(n)
    b = rng.standard_normal((n, 6))
    da = distribute(a, mesh, nb, row_mult=q, col_mult=p)
    db = distribute(b, mesh, nb, row_mult=q)
    x = np.asarray(undistribute(pgbsv(da, kl, ku, db)))
    assert np.linalg.norm(a @ x - b) / np.linalg.norm(b) < 1e-10


@pytest.mark.parametrize("lower", [True, False])
def test_ppbsv(mesh, lower):
    n, nb, kd = 96, 16, 4
    p, q = mesh.shape["p"], mesh.shape["q"]
    rng = np.random.default_rng(11)
    d = np.subtract.outer(np.arange(n), np.arange(n))
    g = np.where(np.abs(d) <= kd, rng.standard_normal((n, n)), 0)
    a = (g + g.T) / 2 + n * np.eye(n)
    b = rng.standard_normal((n, 3))
    da = distribute(a, mesh, nb, row_mult=q, col_mult=p)
    db = distribute(b, mesh, nb, row_mult=q)
    x = np.asarray(undistribute(ppbsv(da, kd, db, lower=lower)))
    assert np.linalg.norm(a @ x - b) / np.linalg.norm(b) < 1e-10


def test_pcolnorms(mesh):
    m, n, nb = 70, 90, 16
    a = np.random.default_rng(12).standard_normal((m, n))
    da = distribute(a, mesh, nb)
    cn = np.asarray(pcolnorms(da))
    assert np.allclose(cn, np.abs(a).max(axis=0))


def test_predistribute_roundtrip(mesh):
    a = np.random.default_rng(13).standard_normal((90, 70))
    da = distribute(a, mesh, 16)
    r = predistribute(da, nb_new=32)
    assert r.nb == 32
    assert np.allclose(np.asarray(undistribute(r)), a)
    mesh2 = make_grid_mesh(1, 1, devices=jax.devices()[:1])
    r2 = predistribute(da, nb_new=8, mesh_new=mesh2)
    assert np.allclose(np.asarray(undistribute(r2)), a)


def test_ptranspose_peye(mesh):
    a = np.random.default_rng(14).standard_normal((50, 90)) \
        + 1j * np.random.default_rng(15).standard_normal((50, 90))
    da = distribute(a, mesh, 16)
    assert np.allclose(np.asarray(undistribute(ptranspose(da))), a.T)
    assert np.allclose(
        np.asarray(undistribute(ptranspose(da, conj=True))), a.conj().T)
    e = peye(45, 16, mesh)
    assert np.allclose(np.asarray(undistribute(e)), np.eye(45))


class TestBandMultsAndMixedPosv:
    """Round-3 additions: distributed band multiplies, triangular band
    solve, and mixed-precision Cholesky (VERDICT r2 item 7)."""

    def _band(self, n, kl, ku, herm=False, seed=50):
        rng = np.random.default_rng(seed)
        full = rng.standard_normal((n, n))
        mask = np.arange(n)[None, :] - np.arange(n)[:, None]
        full = np.where((mask <= ku) & (mask >= -kl), full, 0)
        if herm:
            full = (full + full.T) / 2 + n * np.eye(n)
        return full

    def test_pgbmm(self, mesh):
        from slate_tpu.parallel import distribute, pgbmm, undistribute
        from slate_tpu.parallel.mesh import mesh_grid_shape
        mesh24 = mesh
        n, kl, ku, nb = 96, 5, 3, 16
        full = self._band(n, kl, ku)
        rng = np.random.default_rng(51)
        bm = rng.standard_normal((n, 24))
        p, q = mesh_grid_shape(mesh)
        # hand a DENSE matrix in: the mask must enforce the band
        dense = full + np.where(full == 0, 0.1, 0.0)
        ad = distribute(dense, mesh24, nb, col_mult=p)
        bd = distribute(bm, mesh24, nb, row_mult=q)
        out = np.asarray(undistribute(pgbmm(2.0, ad, kl, ku, bd)))
        assert np.allclose(out, 2.0 * full @ bm, atol=1e-12)

    def test_phbmm(self, mesh):
        from slate_tpu.parallel import distribute, phbmm, undistribute
        from slate_tpu.parallel.mesh import mesh_grid_shape
        mesh24 = mesh
        n, kd, nb = 96, 4, 16
        full = self._band(n, kd, 0, seed=52)
        sym = np.tril(full) + np.tril(full, -1).T
        rng = np.random.default_rng(53)
        bm = rng.standard_normal((n, 8))
        p, q = mesh_grid_shape(mesh)
        # square padding: phermitize transposes the shard layout
        ad = distribute(np.tril(full), mesh24, nb, row_mult=q, col_mult=p)
        bd = distribute(bm, mesh24, nb, row_mult=q)
        out = np.asarray(undistribute(phbmm(1.0, ad, kd, bd)))
        assert np.allclose(out, sym @ bm, atol=1e-12)

    def test_ptbsm(self, mesh):
        from slate_tpu.parallel import distribute, ptbsm, undistribute
        from slate_tpu.parallel.mesh import mesh_grid_shape
        mesh24 = mesh
        n, kd, nb = 96, 4, 16
        full = self._band(n, kd, 0, seed=54)
        tri = np.tril(full) + 2 * n * np.eye(n)
        rng = np.random.default_rng(55)
        bm = rng.standard_normal((n, 6))
        p, q = mesh_grid_shape(mesh)
        ad = distribute(tri, mesh24, nb, row_mult=q, col_mult=p)
        bd = distribute(bm, mesh24, nb, row_mult=q)
        x = np.asarray(undistribute(ptbsm(
            Side.Left, Uplo.Lower, Op.NoTrans, Diag.NonUnit, ad, kd, bd)))
        assert np.linalg.norm(tri @ x - bm) / np.linalg.norm(bm) < 1e-11

    def test_pposv_mixed(self, mesh):
        from slate_tpu.parallel import pposv_mixed, undistribute
        mesh24 = mesh
        rng = np.random.default_rng(56)
        n = 80
        g = rng.standard_normal((n, n))
        a = g @ g.T + n * np.eye(n)
        b = rng.standard_normal((n, 4))
        x, iters = pposv_mixed(a, b, mesh24, nb=16)
        xh = np.asarray(undistribute(x))
        assert np.linalg.norm(a @ xh - b) / np.linalg.norm(b) < 1e-10
        assert iters >= 0   # converged without fallback

    def test_pposv_mixed_gmres(self, mesh):
        from slate_tpu.parallel import pposv_mixed_gmres
        mesh24 = mesh
        rng = np.random.default_rng(57)
        n = 64
        g = rng.standard_normal((n, n))
        a = g @ g.T + n * np.eye(n)
        b = rng.standard_normal((n,))
        x, iters = pposv_mixed_gmres(a, b, mesh24, nb=16)
        xh = np.asarray(x)
        assert np.linalg.norm(a @ xh - b) / np.linalg.norm(b) < 1e-10


@pytest.mark.parametrize("kl,ku", [(4, 7), (16, 16), (0, 3)])
def test_pgbsv_band_shapes(mesh, kl, ku, monkeypatch):
    """Device-scan band LU: results match scipy for general (kl, ku);
    the band must NEVER be gathered to host for the factorization
    (VERDICT r3 Missing #2) — the host extraction helper is poisoned."""
    from slate_tpu.parallel import dist_band
    monkeypatch.setattr(
        dist_band, "_extract_band",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("band gathered to host")))
    n, nb = 200, 16
    rng = np.random.default_rng(13)
    g = np.zeros((n, n))
    for dd in range(-kl, ku + 1):
        g += np.diag(rng.standard_normal(n - abs(dd)), dd)
    g += (kl + ku + 2) * np.eye(n)
    b = rng.standard_normal((n, 3))
    p, q = mesh.shape["p"], mesh.shape["q"]
    dg = distribute(g, mesh, nb, row_mult=q, col_mult=p)
    db = distribute(b, mesh, nb, row_mult=q)
    x = np.asarray(undistribute(dist_band.pgbsv(dg, kl, ku, db)))[:n]
    from scipy.linalg import solve
    assert np.abs(x - solve(g, b)).max() < 1e-10


def test_ppbtrf_factor_matches_scipy(mesh):
    """The device-scan band Cholesky factor itself (diag + sub tile
    stacks) reconstructs scipy's cholesky of the band matrix."""
    from slate_tpu.parallel.dist_band import ppbtrf
    n, nb, kd = 96, 16, 5
    rng = np.random.default_rng(14)
    d = np.subtract.outer(np.arange(n), np.arange(n))
    g = np.where(np.abs(d) <= kd, rng.standard_normal((n, n)), 0)
    a = (g + g.T) / 2 + n * np.eye(n)
    p, q = mesh.shape["p"], mesh.shape["q"]
    da = distribute(a, mesh, nb, row_mult=q, col_mult=p)
    l_diag, l_sub = ppbtrf(da, kd)
    nt = n // nb
    l = np.zeros((n, n))
    for k in range(nt):
        l[k * nb:(k + 1) * nb, k * nb:(k + 1) * nb] = l_diag[k]
        if k + 1 < nt:
            l[(k + 1) * nb:(k + 2) * nb, k * nb:(k + 1) * nb] = l_sub[k]
    want = np.linalg.cholesky(a)
    assert np.abs(l - want).max() < 1e-10


def test_phesv_n1024(mesh):
    """Distributed Aasen solve at n >= 1024 (VERDICT r3 Next #9: the
    round-3 suite only exercised phetrf at --dim 128-class sizes).

    This test exposed two pre-existing r3 bugs, both fixed in round 4:
    the column swap moved a STALE copy of the outgoing window column
    (the win buffer is the only current copy mid-panel), and the
    trailing re-hermitization gathered the mixed-map permutation
    without the final transpose (for REAL input on identity maps that
    reduced to averaging a with itself — why real-only tests never
    caught it; on p != q grids it corrupted the trailing block)."""
    from slate_tpu.parallel.dist_hesv import phesv
    n, nb = 1024, 128
    rng = np.random.default_rng(21)
    g = rng.standard_normal((n, n))
    a = (g + g.T) / 2 + 0.1 * np.eye(n)
    b = rng.standard_normal((n, 2))
    _, x = phesv(jnp.asarray(a), jnp.asarray(b), mesh, nb=nb)
    xv = np.asarray(jax.device_get(x))[:n, :2]
    res = np.linalg.norm(a @ xv - b) / (
        np.linalg.norm(a) * np.linalg.norm(xv))
    assert res < 1e-12, res


def test_phesv_complex_hermitian(mesh):
    """Complex Hermitian distributed Aasen: guards every conj in the
    deferred refresh and the re-hermitization (the r3 bugs were masked
    by real-only tests — Re(A) averaging is a no-op on real data but
    zeroes imaginary parts on complex)."""
    from slate_tpu.parallel.dist_hesv import phesv
    n, nb = 192, 32
    rng = np.random.default_rng(9)
    g = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
    a = (g + g.conj().T) / 2 + 0.1 * np.eye(n)
    b = rng.standard_normal((n, 2)) + 1j * rng.standard_normal((n, 2))
    _, x = phesv(jnp.asarray(a), jnp.asarray(b), mesh, nb=nb)
    xv = np.asarray(jax.device_get(x))[:n, :2]
    res = np.linalg.norm(a @ xv - b) / (
        np.linalg.norm(a) * np.linalg.norm(xv))
    assert res < 1e-12, res
