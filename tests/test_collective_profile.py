"""Per-driver collective/flop budgets for the lookahead-pipelined
distributed factorizations, read off the COMPILED HLO.

The reference reads its comm behavior off MPI traces; here the whole
communication schedule is a compile-time artifact, so regressions are
pinned without running anything (round 5 proved runtime-only accounting
is too fragile — BENCH_r05.json came back empty):

* one fused panel broadcast per factorization step — the single (M, nb)
  ``psum`` of :func:`~slate_tpu.parallel.dist_util.bcast_block_col`,
  down from the masked-psum + all_gather pair that paid two serialized
  collective latencies;
* a pinned TOTAL collective count per step body (pgetrf adds the swap
  fetch, pgeqrf the Vᴴ·C inner-product reduce — and nothing else);
* trailing-update flops within 1.5× of the ideal shrinking-trailing
  count (down from ~3× for the old fixed full-size loop body), via the
  staged windows of :func:`~slate_tpu.parallel.dist_util.stage_bounds`;
* no collective anywhere materializes more than a panel;
* residual gates for the rewritten drivers unchanged at ≤ 3·eps·n.

All on the 2×4 CPU mesh — only HLO text is inspected, so the same
numbers hold for the TPU lowering of the same program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.parallel import (distribute, make_grid_mesh, pgeqrf, pgesv,
                                pposv, undistribute)
from slate_tpu.parallel.dist_util import stage_bounds
from slate_tpu.perf.hlo_profile import profile_fn

# profile dims: nt = 32 steps keeps every stage boundary aligned to both
# mesh axes (row0 multiples of p·nb, col0 of q·nb), so the staged
# windows shrink on schedule instead of snapping wide
P, Q = 2, 4
N, NB = 512, 16
NT = N // NB
ML, NL = NT // P, NT // Q

#: total collectives per step body: the fused panel broadcast, plus
#: pgetrf's pivot-row swap fetch / pgeqrf's Vᴴ·C inner-product psum
_STEP_COLLECTIVES = {"ppotrf": 1, "pgetrf": 2, "pgeqrf": 2}


def _rng(seed=0):
    return np.random.default_rng(seed)


@pytest.fixture(scope="module")
def mesh24():
    return make_grid_mesh(P, Q)


@pytest.fixture(scope="module")
def profiles(mesh24):
    """Compile each driver's shard_map kernel once; profile the HLO."""
    from slate_tpu.parallel.dist_factor import _build_ppotrf
    from slate_tpu.parallel.dist_lu import _build_pgetrf
    from slate_tpu.parallel.dist_qr import _build_pgeqrf
    data = jnp.zeros((N, N), jnp.float64)
    out = {}
    for name, build in (("ppotrf", _build_ppotrf),
                        ("pgetrf", _build_pgetrf),
                        ("pgeqrf", _build_pgeqrf)):
        out[name] = profile_fn(build(mesh24, NB, NT, ML, NL, "float64"),
                               data)
    return out


def _trips_and_windows():
    """Per-stage trip counts and static local trailing-window shapes."""
    bounds = stage_bounds(NT)
    trips, wins = [], []
    for s in range(len(bounds) - 1):
        ks, ke = bounds[s], bounds[s + 1]
        trips.append(ke - ks)
        row0 = (ks // P) * NB
        col0 = (ks // Q) * NB
        wins.append((ML * NB - row0, NL * NB - col0))
    return trips, wins


def _ideal_trailing_flops():
    """Global flops of an exactly-shrinking trailing update: step k
    contracts the (n − (k+1)·nb)² remainder against the nb panel."""
    return sum(2.0 * ((NT - 1 - k) * NB) ** 2 * NB for k in range(NT))


@pytest.mark.parametrize("driver", sorted(_STEP_COLLECTIVES))
def test_one_fused_panel_collective_per_step(profiles, driver):
    """(a) of the PR-1 acceptance: the panel path costs exactly ONE
    collective per factorization step — a single (M, nb) all-reduce
    (bcast_block_col), not the old psum + all_gather pair — and the
    step body's TOTAL collective count is pinned so a second hop cannot
    sneak back in."""
    prof = profiles[driver]
    bodies = prof.step_loops
    trips, _ = _trips_and_windows()
    assert len(bodies) == len(trips), \
        f"{driver}: expected {len(trips)} staged step loops, " \
        f"got {len(bodies)}"
    for body in bodies:
        panel = [c for c in body.collectives
                 if c.kind == "all-reduce" and c.shape == (N, NB)]
        assert len(panel) == 1, \
            f"{driver}: {len(panel)} (M, nb) panel broadcasts in " \
            f"{body.name} (want exactly 1 — the fused bcast_block_col)"
        assert body.collective_count == _STEP_COLLECTIVES[driver], \
            f"{driver}: {body.collective_count} collectives per step " \
            f"in {body.name} (budget {_STEP_COLLECTIVES[driver]}); " \
            f"kinds: {[(c.kind, c.shape) for c in body.collectives]}"


@pytest.mark.parametrize("driver", sorted(_STEP_COLLECTIVES))
def test_trailing_flops_within_1p5x_of_shrinking_ideal(profiles, driver):
    """(b) of the PR-1 acceptance: each stage's trailing contraction has
    the stage's STATIC shrunken window shape, and the whole run's
    trailing flops stay within 1.5× of the ideal shrinking-trailing
    count (the old fixed full-size masked body paid ~3×)."""
    prof = profiles[driver]
    trips, wins = _trips_and_windows()
    total = 0.0
    for body, trip, (rows, cols) in zip(prof.step_loops, trips, wins):
        trailing = [d for d in body.dots
                    if d.out_shape == (rows, cols) and d.contract == NB]
        assert trailing, \
            f"{driver}: no ({rows}, {cols})×{NB} trailing dot in " \
            f"{body.name}; dots: {[(d.out_shape, d.contract) for d in body.dots]}"
        total += trip * max(d.flops for d in trailing)
    ratio = total * (P * Q) / _ideal_trailing_flops()
    assert ratio <= 1.5, \
        f"{driver}: trailing flops {ratio:.2f}× the shrinking ideal " \
        "(budget 1.5×)"


@pytest.mark.parametrize("driver", sorted(_STEP_COLLECTIVES))
def test_no_collective_larger_than_a_panel(profiles, driver):
    """Gather-everything smell test, now on COMPILED HLO: the largest
    collective anywhere (entry included) is the (M, nb) panel."""
    prof = profiles[driver]
    assert prof.step_loops, f"{driver}: no communicating step loops"
    assert prof.max_collective_elems <= N * NB, \
        f"{driver}: a collective moves {prof.max_collective_elems} " \
        f"elements (> panel = {N * NB})"


# ---------------------------------------------------------------------------
# Residual gates: the rewrite must not move the numerics (≤ 3·eps·n,
# the reference's criterion test/test_gemm.cc:260).
# ---------------------------------------------------------------------------

def _scaled_res(a, x, b):
    return np.linalg.norm(a @ x - b) / (
        np.linalg.norm(a) * np.linalg.norm(x) + np.linalg.norm(b))


def test_pposv_residual_gate(mesh24):
    """ppotrf + both ptrsm sweeps (L then Lᴴ)."""
    n, nb = 192, 16
    g = _rng(40).standard_normal((n, n))
    a = g @ g.T + n * np.eye(n)
    b = _rng(41).standard_normal((n, 5))
    _, x = pposv(a, b, mesh24, nb=nb)
    xh = np.asarray(undistribute(x))
    assert _scaled_res(a, xh, b) < 3 * np.finfo(np.float64).eps * n


def test_pgesv_residual_gate(mesh24):
    """pgetrf + the pivoted triangular solves."""
    n, nb = 192, 16
    a = _rng(42).standard_normal((n, n))
    b = _rng(43).standard_normal((n, 5))
    _, _, x = pgesv(a, b, mesh24, nb=nb)
    xh = np.asarray(undistribute(x))
    assert _scaled_res(a, xh, b) < 3 * np.finfo(np.float64).eps * n


def test_pgeqrf_residual_gate(mesh24):
    """pgeqrf factorization residual via the Gram identity
    AᵀA = RᵀR (rank-revealing enough for a 3·eps·n gate, and needs no
    explicit Q)."""
    m, n, nb = 192, 96, 16
    a = _rng(44).standard_normal((m, n))
    da = distribute(a, mesh24, nb=nb, diag_pad=1.0,
                    row_mult=Q, col_mult=P)
    qr, _, _ = pgeqrf(da)
    r = np.triu(np.asarray(undistribute(qr)))[:n, :n]
    res = np.linalg.norm(a.T @ a - r.T @ r) / (
        np.linalg.norm(a) ** 2)
    assert res < 3 * np.finfo(np.float64).eps * m


def test_phesv_residual_gate(mesh24):
    """phetrf (lookahead-double-buffered Aasen window) + solve."""
    from slate_tpu.parallel.dist_hesv import phesv
    n, nb = 256, 32
    g = _rng(45).standard_normal((n, n))
    a = (g + g.T) / 2 + 0.1 * np.eye(n)
    b = _rng(46).standard_normal((n, 3))
    _, x = phesv(jnp.asarray(a), jnp.asarray(b), mesh24, nb=nb)
    xh = np.asarray(jax.device_get(x))[:n, :3]
    assert _scaled_res(a, xh, b) < 3 * np.finfo(np.float64).eps * n
