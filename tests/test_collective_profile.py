"""Per-driver collective/flop budgets for the lookahead-pipelined
distributed factorizations, read off the COMPILED HLO.

The reference reads its comm behavior off MPI traces; here the whole
communication schedule is a compile-time artifact, so regressions are
pinned without running anything (round 5 proved runtime-only accounting
is too fragile — BENCH_r05.json came back empty):

* one fused panel broadcast per factorization step — the single (M, nb)
  ``psum`` of :func:`~slate_tpu.parallel.dist_util.bcast_block_col`,
  down from the masked-psum + all_gather pair that paid two serialized
  collective latencies;
* a pinned TOTAL collective count per step body (pgetrf adds the swap
  fetch, pgeqrf the Vᴴ·C inner-product reduce — and nothing else);
* trailing-update flops within 1.5× of the ideal shrinking-trailing
  count (down from ~3× for the old fixed full-size loop body), via the
  staged windows of :func:`~slate_tpu.parallel.dist_util.stage_bounds`;
* no collective anywhere materializes more than a panel;
* residual gates for the rewritten drivers unchanged at ≤ 3·eps·n.

All on the 2×4 CPU mesh — only HLO text is inspected, so the same
numbers hold for the TPU lowering of the same program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from slate_tpu.parallel import (distribute, make_grid_mesh, pgeqrf, pgesv,
                                pposv, undistribute)
from slate_tpu.parallel.dist_util import stage_bounds
from slate_tpu.perf.hlo_profile import profile_fn

# profile dims: nt = 32 steps keeps every stage boundary aligned to both
# mesh axes (row0 multiples of p·nb, col0 of q·nb), so the staged
# windows shrink on schedule instead of snapping wide
P, Q = 2, 4
N, NB = 512, 16
NT = N // NB
ML, NL = NT // P, NT // Q

#: total collectives per step body: the fused panel broadcast, plus
#: pgetrf's pivot-row swap fetch / pgeqrf's Vᴴ·C inner-product psum
_STEP_COLLECTIVES = {"ppotrf": 1, "pgetrf": 2, "pgeqrf": 2}


def _rng(seed=0):
    return np.random.default_rng(seed)


@pytest.fixture(scope="module")
def mesh24():
    return make_grid_mesh(P, Q)


@pytest.fixture(scope="module")
def profiles(mesh24):
    """Compile each driver's shard_map kernel once; profile the HLO."""
    from slate_tpu.parallel.dist_factor import _build_ppotrf
    from slate_tpu.parallel.dist_lu import _build_pgetrf
    from slate_tpu.parallel.dist_qr import _build_pgeqrf
    data = jnp.zeros((N, N), jnp.float64)
    out = {}
    for name, build in (("ppotrf", _build_ppotrf),
                        ("pgetrf", _build_pgetrf),
                        ("pgeqrf", _build_pgeqrf)):
        out[name] = profile_fn(build(mesh24, NB, NT, ML, NL, "float64"),
                               data)
    return out


def _trips_and_windows():
    """Per-stage trip counts and static local trailing-window shapes."""
    bounds = stage_bounds(NT)
    trips, wins = [], []
    for s in range(len(bounds) - 1):
        ks, ke = bounds[s], bounds[s + 1]
        trips.append(ke - ks)
        row0 = (ks // P) * NB
        col0 = (ks // Q) * NB
        wins.append((ML * NB - row0, NL * NB - col0))
    return trips, wins


def _ideal_trailing_flops():
    """Global flops of an exactly-shrinking trailing update: step k
    contracts the (n − (k+1)·nb)² remainder against the nb panel."""
    return sum(2.0 * ((NT - 1 - k) * NB) ** 2 * NB for k in range(NT))


@pytest.mark.parametrize("driver", sorted(_STEP_COLLECTIVES))
def test_one_fused_panel_collective_per_step(profiles, driver):
    """(a) of the PR-1 acceptance: the panel path costs exactly ONE
    collective per factorization step — a single (M, nb) all-reduce
    (bcast_block_col), not the old psum + all_gather pair — and the
    step body's TOTAL collective count is pinned so a second hop cannot
    sneak back in."""
    prof = profiles[driver]
    bodies = prof.step_loops
    trips, _ = _trips_and_windows()
    assert len(bodies) == len(trips), \
        f"{driver}: expected {len(trips)} staged step loops, " \
        f"got {len(bodies)}"
    for body in bodies:
        panel = [c for c in body.collectives
                 if c.kind == "all-reduce" and c.shape == (N, NB)]
        assert len(panel) == 1, \
            f"{driver}: {len(panel)} (M, nb) panel broadcasts in " \
            f"{body.name} (want exactly 1 — the fused bcast_block_col)"
        assert body.collective_count == _STEP_COLLECTIVES[driver], \
            f"{driver}: {body.collective_count} collectives per step " \
            f"in {body.name} (budget {_STEP_COLLECTIVES[driver]}); " \
            f"kinds: {[(c.kind, c.shape) for c in body.collectives]}"


@pytest.mark.parametrize("driver", sorted(_STEP_COLLECTIVES))
def test_trailing_flops_within_1p5x_of_shrinking_ideal(profiles, driver):
    """(b) of the PR-1 acceptance: each stage's trailing contraction has
    the stage's STATIC shrunken window shape, and the whole run's
    trailing flops stay within 1.5× of the ideal shrinking-trailing
    count (the old fixed full-size masked body paid ~3×)."""
    prof = profiles[driver]
    trips, wins = _trips_and_windows()
    total = 0.0
    for body, trip, (rows, cols) in zip(prof.step_loops, trips, wins):
        trailing = [d for d in body.dots
                    if d.out_shape == (rows, cols) and d.contract == NB]
        assert trailing, \
            f"{driver}: no ({rows}, {cols})×{NB} trailing dot in " \
            f"{body.name}; dots: {[(d.out_shape, d.contract) for d in body.dots]}"
        total += trip * max(d.flops for d in trailing)
    ratio = total * (P * Q) / _ideal_trailing_flops()
    assert ratio <= 1.5, \
        f"{driver}: trailing flops {ratio:.2f}× the shrinking ideal " \
        "(budget 1.5×)"


@pytest.mark.parametrize("driver", sorted(_STEP_COLLECTIVES))
def test_no_collective_larger_than_a_panel(profiles, driver):
    """Gather-everything smell test, now on COMPILED HLO: the largest
    collective anywhere (entry included) is the (M, nb) panel."""
    prof = profiles[driver]
    assert prof.step_loops, f"{driver}: no communicating step loops"
    assert prof.max_collective_elems <= N * NB, \
        f"{driver}: a collective moves {prof.max_collective_elems} " \
        f"elements (> panel = {N * NB})"


# ---------------------------------------------------------------------------
# Residual gates: the rewrite must not move the numerics (≤ 3·eps·n,
# the reference's criterion test/test_gemm.cc:260).
# ---------------------------------------------------------------------------

def _scaled_res(a, x, b):
    return np.linalg.norm(a @ x - b) / (
        np.linalg.norm(a) * np.linalg.norm(x) + np.linalg.norm(b))


def test_pposv_residual_gate(mesh24):
    """ppotrf + both ptrsm sweeps (L then Lᴴ)."""
    n, nb = 192, 16
    g = _rng(40).standard_normal((n, n))
    a = g @ g.T + n * np.eye(n)
    b = _rng(41).standard_normal((n, 5))
    _, x = pposv(a, b, mesh24, nb=nb)
    xh = np.asarray(undistribute(x))
    assert _scaled_res(a, xh, b) < 3 * np.finfo(np.float64).eps * n


def test_pgesv_residual_gate(mesh24):
    """pgetrf + the pivoted triangular solves."""
    n, nb = 192, 16
    a = _rng(42).standard_normal((n, n))
    b = _rng(43).standard_normal((n, 5))
    _, _, x = pgesv(a, b, mesh24, nb=nb)
    xh = np.asarray(undistribute(x))
    assert _scaled_res(a, xh, b) < 3 * np.finfo(np.float64).eps * n


def test_pgeqrf_residual_gate(mesh24):
    """pgeqrf factorization residual via the Gram identity
    AᵀA = RᵀR (rank-revealing enough for a 3·eps·n gate, and needs no
    explicit Q)."""
    m, n, nb = 192, 96, 16
    a = _rng(44).standard_normal((m, n))
    da = distribute(a, mesh24, nb=nb, diag_pad=1.0,
                    row_mult=Q, col_mult=P)
    qr, _, _ = pgeqrf(da)
    r = np.triu(np.asarray(undistribute(qr)))[:n, :n]
    res = np.linalg.norm(a.T @ a - r.T @ r) / (
        np.linalg.norm(a) ** 2)
    assert res < 3 * np.finfo(np.float64).eps * m


# ---------------------------------------------------------------------------
# Kernel-launch census: the fused LU panel budget.  The r4 scattered
# driver composed each panel from a chain of per-block Pallas calls (64
# launches at n=8192/nb=512, ~30 µs of HBM glue each); the fused
# mega-kernel owns the panel loop, so the budget is ONE Pallas
# invocation per panel step.  Counted on the jaxpr (platform-independent
# — identical for the TPU compile and the CPU interpret lowering); the
# compiled-HLO custom-call census covers the on-chip artifact.
# ---------------------------------------------------------------------------


def test_getrf_scattered_one_pallas_call_per_panel():
    from slate_tpu.linalg.lu import getrf_scattered
    from slate_tpu.perf.hlo_profile import count_pallas_calls

    for n, nb in ((256, 128), (256, 64)):
        a = jnp.zeros((n, n), jnp.float32)
        calls = count_pallas_calls(lambda x, nb=nb: getrf_scattered(x, nb),
                                   a)
        panels = n // nb
        assert calls == panels, \
            f"n={n} nb={nb}: {calls} Pallas invocations for {panels} " \
            f"panel steps (budget: exactly 1 per panel — the fused " \
            f"mega-kernel owns the panel loop)"


def test_getrf_dispatch_pallas_budget_when_scattered_forced(monkeypatch):
    """The shipped dispatch (getrf → _getrf_partial) honors the same
    launch budget when the scattered driver is selected."""
    from slate_tpu.linalg import lu as lu_mod
    from slate_tpu.perf import autotune
    from slate_tpu.perf.hlo_profile import count_pallas_calls

    monkeypatch.setattr("slate_tpu.config.scattered_lu", True)
    monkeypatch.setattr(lu_mod, "_SCATTERED_NB", 128)
    autotune.reset_table()
    try:
        a = jnp.zeros((256, 256), jnp.float32)
        calls = count_pallas_calls(
            lambda x: lu_mod._getrf_partial(x, 128), a)
        assert calls == 2, calls
    finally:
        autotune.reset_table()


def test_chase_wavefront_one_pallas_call_per_chunk():
    """The device bulge chase owns its whole chunk in ONE Pallas
    invocation (the getrf mega-kernel budget applied to the eig/SVD
    stage-2 middle): a k-chunk checkpointed pass must trace to exactly
    k pallas_calls — a per-window (or per-stagger) launch chain
    sneaking back in fails here, not in a profile someday."""
    from slate_tpu.perf.autotune import kernel
    from slate_tpu.perf.hlo_profile import count_pallas_calls

    n, kd = 64, 8
    hb = kernel("hb2st_wavefront")
    ab = jnp.zeros((n, 2 * kd + 2), jnp.float64)
    assert count_pallas_calls(lambda x: hb(x, kd)[0], ab) == 1
    chunks = [(0, 20), (20, 45), (45, n - 2)]

    def chunked(x):
        for j0, j1 in chunks:
            x, _ = hb(x, kd, j0, j1)
        return x

    assert count_pallas_calls(chunked, ab) == len(chunks)

    tb = kernel("tb2bd_wavefront")
    stm = jnp.zeros((n, 3 * kd + 2), jnp.float64)
    assert count_pallas_calls(lambda x: tb(x, kd)[0], stm) == 1


def test_dist_panel_pallas_launch_budget(mesh24, monkeypatch):
    """ISSUE-6 satellite: the lookahead pipeline inherits the fused
    panel kernels through the ``dist_panel`` site — with it forced to
    ``pallas_panel`` every step body carries exactly ONE pallas_call
    (the fused chol+inverse / trtri panel), replacing the per-step
    cholesky/lu + triangular_solve chain; the xla backend carries
    none.  Counted on the jaxpr (each staged loop body once)."""
    from slate_tpu.parallel.dist_factor import _build_ppotrf
    from slate_tpu.parallel.dist_lu import _build_pgetrf
    from slate_tpu.perf.hlo_profile import count_pallas_calls

    nb2 = 32                      # pow2: dist_panel-eligible, ≠ NB=16
    nt2 = N // nb2
    ml2, nl2 = nt2 // P, nt2 // Q
    nstages = len(stage_bounds(nt2)) - 1
    data = jnp.zeros((N, N), jnp.float64)
    for build, nps in ((_build_ppotrf, 1), (_build_pgetrf, 1)):
        fn_p = build(mesh24, nb2, nt2, ml2, nl2, "float64",
                     "pallas_panel")
        assert count_pallas_calls(fn_p, data) == nps * nstages
        fn_x = build(mesh24, nb2, nt2, ml2, nl2, "float64", "xla")
        assert count_pallas_calls(fn_x, data) == 0


def test_dist_panel_pallas_parity(mesh24, monkeypatch):
    """The pallas_panel dist backend must not move the numerics: pposv
    and pgesv residual-gated end to end with the site forced (interpret
    mode inside the CPU shard_map — the same program a TPU mesh
    compiles)."""
    from slate_tpu.perf import autotune

    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                       "dist_panel=pallas_panel")
    autotune.reset_table()
    try:
        n, nb = 192, 32
        g = _rng(47).standard_normal((n, n))
        a = g @ g.T + n * np.eye(n)
        b = _rng(48).standard_normal((n, 4))
        _, x = pposv(a, b, mesh24, nb=nb)
        xh = np.asarray(undistribute(x))
        assert _scaled_res(a, xh, b) < 3 * np.finfo(np.float64).eps * n
        a2 = _rng(49).standard_normal((n, n))
        _, _, x2 = pgesv(a2, b, mesh24, nb=nb)
        x2h = np.asarray(undistribute(x2))
        assert _scaled_res(a2, x2h, b) < 3 * np.finfo(np.float64).eps * n
    finally:
        autotune.reset_table()


def test_custom_call_census_parses_compiled_hlo():
    """The HLO-text census (what the on-chip artifact uses: Pallas
    lowers to custom_call_target=\"tpu_custom_call\") counts targets
    through fusion wrappers and ignores unrelated custom calls."""
    from slate_tpu.perf.hlo_profile import profile_hlo_text

    hlo = """HloModule m
%helper (x: f32[8,8]) -> f32[8,8] {
  %x = f32[8,8] parameter(0)
  ROOT %cc = f32[8,8] custom-call(%x), custom_call_target="tpu_custom_call"
}
ENTRY %main (p0: f32[8,8]) -> f32[8,8] {
  %p0 = f32[8,8] parameter(0)
  %c1 = f32[8,8] custom-call(%p0), custom_call_target="tpu_custom_call"
  %c2 = f32[8,8] custom-call(%c1), custom_call_target="Sharding"
  ROOT %h = f32[8,8] call(%c2), to_apply=%helper
}
"""
    prof = profile_hlo_text(hlo)
    assert prof.count_custom_calls("tpu_custom_call") == 2
    assert prof.count_custom_calls("Sharding") == 1
    assert prof.entry.custom_calls.count("tpu_custom_call") == 2


def test_geqrf_guard_is_one_whole_loop_conditional():
    """The r3→r4 geqrf regression root cause (STATUS round-6 note): the
    r4 CholQR² conditioning guard ran as a per-panel lax.cond, so every
    panel step carried both a CholQR² and a full Householder branch
    (−20% throughput, minutes of compile).  The fix aggregates the
    departure and guards ONCE outside the loop — pin that shape: the
    compiled fast path contains exactly one conditional."""
    from slate_tpu.linalg.qr import geqrf_panels

    a = jnp.zeros((256, 64), jnp.float32)
    jaxpr = jax.make_jaxpr(lambda x: geqrf_panels(x, 32))(a)
    conds = str(jaxpr).count("cond[")
    assert conds <= 1, \
        f"{conds} lax.cond branches in geqrf_panels (budget 1 — the " \
        "whole-loop conditioning guard; a per-panel guard regressed " \
        "geqrf 20% in r4)"


def test_phesv_residual_gate(mesh24):
    """phetrf (lookahead-double-buffered Aasen window) + solve."""
    from slate_tpu.parallel.dist_hesv import phesv
    n, nb = 256, 32
    g = _rng(45).standard_normal((n, n))
    a = (g + g.T) / 2 + 0.1 * np.eye(n)
    b = _rng(46).standard_normal((n, 3))
    _, x = phesv(jnp.asarray(a), jnp.asarray(b), mesh24, nb=nb)
    xh = np.asarray(jax.device_get(x))[:n, :3]
    assert _scaled_res(a, xh, b) < 3 * np.finfo(np.float64).eps * n
