"""Pallas device-kernel tests (interpret mode on CPU — compiled on TPU)
— mirroring the reference's kernel unit tests ``unit_test/test_geadd.cc``
/ ``test_gescale.cc`` / ``test_geset.cc`` / ``test_norm.cc`` against
straight-line references."""

import numpy as np
import pytest
import jax.numpy as jnp

from slate_tpu.ops import pallas_kernels as pk


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def test_matmul(rng):
    a = jnp.asarray(rng.standard_normal((512, 384)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((384, 256)).astype(np.float32))
    c = pk.matmul(a, b, bm=128, bn=128, bk=128)
    assert float(jnp.abs(c - a @ b).max()) < 1e-3


def test_matmul_f64(rng):
    a = jnp.asarray(rng.standard_normal((256, 256)))
    b = jnp.asarray(rng.standard_normal((256, 128)))
    c = pk.matmul(a, b, bm=128, bn=128, bk=128)
    assert float(jnp.abs(c - a @ b).max()) < 1e-12 * 256


def test_tile_norms(rng):
    t = jnp.asarray(rng.standard_normal((6, 64, 128)))
    got = pk.tile_norms(t, "max")
    np.testing.assert_allclose(np.asarray(got),
                               np.abs(np.asarray(t)).max(axis=(1, 2)))
    got = pk.tile_norms(t, "fro")
    np.testing.assert_allclose(np.asarray(got),
                               (np.asarray(t) ** 2).sum(axis=(1, 2)),
                               rtol=1e-12)


def test_tzset_tzscale(rng):
    x = jnp.asarray(rng.standard_normal((256, 256)))
    z = np.asarray(pk.tzset(x, True, 0.5, 2.0, bm=128, bn=128))
    i, j = np.indices((256, 256))
    xn = np.asarray(x)
    assert np.all(z[i > j] == 0.5) and np.all(z[i == j] == 2.0)
    assert np.all(z[i < j] == xn[i < j])
    s = np.asarray(pk.tzscale(x, False, 2.0, 3.0, bm=128, bn=128))
    assert np.allclose(s[i < j], 2 * xn[i < j])
    assert np.allclose(s[i == j], 3 * xn[i == j])
    assert np.all(s[i > j] == xn[i > j])


def test_geadd_scale_rc(rng):
    x = jnp.asarray(rng.standard_normal((256, 128)))
    y = jnp.asarray(rng.standard_normal((256, 128)))
    out = pk.geadd(2.0, x, -0.5, y, bm=128, bn=128)
    np.testing.assert_allclose(np.asarray(out),
                               2 * np.asarray(x) - 0.5 * np.asarray(y))
    r = jnp.asarray(rng.standard_normal(256))
    c = jnp.asarray(rng.standard_normal(128))
    w = pk.gescale_row_col(r, c, x, bm=128, bn=128)
    np.testing.assert_allclose(
        np.asarray(w),
        np.asarray(r)[:, None] * np.asarray(x) * np.asarray(c)[None, :])


@pytest.mark.parametrize("nb", [128, 256])
def test_chol_inv_panel(nb):
    """Fused Cholesky+inverse panel kernel (interpret mode on CPU)."""
    from slate_tpu.ops.pallas_kernels import chol_inv_panel
    rng = np.random.default_rng(3)
    g = rng.standard_normal((nb, nb)).astype(np.float32)
    spd = g @ g.T + nb * np.eye(nb, dtype=np.float32)
    l, linv = map(np.asarray, chol_inv_panel(jnp.asarray(spd)))
    assert np.allclose(np.triu(l, 1), 0) and np.allclose(np.triu(linv, 1), 0)
    assert np.linalg.norm(l @ l.T - spd) / np.linalg.norm(spd) < 1e-5
    assert np.linalg.norm(l @ linv - np.eye(nb)) < 1e-4


def test_trtri_panel():
    from slate_tpu.ops.pallas_kernels import trtri_panel
    rng = np.random.default_rng(5)
    nb = 256
    l = np.tril(rng.standard_normal((nb, nb))).astype(np.float32)
    l += nb * np.eye(nb, dtype=np.float32)
    linv = np.asarray(trtri_panel(jnp.asarray(l)))
    assert np.linalg.norm(l @ linv - np.eye(nb)) < 1e-4
