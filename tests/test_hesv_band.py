"""Hermitian-indefinite + band routine tests — mirroring the reference
testers ``test/test_hesv.cc``, ``test_gbsv.cc``, ``test_pbsv.cc``,
``test_gbmm.cc``, ``test_hbmm.cc``, ``test_tbsm.cc``: residual identities
against dense numpy references.
"""

import numpy as np
import pytest
import jax.numpy as jnp

import slate_tpu as st
from slate_tpu.enums import Diag, Side, Uplo
from slate_tpu.matrix import BandMatrix, HermitianBandMatrix, TriangularBandMatrix


def _band(rng, m, n, kl, ku, dtype=np.float64):
    a = rng.standard_normal((m, n))
    if np.dtype(dtype).kind == "c":
        a = a + 1j * rng.standard_normal((m, n))
    i, j = np.indices((m, n))
    a[(j - i > ku) | (i - j > kl)] = 0
    return a.astype(dtype)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n", [1, 2, 5, 40, 65])
def test_hesv(dtype, n):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        a = a + 1j * rng.standard_normal((n, n))
    a = ((a + a.conj().T) / 2).astype(dtype)
    b = rng.standard_normal((n, 2)).astype(dtype)
    f, x = st.hesv(jnp.asarray(a), jnp.asarray(b))
    resid = np.abs(a @ np.asarray(x) - b).max()
    assert resid < 1e-10 * max(1, np.abs(a).max()) * n


def test_hetrf_tridiagonal_T():
    rng = np.random.default_rng(1)
    n = 30
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    f = st.hetrf(jnp.asarray(a))
    # P·A·Pᴴ = L·T·Lᴴ
    l = np.asarray(f.l) + np.eye(n)
    d, e = np.asarray(f.d), np.asarray(f.e)
    t = np.diag(d) + np.diag(e, -1) + np.diag(e, 1)
    perm = np.arange(n)
    ipiv = np.asarray(f.ipiv)
    for j in range(n - 2):
        p = ipiv[j]
        perm[[j + 1, p]] = perm[[p, j + 1]]
    pa = a[perm][:, perm]
    assert np.abs(l @ t @ l.T - pa).max() < 1e-11


def test_gbmm():
    rng = np.random.default_rng(2)
    m, n, k, kl, ku = 30, 20, 25, 3, 5
    ab = _band(rng, m, k, kl, ku)
    A = BandMatrix(jnp.asarray(ab), kl=kl, ku=ku)
    b = rng.standard_normal((k, n))
    c = rng.standard_normal((m, n))
    out = st.gbmm(2.0, A, jnp.asarray(b), -1.0, jnp.asarray(c))
    assert np.abs(np.asarray(out) - (2 * ab @ b - c)).max() < 1e-12


def test_hbmm():
    rng = np.random.default_rng(3)
    n, kd = 24, 4
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    i, j = np.indices((n, n))
    a[np.abs(i - j) > kd] = 0
    A = HermitianBandMatrix(jnp.asarray(np.tril(a)), kd=kd, uplo=Uplo.Lower)
    b = rng.standard_normal((n, 3))
    c = rng.standard_normal((n, 3))
    out = st.hbmm(Side.Left, 1.0, A, jnp.asarray(b), 0.5, jnp.asarray(c))
    assert np.abs(np.asarray(out) - (a @ b + 0.5 * c)).max() < 1e-12


@pytest.mark.parametrize("kd", [1, 4, 9])
def test_pbsv(kd):
    rng = np.random.default_rng(4)
    n = 36
    a = rng.standard_normal((n, n))
    i, j = np.indices((n, n))
    a[np.abs(i - j) > kd] = 0
    spd = a @ a.T + n * np.eye(n)       # SPD with bandwidth ≤ 2kd... make band
    i, j = np.indices((n, n))
    spd[np.abs(i - j) > kd] = 0         # keep band, still diag-dominant SPD
    A = HermitianBandMatrix(jnp.asarray(np.tril(spd)), kd=kd,
                            uplo=Uplo.Lower, nb=8)
    b = rng.standard_normal((n, 2))
    f, x = st.pbsv(A, jnp.asarray(b))
    assert np.abs(spd @ np.asarray(x) - b).max() < 1e-10
    # factor stays within the band
    lv = np.asarray(f.data)
    assert np.abs(lv[(i - j > kd) | (j > i)]).max() < 1e-12
    assert np.abs(np.tril(lv) @ np.tril(lv).T - spd).max() < 1e-10


def test_gbsv():
    rng = np.random.default_rng(5)
    n, kl, ku = 40, 3, 2
    ab = _band(rng, n, n, kl, ku) + np.eye(n) * n
    A = BandMatrix(jnp.asarray(ab), kl=kl, ku=ku, nb=8)
    b = rng.standard_normal((n, 2))
    f, piv, x = st.gbsv(A, jnp.asarray(b))
    assert np.abs(ab @ np.asarray(x) - b).max() < 1e-10
    assert f.ku == kl + ku


def test_tbsm():
    rng = np.random.default_rng(6)
    n, kd = 32, 4
    l = np.tril(_band(rng, n, n, kd, 0)) + np.eye(n) * n
    A = TriangularBandMatrix(jnp.asarray(l), kd=kd, uplo=Uplo.Lower,
                             diag=Diag.NonUnit, nb=8)
    b = rng.standard_normal((n, 3))
    x = st.tbsm(Side.Left, 1.0, A, jnp.asarray(b))
    assert np.abs(l @ np.asarray(x) - b).max() < 1e-11


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb", [(96, 16), (131, 32), (200, 48)])
def test_hetrf_blocked_matches_unblocked(dtype, n, nb):
    """The panel-blocked Aasen path (deferred her2k trailing updates,
    watermarked swaps) reproduces the rank-1 reference loop exactly:
    same pivots, same factors to rounding."""
    import importlib
    Hm = importlib.import_module("slate_tpu.linalg.hesv")
    rng = np.random.default_rng(31)
    a = rng.standard_normal((n, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    a = (a + a.conj().T).astype(dtype)
    b = rng.standard_normal((n, 4))
    l, d, e, ipiv = Hm._hetrf_blocked(jnp.asarray(a), nb)
    f = Hm.HetrfFactors(l=l, d=d, e=e, ipiv=ipiv)
    x = np.asarray(Hm.hetrs(f, jnp.asarray(b.astype(dtype))))
    r = np.linalg.norm(a @ x - b) / (np.linalg.norm(a) * np.linalg.norm(x))
    assert r < 1e-12
    # driver picks the blocked path at this size
    f2 = Hm.hetrf(jnp.asarray(a), {"block_size": nb})
    assert np.array_equal(np.asarray(f2.ipiv), np.asarray(ipiv))


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_gtsv_scan_pivoted(dtype):
    import jax
    """Traceable gtsv (lax.scan, adjacent-row pivoting) solves an
    indefinite Hermitian tridiagonal with a forced zero pivot."""
    from slate_tpu.linalg.hesv import _gtsv_scan
    rng = np.random.default_rng(7)
    n = 150
    d = rng.standard_normal(n)
    d[3] = 0.0   # forces a swap step
    e = rng.standard_normal(n - 1).astype(dtype)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        e = e + 1j * rng.standard_normal(n - 1)
    b = rng.standard_normal((n, 3)).astype(dtype)
    t = np.diag(d.astype(dtype)) + np.diag(e, -1) + np.diag(np.conj(e), 1)
    x = np.asarray(jax.jit(_gtsv_scan)(jnp.asarray(d), jnp.asarray(e),
                                       jnp.asarray(b)))
    assert np.linalg.norm(t @ x - b) / np.linalg.norm(b) < 1e-12


def test_hetrs_under_jit_matches_eager():
    import jax
    """Jitted hetrs uses the O(n·nrhs) scan solve, not a dense O(n³)
    fallback, and matches the eager (host banded) path."""
    from slate_tpu.linalg.hesv import hetrf, hetrs
    rng = np.random.default_rng(8)
    n = 200
    a = rng.standard_normal((n, n))
    a = (a + a.T) / 2
    b = rng.standard_normal((n, 4))
    f = hetrf(jnp.asarray(a))
    x_e = np.asarray(hetrs(f, jnp.asarray(b)))
    x_j = np.asarray(jax.jit(
        lambda ft, fb: hetrs(type(f)(*ft), fb))(tuple(f), jnp.asarray(b)))
    assert np.allclose(x_j, x_e, atol=1e-9)
    assert np.linalg.norm(a @ x_j - b) / np.linalg.norm(b) < 1e-10
