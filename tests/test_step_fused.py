"""The ISSUE-6 fused right-looking factorization STEP mega-kernels —
ONE pallas_call owns panel + trsm + trailing update of a whole
block-column step (``getrf_step_fused`` / ``potrf_step_fused``) — and
the ``lu_step`` / ``potrf_step`` autotuned step-composition sites that
ship them, exercised in interpret mode on CPU (the same program the TPU
compiles, so pivot/factor parity and residuals here certify the
default-capable path).
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import scipy.linalg as sla

import slate_tpu as st
from slate_tpu.linalg.lu import getrf_scattered
from slate_tpu.ops import blocks
from slate_tpu.perf import autotune, metrics
from slate_tpu.perf.hlo_profile import count_pallas_calls


def _scipy_perm(a):
    """Replay scipy's swap sequence into a permutation vector."""
    _, piv = sla.lu_factor(np.asarray(a, np.float64)
                           if a.dtype == np.float64 else np.asarray(a),
                           check_finite=False)
    want = np.arange(a.shape[0])
    for k, p in enumerate(piv):
        want[k], want[p] = want[p], want[k]
    return want


def _check_lu(a, nb, step, pivot_parity=True, tol=3.0):
    """Residual gate + (optionally) scipy-exact pivots for one step
    composition of the scattered driver."""
    m, n = a.shape
    lu, perm = jax.jit(
        lambda x: getrf_scattered(x, nb, step=step))(jnp.asarray(a))
    lu, perm = np.asarray(lu), np.asarray(perm)
    k = min(m, n)
    assert sorted(perm.tolist()) == list(range(m)), "perm not a permutation"
    lmat = np.tril(lu[:, :k], -1) + np.eye(m, k, dtype=a.dtype)
    umat = np.triu(lu[:k])
    eps = np.finfo(a.dtype).eps
    res = (np.abs(a[perm] - lmat @ umat).max()
           / (np.abs(a).max() * max(m, n) * eps))
    assert res < tol, f"scaled residual {res} ({step})"
    # TRUE partial pivoting: |L| ≤ 1 up to roundoff
    assert np.abs(np.tril(lu[:, :k], -1)).max() <= 1.0 + 100 * eps
    if pivot_parity:
        np.testing.assert_array_equal(perm[:k], _scipy_perm(a)[:k])
    return lu, perm


class TestGetrfStepFused:
    """Driver-level parity of the fused step depths vs scipy across
    square/tall × f32/f64 × the nb sweep the ISSUE names."""

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    @pytest.mark.parametrize("m,n", [(256, 256), (384, 256)])
    def test_shapes(self, m, n, dtype):
        a = np.random.default_rng(m + n).standard_normal(
            (m, n)).astype(dtype)
        _check_lu(a, 128, "fused")

    @pytest.mark.parametrize("nb", [128, 256, 512])
    def test_nb_sweep(self, nb):
        n = 2 * nb if nb <= 256 else nb
        a = np.random.default_rng(nb).standard_normal(
            (n, n)).astype(np.float32)
        _check_lu(a, nb, "fused")

    def test_fused_trsm_depth(self):
        a = np.random.default_rng(5).standard_normal(
            (256, 256)).astype(np.float32)
        _check_lu(a, 128, "fused_trsm")

    def test_depths_agree_on_pivots(self):
        """All three step compositions run the SAME panel arithmetic —
        their pivots must be identical, and the factors must agree to
        gemm-rounding (the fused path reorders the trailing products)."""
        a = np.random.default_rng(6).standard_normal(
            (256, 256)).astype(np.float32)
        outs = {s: _check_lu(a, 128, s) for s in
                ("composed", "fused", "fused_trsm")}
        lu0, perm0 = outs["composed"]
        for s in ("fused", "fused_trsm"):
            lu, perm = outs[s]
            np.testing.assert_array_equal(perm, perm0)
            assert np.abs(lu - lu0).max() < 1e-3 * np.abs(lu0).max()

    def test_many_tied_pivots(self):
        """Adversarial ±1 matrix: every column's pivot search hits an
        m-way exact magnitude tie; the fused step must still produce a
        valid partial-pivot factorization (distinct pivots, |L| ≤ 1,
        residual-gated) even though tie ORDER differs from LAPACK."""
        rng = np.random.default_rng(13)
        a = np.sign(rng.standard_normal((256, 256))).astype(np.float32)
        _check_lu(a, 128, "fused", pivot_parity=False)


class TestPotrfStepFused:
    """Factor parity of the whole-step Cholesky kernel vs LAPACK."""

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    @pytest.mark.parametrize("n,nb", [(256, 128), (384, 128), (512, 256)])
    def test_factor_parity(self, n, nb, dtype):
        rng = np.random.default_rng(n + nb)
        g = rng.standard_normal((n, n)).astype(dtype)
        spd = g @ g.T + n * np.eye(n, dtype=dtype)
        l = np.asarray(jax.jit(
            lambda x: blocks.potrf_steps(x, nb))(jnp.asarray(spd)))
        eps = np.finfo(dtype).eps
        res = np.linalg.norm(l @ l.T - spd) / (
            np.linalg.norm(spd) * eps * n)
        assert res < 3.0, res
        assert np.abs(np.triu(l, 1)).max() == 0.0
        ref = np.linalg.cholesky(spd.astype(np.float64))
        dev = np.abs(l - ref).max() / np.abs(ref).max()
        assert dev < 300 * eps, dev

    def test_nb512(self):
        n, nb = 1024, 512
        rng = np.random.default_rng(7)
        g = rng.standard_normal((n, n)).astype(np.float32)
        spd = g @ g.T + n * np.eye(n, dtype=np.float32)
        l = np.asarray(jax.jit(
            lambda x: blocks.potrf_steps(x, nb))(jnp.asarray(spd)))
        eps = np.finfo(np.float32).eps
        res = np.linalg.norm(l @ l.T - spd) / (
            np.linalg.norm(spd) * eps * n)
        assert res < 3.0, res

    def test_matches_composed_strips(self):
        rng = np.random.default_rng(8)
        g = rng.standard_normal((256, 256)).astype(np.float32)
        spd = g @ g.T + 256 * np.eye(256, dtype=np.float32)
        l_f = np.asarray(blocks.potrf_steps(jnp.asarray(spd), 128))
        l_c = np.asarray(blocks.potrf_panels(jnp.asarray(spd), 128))
        assert np.abs(l_f - l_c).max() < 1e-3 * np.abs(l_c).max()


class TestLaunchAndRoundtripBudgets:
    """The acceptance pins: exactly 1 pallas_call per fused step, and
    the inter-stage HBM round-trip counter at its minimum (ZERO) on the
    fused paths."""

    def test_getrf_one_pallas_call_per_fused_step(self):
        for n, nb in ((256, 128), (384, 128)):
            a = jnp.zeros((n, n), jnp.float32)
            for step in ("fused", "fused_trsm", "composed"):
                calls = count_pallas_calls(
                    lambda x, s=step: getrf_scattered(x, nb, step=s), a)
                assert calls == n // nb, (step, calls)

    def test_potrf_one_pallas_call_per_fused_step(self):
        a = jnp.zeros((256, 256), jnp.float32)
        calls = count_pallas_calls(
            lambda x: blocks.potrf_steps(x, 128), a)
        assert calls == 2, calls

    def _roundtrips(self, fn, *args):
        was = metrics.enabled()
        metrics.reset()
        metrics.on()
        try:
            jax.make_jaxpr(fn)(*args)   # trace-time counters fire here
            snap = metrics.snapshot()["counters"]
        finally:
            metrics.reset()
            if not was:
                metrics.off()
        return snap.get(metrics.STEP_HBM_ROUNDTRIPS, 0.0)

    def test_fused_steps_pin_zero_hbm_roundtrips(self):
        a = jnp.zeros((256, 256), jnp.float32)
        assert self._roundtrips(
            lambda x: getrf_scattered(x, 128, step="fused"), a) == 0.0
        assert self._roundtrips(
            lambda x: blocks.potrf_steps(x, 128), a) == 0.0
        # composed paths materialize intermediates every non-final step
        assert self._roundtrips(
            lambda x: getrf_scattered(x, 128, step="composed"), a) == 3.0
        assert self._roundtrips(
            lambda x: blocks.potrf_panels(x, 128), a) > 0.0
        # the intermediate depth pays exactly ONE (the u12 re-gather)
        assert self._roundtrips(
            lambda x: getrf_scattered(x, 128, step="fused_trsm"), a) == 1.0


class TestEndToEndThroughStepSites:
    """gesv/posv routed through the fused step kernels by the autotune
    sites (force knobs), residual-gated end to end — proof the
    SHIPPED dispatch (not just the raw drivers) takes the fused path."""

    @pytest.fixture(autouse=True)
    def _force(self, monkeypatch):
        from slate_tpu.linalg import lu as lu_mod
        monkeypatch.setattr("slate_tpu.config.scattered_lu", True)
        monkeypatch.setattr(lu_mod, "_SCATTERED_NB", 128)
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                           "lu_step=fused,potrf_step=fused")
        autotune.reset_table()
        yield
        autotune.reset_table()

    def test_gesv(self):
        rng = np.random.default_rng(4)
        n, nrhs = 256, 3
        a = (rng.standard_normal((n, n)).astype(np.float32)
             + n * np.eye(n, dtype=np.float32))
        b = rng.standard_normal((n, nrhs)).astype(np.float32)
        lu, perm, x = st.gesv(st.Matrix.from_array(a, nb=128),
                              jnp.asarray(b))
        xv = np.asarray(x)
        eps = np.finfo(np.float32).eps
        res = (np.linalg.norm(a @ xv - b)
               / (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))
        assert res < 3, f"solve residual {res}"
        dec = autotune.decisions()
        assert any(k.startswith("lu_step|") and v == "fused"
                   for k, v in dec.items()), dec

    def test_posv(self):
        rng = np.random.default_rng(9)
        n, nrhs = 1024, 2
        g = rng.standard_normal((n, n)).astype(np.float32)
        a = (g @ g.T / n + np.eye(n, dtype=np.float32)).astype(np.float32)
        b = rng.standard_normal((n, nrhs)).astype(np.float32)
        fac, x = st.posv(st.HermitianMatrix(jnp.asarray(a),
                                            uplo=st.Uplo.Lower),
                         jnp.asarray(b))
        xv = np.asarray(x)
        eps = np.finfo(np.float32).eps
        res = (np.linalg.norm(a @ xv - b)
               / (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))
        assert res < 3, f"solve residual {res}"
        dec = autotune.decisions()
        assert any(k.startswith("potrf_step|") and v == "fused"
                   for k, v in dec.items()), dec


def test_u12_fallback_activations_drop(monkeypatch):
    """Satellite: the Newton-refined ``_u12_with_linv`` keeps the
    fast branch active (fallback count 0) on the panels the blocked
    recursion produces, and the fallback branch no longer captures the
    raw panel slice (it solves against the l11 the residual already
    materialized)."""
    from slate_tpu.linalg import lu as lu_mod

    monkeypatch.setenv("SLATE_TPU_METRICS_DEVICE", "1")
    monkeypatch.setattr(lu_mod, "_use_pallas_panel",
                        lambda m, w, dtype: dtype == jnp.float32
                        and w % 32 == 0 and m >= w)
    was = metrics.enabled()
    metrics.reset()
    metrics.on()
    try:
        n, nb = 192, 64
        rng = np.random.default_rng(2)
        a_np = (rng.standard_normal((n, n)).astype(np.float32)
                + n * np.eye(n, dtype=np.float32))
        lu, perm = lu_mod.getrf_rec(jnp.asarray(a_np), nb)
        jax.block_until_ready(lu)
        L = np.tril(np.asarray(lu), -1) + np.eye(n, dtype=np.float32)
        U = np.triu(np.asarray(lu))
        res = np.linalg.norm(L @ U - a_np[np.asarray(perm)]) / (
            np.linalg.norm(a_np) * np.finfo(np.float32).eps * n)
        assert res < 3, res
        snap = metrics.snapshot()["counters"]
        assert snap.get("lu.u12_linv.fast", 0) >= 1
        assert snap.get("lu.u12_linv.fallback", 0) == 0
    finally:
        metrics.reset()
        if not was:
            metrics.off()
        autotune.reset_table()
