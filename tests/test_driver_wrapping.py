"""Every public p* driver in the dist modules must be wrapped by
canonical_args (review finding: the _DRIVER_NAMES list in
parallel/__init__.py is maintained by hand; this test catches a new
driver that forgets to register)."""

import inspect

import slate_tpu.parallel  # noqa: F401 — triggers the wrapping
from slate_tpu.parallel import (dist_aux, dist_band, dist_blas3,
                                dist_factor, dist_hesv, dist_lu, dist_qr,
                                dist_twostage, dist_util)

#: names that look like drivers but take no DistMatrix (or are helpers).
#: predistribute and punmqr_conj ARE wrapped and must stay registered —
#: exempting them here would mask an accidental registry removal.
_EXEMPT = {
    "pstedc",            # takes (d, e, mesh) host vectors
    "padded_tiles", "ptranspose", "peye",
    "pgemm_auto",        # distributes its own operands
    "pvary",             # _jax_compat shim imported into the modules
}


def test_all_public_drivers_wrapped():
    missing = []
    for mod in (dist_aux, dist_band, dist_blas3, dist_factor, dist_hesv,
                dist_lu, dist_qr, dist_twostage, dist_util):
        for name, fn in vars(mod).items():
            if not name.startswith("p") or name.startswith("_"):
                continue
            if not inspect.isfunction(fn) and not callable(fn):
                continue
            if name in _EXEMPT or not callable(fn):
                continue
            sig_params = []
            try:
                sig_params = list(inspect.signature(fn).parameters)
            except (TypeError, ValueError):
                continue
            if not sig_params:
                continue
            if not hasattr(fn, "__wrapped_driver__"):
                missing.append(f"{mod.__name__}.{name}")
    # helpers that take DistMatrix but are internal plumbing keep their
    # p-less names; anything here is a public driver that skipped the
    # canonical_args registry in parallel/__init__.py
    assert not missing, f"unwrapped public drivers: {missing}"
