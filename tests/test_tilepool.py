"""ISSUE 17 — out-of-core tile pool: host-DRAM residency for
factorizations whose working set exceeds HBM.

Five structural guarantees under test:

* **residency protocol** — LRU eviction order, dirty write-back
  exactness (host DRAM is byte-for-byte the device value after flush),
  prefetch-hit accounting, and the off-by-default metrics contract
  (registry off → no ``ooc.*`` key ever materializes);
* **window-size bitwise parity** — a forced 2-tile window and an
  all-resident window produce bitwise-identical getrf/potrf factors
  (residency never changes arithmetic: an all-resident pool IS the
  in-core execution of the OOC driver), plus residual gates against
  the factorization identities;
* **dispatch** — with the ``ooc`` site forced, end-to-end gesv/posv
  route through the pool (decision recorded in the autotune table,
  host-link odometer moves) and still pass their residual gates;
* **checkpoint composition** — ``SLATE_TPU_CKPT_EVERY_STEPS`` +
  injected ``device_loss`` rewinds to the window-boundary snapshot and
  reproduces the uninterrupted factors bitwise (the PR 14 contract
  carried into the out-of-core drivers);
* **inertness** — forcing every OOC knob must not change compiled
  programs (traced operands keep the in-core path; the pool is
  host-side/eager-only), and the attr.py ``host`` stage is zero-flop
  so the roofline gap report still reconciles exactly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import slate_tpu as st
from slate_tpu import config
from slate_tpu.linalg import cholesky as chol_mod
from slate_tpu.linalg import lu as lu_mod
from slate_tpu.linalg import ooc
from slate_tpu.ops import tilepool
from slate_tpu.perf import attr, autotune, metrics, regress
from slate_tpu.resilience import inject


@pytest.fixture(autouse=True)
def _clean_state():
    autotune.reset_table()
    inject.clear_plan()
    metrics.reset()
    metrics.off()
    yield
    inject.clear_plan()
    metrics.reset()
    metrics.off()
    autotune.reset_table()


def _lu_mat(n, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)) + 2.0 * np.sqrt(n) * np.eye(n)
    return a.astype(dtype)


def _spd_mat(n, dtype=np.float32, seed=1):
    rng = np.random.default_rng(seed)
    g = rng.standard_normal((n, n))
    return (g @ g.T / n + np.eye(n)).astype(dtype)


def _lu_resid(a, lu, perm):
    n = a.shape[0]
    lmat = np.tril(lu, -1) + np.eye(n, dtype=a.dtype)
    umat = np.triu(lu)
    eps = np.finfo(a.dtype).eps
    return float(np.abs(a[perm] - lmat @ umat).max()
                 / (np.abs(a).max() * n * eps))


def _chol_resid(a, l):
    n = a.shape[0]
    eps = np.finfo(a.dtype).eps
    return float(np.linalg.norm(np.tril(l) @ np.tril(l).T - a)
                 / (np.linalg.norm(a) * eps * n))


def _ooc_counters():
    return {k: v for k, v in metrics.snapshot()["counters"].items()
            if k.startswith(("ooc.", "ckpt."))}


# ---------------------------------------------------------------------------
# The residency protocol: LRU, write-back, prefetch, metrics contract
# ---------------------------------------------------------------------------

class TestTilePool:

    def test_lru_eviction_order(self):
        metrics.on()
        a = _lu_mat(96)
        pool = tilepool.TilePool(a, 32, capacity=2, depth=0)
        pool.get(0, 0)
        pool.get(0, 1)
        pool.get(0, 2)               # over capacity: (0, 0) is LRU
        assert (0, 0) not in pool._resident
        assert (0, 1) in pool._resident and (0, 2) in pool._resident
        pool.get(0, 1)               # touch: (0, 1) becomes MRU
        pool.get(1, 0)               # now (0, 2) is the LRU victim
        assert (0, 2) not in pool._resident
        assert (0, 1) in pool._resident
        assert _ooc_counters().get("ooc.evictions") == 2.0

    def test_dirty_write_back_exact(self):
        a = _lu_mat(96)
        pool = tilepool.TilePool(a, 32, capacity=2, depth=0)
        fresh = jnp.asarray(
            np.random.default_rng(3).standard_normal((32, 32))
            .astype(np.float32))
        pool.put(1, 1, fresh)
        # host DRAM is stale until flush, then byte-for-byte exact
        assert not np.array_equal(pool.host[32:64, 32:64],
                                  np.asarray(fresh))
        pool.flush()
        assert np.array_equal(pool.host[32:64, 32:64],
                              np.asarray(fresh))
        # eviction write-back takes the same exact path
        other = fresh + jnp.float32(1.0)
        pool.put(2, 2, other)
        pool.get(0, 0)
        pool.get(0, 1)               # evicts the dirty (2, 2)
        assert (2, 2) not in pool._resident
        assert np.array_equal(pool.host[64:96, 64:96],
                              np.asarray(other))

    def test_prefetch_hit_accounting(self):
        metrics.on()
        a = _lu_mat(96)
        pool = tilepool.TilePool(a, 32, capacity=4, depth=2)
        assert pool.prefetch([(0, 0), (0, 1), (0, 2)]) == 2  # depth-capped
        pool.get(0, 0)
        pool.get(0, 1)
        c = _ooc_counters()
        assert c.get("ooc.prefetch.hits") == 2.0
        assert "ooc.prefetch.misses" not in c
        pool.get(0, 2)               # never prefetched: a miss
        assert _ooc_counters().get("ooc.prefetch.misses") == 1.0

    def test_bytes_odometer_counts_both_directions(self):
        a = _lu_mat(64)
        pool = tilepool.TilePool(a, 32, capacity=4, depth=0)
        tb = pool.tile_bytes
        pool.get(0, 0)                          # one fetch
        pool.put(0, 0, pool.get(0, 0) * 2.0)    # dirty
        pool.flush()                            # one write-back
        assert pool.bytes_moved == 2 * tb
        assert pool.host_gb_transferred() == pytest.approx(2 * tb / 1e9)

    def test_metrics_off_records_nothing(self):
        # the PR 4 contract: with the registry off (the default) every
        # pool event is a one-attribute-read no-op — no ooc.* key ever
        # materializes
        a = _lu_mat(96)
        pool = tilepool.TilePool(a, 32, capacity=2, depth=1)
        pool.prefetch([(0, 0)])
        pool.get(0, 0)
        pool.put(0, 1, pool.get(0, 1))
        pool.flush()
        snap = metrics.snapshot()
        assert not any(k.startswith("ooc.")
                       for k in (snap.get("counters") or {}))


# ---------------------------------------------------------------------------
# The OOC drivers: window parity, residuals, dispatch composition
# ---------------------------------------------------------------------------

class TestOOCDrivers:

    def test_getrf_window_parity_bitwise(self):
        a = _lu_mat(128)
        lu_all, p_all = ooc.getrf_ooc(jnp.asarray(a), nb=32,
                                      capacity=64, depth=4)
        lu_tiny, p_tiny = ooc.getrf_ooc(jnp.asarray(a), nb=32,
                                        capacity=2, depth=1)
        assert np.array_equal(np.asarray(lu_all), np.asarray(lu_tiny))
        assert np.array_equal(np.asarray(p_all), np.asarray(p_tiny))
        assert _lu_resid(a, np.asarray(lu_all), np.asarray(p_all)) < 3.0

    def test_getrf_residual_vs_incore(self):
        # vs the in-core dispatch the residual gate is the contract
        # (pivot ties and trailing-update summation order may differ)
        a = _lu_mat(128, seed=5)
        lu_p, perm_p = ooc.getrf_ooc(jnp.asarray(a), nb=32, capacity=3)
        lu_i, perm_i = lu_mod._getrf_partial(jnp.asarray(a), 32)
        assert _lu_resid(a, np.asarray(lu_p), np.asarray(perm_p)) < 3.0
        assert _lu_resid(a, np.asarray(lu_i), np.asarray(perm_i)) < 3.0

    def test_potrf_window_parity_bitwise(self):
        a = _spd_mat(128)
        l_all = ooc.potrf_ooc(jnp.asarray(a), nb=32, capacity=64,
                              depth=4)
        l_tiny = ooc.potrf_ooc(jnp.asarray(a), nb=32, capacity=2,
                               depth=1)
        assert np.array_equal(np.asarray(l_all), np.asarray(l_tiny))
        assert _chol_resid(a, np.asarray(l_all)) < 3.0

    def test_getrf_f64_supported(self):
        a = _lu_mat(96, dtype=np.float64, seed=7)
        lu, perm = ooc.getrf_ooc(jnp.asarray(a, jnp.float64), nb=32,
                                 capacity=3)
        assert np.asarray(lu).dtype == np.float64
        assert _lu_resid(a, np.asarray(lu), np.asarray(perm)) < 3.0

    def test_gesv_through_forced_site(self, monkeypatch):
        metrics.on()
        monkeypatch.setattr(config, "ooc", True)
        monkeypatch.setenv("SLATE_TPU_OOC_NB", "32")
        monkeypatch.setenv("SLATE_TPU_OOC_WINDOW_TILES", "3")
        a = _lu_mat(128, seed=2)
        b = np.random.default_rng(4).standard_normal(
            (128, 8)).astype(np.float32)
        lu, perm, x = lu_mod.gesv(jnp.asarray(a), jnp.asarray(b))
        resid = (np.linalg.norm(a @ np.asarray(x) - b)
                 / (np.linalg.norm(a) * np.linalg.norm(b)
                    * np.finfo(np.float32).eps * 128))
        assert resid < 3.0
        dec = autotune.decisions()
        assert any(k.startswith("ooc|") and v == "pool"
                   for k, v in dec.items()), sorted(dec)
        assert _ooc_counters().get("ooc.host_bytes", 0.0) > 0

    def test_posv_through_forced_site(self, monkeypatch):
        metrics.on()
        monkeypatch.setattr(config, "ooc", True)
        monkeypatch.setenv("SLATE_TPU_OOC_NB", "32")
        monkeypatch.setenv("SLATE_TPU_OOC_WINDOW_TILES", "3")
        a = _spd_mat(128, seed=3)
        b = np.random.default_rng(5).standard_normal(
            (128, 4)).astype(np.float32)
        fac, x = chol_mod.posv(
            st.HermitianMatrix(jnp.asarray(a), uplo=st.Uplo.Lower),
            jnp.asarray(b))
        resid = (np.linalg.norm(a @ np.asarray(x) - b)
                 / (np.linalg.norm(a) * np.linalg.norm(b)
                    * np.finfo(np.float32).eps * 128))
        assert resid < 3.0
        dec = autotune.decisions()
        assert any(k.startswith("ooc|") and v == "pool"
                   for k, v in dec.items()), sorted(dec)
        assert _ooc_counters().get("ooc.host_bytes", 0.0) > 0

    def test_config_off_never_pools(self, monkeypatch):
        metrics.on()
        monkeypatch.setattr(config, "ooc", False)
        monkeypatch.setenv("SLATE_TPU_OOC_NB", "32")
        lu, perm = lu_mod._getrf_partial(jnp.asarray(_lu_mat(128)), 32)
        assert _lu_resid(_lu_mat(128), np.asarray(lu),
                         np.asarray(perm)) < 3.0
        assert not any(k.startswith("ooc.")
                       for k in _ooc_counters())


# ---------------------------------------------------------------------------
# Checkpoint composition: window-boundary snapshots, bitwise rewind
# ---------------------------------------------------------------------------

class TestOOCCheckpoint:

    def test_getrf_device_loss_resume_bitwise(self, monkeypatch):
        metrics.on()
        monkeypatch.setenv("SLATE_TPU_CKPT_EVERY_STEPS", "2")
        a = jnp.asarray(_lu_mat(128, seed=9))
        lu_clean, p_clean = ooc.getrf_ooc(a, nb=32, capacity=3)
        inject.install(
            inject.FaultPlan(seed=7).add("step.boundary", "device_loss",
                                         rate=1.0, count=1))
        lu_chaos, p_chaos = ooc.getrf_ooc(a, nb=32, capacity=3)
        assert np.array_equal(np.asarray(lu_clean),
                              np.asarray(lu_chaos))
        assert np.array_equal(np.asarray(p_clean), np.asarray(p_chaos))
        c = _ooc_counters()
        assert c.get("ckpt.restored") == 1.0
        assert c.get("ckpt.saved", 0.0) >= 1.0

    def test_potrf_device_loss_resume_bitwise(self, monkeypatch):
        metrics.on()
        monkeypatch.setenv("SLATE_TPU_CKPT_EVERY_STEPS", "2")
        a = jnp.asarray(_spd_mat(128, seed=11))
        l_clean = ooc.potrf_ooc(a, nb=32, capacity=3)
        inject.install(
            inject.FaultPlan(seed=7).add("step.boundary", "device_loss",
                                         rate=1.0, count=1))
        l_chaos = ooc.potrf_ooc(a, nb=32, capacity=3)
        assert np.array_equal(np.asarray(l_clean), np.asarray(l_chaos))
        assert _ooc_counters().get("ckpt.restored") == 1.0

    def test_checkpointed_matches_unchunked_bitwise(self, monkeypatch):
        # chunking only changes WHEN the pool flushes, never arithmetic
        a = jnp.asarray(_lu_mat(128, seed=13))
        lu_mono, p_mono = ooc.getrf_ooc(a, nb=32, capacity=3)
        monkeypatch.setenv("SLATE_TPU_CKPT_EVERY_STEPS", "1")
        lu_chunk, p_chunk = ooc.getrf_ooc(a, nb=32, capacity=3)
        assert np.array_equal(np.asarray(lu_mono),
                              np.asarray(lu_chunk))
        assert np.array_equal(np.asarray(p_mono), np.asarray(p_chunk))


# ---------------------------------------------------------------------------
# Inertness and the pricing model
# ---------------------------------------------------------------------------

class TestInertAndModel:

    def test_lowering_bit_identical_with_ooc_forced(self, monkeypatch):
        a = jnp.asarray(_lu_mat(64))

        def lower():
            def f(v):        # fresh function: defeat the trace cache
                return lu_mod._getrf_partial(v, 32)

            return jax.jit(f).lower(a).as_text()

        base = lower()
        monkeypatch.setattr(config, "ooc", True)
        monkeypatch.setenv("SLATE_TPU_OOC_NB", "32")
        monkeypatch.setenv("SLATE_TPU_OOC_WINDOW_TILES", "2")
        autotune.reset_table()
        assert lower() == base, (
            "the pool is host-side/eager-only: under a trace the OOC "
            "knobs must not change the compiled program")

    def test_parse_label_ooc_marker(self):
        routine, dt, dims = attr.parse_label(
            "getrf_ooc_fp32_n131072_nb1024")
        assert routine == "getrf" and dt == "fp32"
        assert dims["n"] == 131072 and dims["nb"] == 1024
        assert dims["ooc"] == 1
        # the marker-free label stays marker-free
        assert "ooc" not in attr.parse_label("getrf_fp32_n8192_nb512")[2]

    def test_host_stage_zero_flop_reconciles(self):
        dims = {"m": 512, "n": 512, "nb": 128, "ooc": 1}
        stages, _rts = attr.stage_model("getrf", dims)
        by_name = {s["stage"]: s for s in stages}
        assert "host" in by_name
        assert by_name["host"]["flops"] == 0.0
        assert by_name["host"]["bytes"] > 0
        # zero-flop host stage leaves the normalization contract exact:
        # stage flops still sum to the model count (the 1% gap-report
        # reconciliation rides on this)
        total = sum(s["flops"] for s in stages)
        model = attr.model_flops("getrf", dims)
        assert total == pytest.approx(model, rel=1e-9)

    def test_pool_priced_above_incore(self):
        dims = {"m": 1024, "n": 1024, "nb": 256}
        t_inc = attr.predict_seconds("getrf", dims, "fp32",
                                     platform="cpu")
        t_pool = attr.predict_seconds("getrf", dict(dims, ooc=1),
                                      "fp32", platform="cpu")
        assert t_pool > t_inc        # the PCIe host stage costs time

    def test_pcie_peak_env_override(self, monkeypatch):
        monkeypatch.setenv("SLATE_TPU_PCIE_GBS", "64")
        assert attr.peaks("tpu")["pcie_gbs"] == 64.0

    def test_regress_judges_host_gb_lower_better(self):
        key = "getrf_ooc_fp32_n128_nb32_host_gb_transferred"
        assert regress.direction(key) == -1.0
        # an all-resident window legitimately moves ~0 GB — zero is a
        # measurement, not a failed-routine placeholder
        assert regress._num(0.0, key) == 0.0

    def test_choose_ooc_analytic_budget(self, monkeypatch):
        # off-TPU the ladder resolves in-core; the analytic HBM-budget
        # rule is still unit-testable through the chooser directly by
        # faking the platform check
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        monkeypatch.setenv("SLATE_TPU_OOC_HBM_MB", "1")  # 1 MiB budget
        autotune.reset_table()
        assert autotune.choose_ooc(1024, 256, jnp.float32,
                                   eligible=True) == "pool"
        dec = autotune.table().decisions
        assert any(k.startswith("ooc|") and v.get("source") == "analytic"
                   for k, v in dec.items()), sorted(dec)
