"""ISSUE 13 multichip scale-out pins: deep-lookahead panel rings,
tournament (CALU) pivoting, and chunked panel broadcasts.

Three structural guarantees make the new knobs trustworthy enough to
autotune:

* **bitwise neutrality** — lookahead depth and broadcast chunking are
  SCHEDULE knobs: every element still receives exactly the same
  arithmetic (rank-nb corrections off replicated operands; each column
  rides exactly one psum), so depth-2/chunked results are bitwise
  identical to the depth-1/whole-panel baselines, and on tie-free
  inputs the tournament nominates the same pivots as the maxloc chain
  and shares its elimination arithmetic (``_elim_col``) — bitwise
  identical factors there too.
* **collective budget** — the per-step collective count is pinned
  INDEPENDENT of lookahead depth (the ring updates use only replicated
  operands) and of the pivot backend (the tournament runs redundantly
  on the already-replicated panel); chunking splits the one panel psum
  into exactly ``chunks`` narrower psums moving the same total bytes.
* **residual gates** — the adversarial many-tied-pivot case (every
  candidate magnitude equal) may legitimately pick different pivots
  per backend, so there the gate is the end-to-end gesv residual, not
  bitwise equality.

All on the 2×4 virtual CPU mesh; the HLO pins hold for the TPU
lowering of the same programs.  Compiled baselines are shared through
module fixtures — each distinct (backend, pivot, depth, chunks) build
compiles exactly once in this module.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from slate_tpu.parallel import distribute, make_grid_mesh, pgesv, \
    undistribute
from slate_tpu.parallel.dist_factor import _build_ppotrf
from slate_tpu.parallel.dist_lu import _build_pgetrf
from slate_tpu.parallel.dist_qr import _build_pgeqrf
from slate_tpu.perf.hlo_profile import profile_fn

P, Q = 2, 4
N, NB = 64, 8
NT = N // NB
ML, NL = NT // P, NT // Q


def _rng(seed=0):
    return np.random.default_rng(seed)


@pytest.fixture(scope="module")
def mesh24():
    return make_grid_mesh(P, Q)


@pytest.fixture(scope="module")
def spd_dist(mesh24):
    g = _rng(0).standard_normal((N, N))
    a = g @ g.T + N * np.eye(N)
    return distribute(a, mesh24, NB, diag_pad=1.0, row_mult=Q, col_mult=P)


@pytest.fixture(scope="module")
def gen_dist(mesh24):
    """Tie-free general matrix (continuous iid entries: pivot-magnitude
    ties have probability zero)."""
    a = _rng(1).standard_normal((N, N)) + N * np.eye(N)
    return distribute(a, mesh24, NB, diag_pad=1.0, row_mult=Q, col_mult=P)


def _build(driver, mesh, *, pivot="maxloc", depth=1, chunks=1,
           geom=(NB, NT, ML, NL)):
    nb, nt, ml, nl = geom
    if driver == "ppotrf":
        return _build_ppotrf(mesh, nb, nt, ml, nl, "float64", "xla",
                             depth, chunks)
    if driver == "pgetrf":
        return _build_pgetrf(mesh, nb, nt, ml, nl, "float64", "xla",
                             pivot, depth, chunks)
    return _build_pgeqrf(mesh, nb, nt, ml, nl, "float64", "xla",
                         depth, chunks)


@pytest.fixture(scope="module")
def ref_potrf(mesh24, spd_dist):
    fn = _build("ppotrf", mesh24)
    return np.asarray(jax.jit(fn)(spd_dist.data))


@pytest.fixture(scope="module")
def ref_getrf(mesh24, gen_dist):
    lu, perm = jax.jit(_build("pgetrf", mesh24))(gen_dist.data)
    return np.asarray(lu), np.asarray(perm)


# ---------------------------------------------------------------------------
# Bitwise neutrality of the schedule knobs
# ---------------------------------------------------------------------------

def test_potrf_lookahead_depth_bitwise(mesh24, spd_dist, ref_potrf):
    deep = _build("ppotrf", mesh24, depth=2)
    out = np.asarray(jax.jit(deep)(spd_dist.data))
    assert np.array_equal(ref_potrf, out), \
        "ppotrf depth-2 ring diverged from the depth-1 baseline"


def test_getrf_lookahead_depth_bitwise(mesh24, gen_dist, ref_getrf):
    deep = _build("pgetrf", mesh24, depth=2)
    lu2, p2 = jax.jit(deep)(gen_dist.data)
    assert np.array_equal(ref_getrf[1], np.asarray(p2))
    assert np.array_equal(ref_getrf[0], np.asarray(lu2)), \
        "pgetrf depth-2 ring diverged from the depth-1 baseline"


@pytest.mark.slow
def test_geqrf_lookahead_depth_exact_to_roundoff(mesh24, gen_dist):
    """QR's ring correction is the one place the deep ring REASSOCIATES
    a reduction: pⱼ − V·Tᵀ·(Vᵀ·pⱼ) contracts Vᵀ·pⱼ over all M rows in
    ONE replicated gemm (zero extra collectives), where the depth-1
    panel correction rides the psum-reduced W (p partial gemms summed
    by the fabric).  Same arithmetic count, different association — so
    the pin here is exact-to-roundoff + identical shapes, not bitwise
    (potrf/getrf rings contract over nb only and stay bitwise)."""
    r0 = jax.jit(_build("pgeqrf", mesh24))(gen_dist.data)
    r2 = jax.jit(_build("pgeqrf", mesh24, depth=2))(gen_dist.data)
    eps = np.finfo(np.float64).eps
    for x0, x2, what in zip(r0, r2, ("qr", "tmats", "taus")):
        a0, a2 = np.asarray(x0), np.asarray(x2)
        scale = max(float(np.abs(a0).max()), 1.0)
        assert np.abs(a0 - a2).max() < 100 * eps * N * scale, \
            f"pgeqrf depth-2 {what} beyond roundoff of depth-1"


def test_tournament_bitwise_parity_tie_free(mesh24, gen_dist, ref_getrf):
    """On tie-free inputs the tournament nominates exactly the maxloc
    pivots and eliminates through the shared ``_elim_col`` arithmetic,
    so the packed factor AND the permutation are bitwise identical —
    the pin that makes the ``dist_pivot`` arbitration trustworthy."""
    tr = _build("pgetrf", mesh24, pivot="tournament")
    lu1, p1 = jax.jit(tr)(gen_dist.data)
    assert np.array_equal(ref_getrf[1], np.asarray(p1)), \
        "tournament picked different pivots on a tie-free matrix"
    assert np.array_equal(ref_getrf[0], np.asarray(lu1))


def test_chunked_bcast_bitwise(mesh24, spd_dist, ref_potrf):
    """Chunking only SPLITS the panel psum — every element still rides
    exactly one collective, so the factor is bitwise unchanged."""
    spl = _build("ppotrf", mesh24, chunks=2)
    out = np.asarray(jax.jit(spl)(spd_dist.data))
    assert np.array_equal(ref_potrf, out)


# ---------------------------------------------------------------------------
# End-to-end through the public drivers (the autotune-site wiring)
# ---------------------------------------------------------------------------

def _scaled_res(a, x, b):
    return np.linalg.norm(a @ x - b) / (
        np.linalg.norm(a) * np.linalg.norm(x) + np.linalg.norm(b))


def test_gesv_depth2_matches_depth1_end_to_end(mesh8, monkeypatch):
    """The forced ``dist_lookahead`` knob reaches pgesv through the
    build key, and the depth-2 solve is bitwise the depth-1 solve."""
    n, nb = 64, 16
    a = _rng(2).standard_normal((n, n)) + n * np.eye(n)
    b = _rng(3).standard_normal((n, 3))
    xs = {}
    for d in ("1", "2"):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                           "dist_lookahead=" + d)
        _, _, x = pgesv(a, b, mesh8, nb)
        xs[d] = np.asarray(undistribute(x))
    assert np.array_equal(xs["1"], xs["2"])
    assert _scaled_res(a, xs["2"], b) < 3 * np.finfo(np.float64).eps * n


@pytest.mark.parametrize("dtype", [
    np.float32,
    pytest.param(np.float64, marks=pytest.mark.slow)])
@pytest.mark.parametrize("pivot", ["maxloc", "tournament"])
def test_tied_pivots_residual_gated(mesh8, monkeypatch, dtype, pivot):
    """Adversarial many-tied-pivot case: a ±1 matrix ties EVERY pivot
    candidate's magnitude, so the two backends may legitimately pick
    different rows — the gate is the end-to-end gesv residual, for
    both dtypes, through the forced ``dist_pivot`` site."""
    n, nb = 64, 16
    rng = _rng(4)
    a = np.where(rng.standard_normal((n, n)) >= 0, 1.0, -1.0) \
        .astype(dtype)
    while abs(np.linalg.det(a.astype(np.float64))) < 1e-6:
        a = np.where(rng.standard_normal((n, n)) >= 0, 1.0,
                     -1.0).astype(dtype)
    b = rng.standard_normal((n, 3)).astype(dtype)
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "dist_pivot=" + pivot)
    _, _, x = pgesv(a, b, mesh8, nb)
    xh = np.asarray(undistribute(x))
    # ±1 matrices have real element growth; gate at the usual 3·eps·n
    # scaled residual times a growth allowance
    assert _scaled_res(a, xh, b) < 30 * np.finfo(dtype).eps * n


def test_chunked_trsm_sweeps_bitwise(mesh24, monkeypatch):
    """``dist_chunk`` reaches the ptrsm solve sweeps too — including
    the backward sweep's ``bcast_block_row``, the one row-space
    chunked broadcast in the codebase — and, like the factorization
    broadcasts, splitting is a pure schedule knob: the solve is
    bitwise the whole-psum baseline."""
    from slate_tpu.parallel import pposv
    from slate_tpu.perf import autotune

    n, nb = 128, 32
    g = _rng(31).standard_normal((n, n))
    a = g @ g.T + n * np.eye(n)
    b = _rng(32).standard_normal((n, 4))
    xs = {}
    for ch in ("whole", "4"):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "dist_chunk=" + ch)
        autotune.reset_table()
        try:
            _, x = pposv(a, b, mesh24, nb=nb)
            xs[ch] = np.asarray(undistribute(x))
        finally:
            autotune.reset_table()
    assert np.array_equal(xs["whole"], xs["4"])
    assert _scaled_res(a, xs["4"], b) < 3 * np.finfo(np.float64).eps * n


def test_geqrf_rides_dist_panel_site(mesh8, monkeypatch):
    """ISSUE 13 satellite: pgeqrf resolves the ``dist_panel`` site —
    forced to the CholQR² reconstruction panel it stays residual-gated
    and the decision lands in the autotune table keyed under geqrf."""
    from slate_tpu.parallel import pgeqrf
    from slate_tpu.perf import autotune

    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                       "dist_panel=pallas_panel")
    autotune.reset_table()
    m, n, nb = 128, 64, 32
    a = _rng(7).standard_normal((m, n)).astype(np.float32)
    da = distribute(a, mesh8, nb=nb, diag_pad=1.0, row_mult=Q,
                    col_mult=P)
    qr, _, _ = pgeqrf(da)
    r = np.triu(np.asarray(undistribute(qr)))[:n, :n]
    res = np.linalg.norm(a.T @ a - r.T @ r) / (np.linalg.norm(a) ** 2)
    assert res < 10 * np.finfo(np.float32).eps * m
    dec = autotune.decisions()
    hits = {k: v for k, v in dec.items()
            if k.startswith("dist_panel|geqrf")}
    assert hits and all(v == "pallas_panel" for v in hits.values()), \
        f"geqrf did not resolve the dist_panel site: {sorted(dec)}"
    autotune.reset_table()


# ---------------------------------------------------------------------------
# The pallas_fused dist_panel rung (panel + immediate trailing
# correction in ONE launch per step body) — kernel parity, end-to-end
# residual gates, launch census, and the VMEM eligibility gate
# ---------------------------------------------------------------------------

def test_fused_panel_kernels_match_composed():
    """``chol_l21_panel`` / ``lu_u12_panel`` fold the pallas_panel
    rung's glue gemms into the launch — same arithmetic, one
    invocation: the factor block is bitwise the shared
    ``_chol_inv_kernel``/``_trtri_panel_kernel`` output and the fused
    trailing solve matches the composed gemm (pair) to roundoff."""
    from slate_tpu.perf.autotune import kernel

    nb, m = 32, 96
    rng = _rng(11)
    g = rng.standard_normal((nb, nb))
    d = g @ g.T + nb * np.eye(nb)
    panel = rng.standard_normal((m, nb))
    l_ref, linv = kernel("chol_inv_panel")(jnp.asarray(d))
    l, x = kernel("chol_l21_panel")(jnp.asarray(d), jnp.asarray(panel))
    assert np.array_equal(np.asarray(l_ref), np.asarray(l))
    eps = np.finfo(np.float64).eps
    assert np.allclose(np.asarray(x) @ np.asarray(l).T, panel,
                       atol=100 * eps * nb * np.abs(panel).max())

    # tame subdiagonal: a raw N(0,1) unit-lower triangle's condition
    # grows ~2ⁿ (Viswanath–Trefethen), which would swamp the dev gate
    l11 = np.tril(rng.standard_normal((nb, nb)), -1) / np.sqrt(nb) \
        + np.eye(nb)
    rowblk = rng.standard_normal((nb, 3 * nb))
    u12, dev = kernel("lu_u12_panel")(jnp.asarray(l11),
                                      jnp.asarray(rowblk))
    linv2 = np.asarray(kernel("trtri_panel")(jnp.asarray(l11)))
    u1 = linv2 @ rowblk
    r1 = rowblk - l11 @ u1
    assert np.allclose(np.asarray(u12), u1 + linv2 @ r1,
                       atol=100 * eps * nb * np.abs(rowblk).max())
    assert float(np.asarray(dev)[0, 0]) < 1e-8
    assert np.allclose(l11 @ np.asarray(u12), rowblk,
                       atol=100 * eps * nb * np.abs(rowblk).max())


def test_dist_panel_fused_parity_end_to_end(mesh24, monkeypatch):
    """The fused rung must not move the numerics: pposv and pgesv
    residual-gated end to end with ``dist_panel=pallas_fused`` forced
    (interpret mode inside the CPU shard_map), including the
    depth-2-ring combination — the shipped TPU default configuration,
    where the ring's guarded U12 re-solve must stay consistent with
    the stored factor."""
    from slate_tpu.parallel import pposv
    from slate_tpu.perf import autotune

    n, nb = 192, 32
    g = _rng(51).standard_normal((n, n))
    a_spd = g @ g.T + n * np.eye(n)
    a_gen = _rng(52).standard_normal((n, n)) + n * np.eye(n)
    b = _rng(53).standard_normal((n, 4))
    eps = np.finfo(np.float64).eps
    for force in ("dist_panel=pallas_fused",
                  "dist_panel=pallas_fused,dist_lookahead=2"):
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", force)
        autotune.reset_table()
        try:
            _, x = pposv(a_spd, b, mesh24, nb=nb)
            assert _scaled_res(a_spd, np.asarray(undistribute(x)),
                               b) < 3 * eps * n, force
            _, _, x2 = pgesv(a_gen, b, mesh24, nb=nb)
            assert _scaled_res(a_gen, np.asarray(undistribute(x2)),
                               b) < 3 * eps * n, force
        finally:
            autotune.reset_table()


def test_dist_panel_fused_launch_budget(mesh24):
    """Census pin for the fused rung: ONE pallas_call per step body —
    the panel AND its immediate trailing correction ride a single
    launch (the depth-2 pgetrf ring adds exactly one more launch per
    body: the in-flight panels' concatenated U12 re-solve)."""
    from slate_tpu.parallel.dist_factor import _build_ppotrf
    from slate_tpu.parallel.dist_lu import _build_pgetrf
    from slate_tpu.parallel.dist_util import stage_bounds
    from slate_tpu.perf.hlo_profile import count_pallas_calls

    n, nb = 256, 32
    nt = n // nb
    ml, nl = nt // P, nt // Q
    nstages = len(stage_bounds(nt)) - 1
    data = jnp.zeros((n, n), jnp.float64)
    fn_c = _build_ppotrf(mesh24, nb, nt, ml, nl, "float64",
                         "pallas_fused")
    assert count_pallas_calls(fn_c, data) == nstages
    fn_l = _build_pgetrf(mesh24, nb, nt, ml, nl, "float64",
                         "pallas_fused")
    assert count_pallas_calls(fn_l, data) == nstages
    fn_l2 = _build_pgetrf(mesh24, nb, nt, ml, nl, "float64",
                          "pallas_fused", depth=2)
    assert count_pallas_calls(fn_l2, data) == 2 * nstages


def test_dist_panel_fused_vmem_gated(monkeypatch):
    """Unlike the (nb, nb)-operand pallas_panel rung, the fused
    kernels stage the full (m, nb) panel / (nb, w) block row in VMEM —
    the site must drop the rung (forced pins included) for shapes the
    budget cannot hold, falling back instead of shipping a launch
    Mosaic would reject at the ISSUE-13 target sizes."""
    from slate_tpu.parallel.dist_util import dist_panel_backend
    from slate_tpu.perf import autotune

    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                       "dist_panel=pallas_fused")
    autotune.reset_table()
    try:
        nb = 512
        assert dist_panel_backend("potrf", nb, jnp.float32,
                                  m=4096) == "pallas_fused"
        assert dist_panel_backend("potrf", nb, jnp.float32,
                                  m=65536) != "pallas_fused"
        assert dist_panel_backend("getrf", nb, jnp.float32,
                                  w=4096) == "pallas_fused"
        assert dist_panel_backend("getrf", nb, jnp.float32,
                                  w=1 << 20) != "pallas_fused"
    finally:
        autotune.reset_table()


# ---------------------------------------------------------------------------
# Collective budgets off the compiled HLO
# ---------------------------------------------------------------------------

#: compile-only profile geometry: the per-step collective COUNT is
#: geometry-independent (only the HLO is inspected, nothing runs), so
#: the pins compile the smallest program that still KEEPS its staged
#: while loops — nt = 8 (nt = 4 would give 1-trip stages XLA unrolls,
#: leaving no communicating loop bodies to census) at the tiny nb = 4
_PGEOM = (4, 8, 4, 2)                    # (nb, nt, ml, nl) on the 2x4 mesh
_PN = _PGEOM[0] * _PGEOM[1]


@pytest.fixture(scope="module")
def profiles(mesh24):
    """Every HLO profile this module pins, compiled once each: the
    (pivot, depth, chunks) variants of the three factorizations."""
    data = jnp.zeros((_PN, _PN), jnp.float64)
    out = {}
    for driver in ("ppotrf", "pgetrf", "pgeqrf"):
        for depth in (1, 2):
            out[(driver, "maxloc", depth, 1)] = profile_fn(
                _build(driver, mesh24, depth=depth, geom=_PGEOM), data)
    out[("pgetrf", "tournament", 1, 1)] = profile_fn(
        _build("pgetrf", mesh24, pivot="tournament", geom=_PGEOM), data)
    out[("ppotrf", "maxloc", 1, 2)] = profile_fn(
        _build("ppotrf", mesh24, chunks=2, geom=_PGEOM), data)
    return out


def _per_body_counts(prof):
    return [b.collective_count for b in prof.step_loops]


@pytest.mark.parametrize("driver", ["ppotrf", "pgetrf", "pgeqrf"])
def test_per_step_collectives_do_not_grow_with_depth(profiles, driver):
    """The acceptance pin: the lookahead ring updates use REPLICATED
    operands only, so the per-step collective count is identical at
    depth 1 and depth 2 — deeper rings buy overlap with redundant
    compute, never with extra fabric traffic."""
    base = _per_body_counts(profiles[(driver, "maxloc", 1, 1)])
    assert base, f"{driver}: no communicating step loops"
    deep = _per_body_counts(profiles[(driver, "maxloc", 2, 1)])
    assert deep == base, \
        f"{driver}: per-step collectives changed with lookahead " \
        f"depth 2: {base} -> {deep}"


def test_tournament_adds_no_collectives(profiles):
    """CALU runs redundantly on the already-replicated panel: the
    whole pivot search costs ZERO extra collectives per step."""
    assert _per_body_counts(profiles[("pgetrf", "tournament", 1, 1)]) \
        == _per_body_counts(profiles[("pgetrf", "maxloc", 1, 1)])


def test_chunked_bcast_splits_but_moves_same_bytes(profiles):
    """chunks=2 splits the ONE panel psum into exactly two narrower
    psums per step — collective count +1, total collective bytes
    unchanged (the dist_chunk trade the sweep prices with the ICI
    roofline)."""
    whole = profiles[("ppotrf", "maxloc", 1, 1)]
    split = profiles[("ppotrf", "maxloc", 1, 2)]
    for bw, bs in zip(whole.step_loops, split.step_loops):
        assert bs.collective_count == bw.collective_count + 1
        assert bs.collective_bytes == bw.collective_bytes
