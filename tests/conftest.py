"""Test configuration: CPU mesh simulation.

Mirrors the reference's laptop-testability strategy (SURVEY §4): where the
reference links serial MPI stubs or runs ``mpirun -np 4`` on one box, we
run the same SPMD code on 8 virtual CPU devices
(``--xla_force_host_platform_device_count=8``), overriding the axon/TPU
plugin that the environment pre-registers.

float64 is enabled so residual checks can compare against LAPACK-grade
reference results.
"""

import os

_flag = "--xla_force_host_platform_device_count=8"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    """2×4 mesh over the 8 virtual CPU devices, axes ('p','q')."""
    from slate_tpu.parallel.mesh import make_grid_mesh
    return make_grid_mesh(2, 4)
