"""Test configuration: CPU mesh simulation.

Mirrors the reference's laptop-testability strategy (SURVEY §4): where the
reference links serial MPI stubs or runs ``mpirun -np 4`` on one box, we
run the same SPMD code on 8 virtual CPU devices
(``--xla_force_host_platform_device_count=8``), overriding the axon/TPU
plugin that the environment pre-registers.

float64 is enabled so residual checks can compare against LAPACK-grade
reference results.
"""

import os

_flag = "--xla_force_host_platform_device_count=8"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    """2×4 mesh over the 8 virtual CPU devices, axes ('p','q')."""
    from slate_tpu.parallel.mesh import make_grid_mesh
    return make_grid_mesh(2, 4)


# ---------------------------------------------------------------------------
# Two test tiers, the reference's --quick CI practice
# (``/root/reference/test/run_tests.py``): the default ``pytest tests/``
# gate finishes in ~5 min on a 1-core host; the full sweep runs with
# ``--runslow``.  The slow set was measured with ``--durations`` on a
# 1-core build host (r5): every test ≥ 3.4 s lands here EXCEPT one kept
# representative per driver family, so the fast tier still touches
# gesv/geqrf/heev/svd/ScaLAPACK end to end.
# ---------------------------------------------------------------------------

# Hand-kept fast representatives (measured ≥ 3.4 s but deliberately NOT
# in the slow set, one per driver family): test_getrs_and_gesv,
# test_geqrf[64-64], test_heev[MethodEig.DC-float64],
# test_svd[40-40-float64], test_scalapack_api_smoke.
_SLOW_TESTS = frozenset({
    # ABFT envelope rungs (interpret-mode Pallas): the ``full`` depth
    # stays as the fast representative; the clean-envelope guard and
    # the chunked-vs-monolithic pin are re-proved by the fast
    # device_loss/pgetrf-verify tests at the same cadence
    "tests/test_abft.py::TestEnvelopeRungs::"
    "test_bitflip_detected_recomputed_every_depth[composed]",
    "tests/test_abft.py::TestEnvelopeRungs::"
    "test_bitflip_detected_recomputed_every_depth[fused_trsm]",
    "tests/test_abft.py::TestEnvelopeRungs::"
    "test_bitflip_detected_recomputed_every_depth[fused]",
    "tests/test_abft.py::TestEnvelopeRungs::"
    "test_clean_envelope_no_false_alarm",
    "tests/test_abft.py::TestPgetrfCheckpoint::"
    "test_chunked_bitwise_vs_monolithic",
    # fleet rebalance (round 20): the fleet fast subset joined the fast
    # tier, so an equivalent slice of the heaviest fast-tier sweeps moves
    # here (the 1-core wall drifts ~15% run to run, so the margin is
    # deliberately generous).  Each family keeps a fast representative:
    # test_heev_qdwh_spectra[clustered-float64],
    # test_heev_svd_dispatch_n256[float32], polar rectangular/interval,
    # full-fused shapes[256-256-float32] + nb_sweep[128] + the gesv
    # end-to-end, and the pallas collective-profile parity.
    "tests/test_blackbox.py::TestDistTimeline::test_pgetrf_timeline_matches_monolithic",
    "tests/test_collective_profile.py::test_phesv_residual_gate",
    "tests/test_full_fused.py::TestGetrfFullFused::test_depth_agreement",
    "tests/test_full_fused.py::TestGetrfFullFused::test_wide",
    "tests/test_full_fused.py::TestGetrfFullFused::test_nb_sweep[512]",
    "tests/test_full_fused.py::TestGetrfFullFused::test_nb_sweep[256]",
    "tests/test_full_fused.py::TestGetrfFullFused::test_shapes[256-256-float64]",
    "tests/test_full_fused.py::TestGetrfFullFused::test_shapes[384-256-float32]",
    "tests/test_full_fused.py::TestGetrfFullFused::test_shapes[384-256-float64]",
    "tests/test_full_fused.py::TestPotrfFullFused::test_nb512",
    "tests/test_full_fused.py::TestEndToEndThroughFullSites::test_posv",
    "tests/test_multichip_scaleout.py::test_dist_panel_fused_parity_end_to_end",
    "tests/test_qdwh.py::test_heev_qdwh_spectra[ill-float32]",
    "tests/test_qdwh.py::test_heev_qdwh_spectra[ill-float64]",
    "tests/test_qdwh.py::test_heev_qdwh_spectra[clustered-float32]",
    "tests/test_qdwh.py::test_polar_forced_step_variants_agree[qr]",
    "tests/test_qdwh.py::test_heev_svd_dispatch_n256[float64]",
    "tests/test_qdwh.py::test_crossover_consistency",
    "tests/test_cholesky.py::test_posv[Uplo.Lower-complex64]",
    "tests/test_cholesky.py::test_posv[Uplo.Lower-float32]",
    "tests/test_compat_api.py::TestScalapackApi::test_pgesv_pheev",
    "tests/test_compat_api.py::test_simplified_nopiv_and_indefinite_factor_verbs",
    "tests/test_dist_gaps.py::test_pgbsv[mesh11]",
    "tests/test_dist_gaps.py::test_pgbsv[mesh24]",
    "tests/test_dist_gaps.py::test_pgbsv_band_shapes[mesh11-4-7]",
    "tests/test_dist_gaps.py::test_pgbsv_band_shapes[mesh24-4-7]",
    "tests/test_dist_gaps.py::test_pgecondest[mesh11]",
    "tests/test_dist_gaps.py::test_pgelqf_punmlq[mesh11]",
    "tests/test_dist_gaps.py::test_pgelqf_punmlq[mesh24]",
    "tests/test_dist_gaps.py::test_pgetri[mesh24]",
    "tests/test_dist_gaps.py::test_phesv_complex_hermitian[mesh11]",
    "tests/test_dist_gaps.py::test_phesv_complex_hermitian[mesh24]",
    "tests/test_dist_gaps.py::test_phesv_n1024[mesh11]",
    "tests/test_dist_gaps.py::test_phesv_n1024[mesh24]",
    "tests/test_dist_twostage.py::TestDistStedc::test_dist_band_eig_no_replicated_host_array",
    "tests/test_dist_twostage.py::TestDistStedc::test_dist_band_svd_no_replicated_host_array",
    "tests/test_dist_twostage.py::TestDistStedc::test_dist_band_eig_complex_no_replicated_host_array",
    "tests/test_dist_twostage.py::TestDistStedc::test_pheev_dist_stedc_numerics",
    "tests/test_dist_twostage.py::TestDistStedc::test_pstedc_clustered_deflation",
    "tests/test_dist_twostage.py::TestDistStedc::test_pstedc_matches_scipy",
    "tests/test_dist_twostage.py::test_pge2tb_band_svd_match[complex128]",
    "tests/test_dist_twostage.py::test_phe2hb_band_similarity[complex128]",
    "tests/test_dist_twostage.py::test_pheev_mesh11",
    "tests/test_eig_svd.py::TestHeevBandFastPath::test_complex",
    "tests/test_eig_svd.py::test_he2hb_preserves_spectrum[32-8-complex128]",
    "tests/test_eig_svd.py::test_heev[MethodEig.DC-complex128]",
    "tests/test_eig_svd.py::test_heev[MethodEig.DC-float32]",
    "tests/test_eig_svd.py::test_hegv[1]",
    "tests/test_eig_svd.py::test_svd[40-40-complex128]",
    "tests/test_eig_svd.py::test_svd[56-32-complex128]",
    "tests/test_eig_svd.py::test_svd[56-32-float64]",
    "tests/test_eig_svd.py::test_svd_float32",
    "tests/test_hesv_band.py::test_hesv[65-float64]",
    "tests/test_hesv_band.py::test_hetrf_blocked_matches_unblocked[131-32-complex128]",
    "tests/test_hesv_band.py::test_hetrf_blocked_matches_unblocked[131-32-float64]",
    "tests/test_hesv_band.py::test_hetrf_blocked_matches_unblocked[200-48-complex128]",
    "tests/test_hesv_band.py::test_hetrf_blocked_matches_unblocked[200-48-float64]",
    "tests/test_hesv_band.py::test_hetrf_blocked_matches_unblocked[96-16-complex128]",
    "tests/test_hesv_band.py::test_hetrf_blocked_matches_unblocked[96-16-float64]",
    "tests/test_hesv_band.py::test_hetrs_under_jit_matches_eager",
    "tests/test_hesv_band.py::test_pbsv[1]",
    "tests/test_lu.py::TestScatteredLU::test_wide_f32_residual_gate",
    # fused-panel sweep: representatives kept fast are the kernel-level
    # contract tests and the gesv end-to-end (test_many_tied_pivots and
    # test_shapes_f32[256-256] moved in the round 20 rebalance; the
    # step-fused twins keep pivot-tie and shape coverage fast)
    "tests/test_lu_fused_panel.py::TestScatteredFusedParity::test_many_tied_pivots",
    "tests/test_lu_fused_panel.py::TestScatteredFusedParity::test_shapes_f32[256-256]",
    "tests/test_lu_fused_panel.py::TestScatteredFusedParity::test_shapes_f32[384-128]",
    "tests/test_lu_fused_panel.py::TestScatteredFusedParity::test_shapes_f32[128-256]",
    "tests/test_lu_fused_panel.py::TestScatteredFusedParity::test_shapes_f64[256-256]",
    "tests/test_lu_fused_panel.py::TestScatteredFusedParity::test_shapes_f64[384-128]",
    "tests/test_lu_fused_panel.py::TestScatteredFusedParity::test_shapes_f64[128-256]",
    "tests/test_lu_fused_panel.py::TestScatteredFusedParity::test_nb_sweep[128]",
    "tests/test_lu_fused_panel.py::TestScatteredFusedParity::test_nb_sweep[256]",
    "tests/test_lu_fused_panel.py::TestScatteredFusedParity::test_nb_sweep[512]",
    "tests/test_lu_fused_panel.py::TestEndToEndThroughFusedPath::test_getrf",
    # fused-step sweep (round 8, rebalanced round 20): representatives
    # kept fast are test_shapes[256-256-float32], test_fused_trsm_depth,
    # test_many_tied_pivots and the potrf [256-128]/[384-128-f32]/
    # [512-256-f32] parities (both end-to-end solves and nb_sweep[128]
    # moved; the full-fused gesv end-to-end keeps a fast through-site
    # solve, and the nb sweep is fully covered under --runslow)
    "tests/test_step_fused.py::TestEndToEndThroughStepSites::test_gesv",
    "tests/test_step_fused.py::TestEndToEndThroughStepSites::test_posv",
    "tests/test_step_fused.py::TestGetrfStepFused::test_nb_sweep[128]",
    "tests/test_step_fused.py::TestGetrfStepFused::test_depths_agree_on_pivots",
    "tests/test_step_fused.py::TestGetrfStepFused::test_nb_sweep[256]",
    "tests/test_step_fused.py::TestGetrfStepFused::test_nb_sweep[512]",
    "tests/test_step_fused.py::TestGetrfStepFused::test_shapes[256-256-float64]",
    "tests/test_step_fused.py::TestGetrfStepFused::test_shapes[384-256-float32]",
    "tests/test_step_fused.py::TestGetrfStepFused::test_shapes[384-256-float64]",
    "tests/test_step_fused.py::TestPotrfStepFused::test_nb512",
    "tests/test_step_fused.py::TestPotrfStepFused::test_factor_parity[384-128-float64]",
    "tests/test_step_fused.py::TestPotrfStepFused::test_factor_parity[512-256-float64]",
    "tests/test_lu.py::test_gesv_mixed_converges",
    "tests/test_lu.py::test_gesv_mixed_gmres_complex",
    "tests/test_lu.py::test_getrf_nopiv_dominant",
    "tests/test_lu.py::test_getrf_partial[130-float32]",
    "tests/test_lu.py::test_getrf_partial[130-float64]",
    "tests/test_lu.py::test_getrf_rectangular",
    "tests/test_lu.py::test_getrf_tntpiv[100-32]",
    "tests/test_lu.py::test_getrf_tntpiv[64-16]",
    "tests/test_lu.py::test_getrf_wide",
    "tests/test_lu.py::test_getri",
    "tests/test_lu.py::test_tall_panel_lu_pp_true_partial_pivot",
    "tests/test_pallas.py::test_chol_inv_panel[256]",
    "tests/test_parallel.py::TestPgemmA::test_gemm_a_collective_profile",
    "tests/test_qr.py::test_cholqr",
    "tests/test_qr.py::test_cholqr2_panel_guard_ill_conditioned",
    "tests/test_qr.py::test_gelqf_unmlq",
    "tests/test_qr.py::test_gels_cholqr_and_auto",
    "tests/test_qr.py::test_gels_qr[30-80]",
    "tests/test_qr.py::test_gels_qr[90-30]",
    "tests/test_qr.py::test_geqrf[120-40]",
    "tests/test_qr.py::test_geqrf[40-96]",
    "tests/test_qr.py::test_geqrf_complex",
    "tests/test_qr.py::test_unmqr_sides_ops[Op.NoTrans-Side.Left]",
})


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run the slow tier too (full sweep; ~20 min on one core)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: slow tier, skipped unless --runslow is given")


def _canonical_nodeid(item):
    """``tests/<file>::<test>`` regardless of pytest's rootdir (the ids
    in _SLOW_TESTS are repo-root-relative; a bare ``cd tests && pytest``
    would otherwise match nothing and silently run the full sweep)."""
    import pathlib
    here = pathlib.Path(__file__).parent
    try:
        rel = pathlib.Path(str(item.fspath)).resolve().relative_to(here)
    except ValueError:
        return item.nodeid
    rest = item.nodeid.split("::", 1)
    tail = ("::" + rest[1]) if len(rest) > 1 else ""
    return "tests/" + str(rel) + tail


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow tier: use --runslow")
    for item in items:
        if _canonical_nodeid(item) in _SLOW_TESTS or "slow" in item.keywords:
            item.add_marker(skip)
