"""Distributed two-stage eig/SVD over the CPU mesh.

Mirrors the reference's rank-count-independent validation (SURVEY §4):
the same residual gates on a 2×4 mesh and the serial-stub 1×1 mesh.
"""

import jax
import numpy as np
import pytest

from slate_tpu.parallel import (band_tiles_to_dense, distribute, pge2tb,
                                phe2hb, pheev, psvd, punmbr_ge2tb_q,
                                punmtr_he2hb, undistribute,
                                make_grid_mesh)


@pytest.fixture(scope="module")
def mesh24():
    return make_grid_mesh(2, 4)


@pytest.fixture(scope="module")
def mesh11():
    return make_grid_mesh(1, 1, devices=jax.devices()[:1])


def _rand_herm(n, dtype, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    a = (a + a.conj().T) / 2
    return a.astype(dtype)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_phe2hb_band_similarity(mesh24, dtype):
    """Band from phe2hb has the same spectrum as A (unitary congruence)."""
    n, nb = 96, 16
    a = _rand_herm(n, dtype)
    ad = distribute(a, mesh24, nb, row_mult=4, col_mult=2)
    fac, tmats, tiles = phe2hb(ad)
    band = band_tiles_to_dense(tiles, n, nb, lower=True)
    # band is Hermitian with lower bandwidth nb
    assert np.allclose(band, band.conj().T)
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) > nb
    assert np.abs(band[mask]).max() < 1e-10
    wa = np.linalg.eigvalsh(a)
    wb = np.linalg.eigvalsh(band)
    assert np.allclose(wa, wb, atol=1e-8 * max(1, np.abs(wa).max()))


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_pheev_vectors(mesh24, dtype):
    n, nb = 96, 16
    a = _rand_herm(n, dtype)
    w, zd = pheev(a, mesh24, nb)
    z = np.asarray(undistribute(zd))
    w = np.asarray(w)
    anorm = np.linalg.norm(a)
    assert np.linalg.norm(a @ z - z * w[None, :]) / (anorm * n) < 1e-12
    assert np.linalg.norm(z.conj().T @ z - np.eye(n)) < 1e-10
    assert np.allclose(w, np.linalg.eigvalsh(a), atol=1e-9 * anorm)


def test_pheev_values_only(mesh24):
    n, nb = 80, 16
    a = _rand_herm(n, np.float64, seed=3)
    w, z = pheev(a, mesh24, nb, jobz=False)
    assert z is None
    assert np.allclose(np.asarray(w), np.linalg.eigvalsh(a), atol=1e-10)


def test_pheev_mesh11(mesh11):
    n, nb = 48, 16
    a = _rand_herm(n, np.float64, seed=5)
    w, zd = pheev(a, mesh11, nb)
    z = np.asarray(undistribute(zd))
    assert np.linalg.norm(a @ z - z * np.asarray(w)[None, :]) < 1e-10 * n


def test_pheev_odd_n(mesh24):
    """n not a multiple of nb exercises the padded-tile masking."""
    n, nb = 90, 16
    a = _rand_herm(n, np.float64, seed=7)
    w, zd = pheev(a, mesh24, nb)
    z = np.asarray(undistribute(zd))
    assert z.shape == (n, n)
    assert np.linalg.norm(a @ z - z * np.asarray(w)[None, :]) < 1e-10 * n
    assert np.allclose(np.asarray(w), np.linalg.eigvalsh(a), atol=1e-9)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_pge2tb_band_svd_match(mesh24, dtype):
    """pge2tb band has the same singular values as A."""
    m, n, nb = 128, 96, 16
    rng = np.random.default_rng(11)
    a = rng.standard_normal((m, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * rng.standard_normal((m, n))
    a = a.astype(dtype)
    ad = distribute(a, mesh24, nb, row_mult=4, col_mult=2)
    fac, qt, pt, tiles = pge2tb(ad)
    band = band_tiles_to_dense(tiles, n, nb, lower=False)
    # upper-banded
    i, j = np.indices((n, n))
    assert np.abs(band[(j - i < 0) | (j - i > nb)]).max() < 1e-10
    sa = np.linalg.svd(a, compute_uv=False)
    sb = np.linalg.svd(band, compute_uv=False)
    assert np.allclose(sa, sb, atol=1e-9 * sa[0])


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_psvd_full(mesh24, dtype):
    m, n, nb = 128, 96, 16
    rng = np.random.default_rng(13)
    a = rng.standard_normal((m, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * rng.standard_normal((m, n))
    a = a.astype(dtype)
    s, ud, vd = psvd(a, mesh24, nb)
    s = np.asarray(s)
    u = np.asarray(undistribute(ud))
    v = np.asarray(undistribute(vd))
    assert np.allclose(s, np.linalg.svd(a, compute_uv=False),
                       atol=1e-9 * s[0])
    rec = u[:, :n] @ np.diag(s) @ v.conj().T
    assert np.linalg.norm(a - rec) / np.linalg.norm(a) < 1e-10
    assert np.linalg.norm(u[:, :n].conj().T @ u[:, :n] - np.eye(n)) < 1e-9
    assert np.linalg.norm(v.conj().T @ v - np.eye(n)) < 1e-9


def test_psvd_values_only_mesh11(mesh11):
    m, n, nb = 64, 48, 16
    rng = np.random.default_rng(17)
    a = rng.standard_normal((m, n))
    s, u, v = psvd(a, mesh11, nb, jobu=False, jobvt=False)
    assert u is None and v is None
    assert np.allclose(np.asarray(s), np.linalg.svd(a, compute_uv=False),
                       atol=1e-10)


def test_psvd_square_odd(mesh24):
    m = n = 90
    nb = 16
    rng = np.random.default_rng(19)
    a = rng.standard_normal((m, n))
    s, ud, vd = psvd(a, mesh24, nb)
    u = np.asarray(undistribute(ud))
    v = np.asarray(undistribute(vd))
    rec = u @ np.diag(np.asarray(s)) @ v.conj().T
    assert np.linalg.norm(a - rec) / np.linalg.norm(a) < 1e-10
