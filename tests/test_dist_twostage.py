"""Distributed two-stage eig/SVD over the CPU mesh.

Mirrors the reference's rank-count-independent validation (SURVEY §4):
the same residual gates on a 2×4 mesh and the serial-stub 1×1 mesh.
"""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from slate_tpu.parallel import (band_tiles_to_dense, distribute, pge2tb,
                                phe2hb, pheev, psvd, punmbr_ge2tb_q,
                                punmtr_he2hb, undistribute,
                                make_grid_mesh)


@pytest.fixture(scope="module")
def mesh24():
    return make_grid_mesh(2, 4)


@pytest.fixture(scope="module")
def mesh11():
    return make_grid_mesh(1, 1, devices=jax.devices()[:1])


def _rand_herm(n, dtype, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * rng.standard_normal((n, n))
    a = (a + a.conj().T) / 2
    return a.astype(dtype)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_phe2hb_band_similarity(mesh24, dtype):
    """Band from phe2hb has the same spectrum as A (unitary congruence)."""
    n, nb = 96, 16
    a = _rand_herm(n, dtype)
    ad = distribute(a, mesh24, nb, row_mult=4, col_mult=2)
    fac, tmats, tiles = phe2hb(ad)
    band = band_tiles_to_dense(tiles, n, nb, lower=True)
    # band is Hermitian with lower bandwidth nb
    assert np.allclose(band, band.conj().T)
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) > nb
    assert np.abs(band[mask]).max() < 1e-10
    wa = np.linalg.eigvalsh(a)
    wb = np.linalg.eigvalsh(band)
    assert np.allclose(wa, wb, atol=1e-8 * max(1, np.abs(wa).max()))


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_pheev_vectors(mesh24, dtype):
    n, nb = 96, 16
    a = _rand_herm(n, dtype)
    w, zd = pheev(a, mesh24, nb)
    z = np.asarray(undistribute(zd))
    w = np.asarray(w)
    anorm = np.linalg.norm(a)
    assert np.linalg.norm(a @ z - z * w[None, :]) / (anorm * n) < 1e-12
    assert np.linalg.norm(z.conj().T @ z - np.eye(n)) < 1e-10
    assert np.allclose(w, np.linalg.eigvalsh(a), atol=1e-9 * anorm)


def test_pheev_values_only(mesh24):
    n, nb = 80, 16
    a = _rand_herm(n, np.float64, seed=3)
    w, z = pheev(a, mesh24, nb, jobz=False)
    assert z is None
    assert np.allclose(np.asarray(w), np.linalg.eigvalsh(a), atol=1e-10)


def test_pheev_mesh11(mesh11):
    n, nb = 48, 16
    a = _rand_herm(n, np.float64, seed=5)
    w, zd = pheev(a, mesh11, nb)
    z = np.asarray(undistribute(zd))
    assert np.linalg.norm(a @ z - z * np.asarray(w)[None, :]) < 1e-10 * n


def test_pheev_odd_n(mesh24):
    """n not a multiple of nb exercises the padded-tile masking."""
    n, nb = 90, 16
    a = _rand_herm(n, np.float64, seed=7)
    w, zd = pheev(a, mesh24, nb)
    z = np.asarray(undistribute(zd))
    assert z.shape == (n, n)
    assert np.linalg.norm(a @ z - z * np.asarray(w)[None, :]) < 1e-10 * n
    assert np.allclose(np.asarray(w), np.linalg.eigvalsh(a), atol=1e-9)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_pge2tb_band_svd_match(mesh24, dtype):
    """pge2tb band has the same singular values as A."""
    m, n, nb = 128, 96, 16
    rng = np.random.default_rng(11)
    a = rng.standard_normal((m, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * rng.standard_normal((m, n))
    a = a.astype(dtype)
    ad = distribute(a, mesh24, nb, row_mult=4, col_mult=2)
    fac, qt, pt, tiles = pge2tb(ad)
    band = band_tiles_to_dense(tiles, n, nb, lower=False)
    # upper-banded
    i, j = np.indices((n, n))
    assert np.abs(band[(j - i < 0) | (j - i > nb)]).max() < 1e-10
    sa = np.linalg.svd(a, compute_uv=False)
    sb = np.linalg.svd(band, compute_uv=False)
    assert np.allclose(sa, sb, atol=1e-9 * sa[0])


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_psvd_full(mesh24, dtype):
    m, n, nb = 128, 96, 16
    rng = np.random.default_rng(13)
    a = rng.standard_normal((m, n))
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        a = a + 1j * rng.standard_normal((m, n))
    a = a.astype(dtype)
    s, ud, vd = psvd(a, mesh24, nb)
    s = np.asarray(s)
    u = np.asarray(undistribute(ud))
    v = np.asarray(undistribute(vd))
    assert np.allclose(s, np.linalg.svd(a, compute_uv=False),
                       atol=1e-9 * s[0])
    rec = u[:, :n] @ np.diag(s) @ v.conj().T
    assert np.linalg.norm(a - rec) / np.linalg.norm(a) < 1e-10
    assert np.linalg.norm(u[:, :n].conj().T @ u[:, :n] - np.eye(n)) < 1e-9
    assert np.linalg.norm(v.conj().T @ v - np.eye(n)) < 1e-9


def test_psvd_values_only_mesh11(mesh11):
    m, n, nb = 64, 48, 16
    rng = np.random.default_rng(17)
    a = rng.standard_normal((m, n))
    s, u, v = psvd(a, mesh11, nb, jobu=False, jobvt=False)
    assert u is None and v is None
    assert np.allclose(np.asarray(s), np.linalg.svd(a, compute_uv=False),
                       atol=1e-10)


def test_psvd_square_odd(mesh24):
    m = n = 90
    nb = 16
    rng = np.random.default_rng(19)
    a = rng.standard_normal((m, n))
    s, ud, vd = psvd(a, mesh24, nb)
    u = np.asarray(undistribute(ud))
    v = np.asarray(undistribute(vd))
    rec = u @ np.diag(np.asarray(s)) @ v.conj().T
    assert np.linalg.norm(a - rec) / np.linalg.norm(a) < 1e-10


def test_psvd_dist_middle_numerics(mesh24):
    """The scale-safe middle (checkpointed tb2bd + Golub–Kahan pstedc +
    sharded WY back-transforms) must reproduce the SVD at small n when
    forced on (``svd_dist``)."""
    native = pytest.importorskip("slate_tpu.native")
    if not native.available():
        pytest.skip(native.build_error())
    m, n, nb = 128, 96, 16
    rng = np.random.default_rng(23)
    a = rng.standard_normal((m, n))
    s, ud, vd = psvd(a, mesh24, nb, opts={"svd_dist": True})
    s = np.asarray(s)
    u = np.asarray(undistribute(ud))
    v = np.asarray(undistribute(vd))
    assert np.allclose(s, np.linalg.svd(a, compute_uv=False),
                       atol=1e-9 * s[0])
    rec = u[:, :n] @ np.diag(s) @ v.conj().T
    assert np.linalg.norm(a - rec) / np.linalg.norm(a) < 1e-10
    assert np.linalg.norm(u[:, :n].conj().T @ u[:, :n] - np.eye(n)) < 1e-9
    assert np.linalg.norm(v.conj().T @ v - np.eye(n)) < 1e-9


class TestDistStedc:
    def test_pstedc_matches_scipy(self, mesh8):
        from slate_tpu.parallel.dist_stedc import pstedc
        rng = np.random.default_rng(3)
        n = 700
        d = rng.standard_normal(n)
        e = rng.standard_normal(n - 1)
        w, q = pstedc(d, e, mesh8, host_cutoff=128)
        q = np.asarray(q)
        from scipy.linalg import eigh_tridiagonal
        w_ref = eigh_tridiagonal(d, e, eigvals_only=True)
        eps = np.finfo(np.float64).eps
        np.testing.assert_allclose(w, w_ref, atol=300 * eps * np.abs(
            w_ref).max())
        t = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
        res = (np.linalg.norm(t @ q - q * w[None, :])
               / (np.linalg.norm(t) * n * eps))
        orth = np.linalg.norm(q.T @ q - np.eye(n)) / (n * eps)
        assert res < 10 and orth < 10, (res, orth)

    def test_pstedc_clustered_deflation(self, mesh8):
        """Heavy deflation (repeated poles) exercises the Givens row
        formulation — the path where a sign/order slip corrupts columns
        while eigenvalues stay perfect."""
        from slate_tpu.parallel.dist_stedc import pstedc
        rng = np.random.default_rng(4)
        n = 512
        d = np.repeat(rng.standard_normal(8), 64)
        e = 1e-8 * rng.standard_normal(n - 1)
        w, q = pstedc(d, e, mesh8, host_cutoff=128)
        q = np.asarray(q)
        eps = np.finfo(np.float64).eps
        t = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
        res = (np.linalg.norm(t @ q - q * w[None, :])
               / (max(np.linalg.norm(t), 1.0) * n * eps))
        orth = np.linalg.norm(q.T @ q - np.eye(n)) / (n * eps)
        assert res < 10 and orth < 10, (res, orth)

    def test_pheev_dist_stedc_numerics(self, mesh8):
        """pheev through the distributed stedc path: residual +
        orthogonality gates (VERDICT r3 Missing #1 / Weak #3)."""
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        from slate_tpu.parallel.dist_twostage import pheev
        n, nb = 2048, 256
        rng = np.random.default_rng(5)
        g = rng.standard_normal((n, n))
        a = (g + g.T) / 2
        w, z = pheev(jnp.asarray(a), mesh8, nb=nb, jobz=True,
                     opts={"stedc_dist": True})
        from slate_tpu.parallel.dist import undistribute
        zg = np.asarray(undistribute(z))[:n, :n]
        w = np.asarray(w)
        eps = np.finfo(np.float64).eps
        res = (np.linalg.norm(a @ zg - zg * w[None, :])
               / (np.linalg.norm(a) * n * eps))
        orth = np.linalg.norm(zg.T @ zg - np.eye(n)) / (n * eps)
        assert res < 50 and orth < 50, (res, orth)

    def test_pheev_dist_stedc_complex(self, mesh24):
        """Complex-Hermitian input through the scale-safe middle: the
        zhbtrd-style c128 chase + phase fold + real pstedc + complex WY
        back-transform (VERDICT r4 Next #6b) must match eigh at small n
        when forced on."""
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        n, nb = 192, 16
        a = _rand_herm(n, np.complex128, seed=31)
        w, zd = pheev(a, mesh24, nb, opts={"stedc_dist": True})
        z = np.asarray(undistribute(zd))[:n, :n]
        w = np.asarray(w)
        assert np.allclose(w, np.linalg.eigvalsh(a),
                           atol=1e-9 * max(1.0, np.abs(w).max()))
        eps = np.finfo(np.float64).eps
        res = (np.linalg.norm(a @ z - z * w[None, :])
               / (np.linalg.norm(a) * n * eps))
        orth = np.linalg.norm(z.conj().T @ z - np.eye(n)) / (n * eps)
        assert res < 50 and orth < 50, (res, orth)

    def test_dist_band_eig_complex_band(self, mesh8):
        """dist_band_eig on a complex Hermitian band: residual +
        orthogonality + unitarity of the sharded Q."""
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        from slate_tpu.parallel.dist_twostage import dist_band_eig
        n, kd = 384, 12
        rng = np.random.default_rng(11)
        ab = np.zeros((n, kd + 2), dtype=np.complex128)
        ab[:, 0] = rng.standard_normal(n)          # real diagonal
        for dd in range(1, kd + 1):
            ab[:n - dd, dd] = (rng.standard_normal(n - dd)
                               + 1j * rng.standard_normal(n - dd)) / (1 + dd)
        w, q_dev = dist_band_eig(ab, kd, mesh8)
        dense = np.zeros((n, n), dtype=np.complex128)
        idx = np.arange(n)
        for dd in range(kd + 1):
            dense[idx[:n - dd] + dd, idx[:n - dd]] = ab[:n - dd, dd]
        dense = dense + np.tril(dense, -1).conj().T
        q = np.asarray(q_dev)
        w = np.asarray(w)
        eps = np.finfo(np.float64).eps
        res = (np.linalg.norm(dense @ q - q * w[None, :])
               / (max(np.linalg.norm(dense), 1) * n * eps))
        orth = np.linalg.norm(q.conj().T @ q - np.eye(n)) / (n * eps)
        assert res < 50 and orth < 50, (res, orth)

    def test_dist_band_eig_no_replicated_host_array(self, mesh8):
        """The distributed middle section (checkpointed chase + mesh
        stedc + device WY back-transform) must never hold an O(n²) host
        array: tracemalloc sees every NumPy buffer; the gate is n²/2
        doubles, half one replicated eigenvector matrix (the round-3
        path allocated ≥ 3·n² — z_tri, z_band, LAPACK workspace).
        n=4096 with kd=64 so the O(n·kd·nchunks) snapshot/log constants
        sit well under the gate."""
        import tracemalloc
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        from slate_tpu.parallel.dist_twostage import dist_band_eig
        n, kd = 4096, 64
        rng = np.random.default_rng(6)
        # random symmetric band in lower-band storage ab[c, d] = A[c+d, c]
        ab = np.zeros((n, kd + 2))
        for dd in range(kd + 1):
            ab[:n - dd, dd] = rng.standard_normal(n - dd) / (1 + dd)
        tracemalloc.start()
        w, q_dev = dist_band_eig(ab, kd, mesh8)
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # measured breakdown at this config: chase snapshots+logs ~47MB,
        # mesh stedc host control ~70MB, per-chunk WY packs ≤38MB — all
        # O(n·kd·nchunks)/O(cutoff²) constants.  The round-3 path
        # replicated >= 3·n² host doubles (z_tri + z_band + LAPACK
        # workspace = 400MB here); gate at 0.8·n² to pin the regression
        # while leaving headroom for the linear-term constants.
        assert peak < 0.8 * n * n * 8, \
            f"host peak {peak/1e6:.0f} MB suggests a replicated n^2 array"
        # residual check on probe vectors (O(n²) host at test scope only)
        dense = np.zeros((n, n))
        idx = np.arange(n)
        for dd in range(kd + 1):
            dense[idx[:n - dd] + dd, idx[:n - dd]] = ab[:n - dd, dd]
        dense = dense + np.tril(dense, -1).T
        q = np.asarray(q_dev)
        eps = np.finfo(np.float64).eps
        res = (np.linalg.norm(dense @ q - q * np.asarray(w)[None, :])
               / (max(np.linalg.norm(dense), 1) * n * eps))
        orth = np.linalg.norm(q.T @ q - np.eye(n)) / (n * eps)
        assert res < 50 and orth < 50, (res, orth)

    def test_dist_band_eig_complex_no_replicated_host_array(self, mesh8):
        """Complex-Hermitian band through the scale-safe middle under
        the tracemalloc gate (VERDICT r4 Next #6b done-criterion): the
        c128 chase + phase fold + pstedc + complex WY applies must keep
        host memory O(n·kd), never O(n²)."""
        import tracemalloc
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        from slate_tpu.parallel.dist_twostage import dist_band_eig
        n, kd = 2048, 48
        rng = np.random.default_rng(12)
        ab = np.zeros((n, kd + 2), dtype=np.complex128)
        ab[:, 0] = rng.standard_normal(n)
        for dd in range(1, kd + 1):
            ab[:n - dd, dd] = (rng.standard_normal(n - dd)
                               + 1j * rng.standard_normal(n - dd)) / (1 + dd)
        tracemalloc.start()
        w, q_dev = dist_band_eig(ab, kd, mesh8)
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # complex beats the f64 gate class by 2× element size: the
        # replicated alternative holds ≥ 2·n² c128 (32·n² bytes); gate
        # at 1.6·n²·8 bytes = 0.1× that, generous for the O(n·kd)
        # snapshot/log constants at this small n
        assert peak < 1.6 * n * n * 8, \
            f"host peak {peak/1e6:.0f} MB suggests a replicated n^2 array"
        dense = np.zeros((n, n), dtype=np.complex128)
        idx = np.arange(n)
        for dd in range(kd + 1):
            dense[idx[:n - dd] + dd, idx[:n - dd]] = ab[:n - dd, dd]
        dense = dense + np.tril(dense, -1).conj().T
        q = np.asarray(q_dev)
        w = np.asarray(w)
        eps = np.finfo(np.float64).eps
        res = (np.linalg.norm(dense @ q - q * w[None, :])
               / (max(np.linalg.norm(dense), 1) * n * eps))
        orth = np.linalg.norm(q.conj().T @ q - np.eye(n)) / (n * eps)
        assert res < 50 and orth < 50, (res, orth)

    def test_dist_band_svd_no_replicated_host_array(self, mesh8):
        """psvd's scale-safe middle under the same tracemalloc gate as
        the eig path: checkpointed tb2bd + Golub–Kahan pstedc + device
        WY applies must never hold an O(n²) host array (VERDICT r4
        Next #6 done-criterion)."""
        import tracemalloc
        native = pytest.importorskip("slate_tpu.native")
        if not native.available():
            pytest.skip(native.build_error())
        from slate_tpu.parallel.dist_svd import dist_band_svd
        n, kd = 4096, 64
        rng = np.random.default_rng(8)
        # random upper-band storage ab[c, d+1] = A[c-d, c]
        ab = np.zeros((n, kd + 3))
        for dd in range(kd + 1):
            ab[dd:, dd + 1] = rng.standard_normal(n - dd) / (1 + dd)
        tracemalloc.start()
        s, u_dev, v_dev = dist_band_svd(ab, kd, mesh8, True, True)
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # the GK solve runs pstedc at 2n, so the host-control constants
        # are ~2× the eig path's; the replicated alternative is ≥ 3·n²
        # (u_b + vh_b + LAPACK bdsdc workspace = 3·n²+ here).  Gate at
        # 1.2·n² doubles.
        assert peak < 1.2 * n * n * 8, \
            f"host peak {peak/1e6:.0f} MB suggests a replicated n^2 array"
        dense = np.zeros((n, n))
        idx = np.arange(n)
        for dd in range(kd + 1):
            dense[idx[:n - dd], idx[:n - dd] + dd] = ab[dd:, dd + 1]
        u = np.asarray(u_dev)
        v = np.asarray(v_dev)
        s = np.asarray(s)
        eps = np.finfo(np.float64).eps
        res = (np.linalg.norm(dense - (u * s[None, :]) @ v.T)
               / (max(np.linalg.norm(dense), 1) * n * eps))
        orth_u = np.linalg.norm(u.T @ u - np.eye(n)) / (n * eps)
        orth_v = np.linalg.norm(v.T @ v - np.eye(n)) / (n * eps)
        assert res < 50 and orth_u < 50 and orth_v < 50, \
            (res, orth_u, orth_v)
