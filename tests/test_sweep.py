"""Autotune v2 (slate_tpu/perf/sweep.py): analytical pre-pruning with
audited predicted gaps, the resumable sweep engine, bundle round-trip
(fresh module state resolves probe-free from the bundle, including
shapes the sweep never timed via the interpolating model),
stale-version rejection, quarantine-masks-bundle-entry, the >10×
analytical model guard, the shared pow2 bucketing helper across
autotune/serve/sweep keys, and the serve warm-start-from-bundle
zero-compile boot."""

import importlib
import json
import time

import numpy as np
import pytest

from slate_tpu.perf import autotune, metrics, sweep, xprof


@pytest.fixture
def atab(tmp_path, monkeypatch):
    """Fresh table on a tmp cache (the test_autotune pattern)."""
    monkeypatch.setenv("SLATE_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    autotune.reset_table()
    yield autotune
    autotune.reset_table()


def _toy(name, delay, result="out"):
    def setup():
        def run():
            time.sleep(delay)
            return result
        return run
    return autotune.Candidate(name, setup)


def _toy_site(predicted, durations):
    """A sweepable toy site: key (n, dtype, precision) like the real
    pow2-keyed sites, candidates that just sleep."""
    def build(u):
        key = (int(u["n"]), "float32", "HIGH")
        return key, [_toy(n2, d) for n2, d in durations.items()]

    def predict(key_parts, names, platform):
        return dict(predicted)

    return sweep.SiteSpec(build, predict)


def _results(keys, times, site="toyop", backend=None):
    return [{"site": site, "key_parts": list(kp), "times": dict(times),
             "backend": backend or min(times, key=times.get)}
            for kp in keys]


def _write(tmp_path, results, warm=(), version=None, pruned=()):
    blob = sweep.build_bundle(results, version or autotune._version_key(),
                              pruned=pruned, grid_name="test", warm=warm)
    path = tmp_path / "bundle.json"
    sweep.write_bundle(str(path), blob)
    return str(path), blob


class TestSharedBucketing:
    def test_one_pow2_helper_everywhere(self):
        """The ISSUE 11 bucketing fix: sweep grid keys, autotune cache
        keys and serve bucket keys must derive from ONE helper."""
        from slate_tpu.serve.queue import _bucket as serve_bucket

        for d in (1, 5, 8, 9, 37, 100, 511, 512, 513):
            assert autotune._bucket_dim(d) == sweep.pow2_bucket(d)
            assert serve_bucket(d) == sweep.pow2_bucket(d)
            assert serve_bucket(d, floor=1) == sweep.pow2_bucket(d, 1)

    def test_serve_autotune_sweep_keys_agree_for_same_shape(self, atab):
        """For one raw shape, the serve bucket key, the batched
        chooser's recorded decision key and the sweep builder's grid
        key all name the SAME pow2 bucket."""
        from slate_tpu.linalg import batched
        from slate_tpu.serve.queue import BatchQueue

        b, n = 3, 50
        big = sweep.pow2_bucket(n)                      # 64
        srv = BatchQueue()
        skey = srv.bucket_key("potrf",
                              (np.zeros((n, n), np.float32),))
        srv.close()
        assert skey == ("potrf", "float32", big)

        rng = np.random.default_rng(0)
        g = rng.standard_normal((b, n, n)).astype(np.float32)
        spd = (np.einsum("bij,bkj->bik", g, g)
               + n * np.eye(n, dtype=np.float32))
        batched.potrf_batched(spd)
        dec_keys = [k for k in autotune.decisions()
                    if k.startswith("batched_potrf|")]
        assert len(dec_keys) == 1

        key_parts, _cands = sweep.SITES["batched_potrf"].build(
            {"b": b, "n": n})
        assert sweep.key_str("batched_potrf", key_parts) == dec_keys[0]
        assert key_parts[0] == sweep.pow2_bucket(b)
        assert key_parts[1] == big


class TestPruning:
    def test_prune_logs_predicted_gap(self):
        pred = {"a": 1.0, "b": 1.05, "c": 3.0, "d": 9.0}
        surv, dropped = sweep.prune(pred, ["a", "b", "c", "d"], 0.25)
        assert surv == ["a", "b"]
        assert [d["candidate"] for d in dropped] == ["c", "d"]
        assert [d["predicted_gap"] for d in dropped] == [3.0, 9.0]
        assert all(d["best_predicted_s"] == 1.0 for d in dropped)

    def test_unpriced_units_never_pruned(self):
        surv, dropped = sweep.prune({"a": 1.0}, ["a", "b"], 0.25)
        assert surv == ["a", "b"] and not dropped
        assert sweep.predict_times("no_such_site", (64,), ["a"]) == {}

    def test_sweep_cuts_reps_2x_and_audits_skips(self, atab, tmp_path,
                                                 monkeypatch):
        """The acceptance pin: on a grid the model can price, pruning
        cuts timing reps ≥2× vs exhaustive, and every skipped candidate
        lands in bundle["pruned"] with its predicted gap."""
        monkeypatch.setitem(
            sweep.SITES, "toyop",
            _toy_site({"a": 1.0, "b": 1.05, "c": 3.0, "d": 9.0},
                      {"a": 0.0, "b": 0.002, "c": 0.02, "d": 0.02}))
        grid = {"name": "toy", "margin": 0.25,
                "units": [{"site": "toyop", "n": 64},
                          {"site": "toyop", "n": 128}]}
        bundle = sweep.run_sweep(
            grid, table_path=str(tmp_path / "table.json"))
        st = bundle["stats"]
        assert st["reps_exhaustive"] >= 2 * st["reps_timed"] > 0
        assert st["timing_reps_actual"] == st["reps_timed"]
        assert len(bundle["pruned"]) == 4
        for p in bundle["pruned"]:
            assert p["predicted_gap"] >= 1.25
            assert p["predicted_s"] > p["best_predicted_s"]
        assert bundle["decisions"]["toyop|64,float32,HIGH"]["backend"] \
            == "a"

    def test_smoke_grid_prunes_every_fusion_site_to_one(self):
        """The shipped smoke grid's pruning is deterministic: every
        unit the roofline can price keeps exactly ONE survivor at its
        margin (the ≥2× rep cut run_tests --sweep pins end-to-end)."""
        grid = sweep.GRIDS["smoke"]
        cases = {
            "lu_step": ["composed", "fused", "fused_trsm", "full"],
            "potrf_step": ["composed", "fused", "full"],
            "lu_driver": ["rec", "scattered"],
            "batched_potrf": ["vmapped", "grid"],
            "batched_lu": ["vmapped", "grid"],
            "ooc": ["incore", "pool"],
        }
        total = timed = 0
        for u in grid["units"]:
            names = cases[u["site"]]
            if u["site"].startswith("batched"):
                kp = (sweep.pow2_bucket(u["b"]),
                      sweep.pow2_bucket(u["n"]), "float32", "HIGH")
            elif u["site"] in ("potrf_step", "ooc"):
                kp = (u["n"], u["nb"], "float32", "HIGH")
            else:
                kp = (u["m"], u["n"], u["nb"], "float32", "HIGH")
            pred = sweep.predict_times(u["site"], kp, names, "cpu")
            surv, dropped = sweep.prune(pred, names, grid["margin"])
            assert len(surv) == 1, (u, pred)
            total += len(names)
            timed += len(surv)
        assert total >= 2 * timed


class TestSweepEngine:
    def test_checkpoint_resume_skips_done_units(self, atab, tmp_path,
                                                monkeypatch):
        calls = []

        def build(u):
            def setup():
                calls.append(u["n"])
                return lambda: "out"
            return ((int(u["n"]), "float32", "HIGH"),
                    [autotune.Candidate("a", setup),
                     _toy("b", 0.005)])

        monkeypatch.setitem(
            sweep.SITES, "toyop",
            sweep.SiteSpec(build, lambda kp, names, p: {}))
        grid = {"units": [{"site": "toyop", "n": 64}]}
        ck = str(tmp_path / "ck.json")
        b1 = sweep.run_sweep(grid, checkpoint=ck,
                             table_path=str(tmp_path / "t1.json"))
        assert calls == [64] and b1["stats"]["units"] == 1
        b2 = sweep.run_sweep(grid, checkpoint=ck, resume=True,
                             table_path=str(tmp_path / "t2.json"))
        assert calls == [64], "a resumed unit must not re-probe"
        assert b2["stats"]["units_resumed"] == 1
        assert b2["decisions"] == b1["decisions"]
        assert b2["digest"] == b1["digest"]

    def test_transient_infra_failure_retries_classified(self, atab,
                                                        tmp_path,
                                                        monkeypatch):
        attempts = []

        def build(u):
            attempts.append(1)
            if len(attempts) == 1:
                raise TimeoutError("worker rpc deadline")   # transient
            return ((64, "float32", "HIGH"), [_toy("a", 0.0)])

        monkeypatch.setitem(
            sweep.SITES, "toyop",
            sweep.SiteSpec(build, lambda kp, names, p: {}))
        bundle = sweep.run_sweep({"units": [{"site": "toyop", "n": 64}]},
                                 table_path=str(tmp_path / "t.json"))
        assert len(attempts) == 2
        assert bundle["stats"]["units"] == 1
        assert bundle["stats"]["units_failed"] == 0

    def test_failed_unit_never_kills_sweep(self, atab, tmp_path,
                                           monkeypatch):
        def boom(u):
            raise AssertionError("deterministic bug")       # never retried

        monkeypatch.setitem(sweep.SITES, "toyop",
                            sweep.SiteSpec(boom,
                                           lambda kp, names, p: {}))
        monkeypatch.setitem(
            sweep.SITES, "toyop2",
            _toy_site({}, {"a": 0.0, "b": 0.005}))
        bundle = sweep.run_sweep(
            {"units": [{"site": "toyop", "n": 64},
                       {"site": "toyop2", "n": 32}]},
            table_path=str(tmp_path / "t.json"))
        assert bundle["stats"]["units_failed"] == 1
        assert bundle["stats"]["units"] == 1
        assert "toyop2|32,float32,HIGH" in bundle["decisions"]

    def test_duplicate_pow2_bucket_units_swept_once(self, atab,
                                                    tmp_path,
                                                    monkeypatch):
        """Two grid units bucketing to the same pow2 key yield ONE
        lattice point — a duplicate would double-weight the model's
        nearest-neighbor blend and duplicate the pruning audit."""
        def build(u):
            n = sweep.pow2_bucket(int(u["n"]))
            return ((n, "float32", "HIGH"),
                    [_toy("a", 0.0), _toy("b", 0.005)])

        monkeypatch.setitem(
            sweep.SITES, "toyop",
            sweep.SiteSpec(build, lambda kp, names, p: {}))
        bundle = sweep.run_sweep(
            {"units": [{"site": "toyop", "n": 5},
                       {"site": "toyop", "n": 8}]},     # both bucket to 8
            table_path=str(tmp_path / "t.json"))
        assert len(bundle["decisions"]) == 1
        assert len(bundle["model"]["toyop"]["float32,HIGH"]) == 1
        assert bundle["stats"]["units"] == 1
        assert bundle["stats"]["units_duplicate"] == 1
        assert bundle["stats"]["units_resumed"] == 0

    def test_warm_specs_derived_from_batched_results(self):
        res = _results([(8, 64, "float32", "HIGH")], {"grid": 1e-4},
                       site="batched_potrf")
        specs = sweep.warm_specs_from_results(
            res, extra=[{"op": "posv", "batch": 1, "dims": [96],
                         "dtype": "float32"}])
        ops = {(s["op"], tuple(s["dims"]), s["batch"]) for s in specs}
        assert ("potrf", (64,), 8) in ops
        assert ("posv", (64,), 8) in ops
        assert ("posv", (96,), 1) in ops


class TestBundleLadder:
    def test_bundle_roundtrip_zero_timing_reps(self, atab, tmp_path,
                                               monkeypatch):
        """The round-trip pin: a fresh module state with the bundle env
        set resolves the swept key probe-free even ON TPU — to the
        bundle's backend, NOT the one runtime timing would pick."""
        path, _ = _write(tmp_path, _results(
            [(64, "float32", "HIGH")], {"slow": 0.001, "fast": 0.005},
            backend="slow"))
        monkeypatch.setenv(sweep.BUNDLE_ENV, path)
        autotune.reset_table()
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        cands = [_toy("slow", 0.02), _toy("fast", 0.0)]
        got = autotune.decide("toyop", (64, "float32", "HIGH"), cands)
        assert got == "slow", "the bundle entry must outrank timing"
        assert autotune.timing_reps() == 0
        info = autotune.table().decisions["toyop|64,float32,HIGH"]
        assert info["source"] == "bundle"
        # repeat dispatch stays probe-free through the fast path
        assert autotune.decide("toyop", (64, "float32", "HIGH"),
                               cands) == "slow"
        assert autotune.timing_reps() == 0

        # the satellite's importlib-reload analog of a fresh process
        mod = importlib.reload(importlib.import_module(
            "slate_tpu.perf.autotune"))
        try:
            monkeypatch.setattr(mod, "_on_tpu", lambda: True)
            got = mod.decide("toyop", (64, "float32", "HIGH"),
                             [mod.Candidate("slow", _toy("slow", 0.02).setup),
                              mod.Candidate("fast", _toy("fast", 0.0).setup)])
            assert got == "slow"
            assert mod.timing_reps() == 0
        finally:
            mod.reset_table()

    def test_model_resolves_unswept_shape_probe_free(self, atab,
                                                     tmp_path,
                                                     monkeypatch):
        path, _ = _write(tmp_path, _results(
            [(32, "float32", "HIGH"), (64, "float32", "HIGH")],
            {"fast": 1e-4, "slow": 5e-4}))
        monkeypatch.setenv(sweep.BUNDLE_ENV, path)
        autotune.reset_table()
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        got = autotune.decide("toyop", (256, "float32", "HIGH"),
                              [_toy("slow", 0.0), _toy("fast", 0.02)])
        assert got == "fast"
        assert autotune.timing_reps() == 0
        info = autotune.table().decisions["toyop|256,float32,HIGH"]
        assert info["source"] == "bundle-model"

    def test_ctx_mismatch_falls_through_to_probe(self, atab, tmp_path,
                                                 monkeypatch):
        path, _ = _write(tmp_path, _results(
            [(64, "float64", "HIGH")], {"fast": 1e-4, "slow": 5e-4}))
        monkeypatch.setenv(sweep.BUNDLE_ENV, path)
        autotune.reset_table()
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        got = autotune.decide("toyop", (64, "float32", "HIGH"),
                              [_toy("slow", 0.02), _toy("fast", 0.0)])
        assert got == "fast"
        assert autotune.timing_reps() > 0, \
            "a float64 model point must not resolve a float32 key"

    def test_stale_version_bundle_rejected(self, atab, tmp_path,
                                           monkeypatch):
        version = dict(autotune._version_key(), jax="0.0.older")
        path, _ = _write(tmp_path, _results(
            [(64, "float32", "HIGH")], {"slow": 1e-4}, backend="slow"),
            version=version)
        monkeypatch.setenv(sweep.BUNDLE_ENV, path)
        autotune.reset_table()
        assert autotune.table().bundle is None
        assert autotune.bundle_info() is None
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        got = autotune.decide("toyop", (64, "float32", "HIGH"),
                              [_toy("slow", 0.02), _toy("fast", 0.0)])
        assert got == "fast"
        assert autotune.timing_reps() > 0, \
            "a stale bundle must retime, not resolve"

    def test_malformed_bundle_rejected(self, atab, tmp_path,
                                       monkeypatch):
        path = tmp_path / "bad.json"
        path.write_text("{not json")
        monkeypatch.setenv(sweep.BUNDLE_ENV, str(path))
        autotune.reset_table()
        assert autotune.table().bundle is None

    def test_pre_full_bundle_still_resolves(self, atab, tmp_path,
                                            monkeypatch):
        """ISSUE 12 compat pin: a bundle swept BEFORE the ``full``
        depth rung existed keeps loading and resolving — its ``fused``
        entry wins even though today's candidate list carries ``full``,
        and a key the old bundle never swept falls through to cached
        timing/probe instead of erroring."""
        import jax.numpy as jnp

        old_key = (256, 256, 128, "float32", "HIGH")
        path, _ = _write(tmp_path, _results(
            [old_key],
            {"composed": 5e-4, "fused": 1e-4, "fused_trsm": 3e-4},
            site="lu_step", backend="fused"))
        monkeypatch.setenv(sweep.BUNDLE_ENV, path)
        autotune.reset_table()
        assert autotune.table().bundle is not None

        # off-TPU chooser ladder (the CI path): the old entry resolves
        # against the WIDENED depth ladder, probe-free
        monkeypatch.delenv("SLATE_TPU_AUTOTUNE_FORCE", raising=False)
        got = autotune.choose_lu_step(256, 256, 128, jnp.float32,
                                      eligible=True, eligible_full=True)
        assert got == "fused"
        assert autotune.timing_reps() == 0
        info = autotune.table().decisions["lu_step|256,256,128,"
                                          "float32,HIGH"]
        assert info["source"] == "bundle"

        # decide() with the full candidate present: the bundle entry
        # still outranks timing for the swept key...
        autotune.reset_table()
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        cands = [_toy(d, t) for d, t in
                 (("composed", 0.02), ("fused", 0.01),
                  ("fused_trsm", 0.015), ("full", 0.0))]
        assert autotune.decide("lu_step", old_key, cands) == "fused"
        assert autotune.timing_reps() == 0
        # ...an unswept same-context key resolves through the old
        # bundle's interpolating model (still probe-free, still a
        # pre-full rung — no KeyError on the widened ladder)...
        got = autotune.decide("lu_step", (512, 512, 128, "float32",
                                          "HIGH"), cands)
        assert got == "fused"
        assert autotune.timing_reps() == 0
        # ...and a key NEITHER the entries nor the model can match
        # (different dtype context) falls through to the probe, where
        # timing is free to pick the new rung
        got = autotune.decide("lu_step", (512, 512, 128, "float64",
                                          "HIGH"), cands)
        assert got == "full"
        assert autotune.timing_reps() > 0

    def test_quarantine_masks_bundle_entry(self, atab, tmp_path,
                                           monkeypatch):
        """PR 9 negative evidence: a live quarantine for the bundle's
        winner masks the entry — the resolve degrades exactly as it
        would for a cached winner, and never returns the demoted
        backend."""
        path, _ = _write(tmp_path, _results(
            [(64, "float32", "HIGH")], {"slow": 1e-4, "fast": 5e-4},
            backend="slow"))
        monkeypatch.setenv(sweep.BUNDLE_ENV, path)
        autotune.reset_table()
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        key = (64, "float32", "HIGH")
        cands = [_toy("slow", 0.02), _toy("fast", 0.0)]
        assert autotune.decide("toyop", key, cands) == "slow"
        autotune.quarantine("toyop", key, "slow",
                            reason="health gate failed")
        got = autotune.decide("toyop", key, cands)
        assert got == "fast"
        # the mask degrades to the model's next-best offline evidence
        # (quarantined backend excluded), not to a runtime probe
        assert autotune.table().decisions[
            "toyop|64,float32,HIGH"]["source"] == "bundle-model"
        assert autotune.timing_reps() == 0
        # expiry re-admits the bundle entry (the bundle-model record
        # must not outlive the mask that produced it)
        autotune.quarantine("toyop", key, "slow", ttl_s=0.0)
        time.sleep(0.01)
        assert autotune.decide("toyop", key, cands) == "slow"

    def test_health_gate_demotes_bundle_sourced_winner(self, atab,
                                                       tmp_path,
                                                       monkeypatch):
        """resilience/health.py treats bundle-sourced decisions as
        settled, demotable evidence: quarantine_driver masks them like
        timed/cached winners."""
        from slate_tpu.resilience import health

        path, _ = _write(tmp_path, _results(
            [(8, 64, "float32", "HIGH")], {"grid": 1e-4, "vmapped": 5e-4},
            site="batched_potrf", backend="grid"))
        monkeypatch.setenv(sweep.BUNDLE_ENV, path)
        autotune.reset_table()
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        cands = [_toy("vmapped", 0.0), _toy("grid", 0.02)]
        assert autotune.decide("batched_potrf",
                               (8, 64, "float32", "HIGH"),
                               cands) == "grid"
        demoted = health.quarantine_driver(
            "potrf_batched", reason="live sentinel degradation")
        assert demoted == 1
        got = autotune.decide("batched_potrf", (8, 64, "float32", "HIGH"),
                              cands)
        assert got == "vmapped"

    def test_forced_pin_outranks_bundle(self, atab, tmp_path,
                                        monkeypatch):
        path, _ = _write(tmp_path, _results(
            [(64, "float32", "HIGH")], {"slow": 1e-4}, backend="slow"))
        monkeypatch.setenv(sweep.BUNDLE_ENV, path)
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE", "toyop=fast")
        autotune.reset_table()
        monkeypatch.setattr(autotune, "_on_tpu", lambda: True)
        got = autotune.decide("toyop", (64, "float32", "HIGH"),
                              [_toy("slow", 0.02), _toy("fast", 0.0)])
        assert got == "fast"
        assert autotune.timing_reps() == 0


class TestModelGuard:
    def test_never_selects_candidate_rejected_10x_by_model(self,
                                                           monkeypatch):
        """Interpolation sanity: a candidate whose MEASURED grid times
        look best but whose analytical prediction at the query shape is
        >10× the predicted best can never be selected."""
        monkeypatch.setitem(
            sweep.SITES, "toyop",
            sweep.SiteSpec(lambda u: None,
                           lambda kp, names, p: {"fast": 1.0,
                                                 "cheat": 100.0}))
        results = _results(
            [(32, "float32", "HIGH"), (64, "float32", "HIGH")],
            {"cheat": 1e-6, "fast": 1e-3}, backend="cheat")
        blob = sweep.build_bundle(results, {"platform": "cpu"})
        got = sweep.model_backend(blob, "toyop",
                                  (128, "float32", "HIGH"),
                                  ["fast", "cheat"])
        assert got == "fast"
        # within the guard the measured times decide
        monkeypatch.setitem(
            sweep.SITES, "toyop",
            sweep.SiteSpec(lambda u: None,
                           lambda kp, names, p: {"fast": 1.0,
                                                 "cheat": 2.0}))
        assert sweep.model_backend(blob, "toyop",
                                   (128, "float32", "HIGH"),
                                   ["fast", "cheat"]) == "cheat"

    def test_model_only_selects_measured_candidates(self):
        results = _results([(32, "float32", "HIGH")], {"fast": 1e-3})
        blob = sweep.build_bundle(results, {"platform": "cpu"})
        assert sweep.model_backend(blob, "toyop", (64, "float32", "HIGH"),
                                   ["fast", "never_timed"]) == "fast"
        assert sweep.model_backend(blob, "toyop", (64, "float32", "HIGH"),
                                   ["never_timed"]) is None
        assert sweep.model_backend(blob, "nosite",
                                   (64, "float32", "HIGH"),
                                   ["fast"]) is None


class TestServeBundleBoot:
    def test_warm_start_from_bundle_zero_compiles(self, atab, tmp_path,
                                                  monkeypatch):
        """The in-process analog of the acceptance criterion: a fresh
        table with only the bundle env set warm-starts from the
        bundle's AOT specs and serves its first bucketed request —
        including an UNSWEPT shape resolved by the model — with zero
        timing reps, zero on-demand compiles and zero jit compiles."""
        from slate_tpu import serve
        from slate_tpu.serve.queue import BatchQueue, ServeConfig

        prec = autotune._precision_name()
        results = [{"site": "batched_potrf",
                    "key_parts": [8, 64, "float32", prec],
                    "backend": "vmapped",
                    "times": {"vmapped": 1e-4}}]
        warm = [{"op": "posv", "batch": 2, "dims": [64],
                 "dtype": "float32"},
                {"op": "posv", "batch": 1, "dims": [96],
                 "dtype": "float32"}]
        path, _ = _write(tmp_path, results, warm=warm)
        monkeypatch.setenv(sweep.BUNDLE_ENV, path)
        autotune.reset_table()
        was = metrics.enabled()
        metrics.on()
        metrics.reset()
        srv = BatchQueue(ServeConfig(max_batch=2, max_wait_s=0.005))
        try:
            assert serve.specs_from_bundle() == warm
            compiled = serve.warm_start(srv)      # specs=None → bundle
            assert compiled >= 3
            metrics.reset()
            rng = np.random.default_rng(0)

            def spd(n):
                g = rng.standard_normal((n, n)).astype(np.float32)
                return g @ g.T + n * np.eye(n, dtype=np.float32)

            eps = float(np.finfo(np.float32).eps)
            for n in (64, 96):
                a = spd(n)
                b = np.ones(n, np.float32)
                x = srv.submit("posv", a, b).result(timeout=120)
                r = (np.linalg.norm(a @ x - b)
                     / (np.linalg.norm(a) * np.linalg.norm(b)
                        * eps * n))
                assert r < 3, (n, r)
            counters = metrics.snapshot()["counters"]
            assert counters.get("serve.compile.on_demand", 0) == 0
            assert counters.get("jit.backend_compiles", 0) == 0
            assert autotune.timing_reps() == 0
            dec = autotune.table().decisions
            assert dec["batched_potrf|8,64,float32,%s" % prec][
                "source"] == "bundle"
            assert dec["batched_potrf|8,128,float32,%s" % prec][
                "source"] == "bundle-model"
        finally:
            srv.close()
            metrics.reset()
            if not was:
                metrics.off()


class TestRegressNote:
    def test_bundle_change_surfaces_as_note(self, tmp_path):
        from slate_tpu.perf import regress

        def art(name, bundle):
            agg = {"metric": "factor_suite_fp32_geomean", "value": 1.0,
                   "unit": "GFLOP/s",
                   "submetrics": {"gemm_fp32_n8192": 100.0}}
            if bundle is not None:
                agg["bundle"] = bundle
            p = tmp_path / name
            p.write_text(json.dumps(agg))
            return regress.load_artifact(str(p))

        a1 = art("r1.json", None)
        a2 = art("r2.json", {"digest": "abc123", "version": {}})
        report = regress.diff([a1, a2])
        table = regress.format_table(report)
        assert "NOTE r2.json: bundle changed: none -> abc123" in table
        assert report.exit_code == 0
        # unchanged bundles stay silent
        report2 = regress.diff([art("r3.json", {"digest": "abc123"}),
                                art("r4.json", {"digest": "abc123"})])
        assert "bundle changed" not in regress.format_table(report2)


class TestBenchTag:
    def test_bench_lines_carry_bundle_tag(self, atab, tmp_path,
                                          monkeypatch, capsys):
        bench = pytest.importorskip("bench")
        path, blob = _write(tmp_path, _results(
            [(64, "float32", "HIGH")], {"fast": 1e-4}))
        monkeypatch.setenv(sweep.BUNDLE_ENV, path)
        autotune.reset_table()
        sub, fails, infra = {}, [], []
        bench._run_routine("toy", lambda: ("toy_fp32_n64", 1.0, 0.0),
                           sub, fails, infra)
        line = json.loads(capsys.readouterr().out.strip()
                          .splitlines()[-1])
        assert line["bundle"]["digest"] == blob["digest"]
        agg = bench._partial_aggregate(sub, fails, infra)
        assert agg["bundle"]["digest"] == blob["digest"]
        # probe-cold process: the tag is null, not absent
        monkeypatch.delenv(sweep.BUNDLE_ENV)
        autotune.reset_table()
        bench._run_routine("toy2", lambda: ("toy2_fp32_n64", 1.0, 0.0),
                           sub, fails, infra)
        line = json.loads(capsys.readouterr().out.strip()
                          .splitlines()[-1])
        assert line["bundle"] is None


class TestProfileSignals:
    """ISSUE 19: a captured device profile feeds the sweep's pricing —
    the bundle is stamped with the profile digest (and so can never
    collide with the roofline-only bundle of the same grid), and the
    measured launch signal flips a dist_chunk decision the roofline
    prices the other way."""

    _PROFILE = {"digest": "feedbeefcafe0123",
                "stages": {"getrf": {"panel": 0.2, "update": 0.8}},
                "signals": {"launch_s": 1e-3}}

    def test_profile_informed_bundle_digest_and_provenance(
            self, atab, tmp_path, monkeypatch):
        monkeypatch.setitem(
            sweep.SITES, "toyop",
            _toy_site({"a": 1.0, "b": 1.05},
                      {"a": 0.0, "b": 0.002}))
        grid = {"name": "toy", "margin": 0.25,
                "units": [{"site": "toyop", "n": 64}]}
        base = sweep.run_sweep(grid,
                               table_path=str(tmp_path / "t0.json"))
        assert "profile" not in base
        assert "profile" not in base["version"]
        autotune.reset_table()
        informed = sweep.run_sweep(grid, profile=dict(self._PROFILE),
                                   table_path=str(tmp_path / "t1.json"))
        assert informed["digest"] != base["digest"]
        prov = informed["profile"]
        assert prov["digest"] == "feedbeefcafe0123"
        assert prov["launch_s"] == pytest.approx(1e-3)
        assert "getrf" in prov["stage_ops"]
        assert informed["version"]["profile"] == prov
        # the signals never leak past the sweep call
        assert sweep.profile_signals() is None

    def test_profile_loaded_from_artifact_path(self, atab, tmp_path,
                                               monkeypatch):
        monkeypatch.setitem(
            sweep.SITES, "toyop",
            _toy_site({}, {"a": 0.0, "b": 0.002}))
        apath = tmp_path / "xprof_t.json"
        apath.write_text(json.dumps(self._PROFILE))
        bundle = sweep.run_sweep(
            {"units": [{"site": "toyop", "n": 64}]},
            profile=str(apath), table_path=str(tmp_path / "t.json"))
        assert bundle["profile"]["digest"] == "feedbeefcafe0123"
        assert bundle["profile"]["source"] == str(apath)

    def test_unusable_profile_prices_roofline_only(self, atab, tmp_path,
                                                   monkeypatch):
        monkeypatch.setitem(
            sweep.SITES, "toyop",
            _toy_site({}, {"a": 0.0, "b": 0.002}))
        said = []
        bundle = sweep.run_sweep(
            {"units": [{"site": "toyop", "n": 64}]},
            profile=str(tmp_path / "nosuch"),
            table_path=str(tmp_path / "t.json"), log=said.append)
        assert "profile" not in bundle
        assert any("roofline-only" in s for s in said)

    def test_measured_launch_flips_dist_chunk_decision(self):
        """The decision-delta pin: on a small mesh/nb the roofline's
        launch constant keeps slicing cheap (winner "2"), a measured
        1ms dispatch overhead makes every extra collective dear and
        whole-panel broadcast wins — c* = sqrt(wire/launch) moved."""
        key = ("getrf", 2, 2, 128, "float32")
        names = ["whole", "2", "4"]
        roof = sweep.SITES["dist_chunk"].predict(key, names, "cpu")
        assert min(roof, key=roof.get) == "2"
        sweep.set_profile_signals(
            xprof.signals_from(dict(self._PROFILE)))
        try:
            informed = sweep.SITES["dist_chunk"].predict(key, names,
                                                         "cpu")
        finally:
            sweep.set_profile_signals(None)
        assert min(informed, key=informed.get) == "whole"
        assert informed["4"] > informed["2"] > informed["whole"]

    def test_dist_lookahead_site_priced_and_swept(self):
        """The new dist_lookahead site prices every named depth (no
        unpriced-candidate prune escape) and deeper depths pay their
        redundant-compute + dispatch toll once the wire is hidden."""
        key = ("getrf", 2, 2, 128, "float32")
        pred = sweep.SITES["dist_lookahead"].predict(
            key, ["1", "2", "3", "4"], "cpu")
        assert set(pred) == {"1", "2", "3", "4"}
        assert all(v > 0 for v in pred.values())
        assert sweep.SITES["dist_lookahead"].predict(
            key, ["1", "weird"], "cpu") == {}
        units = [u for u in sweep._full_units()
                 if u.get("site") == "dist_lookahead"]
        assert {"op", "nt", "nb"} <= set(units[0])
        assert len(units) == 9
